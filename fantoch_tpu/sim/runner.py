"""Deterministic discrete-event simulation runner (the oracle).

Capability parity with ``fantoch/src/sim/runner.rs``: wires planet + config
+ workload into processes/executors/clients (runner.rs:64-190), runs the
event loop over submit/send/periodic actions (runner.rs:233-313), models
message delay as half the ping latency (runner.rs:575-595) with optional
symmetric distances and random reordering (×U(0,10), runner.rs:520-524),
and reports per-process protocol/executor metrics, execution-order
monitors, and per-region latency histograms (runner.rs:597-681).

This host runner advances ONE configuration at a time and is the
differential-test oracle for the batched device engine in
``fantoch_tpu.engine``, which advances thousands of configurations in
lockstep under ``vmap``.
"""

from __future__ import annotations

import copy
import random
from typing import Dict, List, Optional, Tuple, Type

from ..client.client import Client
from ..client.workload import Workload
from ..core.command import Command, CommandResult, CommandResultBuilder
from ..core.config import Config
from ..core.ids import ClientId, ProcessId, ShardId
from ..core.metrics import Histogram
from ..core.planet import Planet
from ..core.trace import trace, tracer
from ..core.util import closest_process_per_shard, sort_processes_by_distance
from ..engine.faults import FaultPlan
from ..executor.base import Executor
from ..protocol.base import Protocol, ToForward, ToSend
from .schedule import KIND_MESSAGE, Schedule
from .simulation import Simulation

# schedule action kinds
_log = tracer("sim.runner")

# sentinel crash time for processes that never crash
_NO_CRASH = 1 << 60

_SUBMIT = 0
_SEND = 1
_TO_CLIENT = 2
_PERIODIC = 3
_EXECUTED_NOTIFICATION = 4
_EXECUTOR_INFO = 5       # cross-shard executor-to-executor message
_TO_CLIENT_PARTIAL = 6   # per-key result partial (multi-shard mode)
_EXECUTOR_CLEANUP = 7    # periodic executor cleanup tick (multi-shard)

# client src keys rank after every process src key in same-instant
# message tie-breaks (the engine encodes clients as N + client)
_CLIENT_SRC_OFFSET = 1 << 20


def _action_process(kind: int, action) -> Optional[int]:
    """The process a scheduled action targets (None for client-bound
    actions) — the crash-stop skip's dispatch map."""
    if kind == _SEND:
        return action[3]
    if kind in (
        _SUBMIT,
        _PERIODIC,
        _EXECUTED_NOTIFICATION,
        _EXECUTOR_INFO,
        _EXECUTOR_CLEANUP,
    ):
        return action[1]
    return None


class Runner:
    def __init__(
        self,
        protocol_cls: Type[Protocol],
        planet: Planet,
        config: Config,
        workload: Workload,
        clients_per_process: int,
        process_regions: List[str],
        client_regions: List[str],
        seed: int = 0,
        fault_plan: Optional[FaultPlan] = None,
        traffic=None,
        arrivals=None,
        arrival_load: int = 100,
        arrival_gap_ms: int = 4,
        open_window: int = 4,
    ):
        assert len(process_regions) == config.n
        assert config.gc_interval_ms is not None

        # traffic-schedule mirror (fantoch_tpu/traffic): the oracle adds
        # each command's epoch think delay to its SUBMIT's distance —
        # the bit-exact twin of the engine's `done_t + think` submit
        # base (engine/core.py step 5). Key/read-mix mirroring rides in
        # the workload's DeviceStream(traffic=...) generator; pass the
        # SAME schedule in both places for differential runs.
        self._traffic = traffic

        # open-loop arrival mirror (fantoch_tpu/traffic ArrivalSchedule,
        # docs/TRAFFIC.md "Open-loop arrivals"): the oracle builds the
        # SAME seeded arrival table the engine ships as ctx["ol_arrival"]
        # (engine/spec.py make_lane) and replays the engine's two
        # staging triggers — at-SUBMIT-pop (trigger 1) and
        # gate-crossing-completion (trigger 2) — so command s's SUBMIT
        # reaches its attach process at exactly R(s) + d_sub on both
        # sides, with R(s) = max(A(s), F(s), R(s-1)). Latency is
        # queue-delay-inclusive: completion #k of client c records
        # t - A(c, k) into Runner-owned records (count-based
        # attribution, the engine's step-5 contract), bypassing the
        # closed-loop Client bookkeeping for reporting.
        from ..traffic.schedule import resolve_arrivals

        arrivals = resolve_arrivals(
            arrivals, mean_gap_ms=arrival_gap_ms,
            commands=workload.commands_per_client,
            load_pct=arrival_load,
        )
        self._arrivals = arrivals
        self._ol_table = None
        if arrivals is not None:
            assert config.shard_count == 1, (
                "open-loop arrivals are single-shard (make_lane asserts"
                " the same)"
            )
            assert traffic is None or all(
                p.think_ms == 0 for p in traffic.phases
            ), "open-loop lanes own the issue clock; think must be 0"
            assert open_window >= 1, open_window
            self._ol_window = int(open_window)
            self._ol_budget = int(workload.commands_per_client)
            self._ol_table = arrivals.arrival_table(
                seed=seed,
                clients=clients_per_process * len(client_regions),
                commands=workload.commands_per_client,
            )
            # per-client open-loop state (registered clients only):
            # completion count, completion times in completion order
            # (the engine's ring, unbounded host-side), the monotone
            # release clamp R(s-1), and the latency records (ms)
            self._ol_completed: Dict[int, int] = {}
            self._ol_comp_times: Dict[int, List[int]] = {}
            self._ol_last_rel: Dict[int, int] = {}
            self._ol_lat: Dict[int, List[int]] = {}

        # fault-plan mirror (engine/faults.py): the oracle applies the
        # exact crash/window/drop model the device engine applies, so
        # the differential tests extend to faulty schedules. Process
        # rows in the plan are 0-based; oracle pids are 1-based.
        if fault_plan is not None and fault_plan.is_noop():
            fault_plan = None
        self._fault = fault_plan
        self._crash_ms: Dict[int, int] = {}
        self._drop_table = None
        self._jitter_table = None
        self._horizon: Optional[int] = None
        doomed_pids: set = set()
        if fault_plan is not None:
            assert config.shard_count == 1, (
                "fault plans are single-shard for now"
            )
            self._crash_ms = {
                row + 1: ms for row, ms in fault_plan.crashes.items()
            }
            doomed_pids = set(self._crash_ms)
            if fault_plan.drop_bp:
                self._drop_table = fault_plan.drop_table(config.n)
            if fault_plan.jitter_max > 1:
                # seeded schedule jitter: the same (src, dst, channel
                # emission index)-keyed multipliers the device draws
                # in-loop (engine/faults.py jitter_draw)
                self._jitter_table = fault_plan.jitter_table(config.n)
            self._horizon = fault_plan.horizon_ms

        self.planet = planet
        self.simulation = Simulation()
        self.schedule: Schedule = Schedule()
        self.process_to_region: Dict[ProcessId, str] = {}
        self.client_to_region: Dict[ClientId, str] = {}
        self.make_distances_symmetric = False
        self.reorder_messages = False
        self.rng = random.Random(seed)
        # per-(src, dst) channel emission counters for the schedule
        # tie-break key
        self._chan_seq: Dict[Tuple[int, int], int] = {}

        # the reference's sim is single-shard (runner.rs:84-85) and
        # exercises partial replication only through its TCP run-layer
        # tests (fantoch/src/run/mod.rs:575-849); here shard_count > 1
        # places one process per (shard, region) — the run_test layout —
        # with client-side result aggregation and WAN-delayed cross-
        # shard executor messages
        self.shard_count = config.shard_count
        if self.shard_count > 1:
            # only Tempo and Atlas implement the partial-replication
            # paths (partial.rs's MForwardSubmit aggregation); anything
            # else would hang waiting for the other shard's partials
            assert getattr(protocol_cls, "PARTIAL_REPLICATION", False), (
                f"{protocol_cls.__name__} does not support shard_count > 1"
            )
        from ..core.ids import process_ids

        to_discover = [
            (process_id, shard, region)
            for shard in range(self.shard_count)
            for region, process_id in zip(
                process_regions, process_ids(shard, config.n)
            )
        ]
        self.process_to_region = {
            pid: region for pid, _, region in to_discover
        }

        periodic: List[Tuple[ProcessId, object, int]] = []
        executed_notifications: List[Tuple[ProcessId, int]] = []
        # per-process closest process of each shard (discovery view) —
        # used to route cross-shard executor messages
        self._closest: Dict[ProcessId, Dict[ShardId, ProcessId]] = {}
        # multi-shard client-side aggregation (the run layer's
        # task/client/pending.rs): rifl → partial-result builder
        self._client_pending: Dict[object, object] = {}

        executor_cls = protocol_cls.EXECUTOR  # type: ignore[attr-defined]
        for process_id, shard, region in to_discover:
            process = protocol_cls(process_id, shard, config)
            for event, delay in process.periodic_events():
                periodic.append((process_id, event, delay))
            executed_notifications.append(
                (process_id, config.executor_executed_notification_interval_ms)
            )
            sorted_ = sort_processes_by_distance(
                region, planet, to_discover
            )
            # discovery keeps all same-shard processes (in distance
            # order) plus the closest process of each other shard
            seen_shards = set()
            filtered = []
            for pid, sid in sorted_:
                if sid == shard:
                    filtered.append((pid, sid))
                elif sid not in seen_shards:
                    seen_shards.add(sid)
                    filtered.append((pid, sid))
            if doomed_pids:
                # recovery-free crash model: doomed processes rank last
                # in every discovery order so quorum selection never
                # includes them — identical to the device engine's
                # sorted-index reorder (engine/faults.py)
                filtered = [
                    x for x in filtered if x[0] not in doomed_pids
                ] + [x for x in filtered if x[0] in doomed_pids]
            connect_ok, closest = process.discover(filtered)
            assert connect_ok
            self._closest[process_id] = closest
            executor = executor_cls(process_id, shard, config)
            self.simulation.register_process(process, executor)

        leader_doomed = (
            config.leader is not None and config.leader in doomed_pids
        )
        client_id = 0
        registered = 0
        for region in client_regions:
            for _ in range(clients_per_process):
                client_id += 1
                # consume the seed draw even for halted clients so the
                # surviving clients' streams match a fault-free run
                client_rng = random.Random(self.rng.randrange(2**63))
                closest = closest_process_per_shard(
                    region, planet, to_discover
                )
                # clients attached to a doomed process — or any client
                # under a doomed leader — are halted: they never issue
                # (replica death takes its clients with it; no
                # reconnection protocol, matching the device engine's
                # zeroed budgets). Ids keep counting so the surviving
                # clients' tie-break order matches the device's.
                if leader_doomed or closest.get(0) in doomed_pids:
                    continue
                client = Client(client_id, workload, rng=client_rng)
                client.connect(closest)
                self.simulation.register_client(client)
                self.client_to_region[client_id] = region
                registered += 1
        self.client_count = registered
        if self._ol_table is not None:
            for cid in self.client_to_region:
                self._ol_completed[cid] = 0
                self._ol_comp_times[cid] = []
                # R(0) seeds at the first arrival (the engine's
                # ol_last_rel init, engine/core.py init_lane_state)
                self._ol_last_rel[cid] = int(self._ol_table[cid - 1, 1])
                self._ol_lat[cid] = []

        for process_id, event, delay in periodic:
            self._schedule_periodic(process_id, event, delay)
        for process_id, delay in executed_notifications:
            self._schedule_executed_notification(process_id, delay)
        if self.shard_count > 1:
            # periodic executor cleanup retries buffered cross-shard
            # requests (the run layer's cleanup tick,
            # task/server/executor.rs:281-330); skip executors whose
            # cleanup is the base-class no-op (e.g. Tempo's table)
            for process_id in self.process_to_region:
                _, executor, _, _ = self.simulation.get_process(process_id)
                if type(executor).cleanup is Executor.cleanup:
                    continue
                self._schedule_executor_cleanup(
                    process_id, config.executor_cleanup_interval_ms
                )

    # ------------------------------------------------------------------

    def _think_ms(self, seq: int) -> int:
        return 0 if self._traffic is None else self._traffic.think_ms(seq)

    def run(
        self, extra_sim_time_ms: Optional[int] = None
    ) -> Tuple[dict, dict, Dict[str, Tuple[int, Histogram]]]:
        if self._ol_table is not None:
            # open-loop schedules own the issue clock; the legacy
            # reorder perturbation would scale the release-pinned
            # submit distances (make_lane asserts the same)
            assert not self.reorder_messages
        for client_id, process_id, cmd in self.simulation.start_clients():
            # every first command is seq 1 (the engine arms the first
            # SUBMIT at client_delay + think(1) identically); open loop:
            # it leaves at its arrival time A(c, 1) instead
            extra = self._think_ms(1)
            if self._ol_table is not None:
                extra = int(self._ol_table[client_id - 1, 1])
            self._schedule_submit(
                ("client", client_id), process_id, cmd,
                extra_delay=extra,
            )

        self._simulation_loop(extra_sim_time_ms)

        return (
            self._metrics(),
            self._executors_monitors(),
            self._clients_latencies(),
        )

    def _simulation_loop(self, extra_sim_time_ms: Optional[int]) -> None:
        clients_done = 0
        final_time: Optional[int] = None
        time = self.simulation.time
        if self.client_count == 0:
            # every client halted by the fault plan (e.g. a doomed
            # leader): run periodics for the grace window, like the
            # device lane's immediately-done + extra_time coda
            final_time = extra_sim_time_ms or 0
        while True:
            if self._horizon is not None:
                # fault-plan horizon: never handle an event at or past
                # it (the device masks the same events out of
                # qualification)
                nt = self.schedule.peek_millis()
                if nt is None or nt >= self._horizon:
                    return
            action = self.schedule.next_action(time)
            assert action is not None, (
                "there should be a new action since stability is always"
                " running"
            )
            kind = action[0]
            if self._crash_ms:
                target = _action_process(kind, action)
                if target is not None and time.millis() >= (
                    self._crash_ms.get(target, _NO_CRASH)
                ):
                    # crash-stop: the process handles nothing at or
                    # past its crash time; its periodic events are
                    # also not rescheduled (its timers die with it)
                    continue
            if kind == _PERIODIC:
                _, process_id, event, delay = action
                self._handle_periodic(process_id, event, delay)
            elif kind == _EXECUTED_NOTIFICATION:
                _, process_id, delay = action
                self._handle_executed_notification(process_id, delay)
            elif kind == _SUBMIT:
                _, process_id, cmd = action
                self._handle_submit(process_id, cmd)
            elif kind == _SEND:
                _, from_, from_shard_id, process_id, msg = action
                self._handle_send(from_, from_shard_id, process_id, msg)
            elif kind == _EXECUTOR_INFO:
                _, process_id, info = action
                self._handle_executor_info(process_id, info)
            elif kind == _EXECUTOR_CLEANUP:
                _, process_id, delay = action
                _, executor, _, _time = self.simulation.get_process(
                    process_id
                )
                executor.cleanup(_time)
                for schedule in self._drain_executor(process_id):
                    schedule()
                self._schedule_executor_cleanup(process_id, delay)
            elif kind == _TO_CLIENT_PARTIAL:
                _, client_id, executor_result = action
                cmd_result = self._aggregate_partial(executor_result)
                if cmd_result is not None:
                    kind = _TO_CLIENT
                    action = (_TO_CLIENT, client_id, cmd_result)
            if kind == _TO_CLIENT:
                _, client_id, cmd_result = action
                if self._ol_table is not None:
                    if self._ol_to_client(client_id, cmd_result):
                        clients_done += 1
                        if clients_done == self.client_count:
                            if extra_sim_time_ms is None:
                                return
                            final_time = time.millis() + extra_sim_time_ms
                    if final_time is not None and time.millis() > final_time:
                        return
                    continue
                submit = self.simulation.forward_to_client(cmd_result)
                if submit is not None:
                    process_id, cmd = submit
                    extra = 0
                    if self._traffic is not None:
                        # the workload counter was just bumped by
                        # cmd_send, so it IS the new command's seq
                        client, _ = self.simulation.get_client(client_id)
                        extra = self._think_ms(
                            client.workload.issued_commands()
                        )
                    self._schedule_submit(
                        ("client", client_id), process_id, cmd,
                        extra_delay=extra,
                    )
                else:
                    clients_done += 1
                    if clients_done == self.client_count:
                        if extra_sim_time_ms is None:
                            return
                        final_time = time.millis() + extra_sim_time_ms
            if final_time is not None and time.millis() > final_time:
                return

    def _aggregate_partial(self, executor_result):
        """Client-side partial-result aggregation (the run layer's
        task/client/pending.rs): complete once every key across every
        shard reported."""
        builder = self._client_pending.get(executor_result.rifl)
        assert builder is not None, "partial for unregistered command"
        builder.add_partial(
            executor_result.key, executor_result.partial_results
        )
        if builder.ready():
            del self._client_pending[executor_result.rifl]
            return builder.build()
        return None

    # -- action handlers (runner.rs:315-377) ----------------------------

    def _handle_periodic(self, process_id, event, delay) -> None:
        process, _, _, time = self.simulation.get_process(process_id)
        process.handle_event(event, time)
        self._send_to_processes_and_executors(process_id)
        self._schedule_periodic(process_id, event, delay)

    def _handle_executed_notification(self, process_id, delay) -> None:
        process, executor, _, time = self.simulation.get_process(process_id)
        executed = executor.executed(time)
        if executed is not None:
            process.handle_executed(executed, time)
            self._send_to_processes_and_executors(process_id)
        self._schedule_executed_notification(process_id, delay)

    def _handle_submit(self, process_id: ProcessId, cmd: Command) -> None:
        process, _executor, pending, time = self.simulation.get_process(
            process_id
        )
        if self._ol_table is not None:
            self._ol_trigger1(cmd)
        if self.shard_count == 1:
            # process-side aggregation (runner.rs:351-362); multi-shard
            # registers client-side at submit-schedule time instead
            pending.wait_for(cmd)
        process.submit(None, cmd, time)
        self._send_to_processes_and_executors(process_id)

    # -- open-loop arrival staging (docs/TRAFFIC.md) --------------------

    def _ol_arrival_ms(self, client_id: int, seq: int) -> int:
        """A(c, seq) from the shared seeded table (seqs 1-based; the
        last column extends, mirroring the engine's clamped gather)."""
        row = self._ol_table[client_id - 1]
        return int(row[min(seq, len(row) - 1)])

    def _ol_trigger1(self, cmd: Command) -> None:
        """Trigger 1 — staging at SUBMIT pop (engine/core.py step 4):
        popping client c's latest SUBMIT s stages command q = s+1 at
        release R(q) = max(A(q), F(q), R(s)) when the in-flight window
        already admits it; window-full commands wait for trigger 2."""
        client_id = cmd.rifl.source
        seq = cmd.rifl.sequence
        client, time = self.simulation.get_client(client_id)
        if seq != client.issued_commands():
            return  # an older command's SUBMIT; q was already staged
        q = seq + 1
        if q > self._ol_budget:
            return
        done = self._ol_completed[client_id]
        if done + self._ol_window < q:
            return  # window full: the gate-crossing completion stages q
        # F(q): completion time of command q - W (0 before W completions)
        f_gate = (
            self._ol_comp_times[client_id][q - self._ol_window - 1]
            if q > self._ol_window
            else 0
        )
        rel = max(
            self._ol_arrival_ms(client_id, q),
            f_gate,
            self._ol_last_rel[client_id],
        )
        self._ol_stage(client_id, rel)

    def _ol_to_client(self, client_id: int, cmd_result) -> bool:
        """Open-loop TO_CLIENT: count-based completion accounting plus
        trigger 2 (engine/core.py step 5). Returns True when this
        completion finishes the client's budget. Latency is
        queue-delay-inclusive — t - A(c, k) for completion #k — and
        lands in Runner-owned records; the closed-loop auto-resubmit
        (forward_to_client) is bypassed."""
        client, time = self.simulation.get_client(client_id)
        client.cmd_recv(cmd_result.rifl, time)
        t = time.millis()
        k = self._ol_completed[client_id] + 1
        self._ol_completed[client_id] = k
        self._ol_comp_times[client_id].append(t)
        self._ol_lat[client_id].append(
            t - self._ol_arrival_ms(client_id, k)
        )
        # trigger 2 — gate-crossing completion: command pend = issued+1
        # was window-blocked at its predecessor's SUBMIT pop and this
        # completion just admitted it (gate crosses exactly once, at
        # #(pend - W)); F(pend) = t by construction
        pend = client.issued_commands() + 1
        if (
            pend <= self._ol_budget
            and k + self._ol_window >= pend
            and not ((k - 1) + self._ol_window >= pend)
        ):
            rel = max(
                self._ol_arrival_ms(client_id, pend),
                t,
                self._ol_last_rel[client_id],
            )
            self._ol_stage(client_id, rel)
        return k == self._ol_budget

    def _ol_stage(self, client_id: int, rel: int) -> None:
        """Issue the client's next command with its SUBMIT pinned to
        arrive at the attach process at rel + d_sub — the engine's
        delay-override emission row. ``extra_delay`` may be negative
        (rel can precede now by up to d_sub on trigger 1); the total
        scheduled distance rel - R(s) stays >= 0 because releases are
        monotone."""
        client, time = self.simulation.get_client(client_id)
        nxt = client.cmd_send(time)
        assert nxt is not None, "staged past the command budget"
        target_shard, cmd = nxt
        self._ol_last_rel[client_id] = rel
        self._schedule_submit(
            ("client", client_id),
            client.shard_process(target_shard),
            cmd,
            extra_delay=rel - time.millis(),
        )

    def _handle_send(self, from_, from_shard_id, process_id, msg) -> None:
        process, _, _, time = self.simulation.get_process(process_id)
        trace(
            _log, "t=%s p%s <- p%s: %s",
            time.millis(), process_id, from_, msg,
        )
        process.handle(from_, from_shard_id, msg, time)
        self._send_to_processes_and_executors(process_id)

    def _handle_executor_info(self, process_id, info) -> None:
        """Cross-shard executor message delivery (the run layer's
        executor-to-executor channel, graph/mod.rs:279-330)."""
        _, executor, _, time = self.simulation.get_process(process_id)
        executor.handle(info, time)
        for schedule in self._drain_executor(process_id):
            schedule()

    def _drain_executor(self, process_id: ProcessId):
        """Deliver an executor's pending outputs: same-shard infos
        inline, cross-shard infos and client results as *deferred*
        schedule thunks — the caller flushes them after protocol
        actions, preserving runner.rs:395-441's scheduling order."""
        process, executor, pending, time = self.simulation.get_process(
            process_id
        )
        shard_id = process.shard_id()
        deferred = []
        while True:
            infos = executor.to_executors()
            results = executor.to_clients()
            if not infos and not results:
                break
            for to_shard, info in infos:
                if to_shard == shard_id:
                    executor.handle(info, time)
                else:
                    target = self._closest[process_id][to_shard]
                    deferred.append(
                        lambda t=target, i=info: self._schedule_message(
                            ("process", process_id),
                            ("process", t),
                            (_EXECUTOR_INFO, t, i),
                        )
                    )
            for executor_result in results:
                if self.shard_count == 1:
                    cmd_result = pending.add_executor_result(executor_result)
                    if cmd_result is not None:
                        deferred.append(
                            lambda r=cmd_result: self._schedule_to_client(
                                ("process", process_id), r
                            )
                        )
                else:
                    # only the client's connected process of this shard
                    # reports (run/prelude.rs:35-40 registration)
                    client_id = executor_result.rifl.source
                    client, _ = self.simulation.get_client(client_id)
                    if client.shard_process(shard_id) == process_id:
                        deferred.append(
                            lambda c=client_id, er=executor_result:
                            self._schedule_message(
                                ("process", process_id),
                                ("client", c),
                                (_TO_CLIENT_PARTIAL, c, er),
                            )
                        )
        return deferred

    def _send_to_processes_and_executors(self, process_id: ProcessId) -> None:
        """runner.rs:395-441."""
        process, executor, pending, time = self.simulation.get_process(
            process_id
        )
        shard_id = process.shard_id()

        protocol_actions = process.to_processes()

        deferred = []
        for info in process.to_executors():
            executor.handle(info, time)
            deferred.extend(self._drain_executor(process_id))

        self._schedule_protocol_actions(
            process_id, shard_id, ("process", process_id), protocol_actions
        )
        # client results and cross-shard infos schedule after protocol
        # actions (runner.rs:421-440)
        for schedule in deferred:
            schedule()

    def _schedule_protocol_actions(
        self, process_id, shard_id, from_region, actions
    ) -> None:
        """runner.rs:444-488; self-messages and ToForward are delivered
        immediately (recursively)."""
        for action in actions:
            if isinstance(action, ToSend):
                # targets before the last get their own copy of the
                # message, the last gets the original — the reference
                # clones n-1 times and moves (runner.rs:455-471); copies
                # matter because handlers mutate message contents (e.g.
                # Tempo consumes votes out of MCommit)
                targets = list(action.target)
                for i, to in enumerate(targets):
                    msg = (
                        action.msg
                        if i == len(targets) - 1
                        else copy.deepcopy(action.msg)
                    )
                    if to == process_id:
                        self._handle_send(
                            process_id, shard_id, process_id, msg
                        )
                    else:
                        self._schedule_message(
                            from_region,
                            ("process", to),
                            (_SEND, process_id, shard_id, to, msg),
                        )
            elif isinstance(action, ToForward):
                self._handle_send(process_id, shard_id, process_id, action.msg)
            else:
                raise TypeError(f"unsupported action {action!r}")

    # -- scheduling (runner.rs:379-557) ---------------------------------

    def _schedule_submit(self, from_region, process_id, cmd,
                         extra_delay: int = 0) -> None:
        if self.shard_count > 1:
            # client-side aggregation registers before the submit leaves
            # (client_server_task Register, run/task/server/client.rs)
            self._client_pending[cmd.rifl] = CommandResultBuilder(
                cmd.rifl, cmd.total_key_count()
            )
        self._schedule_message(
            from_region, ("process", process_id),
            (_SUBMIT, process_id, cmd), extra_delay=extra_delay,
        )

    def _schedule_to_client(self, from_region, cmd_result) -> None:
        client_id = cmd_result.rifl.source
        self._schedule_message(
            from_region,
            ("client", client_id),
            (_TO_CLIENT, client_id, cmd_result),
        )

    def _schedule_message(self, from_region, to_region, action,
                          extra_delay: int = 0) -> None:
        from_ = self._compute_region(from_region)
        to = self._compute_region(to_region)
        distance = self._distance(from_, to)
        if self.reorder_messages:
            distance = int(distance * self.rng.uniform(0.0, 10.0))
        # traffic think delay (submits only): added AFTER the reorder
        # scaling, exactly like the engine adds think to the submit's
        # unscaled base time rather than to its wire delay
        distance += extra_delay
        # tie-break key: (message, src, emission index on the (src, dst)
        # channel), src-major — the same total order the device engine
        # computes without a global heap. The counter is per channel so
        # its values are only ever compared between messages both sides
        # enumerate in the same order (FIFO per channel), whatever the
        # global interleaving of handler invocations looks like.
        src_key = self._region_key(from_region)
        chan = (src_key, self._region_key(to_region))
        chan_seq = self._chan_seq.get(chan, 0) + 1
        self._chan_seq[chan] = chan_seq
        if (
            self._fault is not None
            and from_region[0] == "process"
            and to_region[0] == "process"
            and from_region[1] != to_region[1]
        ):
            # fault wire model, after the channel counter ticked: lost
            # messages keep their emission index, exactly like the
            # device's emission choke point (engine/faults.py)
            distance, lost = self._fault.wire(
                from_region[1] - 1,
                to_region[1] - 1,
                self.simulation.time.millis(),
                distance,
                chan_seq,
                self._drop_table,
                self._jitter_table,
            )
            if lost:
                return
        self.schedule.schedule(
            self.simulation.time,
            distance,
            action,
            key=(KIND_MESSAGE, src_key, chan_seq),
        )

    @staticmethod
    def _region_key(message_region) -> int:
        kind, ident = message_region
        return ident if kind == "process" else _CLIENT_SRC_OFFSET + ident

    def _schedule_periodic(self, process_id, event, delay) -> None:
        self.schedule.schedule(
            self.simulation.time, delay, (_PERIODIC, process_id, event, delay)
        )

    def _schedule_executor_cleanup(self, process_id, delay) -> None:
        self.schedule.schedule(
            self.simulation.time,
            delay,
            (_EXECUTOR_CLEANUP, process_id, delay),
        )

    def _schedule_executed_notification(self, process_id, delay) -> None:
        self.schedule.schedule(
            self.simulation.time,
            delay,
            (_EXECUTED_NOTIFICATION, process_id, delay),
        )

    def _compute_region(self, message_region) -> str:
        kind, id_ = message_region
        if kind == "process":
            return self.process_to_region[id_]
        return self.client_to_region[id_]

    def _distance(self, from_: str, to: str) -> int:
        """Half the ping latency (runner.rs:575-595)."""
        from_to = self.planet.ping_latency(from_, to)
        assert from_to is not None
        if self.make_distances_symmetric:
            to_from = self.planet.ping_latency(to, from_)
            assert to_from is not None
            ping = (from_to + to_from) // 2
        else:
            ping = from_to
        return ping // 2

    # -- outputs (runner.rs:597-681) ------------------------------------

    def _metrics(self) -> dict:
        out = {}
        for process_id in self.process_to_region:
            process, executor, _, _ = self.simulation.get_process(process_id)
            out[process_id] = (process.metrics(), executor.metrics())
        return out

    def _executors_monitors(self) -> dict:
        out = {}
        for process_id in self.process_to_region:
            _, executor, _, _ = self.simulation.get_process(process_id)
            out[process_id] = executor.monitor()
        return out

    def _clients_latencies(self) -> Dict[str, Tuple[int, Histogram]]:
        out: Dict[str, Tuple[int, Histogram]] = {}
        for client_id, region in self.client_to_region.items():
            client, _ = self.simulation.get_client(client_id)
            issued, histogram = out.get(region, (0, Histogram()))
            issued += client.issued_commands()
            if self._ol_table is not None:
                # open loop: queue-delay-inclusive ms records owned by
                # the runner (see _ol_to_client) — the Client-side
                # submit-to-response data would omit the arrival wait
                for latency_ms in self._ol_lat[client_id]:
                    histogram.increment(latency_ms)
            else:
                for latency_us in client.data.latency_data():
                    histogram.increment(latency_us // 1000)
            out[region] = (issued, histogram)
        return out

"""Simulation state: processes, executors, clients and the simulated clock.

Capability parity with ``fantoch/src/sim/simulation.rs``: holds every
process (protocol, executor, aggregate-pending) and client, delivers
messages synchronously, and exposes ``start_clients`` /
``forward_to_client`` used by the runner loop.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from ..client.client import Client
from ..core.command import Command, CommandResult
from ..core.ids import ClientId, ProcessId
from ..core.timing import SimTime
from ..executor.base import AggregatePending, Executor
from ..protocol.base import Protocol


class Simulation:
    def __init__(self) -> None:
        self.time = SimTime()
        self.processes: Dict[
            ProcessId, Tuple[Protocol, Executor, AggregatePending]
        ] = {}
        self.clients: Dict[ClientId, Client] = {}

    def register_process(self, process: Protocol, executor: Executor) -> None:
        process_id = process.id()
        assert process_id not in self.processes
        pending = AggregatePending(process_id, process.shard_id())
        self.processes[process_id] = (process, executor, pending)

    def register_client(self, client: Client) -> None:
        assert client.id() not in self.clients
        self.clients[client.id()] = client

    def start_clients(self) -> List[Tuple[ClientId, ProcessId, Command]]:
        out = []
        for client in self.clients.values():
            nxt = client.cmd_send(self.time)
            assert nxt is not None, "clients should submit at least one command"
            target_shard, cmd = nxt
            out.append((client.id(), client.shard_process(target_shard), cmd))
        return out

    def forward_to_client(
        self, cmd_result: CommandResult
    ) -> Optional[Tuple[ProcessId, Command]]:
        client = self.clients[cmd_result.rifl.source]
        client.cmd_recv(cmd_result.rifl, self.time)
        nxt = client.cmd_send(self.time)
        if nxt is None:
            return None
        target_shard, cmd = nxt
        return client.shard_process(target_shard), cmd

    def get_process(
        self, process_id: ProcessId
    ) -> Tuple[Protocol, Executor, AggregatePending, SimTime]:
        process, executor, pending = self.processes[process_id]
        return process, executor, pending, self.time

    def get_client(self, client_id: ClientId) -> Tuple[Client, SimTime]:
        return self.clients[client_id], self.time

"""Discrete-event simulation driver (reference: ``fantoch/src/sim/``)."""

from .runner import Runner
from .schedule import Schedule
from .simulation import Simulation

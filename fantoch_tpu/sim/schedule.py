"""Discrete-event schedule (min-heap on milliseconds).

Capability parity with ``fantoch/src/sim/schedule.rs``: schedule actions at
``now + delay`` and pop them in time order, advancing the simulated clock.
The reference's BinaryHeap breaks same-time ties arbitrarily
(schedule.rs:109-119); here ties break by an explicit, schedule-independent
key — ``(kind_rank, src_key, chan_seq)``, then insertion order — so that
the device engine (which processes events out of global order under its
conservative-lookahead rule) resolves every tie identically without having
to reproduce the oracle's global insertion sequence. Periodic events rank
before message deliveries at the same instant; messages order by source,
then by the source's per-(src, dst)-channel emission counter — src-major,
so counter values are only ever compared within one FIFO channel, which
both sides enumerate identically.
"""

from __future__ import annotations

import heapq
from typing import Generic, List, Optional, Tuple, TypeVar

from ..core.timing import SimTime

A = TypeVar("A")

# kind ranks for the tie-break key
KIND_PERIODIC = 0
KIND_MESSAGE = 1


class Schedule(Generic[A]):
    def __init__(self) -> None:
        self._heap: List[Tuple[int, int, int, int, int, A]] = []
        self._seq = 0

    def schedule(
        self,
        time: SimTime,
        delay_ms: int,
        action: A,
        key: Tuple[int, int, int] = (KIND_PERIODIC, 0, 0),
    ) -> None:
        """``key`` = (kind_rank, src_key, chan_seq) for messages;
        insertion order is the final tie-break (and the only one
        periodic events rely on)."""
        self._seq += 1
        k1, k2, k3 = key
        heapq.heappush(
            self._heap,
            (time.millis() + delay_ms, k1, k2, k3, self._seq, action),
        )

    def next_action(self, time: SimTime) -> Optional[A]:
        if not self._heap:
            return None
        entry = heapq.heappop(self._heap)
        time.set_millis(entry[0])
        return entry[-1]

    def peek_millis(self) -> Optional[int]:
        """Arrival time of the next action without popping it — the
        fault-plan horizon check stops the loop *before* handling any
        event at or past the horizon, matching the device engine's
        qualification mask exactly."""
        if not self._heap:
            return None
        return self._heap[0][0]

    def __len__(self) -> int:
        return len(self._heap)

"""Discrete-event schedule (min-heap on milliseconds).

Capability parity with ``fantoch/src/sim/schedule.rs``: schedule actions at
``now + delay`` and pop them in time order, advancing the simulated clock.
Unlike the reference's BinaryHeap (which breaks same-time ties arbitrarily,
schedule.rs:109-119), ties here break by insertion order, making runs
bit-reproducible — a property the device engine's differential tests rely
on.
"""

from __future__ import annotations

import heapq
from typing import Generic, List, Optional, Tuple, TypeVar

from ..core.timing import SimTime

A = TypeVar("A")


class Schedule(Generic[A]):
    def __init__(self) -> None:
        self._heap: List[Tuple[int, int, A]] = []
        self._seq = 0

    def schedule(self, time: SimTime, delay_ms: int, action: A) -> None:
        self._seq += 1
        heapq.heappush(
            self._heap, (time.millis() + delay_ms, self._seq, action)
        )

    def next_action(self, time: SimTime) -> Optional[A]:
        if not self._heap:
            return None
        schedule_time, _, action = heapq.heappop(self._heap)
        time.set_millis(schedule_time)
        return action

    def __len__(self) -> int:
        return len(self._heap)

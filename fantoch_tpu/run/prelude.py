"""Wire envelopes for the run layer (fantoch/src/run/prelude.rs).

Peer handshake: ``ProcessHi`` (task/server/mod.rs:132-224). Client
handshake: ``ClientHi`` with the connection's client ids
(task/client/mod.rs:35-120). After the handshake each direction carries
tagged tuples (tag, payload...):

peer → peer:
  ("msg", from_id, from_shard, protocol_message)
  ("exec", from_shard, executor_info)   cross-shard executor traffic
                                        (executor/graph Requests)
  ("ping", nonce) / ("pong", nonce)     RTT measurement (ping.rs)

client → server:
  ("register", command)                 AggregatePending.wait_for
                                        (task/server/client.rs:206-243)
  ("submit", command)
client ← server:
  ("result", command_result)
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List

from ..core.ids import ClientId, ProcessId, ShardId


@dataclass
class ProcessHi:
    process_id: ProcessId
    shard_id: ShardId


@dataclass
class ClientHi:
    client_ids: List[ClientId]

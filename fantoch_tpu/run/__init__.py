"""L5 real runtime: processes and clients over TCP (asyncio).

Capability parity with ``fantoch/src/run/`` (run/mod.rs:97-447): a
process binds a peer listener and a client listener, connects to every
peer with a ``ProcessHi`` handshake, spawns reader/writer tasks per
connection, a protocol worker loop, executor tasks routed by key hash,
periodic-event tasks, a metrics logger and an execution logger; clients
connect to the closest process per shard and drive closed- or open-loop
workloads.

Where the reference runs W parallel protocol workers over lock-free
Atomic/Locked state (run/mod.rs:180-183 asserts ``workers > 1 ⇒
P::parallel()``), the host protocols here are the *Sequential* variants,
so the runtime enforces the same rule the reference does for them: one
protocol worker per process. Executor pools are key-hash routed
(executor/mod.rs:148-167) and allowed only for executors declaring
``KEY_HASH_ROUTED`` per-key independence (the basic executor); others
run as a single instance.
"""

from .client import ClientHandle, client
from .prelude import ClientHi, ProcessHi
from .rw import Connection
from .server import ProcessHandle, process

__all__ = [
    "ClientHandle",
    "ClientHi",
    "Connection",
    "ProcessHandle",
    "ProcessHi",
    "client",
    "process",
]

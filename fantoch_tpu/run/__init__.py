"""L5 real runtime: processes and clients over TCP (asyncio).

Capability parity with ``fantoch/src/run/`` (run/mod.rs:97-447): a
process binds a peer listener and a client listener, connects to every
peer with a ``ProcessHi`` handshake, spawns reader/writer tasks per
connection, a protocol worker loop, executor tasks routed by key hash,
periodic-event tasks, a metrics logger and an execution logger; clients
connect to the closest process per shard and drive closed- or open-loop
workloads.

Like the reference, W parallel protocol workers are supported for
``parallel()`` protocols (run/mod.rs:180-198): messages route by the
MessageIndex analog (``Message.WORKER`` — dot/slot shift past the two
reserved workers, GC/leader on worker 0, clock-bump/acceptor on
worker 1), submits are pre-dotted server-side, and cooperative
scheduling gives each ``handle()`` the atomicity the reference's
Atomic/Locked variants provide (``TempoAtomic`` additionally backs its
clocks with the native lock-free CAS map). Executor pools are key-hash
routed (executor/mod.rs:148-167), with cross-key state shared between
pool members where needed (the table executor's stability counts);
peers get ``multiplexing`` parallel TCP connections.
"""

from .client import ClientHandle, client
from .prelude import ClientHi, ProcessHi
from .rw import Connection
from .server import ProcessHandle, process

__all__ = [
    "ClientHandle",
    "ClientHi",
    "Connection",
    "ProcessHandle",
    "ProcessHi",
    "client",
    "process",
]

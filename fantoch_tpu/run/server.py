"""Process-side runtime (fantoch/src/run/mod.rs:97-416 and
fantoch/src/run/task/server/).

``process()`` boots one replica: peer listener + ``connect_to_all`` with
a ``ProcessHi`` handshake, a reader task per peer connection, an
optional ping round that sorts processes by RTT (ping.rs:13-100), the
protocol worker loop (task/server/process.rs:96-300 — a select over
peer messages, client submits, periodic events and executor
notifications, here one work queue), executor tasks routed by key hash
(task/server/executor.rs:52-150), a client listener with per-connection
registration (task/server/client.rs:80-244), a periodic metrics logger
(metrics_logger.rs) and an execution-info logger replayable by
``tools/executor_replay.py`` (execution_logger.rs:11-60).

W protocol workers per process (run/mod.rs:180-198): messages route by
``Message.WORKER`` (the MessageIndex analog — dot/slot messages shift
past the two reserved workers, GC/leader traffic pins to worker 0,
clock-bump/acceptor roles to worker 1), submits are pre-dotted by a
server-side generator so a dot's lifetime stays on one worker, and the
cooperative scheduler gives every ``handle()`` the per-message
atomicity the reference's Atomic/Locked variants provide. Executor
pools are key-hash routed (executor/mod.rs:148-167); pool construction
lives on the executor class so cross-key state can be shared between
members (executor/base.py). Peers get ``multiplexing`` parallel TCP
connections with round-robin sends (task/server/mod.rs:226-310).
"""

from __future__ import annotations

import asyncio
import copy
import pickle
import time as _time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple, Type

from ..core.command import Command, CommandResultBuilder
from ..core.config import Config
from ..core.ids import DotGen, ProcessId, Rifl, ShardId
from ..core.timing import RunTime
from ..core.trace import trace, tracer
from ..core.util import key_hash
from ..executor.base import AggregatePending, Executor
from ..protocol.base import Protocol, ToForward, ToSend
from .prelude import ClientHi, ProcessHi
from .rw import Connection

_GC_EXECUTOR = 0

log = tracer("run.server")


@dataclass
class ProcessHandle:
    """In-process view of a running replica — what the reference's
    ``run_test_with_inspect_fun`` reads back over its inspect channel
    (run/mod.rs:833-848)."""

    process_id: ProcessId
    shard_id: ShardId
    protocol: Protocol
    executors: List[Executor]
    task: "asyncio.Task[None]" = None  # type: ignore[assignment]
    stop_event: asyncio.Event = field(default_factory=asyncio.Event)
    started: asyncio.Event = field(default_factory=asyncio.Event)

    def metrics(self):
        return self.protocol.metrics()

    def executor_metrics(self):
        return [e.metrics() for e in self.executors]

    def monitors(self):
        return [e.monitor() for e in self.executors if e.monitor() is not None]

    async def stop(self) -> None:
        self.stop_event.set()
        if self.task is not None:
            await self.task


def _executor_pool(
    protocol_cls: Type[Protocol],
    process_id: ProcessId,
    shard_id: ShardId,
    config: Config,
    executors: int,
) -> List[Executor]:
    executor_cls = protocol_cls.EXECUTOR  # type: ignore[attr-defined]
    # pool construction (and the per-key-independence gate) lives on
    # the executor class: executors with cross-key state override
    # ``pool`` to share it between members (executor/base.py)
    return executor_cls.pool(process_id, shard_id, config, executors)


def _route_info(info: Any, executors: int) -> int:
    """Executor-pool routing: infos carrying a ``POOL_INDEX`` class
    attribute use the reference's MessageIndex scheme with the do_index
    formula (pool.rs:114-123) — the graph executor's Add/RequestReply
    pin to executor 0 and Request/Executed to executor 1
    (graph/executor.rs:234-253); keyed infos use key-hash routing
    (``MessageKey``, executor/mod.rs:148-167); keyless info goes to the
    reserved executor 0."""
    if executors == 1:
        return _GC_EXECUTOR
    pool_index = getattr(info, "POOL_INDEX", None)
    if pool_index is not None:
        reserved, index = pool_index
        if reserved < executors:
            return reserved + index % (executors - reserved)
        return index % executors
    key = getattr(info, "key", None)
    if key is None:
        return _GC_EXECUTOR
    return key_hash(key) % executors


# the reference's reserved worker indexes (lib.rs:44-76): worker 0 is
# the GC (and leader) worker, worker 1 the aux role (Tempo clock bump,
# FPaxos acceptor); dot/slot-indexed messages shift past both
GC_WORKER = 0
AUX_WORKER = 1
WORKERS_RESERVED = 2


def _route_msg(msg: Any, workers: int) -> int:
    """``MessageIndex`` routing (protocol/mod.rs:182-194): pick one of
    W protocol workers by the message's ``WORKER`` kind."""
    if workers == 1:
        return 0
    kind = getattr(msg, "WORKER", "dot")
    if kind in ("gc", "leader"):
        return GC_WORKER
    if kind == "aux":
        return AUX_WORKER % workers
    if kind == "slot":
        return _shift_index(int(msg.slot), workers)
    dot = getattr(msg, "dot", None)
    if dot is None:
        return GC_WORKER
    return _shift_index(int(dot.sequence), workers)


def _shift_index(value: int, workers: int) -> int:
    """``worker_index_shift`` (lib.rs:63-76): land past the reserved
    workers when there are more than the reserved two."""
    if workers > WORKERS_RESERVED:
        return WORKERS_RESERVED + value % (workers - WORKERS_RESERVED)
    return value % workers


_EVENT_WORKER = {"gc": GC_WORKER, "leader": GC_WORKER, "aux": AUX_WORKER}


async def process(
    protocol_cls: Type[Protocol],
    process_id: ProcessId,
    shard_id: ShardId,
    config: Config,
    *,
    peer_addresses: Dict[ProcessId, Tuple[str, int]],
    peer_shards: Dict[ProcessId, ShardId],
    peer_sock=None,
    client_sock=None,
    listen: Tuple[str, int] = None,
    client_listen: Tuple[str, int] = None,
    sorted_processes: Optional[Sequence[Tuple[ProcessId, ShardId]]] = None,
    workers: int = 1,
    executors: int = 1,
    multiplexing: int = 1,
    delay_ms: int = 0,
    compress: bool = False,
    metrics_file: Optional[str] = None,
    metrics_interval_ms: int = 1000,
    execution_log: Optional[str] = None,
    connect_retries: int = 100,
) -> ProcessHandle:
    """Boot a replica; returns a :class:`ProcessHandle` whose ``task``
    completes after ``handle.stop_event`` is set and shutdown finishes.

    Pass either pre-bound listening sockets (``peer_sock``/
    ``client_sock`` — tests bind port 0 first so addresses are known
    up front, like the reference's random localhost ports,
    run/mod.rs:575-849) or ``listen``/``client_listen`` addresses.
    ``peer_addresses`` maps every *other* process to its peer-listener
    address; ``delay_ms`` injects the reference's artificial
    per-connection delay (delay.rs:7-40)."""
    from ..core.trace import init_tracing

    init_tracing()  # $FANTOCH_TRACE; idempotent, keeps explicit setups
    # run/mod.rs:180-183: worker parallelism needs a protocol whose
    # state tolerates it. Python workers are cooperative asyncio tasks
    # in one thread — every handle() runs to completion unpreempted, so
    # sharing one protocol instance gives exactly the per-message
    # atomicity the reference's Atomic/Locked variants provide.
    assert workers == 1 or protocol_cls.parallel(), (
        f"{protocol_cls.__name__} does not support workers > 1"
    )
    protocol = protocol_cls(process_id, shard_id, config)
    pool = _executor_pool(
        protocol_cls, process_id, shard_id, config, executors
    )
    handle = ProcessHandle(process_id, shard_id, protocol, pool)
    handle.task = asyncio.create_task(
        _process_main(
            protocol,
            pool,
            handle,
            config,
            peer_addresses=peer_addresses,
            peer_shards=peer_shards,
            peer_sock=peer_sock,
            client_sock=client_sock,
            listen=listen,
            client_listen=client_listen,
            sorted_processes=sorted_processes,
            workers=workers,
            multiplexing=multiplexing,
            delay_ms=delay_ms,
            compress=compress,
            metrics_file=metrics_file,
            metrics_interval_ms=metrics_interval_ms,
            execution_log=execution_log,
            connect_retries=connect_retries,
        ),
        name=f"process-{process_id}",
    )
    return handle


async def _process_main(
    protocol: Protocol,
    pool: List[Executor],
    handle: ProcessHandle,
    config: Config,
    **kw,
) -> None:
    rt = _Runtime(protocol, pool, handle, config, **kw)
    try:
        await rt.run()
    finally:
        await rt.shutdown()


class _Runtime:
    def __init__(
        self,
        protocol: Protocol,
        pool: List[Executor],
        handle: ProcessHandle,
        config: Config,
        *,
        peer_addresses,
        peer_shards,
        peer_sock,
        client_sock,
        listen,
        client_listen,
        sorted_processes,
        workers,
        multiplexing,
        delay_ms,
        compress,
        metrics_file,
        metrics_interval_ms,
        execution_log,
        connect_retries,
    ):
        self.protocol = protocol
        self.pool = pool
        self.handle = handle
        self.config = config
        self.process_id = handle.process_id
        self.shard_id = handle.shard_id
        self.time = RunTime()
        self.peer_addresses = peer_addresses
        self.peer_shards = peer_shards
        self.peer_sock = peer_sock
        self.client_sock = client_sock
        self.peer_server = None
        self.client_server = None
        self.listen = listen
        self.client_listen = client_listen
        self.sorted_processes = sorted_processes
        self.multiplexing = max(1, multiplexing)
        self.delay_ms = delay_ms
        self.compress = compress
        self.metrics_file = metrics_file
        self.metrics_interval_ms = metrics_interval_ms
        self.execution_log = execution_log
        self.connect_retries = connect_retries

        # one select queue per protocol worker (the reference's W
        # process_task loops, each selecting over 4 channels; a queue
        # per worker keeps per-worker arrival order total). Messages
        # route by MessageIndex (_route_msg); submits by a server-side
        # dot generator (the AtomicDotGen analog, run/mod.rs:285-291)
        # so a dot's whole lifetime stays on one worker.
        self.workers = workers
        self.works: List["asyncio.Queue[Tuple]"] = [
            asyncio.Queue() for _ in range(workers)
        ]
        self.dot_gen = DotGen(self.process_id)
        self.exec_queues: List["asyncio.Queue[Tuple]"] = [
            asyncio.Queue() for _ in pool
        ]
        # outgoing peer connections (sends ride these; receives ride the
        # connections peers opened to us)
        # outgoing connections per peer: ``multiplexing`` parallel TCP
        # connections (run/mod.rs:113, task/server/mod.rs:226-310);
        # sends spread round-robin like the reference's random writer
        # pick, so cross-connection ordering is NOT guaranteed — the
        # protocols' buffered-commit paths tolerate that by design
        self.out: Dict[ProcessId, List[Connection]] = {}
        self._out_rr: Dict[ProcessId, int] = {}
        self.client_conns: Dict[int, Connection] = {}
        self.client_pending: Dict[int, AggregatePending] = {}
        # rifl → client-connection id that registered it
        self.rifl_conn: Dict[Rifl, int] = {}
        # multi-shard: rifl → [conn id, partials still expected from
        # this shard] (entries drop at 0 — partial counts are known
        # from the command's key set)
        self.rifl_shard_conn: Dict[Rifl, List[int]] = {}
        # multi-shard partials that raced ahead of their register
        # (cross-connection ordering is not guaranteed: the client
        # registers on shard B's connection while submitting on shard
        # A's). Entries are (monotonic time, result); a sweeper evicts
        # entries no register ever claims — every process of a shard
        # executes every command, but only the client's connected
        # process has a register for it.
        self.partial_buf: Dict[Rifl, List[Tuple[float, Any]]] = {}
        self.partial_buf_ttl_s = 10.0
        # rifl -> eviction time for partials the sweeper dropped; a
        # late register finds its rifl here and fails explicitly
        # instead of waiting forever for partials that are gone
        self.partial_evicted: Dict[Rifl, float] = {}
        self.tasks: List[asyncio.Task] = []
        self.exec_log_fh = None
        self._conn_seq = 0
        self._rtt: Dict[ProcessId, float] = {}

    # -- bootstrap -----------------------------------------------------

    async def run(self) -> None:
        if self.execution_log:
            self.exec_log_fh = open(self.execution_log, "ab")
        # bootstrap races stop_event: when the whole cluster is being
        # stopped, peers may never come up, so a SIGTERM that lands
        # mid-connect (or mid-ping) must abort the bootstrap promptly
        # instead of letting it retry toward peers that are gone
        boot = asyncio.create_task(self._bootstrap(), name="bootstrap")
        stop = asyncio.create_task(self.handle.stop_event.wait())
        try:
            done, _ = await asyncio.wait(
                {boot, stop}, return_when=asyncio.FIRST_COMPLETED
            )
            if boot in done:
                boot.result()  # propagate bootstrap failures
            else:
                boot.cancel()
                try:
                    await boot
                except (asyncio.CancelledError, Exception):
                    pass
                return
        finally:
            stop.cancel()
        await self.handle.stop_event.wait()

    async def _bootstrap(self) -> None:
        await self._start_listeners()
        await self._connect_to_all()
        await self._ping_round()
        self._discover()
        self._start_tasks()
        self.handle.started.set()

    async def _start_listeners(self) -> None:
        if self.peer_sock is not None:
            self.peer_server = await asyncio.start_server(
                self._accept_peer, sock=self.peer_sock
            )
        else:
            host, port = self.listen
            self.peer_server = await asyncio.start_server(
                self._accept_peer, host, port
            )
        if self.client_sock is not None:
            self.client_server = await asyncio.start_server(
                self._accept_client, sock=self.client_sock
            )
        else:
            host, port = self.client_listen
            self.client_server = await asyncio.start_server(
                self._accept_client, host, port
            )

    async def _connect_to_all(self) -> None:
        """Open ``multiplexing`` outgoing connections per peer, each
        with its own hi handshake (task/server/mod.rs:40-310; incoming
        connections carry the peer's sends to us)."""
        for peer, (host, port) in self.peer_addresses.items():
            conns = []
            for _m in range(self.multiplexing):
                for attempt in range(self.connect_retries):
                    try:
                        reader, writer = await asyncio.open_connection(
                            host, port
                        )
                        break
                    except ConnectionError:
                        await asyncio.sleep(0.05)
                else:
                    raise ConnectionError(f"cannot reach peer {peer}")
                conn = Connection(reader, writer, compress=self.compress)
                await conn.send(ProcessHi(self.process_id, self.shard_id))
                conns.append(conn)
            self.out[peer] = conns
            self._out_rr[peer] = 0

    def _pick_out(self, peer: ProcessId) -> Connection:
        """Round-robin over the peer's multiplexed connections (the
        reference picks uniformly at random, process.rs:309-319;
        round-robin keeps tests deterministic with the same
        no-cross-connection-ordering contract)."""
        conns = self.out[peer]
        i = self._out_rr[peer]
        self._out_rr[peer] = (i + 1) % len(conns)
        return conns[i]

    async def _accept_peer(self, reader, writer) -> None:
        conn = Connection(
            reader, writer, delay_ms=self.delay_ms, compress=self.compress
        )
        hi = await conn.recv()
        if not isinstance(hi, ProcessHi):
            await conn.close()
            return
        self._spawn(
            self._peer_reader(hi.process_id, hi.shard_id, conn),
            f"reader-{self.process_id}<-{hi.process_id}",
        )

    async def _ping_round(self) -> None:
        """One RTT measurement per peer (ping.rs:13-100); used for
        RTT-sorted discovery when ``sorted_processes`` is not given."""
        for peer, conns in self.out.items():
            t0 = _time.monotonic()
            await conns[0].send(("ping", t0))
            # pongs come back on the incoming connection; readers fill
            # self._rtt. Give them a moment without blocking the boot on
            # a slow peer.
        if self.sorted_processes is None:
            deadline = _time.monotonic() + 1.0
            while (
                len(self._rtt) < len(self.out)
                and _time.monotonic() < deadline
            ):
                await asyncio.sleep(0.01)

    def _discover(self) -> None:
        if self.sorted_processes is not None:
            sorted_ps = list(self.sorted_processes)
        else:
            by_rtt = sorted(
                self.out, key=lambda p: self._rtt.get(p, float("inf"))
            )
            sorted_ps = [(self.process_id, self.shard_id)] + [
                (p, self.peer_shards[p]) for p in by_rtt
            ]
        connected, _ = self.protocol.discover(sorted_ps)
        assert connected, "discovery failed: quorum unavailable"
        log.info(
            "process %s (shard %s) discovered %s",
            self.process_id, self.shard_id, sorted_ps,
        )

    def _spawn(self, coro, name: str) -> asyncio.Task:
        """Supervised spawn: an exception in any task (protocol.handle,
        executor.handle, a reader...) stops the whole replica loudly via
        ``stop_event`` instead of leaving it up but silently stuck with
        clients hanging — mirroring the reference runtime's fail-fast
        behavior when a task dies."""
        task = asyncio.create_task(coro, name=name)

        def _done(t: asyncio.Task) -> None:
            if t.cancelled():
                return
            exc = t.exception()
            if exc is not None:
                log.error(
                    "process %s task %r died: %r",
                    self.process_id, name, exc, exc_info=exc,
                )
                self.handle.stop_event.set()

        task.add_done_callback(_done)
        self.tasks.append(task)
        return task

    def _start_tasks(self) -> None:
        for w in range(self.workers):
            self._spawn(self._worker_loop(w), f"worker-{w}")
        for i in range(len(self.pool)):
            self._spawn(self._executor_loop(i), f"executor-{i}")
        for event, interval in self.protocol.periodic_events():
            self._spawn(
                self._periodic_loop(event, interval), f"periodic-{event}"
            )
        self._spawn(
            self._executed_notification_loop(), "executed-notification"
        )
        cleanup = self.config.executor_cleanup_interval_ms
        if cleanup:
            self._spawn(self._executor_cleanup_loop(cleanup), "cleanup")
        if self.metrics_file:
            self._spawn(self._metrics_logger_loop(), "metrics-logger")
        if self.config.shard_count > 1:
            self._spawn(self._partial_buf_sweeper(), "partial-sweeper")

    # -- readers -------------------------------------------------------

    async def _peer_reader(self, peer, peer_shard, conn: Connection) -> None:
        while True:
            msg = await conn.recv()
            if msg is None:
                return
            tag = msg[0]
            if tag == "msg":
                _, from_id, from_shard, pmsg = msg
                await self.works[_route_msg(pmsg, self.workers)].put(
                    ("msg", from_id, from_shard, pmsg)
                )
            elif tag == "exec":
                _, from_shard, info = msg
                await self.exec_queues[
                    _route_info(info, len(self.pool))
                ].put(("info", info))
            elif tag == "ping":
                # a ping can arrive while our own connect_to_all is
                # still retrying; answer from a side task so the reader
                # never stalls protocol traffic behind the wait
                self._spawn(self._pong(peer, msg[1]), f"pong-{peer}")
            elif tag == "pong":
                self._rtt[peer] = _time.monotonic() - msg[1]

    async def _pong(self, peer, nonce) -> None:
        for _ in range(200):
            out = self.out.get(peer)
            if out:
                await out[0].send(("pong", nonce))
                return
            await asyncio.sleep(0.01)

    async def _accept_client(self, reader, writer) -> None:
        conn = Connection(
            reader, writer, delay_ms=self.delay_ms, compress=self.compress
        )
        hi = await conn.recv()
        if not isinstance(hi, ClientHi):
            await conn.close()
            return
        self._conn_seq += 1
        conn_id = self._conn_seq
        self.client_conns[conn_id] = conn
        self.client_pending[conn_id] = AggregatePending(
            self.process_id, self.shard_id
        )
        self._spawn(
            self._client_reader(conn_id, conn), f"client-conn-{conn_id}"
        )

    async def _client_reader(self, conn_id: int, conn: Connection) -> None:
        """task/server/client.rs:80-244: Register wires the rifl to this
        connection; Submit hands the command to the worker."""
        while True:
            msg = await conn.recv()
            if msg is None:
                self.client_conns.pop(conn_id, None)
                return
            tag = msg[0]
            if tag == "register":
                cmd: Command = msg[1]
                if self.config.shard_count == 1:
                    self.rifl_conn[cmd.rifl] = conn_id
                    self.client_pending[conn_id].wait_for(cmd)
                else:
                    # multi-shard: every shard's connected process sends
                    # partials; this side only tracks which connection
                    # wants them (client aggregates). Commands that do
                    # not touch this shard produce no partials here.
                    expected = cmd.key_count(self.shard_id)
                    if expected:
                        if self.partial_evicted.pop(cmd.rifl, None):
                            # partials already swept: the client would
                            # wait forever — fail the rifl explicitly
                            log.error(
                                "register for %s after partials were "
                                "evicted (register delayed > %ss)",
                                cmd.rifl, self.partial_buf_ttl_s,
                            )
                            await self._send_client(
                                conn_id,
                                conn,
                                ("error", cmd.rifl, "partials evicted"),
                            )
                            continue
                        self.rifl_shard_conn[cmd.rifl] = [conn_id, expected]
                        for _, er in self.partial_buf.pop(cmd.rifl, []):
                            await self._to_client(er)
            elif tag == "submit":
                cmd = msg[1]
                if self.workers > 1 and self.protocol.leaderless():
                    # pre-assign the dot so the submit routes to the
                    # worker that will own the dot's whole lifetime
                    # (the client-side AtomicDotGen analog,
                    # run/mod.rs:285-291)
                    dot = self.dot_gen.next_id()
                    w = _shift_index(int(dot.sequence), self.workers)
                elif self.workers > 1:
                    dot, w = None, GC_WORKER  # leader worker
                else:
                    dot, w = None, 0
                await self.works[w].put(("submit", dot, cmd))

    # -- the protocol worker -------------------------------------------

    async def _worker_loop(self, worker: int) -> None:
        queue = self.works[worker]
        while True:
            item = await queue.get()
            tag = item[0]
            if tag == "msg":
                _, from_id, from_shard, pmsg = item
                trace(
                    log, "p%s/w%s <- p%s: %s",
                    self.process_id, worker, from_id, pmsg,
                )
                self.protocol.handle(from_id, from_shard, pmsg, self.time)
            elif tag == "submit":
                self.protocol.submit(item[1], item[2], self.time)
            elif tag == "periodic":
                self.protocol.handle_event(item[1], self.time)
            elif tag == "executed":
                self.protocol.handle_executed(item[1], self.time)
            await self._send_to_processes_and_executors()

    async def _send_to_processes_and_executors(self) -> None:
        """task/server/process.rs:209-285: ToSend fans out over writer
        connections with one serialization, ToForward re-enters the work
        queue, execution info routes to the executor pool by key."""
        actions = self.protocol.to_processes()
        touched: set = set()
        for info in self.protocol.to_executors():
            await self.exec_queues[_route_info(info, len(self.pool))].put(
                ("info", info)
            )
        for action in actions:
            if isinstance(action, ToForward):
                await self.works[
                    _route_msg(action.msg, self.workers)
                ].put(("msg", self.process_id, self.shard_id, action.msg))
                continue
            assert isinstance(action, ToSend)
            targets = sorted(action.target)
            wire = None
            for to in targets:
                if to == self.process_id:
                    msg = (
                        copy.deepcopy(action.msg)
                        if len(targets) > 1
                        else action.msg
                    )
                    await self.works[
                        _route_msg(msg, self.workers)
                    ].put(("msg", self.process_id, self.shard_id, msg))
                else:
                    conn = self._pick_out(to)
                    if wire is None:
                        wire = conn.serialize(
                            (
                                "msg",
                                self.process_id,
                                self.shard_id,
                                action.msg,
                            )
                        )
                    conn.send_bytes_nowait(wire)
                    touched.add(conn)
        # drain only the connections this batch actually wrote (with
        # multiplexing, round-robin touches a subset per batch)
        for conn in touched:
            await conn.writer.drain()

    # -- executors -----------------------------------------------------

    async def _executor_loop(self, idx: int) -> None:
        """task/server/executor.rs:52-150."""
        executor = self.pool[idx]
        queue = self.exec_queues[idx]
        while True:
            item = await queue.get()
            tag = item[0]
            if tag == "info":
                if self.exec_log_fh is not None:
                    pickle.dump(item[1], self.exec_log_fh)
                executor.handle(item[1], self.time)
            elif tag == "cleanup":
                executor.cleanup(self.time)
            await self._drain_executor(executor)

    async def _drain_executor(self, executor: Executor) -> None:
        while True:
            infos = executor.to_executors()
            results = executor.to_clients()
            if not infos and not results:
                return
            for to_shard, info in infos:
                if to_shard == self.shard_id:
                    await self.exec_queues[
                        _route_info(info, len(self.pool))
                    ].put(("info", info))
                else:
                    target = self.protocol.bp.closest_process(to_shard)
                    await self._pick_out(target).send(
                        ("exec", self.shard_id, info)
                    )
            for er in results:
                await self._to_client(er)

    async def _send_client(self, conn_id: int, conn, payload) -> None:
        """Client-facing send: a client that died mid-run must not take
        the replica down (the supervised-task fail-fast is for internal
        bugs), so a reset connection just gets dropped."""
        try:
            await conn.send(payload)
        except ConnectionError:
            self.client_conns.pop(conn_id, None)

    async def _to_client(self, executor_result) -> None:
        rifl = executor_result.rifl
        if self.config.shard_count == 1:
            conn_id = self.rifl_conn.get(rifl)
            if conn_id is None:
                return  # registered at another process of this shard
            pending = self.client_pending[conn_id]
            cmd_result = pending.add_executor_result(executor_result)
            if cmd_result is not None:
                self.rifl_conn.pop(rifl, None)
                conn = self.client_conns.get(conn_id)
                if conn is not None:
                    await self._send_client(
                        conn_id, conn, ("result", cmd_result)
                    )
        else:
            entry = self.rifl_shard_conn.get(rifl)
            if entry is None:
                self.partial_buf.setdefault(rifl, []).append(
                    (_time.monotonic(), executor_result)
                )
                return
            conn_id, remaining = entry
            entry[1] = remaining - 1
            if entry[1] <= 0:
                del self.rifl_shard_conn[rifl]
            conn = self.client_conns.get(conn_id)
            if conn is not None:
                await self._send_client(
                    conn_id, conn, ("partial", executor_result)
                )

    # -- periodic tasks ------------------------------------------------

    async def _periodic_loop(self, event, interval_ms: int) -> None:
        w = _EVENT_WORKER.get(
            self.protocol.event_worker(event), GC_WORKER
        ) % self.workers
        while True:
            await asyncio.sleep(interval_ms / 1000)
            await self.works[w].put(("periodic", event))

    async def _executed_notification_loop(self) -> None:
        interval = self.config.executor_executed_notification_interval_ms
        while True:
            await asyncio.sleep(interval / 1000)
            for executor in self.pool:
                executed = executor.executed(self.time)
                if executed is not None:
                    # executed notifications feed protocol GC: the GC
                    # worker's role (executor.rs:281-330 ticks)
                    await self.works[GC_WORKER].put(("executed", executed))

    async def _executor_cleanup_loop(self, interval_ms: int) -> None:
        while True:
            await asyncio.sleep(interval_ms / 1000)
            for q in self.exec_queues:
                await q.put(("cleanup",))

    async def _partial_buf_sweeper(self) -> None:
        while True:
            await asyncio.sleep(self.partial_buf_ttl_s / 2)
            now = _time.monotonic()
            cutoff = now - self.partial_buf_ttl_s
            stale = [
                rifl
                for rifl, entries in self.partial_buf.items()
                if entries and entries[0][0] < cutoff
            ]
            for rifl in stale:
                del self.partial_buf[rifl]
                self.partial_evicted[rifl] = now
            # evictions nothing ever claimed age out too, so the
            # record itself cannot leak
            dead = now - 10 * self.partial_buf_ttl_s
            self.partial_evicted = {
                r: t for r, t in self.partial_evicted.items() if t >= dead
            }

    async def _metrics_logger_loop(self) -> None:
        """metrics_logger.rs: periodic (worker, metrics) snapshots."""
        while True:
            await asyncio.sleep(self.metrics_interval_ms / 1000)
            self._dump_metrics()

    def _dump_metrics(self) -> None:
        with open(self.metrics_file, "wb") as fh:
            pickle.dump(
                {
                    "process_id": self.process_id,
                    "shard_id": self.shard_id,
                    "protocol": self.protocol.metrics(),
                    "executors": [e.metrics() for e in self.pool],
                },
                fh,
            )

    # -- shutdown ------------------------------------------------------

    async def shutdown(self) -> None:
        if self.metrics_file:
            self._dump_metrics()
        for task in self.tasks:
            task.cancel()
        for task in self.tasks:
            try:
                await task
            except (asyncio.CancelledError, Exception):
                pass
        out_conns = [c for conns in self.out.values() for c in conns]
        for conn in out_conns + list(
            self.client_conns.values()
        ):
            try:
                await asyncio.wait_for(conn.close(), timeout=1)
            except (asyncio.TimeoutError, Exception):
                pass
        for server in (self.peer_server, self.client_server):
            if server is not None:
                # not wait_closed(): in 3.12 it blocks until every
                # handler connection closes, which deadlocks a cluster
                # stopping replica by replica
                server.close()
        if self.exec_log_fh is not None:
            self.exec_log_fh.close()

"""Process-side runtime (fantoch/src/run/mod.rs:97-416 and
fantoch/src/run/task/server/).

``process()`` boots one replica: peer listener + ``connect_to_all`` with
a ``ProcessHi`` handshake, a reader task per peer connection, an
optional ping round that sorts processes by RTT (ping.rs:13-100), the
protocol worker loop (task/server/process.rs:96-300 — a select over
peer messages, client submits, periodic events and executor
notifications, here one work queue), executor tasks routed by key hash
(task/server/executor.rs:52-150), a client listener with per-connection
registration (task/server/client.rs:80-244), a periodic metrics logger
(metrics_logger.rs) and an execution-info logger replayable by
``tools/executor_replay.py`` (execution_logger.rs:11-60).

One protocol worker per process: the host protocols are the reference's
*Sequential* state variants, for which the reference enforces
``workers == 1`` (run/mod.rs:180-183). Executor pools are key-hash
routed (executor/mod.rs:148-167) and allowed only for executors
declaring ``KEY_HASH_ROUTED`` per-key independence.
"""

from __future__ import annotations

import asyncio
import copy
import pickle
import time as _time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple, Type

from ..core.command import Command, CommandResultBuilder
from ..core.config import Config
from ..core.ids import ProcessId, Rifl, ShardId
from ..core.timing import RunTime
from ..core.trace import trace, tracer
from ..core.util import key_hash
from ..executor.base import AggregatePending, Executor
from ..protocol.base import Protocol, ToForward, ToSend
from .prelude import ClientHi, ProcessHi
from .rw import Connection

_GC_EXECUTOR = 0

log = tracer("run.server")


@dataclass
class ProcessHandle:
    """In-process view of a running replica — what the reference's
    ``run_test_with_inspect_fun`` reads back over its inspect channel
    (run/mod.rs:833-848)."""

    process_id: ProcessId
    shard_id: ShardId
    protocol: Protocol
    executors: List[Executor]
    task: "asyncio.Task[None]" = None  # type: ignore[assignment]
    stop_event: asyncio.Event = field(default_factory=asyncio.Event)
    started: asyncio.Event = field(default_factory=asyncio.Event)

    def metrics(self):
        return self.protocol.metrics()

    def executor_metrics(self):
        return [e.metrics() for e in self.executors]

    def monitors(self):
        return [e.monitor() for e in self.executors if e.monitor() is not None]

    async def stop(self) -> None:
        self.stop_event.set()
        if self.task is not None:
            await self.task


def _executor_pool(
    protocol_cls: Type[Protocol],
    process_id: ProcessId,
    shard_id: ShardId,
    config: Config,
    executors: int,
) -> List[Executor]:
    executor_cls = protocol_cls.EXECUTOR  # type: ignore[attr-defined]
    if executors > 1:
        # key-hash pools require per-key independence; configs asking
        # for a pool of any other executor are rejected at boot. (The
        # graph executor is ``parallel()`` in the reference only
        # through its executor-0-runs-the-graph request protocol,
        # executor/graph/mod.rs:54-67, which this runtime does not
        # implement; the table executor's cross-key stability counting
        # needs state shared between pool members.)
        assert getattr(executor_cls, "KEY_HASH_ROUTED", False), (
            f"{executor_cls.__name__} does not support key-hash executor"
            " pools in this runtime"
        )
    return [
        executor_cls(process_id, shard_id, config) for _ in range(executors)
    ]


def _route_info(info: Any, executors: int) -> int:
    """Key-hash routing (``MessageKey``, executor/mod.rs:148-167);
    keyless info goes to the reserved executor 0."""
    key = getattr(info, "key", None)
    if key is None or executors == 1:
        return _GC_EXECUTOR
    return key_hash(key) % executors


async def process(
    protocol_cls: Type[Protocol],
    process_id: ProcessId,
    shard_id: ShardId,
    config: Config,
    *,
    peer_addresses: Dict[ProcessId, Tuple[str, int]],
    peer_shards: Dict[ProcessId, ShardId],
    peer_sock=None,
    client_sock=None,
    listen: Tuple[str, int] = None,
    client_listen: Tuple[str, int] = None,
    sorted_processes: Optional[Sequence[Tuple[ProcessId, ShardId]]] = None,
    executors: int = 1,
    delay_ms: int = 0,
    compress: bool = False,
    metrics_file: Optional[str] = None,
    metrics_interval_ms: int = 1000,
    execution_log: Optional[str] = None,
    connect_retries: int = 100,
) -> ProcessHandle:
    """Boot a replica; returns a :class:`ProcessHandle` whose ``task``
    completes after ``handle.stop_event`` is set and shutdown finishes.

    Pass either pre-bound listening sockets (``peer_sock``/
    ``client_sock`` — tests bind port 0 first so addresses are known
    up front, like the reference's random localhost ports,
    run/mod.rs:575-849) or ``listen``/``client_listen`` addresses.
    ``peer_addresses`` maps every *other* process to its peer-listener
    address; ``delay_ms`` injects the reference's artificial
    per-connection delay (delay.rs:7-40)."""
    from ..core.trace import init_tracing

    init_tracing()  # $FANTOCH_TRACE; idempotent, keeps explicit setups
    protocol = protocol_cls(process_id, shard_id, config)
    pool = _executor_pool(
        protocol_cls, process_id, shard_id, config, executors
    )
    handle = ProcessHandle(process_id, shard_id, protocol, pool)
    handle.task = asyncio.create_task(
        _process_main(
            protocol,
            pool,
            handle,
            config,
            peer_addresses=peer_addresses,
            peer_shards=peer_shards,
            peer_sock=peer_sock,
            client_sock=client_sock,
            listen=listen,
            client_listen=client_listen,
            sorted_processes=sorted_processes,
            delay_ms=delay_ms,
            compress=compress,
            metrics_file=metrics_file,
            metrics_interval_ms=metrics_interval_ms,
            execution_log=execution_log,
            connect_retries=connect_retries,
        ),
        name=f"process-{process_id}",
    )
    return handle


async def _process_main(
    protocol: Protocol,
    pool: List[Executor],
    handle: ProcessHandle,
    config: Config,
    **kw,
) -> None:
    rt = _Runtime(protocol, pool, handle, config, **kw)
    try:
        await rt.run()
    finally:
        await rt.shutdown()


class _Runtime:
    def __init__(
        self,
        protocol: Protocol,
        pool: List[Executor],
        handle: ProcessHandle,
        config: Config,
        *,
        peer_addresses,
        peer_shards,
        peer_sock,
        client_sock,
        listen,
        client_listen,
        sorted_processes,
        delay_ms,
        compress,
        metrics_file,
        metrics_interval_ms,
        execution_log,
        connect_retries,
    ):
        self.protocol = protocol
        self.pool = pool
        self.handle = handle
        self.config = config
        self.process_id = handle.process_id
        self.shard_id = handle.shard_id
        self.time = RunTime()
        self.peer_addresses = peer_addresses
        self.peer_shards = peer_shards
        self.peer_sock = peer_sock
        self.client_sock = client_sock
        self.peer_server = None
        self.client_server = None
        self.listen = listen
        self.client_listen = client_listen
        self.sorted_processes = sorted_processes
        self.delay_ms = delay_ms
        self.compress = compress
        self.metrics_file = metrics_file
        self.metrics_interval_ms = metrics_interval_ms
        self.execution_log = execution_log
        self.connect_retries = connect_retries

        # the worker loop's single select queue (the reference's
        # process_task selects over 4 channels; one queue keeps their
        # arrival order total)
        self.work: "asyncio.Queue[Tuple]" = asyncio.Queue()
        self.exec_queues: List["asyncio.Queue[Tuple]"] = [
            asyncio.Queue() for _ in pool
        ]
        # outgoing peer connections (sends ride these; receives ride the
        # connections peers opened to us)
        self.out: Dict[ProcessId, Connection] = {}
        self.client_conns: Dict[int, Connection] = {}
        self.client_pending: Dict[int, AggregatePending] = {}
        # rifl → client-connection id that registered it
        self.rifl_conn: Dict[Rifl, int] = {}
        # multi-shard: rifl → [conn id, partials still expected from
        # this shard] (entries drop at 0 — partial counts are known
        # from the command's key set)
        self.rifl_shard_conn: Dict[Rifl, List[int]] = {}
        # multi-shard partials that raced ahead of their register
        # (cross-connection ordering is not guaranteed: the client
        # registers on shard B's connection while submitting on shard
        # A's). Entries are (monotonic time, result); a sweeper evicts
        # entries no register ever claims — every process of a shard
        # executes every command, but only the client's connected
        # process has a register for it.
        self.partial_buf: Dict[Rifl, List[Tuple[float, Any]]] = {}
        self.partial_buf_ttl_s = 10.0
        self.tasks: List[asyncio.Task] = []
        self.exec_log_fh = None
        self._conn_seq = 0
        self._rtt: Dict[ProcessId, float] = {}

    # -- bootstrap -----------------------------------------------------

    async def run(self) -> None:
        if self.execution_log:
            self.exec_log_fh = open(self.execution_log, "ab")
        await self._start_listeners()
        await self._connect_to_all()
        await self._ping_round()
        self._discover()
        self._start_tasks()
        self.handle.started.set()
        await self.handle.stop_event.wait()

    async def _start_listeners(self) -> None:
        if self.peer_sock is not None:
            self.peer_server = await asyncio.start_server(
                self._accept_peer, sock=self.peer_sock
            )
        else:
            host, port = self.listen
            self.peer_server = await asyncio.start_server(
                self._accept_peer, host, port
            )
        if self.client_sock is not None:
            self.client_server = await asyncio.start_server(
                self._accept_client, sock=self.client_sock
            )
        else:
            host, port = self.client_listen
            self.client_server = await asyncio.start_server(
                self._accept_client, host, port
            )

    async def _connect_to_all(self) -> None:
        """Open one outgoing connection per peer, say hi
        (task/server/mod.rs:40-224; incoming connections carry the
        peer's sends to us)."""
        for peer, (host, port) in self.peer_addresses.items():
            for attempt in range(self.connect_retries):
                try:
                    reader, writer = await asyncio.open_connection(host, port)
                    break
                except ConnectionError:
                    await asyncio.sleep(0.05)
            else:
                raise ConnectionError(f"cannot reach peer {peer}")
            conn = Connection(reader, writer, compress=self.compress)
            await conn.send(ProcessHi(self.process_id, self.shard_id))
            self.out[peer] = conn

    async def _accept_peer(self, reader, writer) -> None:
        conn = Connection(
            reader, writer, delay_ms=self.delay_ms, compress=self.compress
        )
        hi = await conn.recv()
        if not isinstance(hi, ProcessHi):
            await conn.close()
            return
        self.tasks.append(
            asyncio.create_task(
                self._peer_reader(hi.process_id, hi.shard_id, conn),
                name=f"reader-{self.process_id}<-{hi.process_id}",
            )
        )

    async def _ping_round(self) -> None:
        """One RTT measurement per peer (ping.rs:13-100); used for
        RTT-sorted discovery when ``sorted_processes`` is not given."""
        for peer, conn in self.out.items():
            t0 = _time.monotonic()
            await conn.send(("ping", t0))
            # pongs come back on the incoming connection; readers fill
            # self._rtt. Give them a moment without blocking the boot on
            # a slow peer.
        if self.sorted_processes is None:
            deadline = _time.monotonic() + 1.0
            while (
                len(self._rtt) < len(self.out)
                and _time.monotonic() < deadline
            ):
                await asyncio.sleep(0.01)

    def _discover(self) -> None:
        if self.sorted_processes is not None:
            sorted_ps = list(self.sorted_processes)
        else:
            by_rtt = sorted(
                self.out, key=lambda p: self._rtt.get(p, float("inf"))
            )
            sorted_ps = [(self.process_id, self.shard_id)] + [
                (p, self.peer_shards[p]) for p in by_rtt
            ]
        connected, _ = self.protocol.discover(sorted_ps)
        assert connected, "discovery failed: quorum unavailable"
        log.info(
            "process %s (shard %s) discovered %s",
            self.process_id, self.shard_id, sorted_ps,
        )

    def _start_tasks(self) -> None:
        t = self.tasks.append
        t(asyncio.create_task(self._worker_loop(), name="worker"))
        for i in range(len(self.pool)):
            t(
                asyncio.create_task(
                    self._executor_loop(i), name=f"executor-{i}"
                )
            )
        for event, interval in self.protocol.periodic_events():
            t(
                asyncio.create_task(
                    self._periodic_loop(event, interval),
                    name=f"periodic-{event}",
                )
            )
        t(
            asyncio.create_task(
                self._executed_notification_loop(),
                name="executed-notification",
            )
        )
        cleanup = self.config.executor_cleanup_interval_ms
        if cleanup:
            t(
                asyncio.create_task(
                    self._executor_cleanup_loop(cleanup), name="cleanup"
                )
            )
        if self.metrics_file:
            t(
                asyncio.create_task(
                    self._metrics_logger_loop(), name="metrics-logger"
                )
            )
        if self.config.shard_count > 1:
            t(
                asyncio.create_task(
                    self._partial_buf_sweeper(), name="partial-sweeper"
                )
            )

    # -- readers -------------------------------------------------------

    async def _peer_reader(self, peer, peer_shard, conn: Connection) -> None:
        while True:
            msg = await conn.recv()
            if msg is None:
                return
            tag = msg[0]
            if tag == "msg":
                _, from_id, from_shard, pmsg = msg
                await self.work.put(("msg", from_id, from_shard, pmsg))
            elif tag == "exec":
                _, from_shard, info = msg
                await self.exec_queues[
                    _route_info(info, len(self.pool))
                ].put(("info", info))
            elif tag == "ping":
                # a ping can arrive while our own connect_to_all is
                # still retrying; answer from a side task so the reader
                # never stalls protocol traffic behind the wait
                self.tasks.append(
                    asyncio.create_task(self._pong(peer, msg[1]))
                )
            elif tag == "pong":
                self._rtt[peer] = _time.monotonic() - msg[1]

    async def _pong(self, peer, nonce) -> None:
        for _ in range(200):
            out = self.out.get(peer)
            if out is not None:
                await out.send(("pong", nonce))
                return
            await asyncio.sleep(0.01)

    async def _accept_client(self, reader, writer) -> None:
        conn = Connection(
            reader, writer, delay_ms=self.delay_ms, compress=self.compress
        )
        hi = await conn.recv()
        if not isinstance(hi, ClientHi):
            await conn.close()
            return
        self._conn_seq += 1
        conn_id = self._conn_seq
        self.client_conns[conn_id] = conn
        self.client_pending[conn_id] = AggregatePending(
            self.process_id, self.shard_id
        )
        self.tasks.append(
            asyncio.create_task(
                self._client_reader(conn_id, conn),
                name=f"client-conn-{conn_id}",
            )
        )

    async def _client_reader(self, conn_id: int, conn: Connection) -> None:
        """task/server/client.rs:80-244: Register wires the rifl to this
        connection; Submit hands the command to the worker."""
        while True:
            msg = await conn.recv()
            if msg is None:
                self.client_conns.pop(conn_id, None)
                return
            tag = msg[0]
            if tag == "register":
                cmd: Command = msg[1]
                if self.config.shard_count == 1:
                    self.rifl_conn[cmd.rifl] = conn_id
                    self.client_pending[conn_id].wait_for(cmd)
                else:
                    # multi-shard: every shard's connected process sends
                    # partials; this side only tracks which connection
                    # wants them (client aggregates). Commands that do
                    # not touch this shard produce no partials here.
                    expected = cmd.key_count(self.shard_id)
                    if expected:
                        self.rifl_shard_conn[cmd.rifl] = [conn_id, expected]
                        for _, er in self.partial_buf.pop(cmd.rifl, []):
                            await self._to_client(er)
            elif tag == "submit":
                await self.work.put(("submit", msg[1]))

    # -- the protocol worker -------------------------------------------

    async def _worker_loop(self) -> None:
        while True:
            item = await self.work.get()
            tag = item[0]
            if tag == "msg":
                _, from_id, from_shard, pmsg = item
                trace(
                    log, "p%s <- p%s: %s", self.process_id, from_id, pmsg
                )
                self.protocol.handle(from_id, from_shard, pmsg, self.time)
            elif tag == "submit":
                self.protocol.submit(None, item[1], self.time)
            elif tag == "periodic":
                self.protocol.handle_event(item[1], self.time)
            elif tag == "executed":
                self.protocol.handle_executed(item[1], self.time)
            await self._send_to_processes_and_executors()

    async def _send_to_processes_and_executors(self) -> None:
        """task/server/process.rs:209-285: ToSend fans out over writer
        connections with one serialization, ToForward re-enters the work
        queue, execution info routes to the executor pool by key."""
        actions = self.protocol.to_processes()
        for info in self.protocol.to_executors():
            await self.exec_queues[_route_info(info, len(self.pool))].put(
                ("info", info)
            )
        for action in actions:
            if isinstance(action, ToForward):
                await self.work.put(
                    ("msg", self.process_id, self.shard_id, action.msg)
                )
                continue
            assert isinstance(action, ToSend)
            targets = sorted(action.target)
            wire = None
            for to in targets:
                if to == self.process_id:
                    msg = (
                        copy.deepcopy(action.msg)
                        if len(targets) > 1
                        else action.msg
                    )
                    await self.work.put(
                        ("msg", self.process_id, self.shard_id, msg)
                    )
                else:
                    conn = self.out[to]
                    if wire is None:
                        wire = conn.serialize(
                            (
                                "msg",
                                self.process_id,
                                self.shard_id,
                                action.msg,
                            )
                        )
                    conn.send_bytes_nowait(wire)
        for to in {t for a in actions if isinstance(a, ToSend)
                   for t in a.target if t != self.process_id}:
            await self.out[to].writer.drain()

    # -- executors -----------------------------------------------------

    async def _executor_loop(self, idx: int) -> None:
        """task/server/executor.rs:52-150."""
        executor = self.pool[idx]
        queue = self.exec_queues[idx]
        while True:
            item = await queue.get()
            tag = item[0]
            if tag == "info":
                if self.exec_log_fh is not None:
                    pickle.dump(item[1], self.exec_log_fh)
                executor.handle(item[1], self.time)
            elif tag == "cleanup":
                executor.cleanup(self.time)
            await self._drain_executor(executor)

    async def _drain_executor(self, executor: Executor) -> None:
        while True:
            infos = executor.to_executors()
            results = executor.to_clients()
            if not infos and not results:
                return
            for to_shard, info in infos:
                if to_shard == self.shard_id:
                    await self.exec_queues[
                        _route_info(info, len(self.pool))
                    ].put(("info", info))
                else:
                    target = self.protocol.bp.closest_process(to_shard)
                    await self.out[target].send(
                        ("exec", self.shard_id, info)
                    )
            for er in results:
                await self._to_client(er)

    async def _to_client(self, executor_result) -> None:
        rifl = executor_result.rifl
        if self.config.shard_count == 1:
            conn_id = self.rifl_conn.get(rifl)
            if conn_id is None:
                return  # registered at another process of this shard
            pending = self.client_pending[conn_id]
            cmd_result = pending.add_executor_result(executor_result)
            if cmd_result is not None:
                self.rifl_conn.pop(rifl, None)
                conn = self.client_conns.get(conn_id)
                if conn is not None:
                    await conn.send(("result", cmd_result))
        else:
            entry = self.rifl_shard_conn.get(rifl)
            if entry is None:
                self.partial_buf.setdefault(rifl, []).append(
                    (_time.monotonic(), executor_result)
                )
                return
            conn_id, remaining = entry
            entry[1] = remaining - 1
            if entry[1] <= 0:
                del self.rifl_shard_conn[rifl]
            conn = self.client_conns.get(conn_id)
            if conn is not None:
                await conn.send(("partial", executor_result))

    # -- periodic tasks ------------------------------------------------

    async def _periodic_loop(self, event, interval_ms: int) -> None:
        while True:
            await asyncio.sleep(interval_ms / 1000)
            await self.work.put(("periodic", event))

    async def _executed_notification_loop(self) -> None:
        interval = self.config.executor_executed_notification_interval_ms
        while True:
            await asyncio.sleep(interval / 1000)
            for executor in self.pool:
                executed = executor.executed(self.time)
                if executed is not None:
                    await self.work.put(("executed", executed))

    async def _executor_cleanup_loop(self, interval_ms: int) -> None:
        while True:
            await asyncio.sleep(interval_ms / 1000)
            for q in self.exec_queues:
                await q.put(("cleanup",))

    async def _partial_buf_sweeper(self) -> None:
        while True:
            await asyncio.sleep(self.partial_buf_ttl_s / 2)
            cutoff = _time.monotonic() - self.partial_buf_ttl_s
            stale = [
                rifl
                for rifl, entries in self.partial_buf.items()
                if entries and entries[0][0] < cutoff
            ]
            for rifl in stale:
                del self.partial_buf[rifl]

    async def _metrics_logger_loop(self) -> None:
        """metrics_logger.rs: periodic (worker, metrics) snapshots."""
        while True:
            await asyncio.sleep(self.metrics_interval_ms / 1000)
            self._dump_metrics()

    def _dump_metrics(self) -> None:
        with open(self.metrics_file, "wb") as fh:
            pickle.dump(
                {
                    "process_id": self.process_id,
                    "shard_id": self.shard_id,
                    "protocol": self.protocol.metrics(),
                    "executors": [e.metrics() for e in self.pool],
                },
                fh,
            )

    # -- shutdown ------------------------------------------------------

    async def shutdown(self) -> None:
        if self.metrics_file:
            self._dump_metrics()
        for task in self.tasks:
            task.cancel()
        for task in self.tasks:
            try:
                await task
            except (asyncio.CancelledError, Exception):
                pass
        for conn in list(self.out.values()) + list(
            self.client_conns.values()
        ):
            try:
                await asyncio.wait_for(conn.close(), timeout=1)
            except (asyncio.TimeoutError, Exception):
                pass
        for server in (self.peer_server, self.client_server):
            if server is not None:
                # not wait_closed(): in 3.12 it blocks until every
                # handler connection closes, which deadlocks a cluster
                # stopping replica by replica
                server.close()
        if self.exec_log_fh is not None:
            self.exec_log_fh.close()

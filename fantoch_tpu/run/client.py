"""Client-side runtime (fantoch/src/run/task/client/).

``client()`` connects each client to the closest process of every shard
(client_setup, task/client/mod.rs:35-120), then drives closed-loop
(next command on completion) or open-loop (fixed submit interval)
workloads (mod.rs:122-260). Multi-shard commands register with every
shard's connection and aggregate per-key partials client-side
(task/client/pending.rs); single-shard results arrive whole.

Batching: commands from clients sharing a connection can merge up to
``batch_max_size`` with ``batch_max_delay_ms`` slack (batcher.rs:15-100,
unbatcher.rs:11-106). Merged commands keep their own rifls; the server
executes them as independent submissions, so unbatching is just
result routing — the semantic the reference's unbatcher implements.
"""

from __future__ import annotations

import asyncio
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from ..client.client import Client, ClientData
from ..client.workload import Workload
from ..core.command import CommandResultBuilder
from ..core.ids import ClientId, ProcessId, ShardId
from ..core.timing import RunTime
from .prelude import ClientHi
from .rw import Connection


@dataclass
class ClientHandle:
    """Results of a finished client group."""

    data: Dict[ClientId, ClientData]

    def latencies_us(self) -> List[int]:
        out: List[int] = []
        for d in self.data.values():
            out.extend(d.latency_data())
        return out


async def client(
    client_ids: List[ClientId],
    shard_addresses: Dict[ShardId, Tuple[str, int]],
    shard_processes: Dict[ShardId, ProcessId],
    workload: Workload,
    *,
    open_loop_interval_ms: Optional[int] = None,
    compress: bool = False,
    connect_retries: int = 100,
) -> ClientHandle:
    """Run ``len(client_ids)`` closed-loop clients (or open-loop with
    ``open_loop_interval_ms``) against an already-running cluster;
    returns when every client finished its workload."""
    time = RunTime()
    conns: Dict[ShardId, Connection] = {}
    for shard, (host, port) in shard_addresses.items():
        for _ in range(connect_retries):
            try:
                reader, writer = await asyncio.open_connection(host, port)
                break
            except ConnectionError:
                await asyncio.sleep(0.05)
        else:
            raise ConnectionError(f"cannot reach shard {shard}")
        conn = Connection(reader, writer, compress=compress)
        await conn.send(ClientHi(list(client_ids)))
        conns[shard] = conn

    clients: Dict[ClientId, Client] = {}
    for cid in client_ids:
        c = Client(cid, workload)
        c.connect(dict(shard_processes))
        clients[cid] = c

    # route results back to the issuing client
    waiters: Dict[object, asyncio.Future] = {}
    partials: Dict[object, CommandResultBuilder] = {}

    async def dispatcher(conn: Connection) -> None:
        while True:
            msg = await conn.recv()
            if msg is None:
                return
            tag = msg[0]
            if tag == "result":
                fut = waiters.pop(msg[1].rifl, None)
                if fut is not None and not fut.done():
                    fut.set_result(msg[1])
            elif tag == "partial":
                er = msg[1]
                builder = partials.get(er.rifl)
                if builder is None:
                    continue
                builder.add_partial(er.key, er.partial_results)
                if builder.ready():
                    del partials[er.rifl]
                    fut = waiters.pop(er.rifl, None)
                    if fut is not None and not fut.done():
                        fut.set_result(builder.build())

    dispatchers = [
        asyncio.create_task(dispatcher(conn)) for conn in conns.values()
    ]
    multi_shard = len(conns) > 1

    async def run_one(c: Client) -> None:
        loop = asyncio.get_running_loop()
        inflight: List[asyncio.Task] = []

        async def record(fut: asyncio.Future) -> None:
            # latency is measured at completion time, not at drain time
            result = await fut
            c.cmd_recv(result.rifl, time)

        while True:
            nxt = c.cmd_send(time)
            if nxt is None:
                break
            target_shard, cmd = nxt
            fut = loop.create_future()
            waiters[cmd.rifl] = fut
            if multi_shard:
                partials[cmd.rifl] = CommandResultBuilder(
                    cmd.rifl, cmd.total_key_count()
                )
                for shard, conn in conns.items():
                    await conn.send(("register", cmd))
            else:
                await conns[target_shard].send(("register", cmd))
            await conns[target_shard].send(("submit", cmd))
            if open_loop_interval_ms is None:
                await record(fut)
            else:
                inflight.append(asyncio.create_task(record(fut)))
                await asyncio.sleep(open_loop_interval_ms / 1000)
        for task in inflight:
            await task

    await asyncio.gather(*(run_one(c) for c in clients.values()))
    for task in dispatchers:
        task.cancel()
    for conn in conns.values():
        await conn.close()
    return ClientHandle({cid: c.data for cid, c in clients.items()})

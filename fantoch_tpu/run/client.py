"""Client-side runtime (fantoch/src/run/task/client/).

``client()`` connects each client to the closest process of every shard
(client_setup, task/client/mod.rs:35-120), then drives closed-loop
(next command on completion) or open-loop (fixed submit interval)
workloads (mod.rs:122-260). Multi-shard commands register with every
shard's connection and aggregate per-key partials client-side
(task/client/pending.rs); single-shard results arrive whole.

Batching (``batch_max_size`` > 1): commands from clients sharing this
client group merge into one submission, up to ``batch_max_size``
commands or ``batch_max_delay_ms`` of slack, whichever first
(batcher.rs:15-100, batch.rs:17-74). The merged command keeps the
first member's rifl; the batcher remembers every member rifl and fans
the single result back out on completion (unbatcher.rs:11-106).
"""

from __future__ import annotations

import asyncio
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..client.client import Client, ClientData
from ..client.workload import Workload
from ..core.command import Command, CommandResult, CommandResultBuilder
from ..core.ids import ClientId, ProcessId, ShardId
from ..core.timing import RunTime
from .prelude import ClientHi
from .rw import Connection


@dataclass
class ClientHandle:
    """Results of a finished client group."""

    data: Dict[ClientId, ClientData]
    # wire submissions actually sent; < total commands when batching
    # merged some (the batching test's observable)
    submits: int = 0

    def latencies_us(self) -> List[int]:
        out: List[int] = []
        for d in self.data.values():
            out.extend(d.latency_data())
        return out


async def client(
    client_ids: List[ClientId],
    shard_addresses: Dict[ShardId, Tuple[str, int]],
    shard_processes: Dict[ShardId, ProcessId],
    workload: Workload,
    *,
    open_loop_interval_ms: Optional[int] = None,
    compress: bool = False,
    connect_retries: int = 100,
    batch_max_size: int = 1,
    batch_max_delay_ms: float = 5.0,
    command_timeout_s: Optional[float] = None,
) -> ClientHandle:
    """Run ``len(client_ids)`` closed-loop clients (or open-loop with
    ``open_loop_interval_ms``) against an already-running cluster;
    returns when every client finished its workload.

    ``command_timeout_s`` bounds the wait for any single command's
    result; on expiry the run fails loudly (TimeoutError) instead of
    hanging forever on a lost result."""
    time = RunTime()
    conns: Dict[ShardId, Connection] = {}
    for shard, (host, port) in shard_addresses.items():
        for _ in range(connect_retries):
            try:
                reader, writer = await asyncio.open_connection(host, port)
                break
            except ConnectionError:
                await asyncio.sleep(0.05)
        else:
            raise ConnectionError(f"cannot reach shard {shard}")
        conn = Connection(reader, writer, compress=compress)
        await conn.send(ClientHi(list(client_ids)))
        conns[shard] = conn

    clients: Dict[ClientId, Client] = {}
    for cid in client_ids:
        c = Client(cid, workload)
        c.connect(dict(shard_processes))
        clients[cid] = c

    # route results back to the issuing client. ``waiters`` is keyed by
    # member rifl; ``batch_members`` maps a submitted (possibly merged)
    # command's rifl to every member rifl it carries.
    waiters: Dict[object, asyncio.Future] = {}
    partials: Dict[object, CommandResultBuilder] = {}
    batch_members: Dict[object, List[object]] = {}
    stats = {"submits": 0}
    multi_shard = len(conns) > 1

    def _resolve(batch_rifl, result: CommandResult) -> None:
        """Fan one wire result out to every member rifl's waiter
        (unbatcher.rs:96-106 semantics)."""
        for member in batch_members.pop(batch_rifl, [batch_rifl]):
            fut = waiters.pop(member, None)
            if fut is not None and not fut.done():
                fut.set_result(CommandResult(member, result.results))

    def _fail(batch_rifl, reason: str) -> None:
        for member in batch_members.pop(batch_rifl, [batch_rifl]):
            fut = waiters.pop(member, None)
            if fut is not None and not fut.done():
                fut.set_exception(
                    RuntimeError(f"command {member} failed: {reason}")
                )

    async def dispatcher(conn: Connection) -> None:
        while True:
            msg = await conn.recv()
            if msg is None:
                # server side closed mid-run: results in flight are
                # lost for good, so fail every pending waiter loudly
                # rather than letting clients wait forever
                for fut in list(waiters.values()):
                    if not fut.done():
                        fut.set_exception(
                            ConnectionError("server connection closed")
                        )
                waiters.clear()
                return
            tag = msg[0]
            if tag == "result":
                _resolve(msg[1].rifl, msg[1])
            elif tag == "partial":
                er = msg[1]
                builder = partials.get(er.rifl)
                if builder is None:
                    continue
                builder.add_partial(er.key, er.partial_results)
                if builder.ready():
                    del partials[er.rifl]
                    _resolve(er.rifl, builder.build())
            elif tag == "error":
                _fail(msg[1], msg[2])

    dispatchers = [
        asyncio.create_task(dispatcher(conn)) for conn in conns.values()
    ]

    async def _submit(target_shard, cmd: Command, members) -> None:
        stats["submits"] += 1
        if members != [cmd.rifl]:
            batch_members[cmd.rifl] = members
        if multi_shard:
            partials[cmd.rifl] = CommandResultBuilder(
                cmd.rifl, cmd.total_key_count()
            )
            for shard, conn in conns.items():
                await conn.send(("register", cmd))
        else:
            await conns[target_shard].send(("register", cmd))
        await conns[target_shard].send(("submit", cmd))

    batch_q: asyncio.Queue = asyncio.Queue()

    async def batcher_loop() -> None:
        """Hold the open batch until it reaches ``batch_max_size`` or
        its deadline expires, whichever first (batcher.rs:29-91)."""
        loop = asyncio.get_running_loop()
        while True:
            target_shard, cmd = await batch_q.get()
            # the merged command must not alias the member's op maps —
            # the member Command lives on in the client's pending set
            merged = Command(
                cmd.rifl,
                {
                    s: {k: list(v) for k, v in ops.items()}
                    for s, ops in cmd.shard_to_ops.items()
                },
            )
            members = [cmd.rifl]
            # per-shard target votes; the batch targets the most-voted
            # shard (batch.rs:62-74)
            targets = {target_shard: 1}
            deadline = loop.time() + batch_max_delay_ms / 1000
            while len(members) < batch_max_size:
                timeout = deadline - loop.time()
                if timeout <= 0:
                    break
                try:
                    nxt_shard, nxt_cmd = await asyncio.wait_for(
                        batch_q.get(), timeout
                    )
                except asyncio.TimeoutError:
                    break
                merged.merge(nxt_cmd)
                members.append(nxt_cmd.rifl)
                targets[nxt_shard] = targets.get(nxt_shard, 0) + 1
            target = max(targets.items(), key=lambda kv: (kv[1], kv[0]))[0]
            await _submit(target, merged, members)

    batching = batch_max_size > 1
    batcher = asyncio.create_task(batcher_loop()) if batching else None
    batcher_exc: List[BaseException] = []
    if batcher is not None:
        # a dead batcher would strand every future command unsubmitted
        # with its waiter unresolved; fail all pending waiters loudly
        # (and every later submission, via batcher_exc) instead of
        # hanging the run
        def _batcher_died(t: asyncio.Task) -> None:
            if t.cancelled() or t.exception() is None:
                return
            exc = t.exception()
            batcher_exc.append(exc)
            for fut in list(waiters.values()):
                if not fut.done():
                    fut.set_exception(
                        RuntimeError(f"batcher died: {exc!r}")
                    )
            waiters.clear()

        batcher.add_done_callback(_batcher_died)

    async def run_one(c: Client) -> None:
        loop = asyncio.get_running_loop()
        inflight: List[asyncio.Task] = []

        async def record(fut: asyncio.Future) -> None:
            # latency is measured at completion time, not at drain time
            result = await asyncio.wait_for(fut, command_timeout_s)
            c.cmd_recv(result.rifl, time)

        while True:
            nxt = c.cmd_send(time)
            if nxt is None:
                break
            target_shard, cmd = nxt
            fut = loop.create_future()
            waiters[cmd.rifl] = fut
            if batching:
                if batcher_exc:
                    raise RuntimeError(
                        f"batcher died: {batcher_exc[0]!r}"
                    )
                await batch_q.put((target_shard, cmd))
            else:
                await _submit(target_shard, cmd, [cmd.rifl])
            if open_loop_interval_ms is None:
                await record(fut)
            else:
                inflight.append(asyncio.create_task(record(fut)))
                await asyncio.sleep(open_loop_interval_ms / 1000)
        for task in inflight:
            await task

    try:
        await asyncio.gather(*(run_one(c) for c in clients.values()))
    finally:
        # loud-failure paths (command timeout, batcher death, server
        # close) must not leak dispatcher/batcher tasks or sockets
        for task in dispatchers:
            task.cancel()
        if batcher is not None:
            batcher.cancel()
        for conn in conns.values():
            await conn.close()
    return ClientHandle(
        {cid: c.data for cid, c in clients.items()}, stats["submits"]
    )

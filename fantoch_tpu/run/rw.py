"""Framed connections (fantoch/src/run/rw/).

Length-delimited frames (4-byte big-endian length prefix) carrying
pickled payloads — the analog of the reference's tokio length-delimited
codec + bincode (rw/mod.rs:21-90), with the same optional gzip
compression and the same per-connection artificial-delay injection used
to emulate WAN latency on localhost (rw/connection.rs:8-41,
delay.rs:7-40).

Pickle stands in for bincode: like the reference's, this wire format is
for trusted cluster peers only.
"""

from __future__ import annotations

import asyncio
import gzip
import pickle
import struct
from typing import Any, Optional

_LEN = struct.Struct(">I")


class Connection:
    """One framed, optionally delayed, optionally compressed stream."""

    def __init__(
        self,
        reader: asyncio.StreamReader,
        writer: asyncio.StreamWriter,
        delay_ms: int = 0,
        compress: bool = False,
    ):
        self.reader = reader
        self.writer = writer
        self.delay_ms = delay_ms
        self.compress = compress
        self._wlock = asyncio.Lock()

    @property
    def peername(self):
        return self.writer.get_extra_info("peername")

    async def recv(self) -> Optional[Any]:
        """Read one frame; None on clean EOF."""
        try:
            head = await self.reader.readexactly(_LEN.size)
        except (asyncio.IncompleteReadError, ConnectionError):
            return None
        (length,) = _LEN.unpack(head)
        try:
            body = await self.reader.readexactly(length)
        except (asyncio.IncompleteReadError, ConnectionError):
            return None
        if self.compress:
            body = gzip.decompress(body)
        msg = pickle.loads(body)
        if self.delay_ms:
            # the reference's delay_task holds messages for `delay` ms
            # between the reader and the consumer (delay.rs:7-40)
            await asyncio.sleep(self.delay_ms / 1000)
        return msg

    def send_bytes_nowait(self, body: bytes) -> None:
        """Queue one pre-serialized frame (serialize-once fan-out, the
        reference wraps the serialized message in an Arc —
        task/server/process.rs:209-285)."""
        self.writer.write(_LEN.pack(len(body)) + body)

    async def send(self, msg: Any) -> None:
        async with self._wlock:
            self.send_bytes_nowait(self.serialize(msg))
            await self.writer.drain()

    def serialize(self, msg: Any) -> bytes:
        body = pickle.dumps(msg)
        if self.compress:
            body = gzip.compress(body, compresslevel=1)
        return body

    async def close(self) -> None:
        try:
            self.writer.close()
            await self.writer.wait_closed()
        except (ConnectionError, OSError):
            pass

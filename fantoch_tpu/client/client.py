"""Closed-loop synthetic client.

Capability parity with ``fantoch/src/client/``: a client generates the next
workload command when the previous one completes (client/mod.rs:91-137),
tracks pending request start times (``Pending``, client/pending.rs), and
records a latency/throughput series (``ClientData``, client/data.rs).
"""

from __future__ import annotations

import random
from typing import Dict, List, Optional, Tuple

from ..core.command import Command
from ..core.ids import ClientId, ProcessId, Rifl, RiflGen, ShardId
from ..core.timing import SysTime
from .workload import Workload


class Pending:
    """Pending rifl -> start time (micros) (client/pending.rs)."""

    def __init__(self) -> None:
        self._start: Dict[Rifl, int] = {}

    def start(self, rifl: Rifl, time: SysTime) -> None:
        assert rifl not in self._start
        self._start[rifl] = time.micros()

    def end(self, rifl: Rifl, time: SysTime) -> Tuple[int, int]:
        """Returns (latency_micros, end_time_micros)."""
        start = self._start.pop(rifl)
        end = time.micros()
        return end - start, end

    def is_empty(self) -> bool:
        return not self._start


class ClientData:
    """Latency (micros) and throughput series (client/data.rs)."""

    def __init__(self) -> None:
        self.latencies_us: List[int] = []
        self.end_times_ms: List[int] = []

    def record(self, latency_us: int, end_time_us: int) -> None:
        self.latencies_us.append(latency_us)
        self.end_times_ms.append(end_time_us // 1000)

    def latency_data(self) -> List[int]:
        return list(self.latencies_us)

    def throughput_data(self) -> List[Tuple[int, int]]:
        counts: Dict[int, int] = {}
        for ms in self.end_times_ms:
            counts[ms] = counts.get(ms, 0) + 1
        return sorted(counts.items())


class Client:
    """client/mod.rs:27-158."""

    def __init__(
        self,
        client_id: ClientId,
        workload: Workload,
        status_frequency: Optional[int] = None,
        rng: Optional[random.Random] = None,
    ):
        self.client_id = client_id
        self.processes: Dict[ShardId, ProcessId] = {}
        self.rifl_gen = RiflGen(client_id)
        # each client owns an independent workload instance (Copy in Rust)
        self.workload = Workload(**{**workload.__dict__, "command_count": 0})
        self.key_gen_state = workload.initial_state(client_id, rng)
        self.pending = Pending()
        self.data = ClientData()
        self.status_frequency = status_frequency

    def id(self) -> ClientId:
        return self.client_id

    def connect(self, processes: Dict[ShardId, ProcessId]) -> None:
        self.processes = processes

    def shard_process(self, shard_id: ShardId) -> ProcessId:
        return self.processes[shard_id]

    def cmd_send(self, time: SysTime) -> Optional[Tuple[ShardId, Command]]:
        nxt = self.workload.next_cmd(self.rifl_gen, self.key_gen_state)
        if nxt is None:
            return None
        target_shard, cmd = nxt
        self.pending.start(cmd.rifl, time)
        return target_shard, cmd

    def cmd_recv(self, rifl: Rifl, time: SysTime) -> None:
        latency, end_time = self.pending.end(rifl, time)
        self.data.record(latency, end_time)

    def workload_finished(self) -> bool:
        return self.workload.finished()

    def finished(self) -> bool:
        return self.workload.finished() and self.pending.is_empty()

    def issued_commands(self) -> int:
        return self.workload.issued_commands()

"""Workload key generators.

Capability parity with ``fantoch/src/client/key_gen.rs``: two generators —
``ConflictPool`` (with probability ``conflict_rate``% pick a random key from
a shared pool of ``CONFLICT<i>`` keys, otherwise use the client's private
key; key_gen.rs:96-110) and ``Zipf`` over a fixed key universe
(key_gen.rs:113-119).

Unlike the reference (which draws from a global ``thread_rng``), generators
here draw from an explicit ``random.Random`` so simulations are
reproducible; the device engine uses counter-based ``jax.random`` with the
same distributions.
"""

from __future__ import annotations

import random
from dataclasses import dataclass
from typing import Optional, Union

import numpy as np

from ..core.ids import ClientId
from ..core.kvs import Key

CONFLICT_COLOR = "CONFLICT"


@dataclass(frozen=True)
class ConflictPool:
    conflict_rate: int  # percentage 0..=100
    pool_size: int = 1

    def __str__(self) -> str:
        return f"conflict_{self.conflict_rate}_{self.pool_size}"


@dataclass(frozen=True)
class Zipf:
    coefficient: float
    total_keys_per_shard: int

    def __str__(self) -> str:
        return f"zipf_{self.coefficient:.2f}_{self.total_keys_per_shard}".replace(
            ".", "-"
        )


@dataclass(frozen=True)
class DeviceStream:
    """Replays the device engine's counter-based threefry key stream
    host-side (engine/core.py ``gen_key``), so the oracle DES and the
    device engine run the *same* workload at any conflict rate — the
    round-1 diff tests were pinned to conflict ∈ {0, 100} because the
    two sides drew from different PRNGs. Keys are the device's integer
    keys stringified: pool keys ``0..pool_size-1`` (or Zipf ranks),
    private key ``pool_size + client_index``.

    ``traffic`` attaches a time-varying schedule
    (:class:`fantoch_tpu.traffic.TrafficSchedule`): the stream ctx then
    carries the schedule's compiled epoch tables, so the host replays
    the *identical* epoch-indexed ConflictPool draws (conflict rate,
    pool size, hot-key pool rotation) the device lane makes — keys
    rotate on the exact command seq, private keys move up to
    ``pool_span + client``. The per-command read flag is also drawn
    counter-based (``fold_in(k, 3)``) from the epoch's ``read_pct``;
    the device engine carries no GET/PUT distinction, so the flag only
    shapes the mirrored workload's ops (docs/TRAFFIC.md)."""

    conflict_rate: int = 100
    pool_size: int = 1
    zipf: Optional[tuple] = None  # (coefficient, total_keys)
    seed: int = 0
    traffic: Optional[object] = None  # TrafficSchedule (hashable)

    def __str__(self) -> str:
        if self.zipf:
            return f"devstream_zipf_{self.zipf[0]:.2f}_{self.zipf[1]}"
        if self.traffic is not None:
            return (
                f"devstream_traffic_{self.traffic.name}_"
                f"{self.conflict_rate}_{self.pool_size}"
            )
        return f"devstream_{self.conflict_rate}_{self.pool_size}"


KeyGen = Union[ConflictPool, Zipf, DeviceStream]


def zipf_weights(key_count: int, coefficient: float) -> np.ndarray:
    """P(k) ∝ 1 / k^coefficient for k in 1..=key_count, matching the zipf
    crate used by the reference (client/key_gen.rs:62-77)."""
    ranks = np.arange(1, key_count + 1, dtype=np.float64)
    weights = 1.0 / np.power(ranks, coefficient)
    return weights / weights.sum()


class KeyGenState:
    """Per-client generator state (key_gen.rs:54-120)."""

    _BATCH = 512  # device-stream keys computed per jax call

    def __init__(self, key_gen: KeyGen, shard_count: int, client_id: ClientId,
                 rng: Optional[random.Random] = None):
        self.key_gen = key_gen
        self.client_id = client_id
        self.rng = rng if rng is not None else random.Random()
        if isinstance(key_gen, Zipf):
            key_count = key_gen.total_keys_per_shard * shard_count
            self._zipf_cum = np.cumsum(
                zipf_weights(key_count, key_gen.coefficient)
            )
        else:
            self._zipf_cum = None
        self._stream: list = []  # DeviceStream key cache
        self._reads: list = []   # per-seq read flags (traffic mirror)

    def gen_cmd_key(self) -> Key:
        kg = self.key_gen
        if isinstance(kg, DeviceStream):
            return self._device_stream_key(kg)
        if isinstance(kg, ConflictPool):
            if true_if_random_is_less_than(kg.conflict_rate, self.rng):
                return f"{CONFLICT_COLOR}{self.rng.randrange(kg.pool_size)}"
            return str(self.client_id)
        # zipf: sample rank in 1..=key_count
        u = self.rng.random()
        rank = int(np.searchsorted(self._zipf_cum, u, side="right")) + 1
        return str(rank)

    def _device_stream_key(self, kg: DeviceStream) -> Key:
        """Next key of the device's (client, seq)-counter stream; seqs
        are 1-based like the engine's SUBMIT payloads. Computed in
        batches (one vmapped call per _BATCH keys); the keygen ctx is a
        pure function of the frozen generator — with a traffic
        schedule, its epoch tables are (re)compiled to cover the
        batch's seq range (table entries equal the schedule's unbounded
        seq → epoch function, so every table length agrees with the
        device lane's on all seqs within the command budget)."""
        self._cmds_issued = getattr(self, "_cmds_issued", 0) + 1
        while len(self._stream) < self._cmds_issued:
            import jax
            import jax.numpy as jnp
            import jax.random as jr

            from ..engine.core import gen_key

            lo = len(self._stream) + 1
            need = lo + self._BATCH + 1
            ctx = getattr(self, "_stream_ctx", None)
            if ctx is None or (
                kg.traffic is not None
                and ctx["traffic_seq_epoch"].shape[0] < need
            ):
                if kg.zipf is None:
                    ctx = {
                        "key_gen_kind": jnp.int32(0),
                        "zipf_cum": jnp.ones((1,), jnp.float32),
                    }
                else:
                    coefficient, total_keys = kg.zipf
                    ctx = {
                        "key_gen_kind": jnp.int32(1),
                        "zipf_cum": jnp.asarray(
                            np.cumsum(
                                zipf_weights(total_keys, coefficient)
                            ),
                            jnp.float32,
                        ),
                    }
                ctx.update(
                    rng_key=jr.PRNGKey(kg.seed),
                    conflict_rate=jnp.int32(kg.conflict_rate),
                    pool_size=jnp.int32(kg.pool_size),
                )
                if kg.traffic is not None:
                    ctx.update(
                        {
                            k: jnp.asarray(v)
                            for k, v in kg.traffic.compile(need).items()
                        }
                    )
                    if kg.zipf is not None:
                        # epoch-varying zipf mirror: the identical
                        # [E, K] cumulative table make_lane ships as
                        # ctx["traffic_zipf_cum"] (engine/spec.py) —
                        # same builder, same float32 rows, same rule
                        # (traffic AND zipf => table present)
                        coefficient, total_keys = kg.zipf
                        ctx.update(
                            {
                                k: jnp.asarray(v)
                                for k, v in kg.traffic.zipf_tables(
                                    coefficient, int(total_keys)
                                ).items()
                            }
                        )
                self._stream_ctx = ctx
            seqs = jnp.arange(lo, lo + self._BATCH, dtype=jnp.int32)
            client_index = self.client_id - 1
            batch = np.asarray(
                jax.vmap(lambda s: gen_key(ctx, client_index, s))(seqs)
            )
            self._stream.extend(int(k) for k in batch)
            if kg.traffic is not None:
                # the schedule's read mix, drawn from the same counter
                # stream (fold 3; gen_key uses folds 0..2) so which
                # commands are reads is a pure function of
                # (seed, client, seq) on both sides
                def read_one(s):
                    k = jr.fold_in(
                        jr.fold_in(ctx["rng_key"], client_index), s
                    )
                    tbl = ctx["traffic_seq_epoch"]
                    e = tbl[jnp.minimum(s, tbl.shape[0] - 1)]
                    pct = ctx["traffic_read_pct"][e]
                    return jr.randint(jr.fold_in(k, 3), (), 0, 100) < pct

                reads = np.asarray(jax.vmap(read_one)(seqs))
                self._reads.extend(bool(x) for x in reads)
        return str(self._stream[self._cmds_issued - 1])

    def traffic_read_only(self) -> Optional[bool]:
        """The schedule-driven read flag of the most recently drawn
        key's command (None without a traffic DeviceStream — the
        workload then falls back to its own ``read_only_percentage``
        draw). Counter-based, so it never consumes host RNG state."""
        kg = self.key_gen
        if not (isinstance(kg, DeviceStream) and kg.traffic is not None):
            return None
        return bool(self._reads[self._cmds_issued - 1])


def true_if_random_is_less_than(
    percentage: int, rng: random.Random
) -> bool:
    """key_gen.rs:122-128."""
    if percentage == 0:
        return False
    if percentage == 100:
        return True
    return rng.randrange(100) < percentage

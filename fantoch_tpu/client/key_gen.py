"""Workload key generators.

Capability parity with ``fantoch/src/client/key_gen.rs``: two generators —
``ConflictPool`` (with probability ``conflict_rate``% pick a random key from
a shared pool of ``CONFLICT<i>`` keys, otherwise use the client's private
key; key_gen.rs:96-110) and ``Zipf`` over a fixed key universe
(key_gen.rs:113-119).

Unlike the reference (which draws from a global ``thread_rng``), generators
here draw from an explicit ``random.Random`` so simulations are
reproducible; the device engine uses counter-based ``jax.random`` with the
same distributions.
"""

from __future__ import annotations

import random
from dataclasses import dataclass
from typing import Optional, Union

import numpy as np

from ..core.ids import ClientId
from ..core.kvs import Key

CONFLICT_COLOR = "CONFLICT"


@dataclass(frozen=True)
class ConflictPool:
    conflict_rate: int  # percentage 0..=100
    pool_size: int = 1

    def __str__(self) -> str:
        return f"conflict_{self.conflict_rate}_{self.pool_size}"


@dataclass(frozen=True)
class Zipf:
    coefficient: float
    total_keys_per_shard: int

    def __str__(self) -> str:
        return f"zipf_{self.coefficient:.2f}_{self.total_keys_per_shard}".replace(
            ".", "-"
        )


KeyGen = Union[ConflictPool, Zipf]


def zipf_weights(key_count: int, coefficient: float) -> np.ndarray:
    """P(k) ∝ 1 / k^coefficient for k in 1..=key_count, matching the zipf
    crate used by the reference (client/key_gen.rs:62-77)."""
    ranks = np.arange(1, key_count + 1, dtype=np.float64)
    weights = 1.0 / np.power(ranks, coefficient)
    return weights / weights.sum()


class KeyGenState:
    """Per-client generator state (key_gen.rs:54-120)."""

    def __init__(self, key_gen: KeyGen, shard_count: int, client_id: ClientId,
                 rng: Optional[random.Random] = None):
        self.key_gen = key_gen
        self.client_id = client_id
        self.rng = rng if rng is not None else random.Random()
        if isinstance(key_gen, Zipf):
            key_count = key_gen.total_keys_per_shard * shard_count
            self._zipf_cum = np.cumsum(
                zipf_weights(key_count, key_gen.coefficient)
            )
        else:
            self._zipf_cum = None

    def gen_cmd_key(self) -> Key:
        kg = self.key_gen
        if isinstance(kg, ConflictPool):
            if true_if_random_is_less_than(kg.conflict_rate, self.rng):
                return f"{CONFLICT_COLOR}{self.rng.randrange(kg.pool_size)}"
            return str(self.client_id)
        # zipf: sample rank in 1..=key_count
        u = self.rng.random()
        rank = int(np.searchsorted(self._zipf_cum, u, side="right")) + 1
        return str(rank)


def true_if_random_is_less_than(
    percentage: int, rng: random.Random
) -> bool:
    """key_gen.rs:122-128."""
    if percentage == 0:
        return False
    if percentage == 100:
        return True
    return rng.randrange(100) < percentage

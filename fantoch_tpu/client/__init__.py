"""Client/workload layer (reference: ``fantoch/src/client/``)."""

from .client import Client, ClientData, Pending
from .key_gen import (
    CONFLICT_COLOR,
    ConflictPool,
    DeviceStream,
    KeyGen,
    KeyGenState,
    Zipf,
)
from .workload import Workload

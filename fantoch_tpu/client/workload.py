"""Synthetic workload generation.

Capability parity with ``fantoch/src/client/workload.rs``: commands with
``keys_per_command`` unique keys, a read-only percentage, a payload, and a
per-client command budget (workload.rs:12-212). The target shard is the
shard of the first generated key (workload.rs:156-186); key→shard mapping is
``key_hash % shard_count`` (workload.rs:209-211).
"""

from __future__ import annotations

import random
import string
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..core.command import Command
from ..core.ids import RiflGen, ShardId
from ..core.kvs import GET, PUT, Key
from ..core.util import key_hash
from .key_gen import ConflictPool, KeyGen, KeyGenState, true_if_random_is_less_than


@dataclass
class Workload:
    shard_count: int
    key_gen: KeyGen
    keys_per_command: int
    commands_per_client: int
    payload_size: int
    read_only_percentage: int = 0
    command_count: int = 0

    def __post_init__(self) -> None:
        # valid-workload checks (workload.rs:38-55)
        if isinstance(self.key_gen, ConflictPool):
            assert self.key_gen.conflict_rate <= 100
            assert self.key_gen.pool_size >= 1
            if self.key_gen.conflict_rate == 100 and self.keys_per_command > 1:
                raise ValueError(
                    "can't generate more than one key when conflict_rate is 100"
                )
            if self.keys_per_command > 2:
                raise ValueError(
                    "can't generate more than two keys with the conflict_rate"
                    " key generator"
                )

    def initial_state(
        self, client_id: int, rng: Optional[random.Random] = None
    ) -> KeyGenState:
        return KeyGenState(self.key_gen, self.shard_count, client_id, rng)

    def issued_commands(self) -> int:
        return self.command_count

    def finished(self) -> bool:
        return self.command_count == self.commands_per_client

    def next_cmd(
        self, rifl_gen: RiflGen, key_gen_state: KeyGenState
    ) -> Optional[Tuple[ShardId, Command]]:
        """workload.rs:113-128."""
        if self.command_count >= self.commands_per_client:
            return None
        self.command_count += 1
        return self.gen_cmd(rifl_gen, key_gen_state)

    def gen_cmd(
        self, rifl_gen: RiflGen, key_gen_state: KeyGenState
    ) -> Tuple[ShardId, Command]:
        """workload.rs:142-186."""
        rifl = rifl_gen.next_id()
        keys = self._gen_unique_keys(key_gen_state)
        # a traffic-scheduled DeviceStream drives the read mix from its
        # per-epoch read_pct via the counter-based stream (bit-exact
        # with the schedule spec); otherwise the workload's own
        # read_only_percentage draw applies (workload.rs:148-150)
        read_only = key_gen_state.traffic_read_only()
        if read_only is None:
            read_only = true_if_random_is_less_than(
                self.read_only_percentage, key_gen_state.rng
            )
        shard_to_ops: Dict[ShardId, Dict[Key, list]] = {}
        target_shard: Optional[ShardId] = None
        for key in keys:
            op = (GET,) if read_only else (PUT, self._gen_value(key_gen_state))
            shard_id = self.shard_id(key)
            shard_to_ops.setdefault(shard_id, {})[key] = [op]
            if target_shard is None:
                target_shard = shard_id
        assert target_shard is not None
        return target_shard, Command(rifl, shard_to_ops)

    def _gen_unique_keys(self, key_gen_state: KeyGenState) -> List[Key]:
        keys: List[Key] = []
        while len(keys) != self.keys_per_command:
            key = key_gen_state.gen_cmd_key()
            if key not in keys:
                keys.append(key)
        return keys

    def _gen_value(self, key_gen_state: KeyGenState) -> str:
        if self.payload_size == 0:
            return ""
        rng = key_gen_state.rng
        return "".join(
            rng.choices(string.ascii_letters + string.digits,
                        k=self.payload_size)
        )

    def shard_id(self, key: Key) -> ShardId:
        return key_hash(key) % self.shard_count

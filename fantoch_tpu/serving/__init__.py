"""Open-loop serving workloads: the throughput–latency knee.

Closed-loop clients (one command in flight, the next armed by the
previous completion) hide saturation behavior and suffer coordinated
omission ("Open Versus Closed: A Cautionary Tale", Schroeder et al.,
NSDI'06). The engine's open-loop client mode (engine/core.py,
docs/TRAFFIC.md "Open-loop arrivals") timestamps commands by seeded
arrival draws independent of completion, bounds the in-flight window,
and counts arrival-queue delay into latency — so sweeping an
offered-load axis exposes the *knee*: the first load where tail
latency leaves the unloaded baseline.

:mod:`fantoch_tpu.serving.knee` drives that sweep per (protocol,
planet, traffic) point through the campaign manager (journaled,
checkpointed, SIGKILL+resume byte-identical) and writes the measured
latency-vs-offered-load curves plus the located knee as a canonical
atomic artifact (docs/CAMPAIGN.md "Knee artifacts").
"""

from .knee import (
    KNEE_ARTIFACT,
    KNEE_KIND,
    KNEE_VERSION,
    build_knee_artifact,
    check_knee_artifact,
    collect_curves,
    knee_campaign,
    locate_knee,
    run_knee_sweep,
)

__all__ = [
    "KNEE_ARTIFACT",
    "KNEE_KIND",
    "KNEE_VERSION",
    "build_knee_artifact",
    "check_knee_artifact",
    "collect_curves",
    "knee_campaign",
    "locate_knee",
    "run_knee_sweep",
]

"""The measured throughput–latency knee (docs/CAMPAIGN.md).

A knee sweep runs one open-loop arrival preset at a ladder of offered
loads (percent of the preset's base rate) per (protocol, planet,
traffic) point, through the PR-5 campaign manager — every batch is
journaled, the in-flight batch checkpoints at segment boundaries, and
a SIGKILLed sweep resumes byte-identically. Once the grid completes,
the per-point latency-vs-offered-load curves (p50/p99/mean + goodput)
and the located knee — the first load whose p99 exceeds
``knee_mult`` × the lowest load's p99 — are written as one canonical
atomic ``knee.json`` artifact.

Latency here is the open loop's queue-delay-inclusive latency
(engine/core.py step 5): completion time minus *arrival* time, so a
saturated point's arrival-queue wait lands in the curve instead of
being coordinated-omission'd away. Goodput is completed commands per
second of offered window (the span of the lane's arrival table), a
host-side derivation from journaled lane results — no extra device
work.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence, Tuple

from ..core.metrics import Histogram

KNEE_ARTIFACT = "knee.json"
KNEE_KIND = "serving-knee"
KNEE_VERSION = 1

# offered-load ladder (percent of the arrival preset's base rate) and
# the knee multiplier: p99(load) > KNEE_MULT * p99(loads[0]) locates it
DEFAULT_LOADS = (50, 100, 200, 400)
DEFAULT_KNEE_MULT = 3.0


def knee_campaign(
    *,
    protocols: Sequence[str],
    ns: Sequence[int] = (3,),
    region_sets=None,
    arrival: str = "poisson",
    loads: Sequence[int] = DEFAULT_LOADS,
    traffic: Sequence[str] = ("flat",),
    fs: Sequence[int] = (1,),
    conflicts: Sequence[int] = (100,),
    commands_per_client: int = 20,
    clients_per_region: int = 1,
    open_window: int = 4,
    mean_gap_ms: int = 4,
    batch_lanes: int = 64,
    segment_steps: int = 2048,
    aws: bool = False,
):
    """The knee sweep's campaign spec: the arrival preset at every
    offered load, each (preset, load) point its own batch group
    (campaign/manager.py ``arrivals`` axis)."""
    from ..campaign.manager import SweepCampaign

    assert arrival != "closed", (
        "a knee sweep needs an open-loop arrival process; 'closed' "
        "has no offered-load axis"
    )
    kw = dict(
        protocols=tuple(protocols),
        ns=tuple(int(n) for n in ns),
        fs=tuple(int(f) for f in fs),
        conflicts=tuple(int(c) for c in conflicts),
        traffic=tuple(traffic),
        arrivals=(arrival,),
        offered_loads=tuple(int(l) for l in loads),
        open_window=int(open_window),
        mean_gap_ms=int(mean_gap_ms),
        commands_per_client=int(commands_per_client),
        clients_per_region=int(clients_per_region),
        batch_lanes=int(batch_lanes),
        segment_steps=int(segment_steps),
        aws=aws,
    )
    if region_sets is not None:
        kw["region_sets"] = tuple(tuple(r) for r in region_sets)
    return SweepCampaign(**kw)


def _arrival_load(meta: dict) -> int:
    """The offered load of a lane's arrival meta: ``scale`` renames a
    scaled schedule to ``<preset>@<load>`` (traffic/schedule.py);
    an unsuffixed name is the base 100% point."""
    name = meta["name"]
    return int(name.split("@", 1)[1]) if "@" in name else 100


def _offered_span_ms(lane) -> int:
    """The offered window of a lane: the latest per-client arrival of
    a budgeted command (columns 1..commands of the ctx table; the
    final column is the staging lookahead slot, never offered)."""
    table = lane.ctx["ol_arrival"]
    commands = table.shape[1] - 2
    return max(1, int(table[:, commands].max()))


def collect_curves(path: str, spec) -> Dict[Tuple[str, ...], dict]:
    """Aggregate a completed knee campaign's journal into measured
    curves: candidate regions → protocol → load → {mean, p50, p99,
    count, goodput_cps, lanes, errors}. Lane → (protocol, load)
    attribution re-enumerates the deterministic batch order (the same
    alignment ``_run_sweep_campaign`` journals by); each point's lanes
    (fault plans, conflicts, fs) merge into one histogram."""
    from ..campaign.manager import _read_journal, _sweep_batches
    from ..engine.results import LaneResults

    done: Dict[str, List[dict]] = {}
    for entry in _read_journal(path):
        if entry.get("kind") == "batch":
            done[entry["id"]] = entry["results"]

    hists: Dict[tuple, Histogram] = {}
    acc: Dict[tuple, dict] = {}
    for key, _dev, _dims, lanes in _sweep_batches(spec):
        rows = done.get(key)
        assert rows is not None and len(rows) == len(lanes), (
            f"campaign journal incomplete at batch {key!r}; collect "
            "knee curves only from a completed campaign"
        )
        proto = key.split("/", 1)[0]
        for lane, row in zip(lanes, rows):
            assert lane.arrival_meta is not None, (
                f"closed-loop lane in knee batch {key!r}"
            )
            res = LaneResults.from_json(row)
            point = (
                tuple(lane.process_regions),
                proto,
                _arrival_load(lane.arrival_meta),
            )
            hist = hists.setdefault(point, Histogram())
            slot = acc.setdefault(
                point,
                {
                    "lanes": 0,
                    "errors": 0,
                    "completed": 0,
                    "span_ms": 0,
                    "error_cause": None,
                },
            )
            slot["lanes"] += 1
            if res.err:
                # an errored lane's partial histogram must never shape
                # a curve point — carry the cause, null the stats below
                slot["errors"] += 1
                slot["error_cause"] = res.err_cause
                continue
            for region in lane.region_rows:
                hist.merge(res.histogram(region))
            slot["completed"] += int(res.completed)
            slot["span_ms"] = max(slot["span_ms"], _offered_span_ms(lane))

    out: Dict[Tuple[str, ...], dict] = {}
    for (regions, proto, load), slot in acc.items():
        hist = hists[(regions, proto, load)]
        if slot["errors"]:
            stats = {
                "mean": None,
                "p50": None,
                "p99": None,
                "count": hist.count(),
                "goodput_cps": None,
                "error_cause": slot["error_cause"],
            }
        else:
            stats = {
                "mean": round(hist.mean(), 3),
                "p50": round(hist.percentile(0.5), 3),
                "p99": round(hist.percentile(0.99), 3),
                "count": hist.count(),
                "goodput_cps": round(
                    slot["completed"] * 1000.0 / slot["span_ms"], 3
                ),
            }
        stats["lanes"] = slot["lanes"]
        stats["errors"] = slot["errors"]
        out.setdefault(regions, {}).setdefault(proto, {})[
            str(load)
        ] = stats
    return out


def locate_knee(
    curve: Dict[str, dict], knee_mult: float = DEFAULT_KNEE_MULT
) -> Optional[int]:
    """The knee of one measured curve (load → stats): the first load,
    ascending, whose p99 exceeds ``knee_mult`` × the lowest load's
    p99. None when the curve never leaves the baseline envelope (not
    saturated within the swept ladder) or the baseline itself errored."""
    loads = sorted(int(l) for l in curve)
    base = curve[str(loads[0])].get("p99")
    if base is None:
        return None
    for load in loads[1:]:
        p99 = curve[str(load)].get("p99")
        if p99 is not None and p99 > knee_mult * max(base, 1e-9):
            return load
    return None


def build_knee_artifact(
    spec,
    *,
    measured: "Dict[Tuple[str, ...], dict] | None",
    knee_mult: float = DEFAULT_KNEE_MULT,
    dryrun: bool = False,
) -> dict:
    """The canonical knee artifact (docs/CAMPAIGN.md "Knee
    artifacts"): sweep parameters, per-(regions, protocol) curves, and
    each curve's located knee. ``dryrun`` emits the parameter shell
    with ``points: null`` — the CI schema check's fast path."""
    points = None
    if measured is not None:
        points = [
            {
                "regions": list(regions),
                "protocol": proto,
                "curve": {
                    str(load): curve[str(load)]
                    for load in sorted(int(l) for l in curve)
                },
                "knee": locate_knee(curve, knee_mult),
            }
            for regions, protos in sorted(measured.items())
            for proto, curve in sorted(protos.items())
        ]
    return {
        "kind": KNEE_KIND,
        "version": KNEE_VERSION,
        "planet": "aws" if spec.aws else "gcp",
        "protocols": list(spec.protocols),
        "arrival": spec.arrivals[0],
        "loads": [int(l) for l in spec.offered_loads],
        "knee_mult": float(knee_mult),
        "open_window": int(spec.open_window),
        "mean_gap_ms": int(spec.mean_gap_ms),
        "traffic": list(spec.traffic),
        "fs": [int(f) for f in spec.fs],
        "conflicts": [int(c) for c in spec.conflicts],
        "commands_per_client": int(spec.commands_per_client),
        "clients_per_region": int(spec.clients_per_region),
        "dryrun": bool(dryrun),
        "points": points,
    }


def check_knee_artifact(obj: dict) -> None:
    """Schema gate for the knee artifact (the CI openloop-smoke job
    pins this): required keys, per-point curves covering every swept
    load with numeric p50/p99/goodput (or nulls + a cause on errored
    points), and a knee that is either null or one of the swept
    loads."""
    for k in (
        "kind", "version", "planet", "protocols", "arrival", "loads",
        "knee_mult", "open_window", "mean_gap_ms", "traffic", "fs",
        "conflicts", "commands_per_client", "clients_per_region",
        "dryrun", "points",
    ):
        assert k in obj, f"knee artifact missing {k!r}"
    assert obj["kind"] == KNEE_KIND, obj["kind"]
    assert obj["arrival"] != "closed", "knee artifacts are open-loop"
    assert obj["loads"], "knee artifact has no offered-load ladder"
    if obj["dryrun"]:
        assert obj["points"] is None, (
            "dryrun artifacts must not claim measured curves"
        )
        return
    points = obj["points"]
    assert points, "knee artifact has no measured points"
    seen = set()
    for point in points:
        for k in ("regions", "protocol", "curve", "knee"):
            assert k in point, f"knee point missing {k!r}"
        seen.add(point["protocol"])
        curve = point["curve"]
        for load in obj["loads"]:
            stats = curve.get(str(load))
            assert stats is not None, (
                f"curve missing load {load} for {point['protocol']} "
                f"{point['regions']}"
            )
            if stats.get("errors"):
                assert stats.get("error_cause"), stats
                for field in ("mean", "p50", "p99", "goodput_cps"):
                    assert stats.get(field) is None, (field, stats)
                continue
            for field in ("mean", "p50", "p99", "goodput_cps"):
                assert isinstance(stats.get(field), (int, float)), (
                    point["protocol"], load, field,
                )
        assert point["knee"] is None or point["knee"] in obj["loads"], (
            point["knee"]
        )
    missing = set(obj["protocols"]) - seen
    assert not missing, f"no measured points for protocol(s) {missing}"


def run_knee_sweep(
    path: str,
    *,
    protocols: Sequence[str],
    ns: Sequence[int] = (3,),
    region_sets=None,
    arrival: str = "poisson",
    loads: Sequence[int] = DEFAULT_LOADS,
    traffic: Sequence[str] = ("flat",),
    fs: Sequence[int] = (1,),
    conflicts: Sequence[int] = (100,),
    commands_per_client: int = 20,
    clients_per_region: int = 1,
    open_window: int = 4,
    mean_gap_ms: int = 4,
    batch_lanes: int = 64,
    segment_steps: int = 2048,
    knee_mult: float = DEFAULT_KNEE_MULT,
    aws: bool = False,
    resume: bool = False,
    budget_s: Optional[float] = None,
    dryrun: bool = False,
    out: Optional[str] = None,
) -> Tuple[Optional[dict], dict]:
    """Run (or resume) a knee sweep and, once the campaign grid
    completes, write the knee artifact.

    Returns ``(artifact, campaign_summary)``; ``artifact`` is None
    when the campaign was interrupted (budget/signal) — re-invoke with
    ``resume=True`` to continue exactly where it stopped. ``dryrun``
    skips the device sweeps and emits the parameter shell with
    ``points: null``."""
    spec = knee_campaign(
        protocols=protocols, ns=ns, region_sets=region_sets,
        arrival=arrival, loads=loads, traffic=traffic, fs=fs,
        conflicts=conflicts, commands_per_client=commands_per_client,
        clients_per_region=clients_per_region, open_window=open_window,
        mean_gap_ms=mean_gap_ms, batch_lanes=batch_lanes,
        segment_steps=segment_steps, aws=aws,
    )
    out = out or os.path.join(path, KNEE_ARTIFACT)
    if dryrun:
        artifact = build_knee_artifact(
            spec, measured=None, knee_mult=knee_mult, dryrun=True
        )
        check_knee_artifact(artifact)
        _write_artifact(out, artifact)
        return artifact, {"done": True, "dryrun": True, "artifact": out}

    from ..campaign.manager import run_campaign

    summary = run_campaign(path, spec, resume=resume, budget_s=budget_s)
    if not summary["done"]:
        return None, summary

    measured = collect_curves(path, spec)
    artifact = build_knee_artifact(
        spec, measured=measured, knee_mult=knee_mult, dryrun=False
    )
    check_knee_artifact(artifact)
    _write_artifact(out, artifact)
    return artifact, dict(summary, artifact=out)


def _write_artifact(path: str, artifact: dict) -> None:
    from ..engine.checkpoint import atomic_write, canonical_json

    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    atomic_write(path, canonical_json(artifact, indent=2))

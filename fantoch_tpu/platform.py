"""JAX platform selection under the axon site hook.

The deployment environment pre-imports jax and presets JAX_PLATFORMS to
the tunneled device backend, so a plain env-var override is too late —
but XLA backends initialize lazily, so flipping the jax config before
the first computation still wins (the same trick as tests/conftest.py).
Every entry point that needs to force the CPU backend (CLI, bench
smoke runs, the standalone graft check) goes through here so the
recipe lives in one place.
"""

from __future__ import annotations

import os
import sys


def force_cpu(devices: int = 8) -> None:
    """Force the CPU backend at jax-config level (and export the env
    var for subprocesses). Cheap when jax is not yet imported."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    if "jax" not in sys.modules:
        # env var wins for everything imported from here on; skipping
        # the import keeps host-only paths free of jax startup cost
        return
    import jax

    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", devices)
    except Exception:
        pass  # backend already initialized; keep its device count


def force_cpu_from_env(devices: int = 8) -> bool:
    """Apply :func:`force_cpu` when the caller's environment asks for
    the CPU backend (JAX_PLATFORMS=cpu); returns whether it did."""
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        force_cpu(devices)
        return True
    return False

"""JAX platform selection under the axon site hook.

The deployment environment pre-imports jax and presets JAX_PLATFORMS to
the tunneled device backend, so a plain env-var override is too late —
but XLA backends initialize lazily, so flipping the jax config before
the first computation still wins (the same trick as tests/conftest.py).
Every entry point that needs to force the CPU backend (CLI, bench
smoke runs, the standalone graft check) goes through here so the
recipe lives in one place.
"""

from __future__ import annotations

import os
import sys


def force_cpu(devices: int = 8) -> None:
    """Force the CPU backend at jax-config level (and export the env
    vars for this process and subprocesses). Cheap when jax is not yet
    imported."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    # the XLA_FLAGS fallback carries the virtual device mesh on jax
    # versions without the jax_num_cpu_devices option — and it is the
    # only mechanism that works for a fresh (not-yet-imported) jax
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={devices}"
        ).strip()
    if "jax" not in sys.modules:
        # env vars win for everything imported from here on; skipping
        # the import keeps host-only paths free of jax startup cost
        return
    import jax

    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", devices)
    except Exception:
        pass  # option absent or backend already initialized


def enable_compile_cache(cache_dir: str | None = None) -> str:
    """Point XLA's persistent compilation cache at a stable on-disk
    directory so every entry point (bench, accuracy, stress, CLI) pays
    each trace's compile cost once *ever*, not once per process.

    This matters most for the all-protocol bench: CaesarDev alone
    compiles for minutes, and the driver's bench budget is 600 s — a
    cold warmup can eat the entire budget, while a cached one replays
    in seconds.  The threshold knobs are dropped to "cache everything"
    because even sub-second entries add up across five protocols ×
    chunk shapes.  Safe to call before or after backend init; must run
    before the first jit execution to help that execution.
    """
    if cache_dir is None:
        cache_dir = os.environ.get(
            "FANTOCH_COMPILE_CACHE",
            os.path.join(
                os.path.expanduser("~"), ".cache", "fantoch_tpu", "xla"
            ),
        )
    os.makedirs(cache_dir, exist_ok=True)
    import jax

    jax.config.update("jax_compilation_cache_dir", cache_dir)
    for knob, val in (
        ("jax_persistent_cache_min_compile_time_secs", 0.0),
        ("jax_persistent_cache_min_entry_size_bytes", -1),
        ("jax_persistent_cache_enable_xla_caches", "all"),
    ):
        try:
            jax.config.update(knob, val)
        except Exception:
            pass  # knob not present in this jax version
    return cache_dir


def probe_device_backend(timeout_s: float):
    """Initialize the JAX backend in a THROWAWAY subprocess.

    Returns ``(status, platform)`` where status is:

    * ``"up"`` — a non-cpu backend initialized; platform is its name;
    * ``"cpu-only"`` — init succeeded but only the CPU fallback
      answered (no device plugin at all): deterministic, retrying
      cannot fix it;
    * ``"down"`` — init failed or timed out (dead tunnel): transient,
      worth retrying.

    Backend init happens inside a C extension and can block for many
    minutes when the device tunnel is down, so an in-process attempt
    cannot be cancelled — a subprocess with a hard timeout can.  The
    non-cpu assertion matters: with JAX_PLATFORMS unset, a dead tunnel
    makes ``jax.devices()`` fall back to the CPU backend, which must
    not be mistaken for a live device.
    """
    import subprocess

    check = (
        "import jax; ds = jax.devices(); "
        "assert any(d.platform != 'cpu' for d in ds), 'cpu only'; "
        "print([d.platform for d in ds if d.platform != 'cpu'][0])"
    )
    try:
        proc = subprocess.run(
            [sys.executable, "-c", check],
            timeout=max(timeout_s, 1.0),
            capture_output=True,
            text=True,
        )
    except subprocess.TimeoutExpired:
        return ("down", None)
    if proc.returncode != 0:
        stderr = proc.stderr or ""
        # a dead tunnel can make jax fall back to the CPU backend after
        # logging an unavailability warning, which then fails the same
        # 'cpu only' assert — that is transient ("down"), not a
        # deterministic plugin-less install
        transient = (
            "Unable to initialize backend" in stderr
            or "UNAVAILABLE" in stderr
        )
        if "cpu only" in stderr and not transient:
            return ("cpu-only", None)
        return ("down", None)
    if not proc.stdout.strip():
        return ("down", None)
    return ("up", proc.stdout.strip().splitlines()[-1])


def force_cpu_from_env(devices: int = 8) -> bool:
    """Apply :func:`force_cpu` when the caller's environment asks for
    the CPU backend (JAX_PLATFORMS=cpu); returns whether it did."""
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        force_cpu(devices)
        return True
    return False

"""Batched on-device discrete-event simulation engine.

This is the TPU-native replacement for the reference's per-config
single-threaded simulator (fantoch/src/sim/) *and* its rayon sweep driver
(fantoch_ps/src/bin/simulation.rs:165-217): thousands of independent
(protocol, latency-matrix, conflict-rate) configurations advance in
lockstep as one `jax.vmap`'d step function driven by `lax.while_loop`,
sharded over a TPU device mesh by the sweep driver.

Design (see SURVEY.md §7):
- each *lane* (= one simulated deployment) holds a fixed-capacity message
  pool and fixed-shape per-process protocol state;
- each engine step advances simulated time to the earliest pending event
  and delivers at most ONE message per destination process — messages to
  different processes commute, so this preserves per-process timestamp
  order, which is all a DES needs;
- protocol handlers are pure per-process functions dispatched with
  `lax.switch` over the message type and `jax.vmap`'d over the process
  axis; the whole step is then vmapped over the lane (config) axis.
"""

from .checkpoint import (
    CheckpointCorruptError,
    CheckpointError,
    CheckpointMismatchError,
    CheckpointSpec,
    SweepInterrupted,
)
from .dims import EngineDims
from .faults import FaultPlan, LinkWindow, parse_fault_specs
from .core import build_runner, init_lane_state
from .monitor import (
    VIOL_DUP,
    VIOL_KEYRANGE,
    VIOL_MISSING,
    VIOL_ORDER,
    VIOL_PREMATURE,
    viol_names,
)
from .spec import LaneSpec, make_lane, stack_lanes
from .results import LaneResults, collect_results
from .driver import run_lanes

__all__ = [
    "CheckpointCorruptError",
    "CheckpointError",
    "CheckpointMismatchError",
    "CheckpointSpec",
    "SweepInterrupted",
    "EngineDims",
    "FaultPlan",
    "LinkWindow",
    "LaneSpec",
    "LaneResults",
    "VIOL_DUP",
    "VIOL_KEYRANGE",
    "VIOL_MISSING",
    "VIOL_ORDER",
    "VIOL_PREMATURE",
    "build_runner",
    "init_lane_state",
    "make_lane",
    "parse_fault_specs",
    "stack_lanes",
    "collect_results",
    "run_lanes",
    "viol_names",
]

"""On-device safety monitors for the batched engine.

The host explorer (``fantoch_tpu/mc/checker.py``) checks agreement and
exactly-once execution by materializing every process's full per-key
execution order — fine for 5k-state workloads, impossible for a
million-schedule device fuzz run. These monitors compress the same
properties into O(N x K) integers that update *inside* the vmapped step
function and reduce to two scalars per lane:

* ``_mon_hash [K]`` per process — a rolling order-sensitive hash of the
  commands executed on each key, updated at the protocol's executor
  choke point (``mon_exec``). Two processes that executed the same
  *number* of commands on a key but in different orders end with
  different hashes (modulo an astronomically unlikely i32 collision),
  so the cross-process comparison at lane end is the array analog of
  the reference's ``check_monitors`` (fantoch_ps protocol/mod.rs:724).
  Crucially the equal-count/different-hash test is sound *mid-run* too:
  for protocols whose executors enforce a per-key total order
  (timestamp, clock, slot or dependency order), two live processes with
  the same per-key execution count must have executed the same prefix;
* ``_mon_cnt [K]`` per process — exactly-once counters. At clean
  quiescence every live process must have executed every command
  exactly once, so each per-process total must equal the lane's
  completed-command total;
* ``_mon_flags`` per process — in-run guard bits: the
  execute-before-commit guard (``premature``; a command executed whose
  dot is not in the process's *committed* record — an independent data
  path from the executor's own readiness predicate) and a key-range
  guard that makes a misconfigured monitor key capacity loud instead of
  a false violation.

Monitoring is trace-gated by the ``monitor_keys`` argument threaded
through ``build_runner``/``init_lane_state``: when it is 0 the monitor
arrays are never created, ``mon_exec`` is a no-op at *trace time* (it
keys on the presence of ``_mon_hash`` in the state dict), and the
compiled step is bit-identical to an unmonitored engine — a fuzz-
disabled sweep pays nothing (tests/test_mc_fuzz.py pins this).

What the order hash does and does not prove is documented in
``docs/MC.md``.
"""

from __future__ import annotations

import functools
from typing import Dict

import jax.numpy as jnp
import numpy as np

from .dims import INF, SEQ_BOUND

I32 = jnp.int32

# rolling-hash multiplier (a prime; i32 multiplication wraps two's
# complement under XLA, which is exactly the modulus we want)
HASH_MUL = 1_000_003

# per-process in-run guard bits (``_mon_flags``)
MON_F_PREMATURE = 1  # executed a dot absent from the committed record
MON_F_KEYRANGE = 2   # executed key >= monitor_keys (monitor misconfig)

# per-lane violation bitmask (LaneResults.violation)
VIOL_ORDER = 1      # per-key execution orders diverge across live
                    # processes (equal counts, different hashes)
VIOL_DUP = 2        # a process executed more commands than completed
                    # (clean quiescent lanes only)
VIOL_MISSING = 4    # a process executed fewer (clean quiescent lanes;
                    # fuzz drivers may treat this as advisory — an
                    # undersized extra_time tail can leave a correct
                    # protocol's executors undrained)
VIOL_PREMATURE = 8  # execute-before-commit guard tripped
VIOL_KEYRANGE = 16  # monitor key capacity too small (setup error, not
                    # a protocol bug)

VIOL_NAMES = {
    VIOL_ORDER: "order-divergence",
    VIOL_DUP: "duplicate-execution",
    VIOL_MISSING: "missing-execution",
    VIOL_PREMATURE: "execute-before-commit",
    VIOL_KEYRANGE: "monitor-key-range",
}

# monitor keys carried inside the per-process protocol state during a
# step (merged before the handler vmap, stripped after)
MON_PS_KEYS = ("_mon_hash", "_mon_cnt", "_mon_flags")


def viol_names(code: int) -> str:
    if not code:
        return "ok"
    return "+".join(
        name for bit, name in sorted(VIOL_NAMES.items()) if code & bit
    ) or f"unknown({code})"


def mon_init(dims, monitor_keys: int) -> Dict[str, np.ndarray]:
    """Host-side monitor state for one lane (top-level lane-state keys;
    the engine merges the per-process arrays into ``ps`` around the
    handler vmap)."""
    N = dims.N
    return {
        "mon_hash": np.zeros((N, monitor_keys), np.int32),
        "mon_cnt": np.zeros((N, monitor_keys), np.int32),
        "mon_flags": np.zeros((N,), np.int32),
        "viol": np.int32(0),
        "viol_step": np.int32(INF),
        # the lane's coverage digest (cov_digest at lane end; 0 while
        # the lane is still mid-flight under the segmented runner)
        "cov": np.int32(0),
    }


@functools.lru_cache(maxsize=None)
def _digest_weights(length: int) -> np.ndarray:
    """``[length + 1]`` position weights for :func:`cov_digest`: the
    closed form of the rolling fold ``h ← h·HASH_MUL + x`` started from
    1 — ``MUL^L + Σ x_i · MUL^(L-1-i)`` mod 2^32 — computed host-side
    in exact integers, then reinterpreted as wrapping i32 (trace-time
    constants; the device does one multiply-add per element)."""
    powers = [1]
    for _ in range(length):
        powers.append((powers[-1] * HASH_MUL) & 0xFFFFFFFF)
    # weights[0] = MUL^L (the leading "1" term), weights[1 + i] =
    # MUL^(L-1-i) for flat element i
    w = np.asarray([powers[length]] + powers[length - 1 :: -1], np.uint32)
    return w.astype(np.int32)


def cov_digest(hashes, cnts):
    """Fold a lane's final ``[N, K]`` order-hash + count matrices into
    one i32 coverage digest — the AFL-style "which interleaving was
    this" signature (mc/coverage.py buckets it). Equals the rolling
    hash ``1 → fold(h·MUL + x)`` over the row-major concatenation of
    hashes then counts, in wrapping i32 (i32 multiply/add wrap two's
    complement under XLA, the modulus the weights are computed in).
    Order-sensitive by position weighting, and starting from 1 keeps an
    all-zero matrix (a lane that executed nothing) from aliasing the
    "unmonitored" zero. A pure function of frozen lane state, so
    re-running it per segment on a finished lane is idempotent."""
    flat = jnp.concatenate(
        [jnp.reshape(hashes, (-1,)), jnp.reshape(cnts, (-1,))]
    )
    w = _digest_weights(int(flat.shape[0]))
    return jnp.asarray(w[0], I32) + jnp.sum(
        flat.astype(I32) * jnp.asarray(w[1:], I32), dtype=I32
    )


def mon_exec(ps, key, src, seq, enable, premature=False):
    """Record one command execution at the calling protocol's executor
    choke point: ``(src, seq)`` executed on ``key`` by this process.

    A trace-time no-op when monitors are disabled (the ``_mon_*`` keys
    are only merged into ``ps`` by a monitored engine), so unmonitored
    sweeps compile zero monitor ops. ``premature`` is the protocol's
    execute-before-commit guard — True means the executed dot is NOT in
    this process's committed record."""
    if "_mon_hash" not in ps:
        return ps
    km = ps["_mon_hash"].shape[0]
    do = jnp.asarray(enable, bool)
    key = jnp.asarray(key, I32)
    in_range = (key >= 0) & (key < km)
    # command identity packs into i32: src < N << seq bound
    cmd = jnp.asarray(src, I32) * SEQ_BOUND + jnp.asarray(seq, I32) + 1
    iota = jnp.arange(km, dtype=I32)
    hit = (iota == key) & do & in_range
    return dict(
        ps,
        _mon_hash=jnp.where(
            hit, ps["_mon_hash"] * HASH_MUL + cmd, ps["_mon_hash"]
        ),
        _mon_cnt=ps["_mon_cnt"] + hit.astype(I32),
        _mon_flags=ps["_mon_flags"]
        | MON_F_PREMATURE * (do & jnp.asarray(premature, bool))
        | MON_F_KEYRANGE * (do & ~in_range),
    )


def merge_mon(st):
    """Lane-state monitor arrays → per-process ``ps`` keys (pre-vmap)."""
    return dict(
        st["ps"],
        _mon_hash=st["mon_hash"],
        _mon_cnt=st["mon_cnt"],
        _mon_flags=st["mon_flags"],
    )


def strip_mon(ps):
    """Inverse of :func:`merge_mon` after the handler vmap: returns
    (clean ps, monitor dict)."""
    ps = dict(ps)
    mon = {k.lstrip("_"): ps.pop(k) for k in MON_PS_KEYS}
    return ps, mon


def step_viol(st, mon_flags):
    """Per-step violation tracking: fold the in-run guard bits into the
    lane bitmask and pin the first violating step. A couple of tiny
    reductions — the heavy checks run once at lane end."""
    flags = jnp.bitwise_or.reduce(jnp.asarray(mon_flags, I32))
    viol = (
        st["viol"]
        | VIOL_PREMATURE * ((flags & MON_F_PREMATURE) != 0)
        | VIOL_KEYRANGE * ((flags & MON_F_KEYRANGE) != 0)
    )
    viol_step = jnp.where(
        (viol != 0) & (st["viol_step"] >= INF),
        st["steps"] + 1,
        st["viol_step"],
    )
    return viol, viol_step


def finalize_lane(protocol, dims, st, ctx, faults, running):
    """End-of-run monitor reduction (on device, once per lane): the
    cross-process order/count comparisons, folded into ``viol`` /
    ``viol_step``. ``running`` guards the segmented runner — a lane
    still mid-flight keeps its in-run bits only (the checks re-run
    idempotently on the final segment, when its state is frozen).

    * order: any pair of live processes with equal per-key counts but
      different hashes (skipped for protocols that declare
      ``MONITOR_ORDER = False`` — Basic's executor provides no
      cross-process order guarantee). Gated on a lossless lane: under
      message *loss* two correct processes can each permanently miss a
      different dropped commit and end with equal counts over different
      command sets — a modeling artifact of the no-retransmission
      network, not a protocol bug (docs/MC.md);
    * exactly-once/completeness: at clean quiescence (budget done, no
      error, nothing lost to faults, no crash plan, and the grace tail
      not cut by a fault horizon) every live process must have executed
      exactly the completed-command total.
    """
    N = dims.N
    procs = jnp.arange(N, dtype=I32)
    live = procs < ctx["rows"]
    if faults.crash:
        live = live & (ctx["fault_crash_t"] >= INF)

    hashes = st["mon_hash"]  # [N, K]
    cnts = st["mon_cnt"]
    viol = st["viol"]

    if getattr(protocol, "MONITOR_ORDER", True):
        pair = live[:, None] & live[None, :]
        same_cnt = cnts[:, None, :] == cnts[None, :, :]
        diff_hash = hashes[:, None, :] != hashes[None, :, :]
        order_bad = jnp.any(pair[:, :, None] & same_cnt & diff_hash)
        viol = viol | VIOL_ORDER * (
            order_bad & (st["fault_dropped"] == 0)
        )

    clean = (
        (st["done_time"] < INF)
        & (st["err"] == 0)
        & (st["fault_dropped"] == 0)
    )
    if faults.crash:
        clean = clean & jnp.all(ctx["fault_crash_t"] >= INF)
    if faults.horizon:
        # the extra_time drain tail must fit before the horizon, else
        # executors are legitimately undrained at lane end
        clean = clean & (
            st["done_time"] + ctx["extra_time"] <= ctx["fault_horizon"]
        )
    total = jnp.sum(st["clients"]["completed"])
    per_proc = jnp.sum(cnts, axis=1)  # [N]
    viol = viol | VIOL_DUP * (clean & jnp.any(live & (per_proc > total)))
    viol = viol | VIOL_MISSING * (
        clean & jnp.any(live & (per_proc < total))
    )

    viol = jnp.where(running, st["viol"], viol)
    viol_step = jnp.where(
        (viol != 0) & (st["viol_step"] >= INF), st["steps"], st["viol_step"]
    )
    # coverage digest: the interleaving signature the fuzzer buckets
    # (mc/coverage.py). Computed only once the lane's state is frozen —
    # a mid-flight lane keeps 0 and the final segment's re-run derives
    # the same digest idempotently, like the checks above.
    cov = jnp.where(running, st["cov"], cov_digest(hashes, cnts))
    return dict(st, viol=viol, viol_step=viol_step, cov=cov)

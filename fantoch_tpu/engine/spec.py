"""Host-side lane construction: planet + config + workload → ctx arrays.

Mirrors the oracle runner's wiring (fantoch/src/sim/runner.rs:64-190):
processes are placed one per region, discovery sorts processes by distance
with id tie-breaks (util.rs:153-186), clients connect to the closest
process (util.rs:188-230), and message delay is half the ping latency
(runner.rs:575-595). The output is a dict of fixed-shape numpy arrays — a
*lane context* — ready to be stacked into a batch and shipped to device.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Sequence

import jax.random as jr
import numpy as np

from ..client.key_gen import zipf_weights
from ..core.config import Config
from ..core.planet import Planet
from .dims import INF, EngineDims
from .faults import (
    NO_FAULTS,
    FaultFlags,
    FaultPlan,
    fault_ctx,
    halted_client_mask,
    min_link_delays,
    reorder_doomed_last,
    unavailable,
)


@dataclass
class LaneSpec:
    """One configuration of the sweep: device ctx + host-side metadata."""

    ctx: Dict[str, np.ndarray]
    config: Config
    region_rows: List[str]  # row index → client region name
    process_regions: List[str] = field(default_factory=list)
    # fault-plan capabilities + compact metadata (engine/faults.py);
    # NO_FAULTS / None for fault-free lanes
    fault_flags: FaultFlags = NO_FAULTS
    fault_meta: "dict | None" = None
    # traffic-schedule metadata (fantoch_tpu/traffic); None for static
    # lanes AND for flat schedules (which collapse to the static path)
    traffic_meta: "dict | None" = None
    # open-loop arrival-schedule metadata (docs/TRAFFIC.md "Open-loop
    # arrivals"); None for closed-loop lanes
    arrival_meta: "dict | None" = None


def _sorted_indices(planet: Planet, process_regions: Sequence[str]) -> np.ndarray:
    """For each process, all processes ordered by (distance, id) from its
    region — the discovery order (util.rs:153-186). 0-based indices."""
    n = len(process_regions)
    out = np.zeros((n, n), np.int32)
    for p, region in enumerate(process_regions):
        order = {r: i for i, (_lat, r) in enumerate(planet.sorted(region))}
        ranked = sorted(range(n), key=lambda q: (order[process_regions[q]], q))
        out[p] = ranked
    return out


def make_lane(
    protocol,
    planet: Planet,
    config: Config,
    *,
    conflict_rate: int = 100,
    pool_size: int = 1,
    zipf: "tuple[float, int] | None" = None,
    commands_per_client: int,
    clients_per_region: int,
    process_regions: Sequence[str],
    client_regions: Sequence[str],
    dims: EngineDims,
    extra_time_ms: int = 1000,
    seed: int = 0,
    reorder: bool = False,
    faults: "FaultPlan | None" = None,
    traffic=None,
    arrivals=None,
    arrival_load: int = 100,
    arrival_gap_ms: int = 4,
    open_window: int = 4,
) -> LaneSpec:
    """``zipf=(coefficient, total_keys)`` switches the workload from the
    ConflictPool generator to Zipf sampling over ``total_keys`` keys
    (key_gen.rs:113-119); lanes batched together must share the same
    zipf table size.

    ``reorder`` enables the oracle's message-reordering perturbation —
    every message delay is scaled by a uniform [0, 10) multiplier
    (runner.rs:520-524) — for race-hunting runs. Randomized delays void
    the conservative-lookahead bound, so reorder lanes run serialized
    (global-time stepping), and tie order is engine-defined: assert
    protocol invariants against these lanes, not oracle equality.

    ``config.shard_count > 1`` builds a partial-replication lane: one
    process per (shard, region) — the oracle Runner's multi-shard
    layout (sim/runner.py:81-103) — with per-shard client attachment
    and precomputed per-command shard/key tables (the device reads a
    command's keys from ctx by (client, seq) instead of carrying them
    in payloads).

    ``faults`` attaches a per-lane :class:`FaultPlan` (engine/faults.py):
    crash-stop processes, link-degradation/partition windows, and
    probabilistic drops. Lanes with and without plans can share one
    batch; the runner must be built with the batch's fault-flag union
    (``run_lanes``/``run_sweep`` derive it automatically).

    ``traffic`` attaches a time-varying traffic schedule
    (fantoch_tpu/traffic, docs/TRAFFIC.md): a
    :class:`~fantoch_tpu.traffic.TrafficSchedule`, a preset name from
    ``registry.TRAFFIC_PRESETS`` (resolved against this lane's
    ``conflict_rate``/``pool_size``/``commands_per_client``), a JSON
    schedule dict, or None. A **flat** schedule collapses to the static
    ctx path right here — same ctx fields, bit-identical traced jaxpr,
    byte-identical ``LaneResults`` — so the seed-warmed XLA cache and
    the GL005 gating pin survive; only non-flat schedules add the
    ``traffic_*`` epoch tables (structure-gated in engine/core.py).
    Lanes of one batch must agree on having (or not having) tables —
    ``stack_lanes`` refuses a mix.

    ``arrivals`` attaches an open-loop arrival process (docs/TRAFFIC.md
    "Open-loop arrivals"): an
    :class:`~fantoch_tpu.traffic.ArrivalSchedule`, a preset name from
    ``registry.ARRIVAL_PRESETS`` (resolved against this lane's
    ``arrival_gap_ms``/``commands_per_client``), a JSON schedule dict,
    or None/"closed" — the closed loop, tracing the bit-identical
    legacy jaxpr. Open-loop lanes timestamp every command by a seeded
    arrival draw independent of completion; at most ``open_window``
    commands are in flight per client (window-blocked commands queue,
    and queue delay counts into latency). ``arrival_load`` scales the
    offered load (percent of the schedule's base rate). Open-loop
    lanes are single-shard, non-reorder, think-free; the closed- and
    open-loop forms never share a batch (``stack_lanes`` refuses)."""
    n = config.n
    S = config.shard_count
    assert len(process_regions) == n
    assert S * n <= dims.N
    N, C = dims.N, dims.C
    total = S * n  # live process rows; row = shard * n + region index

    from ..traffic.schedule import resolve_traffic

    traffic = resolve_traffic(
        traffic, conflict=conflict_rate, pool_size=pool_size,
        commands=commands_per_client,
    )
    if traffic is not None and traffic.is_flat():
        # flat collapse: the schedule IS the static path; its single
        # effective phase becomes the lane's scalar knobs and no tables
        # are emitted, so the step traces the bit-identical jaxpr
        phase0 = traffic.phases[0]
        conflict_rate, pool_size = phase0.conflict_rate, phase0.pool_size
        traffic = None
    traffic_meta = None
    if traffic is not None:
        assert S == 1 and getattr(protocol, "KPC", 1) == 1, (
            "traffic schedules are single-shard/single-key for now"
        )
        traffic_meta = traffic.meta()

    from ..traffic.schedule import resolve_arrivals

    arrivals = resolve_arrivals(
        arrivals, mean_gap_ms=arrival_gap_ms,
        commands=commands_per_client, load_pct=arrival_load,
    )
    arrival_meta = None
    if arrivals is not None:
        assert S == 1 and getattr(protocol, "KPC", 1) == 1, (
            "open-loop arrivals are single-shard/single-key for now"
        )
        assert not reorder, (
            "open-loop arrivals need the deterministic delay matrix "
            "(count-based completion attribution); reorder lanes are "
            "closed-loop only"
        )
        assert traffic is None or all(
            p.think_ms == 0 for p in traffic.phases
        ), (
            "think delays model a closed loop's idle time between "
            "commands; an open-loop lane's issue times come from the "
            "arrival schedule instead"
        )
        assert open_window >= 1, open_window
        arrival_meta = dict(arrivals.meta(), window=int(open_window))

    if faults is not None and faults.is_noop():
        faults = None
    if faults is not None:
        assert S == 1, "fault plans are single-shard for now"
        assert all(r < n for r in faults.crashes), (
            f"crash rows {sorted(faults.crashes)} out of range for n={n}"
        )
        assert all(
            w.src < n and w.dst < n for w in faults.windows
        ), "window endpoints out of range"
    # crashes beyond what the protocol tolerates: the lane terminates
    # immediately with ERR_UNAVAIL (quorum unreachable), so quorum
    # selection below stays at its fault-free default
    unavail = faults is not None and unavailable(faults, protocol, config)

    def row_region(row: int) -> str:
        return process_regions[row % n]

    # process↔process delays: half the ping latency (runner.rs:575-595)
    delay_pp = np.zeros((N, N), np.int32)
    for i in range(total):
        for j in range(total):
            delay_pp[i, j] = (
                planet.ping_latency(row_region(i), row_region(j)) // 2
            )

    # conservative-lookahead matrix: lookahead[q, p] = minimum time any
    # chain of messages starting at q can take to reach p (all-pairs
    # shortest path over delay_pp; client hops never cross processes —
    # TO_CLIENT and the rewritten SUBMIT both stay on the attached
    # process). The engine lets p pop its earliest event at local time
    # e_p whenever e_p < min_{q != p}(e_q + lookahead[q, p]): nothing
    # can still arrive at or before e_p. The diagonal is INF — p's own
    # future emissions to itself land at or after e_p and are ordered by
    # the pool's prio/pop mechanism, so they never gate p's progress.
    # Padded rows stay at INF.
    lookahead = np.full((N, N), INF, np.int64)
    if faults is not None and faults.windows:
        # a window *override* may undercut the base delay, so the
        # lookahead lower bound is computed over each pair's minimum
        # effective delay across the whole run (multipliers only slow
        # links down; partitions only remove messages — both leave the
        # bound conservative)
        sp = min_link_delays(faults, delay_pp, total)
    else:
        sp = delay_pp[:total, :total].astype(np.int64)
    for k in range(total):
        sp = np.minimum(sp, sp[:, k, None] + sp[None, k, :])
    lookahead[:total, :total] = sp
    np.fill_diagonal(lookahead[:total, :total], INF)
    # the strict bound plus the global-minimum escape hatch are only
    # tie-safe when distinct processes can never exchange same-instant
    # messages; with a zero inter-process delay (colocated process
    # regions — always the case for multi-shard lanes, whose co-region
    # cross-shard processes sit at distance ~0) fall back to serialized
    # global-time stepping — such schedules are inherently tied, so the
    # exact-match contract (which only covers tie-free schedules) is
    # unaffected, only speed is
    offdiag = delay_pp[:total, :total][~np.eye(total, dtype=bool)]
    if (total > 1 and offdiag.min() < 1) or reorder:
        lookahead[:total, :total] = 0
        np.fill_diagonal(lookahead[:total, :total], INF)

    sorted_idx = _sorted_indices(planet, process_regions)
    if faults is not None and faults.crashes and not unavail:
        # recovery-free crash model: processes that are going to crash
        # are suspected from the start — rank them last in every
        # discovery order so quorum selection never includes them (the
        # oracle reorders its discovery lists identically)
        sorted_idx = reorder_doomed_last(sorted_idx, faults.crashes)

    # clients: clients_per_region per region, attached to the closest
    # process (closest_process_per_shard; single shard in the simulator)
    region_rows = list(dict.fromkeys(client_regions))
    assert len(region_rows) <= dims.RR
    client_attach = np.zeros((C,), np.int32)
    client_attach_s = np.zeros((C, S), np.int32)
    client_region_row = np.full((C,), dims.RR, np.int32)
    client_delay = np.zeros((C, N), np.int32)
    cmd_budget = np.zeros((C,), np.int32)
    c = 0
    for region in client_regions:
        order = {r: i for i, (_lat, r) in enumerate(planet.sorted(region))}
        closest = min(range(n), key=lambda q: (order[process_regions[q]], q))
        for _ in range(clients_per_region):
            assert c < C, "raise EngineDims.C"
            client_attach[c] = closest
            # per-shard connected process (closest_process_per_shard,
            # util.rs:188-230): shards share the region layout, so the
            # closest row index repeats per shard block
            for s in range(S):
                client_attach_s[c, s] = s * n + closest
            client_region_row[c] = region_rows.index(region)
            for p in range(total):
                client_delay[c, p] = (
                    planet.ping_latency(region, row_region(p)) // 2
                )
            cmd_budget[c] = commands_per_client
            c += 1

    halted = 0
    if faults is not None and faults.crashes:
        # clients attached to a doomed process (or any client under a
        # doomed leader) are halted: their budget is zeroed so they
        # never issue and the termination predicate excuses them —
        # replica death takes its clients with it (no reconnection
        # protocol, like the reference)
        mask = halted_client_mask(faults, config, client_attach[:c])
        cmd_budget[:c][mask] = 0
        halted = int(mask.sum())

    intervals = np.asarray(
        protocol.periodic_intervals(config, dims), np.int32
    )
    assert intervals.shape == (dims.R,)

    # workload switch (key_gen.rs:113-119): kind 0 = ConflictPool, kind
    # 1 = Zipf via inverse-CDF over the cumulative weight table; pool
    # lanes carry a 1-element dummy table so shapes stay static
    if zipf is None:
        key_gen_kind = np.int32(0)
        zipf_cum = np.ones((1,), np.float32)
    else:
        coefficient, total_keys = zipf
        key_cap = getattr(protocol, "K", None)
        assert key_cap is None or total_keys <= key_cap, (
            f"zipf universe {total_keys} exceeds protocol key capacity "
            f"{key_cap}; out-of-range keys would be silently dropped"
        )
        key_gen_kind = np.int32(1)
        zipf_cum = np.cumsum(
            zipf_weights(total_keys, coefficient)
        ).astype(np.float32)

    ctx: Dict[str, np.ndarray] = {
        "n": np.int32(n),
        "rows": np.int32(total),
        "f": np.int32(config.f),
        "delay_pp": delay_pp,
        "lookahead": np.minimum(lookahead, INF).astype(np.int32),
        "client_delay": client_delay,
        "client_attach": client_attach,
        "client_attach_s": client_attach_s,
        "client_region_row": client_region_row,
        "cmd_budget": cmd_budget,
        "conflict_rate": np.int32(conflict_rate),
        "pool_size": np.int32(pool_size),
        "key_gen_kind": key_gen_kind,
        "zipf_cum": zipf_cum,
        "rng_key": np.asarray(jr.PRNGKey(seed)),
        "reorder": np.int32(1 if reorder else 0),
        # distinct stream from the workload key generator
        "reorder_key": np.asarray(jr.fold_in(jr.PRNGKey(seed), 0x5EED)),
        "periodic_intervals": intervals,
        "extra_time": np.int32(extra_time_ms),
    }
    if traffic is not None:
        # rotated pools must fit the protocol's key capacity: private
        # keys sit at pool_span + client, so the top key of this lane
        # is pool_span + (live clients - 1)
        key_cap = getattr(protocol, "K", None)
        span = traffic.pool_span()
        assert key_cap is None or span + c <= key_cap, (
            f"traffic schedule {traffic.name!r} needs keys up to "
            f"{span + c - 1} but protocol key capacity is {key_cap}; "
            "out-of-range keys would be silently dropped"
        )
        ctx.update(traffic.compile(commands_per_client))
        if zipf is not None:
            # epoch-varying Zipf (satellite of docs/TRAFFIC.md): one
            # cumulative row per phase, phase coef 0.0 = the lane's
            # base coefficient; gen_key gathers the command's epoch
            # row, the DeviceStream mirror builds the identical table
            ctx.update(traffic.zipf_tables(zipf[0], int(zipf[1])))
    if arrivals is not None:
        # the whole per-client arrival-time table is drawn host-side
        # once and shipped verbatim to the engine AND the host oracle
        # (sim/runner.py) — bit-exact mirroring by construction; the
        # in-step queue plane (clients/ol_comp_t) is [C, open_window],
        # GL202-bounded by the compile-time window knob
        ctx["ol_arrival"] = arrivals.arrival_table(
            seed=seed, clients=C, commands=commands_per_client,
        )
        ctx["ol_window"] = np.int32(open_window)
    ctx.update(fault_ctx(faults, dims))
    ctx["fault_unavail"] = np.int32(1 if unavail else 0)
    if S > 1 or getattr(protocol, "KPC", 1) > 1:
        assert getattr(protocol, "S", 1) == S, (
            "protocol shards must match config.shard_count"
        )
        ctx.update(
            _partial_tables(
                protocol, planet, config, dims, ctx,
                commands_per_client, process_regions, row_region, total,
            )
        )
    ctx.update(protocol.lane_ctx(config, dims, sorted_idx))
    return LaneSpec(
        ctx=ctx,
        config=config,
        region_rows=region_rows,
        process_regions=list(process_regions),
        fault_flags=faults.flags if faults is not None else NO_FAULTS,
        fault_meta=(
            faults.meta(halted_clients=halted, unavail=unavail)
            if faults is not None
            else None
        ),
        traffic_meta=traffic_meta,
        arrival_meta=arrival_meta,
    )


def _partial_tables(
    protocol, planet: Planet, config: Config, dims: EngineDims, ctx,
    commands_per_client: int, process_regions, row_region, total: int,
):
    """Precomputed per-command shard/key tables for partial-replication
    (or multi-key) lanes.

    A command is fully determined by (client, seq): ``KPC`` key draws
    from the same counter-based stream the single-shard engine uses
    (``gen_key``; host replay = client/key_gen.py DeviceStream), each
    mapped to its shard by ``key_hash(str(key)) % shard_count`` —
    identical to the oracle workload's routing (client/workload.py:
    106-107) — then grouped: ``cmd_skey[c, j, s, :]`` = the command's
    distinct keys on shard s (-1 pad), ``cmd_kmask`` the touched-shard
    bitmask, ``cmd_parts`` the total distinct keys (= expected client
    result parts), ``cmd_target`` the first key's shard (the submit
    target, client/workload.py:84)."""
    import jax.numpy as jnp

    from ..core.util import key_hash
    from .core import KEYGEN_CTX_FIELDS, key_table_fn

    n, S = config.n, config.shard_count
    C, N = dims.C, dims.N
    T = commands_per_client
    KPC = getattr(protocol, "KPC", 1)

    keyctx = {k: jnp.asarray(ctx[k]) for k in KEYGEN_CTX_FIELDS}
    # the workload redraws duplicates until it has KPC *unique* keys
    # (workload.rs:156-186 / client/workload.py _gen_unique_keys), so
    # each command consumes a variable number of stream draws; walk the
    # stream exactly like the oracle does, growing the table on demand
    n_draws = T * KPC * 4 + 1
    draws = np.asarray(key_table_fn(C, n_draws)(keyctx))

    kmask = np.zeros((C, T + 1), np.int32)
    skey = np.full((C, T + 1, S, KPC), -1, np.int32)
    parts = np.ones((C, T + 1), np.int32)
    target = np.zeros((C, T + 1), np.int32)
    shard_cache: Dict[int, int] = {}
    for c in range(C):
        i = 1  # draw counter, 1-based like the engine's key stream
        for j in range(1, T + 1):
            keys: List[int] = []
            redraws = 0
            while len(keys) < KPC:
                if i >= draws.shape[1]:
                    n_draws *= 2
                    draws = np.asarray(key_table_fn(C, n_draws)(keyctx))
                k = int(draws[c, i])
                i += 1
                if k in keys:
                    redraws += 1
                    assert redraws < 10_000, (
                        "workload cannot produce unique keys (pool too "
                        "small for keys_per_command at this conflict "
                        "rate) — the oracle would spin here too"
                    )
                    continue
                keys.append(k)
            mask, tgt = 0, None
            per_shard: Dict[int, List[int]] = {}
            for k in keys:
                s = shard_cache.get(k)
                if s is None:
                    s = key_hash(str(k)) % S
                    shard_cache[k] = s
                if tgt is None:
                    tgt = s
                mask |= 1 << s
                per_shard.setdefault(s, []).append(k)
            kmask[c, j] = mask
            parts[c, j] = len(keys)
            target[c, j] = tgt
            for s, ks in per_shard.items():
                for d, k in enumerate(ks):
                    skey[c, j, s, d] = k

    # per-row shard id + closest process of every shard (the discovery
    # view each process routes cross-shard messages through,
    # util.rs:188-230; ties break by process id). Pad rows carry the
    # invalid shard id S so no shard-membership mask ever includes them.
    shard_of = np.full((N,), S, np.int32)
    closest = np.zeros((N, S), np.int32)
    for p in range(total):
        shard_of[p] = p // n
        order = {
            r: i for i, (_l, r) in enumerate(planet.sorted(row_region(p)))
        }
        i_star = min(
            range(n), key=lambda i: (order[process_regions[i]], i)
        )
        for s in range(S):
            closest[p, s] = s * n + i_star

    return {
        "cmd_kmask": kmask,
        "cmd_skey": skey,
        "cmd_parts": parts,
        "cmd_target": target,
        "shard_of": shard_of,
        "closest": closest,
    }


def _storage_dtype(bound: int) -> "str | None":
    """Smallest signed storage dtype that exactly holds [0, bound], or
    None when nothing below i32 does (the plane stays wide)."""
    if bound <= np.iinfo(np.int8).max:
        return "int8"
    if bound <= np.iinfo(np.int16).max:
        return "int16"
    return None


def narrow_spec(protocol, ctx: Dict[str, np.ndarray]) -> tuple:
    """The dtype-narrowing spec for one batch: a static tuple of
    ``(state path, storage dtype)`` entries naming cold i32 planes the
    segment runner stores as i16/i8 (engine/core.py
    ``cast_state_planes``; docs/PERF.md "Pipelined dispatch &
    donation").

    A plane is narrowed only when its value bound — already established
    by the GL001 interval family as a monotone per-command counter, and
    tightened here with the batch's *host-known* command budget — fits
    the storage dtype for the whole run:

    * ``clients/issued`` / ``clients/completed`` count a client's own
      commands: bounded by the batch's max per-client budget.
    * ``clients/parts`` counts one in-flight command's result parts and
      resets on completion: bounded by the cmd tables' max part count
      (1 on single-shard lanes).
    * ``metrics/hist`` / ``metrics/lat_count`` count completions per
      (region, bucket) / region: bounded by a lane's total commands.
    * protocol planes named by the protocol's ``NARROW_METRICS``
      declaration — per-process counters the owning module asserts
      increment at most once per command per process (fast/slow-path
      and stability counters): bounded by a lane's total commands.

    ``ctx`` is the stacked (or single-lane) numpy ctx; the bounds take
    the max over the batch. The tuple is hashable — it keys the cached
    runner — and deterministic (sorted by path)."""
    budget = np.asarray(ctx["cmd_budget"])
    budget_max = int(budget.max()) if budget.size else 0
    # max total commands of any one lane (the per-lane completion count)
    lane_total = int(
        budget.sum(axis=-1).max() if budget.ndim > 1 else budget.sum()
    )
    parts_max = (
        int(np.asarray(ctx["cmd_parts"]).max()) if "cmd_parts" in ctx
        else 1
    )
    candidates = {
        "clients/issued": budget_max,
        "clients/completed": budget_max,
        "clients/parts": parts_max,
        "metrics/hist": lane_total,
        "metrics/lat_count": lane_total,
    }
    for field in getattr(protocol, "NARROW_METRICS", ()):
        candidates[f"ps/{field}"] = lane_total
    out = []
    for path, bound in sorted(candidates.items()):
        # 2x headroom on every bound: the engine planes hit their bound
        # exactly (issue/complete guards), but fuzzing runs deliberately
        # broken protocol twins (mc/fuzz.py --inject-bug) that inherit
        # NARROW_METRICS — a counter a seeded bug overshoots by a few
        # must still be exact in storage so the monitors see the true
        # value. Budgets anywhere near the i16 range keep planes wide.
        dt = _storage_dtype(2 * bound)
        if dt is not None:
            out.append((path, dt))
    return tuple(out)


def stack_lanes(specs: Sequence[LaneSpec]) -> Dict[str, np.ndarray]:
    """Stack per-lane ctx dicts into one batched ctx (leading lane axis).

    Every lane must carry the same ctx fields: a batch compiles ONE
    step function, and structure-gated extensions (traffic tables, the
    partial-replication cmd tables) change the traced graph — mixing
    them would silently stack mismatched trees, so refuse loudly."""
    keys = specs[0].ctx.keys()
    for i, s in enumerate(specs[1:], start=1):
        assert s.ctx.keys() == keys, (
            f"lane {i} ctx fields differ from lane 0 "
            f"({sorted(set(s.ctx) ^ set(keys))}); lanes with and "
            "without traffic tables (or other structure-gated ctx) "
            "cannot share a batch"
        )
    return {k: np.stack([s.ctx[k] for s in specs]) for k in keys}

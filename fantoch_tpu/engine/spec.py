"""Host-side lane construction: planet + config + workload → ctx arrays.

Mirrors the oracle runner's wiring (fantoch/src/sim/runner.rs:64-190):
processes are placed one per region, discovery sorts processes by distance
with id tie-breaks (util.rs:153-186), clients connect to the closest
process (util.rs:188-230), and message delay is half the ping latency
(runner.rs:575-595). The output is a dict of fixed-shape numpy arrays — a
*lane context* — ready to be stacked into a batch and shipped to device.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Sequence

import jax.random as jr
import numpy as np

from ..client.key_gen import zipf_weights
from ..core.config import Config
from ..core.planet import Planet
from .dims import INF, EngineDims


@dataclass
class LaneSpec:
    """One configuration of the sweep: device ctx + host-side metadata."""

    ctx: Dict[str, np.ndarray]
    config: Config
    region_rows: List[str]  # row index → client region name
    process_regions: List[str] = field(default_factory=list)


def _sorted_indices(planet: Planet, process_regions: Sequence[str]) -> np.ndarray:
    """For each process, all processes ordered by (distance, id) from its
    region — the discovery order (util.rs:153-186). 0-based indices."""
    n = len(process_regions)
    out = np.zeros((n, n), np.int32)
    for p, region in enumerate(process_regions):
        order = {r: i for i, (_lat, r) in enumerate(planet.sorted(region))}
        ranked = sorted(range(n), key=lambda q: (order[process_regions[q]], q))
        out[p] = ranked
    return out


def make_lane(
    protocol,
    planet: Planet,
    config: Config,
    *,
    conflict_rate: int = 100,
    pool_size: int = 1,
    zipf: "tuple[float, int] | None" = None,
    commands_per_client: int,
    clients_per_region: int,
    process_regions: Sequence[str],
    client_regions: Sequence[str],
    dims: EngineDims,
    extra_time_ms: int = 1000,
    seed: int = 0,
    reorder: bool = False,
) -> LaneSpec:
    """``zipf=(coefficient, total_keys)`` switches the workload from the
    ConflictPool generator to Zipf sampling over ``total_keys`` keys
    (key_gen.rs:113-119); lanes batched together must share the same
    zipf table size.

    ``reorder`` enables the oracle's message-reordering perturbation —
    every message delay is scaled by a uniform [0, 10) multiplier
    (runner.rs:520-524) — for race-hunting runs. Randomized delays void
    the conservative-lookahead bound, so reorder lanes run serialized
    (global-time stepping), and tie order is engine-defined: assert
    protocol invariants against these lanes, not oracle equality."""
    n = config.n
    assert len(process_regions) == n <= dims.N
    N, C = dims.N, dims.C

    # process↔process delays: half the ping latency (runner.rs:575-595)
    delay_pp = np.zeros((N, N), np.int32)
    for i, a in enumerate(process_regions):
        for j, b in enumerate(process_regions):
            delay_pp[i, j] = planet.ping_latency(a, b) // 2

    # conservative-lookahead matrix: lookahead[q, p] = minimum time any
    # chain of messages starting at q can take to reach p (all-pairs
    # shortest path over delay_pp; client hops never cross processes —
    # TO_CLIENT and the rewritten SUBMIT both stay on the attached
    # process). The engine lets p pop its earliest event at local time
    # e_p whenever e_p < min_{q != p}(e_q + lookahead[q, p]): nothing
    # can still arrive at or before e_p. The diagonal is INF — p's own
    # future emissions to itself land at or after e_p and are ordered by
    # the pool's prio/pop mechanism, so they never gate p's progress.
    # Padded rows stay at INF.
    lookahead = np.full((N, N), INF, np.int64)
    sp = delay_pp[:n, :n].astype(np.int64)
    for k in range(n):
        sp = np.minimum(sp, sp[:, k, None] + sp[None, k, :])
    lookahead[:n, :n] = sp
    np.fill_diagonal(lookahead[:n, :n], INF)
    # the strict bound plus the global-minimum escape hatch are only
    # tie-safe when distinct processes can never exchange same-instant
    # messages; with a zero inter-process delay (colocated process
    # regions) fall back to serialized global-time stepping — such
    # schedules are inherently tied, so the exact-match contract (which
    # only covers tie-free schedules) is unaffected, only speed is
    offdiag = delay_pp[:n, :n][~np.eye(n, dtype=bool)]
    if (n > 1 and offdiag.min() < 1) or reorder:
        lookahead[:n, :n] = 0
        np.fill_diagonal(lookahead[:n, :n], INF)

    sorted_idx = _sorted_indices(planet, process_regions)

    # clients: clients_per_region per region, attached to the closest
    # process (closest_process_per_shard; single shard in the simulator)
    region_rows = list(dict.fromkeys(client_regions))
    assert len(region_rows) <= dims.RR
    client_attach = np.zeros((C,), np.int32)
    client_region_row = np.full((C,), dims.RR, np.int32)
    client_delay = np.zeros((C, N), np.int32)
    cmd_budget = np.zeros((C,), np.int32)
    c = 0
    for region in client_regions:
        order = {r: i for i, (_lat, r) in enumerate(planet.sorted(region))}
        closest = min(range(n), key=lambda q: (order[process_regions[q]], q))
        for _ in range(clients_per_region):
            assert c < C, "raise EngineDims.C"
            client_attach[c] = closest
            client_region_row[c] = region_rows.index(region)
            for p in range(n):
                client_delay[c, p] = (
                    planet.ping_latency(region, process_regions[p]) // 2
                )
            cmd_budget[c] = commands_per_client
            c += 1

    intervals = np.asarray(
        protocol.periodic_intervals(config, dims), np.int32
    )
    assert intervals.shape == (dims.R,)

    # workload switch (key_gen.rs:113-119): kind 0 = ConflictPool, kind
    # 1 = Zipf via inverse-CDF over the cumulative weight table; pool
    # lanes carry a 1-element dummy table so shapes stay static
    if zipf is None:
        key_gen_kind = np.int32(0)
        zipf_cum = np.ones((1,), np.float32)
    else:
        coefficient, total_keys = zipf
        key_cap = getattr(protocol, "K", None)
        assert key_cap is None or total_keys <= key_cap, (
            f"zipf universe {total_keys} exceeds protocol key capacity "
            f"{key_cap}; out-of-range keys would be silently dropped"
        )
        key_gen_kind = np.int32(1)
        zipf_cum = np.cumsum(
            zipf_weights(total_keys, coefficient)
        ).astype(np.float32)

    ctx: Dict[str, np.ndarray] = {
        "n": np.int32(n),
        "f": np.int32(config.f),
        "delay_pp": delay_pp,
        "lookahead": np.minimum(lookahead, INF).astype(np.int32),
        "client_delay": client_delay,
        "client_attach": client_attach,
        "client_region_row": client_region_row,
        "cmd_budget": cmd_budget,
        "conflict_rate": np.int32(conflict_rate),
        "pool_size": np.int32(pool_size),
        "key_gen_kind": key_gen_kind,
        "zipf_cum": zipf_cum,
        "rng_key": np.asarray(jr.PRNGKey(seed)),
        "reorder": np.int32(1 if reorder else 0),
        # distinct stream from the workload key generator
        "reorder_key": np.asarray(jr.fold_in(jr.PRNGKey(seed), 0x5EED)),
        "periodic_intervals": intervals,
        "extra_time": np.int32(extra_time_ms),
    }
    ctx.update(protocol.lane_ctx(config, dims, sorted_idx))
    return LaneSpec(
        ctx=ctx,
        config=config,
        region_rows=region_rows,
        process_regions=list(process_regions),
    )


def stack_lanes(specs: Sequence[LaneSpec]) -> Dict[str, np.ndarray]:
    """Stack per-lane ctx dicts into one batched ctx (leading lane axis)."""
    keys = specs[0].ctx.keys()
    return {k: np.stack([s.ctx[k] for s in specs]) for k in keys}

"""Fixed-shape interval sets for the device engine.

Array twin of ``fantoch_tpu/core/intervals.IntervalSet`` (itself the host
mirror of the threshold crate's AboveExSet/ARClock): a *frontier* scalar
(all of 1..=frontier present) plus up to G buffered gap ranges above it.
Used for per-(key, voter) vote clocks in the Tempo table executor (votes
can arrive out of order because attached votes ride through the
coordinator while detached votes fly direct) and per-source committed-dot
clocks in GC (slow-path commits can finish after later fast-path ones).

All functions are pure and shaped for ``vmap``/scatter composition:
state is a pair of arrays ``frontier`` (i32 scalar) and ``gaps`` [G, 2]
(start, end; start == 0 marks a free slot). Overflowing G is reported via
the returned flag — callers surface it as a lane error, never silently
drop votes.
"""

from __future__ import annotations

import jax.numpy as jnp

I32 = jnp.int32


def iset_empty(g: int):
    return jnp.zeros((), I32), jnp.zeros((g, 2), I32)


def iset_add_range(frontier, gaps, start, end, enable=True):
    """Union ``start..=end`` into the set. Returns (frontier, gaps,
    overflow). Tolerates overlap with already-present events (union
    semantics — the host IntervalSet's add returns False there; device
    callers don't need that signal)."""
    g = gaps.shape[0]
    start = jnp.maximum(start, frontier + 1)
    do = jnp.asarray(enable, bool) & (end >= start)

    # extend the frontier directly when adjacent, else buffer as a gap
    direct = do & (start == frontier + 1)
    frontier = jnp.where(direct, jnp.maximum(frontier, end), frontier)

    store = do & ~direct
    free = gaps[:, 0] == 0
    slot = jnp.argmax(free)
    overflow = store & ~jnp.any(free)
    slot = jnp.where(store & ~overflow, slot, g)
    # one-hot instead of scatters: G is tiny and a scatter is one whole
    # kernel on the target runtime while this fuses away
    hit_slot = jnp.arange(g) == slot
    gaps = jnp.where(
        hit_slot[:, None], jnp.stack([start, end])[None, :], gaps
    )

    # absorb gaps that touch the (possibly advanced) frontier; one pass
    # per buffered gap bounds the chain. Statically unrolled: the loop
    # body is pure elementwise/reduce work, so unrolling keeps the whole
    # absorption inside one fusion instead of paying per-iteration
    # kernel launches inside a lax loop.
    for _ in range(g):
        hit = (gaps[:, 0] > 0) & (gaps[:, 0] <= frontier + 1)
        frontier = jnp.maximum(
            frontier, jnp.max(jnp.where(hit, gaps[:, 1], 0))
        )
        gaps = jnp.where(hit[:, None], 0, gaps)
    return frontier, gaps, overflow


def iset_add(frontier, gaps, event, enable=True):
    return iset_add_range(frontier, gaps, event, event, enable)


def iset_contains(frontier, gaps, x):
    """Membership test; broadcasts over leading axes of ``x`` when
    ``frontier``/``gaps`` are gathered to matching shapes (gaps'
    trailing axes must be [..., G, 2]).

    Callers whose gathered ``gaps`` operand is huge (per-dependency
    gathers at sweep scale) should use :func:`iset_contains_gathered`
    instead — one fusion holding the [..., G]-wide comparison block
    can overflow VMEM on the TPU runtime (worker kernel fault)."""
    in_gap = jnp.any(
        (gaps[..., 0] > 0)
        & (gaps[..., 0] <= x[..., None])
        & (x[..., None] <= gaps[..., 1]),
        axis=-1,
    )
    # events are 1-based; 0 is the codebase's empty-slot marker and is
    # never a member
    return (x >= 1) & ((x <= frontier) | in_gap)


def iset_contains_gathered(front_by_src, gaps_by_src, src, x):
    """Membership of ``x[...]`` in the interval set of ``src[...]``,
    with per-source state ``front_by_src [S]`` / ``gaps_by_src
    [S, G, 2]``. Gathers one [*, 2] gap slice per g instead of the full
    [..., G, 2] block, keeping every intermediate at ``x``'s size — the
    VMEM-safe form of ``iset_contains(front[src], gaps[src], x)``."""
    out = (x >= 1) & (x <= front_by_src[src])
    for g in range(gaps_by_src.shape[-2]):
        s = gaps_by_src[src, g, 0]
        e = gaps_by_src[src, g, 1]
        out = out | ((s > 0) & (s <= x) & (x <= e))
    return out

"""Static shape bounds for the device engine.

Everything under `jit` needs static shapes; these bounds are the knobs.
Per-lane *values* (n, f, delays, conflict rate, ...) vary freely inside a
batch; the *bounds* below are shared by every lane of a compiled sweep.
Overflow of any bound is detected at runtime and surfaced to the host as a
per-lane error flag (SURVEY.md §7.3) — results of flagged lanes are
discarded, never silently wrong.
"""

from __future__ import annotations

from dataclasses import dataclass

# simulated time / sequence sentinel: far enough from i32 overflow that
# `INF + delay` cannot wrap
INF = 1 << 30

# i32 value ceiling: the bound the lint auditor (fantoch_tpu/lint)
# checks derived interval bounds against — any add/mul/sum chain that
# can exceed it without a clamp/`where` guard is flagged GL001
I32_MAX = (1 << 31) - 1

# largest integer magnitude float32 represents exactly; integer sums
# computed through f32 matmuls (engine/core.py cumsum_i32) must stay
# at or below this or the result silently rounds
F32_EXACT = 1 << 24

# dot sequences must stay below this bound so (source, sequence) packs
# into one i32 for lexicographic argmin scans; protocols flag `err` on a
# sequence reaching it
SEQ_BOUND = 1 << 20

# measured per-kernel fixed overhead on the target runtime (docs/PERF.md
# "cost model"): every emitted kernel — fusion, scatter, gather, reduce,
# sort, loop iteration — costs this much regardless of data size at
# engine scales. The lint cost ledger (fantoch_tpu/lint/cost.py, GL201)
# turns a static kernel count into an estimated ms/step range with it.
KERNEL_MS_LO = 0.1
KERNEL_MS_HI = 0.3

# the measured throughput sweet spot of the target runtime: batch
# scaling turns bandwidth-bound past ~512 lanes (docs/PERF.md), so 512
# is the documented sweep shape — the lane count the VMEM-footprint
# estimator (GL202) multiplies per-lane intermediates by
SWEEP_LANES = 512

# ----------------------------------------------------------------------
# declared backend profiles — the ROADMAP item-5 seam. Every width /
# packing / cost constant above is TPU-shaped; before GPU/CPU become
# real sweep axes, the GL303 backend-width audit
# (fantoch_tpu/lint/transfer.py, docs/LINT.md) checks the engine's
# packing and narrowing choices against EVERY profile declared here,
# so porting starts from a machine-checked inventory of what breaks
# where instead of a grep. Fields:
#
#   int_width        — signed integer width (bits) of the backend's
#                      native lane integer; ``SEQ_BOUND`` packings,
#                      ``INF`` headroom and ``I32_MAX`` clamp targets
#                      must fit in it
#   matmul_exact_bound — largest integer magnitude the backend's
#                      *default* f32 matmul accumulates exactly;
#                      ``cumsum_i32`` (engine/core.py) computes integer
#                      prefix sums through f32 matmuls and silently
#                      rounds past this. TPU/CPU f32 carries the full
#                      24-bit mantissa; GPU defaults to tf32 tensor
#                      cores (10 explicit mantissa bits → 1 << 11)
#                      unless the highest-precision mode is forced
#   subword_dtypes   — storage dtypes the backend supports for the
#                      narrowed cold planes (engine/spec.py
#                      ``narrow_spec``: i16/i8 carry compaction)
#   kernel_ms        — measured (lo, hi) per-kernel dispatch overhead
#                      (the docs/PERF.md cost model GL201 gates on),
#                      or None when unmeasured on that backend — GL303
#                      flags None so the gap stays a named, baselined
#                      finding until item 5 measures it
BACKEND_PROFILES = {
    "tpu": dict(
        int_width=32,
        matmul_exact_bound=F32_EXACT,
        subword_dtypes=("int8", "int16"),
        kernel_ms=(KERNEL_MS_LO, KERNEL_MS_HI),
    ),
    "gpu": dict(
        int_width=32,
        matmul_exact_bound=1 << 11,  # tf32 default
        subword_dtypes=("int8", "int16"),
        kernel_ms=None,
    ),
    "cpu": dict(
        int_width=64,
        matmul_exact_bound=F32_EXACT,
        subword_dtypes=("int8", "int16"),
        kernel_ms=None,
    ),
}

# ----------------------------------------------------------------------
# declared heterogeneous-megabatch grid compositions — the ROADMAP
# item-1 seam. A ``lax.switch`` megabatch packs every lane of a grid
# into ONE union state skeleton (engine/skeleton.py), so each lane pays
# the union's resident bytes instead of its own protocol's: a
# caesar-shaped union silently multiplies a tempo-only sweep's HBM
# footprint unless the composition is declared and budgeted here. The
# GL603 padding-amplification gate (fantoch_tpu/lint/skeleton.py)
# computes, per composition, union-resident bytes / native per-protocol
# bytes over the GL601 ledger and fails by name when any member exceeds
# ``max_amplification``. Audit names follow the lint grid: a bare
# protocol name is its single-shard audit, ``<name>@2shards`` the
# partial-replication variant. Budgets are declared against measured
# HEAD ratios with headroom (docs/PERF.md "Skeleton amplification"),
# like the GL202/GL503 VMEM budgets — raising one is a reviewed diff,
# never a silent drift.
SKELETON_GRIDS = {
    # the cheapest real megabatch: one protocol, both replication
    # modes (measured 4.45x at HEAD — the 2-shard pool/dot extents
    # dominate the single-shard lanes)
    "tempo-mixed": {
        "audits": ("tempo", "tempo@2shards"),
        "max_amplification": 6.0,
    },
    # the paper's core grid: every full-replication protocol in
    # lockstep (measured 35.6x at HEAD for fpaxos — tiny native state,
    # union shaped by caesar/tempo extents plus every ps slot)
    "full-replication": {
        "audits": (
            "basic", "fpaxos", "tempo", "atlas", "epaxos", "caesar",
        ),
        "max_amplification": 40.0,
    },
    # everything the lint families audit — the worst-case union
    # (measured 109x at HEAD for fpaxos: declared here so the cost of
    # an everything-batch is a number in a reviewed file, not a
    # surprise OOM; real campaigns should compose narrower grids)
    "full-grid": {
        "audits": (
            "basic", "fpaxos", "tempo", "atlas", "epaxos", "caesar",
            "tempo@2shards", "atlas@2shards",
        ),
        "max_amplification": 120.0,
    },
}

# per-lane error taxonomy: the engine and the protocol modules OR these
# bits into int32 error words (per process for protocol state, per lane
# for engine conditions), so a failing lane names its cause instead of
# reporting one opaque bool (VERDICT round 1, weak #8)
ERR_POOL = 1        # message-pool overflow — raise EngineDims.M
ERR_TRUNCATED = 2   # max_steps exhausted before the lane finished
ERR_SEQ = 4         # sequence/clock packing bound exceeded (SEQ_BOUND)
ERR_DOT = 8         # dot-slot window collision — raise EngineDims.D
ERR_CAPACITY = 16   # fixed-width table/buffer overflow (rows, slots)
ERR_PROTO = 32      # protocol invariant violated (missing/dup entries)
ERR_STUCK = 64      # one message requeued > REQUEUE_LIMIT times — a
                    # prerequisite that never arrives (deadlocked lane)
ERR_UNAVAIL = 128   # fault plan exceeds what the protocol tolerates
                    # (crashes > f, or survivors < its largest quorum):
                    # quorum unreachable, the lane terminates instead of
                    # hanging (engine/faults.py)

# readiness-gate bounces per message before the lane is declared stuck;
# legitimate waits are bounded by the largest delivery-time gap between
# a message and its prerequisite (~10 × max WAN delay under reordering,
# i.e. a few thousand 1 ms requeues)
REQUEUE_LIMIT = 1 << 13

ERR_NAMES = {
    ERR_POOL: "pool-overflow",
    ERR_TRUNCATED: "truncated",
    ERR_SEQ: "seq-overflow",
    ERR_DOT: "dot-collision",
    ERR_CAPACITY: "capacity-overflow",
    ERR_PROTO: "protocol-invariant",
    ERR_STUCK: "requeue-livelock",
    ERR_UNAVAIL: "quorum-unavailable",
}


def err_names(code: int) -> str:
    """Decode an error word into a readable cause list."""
    if not code:
        return "ok"
    return "+".join(
        name for bit, name in sorted(ERR_NAMES.items()) if code & bit
    ) or f"unknown({code})"


def dot_slot(seq, dims: "EngineDims"):
    """Recycled per-source dot-slot index for a 1-based sequence."""
    return (seq - 1) % dims.D


@dataclass(frozen=True)
class EngineDims:
    """Static bounds shared by all lanes of one compiled engine.

    N: max processes per lane (lanes with n < N mask the tail)
    C: max clients per lane (padded clients have a 0-command budget)
    M: message-pool capacity (in-flight messages per lane)
    D: per-source dot-slot capacity (in-flight + not-yet-GC'd commands
       issued by one process; slots recycle modulo D after GC)
    F: max messages a single handler invocation may emit
    R: periodic-event rows per process (protocol-specific timers)
    P: payload words per message
    H: latency-histogram buckets (1 ms each; last bucket catches the tail)
    RR: client-region rows for latency aggregation
    """

    N: int
    C: int
    M: int
    D: int
    F: int
    R: int
    P: int
    H: int = 512
    RR: int = 8

    @staticmethod
    def for_protocol(protocol, n: int, clients: int, payload: int,
                     dot_slots: int = 64, pool: int | None = None,
                     total_commands: int | None = None,
                     regions: int = 8,
                     hist_buckets: int = 512) -> "EngineDims":
        """Reasonable bounds for a (protocol, n, client-count) sweep.

        When a client sits at 0 latency from its whole quorum the closed
        loop degenerates: the entire command budget is issued in one
        simulated instant and every remote delivery queues up, so the
        safe pool bound is ``total_commands × 2(n-1)``. Pass
        ``total_commands`` to get that bound, or ``pool`` to override;
        otherwise the steady-state bound (clients pace themselves at WAN
        RTT) is used. Overflow is always detected, never silent.
        """
        fanout = getattr(protocol, "MAX_FANOUT", n + 1)
        # slots a protocol's hoisted post-switch stages need beyond
        # what any single branch fills (CaesarDev's hoisted scans)
        extra = getattr(protocol, "EXTRA_SLOTS", 0)
        if pool is None:
            # closed-loop clients keep ≤ ~n messages in flight per command
            # plus periodic GC traffic
            pool = clients * (n + 2) + 4 * n * n + 64
            if total_commands is not None:
                pool = max(pool, total_commands * 2 * (n - 1) + clients + 64)
        return EngineDims(
            N=n,
            C=clients,
            M=pool,
            D=dot_slots,
            F=max(fanout, n + 1) + extra,
            R=getattr(protocol, "PERIODIC_ROWS", 1),
            P=max(payload, 3),
            H=hist_buckets,
            RR=regions,
        )

    @staticmethod
    def for_partial(protocol, n: int, clients: int,
                    total_commands: int,
                    dot_slots: int | None = None,
                    regions: int | None = None) -> "EngineDims":
        """Bounds for a partial-replication (multi-shard) lane: the
        process axis spans every shard's rows and the pool bound covers
        the cross-shard fan-out (forwards, shard commits, executor
        requests). One definition serves the CLI, the accuracy tool and
        the diff tests so the tuned capacity formulas live here."""
        S = protocol.S
        return EngineDims(
            N=S * n,
            C=clients,
            M=total_commands * 4 * S * n + 64,
            D=dot_slots if dot_slots is not None else total_commands + 1,
            F=protocol.fanout(n),
            R=protocol.PERIODIC_ROWS,
            P=protocol.payload_width(n),
            H=2048,
            RR=regions if regions is not None else n,
        )

"""The heterogeneous protocol megabatch runner — ROADMAP item 1's
switch-dispatched step over skeleton-packed lanes.

``engine/skeleton.py`` proved the unification (GL601 ledger, GL602
branch avals, GL603 amplification budgets, GL604 homogeneous
round-trips); this module is the runner that proof layer exists for:

- :func:`hetero_switch_step` routes one ``lax.switch`` over the grid's
  audits, each branch exactly ``unpack -> _lane_step -> pack`` — legal
  because GL602 proved every branch consumes and produces the union's
  own avals. Fault flags compose through the switch the same way they
  compose through a homogeneous batch (the batch union selects traced
  graphs, never avals — GL602's fault leg); monitored states compose
  by *structure refusal* (GL602's monitor leg): the skeleton does not
  know monitor planes, so ``monitor_keys > 0`` is refused by name here
  rather than silently absorbed.
- :func:`hetero_segment_lane_fn` mirrors ``engine/core.py
  segment_lane_fn`` on packed trees: the while-loop condition reads
  the engine-common liveness scalars (``done_time``/``now``/``err``/
  ``steps``, ``extra_time``, ``fault_horizon``) straight from the
  union's SHARED slots — proven SHARED at build time, refused by name
  otherwise — so liveness never pays an unpack.
- :func:`build_hetero_segment_runner` / :func:`build_hetero_window_runner`
  are the batched flavors (vmap + jit, ``donate_argnums`` donation,
  scan-fused checkpoint windows) with exactly the native builders'
  contracts, including the fixed-point property the pipelined sweep
  driver and the scan windows lean on: a finished batch re-running a
  segment is a byte-exact no-op.
- :func:`prepare_batch` is the host-side adapter ``parallel/sweep.py``
  calls in ``hetero=True`` mode: group the mixed lanes by audit, stack
  each group's ctx (the per-group twin of ``stack_lanes`` — which by
  design refuses cross-protocol batches), precompute key tables with
  the same bit-identity contract as the native driver, init native
  lane states, then pack everything through the skeleton.
- :func:`collect_hetero_results` inverts the packing on the fetched
  result sub-tree and hands each group's native planes to the
  unchanged ``collect_results`` — per-lane results are byte-identical
  to each lane's homogeneous-control run, which is exactly what the
  GL605 lint pin (lint/skeleton.py) and tests/test_hetero.py gate.

Amplification caveat (docs/PERF.md "Heterogeneous megabatch"): under a
batched ``protocol_id``, ``lax.switch`` lowers to computing EVERY
branch and selecting — a mixed step costs roughly the sum of its
audits' steps, on top of the GL603-budgeted resident-byte padding. The
win is batch *fullness*, one compile, and one fleet-wide AOT artifact,
not per-step FLOPs; homogeneous batching still wins when a grid is
dominated by one protocol.
"""

from __future__ import annotations

import functools
import hashlib
from typing import Any, Dict, List, Mapping, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .checkpoint import canonical_json, step_signature
from .core import (
    _lane_running,
    _lane_step,
    finish_segmented,
    init_lane_state,
    key_table_fn,
    keygen_ctx_fields,
)
from .faults import NO_FAULTS, FaultFlags
from .skeleton import (
    PRIVATE,
    SHARED,
    Skeleton,
    SkeletonMismatchError,
    build_skeleton,
    classify_planes,
    pack_ctx,
    pack_state,
    skeleton_fingerprint,
    unflatten_planes,
    unpack_ctx,
    unpack_state,
    walk_planes,
)
from .spec import narrow_spec, stack_lanes

#: signature ``kind`` of a hetero megabatch (vs checkpoint.py's native
#: kind) — a native artifact can never satisfy a hetero signature or
#: vice versa, before any field-level compare even runs
HETERO_KIND = "fantoch-hetero-sweep-v1"

#: the engine-common liveness scalars the while-loop condition reads —
#: served from the union's SHARED slots (proven at build time)
_RUNNING_STATE_PLANES = ("done_time", "err", "now", "steps")


class HeteroBatchError(RuntimeError):
    """A mixed batch cannot be built or run as asked — always refused
    by name (a silently mis-grouped or mis-monitored megabatch would
    be a wrong-result bug, not a crash)."""


class HeteroBatch:
    """The grid-wide identity of a heterogeneous megabatch: the proven
    union :class:`~fantoch_tpu.engine.skeleton.Skeleton` plus each
    audit's ``(protocol, dims)`` pair, in skeleton audit order (index =
    ``protocol_id``). Hashable — it keys the cached compiled runners in
    ``parallel/sweep.py`` the way ``(protocol, dims)`` keys the native
    ones — via the skeleton fingerprint and the protocols' value
    identity, never via the (unhashable) plane mapping itself."""

    def __init__(self, skeleton: Skeleton, protocols: Mapping[str, Any],
                 dims: Mapping[str, Any]):
        missing = sorted(
            set(skeleton.audits) - (set(protocols) & set(dims))
        )
        if missing:
            raise HeteroBatchError(
                f"skeleton grid audits {missing} have no (protocol, "
                "dims) mapping entry — the switch must enumerate every "
                "audit of the skeleton, present in this batch or not"
            )
        slashed = sorted(a for a in skeleton.audits if "/" in a)
        if slashed:
            raise HeteroBatchError(
                f"audit key(s) {slashed} contain '/', the checkpoint "
                "flattener's path separator — packed state keyed by "
                "them would not survive a checkpoint round trip; "
                "rename the groups (campaign.manager.hetero_plan maps "
                "'/' to '_')"
            )
        self.skeleton = skeleton
        self.audits: Tuple[str, ...] = skeleton.audits
        self.protocols = {a: protocols[a] for a in self.audits}
        self.dims = {a: dims[a] for a in self.audits}
        self.fingerprint = skeleton_fingerprint(skeleton)
        self._key = (
            self.fingerprint,
            self.audits,
            tuple(self.protocols[a] for a in self.audits),
            tuple(self.dims[a] for a in self.audits),
        )

    def __hash__(self):
        return hash(self._key)

    def __eq__(self, other):
        return (
            isinstance(other, HeteroBatch) and self._key == other._key
        )

    def __repr__(self):
        return (
            f"HeteroBatch(audits={list(self.audits)}, "
            f"skeleton={self.fingerprint[:12]}...)"
        )


def _check_unmonitored(monitor_keys: int) -> None:
    if monitor_keys:
        raise HeteroBatchError(
            "monitored fuzz states carry planes outside the proven "
            "skeleton (monitor gating composes by structure refusal — "
            "GL602's monitor leg); run monitored batches homogeneous"
        )


# ----------------------------------------------------------------------
# the switch-dispatched step
# ----------------------------------------------------------------------

def hetero_switch_step(hb: HeteroBatch, reorder: bool = False,
                       faults: FaultFlags = NO_FAULTS,
                       monitor_keys: int = 0):
    """One packed-state step: ``step(packed_st, packed_ctx) ->
    packed_st`` dispatching on the lane's ``protocol_id`` plane over
    one branch per skeleton audit, each branch exactly ``unpack ->
    _lane_step -> pack`` (the traced composition GL602 proves aval-
    identical across branches). ``faults`` is the whole mixed batch's
    capability union — flags select traced graphs, never avals, and a
    fault-free lane's ctx planes are inert, so every branch compiles
    the union graph and results stay byte-identical to each lane's
    homogeneous control (the GL605 pin). The switch enumerates EVERY
    skeleton audit whether or not this batch carries lanes of it,
    which is what keeps the traced graph — and therefore the AOT slot
    hash — a function of the grid, not of one batch's composition."""
    _check_unmonitored(monitor_keys)
    skeleton = hb.skeleton

    def make_branch(audit):
        protocol, dims = hb.protocols[audit], hb.dims[audit]

        def branch(packed_st, packed_cx):
            st = unpack_state(skeleton, audit, packed_st, xp=jnp)
            cx = unpack_ctx(skeleton, audit, packed_cx, xp=jnp)
            out = _lane_step(
                protocol, dims, st, cx, reorder, faults, monitor_keys
            )
            # pack_state re-stamps this branch's own protocol_id — for
            # the selected branch that is exactly the lane's input id,
            # so the dispatch plane is a per-lane constant of the run
            return pack_state(skeleton, audit, out, xp=jnp)

        return branch

    branches = tuple(make_branch(a) for a in skeleton.audits)

    def step(packed_st, packed_cx):
        return jax.lax.switch(
            packed_st["protocol_id"], branches, packed_st, packed_cx
        )

    return step


def _running_views(skeleton: Skeleton, faults: FaultFlags):
    """Build-time proof + view builder for the while-loop condition:
    every liveness scalar ``_lane_running`` reads must live in a SHARED
    union slot (same dtype and extent in every audit), so the condition
    reads it straight off the packed tree with no unpack and no switch.
    A skeleton that stores one of them any other way is refused by
    name — the condition would otherwise need per-audit dispatch."""
    needed = [("state", n) for n in _RUNNING_STATE_PLANES]
    needed.append(("ctx", "extra_time"))
    if faults.horizon:
        needed.append(("ctx", "fault_horizon"))
    for prefix, name in needed:
        ent = skeleton.planes.get(f"{prefix}.{name}")
        verdict = ent["verdict"] if ent else "ABSENT"
        if verdict != SHARED:
            raise HeteroBatchError(
                f"the megabatch loop condition reads {prefix}.{name} "
                f"from the union's shared slots, but this skeleton "
                f"stores it as {verdict} — liveness must be SHARED "
                "across every audit of the grid"
            )

    def views(packed_st, packed_cx):
        st = {
            n: packed_st["shared"][n] for n in _RUNNING_STATE_PLANES
        }
        cx = {"extra_time": packed_cx["shared"]["extra_time"]}
        if faults.horizon:
            cx["fault_horizon"] = packed_cx["shared"]["fault_horizon"]
        return st, cx

    return views


def cast_packed_planes(packed, narrow: tuple, *, store: bool):
    """The packed twin of ``engine/core.py cast_state_planes``: cast
    the SHARED union slots named by ``narrow`` (``("clients/issued",
    "int16")``-style entries from :func:`hetero_narrow_spec`) to their
    storage dtype (``store=True``) or back to the i32 union dtype
    (``store=False``). Only shared slots are ever narrowed (private
    slots are per-audit storage the native spec already sized), and
    paths missing from the tree are skipped — result fetches carry
    only a sub-tree."""
    if not narrow:
        return packed
    shared = dict(packed["shared"])
    for path, dtname in narrow:
        sub = path.replace("/", ".")
        if sub in shared:
            shared[sub] = shared[sub].astype(
                dtname if store else jnp.int32
            )
    return dict(packed, shared=shared)


def hetero_narrow_spec(hb: HeteroBatch,
                       group_ctxs: Mapping[str, dict]) -> tuple:
    """The mixed batch's dtype-narrowing spec: the *intersection* of
    every group's own ``narrow_spec`` (a path every group proves
    narrowable under its own host-known budget), restricted to planes
    the skeleton stores in an i32 shared/castable union slot, at the
    *widest* storage dtype any group chose (each group's bound fits its
    own dtype, so the widest holds every group exactly). Deterministic
    (sorted by path) and hashable like the native spec."""
    per_group = {
        a: dict(narrow_spec(hb.protocols[a], cx))
        for a, cx in group_ctxs.items()
    }
    if not per_group:
        return ()
    paths = set.intersection(*[set(d) for d in per_group.values()])
    out = []
    for path in sorted(paths):
        ent = hb.skeleton.planes.get(
            "state." + path.replace("/", ".")
        )
        if ent is None or ent["verdict"] == PRIVATE:
            continue
        if ent["union"]["dtype"] != "int32":
            continue
        widest = max(
            (d[path] for d in per_group.values()),
            key=lambda dt: np.dtype(dt).itemsize,
        )
        out.append((path, widest))
    return tuple(out)


# ----------------------------------------------------------------------
# segment / window runners — the packed mirrors of engine/core.py's
# ----------------------------------------------------------------------

def hetero_segment_lane_fn(hb: HeteroBatch, max_steps: int = 1 << 22,
                           reorder: bool = False,
                           faults: FaultFlags = NO_FAULTS,
                           monitor_keys: int = 0, narrow: tuple = ()):
    """The packed per-lane bounded-segment function:
    ``run_lane(packed_st, packed_ctx, until) -> (packed_st, running)``
    with exactly ``segment_lane_fn``'s contract — while-loop over the
    switch step, narrow storage widened around the step, liveness from
    the shared views (never a narrowed plane) — so the batched runners
    below inherit the fixed-point property byte-for-byte."""
    _check_unmonitored(monitor_keys)
    step = hetero_switch_step(hb, reorder, faults, monitor_keys)
    views = _running_views(hb.skeleton, faults)
    # _lane_running never reads dims (liveness is engine-common); any
    # audit's dims satisfies its signature
    dims0 = hb.dims[hb.audits[0]]

    def running(packed_st, packed_cx):
        st, cx = views(packed_st, packed_cx)
        return _lane_running(dims0, st, cx, max_steps, faults)

    def run_lane(st, ctx, until):
        lim = jnp.minimum(until, max_steps)

        def body(s):
            wide = cast_packed_planes(s, narrow, store=False)
            out = step(wide, ctx)
            return cast_packed_planes(out, narrow, store=True)

        out = jax.lax.while_loop(
            lambda s: running(s, ctx)
            & (s["shared"]["steps"] < lim),
            body,
            st,
        )
        return out, running(out, ctx)

    return run_lane


def build_hetero_segment_runner(
    hb: HeteroBatch, max_steps: int = 1 << 22, reorder: bool = False,
    faults: FaultFlags = NO_FAULTS, monitor_keys: int = 0,
    narrow: tuple = (), donate: bool = False,
):
    """The packed mirror of ``build_segment_runner``: ``runner(state,
    ctx, until) -> (state, any_alive)`` plus a standalone ``alive``
    probe, vmapped over the mixed lane batch, one liveness flag riding
    home per call, ``donate=True`` consuming the input state exactly
    like the native runner (same GL302 lifetime discipline)."""
    run_lane = hetero_segment_lane_fn(
        hb, max_steps, reorder, faults, monitor_keys, narrow=narrow
    )
    views = _running_views(hb.skeleton, faults)
    dims0 = hb.dims[hb.audits[0]]

    def run_batch(st, ctx, until):
        out, alive = jax.vmap(run_lane, in_axes=(0, 0, None))(
            st, ctx, until
        )
        return out, jnp.any(alive)

    runner = jax.jit(
        run_batch, donate_argnums=(0,) if donate else ()
    )

    def lane_alive(s, c):
        sv, cv = views(s, c)
        return _lane_running(dims0, sv, cv, max_steps, faults)

    alive = jax.jit(
        lambda st, ctx: jnp.any(jax.vmap(lane_alive)(st, ctx))
    )
    return runner, alive


def hetero_window_batch_fn(
    hb: HeteroBatch, max_steps: int = 1 << 22, reorder: bool = False,
    faults: FaultFlags = NO_FAULTS, monitor_keys: int = 0,
    narrow: tuple = (),
):
    """The packed mirror of ``window_batch_fn``: a ``lax.scan`` over
    the batched segment step advancing the mixed batch through a whole
    ``[W]`` boundary ladder in one device call, liveness carried
    through the scan — safe for exactly the native reason (a finished
    batch's dead tail segments are byte-exact no-ops)."""
    run_lane = hetero_segment_lane_fn(
        hb, max_steps, reorder, faults, monitor_keys, narrow=narrow
    )

    def run_window(st, ctx, untils):
        def seg(carry, until):
            s, _alive = carry
            out, running = jax.vmap(run_lane, in_axes=(0, 0, None))(
                s, ctx, until
            )
            return (out, jnp.any(running)), ()

        (out, alive), _ = jax.lax.scan(
            seg, (st, jnp.asarray(True)), untils
        )
        return out, alive

    return run_window


def build_hetero_window_runner(
    hb: HeteroBatch, max_steps: int = 1 << 22, reorder: bool = False,
    faults: FaultFlags = NO_FAULTS, monitor_keys: int = 0,
    narrow: tuple = (), donate: bool = False,
):
    """The packed mirror of ``build_window_runner`` — the flavor
    ``parallel/aot.py`` serializes, so ONE artifact format serves every
    window size of a hetero campaign exactly as it does natively."""
    run_window = hetero_window_batch_fn(
        hb, max_steps, reorder, faults, monitor_keys, narrow=narrow
    )
    views = _running_views(hb.skeleton, faults)
    dims0 = hb.dims[hb.audits[0]]
    runner = jax.jit(
        run_window, donate_argnums=(0,) if donate else ()
    )

    def lane_alive(s, c):
        sv, cv = views(s, c)
        return _lane_running(dims0, sv, cv, max_steps, faults)

    alive = jax.jit(
        lambda st, ctx: jnp.any(jax.vmap(lane_alive)(st, ctx))
    )
    return runner, alive


# ----------------------------------------------------------------------
# host-side batch preparation
# ----------------------------------------------------------------------

@functools.lru_cache(maxsize=None)
def _cached_key_table(C: int, T: int):
    # hetero's own cache of the batched key-table builder (the engine
    # layer cannot import parallel/sweep.py's); same bit-identical
    # keygen contract either way
    return jax.jit(jax.vmap(key_table_fn(C, T)))


def _group_lanes(lane_specs) -> "Dict[str, list]":
    groups: Dict[str, list] = {}
    for i, item in enumerate(lane_specs):
        try:
            audit, spec = item
        except (TypeError, ValueError):
            raise HeteroBatchError(
                "hetero batches take (group, LaneSpec) pairs — got "
                f"{type(item).__name__} at lane {i}"
            ) from None
        groups.setdefault(str(audit), []).append((i, spec))
    return groups


def _keys_budget_T(group_ctxs: Mapping[str, dict]) -> int:
    """One key-table seq extent across the whole batch (bit-identical
    keys whatever T is, so a grid-wide T keeps shapes uniform)."""
    return int(
        max(
            [2]
            + [
                int(np.asarray(cx["cmd_budget"]).max()) + 2
                for cx in group_ctxs.values()
            ]
        )
    )


def _lane0_ctx(stacked: Mapping[str, np.ndarray]) -> dict:
    return {k: np.asarray(v)[0] for k, v in stacked.items()}


def _classify_specs(probes: Mapping[str, tuple]) -> Dict[str, dict]:
    out: Dict[str, dict] = {}
    for a in sorted(probes):
        st0, cx0 = probes[a]
        leaves = {
            **walk_planes(st0, "state"),
            **walk_planes(cx0, "ctx"),
        }
        out[a] = {
            n: (tuple(np.shape(v)), str(np.asarray(v).dtype))
            for n, v in leaves.items()
        }
    return out


def _group_key_tables(skeleton, audit, dims, ctx_a, T, table_on):
    """Attach the group's key table (at its native seq extent when a
    skeleton dictates one) and return the per-lane first-key rows —
    the same precompute-vs-in-loop contract as the native driver,
    bit-identical keys either way."""
    C = dims.C
    kctx = {k: ctx_a[k] for k in keygen_ctx_fields(ctx_a)}
    if table_on:
        T_a = T
        if skeleton is not None:
            nat = skeleton.planes["ctx.key_table"]["native"].get(audit)
            if nat is None:
                raise SkeletonMismatchError(
                    f"skeleton carries ctx.key_table but has no native "
                    f"spec for group {audit!r}"
                )
            T_a = int(nat["shape"][1])
        table = np.asarray(_cached_key_table(C, T_a)(kctx))
        ctx_a["key_table"] = table
        return table[:, :, 1]
    return np.asarray(_cached_key_table(C, 2)(kctx))[:, :, 1]


def prepare_batch(
    protocols: Mapping[str, Any],
    dims: Mapping[str, Any],
    lane_specs: Sequence[tuple],
    *,
    monitor_keys: int = 0,
    skeleton: "Skeleton | None" = None,
    key_table_limit: int = 1 << 24,
):
    """Host-side prep for one mixed batch. ``lane_specs`` is the
    (already padded) ordered ``[(group, LaneSpec), ...]`` list;
    ``protocols``/``dims`` map every group — and, when ``skeleton`` is
    given, every skeleton audit — to its device protocol and dims.

    Returns ``(hb, packed_state, packed_ctx, probes, nspec)``:
    the :class:`HeteroBatch`, the lane-stacked packed state/ctx numpy
    trees, one native ``(state, ctx)`` probe per group present in the
    batch (what the GL203 proof and the step signature trace over),
    and the batch's :func:`hetero_narrow_spec`.

    When ``skeleton`` is None it is derived from the batch itself —
    each group's lane-0 native trees classified across groups (the
    same classifier the GL601 ledger pins); the key-table decision
    then uses this batch's own total (``sum(lanes_g * C_g) * T`` vs
    ``key_table_limit``). When a skeleton IS given (the campaign path:
    one grid-wide skeleton for every unit), the key-table decision and
    per-group seq extents are read off the skeleton so every batch of
    the grid packs — and traces — identically."""
    _check_unmonitored(monitor_keys)
    groups = _group_lanes(lane_specs)
    order = sorted(groups)
    for a in order:
        if a not in protocols or a not in dims:
            raise HeteroBatchError(
                f"mixed batch names group {a!r} with no (protocol, "
                "dims) mapping entry"
            )
    if skeleton is not None:
        stray = sorted(set(order) - set(skeleton.audits))
        if stray:
            raise SkeletonMismatchError(
                f"batch carries groups {stray} outside the skeleton "
                f"grid {list(skeleton.audits)}"
            )

    gctx = {
        a: stack_lanes([s for _, s in groups[a]]) for a in order
    }
    T = _keys_budget_T(gctx)
    if skeleton is not None:
        table_on = "ctx.key_table" in skeleton.planes
    else:
        total = sum(len(groups[a]) * dims[a].C for a in order) * T
        table_on = total <= key_table_limit

    gstate: Dict[str, list] = {}
    for a in order:
        first = _group_key_tables(
            skeleton, a, dims[a], gctx[a], T, table_on
        )
        gstate[a] = [
            init_lane_state(
                protocols[a], dims[a], s.ctx, first_keys=first[j],
                monitor_keys=0,
            )
            for j, (_, s) in enumerate(groups[a])
        ]

    probes = {
        a: (gstate[a][0], _lane0_ctx(gctx[a])) for a in order
    }
    if skeleton is None:
        skeleton = build_skeleton(
            classify_planes(_classify_specs(probes)),
            audits=tuple(order),
        )
    hb = HeteroBatch(skeleton, protocols, dims)

    packed: List[tuple] = [None] * len(lane_specs)
    for a in order:
        ctx_a = gctx[a]
        for j, (i, _s) in enumerate(groups[a]):
            cx = {k: np.asarray(v)[j] for k, v in ctx_a.items()}
            packed[i] = (
                pack_state(skeleton, a, gstate[a][j]),
                pack_ctx(skeleton, a, cx),
            )
    state = jax.tree_util.tree_map(
        lambda *xs: np.stack(xs), *[p[0] for p in packed]
    )
    ctx = jax.tree_util.tree_map(
        lambda *xs: np.stack(xs), *[p[1] for p in packed]
    )
    nspec = hetero_narrow_spec(hb, gctx)
    return hb, state, ctx, probes, nspec


def build_grid_skeleton(
    protocols: Mapping[str, Any],
    dims: Mapping[str, Any],
    rep_specs: Mapping[str, Any],
    *,
    batch_lanes: int,
    key_table_limit: int = 1 << 24,
):
    """The campaign manager's skeleton builder: classify ONE
    representative lane per grid group into the grid-wide union, with
    the key-table decision taken at the campaign's real unit size
    (``batch_lanes``) so every unit of the grid packs through the same
    structure whatever its own composition. Returns ``(skeleton,
    nspec)`` — the grid-wide narrowing spec ships with the skeleton so
    every unit (and therefore the single AOT slot) narrows
    identically."""
    order = sorted(rep_specs)
    if not order:
        raise HeteroBatchError("a hetero grid needs at least one group")
    gctx = {a: stack_lanes([rep_specs[a]]) for a in order}
    T = _keys_budget_T(gctx)
    C_max = max(dims[a].C for a in order)
    table_on = batch_lanes * C_max * T <= key_table_limit
    probes: Dict[str, tuple] = {}
    for a in order:
        first = _group_key_tables(
            None, a, dims[a], gctx[a], T, table_on
        )
        st0 = init_lane_state(
            protocols[a], dims[a], rep_specs[a].ctx,
            first_keys=first[0], monitor_keys=0,
        )
        probes[a] = (st0, _lane0_ctx(gctx[a]))
    skeleton = build_skeleton(
        classify_planes(_classify_specs(probes)),
        audits=tuple(order),
    )
    hb = HeteroBatch(skeleton, protocols, dims)
    return skeleton, hetero_narrow_spec(hb, gctx)


# ----------------------------------------------------------------------
# signature — checkpoint staleness refusal + AOT slot identity
# ----------------------------------------------------------------------

def _zero_native_tree(skeleton: Skeleton, audit: str, prefix: str):
    leaves = {
        sub: np.zeros(tuple(nat["shape"]), dtype=nat["dtype"])
        for sub, ent in skeleton.slots(prefix)
        for a, nat in ent["native"].items()
        if a == audit
    }
    return unflatten_planes(leaves)


def hetero_step_signature(
    hb: HeteroBatch, probes: Mapping[str, tuple], *,
    reorder: bool, faults: FaultFlags, monitor_keys: int = 0,
) -> Dict[str, str]:
    """The hetero twin of ``engine/checkpoint.py step_signature``: one
    per-audit native signature for EVERY skeleton audit (absent groups
    trace over zero trees synthesized from the skeleton's native specs
    — ``make_jaxpr`` reads avals only, so the hash is identical to a
    probe-backed trace), folded with the skeleton fingerprint into one
    all-string dict the checkpoint loader and the AOT slot hash consume
    unchanged. Being composition-independent is the point: every unit
    of a hetero campaign — whatever lanes it happens to carry — shares
    one signature and therefore ONE serialized executable."""
    _check_unmonitored(monitor_keys)
    parts = {}
    for a in hb.audits:
        if a in probes:
            st0, cx0 = probes[a]
        else:
            st0 = _zero_native_tree(hb.skeleton, a, "state")
            cx0 = _zero_native_tree(hb.skeleton, a, "ctx")
        parts[a] = step_signature(
            hb.protocols[a], hb.dims[a], reorder=reorder,
            faults=faults, monitor_keys=monitor_keys, state=st0,
            ctx=cx0,
        )
    payload = {
        "skeleton": hb.fingerprint,
        "audits": {
            a: {
                "protocol": parts[a]["protocol"],
                "dims": parts[a]["dims"],
                "step_jaxpr_sha256": parts[a]["step_jaxpr_sha256"],
            }
            for a in hb.audits
        },
    }
    return {
        "kind": HETERO_KIND,
        "protocol": "hetero[" + "+".join(hb.audits) + "]",
        "dims": "+".join(
            f"{a}={parts[a]['dims']}" for a in hb.audits
        ),
        "skeleton": hb.fingerprint,
        "jax": jax.__version__,
        "reorder": repr(bool(reorder)),
        "faults": repr(faults),
        "monitor_keys": repr(int(monitor_keys)),
        "step_jaxpr_sha256": hashlib.sha256(
            canonical_json(payload).encode()
        ).hexdigest(),
    }


# ----------------------------------------------------------------------
# result fetch + collection — inverting the packing at the seam the
# native driver fetches (same GL301-audited host_fetch site)
# ----------------------------------------------------------------------

#: the engine-common state planes result collection reads (the packed
#: twin of the native driver's fetch dict), dotted sub-names
_RESULT_SUBS = (
    "clients.completed",
    "done_time",
    "err",
    "fault_dropped",
    "metrics.hist",
    "metrics.lat_count",
    "metrics.lat_sum",
    "pool_peak",
    "requeues",
    "steps",
)


def _result_subs(skeleton: Skeleton, audit: str) -> List[str]:
    subs = list(_RESULT_SUBS)
    for sub, ent in skeleton.slots("state"):
        if sub.startswith("ps.m_") and audit in ent["native"]:
            subs.append(sub)
    return subs


def result_fetch_tree(hb: HeteroBatch, state) -> dict:
    """The device-side sub-tree one ``host_fetch`` brings home for
    result collection: every audit's needed shared slots plus its
    private ``ps.m_*`` metric slots — the packed mirror of the native
    driver's ~10-plane fetch dict (never the full ~100 MB state)."""
    shared: Dict[str, Any] = {}
    priv: Dict[str, Dict[str, Any]] = {a: {} for a in hb.audits}
    for a in hb.audits:
        for sub in _result_subs(hb.skeleton, a):
            ent = hb.skeleton.planes.get("state." + sub)
            if ent is None or a not in ent["native"]:
                continue
            if ent["verdict"] == PRIVATE:
                priv[a][sub] = state["priv"][a][sub]
            else:
                shared[sub] = state["shared"][sub]
    return {"shared": shared, "priv": priv}


def collect_hetero_results(
    hb: HeteroBatch, lane_specs: Sequence[tuple], fetched,
    max_steps: int, narrow: tuple = (),
):
    """Invert the packing on the fetched result sub-tree and run each
    group's lanes through the unchanged native ``collect_results`` —
    slicing shared slots back to native extents, casting storage back
    to native dtypes (both exact, the GL604-pinned round-trip), and
    applying ``finish_segmented`` per group exactly where the native
    driver applies it. Lane order is the caller's."""
    from .results import collect_results

    fetched = cast_packed_planes(fetched, narrow, store=False)
    out: List[Any] = [None] * len(lane_specs)
    groups = _group_lanes(lane_specs)
    for a in sorted(groups):
        items = groups[a]
        idx = np.asarray([i for i, _ in items])
        leaves: Dict[str, np.ndarray] = {}
        for sub in _result_subs(hb.skeleton, a):
            ent = hb.skeleton.planes.get("state." + sub)
            if ent is None or a not in ent["native"]:
                continue
            nat = ent["native"][a]
            if ent["verdict"] == PRIVATE:
                arr = np.asarray(fetched["priv"][a][sub])[idx]
            else:
                arr = np.asarray(fetched["shared"][sub])[idx]
                arr = arr[
                    (slice(None),)
                    + tuple(slice(0, d) for d in nat["shape"])
                ]
            leaves[sub] = arr.astype(nat["dtype"])
        tree = finish_segmented(
            unflatten_planes(leaves), max_steps
        )
        res = collect_results(
            hb.protocols[a], hb.dims[a], tree, [s for _, s in items]
        )
        for (i, _), r in zip(items, res):
            out[i] = r
    return out

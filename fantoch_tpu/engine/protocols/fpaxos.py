"""Device twin of FPaxos (fantoch_ps/src/protocol/fpaxos.rs, host
oracle: fantoch_tpu/protocol/fpaxos.py).

Semantics: submits at non-leaders forward to the leader; the leader
assigns the next slot and sends ``MAccept`` to the f+1 write quorum;
on f+1 ``MAccepted`` the slot is chosen and broadcast; every process
executes slots in order (SlotExecutor) and the process a client is
attached to reports the result back. Stable slots are GC'd via
committed-frontier exchange (synod/gc.rs).

Device encoding notes:
- the reference's ``MSpawnCommander`` self-forward is worker routing
  (fpaxos.rs:198-238); on device the leader's submit handler spawns the
  commander directly — same messages on the wire;
- ballots never change (recovery is out of scope in the reference too),
  so the acceptor's ``b >= ballot`` check always passes and ballots are
  omitted from payloads;
- with constant per-pair delays the engine delivers the leader's
  ``MChosen`` stream in slot order, so the SlotExecutor's buffer
  degenerates to a frontier counter; an out-of-order arrival trips the
  lane error flag rather than silently reordering execution;
- slots live in a window of D recycled entries, freed by GC, with
  dirty-slot checks surfacing window overflow.
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np

from ..core import (
    I32, emit, emit_broadcast, empty_outbox, oh_get, oh_set, pack_outbox,
)
from ..dims import ERR_DOT, ERR_PROTO, INF, EngineDims, dot_slot
from ..monitor import mon_exec
from .identity import DevIdentity


class FPaxosDev(DevIdentity):
    SUBMIT = 0
    MFORWARD = 1
    MACCEPT = 2
    MACCEPTED = 3
    MCHOSEN = 4
    MGC = 5
    NUM_TYPES = 6
    TO_CLIENT = 7

    PERIODIC_ROWS = 1  # garbage collection
    MONITORED = True  # mon_exec hook at the slot executor's frontier
    # per-command counter the sweep driver may store narrowed
    # (engine/spec.py narrow_spec): m_stable counts slots GC'd, at most
    # once per command per process — a lane's total command budget
    # bounds every entry
    NARROW_METRICS = ("m_stable",)

    # -- host-side builders -------------------------------------------

    @staticmethod
    def payload_width(n: int) -> int:
        return 3  # [slot, client, key]

    @staticmethod
    def periodic_intervals(config, dims: EngineDims):
        gc = config.gc_interval_ms
        return [gc if gc is not None else INF]

    @staticmethod
    def min_live(config) -> int:
        """f+1 write-quorum members (the leader included). A crashed
        *leader* is not unavailability — it halts every client instead
        (no election is modeled; engine/faults.py)."""
        return config.fpaxos_quorum_size()

    @staticmethod
    def lane_ctx(config, dims: EngineDims, sorted_idx: np.ndarray):
        """Write quorum = first f+1 processes in the leader's discovery
        order (fpaxos_quorum_size, config.rs:270-272)."""
        assert config.leader is not None, "FPaxos needs an initial leader"
        N = dims.N
        leader = config.leader - 1  # ids are 1-based, device is 0-based
        q = config.fpaxos_quorum_size()
        wq = np.zeros((N,), bool)
        for member in sorted_idx[leader][:q]:
            wq[member] = True
        return {
            "leader": np.int32(leader),
            "write_quorum": wq,
            "q_size": np.int32(q),
        }

    @staticmethod
    def init_state(dims: EngineDims, ctx_np) -> Dict[str, np.ndarray]:
        N, D = dims.N, dims.D
        return {
            # leader role: commander window. cmd_slot holds the slot an
            # in-flight commander owns (0 = free) — occupancy is tracked
            # explicitly because acc_count == 0 cannot distinguish a free
            # entry from a commander that has not heard any MAccepted yet
            "last_slot": np.zeros((N,), np.int32),
            "cmd_slot": np.zeros((N, D), np.int32),
            "acc_count": np.zeros((N, D), np.int32),
            # acceptor role: window entry → accepted slot (0 = free)
            "acc_slot": np.zeros((N, D), np.int32),
            # executor frontier: next slot to execute is exec_frontier+1
            "exec_frontier": np.zeros((N,), np.int32),
            # GC (SynodGCTrack): committed frontier per other process
            "others_committed": np.zeros((N, N), np.int32),
            "seen": np.zeros((N, N), bool),
            "m_stable": np.zeros((N,), np.int32),
            "err": np.zeros((N,), np.int32),
        }

    @staticmethod
    def error(ps):
        return ps["err"]

    @staticmethod
    def metrics(ps_np) -> Dict[str, np.ndarray]:
        return {"stable": ps_np["m_stable"]}

    # -- device handlers ----------------------------------------------

    @staticmethod
    def ready(ps, msg, me, ctx, dims: EngineDims):
        """Readiness gate: MAccept needs a free acceptor window slot,
        MChosen executes in slot order (the reference's SlotExecutor
        buffers out-of-order slots, executor/slot.rs:17-69)."""
        t = msg["mtype"]
        slot = msg["payload"][0]
        idx = dot_slot(slot, dims)
        ok = jnp.where(
            t == FPaxosDev.MACCEPT, oh_get(ps["acc_slot"], idx) == 0, True
        )
        return jnp.where(
            t == FPaxosDev.MCHOSEN, slot == ps["exec_frontier"] + 1, ok
        )

    @staticmethod
    def handle(ps, msg, me, now, ctx, dims: EngineDims):
        def _noop(ps, msg):
            return ps, empty_outbox(dims)

        branches = [
            lambda ps, msg: _submit(ps, msg, me, ctx, dims),
            lambda ps, msg: _submit(ps, msg, me, ctx, dims),  # MFORWARD
            lambda ps, msg: _maccept(ps, msg, me, ctx, dims),
            lambda ps, msg: _maccepted(ps, msg, me, ctx, dims),
            lambda ps, msg: _mchosen(ps, msg, me, ctx, dims),
            lambda ps, msg: _mgc(ps, msg, me, ctx, dims),
            _noop,
        ]
        idx = jnp.clip(msg["mtype"], 0, FPaxosDev.NUM_TYPES)
        return jax.lax.switch(idx, branches, ps, msg)

    @staticmethod
    def periodic(ps, fire, me, now, ctx, dims: EngineDims):
        """Broadcast my committed frontier (== executed frontier, since
        slots are chosen in order) to all-but-me (fpaxos.rs:343-357)."""
        ob = emit_broadcast(
            empty_outbox(dims),
            FPaxosDev.MGC,
            [ps["exec_frontier"], 0, 0],
            ctx["n"],
            me,
            exclude_me=True,
        )
        ob = dict(ob, valid=ob["valid"] & fire[0])
        return ps, ob



def _submit(ps, msg, me, ctx, dims):
    """SUBMIT/MFORWARD: non-leader forwards to the leader; the leader
    assigns the next slot, spawns the commander, and sends MAccept to
    the write quorum (fpaxos.rs:165-238)."""
    client = msg["payload"][0]
    key = msg["payload"][2]
    is_leader = me == ctx["leader"]
    do = msg["valid"] & is_leader

    slot = ps["last_slot"] + 1
    idx = dot_slot(slot, dims)
    dirty = oh_get(ps["cmd_slot"], idx) != 0
    ps = dict(
        ps,
        err=ps["err"] | ERR_DOT * (do & dirty),
        last_slot=jnp.where(do, slot, ps["last_slot"]),
        cmd_slot=oh_set(ps["cmd_slot"], jnp.where(do, idx, dims.D), slot),
        acc_count=oh_set(ps["acc_count"], jnp.where(do, idx, dims.D), 0),
    )

    # outbox: slot 0 = forward-to-leader, slots 1..N = MAccept broadcast
    # masked to the write quorum (F >= N + 1)
    F, N, P = dims.F, dims.N, dims.P
    procs = jnp.arange(N, dtype=I32)
    valid = jnp.zeros((F,), bool)
    dst = jnp.zeros((F,), I32)
    mtype = jnp.zeros((F,), I32)
    payload = jnp.zeros((F, P), I32)

    valid = valid.at[0].set(msg["valid"] & ~is_leader)
    dst = dst.at[0].set(ctx["leader"])
    mtype = mtype.at[0].set(FPaxosDev.MFORWARD)
    # MFORWARD is re-handled by _submit, which reads the SUBMIT payload
    # layout [client, cmd_seq, key]
    payload = payload.at[0, 0].set(client)
    payload = payload.at[0, 2].set(key)

    valid = valid.at[1 : N + 1].set(
        do & ctx["write_quorum"] & (procs < ctx["n"])
    )
    dst = dst.at[1 : N + 1].set(procs)
    mtype = mtype.at[1 : N + 1].set(FPaxosDev.MACCEPT)
    payload = payload.at[1 : N + 1, 0].set(slot)
    payload = payload.at[1 : N + 1, 1].set(client)
    payload = payload.at[1 : N + 1, 2].set(key)

    return ps, pack_outbox(valid, dst, mtype, payload)


def _maccept(ps, msg, me, ctx, dims):
    """Acceptor stores the slot and replies MAccepted to the leader
    (fpaxos.rs:240-262)."""
    slot, client = msg["payload"][0], msg["payload"][1]
    idx = dot_slot(slot, dims)
    dirty = oh_get(ps["acc_slot"], idx) != 0
    ps = dict(
        ps,
        err=ps["err"] | ERR_DOT * dirty,
        acc_slot=oh_set(ps["acc_slot"], idx, slot),
    )
    ob = emit(
        empty_outbox(dims),
        0,
        msg["src"],
        FPaxosDev.MACCEPTED,
        [slot, client, 0],
    )
    return ps, ob


def _maccepted(ps, msg, me, ctx, dims):
    """Commander counts accepts; on exactly f+1 the slot is chosen and
    broadcast to all (fpaxos.rs:264-315)."""
    slot, client = msg["payload"][0], msg["payload"][1]
    idx = dot_slot(slot, dims)
    # a stale MAccepted for a retired commander (slot mismatch) is a
    # protocol error, not a silent merge into the new occupant's count
    stale = oh_get(ps["cmd_slot"], idx) != slot
    cnt = oh_get(ps["acc_count"], idx) + 1
    chosen = ~stale & (cnt == ctx["q_size"])
    # the commander is retired once the slot is chosen (commanders.pop),
    # freeing the window entry for reuse
    ps = dict(
        ps,
        err=ps["err"] | ERR_PROTO * stale,
        acc_count=oh_set(ps["acc_count"], idx, jnp.where(chosen, 0, cnt)),
        cmd_slot=oh_set(
            ps["cmd_slot"], idx,
            jnp.where(chosen, 0, oh_get(ps["cmd_slot"], idx)),
        ),
    )
    ob = emit_broadcast(
        empty_outbox(dims),
        FPaxosDev.MCHOSEN,
        [slot, client, 0],
        ctx["n"],
    )
    ob = dict(ob, valid=ob["valid"] & chosen)
    return ps, ob


def _mchosen(ps, msg, me, ctx, dims):
    """SlotExecutor: with FIFO delivery the chosen stream arrives in
    slot order, so execution is a frontier bump; the client's attached
    process reports the result (executor/slot.rs:17-69)."""
    slot, client = msg["payload"][0], msg["payload"][1]
    in_order = slot == ps["exec_frontier"] + 1
    # safety monitor (engine/monitor.py; the ``if`` is a trace-time
    # gate). FPaxos executes ONE total order — every process applies
    # every slot in slot order — so all executions hash into monitor
    # key 0: equal counts mean the same slot prefix, and any stream
    # divergence diverges the rolling hash. Commands are identified by
    # slot (src 0); the per-key split other protocols need carries no
    # extra information here.
    if "_mon_hash" in ps:
        ps = mon_exec(ps, 0, 0, slot, in_order)
    ps = dict(
        ps,
        err=ps["err"] | ERR_PROTO * ~in_order,
        exec_frontier=ps["exec_frontier"] + in_order.astype(I32),
    )
    mine = oh_get(ctx["client_attach"], client) == me
    ob = emit(
        empty_outbox(dims),
        0,
        dims.N + client,
        FPaxosDev.TO_CLIENT,
        [slot],
        valid=in_order & mine,
    )
    return ps, ob


def _mgc(ps, msg, me, ctx, dims):
    """SynodGCTrack: stable slot = min committed frontier across all
    processes; free acceptor window entries up to it, counting only the
    slots this process actually accepted (synod/gc.rs, acceptor.gc)."""
    s = msg["src"]
    committed = msg["payload"][0]
    oc = oh_set(
        ps["others_committed"],
        s,
        jnp.maximum(oh_get(ps["others_committed"], s), committed),
    )
    seen = oh_set(ps["seen"], s, True)
    procs = jnp.arange(dims.N, dtype=I32)
    others = (procs < ctx["n"]) & (procs != me)
    ready = jnp.all(seen | ~others)
    min_others = jnp.min(jnp.where(others, oc, INF))
    stable = jnp.minimum(ps["exec_frontier"], min_others)
    stable = jnp.where(ready, stable, 0)
    freed = (ps["acc_slot"] > 0) & (ps["acc_slot"] <= stable)
    ps = dict(
        ps,
        others_committed=oc,
        seen=seen,
        m_stable=ps["m_stable"] + jnp.sum(freed),
        acc_slot=jnp.where(freed, 0, ps["acc_slot"]),
    )
    return ps, empty_outbox(dims)

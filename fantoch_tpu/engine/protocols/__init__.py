"""Array-state protocol implementations for the device engine.

Each module is the fixed-shape twin of a host oracle protocol in
``fantoch_tpu/protocol/``: per-process state becomes a dict of i32/bool
arrays, ``handle`` becomes a ``lax.switch`` over message types, and
quorum membership / discovery orders arrive as precomputed lane-context
matrices.
"""

from .basic import BasicDev
from .caesar import CaesarDev
from .fpaxos import FPaxosDev
from .graphdep import AtlasDev, EPaxosDev
from .graphdep_partial import AtlasPartialDev
from .tempo import TempoDev
from .tempo_partial import TempoPartialDev

# the canonical name lists live in the jax-free fantoch_tpu.registry
# (the CLI imports them before jax may initialize); re-exported here so
# engine-side consumers find them next to the constructors they mirror
from ...registry import DEV_PROTOCOLS, PARTIAL_DEV_PROTOCOLS

__all__ = [
    "AtlasDev",
    "AtlasPartialDev",
    "BasicDev",
    "CaesarDev",
    "DEV_PROTOCOLS",
    "EPaxosDev",
    "FPaxosDev",
    "PARTIAL_DEV_PROTOCOLS",
    "TempoDev",
    "TempoPartialDev",
    "dev_protocol",
    "dev_config_kwargs",
    "partial_dev_protocol",
]


def dev_protocol(name: str, clients: int, keys: "int | None" = None):
    """The one protocol-name → device-protocol switch (bench, graft
    entry, sweep tools and the CLI all construct through here so a new
    protocol or capacity knob is one edit)."""
    keys = keys if keys is not None else 1 + clients
    if name == "tempo":
        return TempoDev.for_load(keys=keys, clients=clients)
    if name == "basic":
        return BasicDev
    if name == "fpaxos":
        return FPaxosDev
    if name == "atlas":
        return AtlasDev(keys=keys)
    if name == "epaxos":
        return EPaxosDev(keys=keys)
    if name == "caesar":
        return CaesarDev.for_load(keys=keys, clients=clients)
    raise ValueError(f"unknown protocol {name!r}")


def partial_dev_protocol(name: str, clients: int, shards: int,
                         keys_per_cmd: int = 2, pool_size: int = 1):
    """The partial-replication twin switch — only the protocols whose
    reference implements partial.rs have one (Tempo, Atlas); anything
    else raises ValueError with the reason."""
    keys = pool_size + clients + 1
    if name == "tempo":
        return TempoPartialDev(
            keys=keys, shards=shards, keys_per_cmd=keys_per_cmd
        )
    if name == "atlas":
        return AtlasPartialDev(
            keys=keys, shards=shards, keys_per_cmd=keys_per_cmd
        )
    raise ValueError(
        f"{name} does not support partial replication (only tempo and "
        "atlas implement the reference's partial.rs paths)"
    )


def dev_config_kwargs(name: str, n: int, f: int, **overrides):
    """Default Config kwargs per protocol (leader for FPaxos, wait
    condition for Caesar, detached sends for Tempo); ``overrides``
    win."""
    kw = dict(n=n, f=f, gc_interval_ms=100)
    if name == "tempo":
        kw["tempo_detached_send_interval_ms"] = 100
    if name == "fpaxos":
        kw["leader"] = 1
    if name == "caesar":
        kw["caesar_wait_condition"] = True
    kw.update(overrides)
    return kw

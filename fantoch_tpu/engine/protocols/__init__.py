"""Array-state protocol implementations for the device engine.

Each module is the fixed-shape twin of a host oracle protocol in
``fantoch_tpu/protocol/``: per-process state becomes a dict of i32/bool
arrays, ``handle`` becomes a ``lax.switch`` over message types, and
quorum membership / discovery orders arrive as precomputed lane-context
matrices.
"""

from .basic import BasicDev
from .caesar import CaesarDev
from .fpaxos import FPaxosDev
from .graphdep import AtlasDev, EPaxosDev
from .tempo import TempoDev

__all__ = [
    "AtlasDev",
    "BasicDev",
    "CaesarDev",
    "EPaxosDev",
    "FPaxosDev",
    "TempoDev",
]

"""Value identity for device-protocol objects.

Protocol instances parameterize compiled engine runners, and sweep
drivers cache those runners keyed on the protocol (parallel/sweep.py).
Device protocols are pure behaviour + a handful of integer shape bounds
set in ``__init__``, so two instances of the same class with equal
attributes are interchangeable — give them value semantics so a driver
constructing a fresh instance per call still hits the compile cache
instead of pinning one executable per instance.
"""

from __future__ import annotations


class DevIdentity:
    def __eq__(self, other) -> bool:
        return type(other) is type(self) and vars(other) == vars(self)

    def __hash__(self) -> int:
        return hash((type(self),) + tuple(sorted(vars(self).items())))

"""Device twin of Caesar (fantoch_ps/src/protocol/caesar.rs, host
oracle: fantoch_tpu/protocol/caesar.py) — timestamp + dependency
consensus with the wait condition.

Flow: the coordinator proposes a logical clock and broadcasts MPropose
to everyone — the fastest ⌊3n/4⌋+1 repliers form the (dynamic) fast
quorum (caesar.rs:245-264). Every receiver computes the command's
predecessors (lower-clock conflicts) and blockers (higher-clock
conflicts); with blockers present the *wait condition* holds the reply
until each blocker reaches a safe clock — accepting if this command
appears in the blocker's deps, rejecting otherwise (caesar.rs:932-1096).
All-ok replies commit on the fast path; any rejection once a majority
replied triggers an MRetry round through the write quorum whose acks
aggregate a final dep set (560-822). Execution is the two-phase
predecessors executor: a command executes once every dep is committed
and every lower-clock dep is executed — commands execute in clock order
(executor/pred/mod.rs:104-339). GC frees a command once all n processes
report it executed (BasicGCTrack + periodic MGarbageCollection).

Device-design notes (equivalences relied on):
- The oracle unblocks waiting commands incrementally via back-pointer
  lists (info.blocking / try_to_unblock_again). The device instead
  *re-evaluates* every waiting command's blockers after each
  MCommit/MRetry, which is equivalent because ignore-ability is
  monotone: once a blocker is safe with this command in its deps, its
  committed deps can only be a superset of its retry deps (MCommit deps
  aggregate every MRetryAck, each of which includes the MRetry's deps),
  and a fully GC'd blocker was executed everywhere, so its accept/
  reject decision already fired at its own commit instant.
- Phase-two readiness ("every lower-clock dep executed") needs no fixed
  point: the lower-clock relation is acyclic, so executing one ready
  command per zero-delay drain step reaches the same set the oracle's
  pending-index cascade does, in clock order, at the same instant.
- Rejected proposals include the command's own old-clock entry in the
  recomputed deps, exactly like the oracle (predecessors at the new
  clock sees the old registration); the commit handler discards
  self-deps (caesar.rs:665-668).

Array encoding (per process): per-key clock tables ``kc_*[K, S]``
((dot, clock) registrations; predecessors/blockers are masked compares
over the row), per-dot lifecycle arrays (status, clock, deps, blockers),
dynamic-quorum aggregation tables, committed/executed interval sets per
source, and the executed→notify→broadcast GC buffers.
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np

from ..core import (
    I32, compact_order, emit, emit_broadcast, empty_outbox, oh_get,
    oh_match, oh_pack_pairs, oh_set, oh_set2, oh_take,
)
from ..dims import ERR_CAPACITY, ERR_DOT, ERR_PROTO, ERR_SEQ, INF, SEQ_BOUND, EngineDims, dot_slot
from .identity import DevIdentity
from ..iset import iset_add, iset_contains_gathered
from ..monitor import mon_exec


# statuses (caesar.rs Status; PROPOSE_BEGIN is transient host-side only)
ST_START = 0
ST_PROPOSE_END = 2
ST_REJECT = 3
ST_ACCEPT = 4
ST_COMMIT = 5
ST_EXECUTED = 6


class CaesarDev(DevIdentity):
    SUBMIT = 0
    MPROPOSE = 1
    MPROPOSEACK = 2
    MCOMMIT = 3
    MRETRY = 4
    MRETRYACK = 5
    MGC = 6
    WAIT_DRAIN = 7
    EXEC_DRAIN = 8
    GC_DRAIN = 9
    NUM_TYPES = 10
    TO_CLIENT = 11

    PERIODIC_ROWS = 2  # [garbage collection, executed notification]
    MONITORED = True  # mon_exec hook at the predecessors-executor scan
    # per-command counters the sweep driver may store narrowed
    # (engine/spec.py narrow_spec): m_fast/m_slow increment once per
    # command at its coordinator's commit decision, m_stable once per
    # command per process when it leaves the exec scan fully executed —
    # a lane's total command budget bounds every entry
    NARROW_METRICS = ("m_fast", "m_slow", "m_stable")

    def __init__(
        self,
        keys: int,
        key_slots: int = 32,
        # dep unions aggregate several acks' predecessor lists computed at
        # different instants, so they can exceed the key-row population;
        # GC rounds lag executions by up to one interval (oracle event
        # order), keeping registrations visible longer. DEP multiplies
        # the payload width and every per-step dep tensor (the executor
        # scan is the step's dominant cost) — size through for_load for
        # real workloads; overflow is always loud
        dep_slots: int = 64,
        blocker_slots: int = 16,
        gap_slots: int = 8,
        exec_buffer: int = 128,
    ):
        self.K = keys
        self.S = key_slots       # (dot, clock) registrations per key
        self.DEP = dep_slots     # deps per dot / per message
        self.BB = blocker_slots  # blockers per waiting dot
        self.G = gap_slots
        self.EB = exec_buffer    # executed-dot buffers (notify + GC)

    @classmethod
    def for_load(cls, keys: int, clients: int) -> "CaesarDev":
        """Capacity bounds scaled to the client count. Dep lists grow
        with the concurrently conflicting registrations, which at 100%
        conflict and long command budgets approach the key row (S=32)
        plus union extras: a 32-slot DEP measured ERR_CAPACITY on the
        bench's conflict-100 lanes at 50 commands/client, so the floor
        stays 64 and scales at 8x clients beyond 8 clients; blockers
        (higher-clock conflicts) track at a quarter. Overflow stays
        loud (ERR_CAPACITY), never silent."""
        dep = max(64, 8 * clients)
        return cls(
            keys=keys, dep_slots=dep, blocker_slots=max(16, dep // 4)
        )

    # -- host-side builders -------------------------------------------

    def payload_width(self, n: int) -> int:
        # MCOMMIT/MRETRY: [dsrc, dseq, cseq, cpid, nd] + (src, seq)*DEP
        return max(5 + 2 * self.DEP, n)

    def gc_per_msg(self, dims: EngineDims) -> int:
        return (dims.P - 1) // 2

    def periodic_intervals(self, config, dims: EngineDims):
        gc = config.gc_interval_ms
        return [
            gc if gc is not None else INF,
            config.executor_executed_notification_interval_ms,
        ]

    @staticmethod
    def min_live(config) -> int:
        """Caesar's quorums are dynamic (the fastest repliers), but a
        proposal still needs ⌊3n/4⌋+1 replies and a retry ⌊n/2⌋+1 —
        fewer survivors than that cannot commit (engine/faults.py
        flags such crash plans ERR_UNAVAIL)."""
        fq_size, wq_size = config.caesar_quorum_sizes()
        return max(fq_size, wq_size)

    def lane_ctx(self, config, dims: EngineDims, sorted_idx: np.ndarray):
        fq_size, wq_size = config.caesar_quorum_sizes()
        return {
            "fq_size": np.int32(fq_size),
            "wq_size": np.int32(wq_size),
            "wait_condition": np.bool_(config.caesar_wait_condition),
        }

    def init_state(self, dims: EngineDims, ctx_np) -> Dict[str, np.ndarray]:
        N, D = dims.N, dims.D
        K, S, DEP, BB, G, EB = (
            self.K, self.S, self.DEP, self.BB, self.G, self.EB,
        )
        return {
            # per-key clock table (clocks/keys/locked.rs): registered
            # (dot, clock) pairs; kc_cseq == 0 marks a free slot
            "kc_src": np.zeros((N, K, S), np.int32),
            "kc_seq": np.zeros((N, K, S), np.int32),
            "kc_cseq": np.zeros((N, K, S), np.int32),
            "kc_cpid": np.zeros((N, K, S), np.int32),
            "clk_counter": np.zeros((N,), np.int32),
            # per-dot lifecycle
            "pseq": np.zeros((N, N, D), np.int32),
            "status": np.zeros((N, N, D), np.int32),
            "key_of": np.zeros((N, N, D), np.int32),
            "client_of": np.zeros((N, N, D), np.int32),
            "clk_seq": np.zeros((N, N, D), np.int32),
            "clk_pid": np.zeros((N, N, D), np.int32),
            "dep_src": np.zeros((N, N, D, DEP), np.int32),
            "dep_seq": np.zeros((N, N, D, DEP), np.int32),
            "bb_src": np.zeros((N, N, D, BB), np.int32),
            "bb_seq": np.zeros((N, N, D, BB), np.int32),
            # coordinator aggregation (QuorumClocks / QuorumRetries)
            "own_seq": np.zeros((N,), np.int32),
            "qa_cnt": np.zeros((N, D), np.int32),
            "qa_ok": np.ones((N, D), bool),
            "qa_done": np.zeros((N, D), bool),
            "qa_cseq": np.zeros((N, D), np.int32),
            "qa_cpid": np.zeros((N, D), np.int32),
            "ag_src": np.zeros((N, D, DEP), np.int32),
            "ag_seq": np.zeros((N, D, DEP), np.int32),
            "qr_cnt": np.zeros((N, D), np.int32),
            # executor clock (executed per source; commit-ness of live
            # dots rides their status, and of dead dots this set — see
            # _exec_scan — so no committed set is needed)
            "ex_front": np.zeros((N, N), np.int32),
            "ex_gaps": np.zeros((N, N, G, 2), np.int32),
            # executed→notification buffer (executor.rs:65-77) and the
            # notification→MGC broadcast buffer (caesar.rs:194-213)
            "eb_src": np.zeros((N, EB), np.int32),
            "eb_seq": np.zeros((N, EB), np.int32),
            "eb_n": np.zeros((N,), np.int32),
            "gb_src": np.zeros((N, EB), np.int32),
            "gb_seq": np.zeros((N, EB), np.int32),
            "gb_n": np.zeros((N,), np.int32),
            # dots eligible for the in-flight GC round (snapshot of gb_n
            # at the GC tick, before any same-instant notification drain)
            "gb_gc": np.zeros((N,), np.int32),
            # BasicGCTrack: executed-at count per dot
            "gc_cnt": np.zeros((N, N, D), np.int32),
            "m_fast": np.zeros((N,), np.int32),
            "m_slow": np.zeros((N,), np.int32),
            "m_stable": np.zeros((N,), np.int32),
            "err": np.zeros((N,), np.int32),
        }

    @staticmethod
    def error(ps):
        return ps["err"]

    @staticmethod
    def metrics(ps_np) -> Dict[str, np.ndarray]:
        return {
            "fast_path": ps_np["m_fast"],
            "slow_path": ps_np["m_slow"],
            "stable": ps_np["m_stable"],
        }

    # -- device handlers ----------------------------------------------

    def ready(self, ps, msg, me, ctx, dims: EngineDims):
        """Readiness gate: MPropose needs a free dot slot; MCommit and
        MRetry need the MPropose payload; MGC counts only dots whose
        MPropose has arrived (requeued whole otherwise so sightings are
        never double-counted)."""
        t = msg["mtype"]
        prop_ok = (
            oh_get(oh_get(ps["pseq"], msg["src"]),
                   dot_slot(msg["payload"][0], dims))
            == 0
        )
        dsrc, seq = msg["payload"][0], msg["payload"][1]
        have = oh_get(oh_get(ps["pseq"], dsrc), dot_slot(seq, dims)) == seq
        DPM = self.gc_per_msg(dims)
        idx = jnp.arange(DPM, dtype=I32)
        gsrc = oh_take(msg["payload"], 1 + 2 * idx)
        gseq = oh_take(msg["payload"], 2 + 2 * idx)
        en = idx < msg["payload"][0]
        gc_ok = jnp.all(
            ~en | (ps["pseq"][gsrc, dot_slot(gseq, dims)] == gseq)
        )
        ok = jnp.where(t == CaesarDev.MPROPOSE, prop_ok, True)
        ok = jnp.where(
            (t == CaesarDev.MCOMMIT) | (t == CaesarDev.MRETRY), have, ok
        )
        return jnp.where(t == CaesarDev.MGC, gc_ok, ok)

    # the hoisted scans (see handle) need 4 outbox slots beyond the
    # n+1 a branch itself may fill (gc_drain broadcasts + chains)
    EXTRA_SLOTS = 4

    def handle(self, ps, msg, me, now, ctx, dims: EngineDims):
        def _noop(ps, msg):
            return ps, empty_outbox(dims), _off(), _off()

        branches = [
            lambda ps, msg: _submit(self, ps, msg, me, ctx, dims),
            lambda ps, msg: _mpropose(self, ps, msg, me, ctx, dims),
            lambda ps, msg: _mproposeack(self, ps, msg, me, ctx, dims),
            lambda ps, msg: _mcommit(self, ps, msg, me, ctx, dims),
            lambda ps, msg: _mretry(self, ps, msg, me, ctx, dims),
            lambda ps, msg: _mretryack(self, ps, msg, me, ctx, dims),
            lambda ps, msg: _mgc(self, ps, msg, me, ctx, dims),
            lambda ps, msg: _wait_drain(self, ps, msg, me, ctx, dims),
            lambda ps, msg: _exec_drain(self, ps, msg, me, ctx, dims),
            lambda ps, msg: _gc_drain(self, ps, msg, me, ctx, dims),
            _noop,
        ]
        idx = jnp.clip(msg["mtype"], 0, CaesarDev.NUM_TYPES)
        ps, ob, do_exec, do_wait = jax.lax.switch(idx, branches, ps, msg)
        # The executor drain and the wait-condition re-evaluation are
        # by far the heaviest subgraphs (gathering [N, D, BB, DEP]
        # views of the dep state). Under vmap the switch lowers to a
        # select that executes EVERY branch each step, so these must
        # exist ONCE per step — hoisted here behind enable flags the
        # branches set — not inlined into three branches (which cost
        # ~3x the per-step work AND ~3x the compile size; measured
        # 56 ms/step before the hoist). The reserved slots are the
        # LAST EXTRA_SLOTS outbox rows (dims.for_protocol adds them on
        # top of the branch fanout), so a future wider-fanout branch
        # can never collide with them by convention drift.
        base = dims.F - CaesarDev.EXTRA_SLOTS
        ps, ob = _exec_scan(
            self, ps, me, ctx, dims, ob, base, base + 1, do_exec
        )
        ps, ob = _wait_scan(
            self, ps, me, ctx, dims, ob, base + 2, base + 3, do_wait
        )
        return ps, ob

    def periodic(self, ps, fire, me, now, ctx, dims: EngineDims):
        """Row 0: GC — kick the MGC broadcast chain for buffered
        executed dots. Row 1: executed notification — drain the
        executor's buffer into the GC flow (handle_executed)."""
        # the oracle pops a coinciding GC event before the notification
        # event, so dots drained by a same-instant notification must NOT
        # ride this GC broadcast: snapshot the eligible count before the
        # drain; the GC_DRAIN chain consumes only gb_gc entries (the
        # buffer is FIFO, so those are exactly the pre-notification dots)
        pre_n = ps["gb_n"]
        ps = _drain_executed_notification(self, ps, me, ctx, dims, fire[1])
        ps = dict(ps, gb_gc=jnp.where(fire[0], pre_n, ps["gb_gc"]))
        ob = emit(
            empty_outbox(dims),
            0,
            me,
            CaesarDev.GC_DRAIN,
            [0],
            valid=fire[0] & (pre_n > 0),
        )
        return ps, ob


def _off():
    """Scan-disable flag (a traced scalar, so every switch branch
    returns the same aval)."""
    return jnp.zeros((), bool)


# ----------------------------------------------------------------------
# key-clock helpers (common/pred/clocks)
# ----------------------------------------------------------------------


def _clk_lt(a_seq, a_pid, b_seq, b_pid):
    """Lexicographic Clock order (clocks/mod.rs:27-60)."""
    return (a_seq < b_seq) | ((a_seq == b_seq) & (a_pid < b_pid))


def _kc_add(dev, ps, key, src, seq, cseq, cpid, enable):
    """Register (dot, clock) on the key (locked.rs add); a duplicate
    clock or a full row raises the lane error flag."""
    row_cseq = oh_get(ps["kc_cseq"], key)
    row_cpid = oh_get(ps["kc_cpid"], key)
    do = jnp.asarray(enable, bool)
    dup = jnp.any((row_cseq == cseq) & (row_cpid == cpid) & (row_cseq > 0))
    free = row_cseq == 0
    slot = jnp.argmax(free)
    overflow = do & ~jnp.any(free)
    widx = jnp.where(do & ~overflow & ~dup, slot, dev.S)
    return dict(
        ps,
        kc_src=oh_set2(ps["kc_src"], key, widx, src),
        kc_seq=oh_set2(ps["kc_seq"], key, widx, seq),
        kc_cseq=oh_set2(ps["kc_cseq"], key, widx, cseq),
        kc_cpid=oh_set2(ps["kc_cpid"], key, widx, cpid),
        err=ps["err"] | ERR_CAPACITY * overflow | ERR_PROTO * (do & dup),
    )


def _kc_remove(dev, ps, key, cseq, cpid, enable):
    """Unregister the clock from the key (locked.rs remove); missing
    entries raise the lane error flag."""
    row_cseq = oh_get(ps["kc_cseq"], key)
    row_cpid = oh_get(ps["kc_cpid"], key)
    match = (row_cseq == cseq) & (row_cpid == cpid) & (row_cseq > 0)
    do = jnp.asarray(enable, bool)
    found = jnp.any(match)
    slot = jnp.argmax(match)
    widx = jnp.where(do & found, slot, dev.S)
    zero = jnp.zeros((), I32)
    return dict(
        ps,
        kc_src=oh_set2(ps["kc_src"], key, widx, zero),
        kc_seq=oh_set2(ps["kc_seq"], key, widx, zero),
        kc_cseq=oh_set2(ps["kc_cseq"], key, widx, zero),
        kc_cpid=oh_set2(ps["kc_cpid"], key, widx, zero),
        err=ps["err"] | ERR_PROTO * (do & ~found),
    )


def _predecessors(dev, ps, key, cseq, cpid):
    """Masked row compare (locked.rs:85-131): returns (pred_mask [S],
    blocker_mask [S]) over the key row relative to clock (cseq, cpid)."""
    row_cseq = oh_get(ps["kc_cseq"], key)
    row_cpid = oh_get(ps["kc_cpid"], key)
    present = row_cseq > 0
    lower = _clk_lt(row_cseq, row_cpid, cseq, cpid)
    higher = _clk_lt(cseq, cpid, row_cseq, row_cpid)
    return present & lower, present & higher


def _pack_deps(dev, ps, key, pred_mask, base, pay, dims):
    """Compact the masked key-row dots into payload dep slots starting
    at ``base`` ([nd, (src, seq)*]); returns (pay, nd, overflow).

    Non-predecessor entries order at INF so they can never alias a
    valid dep slot regardless of how S and DEP compare."""
    order, nd = compact_order(pred_mask, dev.DEP)
    overflow = nd > dev.DEP
    lo = base + 1 + 2 * jnp.minimum(order, dims.P)  # > P when order==INF
    pay = oh_set(pay, base, nd)
    pay = oh_pack_pairs(
        pay, lo, oh_get(ps["kc_src"], key), oh_get(ps["kc_seq"], key)
    )
    return pay, nd, overflow



# ----------------------------------------------------------------------
# wait-condition scan
# ----------------------------------------------------------------------


def _blocker_verdicts(dev, ps, dims):
    """For every dot's blocker entries: (resolved, reject) masks
    [N, D, BB] (caesar.rs:932-1096 re-evaluated lazily; see module
    docstring for the monotonicity argument).

    The membership test ("my dot ∈ blocker.deps") goes through a
    live-dep relation R[q, e, p, d] = "dot (q, e)'s dep list contains
    the live dot at (p, d)" built with ONE [N, D, DEP]-sized scatter —
    not by gathering every blocker's whole dep list, which materialized
    [N, D, BB, DEP] (two ~330k-element gathers per step; this scan runs
    every step under vmap and dominated CaesarDev's runtime)."""
    N, D = dims.N, dims.D
    bsrc = ps["bb_src"]                       # [N, D, BB]
    bseq = ps["bb_seq"]
    bslot = dot_slot(bseq, dims)
    present = bseq > 0
    valid = ps["pseq"][bsrc, bslot] == bseq
    gcd = present & ~valid                    # freed ⇒ executed everywhere
    b_st = ps["status"][bsrc, bslot]
    safe = present & valid & (b_st >= ST_ACCEPT)
    # live-dep relation: a dep entry (src, seq) refers to the live dot
    # at (src, slot) exactly when pseq[src, slot] == seq — the same
    # equality the direct per-blocker compare used. One [N, D, DEP]
    # scatter + two small gathers, instead of materializing every
    # blocker's whole dep list as two [N, D, BB, DEP] gathers (~330k
    # elements each; this scan runs every step under vmap)
    dsrc = ps["dep_src"]                      # [N, D, DEP]
    dseq = ps["dep_seq"]
    dslot = dot_slot(dseq, dims)
    dep_live = (dseq > 0) & (ps["pseq"][dsrc, dslot] == dseq)
    shape = dsrc.shape
    qq = jnp.broadcast_to(
        jnp.arange(N, dtype=I32)[:, None, None], shape
    )
    ee = jnp.broadcast_to(
        jnp.arange(D, dtype=I32)[None, :, None], shape
    )
    rel = jnp.zeros((N, D, N, D), bool)
    rel = rel.at[
        qq, ee, jnp.where(dep_live, dsrc, 0), jnp.where(dep_live, dslot, 0)
    ].max(dep_live)
    # member[p, d, b] = rel[blocker(p,d,b), (p, d)]
    pp = jnp.arange(N, dtype=I32)[:, None, None]
    dd = jnp.arange(D, dtype=I32)[None, :, None]
    member = rel[bsrc, bslot, pp, dd]
    ign = safe & member
    reject = safe & ~member
    resolved = ~present | gcd | ign
    return resolved, reject


def _blocker_verdicts_one(dev, ps, src, slot, dims):
    """Single-dot variant of :func:`_blocker_verdicts` for the dot at
    (src, slot): returns (resolved [BB], reject [BB]) without gathering
    the whole [N, D, BB, DEP] state."""
    bsrc = oh_get(oh_get(ps["bb_src"], src), slot)  # [BB]
    bseq = oh_get(oh_get(ps["bb_seq"], src), slot)
    bslot = dot_slot(bseq, dims)
    present = bseq > 0
    valid = ps["pseq"][bsrc, bslot] == bseq
    gcd = present & ~valid                    # freed ⇒ executed everywhere
    b_st = ps["status"][bsrc, bslot]
    safe = present & valid & (b_st >= ST_ACCEPT)
    my_seq = oh_get(oh_get(ps["pseq"], src), slot)
    b_dsrc = ps["dep_src"][bsrc, bslot]       # [BB, DEP]
    b_dseq = ps["dep_seq"][bsrc, bslot]
    member = jnp.any(
        (b_dseq > 0) & (b_dsrc == src) & (b_dseq == my_seq), axis=1
    )
    ign = safe & member
    reject = safe & ~member
    resolved = ~present | gcd | ign
    return resolved, reject


def _wait_scan(dev, ps, me, ctx, dims, ob, ack_slot, chain_slot,
               enable=True):
    """Find one waiting dot whose wait condition resolves, reply its
    MProposeAck, and chain while more remain."""
    resolved, reject = _blocker_verdicts(dev, ps, dims)
    waiting = (ps["status"] == ST_PROPOSE_END) & jnp.any(
        ps["bb_seq"] > 0, axis=2
    )
    w_rej = waiting & jnp.any(reject, axis=2)
    w_acc = waiting & jnp.all(resolved, axis=2) & ~w_rej
    actionable = w_rej | w_acc
    num = jnp.sum(actionable)

    srcs = jnp.arange(dims.N, dtype=I32)[:, None]
    packed = srcs * SEQ_BOUND + ps["pseq"]
    flat = jnp.argmin(jnp.where(actionable, packed, INF))
    wsrc, wslot = flat // dims.D, flat % dims.D
    wseq = oh_get(oh_get(ps["pseq"], wsrc), wslot)
    is_rej = oh_get(oh_get(w_rej, wsrc), wslot)

    do = jnp.asarray(enable, bool) & (num > 0)
    ps, ob = _propose_reply(
        dev, ps, me, wsrc, wslot, wseq, ~is_rej, ctx, dims, ob, ack_slot, do
    )
    ob = emit(
        ob, chain_slot, me, CaesarDev.WAIT_DRAIN, [0], valid=do & (num > 1)
    )
    return ps, ob


def _propose_reply(dev, ps, me, wsrc, wslot, wseq, accept, ctx, dims, ob,
                   ob_slot, enable):
    """Send the MProposeAck for a decided proposal: accept echoes the
    registered clock + deps; reject generates a fresh clock and
    recomputes deps at it (_accept_command/_reject_command)."""
    do = jnp.asarray(enable, bool)
    rej = do & ~jnp.asarray(accept, bool)
    key = oh_get(oh_get(ps["key_of"], wsrc), wslot)

    # reject: new clock from my counter; deps = all lower-clock entries
    # on the key (including this dot's own old registration)
    new_cseq = ps["clk_counter"] + 1
    ps = dict(
        ps,
        # the executor's clock packing clk_seq*(N+1)+pid must stay < INF
        err=ps["err"] | ERR_SEQ * (rej & (new_cseq >= INF // (dims.N + 1))),
        clk_counter=jnp.where(rej, new_cseq, ps["clk_counter"]),
        status=oh_set2(
            ps["status"], jnp.where(rej, wsrc, dims.N), wslot, ST_REJECT
        ),
        # accept: clear the blocker list so the scan never re-fires
        bb_seq=oh_set2(
            ps["bb_seq"], jnp.where(do & ~rej, wsrc, dims.N), wslot,
            jnp.zeros((dev.BB,), I32),
        ),
    )

    # reject payload: fresh clock + deps recomputed at it (this dot's
    # own old-clock registration is included, like the oracle)
    rpay = jnp.zeros((dims.P,), I32)
    rpay = rpay.at[0].set(wseq)
    rpay = rpay.at[1].set(new_cseq)
    rpay = rpay.at[2].set(me)
    pred_mask, _ = _predecessors(dev, ps, key, new_cseq, me)
    rpay, _rnd, roverflow = _pack_deps(dev, ps, key, pred_mask, 4, rpay, dims)

    # accept payload: registered clock + propose-time deps (compact)
    apay = jnp.zeros((dims.P,), I32)
    apay = apay.at[0].set(wseq)
    my_dep_src = oh_get(oh_get(ps["dep_src"], wsrc), wslot)
    my_dep_seq = oh_get(oh_get(ps["dep_seq"], wsrc), wslot)
    apay = apay.at[1].set(oh_get(oh_get(ps["clk_seq"], wsrc), wslot))
    apay = apay.at[2].set(oh_get(oh_get(ps["clk_pid"], wsrc), wslot))
    apay = apay.at[3].set(1)
    apay = apay.at[4].set(jnp.sum(my_dep_seq > 0))
    order = 5 + 2 * jnp.arange(dev.DEP, dtype=I32)
    apay = oh_pack_pairs(apay, order, my_dep_src, my_dep_seq)

    pay = jnp.where(rej, rpay, apay)
    ps = dict(ps, err=ps["err"] | ERR_CAPACITY * (rej & roverflow))
    ob = emit(ob, ob_slot, wsrc, CaesarDev.MPROPOSEACK, pay, valid=do)
    return ps, ob


# ----------------------------------------------------------------------
# predecessors-executor drain
# ----------------------------------------------------------------------


def _exec_scan(dev, ps, me, ctx, dims, ob, client_slot, chain_slot,
               enable=True):
    """Execute one command whose deps are committed and whose
    lower-clock deps are executed (pred/mod.rs:104-275); chain while
    more are ready. Lower-clock gating is acyclic, so one execution per
    zero-delay step reaches the oracle's cascade at the same instant."""
    dsrc = ps["dep_src"]                      # [N, D, DEP]
    dseq = ps["dep_seq"]
    dslot = dot_slot(dseq, dims)
    absent = dseq == 0
    # Dep commit/execution status with ONE interval-set walk instead of
    # two (this scan runs every step under vmap and dominated the step
    # cost — the per-entry gap gathers are the expensive part):
    # * live dep (slot holds exactly this dot): its local status says
    #   it all — MCommit sets ST_COMMIT in the same handler call that
    #   feeds the cm set, execution sets ST_EXECUTED;
    # * dead dep (slot empty or recycled): the dot was either GC'd
    #   (⟹ executed HERE ⟹ in the executed set) or never proposed
    #   here (⟹ not executed, and not committed either — the ready()
    #   gate holds MCommit until the MPropose landed). So executed-set
    #   membership decides BOTH bits exactly.
    pseq_g = ps["pseq"][dsrc, dslot]
    st_g = ps["status"][dsrc, dslot]
    live = pseq_g == dseq
    dead_done = iset_contains_gathered(
        ps["ex_front"], ps["ex_gaps"], dsrc, dseq
    )
    committed = jnp.where(live, st_g >= ST_COMMIT, dead_done)
    executed = jnp.where(live, st_g == ST_EXECUTED, dead_done)
    d_cseq = ps["clk_seq"][dsrc, dslot]
    d_cpid = ps["clk_pid"][dsrc, dslot]
    my_cseq = ps["clk_seq"][..., None]
    my_cpid = ps["clk_pid"][..., None]
    lower = _clk_lt(d_cseq, d_cpid, my_cseq, my_cpid)
    dep_ok = absent | (committed & (executed | ~lower))
    ready = (ps["status"] == ST_COMMIT) & jnp.all(dep_ok, axis=2)
    num = jnp.sum(ready)

    # clock order (phase-two executes in clock order, mod.rs:208-275);
    # ERR_SEQ keeps clk_seq < INF // (N + 1), so the packing stays
    # *strictly* below the INF not-ready sentinel in the argmin — the
    # min makes that bound structural (GL001); the - 1 matters when
    # INF divides by N + 1 exactly (a saturated entry must not tie INF)
    packed = (
        jnp.minimum(ps["clk_seq"], INF // (dims.N + 1) - 1) * (dims.N + 1)
        + ps["clk_pid"]
    )
    flat = jnp.argmin(jnp.where(ready, packed, INF))
    esrc, eslot = flat // dims.D, flat % dims.D
    eseq = oh_get(oh_get(ps["pseq"], esrc), eslot)
    client = oh_get(oh_get(ps["client_of"], esrc), eslot)

    do = jnp.asarray(enable, bool) & (num > 0)
    # safety monitor (engine/monitor.py; the ``if`` is a trace-time
    # gate). Caesar keeps no committed interval set independent of
    # the status table that gates this scan, so the execute-before-
    # commit guard stays off here (docs/MC.md).
    if "_mon_hash" in ps:
        ekey = oh_get(oh_get(ps["key_of"], esrc), eslot)
        ps = mon_exec(ps, ekey, esrc, eseq, do)
    front, gaps, overflow = iset_add(
        oh_get(ps["ex_front"], esrc), oh_get(ps["ex_gaps"], esrc), eseq, do
    )
    # buffer the executed dot for the notification tick
    eb_n = ps["eb_n"]
    eb_overflow = do & (eb_n >= dev.EB)
    widx = jnp.where(do & ~eb_overflow, eb_n, dev.EB)
    ps = dict(
        ps,
        ex_front=oh_set(ps["ex_front"], esrc, front),
        ex_gaps=oh_set(ps["ex_gaps"], esrc, gaps),
        status=oh_set2(
            ps["status"], jnp.where(do, esrc, dims.N), eslot, ST_EXECUTED
        ),
        eb_src=oh_set(ps["eb_src"], widx, esrc),
        eb_seq=oh_set(ps["eb_seq"], widx, eseq),
        eb_n=eb_n + (do & ~eb_overflow).astype(I32),
        err=ps["err"] | ERR_CAPACITY * (overflow | eb_overflow),
    )
    ob = emit(
        ob,
        client_slot,
        dims.N + client,
        CaesarDev.TO_CLIENT,
        [0],
        valid=do & (oh_get(ctx["client_attach"], client) == me),
    )
    # always re-chain after an execution: executing this command may
    # make lower-frontier commands ready (the oracle's pending-index
    # cascade); the follow-up drain no-ops when nothing is left
    ob = emit(
        ob, chain_slot, me, CaesarDev.EXEC_DRAIN, [0], valid=do
    )
    return ps, ob


# ----------------------------------------------------------------------
# GC helpers
# ----------------------------------------------------------------------


def _gc_count(dev, ps, freed, me, ctx, dims, src, seq, enable):
    """BasicGCTrack.add for one dot: at n sightings, free it
    (caesar.rs _gc_command + bp.stable).

    Runs inside fori_loop bodies, so it touches only SMALL arrays (the
    [K, S] clock table, the [N, D] counters) and records frees in the
    ``freed`` [N, D] mask; the caller applies :func:`_apply_freed` ONCE
    after its loop. Clearing the [N, D, DEP]/[N, D, BB] dep arrays per
    iteration rewrote ~100 KB x loop-trips every engine step (loop
    bodies cannot fuse across iterations) and dominated step cost."""
    slot = dot_slot(seq, dims)
    do = jnp.asarray(enable, bool) & (seq > 0)
    valid = oh_get(oh_get(ps["pseq"], src), slot) == seq
    cnt = oh_get(oh_get(ps["gc_cnt"], src), slot) + 1
    full = do & valid & (cnt == ctx["n"])
    wsrc = jnp.where(do & valid, src, dims.N)
    ps = dict(
        ps,
        err=ps["err"] | ERR_PROTO * (do & ~valid),
        gc_cnt=oh_set2(ps["gc_cnt"], wsrc, slot, cnt),
    )
    # free: unregister the clock now (small table); defer the slot
    # clears to the caller's one masked write
    key = oh_get(oh_get(ps["key_of"], src), slot)
    ps = _kc_remove(
        dev, ps, key,
        oh_get(oh_get(ps["clk_seq"], src), slot),
        oh_get(oh_get(ps["clk_pid"], src), slot),
        full,
    )
    fsrc = jnp.where(full, src, dims.N)
    hit = (
        jnp.arange(dims.N, dtype=I32)[:, None] == fsrc
    ) & (jnp.arange(dims.D, dtype=I32)[None, :] == slot)
    ps = dict(ps, m_stable=ps["m_stable"] + full.astype(I32))
    return ps, freed | hit


def _apply_freed(dev, ps, freed):
    """Clear every freed dot's lifecycle state in one masked write
    (the deferred half of :func:`_gc_count`)."""
    f3 = freed[:, :, None]
    return dict(
        ps,
        pseq=jnp.where(freed, 0, ps["pseq"]),
        status=jnp.where(freed, 0, ps["status"]),
        gc_cnt=jnp.where(freed, 0, ps["gc_cnt"]),
        dep_seq=jnp.where(f3, 0, ps["dep_seq"]),
        bb_seq=jnp.where(f3, 0, ps["bb_seq"]),
    )


def _drain_executed_notification(dev, ps, me, ctx, dims, enable):
    """handle_executed (caesar.rs:194-213): move the executor's newly
    executed dots into the MGC broadcast buffer and count my own
    sighting of each."""
    do = jnp.asarray(enable, bool)
    n_dots = jnp.where(do, ps["eb_n"], 0)

    # a lax loop, not an unroll: the body embeds _gc_count (a large
    # subgraph) and EB copies of it explode compile time. (A dynamic
    # while_loop bounded by n_dots measured SLOWER than the fixed fori
    # here — the batched-while per-iteration select machinery costs
    # more than the masked no-op iterations save.)
    def body(i, carry):
        ps, freed = carry
        take = i < n_dots
        src = ps["eb_src"][i]
        seq = ps["eb_seq"][i]
        gb_n = ps["gb_n"]
        overflow = take & (gb_n >= dev.EB)
        widx = jnp.where(take & ~overflow, gb_n, dev.EB)
        ps = dict(
            ps,
            gb_src=oh_set(ps["gb_src"], widx, src),
            gb_seq=oh_set(ps["gb_seq"], widx, seq),
            gb_n=gb_n + (take & ~overflow).astype(I32),
            err=ps["err"] | ERR_CAPACITY * overflow,
        )
        return _gc_count(dev, ps, freed, me, ctx, dims, src, seq, take)

    freed0 = jnp.zeros((dims.N, dims.D), bool)
    ps, freed = jax.lax.fori_loop(0, dev.EB, body, (ps, freed0))
    ps = _apply_freed(dev, ps, freed)
    return dict(ps, eb_n=jnp.where(do, 0, ps["eb_n"]))


# ----------------------------------------------------------------------
# handlers
# ----------------------------------------------------------------------


def _submit(dev, ps, msg, me, ctx, dims):
    """caesar.rs:245-264: next dot + fresh clock, MPropose to everyone
    (the fastest repliers form the fast quorum)."""
    client = msg["payload"][0]
    key = msg["payload"][2]
    seq = ps["own_seq"] + 1
    slot = dot_slot(seq, dims)
    cseq = ps["clk_counter"] + 1
    DEP = dev.DEP
    ps = dict(
        ps,
        # (source, sequence) packing in the scans requires seq < bound;
        # the executor's clock packing clk_seq*(N+1)+pid must stay < INF
        err=ps["err"]
        | ERR_SEQ * ((seq >= SEQ_BOUND) | (cseq >= INF // (dims.N + 1))),
        own_seq=seq,
        clk_counter=cseq,
        qa_cnt=oh_set(ps["qa_cnt"], slot, 0),
        qa_ok=oh_set(ps["qa_ok"], slot, True),
        qa_done=oh_set(ps["qa_done"], slot, False),
        qa_cseq=oh_set(ps["qa_cseq"], slot, 0),
        qa_cpid=oh_set(ps["qa_cpid"], slot, 0),
        qr_cnt=oh_set(ps["qr_cnt"], slot, 0),
        ag_src=oh_set(ps["ag_src"], slot, jnp.zeros((DEP,), I32)),
        ag_seq=oh_set(ps["ag_seq"], slot, jnp.zeros((DEP,), I32)),
    )
    ob = emit_broadcast(
        empty_outbox(dims),
        CaesarDev.MPROPOSE,
        [seq, key, client, cseq],
        ctx["n"],
    )
    ob = dict(ob, valid=ob["valid"] & msg["valid"])
    return ps, ob, _off(), _off()


def _mpropose(dev, ps, msg, me, ctx, dims):
    """caesar.rs:266-510: join the clock, compute predecessors and
    blockers, register the proposal, and decide accept/reject/wait."""
    s = msg["src"]
    seq, key, client, cseq = (
        msg["payload"][0],
        msg["payload"][1],
        msg["payload"][2],
        msg["payload"][3],
    )
    # clock sequences ride in payload words; the generator enforces
    # cseq < INF // (N + 1) (ERR_SEQ), so clamping the re-entry to the
    # strict bound keeps every downstream cseq * (N + 1) + pid packing
    # wrap-free AND strictly below the INF sentinel on any word (GL001)
    cseq = jnp.clip(cseq, 0, INF // (dims.N + 1) - 1)
    cpid = jnp.clip(s, 0, dims.N)
    slot = dot_slot(seq, dims)
    dirty = oh_get(oh_get(ps["pseq"], s), slot) != 0
    ps = dict(
        ps,
        clk_counter=jnp.maximum(ps["clk_counter"], cseq),
        err=ps["err"] | ERR_DOT * dirty,
        pseq=oh_set2(ps["pseq"], s, slot, seq),
        key_of=oh_set2(ps["key_of"], s, slot, key),
        client_of=oh_set2(ps["client_of"], s, slot, client),
        clk_seq=oh_set2(ps["clk_seq"], s, slot, cseq),
        clk_pid=oh_set2(ps["clk_pid"], s, slot, cpid),
        status=oh_set2(ps["status"], s, slot, ST_PROPOSE_END),
    )

    # predecessors + blockers over the key row, then register the dot
    # (compact_order's INF sentinel can never alias a valid index of the
    # DEP-/BB-wide arrays, whatever their size relative to S)
    pred_mask, block_mask = _predecessors(dev, ps, key, cseq, cpid)
    row_src = oh_get(ps["kc_src"], key)
    row_seq = oh_get(ps["kc_seq"], key)
    # store deps, scattered through one-hot compaction masks
    order, nd = compact_order(pred_mask, dev.DEP)
    oh_ord = order[:, None] == jnp.arange(dev.DEP, dtype=I32)[None, :]
    d_src = jnp.sum(jnp.where(oh_ord, row_src[:, None], 0), axis=0, dtype=I32)
    d_seq = jnp.sum(jnp.where(oh_ord, row_seq[:, None], 0), axis=0, dtype=I32)
    border, nb = compact_order(block_mask, dev.BB)
    oh_bord = border[:, None] == jnp.arange(dev.BB, dtype=I32)[None, :]
    b_src = jnp.sum(jnp.where(oh_bord, row_src[:, None], 0), axis=0, dtype=I32)
    b_seq = jnp.sum(jnp.where(oh_bord, row_seq[:, None], 0), axis=0, dtype=I32)
    ps = dict(
        ps,
        dep_src=oh_set2(ps["dep_src"], s, slot, d_src),
        dep_seq=oh_set2(ps["dep_seq"], s, slot, d_seq),
        bb_src=oh_set2(ps["bb_src"], s, slot, b_src),
        bb_seq=oh_set2(ps["bb_seq"], s, slot, b_seq),
        err=ps["err"] | ERR_CAPACITY * ((nd > dev.DEP) | (nb > dev.BB)),
    )
    ps = _kc_add(dev, ps, key, s, seq, cseq, cpid, True)

    # decide: no blockers → accept; wait condition off → reject;
    # otherwise evaluate each blocker now (safe ones ignore/reject,
    # unsafe ones leave us waiting)
    resolved, reject = _blocker_verdicts_one(dev, ps, s, slot, dims)
    has_block = nb > 0
    any_rej = jnp.any(reject)
    all_res = jnp.all(resolved)
    accept_now = ~has_block | (ctx["wait_condition"] & all_res & ~any_rej)
    reject_now = has_block & (~ctx["wait_condition"] | any_rej)
    decided = accept_now | reject_now
    ps, ob = _propose_reply(
        dev, ps, me, s, slot, seq, accept_now, ctx, dims,
        empty_outbox(dims), 0, decided,
    )
    return ps, ob, _off(), _off()


def _agg_union(dev, ps, slot, pay_base, msg, enable):
    """Union the message's dep list into the per-dot aggregate table
    (QuorumClocks/QuorumRetries dep union).

    One vectorized rank-match instead of a DEP-long unrolled insert
    chain: with DEP=64 the unroll put ~64 scatter subgraphs into BOTH
    ack handlers and dominated CaesarDev's XLA compile time (measured
    385 s on CPU). Entries are deduped against the table AND against
    earlier same-message entries (triangular compare), so the result is
    exactly the sequential chain's."""
    Q = dev.DEP
    do = jnp.asarray(enable, bool)
    nd = msg["payload"][pay_base]
    iota = jnp.arange(Q, dtype=I32)
    idxs = pay_base + 1 + 2 * iota
    en = do & (iota < nd)
    dsrcs = jnp.where(en, oh_take(msg["payload"], idxs), 0)
    dseqs = jnp.where(en, oh_take(msg["payload"], idxs + 1), 0)
    row_src = oh_get(ps["ag_src"], slot)  # [Q]
    row_seq = oh_get(ps["ag_seq"], slot)
    in_table = jnp.any(
        (row_seq[None, :] == dseqs[:, None])
        & (row_src[None, :] == dsrcs[:, None])
        & (row_seq[None, :] > 0),
        axis=1,
    )
    same = (dseqs[None, :] == dseqs[:, None]) & (
        dsrcs[None, :] == dsrcs[:, None]
    )
    earlier = en[None, :] & (iota[None, :] < iota[:, None])
    dup_in_msg = jnp.any(same & earlier, axis=1)
    new = en & ~in_table & ~dup_in_msg
    # rank the i-th new entry onto the i-th free table slot
    new_order, n_new = compact_order(new, Q)
    free = row_seq == 0
    free_order, n_free = compact_order(free, Q)
    match = (
        (new_order[:, None] == free_order[None, :])
        & new[:, None]
        & free[None, :]
    )
    write = jnp.any(match, axis=0)  # [Q] table slots written
    w_src = oh_match(match, dsrcs)
    w_seq = oh_match(match, dseqs)
    overflow = n_new > n_free
    return dict(
        ps,
        ag_src=oh_set(
            ps["ag_src"], jnp.where(do, slot, ps["ag_src"].shape[0]),
            jnp.where(write, w_src, row_src),
        ),
        ag_seq=oh_set(
            ps["ag_seq"], jnp.where(do, slot, ps["ag_seq"].shape[0]),
            jnp.where(write, w_seq, row_seq),
        ),
        err=ps["err"] | ERR_CAPACITY * (do & overflow),
    )


def _agg_broadcast(dev, ps, me, seq, cseq, cpid, mtype, ctx, dims, valid):
    """Broadcast MCommit/MRetry carrying the aggregated clock + deps."""
    slot = dot_slot(seq, dims)
    P = dims.P
    ag_seq_row = oh_get(ps["ag_seq"], slot)
    present = ag_seq_row > 0
    order, nd = compact_order(present, dev.DEP)
    pay = jnp.zeros((P,), I32)
    pay = pay.at[0].set(me)
    pay = pay.at[1].set(seq)
    pay = pay.at[2].set(cseq)
    pay = pay.at[3].set(cpid)
    pay = pay.at[4].set(nd)
    lo = 5 + 2 * jnp.minimum(order, P)  # > P when order==INF
    pay = oh_pack_pairs(pay, lo, oh_get(ps["ag_src"], slot), ag_seq_row)
    ob = emit_broadcast(empty_outbox(dims), mtype, pay, ctx["n"])
    return dict(ob, valid=ob["valid"] & jnp.asarray(valid, bool))


def _mproposeack(dev, ps, msg, me, ctx, dims):
    """caesar.rs:512-558 + QuorumClocks (clocks/quorum.rs:7-81): join
    clocks, union deps, and fire fast path (all ok at fq_size) or the
    retry round (some reject once a majority replied)."""
    seq = msg["payload"][0]
    # clamped like _mpropose: payload clocks stay packing-safe
    cseq = jnp.clip(msg["payload"][1], 0, INF // (dims.N + 1) - 1)
    cpid = msg["payload"][2]
    ok = msg["payload"][3] > 0
    slot = dot_slot(seq, dims)

    st = oh_get(oh_get(ps["status"], me), slot)
    qa_done_s = oh_get(ps["qa_done"], slot)
    live = ((st == ST_PROPOSE_END) | (st == ST_REJECT)) & ~qa_done_s

    qa_cseq_s = oh_get(ps["qa_cseq"], slot)
    qa_cpid_s = oh_get(ps["qa_cpid"], slot)
    join_hi = _clk_lt(qa_cseq_s, qa_cpid_s, cseq, cpid)
    qa_cnt_s = oh_get(ps["qa_cnt"], slot)
    cnt = qa_cnt_s + 1
    qa_ok_s = oh_get(ps["qa_ok"], slot)
    all_ok = qa_ok_s & ok
    ps = dict(
        ps,
        qa_cnt=oh_set(ps["qa_cnt"], slot, jnp.where(live, cnt, qa_cnt_s)),
        qa_ok=oh_set(ps["qa_ok"], slot, jnp.where(live, all_ok, qa_ok_s)),
        qa_cseq=oh_set(
            ps["qa_cseq"], slot, jnp.where(live & join_hi, cseq, qa_cseq_s)
        ),
        qa_cpid=oh_set(
            ps["qa_cpid"], slot, jnp.where(live & join_hi, cpid, qa_cpid_s)
        ),
    )
    ps = _agg_union(dev, ps, slot, 4, msg, live)

    done = live & (
        (cnt == ctx["fq_size"])
        | (~all_ok & (cnt >= ctx["wq_size"]))
    )
    fast = done & all_ok
    slow = done & ~all_ok
    ps = dict(
        ps,
        qa_done=oh_set(ps["qa_done"], slot, qa_done_s | done),
        m_fast=ps["m_fast"] + fast.astype(I32),
        m_slow=ps["m_slow"] + slow.astype(I32),
    )
    cseq_f = oh_get(ps["qa_cseq"], slot)
    cpid_f = oh_get(ps["qa_cpid"], slot)
    # one broadcast: identical payload either way, only the type differs
    mtype = jnp.where(fast, CaesarDev.MCOMMIT, CaesarDev.MRETRY)
    ob = _agg_broadcast(
        dev, ps, me, seq, cseq_f, cpid_f, mtype, ctx, dims, done
    )
    return ps, ob, _off(), _off()


def _store_deps_from_msg(dev, ps, src, slot, msg, base, skip_self, seq,
                         enable, dims):
    """Replace the dot's dep list with the message's (minus a self-dep
    when ``skip_self``; caesar.rs:665-668)."""
    Q = dev.DEP
    nd = msg["payload"][base]
    idxs = base + 1 + 2 * jnp.arange(Q, dtype=I32)
    en = jnp.arange(Q, dtype=I32) < nd
    dsrcs = jnp.where(en, oh_take(msg["payload"], idxs), 0)
    dseqs = jnp.where(en, oh_take(msg["payload"], idxs + 1), 0)
    if skip_self:
        selfdep = (dsrcs == src) & (dseqs == seq)
        dsrcs = jnp.where(selfdep, 0, dsrcs)
        dseqs = jnp.where(selfdep, 0, dseqs)
    do = jnp.asarray(enable, bool)
    wsrc = jnp.where(do, src, dims.N)
    return dict(
        ps,
        dep_src=oh_set2(ps["dep_src"], wsrc, slot, dsrcs),
        dep_seq=oh_set2(ps["dep_seq"], wsrc, slot, dseqs),
        err=ps["err"] | ERR_CAPACITY * (do & (nd > Q)),
    )


def _update_clock(dev, ps, src, slot, key, new_cseq, new_cpid, enable, dims):
    """Swap the registered clock (caesar.rs:893-918). ``new_cseq`` may
    ride in from a payload word, so it is clamped to the executor's
    cseq * (N + 1) + pid packing bound here (lint GL001) — a no-op for
    every in-contract clock (ERR_SEQ enforces the bound at
    generation)."""
    do = jnp.asarray(enable, bool)
    new_cseq = jnp.clip(new_cseq, 0, INF // (dims.N + 1) - 1)
    new_cpid = jnp.clip(new_cpid, 0, dims.N)
    old_cseq = oh_get(oh_get(ps["clk_seq"], src), slot)
    old_cpid = oh_get(oh_get(ps["clk_pid"], src), slot)
    changed = do & ((old_cseq != new_cseq) | (old_cpid != new_cpid))
    ps = _kc_remove(dev, ps, key, old_cseq, old_cpid, changed)
    ps = _kc_add(
        dev, ps, key, src, oh_get(oh_get(ps["pseq"], src), slot),
        new_cseq, new_cpid, changed,
    )
    wsrc = jnp.where(do, src, dims.N)
    return dict(
        ps,
        clk_seq=oh_set2(ps["clk_seq"], wsrc, slot, new_cseq),
        clk_pid=oh_set2(ps["clk_pid"], wsrc, slot, new_cpid),
    )


def _mcommit(dev, ps, msg, me, ctx, dims):
    """caesar.rs:634-702: final clock + deps, feed the executor, and
    re-evaluate waiting proposals."""
    dsrc = msg["payload"][0]
    seq = msg["payload"][1]
    cseq = msg["payload"][2]
    cpid = msg["payload"][3]
    slot = dot_slot(seq, dims)
    st = oh_get(oh_get(ps["status"], dsrc), slot)
    have = oh_get(oh_get(ps["pseq"], dsrc), slot) == seq
    do = have & (st != ST_COMMIT) & (st != ST_EXECUTED)
    key = oh_get(oh_get(ps["key_of"], dsrc), slot)

    ps = dict(
        ps,
        clk_counter=jnp.maximum(ps["clk_counter"], cseq),
        err=ps["err"] | ERR_PROTO * ~have,
    )
    ps = _store_deps_from_msg(dev, ps, dsrc, slot, msg, 4, True, seq, do,
                              dims)
    ps = _update_clock(dev, ps, dsrc, slot, key, cseq, cpid, do, dims)
    wsrc = jnp.where(do, dsrc, dims.N)
    ps = dict(
        ps,
        status=oh_set2(ps["status"], wsrc, slot, ST_COMMIT),
    )
    # executor + wait re-evaluation run in the hoisted scans (handle)
    return ps, empty_outbox(dims), jnp.asarray(do, bool), jnp.asarray(
        do, bool
    )


def _mretry(dev, ps, msg, me, ctx, dims):
    """caesar.rs:704-760: adopt the retry clock + deps, reply with my
    predecessors at the new clock, and re-evaluate waiting proposals."""
    dsrc = msg["payload"][0]
    seq = msg["payload"][1]
    cseq = msg["payload"][2]
    cpid = msg["payload"][3]
    slot = dot_slot(seq, dims)
    st = oh_get(oh_get(ps["status"], dsrc), slot)
    have = oh_get(oh_get(ps["pseq"], dsrc), slot) == seq
    do = have & (st != ST_COMMIT) & (st != ST_EXECUTED)
    key = oh_get(oh_get(ps["key_of"], dsrc), slot)

    ps = dict(
        ps,
        clk_counter=jnp.maximum(ps["clk_counter"], cseq),
        err=ps["err"] | ERR_PROTO * ~have,
    )
    ps = _store_deps_from_msg(dev, ps, dsrc, slot, msg, 4, False, seq, do,
                              dims)
    ps = _update_clock(dev, ps, dsrc, slot, key, cseq, cpid, do, dims)
    wsrc = jnp.where(do, dsrc, dims.N)
    ps = dict(
        ps,
        status=oh_set2(ps["status"], wsrc, slot, ST_ACCEPT),
        bb_seq=oh_set2(
            ps["bb_seq"], wsrc, slot, jnp.zeros((dev.BB,), I32)
        ),
    )

    # reply: my predecessors at the new clock ∪ the message deps
    pred_mask, _ = _predecessors(dev, ps, key, cseq, cpid)
    pay = jnp.zeros((dims.P,), I32)
    pay = pay.at[0].set(dsrc)
    pay = pay.at[1].set(seq)
    pay, nd, overflow = _pack_deps(dev, ps, key, pred_mask, 2, pay, dims)

    # union the MRetry's dep list into the reply, vectorized (the
    # DEP-long unrolled insert chain here was the other half of
    # CaesarDev's compile blowup — see _agg_union): dedup each message
    # entry against my packed predecessors and against earlier message
    # entries, then append survivors in message order after slot nd
    Q = dev.DEP
    iota_q = jnp.arange(Q, dtype=I32)
    dep_idxs = 3 + 2 * iota_q
    my_valid = iota_q < nd
    my_src = oh_take(pay, dep_idxs)
    my_seq = oh_take(pay, dep_idxs + 1)
    m_en = iota_q < msg["payload"][4]
    msrcs = jnp.where(m_en, oh_take(msg["payload"], 5 + 2 * iota_q), 0)
    mseqs = jnp.where(m_en, oh_take(msg["payload"], 6 + 2 * iota_q), 0)
    have_already = jnp.any(
        my_valid[None, :]
        & (my_src[None, :] == msrcs[:, None])
        & (my_seq[None, :] == mseqs[:, None]),
        axis=1,
    )
    same = (mseqs[None, :] == mseqs[:, None]) & (
        msrcs[None, :] == msrcs[:, None]
    )
    earlier = m_en[None, :] & (iota_q[None, :] < iota_q[:, None])
    dup_in_msg = jnp.any(same & earlier, axis=1)
    add = m_en & ~have_already & ~dup_in_msg
    add_order, n_add = compact_order(add, Q)
    # bound the INF sentinel before the affine packing math: masked
    # entries pick dims.P below anyway, and 2 * INF would wrap i32
    # (lint GL001)
    safe_order = jnp.minimum(add_order, Q)
    lo = jnp.where(
        add & (nd + add_order < Q), 3 + 2 * (nd + safe_order), dims.P
    )
    pay = oh_pack_pairs(pay, lo, msrcs, mseqs)
    o2 = nd + n_add > Q
    nd = jnp.minimum(nd + n_add, Q)
    pay = pay.at[2].set(nd)
    ps = dict(ps, err=ps["err"] | ERR_CAPACITY * (do & (overflow | o2)))
    ob = emit(
        empty_outbox(dims), 0, msg["src"], CaesarDev.MRETRYACK, pay,
        valid=do,
    )
    # wait re-evaluation runs in the hoisted scan (handle)
    return ps, ob, _off(), jnp.asarray(do, bool)


def _mretryack(dev, ps, msg, me, ctx, dims):
    """caesar.rs:762-822 + QuorumRetries: union write-quorum dep
    replies; on the last one, commit."""
    seq = msg["payload"][1]
    slot = dot_slot(seq, dims)
    live = oh_get(oh_get(ps["status"], me), slot) == ST_ACCEPT
    qr_cnt_s = oh_get(ps["qr_cnt"], slot)
    cnt = qr_cnt_s + 1
    ps = dict(
        ps,
        qr_cnt=oh_set(ps["qr_cnt"], slot, jnp.where(live, cnt, qr_cnt_s)),
    )
    ps = _agg_union(dev, ps, slot, 2, msg, live)
    chosen = live & (cnt == ctx["wq_size"])
    ob = _agg_broadcast(
        dev,
        ps,
        me,
        seq,
        oh_get(oh_get(ps["clk_seq"], me), slot),
        oh_get(oh_get(ps["clk_pid"], me), slot),
        CaesarDev.MCOMMIT,
        ctx,
        dims,
        chosen,
    )
    return ps, ob, _off(), _off()


def _mgc(dev, ps, msg, me, ctx, dims):
    """MGarbageCollection: count each advertised executed dot
    (BasicGCTrack; frees at n sightings)."""
    nd = msg["payload"][0]

    # a lax loop, not an unroll: gc_per_msg copies of _gc_count's
    # subgraph explode compile time
    def body(i, carry):
        ps, freed = carry
        take = i < nd
        src = msg["payload"][1 + 2 * i]
        seq = msg["payload"][2 + 2 * i]
        return _gc_count(dev, ps, freed, me, ctx, dims, src, seq, take)

    freed0 = jnp.zeros((dims.N, dims.D), bool)
    ps, freed = jax.lax.fori_loop(
        0, dev.gc_per_msg(dims), body, (ps, freed0)
    )
    ps = _apply_freed(dev, ps, freed)
    return ps, empty_outbox(dims), _off(), _off()


def _wait_drain(dev, ps, msg, me, ctx, dims):
    # the hoisted wait scan (handle) does the work
    return ps, empty_outbox(dims), _off(), jnp.ones((), bool)


def _exec_drain(dev, ps, msg, me, ctx, dims):
    # the hoisted executor scan (handle) does the work
    return ps, empty_outbox(dims), jnp.ones((), bool), _off()


def _gc_drain(dev, ps, msg, me, ctx, dims):
    """Broadcast up to one message's worth of buffered executed dots to
    all-but-me; chain while this GC round's snapshot (gb_gc) remains."""
    DPM = dev.gc_per_msg(dims)
    n_buf = ps["gb_n"]
    take = jnp.minimum(jnp.minimum(ps["gb_gc"], n_buf), DPM)
    pay = jnp.zeros((dims.P,), I32)
    pay = pay.at[0].set(take)
    idx = jnp.arange(DPM, dtype=I32)
    en = idx < take
    lo_gc = jnp.where(en, 1 + 2 * idx, dims.P)
    pay = oh_pack_pairs(pay, lo_gc, ps["gb_src"][idx], ps["gb_seq"][idx])
    # shift the buffer down
    src_rolled = jnp.roll(ps["gb_src"], -take)
    seq_rolled = jnp.roll(ps["gb_seq"], -take)
    remaining = n_buf - take
    remaining_gc = ps["gb_gc"] - take
    keep = jnp.arange(dev.EB, dtype=I32) < remaining
    ps = dict(
        ps,
        gb_src=jnp.where(keep, src_rolled, 0),
        gb_seq=jnp.where(keep, seq_rolled, 0),
        gb_n=remaining,
        gb_gc=remaining_gc,
    )
    ob = emit_broadcast(
        empty_outbox(dims), CaesarDev.MGC, pay, ctx["n"], me,
        exclude_me=True,
    )
    ob = dict(ob, valid=ob["valid"] & (take > 0))
    ob = emit(
        ob, dims.N, me, CaesarDev.GC_DRAIN, [0], valid=remaining_gc > 0
    )
    return ps, ob, _off(), _off()

"""Device Tempo with partial replication and multi-key commands.

The partial-mode twin of :class:`TempoDev` — same protocol core
(fantoch_ps/src/protocol/tempo.rs, host oracle protocol/tempo.py) plus
the reference's shard-coordination paths:

- ``MForwardSubmit`` hands the dot to the closest process of every
  other shard the command touches (partial.rs:8-35); each shard runs
  its own collect round for the shared dot;
- quorum members ``MBump`` other shards' closest processes with their
  clock so remote keys advance (tempo.rs:674-701, 1013-1049);
- per-shard commit clocks aggregate at the dot-owner process via
  ``MShardCommit`` → ``MShardAggregatedCommit`` (partial.rs:37-167);
  each shard coordinator then broadcasts the final-clock ``MCommit``
  inside its shard with its locally-held votes;
- the table executor's multi-key/multi-shard readiness protocol:
  per-key pending queues, ``StableAtShard`` fan-out once all local keys
  are stable, cross-shard messages through the closest process
  (executor/table/executor.rs:171-360);
- clients aggregate per-key result partials (task/client/pending.rs) —
  the engine core's ``cmd_parts`` completion counting.

Array encoding notes. A command is fully determined by (client, cseq):
its per-shard keys, touched-shard bitmask and part count live in ctx
tables (``cmd_skey``/``cmd_kmask``/``cmd_parts``, engine/spec.py
``_partial_tables``), so messages carry (client, cseq) instead of key
lists. Coordinator state is per (dot source, slot) — a process
coordinates foreign dots when it is the forwarded shard coordinator.
Parked executor entries keep the reference's invariant that at most
one entry per key (the queue head) has contributed to the
``rifl_to_stable_count`` / sent its ``StableAtShard`` fan-out.

Single-shard single-key lanes should use :class:`TempoDev` — its
narrower state arrays compile leaner; this class exists for
``shard_count > 1`` or ``keys_per_cmd > 1`` lanes and matches the
oracle exactly on tie-free schedules (tests/test_engine_partial.py).
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np

from ..core import (
    I32, cumsum_i32, emit, emit_broadcast, empty_outbox, oh_get, oh_set,
    oh_pack_pairs, oh_route, oh_set2, oh_take,
)
from ..dims import (
    ERR_CAPACITY, ERR_DOT, ERR_PROTO, ERR_SEQ, INF, SEQ_BOUND, EngineDims,
    dot_slot,
)
from ..iset import iset_add, iset_add_range
from .tempo import TempoDev, _bump, _det_add


class TempoPartialDev(TempoDev):
    SUBMIT = 0
    MCOLLECT = 1
    MCOLLECTACK = 2
    MCOMMIT = 3
    MDETACHED = 4
    MCONSENSUS = 5
    MCONSENSUSACK = 6
    MGC = 7
    MDRAIN = 8
    DETACH_DRAIN = 9
    MFWDSUBMIT = 10
    MBUMP = 11
    MSHARDCOMMIT = 12
    MSHARDAGG = 13
    STABLEAT = 14
    NUM_TYPES = 15
    TO_CLIENT = 16

    PERIODIC_ROWS = 3
    # the partial twin's handlers don't carry the safety-monitor hooks
    # (fuzzing is single-shard, like fault plans) — don't inherit the
    # base class's capability flag
    MONITORED = False

    def __init__(
        self,
        keys: int,
        shards: int = 2,
        keys_per_cmd: int = 2,
        pending_per_key: int = 32,
        detached_slots: int = 16,
        gap_slots: int = 8,
    ):
        super().__init__(keys, pending_per_key, detached_slots, gap_slots)
        self.S = shards
        self.KPC = keys_per_cmd

    # -- host-side builders -------------------------------------------

    def payload_width(self, n: int) -> int:
        # MCommit: [dsrc, dseq, clock, client, cseq, nv] then voter ids
        # and per-(key, voter) ranges over the FULL process-row axis
        # N = S*n (voters of one shard occupy n of the N columns)
        N = self.S * n
        return max(6 + N + 2 * self.KPC * N, N, 10)

    def fanout(self, n: int) -> int:
        """Outbox rows one handler may need: a shard broadcast occupies
        slots 0..N-1 (N = S*n), plus forward/bump/stable extras."""
        N = self.S * n
        return max(N + self.S + 2, 3 + self.S * self.KPC)

    def lane_ctx(self, config, dims: EngineDims, sorted_idx: np.ndarray):
        N, n, S = dims.N, config.n, config.shard_count
        fq_size, wq_size, threshold = config.tempo_quorum_sizes()
        fq = np.zeros((N, N), bool)
        wq = np.zeros((N, N), bool)
        # block-diagonal per shard: quorums never cross shards
        for s in range(S):
            for p in range(n):
                row = s * n + p
                for member in sorted_idx[p][:fq_size]:
                    fq[row, s * n + member] = True
                for member in sorted_idx[p][:wq_size]:
                    wq[row, s * n + member] = True
        return {
            "fast_quorum": fq,
            "write_quorum": wq,
            "fq_size": np.int32(fq_size),
            "wq_size": np.int32(wq_size),
            "threshold": np.int32(threshold),
            "clock_bump_mode": np.bool_(
                config.tempo_clock_bump_interval_ms is not None
            ),
        }

    def init_state(self, dims: EngineDims, ctx_np) -> Dict[str, np.ndarray]:
        N, D, C = dims.N, dims.D, dims.C
        K, PK, R, G, KPC = self.K, self.PK, self.R, self.G, self.KPC
        return {
            # key clocks + detached accumulator (protocol)
            "clocks": np.zeros((N, K), np.int32),
            "det": np.zeros((N, K, R, 2), np.int32),
            "max_commit_clock": np.zeros((N,), np.int32),
            # per-dot payload pointers (dot → (client, cseq))
            "seq_in_slot": np.zeros((N, N, D), np.int32),
            "client_of": np.zeros((N, N, D), np.int32),
            "cseq_of": np.zeros((N, N, D), np.int32),
            # coordinator per (dot source, slot): a process coordinates
            # its own dots plus forwarded dots of other shards' owners
            "own_seq": np.zeros((N,), np.int32),
            "ack_cnt": np.zeros((N, N, D), np.int32),
            "max_clock": np.zeros((N, N, D), np.int32),
            "max_cnt": np.zeros((N, N, D), np.int32),
            "slow_acks": np.zeros((N, N, D), np.int32),
            "votes_n": np.zeros((N, N, D), np.int32),
            "votes_by": np.zeros((N, N, D, N), np.int32),
            "votes_s": np.zeros((N, N, D, KPC, N), np.int32),
            "votes_e": np.zeros((N, N, D, KPC, N), np.int32),
            # shard-commit aggregation at the dot owner (own dots only)
            "shag_cnt": np.zeros((N, D), np.int32),
            "shag_max": np.zeros((N, D), np.int32),
            # buffered MBump max clock per dot (tempo.rs:674-701)
            "mbump_buf": np.zeros((N, N, D), np.int32),
            # table executor: votes + pending entries (phase 0 empty,
            # 1 awaiting clock stability, 2 parked queue head)
            "vote_front": np.zeros((N, K, N), np.int32),
            "vote_gaps": np.zeros((N, K, N, G, 2), np.int32),
            "pend_clock": np.zeros((N, K, PK), np.int32),
            "pend_src": np.zeros((N, K, PK), np.int32),
            "pend_seq": np.zeros((N, K, PK), np.int32),
            "pend_client": np.zeros((N, K, PK), np.int32),
            "pend_cseq": np.zeros((N, K, PK), np.int32),
            "pend_kmask": np.zeros((N, K, PK), np.int32),
            "pend_missing": np.zeros((N, K, PK), np.int32),
            "pend_phase": np.zeros((N, K, PK), np.int32),
            # rifl_to_stable_count (executor.rs:318-330): locally stable
            # key count of the client's in-flight rifl
            "stable_cnt": np.zeros((N, C), np.int32),
            "stable_cnt_seq": np.zeros((N, C), np.int32),
            # buffered StableAtShard per (key, client) with rifl guard
            "buf_cnt": np.zeros((N, K, C), np.int32),
            "buf_seq": np.zeros((N, K, C), np.int32),
            # committed-clock GC (sources span all shards; only my
            # shard's sources accumulate)
            "comm_front": np.zeros((N, N), np.int32),
            "comm_gaps": np.zeros((N, N, G, 2), np.int32),
            "others_frontier": np.zeros((N, N, N), np.int32),
            "seen": np.zeros((N, N), bool),
            "prev_stable": np.zeros((N, N), np.int32),
            "m_fast": np.zeros((N,), np.int32),
            "m_slow": np.zeros((N,), np.int32),
            "m_stable": np.zeros((N,), np.int32),
            "err": np.zeros((N,), np.int32),
        }

    # -- device handlers ----------------------------------------------

    def ready(self, ps, msg, me, ctx, dims: EngineDims):
        """Requeue messages that overtook their prerequisite under
        reordering (same contract as TempoDev.ready)."""
        t = msg["mtype"]
        dsrc, dseq = msg["payload"][0], msg["payload"][1]
        slot = dot_slot(dseq, dims)
        free = oh_get(oh_get(ps["seq_in_slot"], dsrc), slot) == 0
        have = (
            oh_get(oh_get(ps["seq_in_slot"], dsrc), slot) == dseq
        )
        ok = jnp.where(t == self.MCOLLECT, free, True)
        needs_payload = (
            (t == self.MCOMMIT)
            | (t == self.MCONSENSUS)
            | (t == self.MSHARDAGG)
            | (t == self.MSHARDCOMMIT)
        )
        return jnp.where(needs_payload, have, ok)

    def handle(self, ps, msg, me, now, ctx, dims: EngineDims):
        def _noop(ps, msg):
            return ps, empty_outbox(dims)

        branches = [
            lambda ps, msg: _p_submit(self, ps, msg, me, ctx, dims),
            lambda ps, msg: _p_mcollect(self, ps, msg, me, ctx, dims),
            lambda ps, msg: _p_mcollectack(self, ps, msg, me, ctx, dims),
            lambda ps, msg: _p_mcommit(self, ps, msg, me, ctx, dims),
            lambda ps, msg: _p_mdetached(self, ps, msg, me, ctx, dims),
            lambda ps, msg: _p_mconsensus(self, ps, msg, me, ctx, dims),
            lambda ps, msg: _p_mconsensusack(self, ps, msg, me, ctx, dims),
            lambda ps, msg: _p_mgc(self, ps, msg, me, ctx, dims),
            lambda ps, msg: _p_mdrain(self, ps, msg, me, ctx, dims),
            lambda ps, msg: _p_detach_drain(self, ps, msg, me, ctx, dims),
            lambda ps, msg: _p_mfwdsubmit(self, ps, msg, me, ctx, dims),
            lambda ps, msg: _p_mbump(self, ps, msg, me, ctx, dims),
            lambda ps, msg: _p_mshardcommit(self, ps, msg, me, ctx, dims),
            lambda ps, msg: _p_mshardagg(self, ps, msg, me, ctx, dims),
            lambda ps, msg: _p_stableat(self, ps, msg, me, ctx, dims),
            _noop,
        ]
        idx = jnp.clip(msg["mtype"], 0, self.NUM_TYPES)
        return jax.lax.switch(idx, branches, ps, msg)

    def periodic(self, ps, fire, me, now, ctx, dims: EngineDims):
        """GC frontier broadcast (within shard), real-time clock bump,
        detached-send kick-off — TempoDev.periodic with a shard-aware
        broadcast base."""
        base = _shard_base(ctx, me)
        ob = emit_broadcast(
            empty_outbox(dims),
            self.MGC,
            ps["comm_front"],
            ctx["n"],
            me,
            exclude_me=True,
            base=base,
        )
        ob = dict(ob, valid=ob["valid"] & fire[0])

        # micros conversion saturates at INF — an i32 wrap would lower
        # every key clock (see tempo.py periodic / lint GL001)
        micros = jnp.where(now >= INF // 1000, INF, now * 1000)
        min_clock = jnp.maximum(ps["max_commit_clock"], micros)
        ps = _detached_all_p(self, ps, min_clock, fire[1])

        has = jnp.any(ps["det"][:, :, 0] > 0)
        ob = emit(
            ob,
            dims.N,
            me,
            self.DETACH_DRAIN,
            [0],
            valid=fire[2] & has,
        )
        return ps, ob


# ----------------------------------------------------------------------
# shared helpers
# ----------------------------------------------------------------------


def _shard_base(ctx, me):
    return oh_get(ctx["shard_of"], me) * ctx["n"]


def _shard_mask(ctx, me, dims):
    """Bool [N]: live processes of my shard."""
    procs = jnp.arange(dims.N, dtype=I32)
    s_me = oh_get(ctx["shard_of"], me)
    return ctx["shard_of"] == s_me  # pad rows carry shard id S (never live)


def _cmd_tables(ctx, client, cseq):
    """(kmask, skey [S, KPC]) of command (client, cseq) from ctx."""
    T = ctx["cmd_kmask"].shape[1]
    j = jnp.minimum(cseq, T - 1)
    kmask = oh_get(oh_get(ctx["cmd_kmask"], client), j)
    skey = oh_get(oh_get(ctx["cmd_skey"], client), j)  # [S, KPC]
    return kmask, skey


def _popcount(kmask, S: int):
    return jnp.sum(
        (kmask[None] >> jnp.arange(S, dtype=I32)) & 1, dtype=I32
    )


def _my_keys(pp, ctx, me, skey):
    """This shard's keys of the command: [KPC] (-1 pad)."""
    s_me = oh_get(ctx["shard_of"], me)
    return oh_get(skey, s_me)


def _proposal(pp, ps, keys, min_clock):
    """key_clocks.proposal (sequential.rs:36-47) over up to KPC keys:
    clock = max(min_clock, highest key clock + 1); each key votes its
    vacated range. Returns (ps, clock, vs [KPC], ve [KPC])."""
    valid = keys >= 0
    cur = jnp.where(valid, oh_take(ps["clocks"], keys), 0)  # [KPC]
    clock = jnp.maximum(min_clock, jnp.max(jnp.where(valid, cur, 0)) + 1)
    vs = jnp.where(valid & (cur < clock), cur + 1, 0)
    ve = jnp.where(valid & (cur < clock), clock, 0)
    clocks = ps["clocks"]
    for d in range(pp.KPC):
        clocks = oh_set(
            clocks, jnp.where(valid[d], keys[d], -1), clock
        )
    return dict(ps, clocks=clocks), clock, vs, ve


def _detached_keys(pp, ps, keys, up_to, enable):
    """key_clocks.detached over the command's local keys."""
    for d in range(pp.KPC):
        ps = _bump(
            pp, ps, jnp.where(keys[d] >= 0, keys[d], -1), up_to,
            jnp.asarray(enable, bool) & (keys[d] >= 0),
        )
    return ps


def _detached_all_p(pp, ps, min_clock, enable):
    """detached_all (vectorized over keys), as in TempoDev."""
    clocks = ps["clocks"]
    det = ps["det"]
    do = jnp.asarray(enable, bool) & (clocks < min_clock)
    free = det[:, :, 0] == 0
    slot = jnp.argmax(free, axis=1)
    overflow = do & ~jnp.any(free, axis=1)
    slot_w = jnp.where(do & ~overflow, slot, pp.R)
    hit = jnp.arange(pp.R, dtype=I32)[None, :] == slot_w[:, None]
    vals = jnp.stack(
        [clocks + 1, jnp.broadcast_to(min_clock, clocks.shape)], axis=-1
    )
    det = jnp.where(hit[:, :, None], vals[:, None, :], det)
    return dict(
        ps,
        det=det,
        clocks=jnp.where(do, min_clock, clocks),
        err=ps["err"] | ERR_CAPACITY * jnp.any(overflow),
    )


def _set_votes_row(arr, dsrc, slot, idx, vals):
    """arr [Nsrc, D, KPC, NV]: write vals [KPC] at voter column idx."""
    row = oh_get(oh_get(arr, dsrc), slot)  # [KPC, NV]
    NV = row.shape[1]
    hit = jnp.arange(NV, dtype=I32)[None, :] == idx
    row = jnp.where(hit, vals[:, None], row)
    return oh_set2(arr, dsrc, slot, row)


def _get2(arr, i, j):
    return oh_get(oh_get(arr, i), j)


def _bump_field2(ps, name, dsrc, slot, value):
    return oh_set2(ps[name], dsrc, slot, value)


# ----------------------------------------------------------------------
# submit / forward / collect
# ----------------------------------------------------------------------


def _p_start(pp, ps, dsrc, dseq, client, cseq, me, ctx, dims, forward):
    """Shared coordinator start (tempo.rs:267-339 at the target shard;
    the MForwardSubmit path runs the same flow without re-forwarding,
    partial.rs:8-35)."""
    kmask, skey = _cmd_tables(ctx, client, cseq)
    keys = _my_keys(pp, ctx, me, skey)
    slot = dot_slot(dseq, dims)

    ps, clock, vs, ve = _proposal(pp, ps, keys, 0)
    # reset this dot's coordinator aggregation state
    for name in ("ack_cnt", "max_clock", "max_cnt", "slow_acks"):
        ps = dict(ps, **{name: oh_set2(ps[name], dsrc, slot, 0)})
    ps = dict(
        ps,
        votes_n=oh_set2(ps["votes_n"], dsrc, slot, 1),
        votes_by=_set_votes_row3(ps["votes_by"], dsrc, slot, 0, me),
        votes_s=_set_votes_row(ps["votes_s"], dsrc, slot, 0, vs),
        votes_e=_set_votes_row(ps["votes_e"], dsrc, slot, 0, ve),
    )
    base = _shard_base(ctx, me)
    ob = emit_broadcast(
        empty_outbox(dims),
        pp.MCOLLECT,
        [dsrc, dseq, client, cseq, clock],
        ctx["n"],
        base=base,
    )
    if forward:
        # own dot: reset the shard aggregation + forward to the closest
        # process of every other touched shard
        ps = dict(
            ps,
            shag_cnt=oh_set(ps["shag_cnt"], slot, 0),
            shag_max=oh_set(ps["shag_max"], slot, 0),
        )
        s_me = oh_get(ctx["shard_of"], me)
        for s in range(pp.S):
            touched = ((kmask >> s) & 1) == 1
            ob = emit(
                ob,
                dims.N + s,
                oh_get(oh_get(ctx["closest"], me), jnp.int32(s)),
                pp.MFWDSUBMIT,
                [dsrc, dseq, client, cseq],
                valid=touched & (s != s_me),
            )
    return ps, ob


def _set_votes_row3(arr, dsrc, slot, idx, val):
    """arr [Nsrc, D, NV]: write scalar val at voter column idx."""
    row = _get2(arr, dsrc, slot)
    NV = row.shape[0]
    hit = jnp.arange(NV, dtype=I32) == idx
    return oh_set2(arr, dsrc, slot, jnp.where(hit, val, row))


def _p_submit(pp, ps, msg, me, ctx, dims):
    client, cseq = msg["payload"][0], msg["payload"][1]
    dseq = ps["own_seq"] + 1
    ps = dict(
        ps,
        own_seq=dseq,
        err=ps["err"] | ERR_SEQ * (dseq >= SEQ_BOUND),
    )
    return _p_start(
        pp, ps, me, dseq, client, cseq, me, ctx, dims, forward=True
    )


def _p_mfwdsubmit(pp, ps, msg, me, ctx, dims):
    dsrc, dseq, client, cseq = (
        msg["payload"][0],
        msg["payload"][1],
        msg["payload"][2],
        msg["payload"][3],
    )
    return _p_start(
        pp, ps, dsrc, dseq, client, cseq, me, ctx, dims, forward=False
    )


def _p_mcollect(pp, ps, msg, me, ctx, dims):
    """tempo.rs:341-459 with the dot source decoupled from the message
    sender (the shard coordinator)."""
    coord = msg["src"]
    dsrc, dseq, client, cseq, rclock = (
        msg["payload"][0],
        msg["payload"][1],
        msg["payload"][2],
        msg["payload"][3],
        msg["payload"][4],
    )
    slot = dot_slot(dseq, dims)
    dirty = _get2(ps["seq_in_slot"], dsrc, slot) != 0
    ps = dict(
        ps,
        err=ps["err"] | ERR_DOT * dirty,
        seq_in_slot=oh_set2(ps["seq_in_slot"], dsrc, slot, dseq),
        client_of=oh_set2(ps["client_of"], dsrc, slot, client),
        cseq_of=oh_set2(ps["cseq_of"], dsrc, slot, cseq),
    )
    in_q = oh_get(oh_get(ctx["fast_quorum"], coord), me)
    from_self = coord == me

    kmask, skey = _cmd_tables(ctx, client, cseq)
    keys = _my_keys(pp, ctx, me, skey)

    # quorum member: proposal with the remote clock as floor (the
    # self-collect keeps the original clock, no votes)
    ps2, pclock, vs, ve = _proposal(pp, ps, keys, rclock)
    propose = in_q & ~from_self
    ps = jax.tree_util.tree_map(
        lambda a, b: jnp.where(propose, a, b), ps2, ps
    )
    clock = jnp.where(from_self, rclock, pclock)
    vs = jnp.where(propose, vs, 0)
    ve = jnp.where(propose, ve, 0)

    # apply a buffered MBump (tempo.rs:371-373: after the proposal)
    bump_to = _get2(ps["mbump_buf"], dsrc, slot)
    ps = _detached_keys(pp, ps, keys, bump_to, in_q & (bump_to > 0))
    ps = dict(
        ps, mbump_buf=oh_set2(ps["mbump_buf"], dsrc, slot, 0)
    )

    pay = jnp.zeros((dims.P,), I32)
    pay = pay.at[0].set(dsrc).at[1].set(dseq).at[2].set(clock)
    pay = jax.lax.dynamic_update_slice(
        pay, jnp.stack([vs, ve], axis=1).reshape(-1), (3,)
    )
    ob = emit(
        empty_outbox(dims), 0, coord, pp.MCOLLECTACK, pay, valid=in_q
    )
    # MBump the other shards' closest processes (tempo.rs:1013-1049)
    s_me = oh_get(ctx["shard_of"], me)
    for s in range(pp.S):
        touched = ((kmask >> s) & 1) == 1
        ob = emit(
            ob,
            1 + s,
            oh_get(oh_get(ctx["closest"], me), jnp.int32(s)),
            pp.MBUMP,
            [dsrc, dseq, clock],
            valid=in_q & touched & (s != s_me),
        )
    return ps, ob


def _p_mbump(pp, ps, msg, me, ctx, dims):
    """tempo.rs:674-701: bump the command's local keys, or buffer the
    max clock until the payload arrives."""
    dsrc, dseq, clock = (
        msg["payload"][0],
        msg["payload"][1],
        msg["payload"][2],
    )
    slot = dot_slot(dseq, dims)
    have = _get2(ps["seq_in_slot"], dsrc, slot) == dseq
    client = _get2(ps["client_of"], dsrc, slot)
    cseq = _get2(ps["cseq_of"], dsrc, slot)
    _, skey = _cmd_tables(ctx, client, cseq)
    keys = _my_keys(pp, ctx, me, skey)
    ps = _detached_keys(pp, ps, keys, clock, have)
    buffered = jnp.maximum(_get2(ps["mbump_buf"], dsrc, slot), clock)
    ps = dict(
        ps,
        mbump_buf=oh_set2(
            ps["mbump_buf"], dsrc, slot,
            jnp.where(have, 0, buffered),
        ),
    )
    return ps, empty_outbox(dims)

# ----------------------------------------------------------------------
# collect-ack / commit paths
# ----------------------------------------------------------------------


def _p_mcollectack(pp, ps, msg, me, ctx, dims):
    """tempo.rs:461-554 at the shard coordinator (possibly of a foreign
    dot)."""
    src = msg["src"]
    dsrc, dseq, clock = (
        msg["payload"][0],
        msg["payload"][1],
        msg["payload"][2],
    )
    vsve = jax.lax.dynamic_slice(
        msg["payload"], (3,), (2 * pp.KPC,)
    ).reshape(pp.KPC, 2)
    vs, ve = vsve[:, 0], vsve[:, 1]
    slot = dot_slot(dseq, dims)

    # late/duplicate acks: the exact-count trigger below ignores them
    nv = _get2(ps["votes_n"], dsrc, slot)
    has_vote = jnp.any(vs > 0)
    fits = has_vote & (nv < dims.N)
    widx = jnp.where(fits, nv, dims.N)
    ps = dict(
        ps,
        votes_by=_set_votes_row3(ps["votes_by"], dsrc, slot, widx, src),
        votes_s=_set_votes_row(ps["votes_s"], dsrc, slot, widx, vs),
        votes_e=_set_votes_row(ps["votes_e"], dsrc, slot, widx, ve),
        votes_n=oh_set2(
            ps["votes_n"], dsrc, slot, nv + fits.astype(I32)
        ),
        err=ps["err"] | ERR_CAPACITY * (has_vote & ~fits),
    )

    old_max = _get2(ps["max_clock"], dsrc, slot)
    new_max = jnp.maximum(old_max, clock)
    new_cnt = jnp.where(
        clock > old_max,
        1,
        _get2(ps["max_cnt"], dsrc, slot) + (clock == old_max),
    )
    cnt = _get2(ps["ack_cnt"], dsrc, slot) + 1
    ps = dict(
        ps,
        max_clock=oh_set2(ps["max_clock"], dsrc, slot, new_max),
        max_cnt=oh_set2(ps["max_cnt"], dsrc, slot, new_cnt),
        ack_cnt=oh_set2(ps["ack_cnt"], dsrc, slot, cnt),
    )

    # bump own keys to the running max (tempo.rs:497-514)
    client = _get2(ps["client_of"], dsrc, slot)
    cseq = _get2(ps["cseq_of"], dsrc, slot)
    kmask, skey = _cmd_tables(ctx, client, cseq)
    keys = _my_keys(pp, ctx, me, skey)
    ps = _detached_keys(pp, ps, keys, new_max, src != me)

    all_acks = cnt == ctx["fq_size"]
    fast = all_acks & (new_cnt >= ctx["f"])
    slow = all_acks & ~fast
    ps = dict(
        ps,
        m_fast=ps["m_fast"] + fast.astype(I32),
        m_slow=ps["m_slow"] + slow.astype(I32),
    )

    ob = _p_commit_actions(
        pp, ps, me, dsrc, dseq, new_max, client, cseq, kmask, ctx, dims,
        fast,
    )
    base = _shard_base(ctx, me)
    obc = emit_broadcast(
        empty_outbox(dims),
        pp.MCONSENSUS,
        [dsrc, dseq, new_max],
        ctx["n"],
        base=base,
    )
    procs = jnp.arange(dims.F, dtype=I32) + base
    wq = oh_take(
        oh_get(ctx["write_quorum"], me),
        jnp.clip(procs, 0, dims.N - 1),
    )
    obc = dict(obc, valid=obc["valid"] & slow & wq)
    ob = jax.tree_util.tree_map(
        lambda a, b: jnp.where(
            fast.reshape((-1,) + (1,) * (a.ndim - 1)) if a.ndim > 1 else fast,
            a,
            b,
        ),
        ob,
        obc,
    )
    return ps, ob


def _p_commit_actions(
    pp, ps, me, dsrc, dseq, clock, client, cseq, kmask, ctx, dims, valid
):
    """partial.rs:37-101: single-shard commands broadcast MCommit in
    this shard; multi-shard commands send MShardCommit to the dot owner
    and keep the votes parked for the MShardAggregatedCommit."""
    nsh = _popcount(kmask, pp.S)
    single = nsh == 1
    ob_commit = _p_commit_broadcast(
        pp, ps, me, dsrc, dseq, clock, client, cseq, ctx, dims,
        jnp.asarray(valid, bool) & single,
    )
    ob_shard = emit(
        empty_outbox(dims),
        0,
        dsrc,
        pp.MSHARDCOMMIT,
        [dsrc, dseq, clock],
        valid=jnp.asarray(valid, bool) & ~single,
    )
    return jax.tree_util.tree_map(
        lambda a, b: jnp.where(
            single.reshape((-1,) + (1,) * (a.ndim - 1))
            if a.ndim > 1
            else single,
            a,
            b,
        ),
        ob_commit,
        ob_shard,
    )


def _p_commit_broadcast(
    pp, ps, me, dsrc, dseq, clock, client, cseq, ctx, dims, valid
):
    """MCommit carrying this shard's aggregated votes."""
    slot = dot_slot(dseq, dims)
    N, P = dims.N, dims.P
    pay = jnp.zeros((P,), I32)
    pay = (
        pay.at[0].set(dsrc).at[1].set(dseq).at[2].set(clock)
        .at[3].set(client).at[4].set(cseq)
        .at[5].set(_get2(ps["votes_n"], dsrc, slot))
    )
    by = _get2(ps["votes_by"], dsrc, slot)          # [NV]
    vs = _get2(ps["votes_s"], dsrc, slot)           # [KPC, NV]
    ve = _get2(ps["votes_e"], dsrc, slot)
    pay = jax.lax.dynamic_update_slice(pay, by, (6,))
    pay = jax.lax.dynamic_update_slice(
        pay,
        jnp.stack([vs, ve], axis=2).reshape(-1),    # KPC*NV*(s,e)
        (6 + N,),
    )
    base = _shard_base(ctx, me)
    ob = emit_broadcast(
        empty_outbox(dims), pp.MCOMMIT, pay, ctx["n"], base=base
    )
    return dict(ob, valid=ob["valid"] & jnp.asarray(valid, bool))


def _p_mshardcommit(pp, ps, msg, me, ctx, dims):
    """partial.rs:103-142 at the dot owner: aggregate per-shard commit
    clocks; when every touched shard reported, send the aggregated
    clock back to the participants (the shard coordinators)."""
    dsrc, dseq, clock = (
        msg["payload"][0],
        msg["payload"][1],
        msg["payload"][2],
    )
    slot = dot_slot(dseq, dims)
    ps = dict(ps, err=ps["err"] | ERR_PROTO * (dsrc != me))
    smax = jnp.maximum(oh_get(ps["shag_max"], slot), clock)
    scnt = oh_get(ps["shag_cnt"], slot) + 1
    ps = dict(
        ps,
        shag_max=oh_set(ps["shag_max"], slot, smax),
        shag_cnt=oh_set(ps["shag_cnt"], slot, scnt),
    )
    client = _get2(ps["client_of"], me, slot)
    cseq = _get2(ps["cseq_of"], me, slot)
    kmask, _ = _cmd_tables(ctx, client, cseq)
    nsh = _popcount(kmask, pp.S)
    done = scnt == nsh
    # participants: me plus the closest process of every other touched
    # shard — exactly who received the MForwardSubmit
    ob = emit(
        empty_outbox(dims),
        0,
        me,
        pp.MSHARDAGG,
        [dsrc, dseq, smax],
        valid=done,
    )
    s_me = oh_get(ctx["shard_of"], me)
    for s in range(pp.S):
        touched = ((kmask >> s) & 1) == 1
        ob = emit(
            ob,
            1 + s,
            oh_get(oh_get(ctx["closest"], me), jnp.int32(s)),
            pp.MSHARDAGG,
            [dsrc, dseq, smax],
            valid=done & touched & (s != s_me),
        )
    return ps, ob


def _p_mshardagg(pp, ps, msg, me, ctx, dims):
    """partial.rs:144-167 at each shard coordinator: broadcast the
    final-clock MCommit inside this shard with the locally-held
    votes."""
    dsrc, dseq, clock = (
        msg["payload"][0],
        msg["payload"][1],
        msg["payload"][2],
    )
    slot = dot_slot(dseq, dims)
    client = _get2(ps["client_of"], dsrc, slot)
    cseq = _get2(ps["cseq_of"], dsrc, slot)
    ob = _p_commit_broadcast(
        pp, ps, me, dsrc, dseq, clock, client, cseq, ctx, dims, True
    )
    return ps, ob

# ----------------------------------------------------------------------
# commit receiver + table executor
# ----------------------------------------------------------------------


def _stable_clock_p(pp, ps, key, ctx, dims, me):
    """Threshold-ranked frontier over this shard's voters
    (table/mod.rs:243-263), rank computed over the shard's process
    rows."""
    fronts = oh_get(ps["vote_front"], key)  # [N]
    procs = jnp.arange(dims.N, dtype=I32)
    mine = _shard_mask(ctx, me, dims)
    masked = jnp.where(mine, fronts, INF)
    rank = jnp.sum(
        (masked[None, :] < masked[:, None])
        | (
            (masked[None, :] == masked[:, None])
            & (procs[None, :] < procs[:, None])
        ),
        axis=1,
    )
    # the (n - threshold)-th smallest among this shard's voters: padded
    # and foreign rows sit at INF, so they always rank above the n live
    # shard rows and the index lands inside them
    k = ctx["n"] - ctx["threshold"]
    return jnp.sum(jnp.where(rank == k, masked, 0))


def _vote_add_p(pp, ps, key, voter, start, end, enable):
    front = _get2(ps["vote_front"], key, voter)
    gaps = _get2(ps["vote_gaps"], key, voter)
    front, gaps, overflow = iset_add_range(front, gaps, start, end, enable)
    return dict(
        ps,
        vote_front=oh_set2(ps["vote_front"], key, voter, front),
        vote_gaps=oh_set2(ps["vote_gaps"], key, voter, gaps),
        err=ps["err"] | ERR_CAPACITY * overflow,
    )


def _pend_insert_p(pp, ps, key, clock, dsrc, dseq, client, cseq, kmask,
                   missing, enable):
    """One per-key pending entry (phase 1: awaiting clock stability)."""
    slots = oh_get(ps["pend_clock"], key)
    free = slots == 0
    idx = jnp.argmax(free)
    overflow = jnp.asarray(enable, bool) & ~jnp.any(free)
    widx = jnp.where(
        jnp.asarray(enable, bool) & ~overflow, idx, pp.PK
    )
    return dict(
        ps,
        pend_clock=oh_set2(ps["pend_clock"], key, widx, clock),
        pend_src=oh_set2(ps["pend_src"], key, widx, dsrc),
        pend_seq=oh_set2(ps["pend_seq"], key, widx, dseq),
        pend_client=oh_set2(ps["pend_client"], key, widx, client),
        pend_cseq=oh_set2(ps["pend_cseq"], key, widx, cseq),
        pend_kmask=oh_set2(ps["pend_kmask"], key, widx, kmask),
        pend_missing=oh_set2(ps["pend_missing"], key, widx, missing),
        pend_phase=oh_set2(ps["pend_phase"], key, widx, 1),
        err=ps["err"] | ERR_CAPACITY * overflow,
    )


def _p_mcommit(pp, ps, msg, me, ctx, dims):
    """tempo.rs:556-654: feed the votes table per local key, insert the
    per-key pending entries, record the commit for GC (own-shard dots
    only — foreign dots free their slot immediately, the gc_single
    path), then kick one drain per key."""
    # the dot source rides in a payload word; clamp it to a process id
    # so the drain's (src, seq) i32 packing (src * SEQ_BOUND + seq)
    # cannot wrap on an out-of-range word (lint GL001) — mirrors
    # tempo._mcommit
    dsrc = jnp.clip(msg["payload"][0], 0, dims.N - 1)
    dseq = msg["payload"][1]
    clock = msg["payload"][2]
    client = msg["payload"][3]
    cseq = msg["payload"][4]
    nv = msg["payload"][5]
    slot = dot_slot(dseq, dims)
    have = _get2(ps["seq_in_slot"], dsrc, slot) == dseq
    ps = dict(ps, err=ps["err"] | ERR_PROTO * ~have)

    kmask, skey = _cmd_tables(ctx, client, cseq)
    keys = _my_keys(pp, ctx, me, skey)
    nsh = _popcount(kmask, pp.S)

    bump_mode = ctx["clock_bump_mode"]
    ps = dict(
        ps,
        max_commit_clock=jnp.where(
            bump_mode,
            jnp.maximum(ps["max_commit_clock"], clock),
            ps["max_commit_clock"],
        ),
    )
    ps = _detached_keys(pp, ps, keys, clock, ~bump_mode)

    # attached votes: payload rows [6..6+N) voters, then (s, e) pairs
    # per (kpc, voter)
    N = dims.N
    idxs = 6 + jnp.arange(N, dtype=I32)
    bys = oh_take(msg["payload"], idxs)
    enable_v = jnp.arange(N, dtype=I32) < nv
    bys = jnp.where(enable_v, bys, N)
    for d in range(pp.KPC):
        key_d = keys[d]
        s_idx = 6 + N + 2 * (d * N + jnp.arange(N, dtype=I32))
        starts = oh_take(msg["payload"], s_idx)
        ends = oh_take(msg["payload"], s_idx + 1)
        # voters are distinct: route ranges to per-voter lanes with
        # one-hot sums, then one vmapped interval-set union
        oh_by = bys[:, None] == jnp.arange(N, dtype=I32)[None, :]
        per_s = oh_route(bys, starts, N)
        per_e = oh_route(bys, ends, N)
        per_en = (
            jnp.any(oh_by & enable_v[:, None], axis=0)
            & (per_s > 0)
            & (key_d >= 0)
        )
        fronts, gaps, ovf = jax.vmap(iset_add_range)(
            oh_get(ps["vote_front"], key_d),
            oh_get(ps["vote_gaps"], key_d),
            per_s,
            per_e,
            per_en,
        )
        ps = dict(
            ps,
            vote_front=oh_set(ps["vote_front"], key_d, fronts),
            vote_gaps=oh_set(ps["vote_gaps"], key_d, gaps),
            err=ps["err"] | ERR_CAPACITY * jnp.any(ovf),
        )
        ps = _pend_insert_p(
            pp, ps, key_d, clock, dsrc, dseq, client, cseq, kmask, nsh,
            key_d >= 0,
        )

    # GC: only dots of this shard feed the committed clock
    # (tempo.rs:463-469); foreign dots free their window slot now
    my_dot = oh_get(ctx["shard_of"], dsrc) == oh_get(ctx["shard_of"], me)
    cf, cg, overflow = iset_add(
        oh_get(ps["comm_front"], dsrc),
        oh_get(ps["comm_gaps"], dsrc),
        dseq,
        enable=my_dot,
    )
    ps = dict(
        ps,
        comm_front=oh_set(ps["comm_front"], dsrc, cf),
        comm_gaps=oh_set(ps["comm_gaps"], dsrc, cg),
        err=ps["err"] | ERR_CAPACITY * overflow,
        seq_in_slot=oh_set2(
            ps["seq_in_slot"], dsrc, slot,
            jnp.where(my_dot, dseq, 0),
        ),
    )

    # one zero-delay drain per local key (same-instant, prio)
    ob = empty_outbox(dims)
    for d in range(pp.KPC):
        ob = emit(
            ob, d, me, pp.MDRAIN, [keys[d]], valid=keys[d] >= 0
        )
    return ps, ob


def _p_mdetached(pp, ps, msg, me, ctx, dims):
    """tempo.rs:703-716: union the sender's detached ranges, drain."""
    voter = msg["src"]
    key = msg["payload"][0]
    nr = msg["payload"][1]
    for i in range(pp.detached_per_msg(dims)):
        s = msg["payload"][2 + 2 * i]
        e = msg["payload"][2 + 2 * i + 1]
        ps = _vote_add_p(pp, ps, key, voter, s, e, i < nr)
    return _p_drain(pp, ps, key, me, ctx, dims, empty_outbox(dims))


def _p_mconsensus(pp, ps, msg, me, ctx, dims):
    """tempo.rs:718-773 (initial ballot always wins)."""
    dsrc, dseq, clock = (
        msg["payload"][0],
        msg["payload"][1],
        msg["payload"][2],
    )
    slot = dot_slot(dseq, dims)
    has_cmd = _get2(ps["seq_in_slot"], dsrc, slot) == dseq
    client = _get2(ps["client_of"], dsrc, slot)
    cseq = _get2(ps["cseq_of"], dsrc, slot)
    _, skey = _cmd_tables(ctx, client, cseq)
    keys = _my_keys(pp, ctx, me, skey)
    ps = _detached_keys(pp, ps, keys, clock, has_cmd)
    ob = emit(
        empty_outbox(dims),
        0,
        msg["src"],
        pp.MCONSENSUSACK,
        [dsrc, dseq],
    )
    return ps, ob


def _p_mconsensusack(pp, ps, msg, me, ctx, dims):
    """tempo.rs:775-812: f+1 accepts choose the slow-path clock."""
    dsrc, dseq = msg["payload"][0], msg["payload"][1]
    slot = dot_slot(dseq, dims)
    cnt = _get2(ps["slow_acks"], dsrc, slot) + 1
    chosen = cnt == ctx["wq_size"]
    ps = dict(
        ps, slow_acks=oh_set2(ps["slow_acks"], dsrc, slot, cnt)
    )
    client = _get2(ps["client_of"], dsrc, slot)
    cseq = _get2(ps["cseq_of"], dsrc, slot)
    kmask, _ = _cmd_tables(ctx, client, cseq)
    ob = _p_commit_actions(
        pp, ps, me, dsrc, dseq,
        _get2(ps["max_clock"], dsrc, slot),
        client, cseq, kmask, ctx, dims, chosen,
    )
    return ps, ob


def _p_mgc(pp, ps, msg, me, ctx, dims):
    """Committed-clock GC within this shard (tempo.rs:897-970)."""
    N = dims.N
    s = msg["src"]
    frontier = msg["payload"][:N]
    of = oh_set(
        ps["others_frontier"],
        s,
        jnp.maximum(oh_get(ps["others_frontier"], s), frontier),
    )
    seen = oh_set(ps["seen"], s, True)
    mine = _shard_mask(ctx, me, dims)
    procs = jnp.arange(N, dtype=I32)
    others = mine & (procs != me)
    ready = jnp.all(seen | ~others)
    min_others = jnp.min(jnp.where(others[:, None], of, INF), axis=0)
    stable = jnp.minimum(ps["comm_front"], min_others)
    stable = jnp.where(ready & mine, stable, 0)
    delta = jnp.maximum(stable - ps["prev_stable"], 0)
    prev_stable = jnp.maximum(ps["prev_stable"], stable)
    freed = (ps["seq_in_slot"] > 0) & (
        ps["seq_in_slot"] <= prev_stable[:, None]
    )
    ps = dict(
        ps,
        others_frontier=of,
        seen=seen,
        prev_stable=prev_stable,
        m_stable=ps["m_stable"] + jnp.sum(delta),
        seq_in_slot=jnp.where(freed, 0, ps["seq_in_slot"]),
    )
    return ps, empty_outbox(dims)


def _p_mdrain(pp, ps, msg, me, ctx, dims):
    return _p_drain(
        pp, ps, msg["payload"][0], me, ctx, dims, empty_outbox(dims)
    )


def _p_detach_drain(pp, ps, msg, me, ctx, dims):
    """One key's detached ranges to this shard's processes, chained
    (TempoDev._detach_drain with a shard-aware broadcast)."""
    det = ps["det"]
    has = det[:, :, 0] > 0
    key_has = jnp.any(has, axis=1)
    key = jnp.argmax(key_has)
    any_key = jnp.any(key_has)

    row = oh_get(det, key)
    occ = row[:, 0] > 0
    order = cumsum_i32(occ)
    per_msg = pp.detached_per_msg(dims)
    take = occ & (order <= per_msg)
    nr = jnp.sum(take)

    pay = jnp.zeros((dims.P,), I32)
    pay = pay.at[0].set(key)
    pay = pay.at[1].set(nr)
    lo = jnp.where(take, 2 + 2 * (order - 1), dims.P)
    pay = oh_pack_pairs(pay, lo, row[:, 0], row[:, 1])

    det = oh_set(det, key, jnp.where(take[:, None], 0, row))
    ps = dict(ps, det=det)

    base = _shard_base(ctx, me)
    ob = emit_broadcast(
        empty_outbox(dims), pp.MDETACHED, pay, ctx["n"], base=base
    )
    ob = dict(ob, valid=ob["valid"] & any_key)
    more = jnp.any(det[:, :, 0] > 0)
    ob = emit(
        ob, dims.N, me, pp.DETACH_DRAIN, [0], valid=any_key & more
    )
    return ps, ob

# ----------------------------------------------------------------------
# the per-key pending queue (executor.rs:171-360)
#
# Invariant mirrored from the reference: at most one entry per key (the
# parked queue head, phase 2) has been *processed* — contributed to
# rifl_to_stable_count and (when the count completed) sent its
# StableAtShard fan-out. Everything behind it waits raw in phase 1;
# the drain promotes entries in (clock, dot) order, which is stability
# order because the stable clock only grows.
# ----------------------------------------------------------------------


def _p_execute(pp, ps, key, idx, client, me, ctx, dims, ob, enable):
    """Execute the entry: emit the per-key result partial to the client
    when this process is the client's connected process of this shard
    (run/prelude.rs:35-40 registration), free the slot, chain."""
    do = jnp.asarray(enable, bool)
    s_me = oh_get(ctx["shard_of"], me)
    connected = oh_get(oh_get(ctx["client_attach_s"], client), s_me) == me
    ob = emit(
        ob,
        0,
        dims.N + client,
        pp.TO_CLIENT,
        [0],
        valid=do & connected,
    )
    widx = jnp.where(do, idx, pp.PK)
    ps = dict(
        ps,
        pend_clock=oh_set2(ps["pend_clock"], key, widx, 0),
        pend_phase=oh_set2(ps["pend_phase"], key, widx, 0),
    )
    return ps, ob


def _p_drain(pp, ps, key, me, ctx, dims, ob):
    """Promote/execute this key's lowest-order ready entry — the array
    form of stable_ops + _send_stable_or_execute +
    _execute_single_or_mark_stable (executor.rs:234-360)."""
    stable = _stable_clock_p(pp, ps, key, ctx, dims, me)
    clocks = oh_get(ps["pend_clock"], key)      # [PK]
    phase = oh_get(ps["pend_phase"], key)
    srcs = oh_get(ps["pend_src"], key)
    seqs = oh_get(ps["pend_seq"], key)
    eligible = ((phase == 1) & (clocks > 0) & (clocks <= stable)) | (
        phase == 2
    )
    any_el = jnp.any(eligible)
    cmin = jnp.min(jnp.where(eligible, clocks, INF))
    tie = eligible & (clocks == cmin)
    packed = srcs * SEQ_BOUND + seqs
    idx = jnp.argmin(jnp.where(tie, packed, INF))
    head_parked = oh_get(phase, idx) == 2
    proceed = any_el & ~head_parked & (key >= 0)

    client = oh_get(oh_get(ps["pend_client"], key), idx)
    cseq = oh_get(oh_get(ps["pend_cseq"], key), idx)
    kmask = oh_get(oh_get(ps["pend_kmask"], key), idx)
    missing0 = oh_get(oh_get(ps["pend_missing"], key), idx)
    _, skey = _cmd_tables(ctx, client, cseq)
    keys_me = _my_keys(pp, ctx, me, skey)
    nloc = jnp.sum((keys_me >= 0).astype(I32))
    nsh = _popcount(kmask, pp.S)
    single = (nsh == 1) & (nloc == 1)

    # rifl_to_stable_count (executor.rs:318-330): only counted for
    # multi-local-key commands; the count completing marks the rifl
    prev = jnp.where(
        oh_get(ps["stable_cnt_seq"], client) == cseq,
        oh_get(ps["stable_cnt"], client),
        0,
    )
    cnt = prev + 1
    counted = proceed & ~single & (nloc > 1)
    do_mark = jnp.where(nloc > 1, cnt == nloc, True) & proceed & ~single
    cw = jnp.where(counted, client, dims.C)
    ps = dict(
        ps,
        stable_cnt=oh_set(
            ps["stable_cnt"], cw, jnp.where(do_mark, 0, cnt)
        ),
        stable_cnt_seq=oh_set(ps["stable_cnt_seq"], cw, cseq),
    )

    # apply + clear buffered StableAtShard counts for this rifl
    bmatch = _get2(ps["buf_seq"], key, client) == cseq
    bcnt = jnp.where(bmatch, _get2(ps["buf_cnt"], key, client), 0)
    bw = jnp.where(proceed & ~single, key, pp.K)
    ps = dict(
        ps, buf_cnt=oh_set2(ps["buf_cnt"], bw, client, 0)
    )
    missing = missing0 - do_mark.astype(I32) - bcnt

    # StableAtShard fan-out to every other key of the command: local
    # keys inline (zero-delay self-message), remote keys through the
    # closest process of their shard (executor.rs:332-344)
    s_me = oh_get(ctx["shard_of"], me)
    slot_i = 2
    for s in range(pp.S):
        for d in range(pp.KPC):
            kk = oh_get(oh_get(skey, jnp.int32(s)), jnp.int32(d))
            is_local = jnp.int32(s) == s_me
            dst = jnp.where(
                is_local,
                me,
                oh_get(oh_get(ctx["closest"], me), jnp.int32(s)),
            )
            ob = emit(
                ob,
                slot_i,
                dst,
                pp.STABLEAT,
                [kk, client, cseq],
                valid=do_mark & (kk >= 0) & (kk != key),
            )
            slot_i += 1

    execute = proceed & (single | (missing <= 0))
    park = proceed & ~execute
    widx = jnp.where(park, idx, pp.PK)
    ps = dict(
        ps,
        pend_phase=oh_set2(ps["pend_phase"], key, widx, 2),
        pend_missing=oh_set2(ps["pend_missing"], key, widx, missing),
    )
    ps, ob = _p_execute(pp, ps, key, idx, client, me, ctx, dims, ob, execute)
    more = jnp.sum(eligible.astype(I32)) > 1
    ob = emit(ob, 1, me, pp.MDRAIN, [key], valid=execute & more)
    return ps, ob


def _p_stableat(pp, ps, msg, me, ctx, dims):
    """StableAtShard arrival (executor.rs:191-214): decrement the
    parked head when it is this rifl, else buffer the count."""
    key, client, cseq = (
        msg["payload"][0],
        msg["payload"][1],
        msg["payload"][2],
    )
    clocks = oh_get(ps["pend_clock"], key)
    phase = oh_get(ps["pend_phase"], key)
    parked = (phase == 2) & (clocks > 0)
    any_parked = jnp.any(parked)
    cmin = jnp.min(jnp.where(parked, clocks, INF))
    tie = parked & (clocks == cmin)
    packed = (
        oh_get(ps["pend_src"], key) * SEQ_BOUND
        + oh_get(ps["pend_seq"], key)
    )
    idx = jnp.argmin(jnp.where(tie, packed, INF))
    match = (
        any_parked
        & (oh_get(oh_get(ps["pend_client"], key), idx) == client)
        & (oh_get(oh_get(ps["pend_cseq"], key), idx) == cseq)
    )

    missing = oh_get(oh_get(ps["pend_missing"], key), idx) - 1
    widx = jnp.where(match, idx, pp.PK)
    ps = dict(
        ps,
        pend_missing=oh_set2(ps["pend_missing"], key, widx, missing),
    )
    execute = match & (missing <= 0)
    ob = empty_outbox(dims)
    ps, ob = _p_execute(pp, ps, key, idx, client, me, ctx, dims, ob, execute)
    ob = emit(ob, 1, me, pp.MDRAIN, [key], valid=execute)

    # no parked head for this rifl yet: buffer (executor.rs:211-214)
    buffer = ~match & (key >= 0)
    old = jnp.where(
        _get2(ps["buf_seq"], key, client) == cseq,
        _get2(ps["buf_cnt"], key, client),
        0,
    )
    bw = jnp.where(buffer, key, pp.K)
    ps = dict(
        ps,
        buf_cnt=oh_set2(ps["buf_cnt"], bw, client, old + 1),
        buf_seq=oh_set2(ps["buf_seq"], bw, client, cseq),
    )
    return ps, ob

"""Device twin of Tempo (fantoch_ps/src/protocol/tempo.rs, host oracle:
fantoch_tpu/protocol/tempo.py) — the flagship protocol.

Flow: submit bumps the coordinator's per-key clock into a timestamp
proposal; fast-quorum members bump their own clocks to at least the
proposal and report (clock, vote range); the fast path commits at the
max reported clock iff it was reported by >= f members, else a
single-decree consensus round fixes the timestamp. Commits carry the
attached votes to the table executor, which executes a command once a
stability threshold's worth of voters have voted past its timestamp.
Detached votes (clock bumps without commands) are batched and broadcast
periodically to keep the stability frontier moving; the optional
real-time mode bumps all clocks to the wall clock.

Array encoding:
- per-key clocks ``[K]``; the detached-vote accumulator is per-key range
  slots ``[K, R, 2]`` (exact ranges, like the reference's ``Votes`` —
  attached votes interleave with detached ones, so prefixes won't do);
- the executor's per-(key, voter) vote clock is a frontier + gap-buffer
  interval set (votes arrive out of order: attached votes ride through
  the coordinator's MCommit while detached fly direct);
- the votes table is ``[K, PK]`` pending slots drained in (clock, dot)
  order; a drain executes ONE command and re-schedules itself via a
  zero-delay self-message, so outbox shapes stay fixed while multiple
  commands stabilize at the same instant;
- commits may complete out of source order (slow vs fast path), so the
  GC committed clock is an interval set per source, not a counter.

Like the oracle, recovery is not modeled; ``skip_fast_ack`` is (the
``skip_capable`` trace-time gate below). Partial replication
(MForwardSubmit/MShardCommit aggregation) has its own device twin —
``tempo_partial.TempoPartialDev`` — which the engine-partial
differential tests hold to exact host-oracle agreement.
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np

from ..core import (
    I32, cumsum_i32, emit, emit_broadcast, empty_outbox, oh_get, oh_set,
    oh_pack_pairs, oh_route, oh_set2, oh_take,
)
from ..dims import (
    ERR_CAPACITY, ERR_DOT, ERR_PROTO, ERR_SEQ, INF, SEQ_BOUND, EngineDims,
    dot_slot,
)
from .identity import DevIdentity
from ..iset import iset_add, iset_add_range, iset_contains
from ..monitor import mon_exec



class TempoDev(DevIdentity):
    SUBMIT = 0
    MCOLLECT = 1
    MCOLLECTACK = 2
    MCOMMIT = 3
    MDETACHED = 4
    MCONSENSUS = 5
    MCONSENSUSACK = 6
    MGC = 7
    MDRAIN = 8
    DETACH_DRAIN = 9
    NUM_TYPES = 10
    TO_CLIENT = 11

    PERIODIC_ROWS = 3  # [garbage collection, clock bump, send detached]
    MONITORED = True  # mon_exec hook at the table executor's drain
    # per-command counters the sweep driver may store narrowed
    # (engine/spec.py narrow_spec): m_fast/m_slow increment once per
    # command at its coordinator, m_stable once per command per process
    # at GC — a lane's total command budget bounds every entry (the
    # partial twin inherits this: same fields, same per-command
    # increments)
    NARROW_METRICS = ("m_fast", "m_slow", "m_stable")

    def __init__(
        self,
        keys: int,
        pending_per_key: int = 32,
        detached_slots: int = 16,
        gap_slots: int = 8,
        skip_capable: bool = False,
    ):
        self.K = keys
        self.PK = pending_per_key
        self.R = detached_slots
        self.G = gap_slots
        # trace-time gate for the skip_fast_ack paths (tempo.rs:91-93,
        # 442-455): lanes select per-config via ctx["skip_fast_ack"],
        # but tracing the extra commit-broadcast work at all costs
        # kernels, so sweeps without the knob compile it out entirely
        self.skip_capable = skip_capable

    @classmethod
    def for_load(cls, keys: int, clients: int) -> "TempoDev":
        """Capacity bounds that survive ``clients`` closed-loop clients
        hammering one conflict key at f up to 2: pending rows hold every
        committed-but-unstable command per key, and detached-vote ranges
        plus frontier gap buffers grow with the stability lag, which
        scales with the number of concurrent writers (measured: the
        defaults overflow detached/gap at 10 clients × conflict 100 ×
        f=2; 2× headroom over the measured need)."""
        return cls(
            keys=keys,
            pending_per_key=max(32, 8 * clients),
            detached_slots=max(16, 4 * clients),
            gap_slots=max(8, 2 * clients),
        )

    # -- host-side builders -------------------------------------------

    def payload_width(self, n: int) -> int:
        # MCOMMIT: [src, seq, clock, key, client, nv] + (by, start, end)*n
        return max(6 + 3 * n, n, 2 + 2 * 4)

    def detached_per_msg(self, dims: EngineDims) -> int:
        return (dims.P - 2) // 2

    def periodic_intervals(self, config, dims: EngineDims):
        def ms(v):
            return v if v is not None else INF

        return [
            ms(config.gc_interval_ms),
            ms(config.tempo_clock_bump_interval_ms),
            ms(config.tempo_detached_send_interval_ms),
        ]

    @staticmethod
    def min_live(config) -> int:
        """Smallest membership that still commits and stabilizes
        (recovery-free): every collect waits on the full fast quorum,
        consensus on the write quorum, and the executor's stability
        rank needs `threshold` advancing voters (engine/faults.py uses
        this to flag crash plans as ERR_UNAVAIL)."""
        fast, write, threshold = config.tempo_quorum_sizes()
        return max(fast, write, threshold)

    def lane_ctx(self, config, dims: EngineDims, sorted_idx: np.ndarray):
        N = dims.N
        fq_size, wq_size, threshold = config.tempo_quorum_sizes()
        fq = np.zeros((N, N), bool)
        wq = np.zeros((N, N), bool)
        for p in range(config.n):
            for member in sorted_idx[p][:fq_size]:
                fq[p, member] = True
            for member in sorted_idx[p][:wq_size]:
                wq[p, member] = True
        return {
            "fast_quorum": fq,
            "write_quorum": wq,
            "fq_size": np.int32(fq_size),
            "wq_size": np.int32(wq_size),
            "threshold": np.int32(threshold),
            "clock_bump_mode": np.bool_(
                config.tempo_clock_bump_interval_ms is not None
            ),
            # tempo.rs:91-93: the optimization only applies when the
            # fast quorum is a pair (coordinator + one member)
            "skip_fast_ack": np.bool_(
                config.skip_fast_ack and fq_size == 2
            ),
        }

    def init_state(self, dims: EngineDims, ctx_np) -> Dict[str, np.ndarray]:
        N, D = dims.N, dims.D
        K, PK, R, G = self.K, self.PK, self.R, self.G
        return {
            # key clocks + detached accumulator (protocol)
            "clocks": np.zeros((N, K), np.int32),
            "det": np.zeros((N, K, R, 2), np.int32),
            "max_commit_clock": np.zeros((N,), np.int32),
            # per-dot payload (every process)
            "seq_in_slot": np.zeros((N, N, D), np.int32),
            "key_of": np.zeros((N, N, D), np.int32),
            "client_of": np.zeros((N, N, D), np.int32),
            # coordinator per own dot
            "own_seq": np.zeros((N,), np.int32),
            "ack_cnt": np.zeros((N, D), np.int32),
            "max_clock": np.zeros((N, D), np.int32),
            "max_cnt": np.zeros((N, D), np.int32),
            "slow_acks": np.zeros((N, D), np.int32),
            "votes_n": np.zeros((N, D), np.int32),
            "votes_by": np.zeros((N, D, N), np.int32),
            "votes_s": np.zeros((N, D, N), np.int32),
            "votes_e": np.zeros((N, D, N), np.int32),
            # table executor
            "vote_front": np.zeros((N, K, N), np.int32),
            "vote_gaps": np.zeros((N, K, N, G, 2), np.int32),
            "pend_clock": np.zeros((N, K, PK), np.int32),
            "pend_src": np.zeros((N, K, PK), np.int32),
            "pend_seq": np.zeros((N, K, PK), np.int32),
            "pend_client": np.zeros((N, K, PK), np.int32),
            # committed-clock GC
            "comm_front": np.zeros((N, N), np.int32),
            "comm_gaps": np.zeros((N, N, G, 2), np.int32),
            "others_frontier": np.zeros((N, N, N), np.int32),
            "seen": np.zeros((N, N), bool),
            "prev_stable": np.zeros((N, N), np.int32),
            "m_fast": np.zeros((N,), np.int32),
            "m_slow": np.zeros((N,), np.int32),
            "m_stable": np.zeros((N,), np.int32),
            "err": np.zeros((N,), np.int32),
        }

    @staticmethod
    def error(ps):
        return ps["err"]

    @staticmethod
    def metrics(ps_np) -> Dict[str, np.ndarray]:
        return {
            "fast_path": ps_np["m_fast"],
            "slow_path": ps_np["m_slow"],
            "stable": ps_np["m_stable"],
        }

    # -- device handlers ----------------------------------------------

    def ready(self, ps, msg, me, ctx, dims: EngineDims):
        """Readiness gate (engine/core.py): requeue messages that
        overtook their prerequisite under reordering. MCollect needs a
        free dot slot (its predecessor GC'd), MCommit/MConsensus need
        the MCollect payload (tempo.rs buffers these commits)."""
        t = msg["mtype"]
        # MCOLLECT: payload [seq, ...] from msg src
        c_slot = dot_slot(msg["payload"][0], dims)
        collect_ok = oh_get(oh_get(ps["seq_in_slot"], msg["src"]), c_slot) == 0
        # MCOMMIT / MCONSENSUS: payload [dsrc, seq, ...]
        dsrc, seq = msg["payload"][0], msg["payload"][1]
        have = (
            oh_get(oh_get(ps["seq_in_slot"], dsrc), dot_slot(seq, dims))
            == seq
        )
        ok = jnp.where(t == TempoDev.MCOLLECT, collect_ok, True)
        return jnp.where(
            (t == TempoDev.MCOMMIT) | (t == TempoDev.MCONSENSUS), have, ok
        )

    def handle(self, ps, msg, me, now, ctx, dims: EngineDims):
        def _noop(ps, msg):
            return ps, empty_outbox(dims)

        branches = [
            lambda ps, msg: _submit(self, ps, msg, me, ctx, dims),
            lambda ps, msg: _mcollect(self, ps, msg, me, ctx, dims),
            lambda ps, msg: _mcollectack(self, ps, msg, me, ctx, dims),
            lambda ps, msg: _mcommit(self, ps, msg, me, ctx, dims),
            lambda ps, msg: _mdetached(self, ps, msg, me, ctx, dims),
            lambda ps, msg: _mconsensus(self, ps, msg, me, ctx, dims),
            lambda ps, msg: _mconsensusack(self, ps, msg, me, ctx, dims),
            lambda ps, msg: _mgc(self, ps, msg, me, ctx, dims),
            lambda ps, msg: _mdrain(self, ps, msg, me, ctx, dims),
            lambda ps, msg: _detach_drain(self, ps, msg, me, ctx, dims),
            _noop,
        ]
        idx = jnp.clip(msg["mtype"], 0, TempoDev.NUM_TYPES)
        return jax.lax.switch(idx, branches, ps, msg)

    def periodic(self, ps, fire, me, now, ctx, dims: EngineDims):
        """Rows: GC frontier broadcast; real-time clock bump
        (tempo.rs:972-992); detached-send kick-off."""
        ob = emit_broadcast(
            empty_outbox(dims),
            TempoDev.MGC,
            ps["comm_front"],
            ctx["n"],
            me,
            exclude_me=True,
        )
        ob = dict(ob, valid=ob["valid"] & fire[0])

        # clock bump: lift every key to max(max commit clock, micros).
        # The micros conversion saturates at INF (lint GL001): past
        # INF // 1000 simulated ms the i32 multiply would wrap negative
        # and *lower* every key clock; saturated clocks stay monotone
        # (the cap sits ~10^3x beyond any sweep the dims admit)
        micros = jnp.where(now >= INF // 1000, INF, now * 1000)
        min_clock = jnp.maximum(ps["max_commit_clock"], micros)
        ps = _detached_all(self, ps, min_clock, fire[1])

        # send-detached: start the per-key drain chain (the oracle sends
        # one message with all keys; the chain emits the same ranges at
        # the same instant)
        has = jnp.any(ps["det"][:, :, 0] > 0)
        ob = emit(
            ob,
            dims.N,  # slot N is free: broadcast used 0..N-1
            me,
            TempoDev.DETACH_DRAIN,
            [0],
            valid=fire[2] & has,
        )
        return ps, ob


# ----------------------------------------------------------------------
# clock/vote helpers
# ----------------------------------------------------------------------


def _det_add(tempo, ps, key, start, end, enable):
    """Append a detached vote range for ``key`` (Votes::add; ranges stay
    exact because attached votes interleave). All updates are one-hot
    selects: scatters cost a kernel each on the target runtime."""
    det = ps["det"]  # [K, R, 2]
    krow = jnp.arange(tempo.K, dtype=I32) == key               # [K]
    row = oh_get(det, key)                                     # [R, 2]
    # compress with an existing contiguous range (votes.rs:131-147)
    touch = (row[:, 0] > 0) & (row[:, 1] + 1 == start)
    can_compress = jnp.any(touch)
    cslot = jnp.argmax(touch)
    do = jnp.asarray(enable, bool) & (end >= start)
    comp = do & can_compress
    hit_c = (
        krow[:, None]
        & (jnp.arange(tempo.R, dtype=I32) == cslot)[None, :]
        & comp
    )                                                          # [K, R]
    det = jnp.where(
        hit_c[:, :, None] & jnp.array([False, True])[None, None, :],
        end,
        det,
    )
    # otherwise take a free slot
    free = row[:, 0] == 0
    slot = jnp.argmax(free)
    store = do & ~can_compress
    overflow = store & ~jnp.any(free)
    hit_s = (
        krow[:, None]
        & (jnp.arange(tempo.R, dtype=I32) == slot)[None, :]
        & (store & ~overflow)
    )
    det = jnp.where(
        hit_s[:, :, None], jnp.stack([start, end])[None, None, :], det
    )
    return dict(ps, det=det, err=ps["err"] | ERR_CAPACITY * overflow)


def _bump(tempo, ps, key, up_to, enable):
    """key_clocks.detached: vote (clock+1..up_to) and lift the clock
    (clocks/keys/sequential.rs:96-104)."""
    cur = oh_get(ps["clocks"], key)
    do = jnp.asarray(enable, bool) & (cur < up_to)
    ps = _det_add(tempo, ps, key, cur + 1, up_to, do)
    return dict(
        ps,
        clocks=oh_set(ps["clocks"], key, jnp.where(do, up_to, cur)),
    )


def _detached_all(tempo, ps, min_clock, enable):
    """Bump every key below ``min_clock`` (detached_all): vectorized over
    keys, each claiming a free detached slot."""
    clocks = ps["clocks"]  # [K]
    det = ps["det"]  # [K, R, 2]
    do = jnp.asarray(enable, bool) & (clocks < min_clock)
    free = det[:, :, 0] == 0  # [K, R]
    slot = jnp.argmax(free, axis=1)  # [K]
    overflow = do & ~jnp.any(free, axis=1)
    slot_w = jnp.where(do & ~overflow, slot, tempo.R)
    hit = jnp.arange(tempo.R, dtype=I32)[None, :] == slot_w[:, None]
    vals = jnp.stack(
        [clocks + 1, jnp.broadcast_to(min_clock, clocks.shape)], axis=-1
    )  # [K, 2]
    det = jnp.where(hit[:, :, None], vals[:, None, :], det)
    return dict(
        ps,
        det=det,
        clocks=jnp.where(do, min_clock, clocks),
        err=ps["err"] | ERR_CAPACITY * jnp.any(overflow),
    )


def _vote_add(tempo, ps, key, voter, start, end, enable):
    """Union a vote range into the (key, voter) interval clock."""
    front = oh_get(oh_get(ps["vote_front"], key), voter)
    gaps = oh_get(oh_get(ps["vote_gaps"], key), voter)
    front, gaps, overflow = iset_add_range(front, gaps, start, end, enable)
    return dict(
        ps,
        vote_front=oh_set2(ps["vote_front"], key, voter, front),
        vote_gaps=oh_set2(ps["vote_gaps"], key, voter, gaps),
        err=ps["err"] | ERR_CAPACITY * overflow,
    )



# ----------------------------------------------------------------------
# table-executor drain
# ----------------------------------------------------------------------


def _stable_clock(tempo, ps, key, ctx, dims):
    """Threshold-ranked frontier over voters (table/mod.rs:243-263).
    The (n - threshold)-th order statistic over N values, computed by
    comparison ranking in one fusion (jnp.sort is a kernel)."""
    fronts = oh_get(ps["vote_front"], key)  # [N]
    procs = jnp.arange(dims.N, dtype=I32)
    masked = jnp.where(procs < ctx["n"], fronts, INF)
    rank = jnp.sum(
        (masked[None, :] < masked[:, None])
        | ((masked[None, :] == masked[:, None]) & (procs[None, :] < procs[:, None])),
        axis=1,
    )
    k = ctx["n"] - ctx["threshold"]
    return jnp.sum(jnp.where(rank == k, masked, 0))


def _drain(tempo, ps, key, me, ctx, dims, ob, exec_slot, drain_slot,
           enable=True):
    """Execute the lowest stable pending command on ``key`` (if any) and
    re-schedule when more are ready (the VotesTable stable_ops loop,
    spread across zero-delay self-messages)."""
    stable = _stable_clock(tempo, ps, key, ctx, dims)
    clocks = oh_get(ps["pend_clock"], key)  # [PK]
    ready = (clocks > 0) & (clocks <= stable)
    num_ready = jnp.sum(ready)
    cmin = jnp.min(jnp.where(ready, clocks, INF))
    tie = ready & (clocks == cmin)
    packed = (
        oh_get(ps["pend_src"], key) * SEQ_BOUND
        + oh_get(ps["pend_seq"], key)
    )
    idx = jnp.argmin(jnp.where(tie, packed, INF))

    do = jnp.asarray(enable, bool) & (num_ready > 0)
    client = oh_get(oh_get(ps["pend_client"], key), idx)
    # safety monitor (engine/monitor.py; the ``if`` is a trace-time
    # gate — fuzz-disabled sweeps trace zero monitor ops): record the
    # execution on this key; the execute-before-commit guard checks
    # the GC committed-clock record — a data path independent of the
    # pending table that fed this drain
    if "_mon_hash" in ps:
        e_src = oh_get(oh_get(ps["pend_src"], key), idx)
        e_seq = oh_get(oh_get(ps["pend_seq"], key), idx)
        ps = mon_exec(
            ps, key, e_src, e_seq, do,
            premature=~iset_contains(
                oh_get(ps["comm_front"], e_src),
                oh_get(ps["comm_gaps"], e_src),
                e_seq,
            ),
        )
    ps = dict(
        ps,
        pend_clock=oh_set2(
            ps["pend_clock"], key, jnp.where(do, idx, tempo.PK), 0
        ),
    )
    ob = emit(
        ob,
        exec_slot,
        dims.N + client,
        TempoDev.TO_CLIENT,
        [0],
        valid=do & (oh_get(ctx["client_attach"], client) == me),
    )
    ob = emit(
        ob,
        drain_slot,
        me,
        TempoDev.MDRAIN,
        [key],
        valid=do & (num_ready > 1),
    )
    return ps, ob


def _pend_insert(tempo, ps, key, clock, src, seq, client):
    slots = oh_get(ps["pend_clock"], key)
    free = slots == 0
    idx = jnp.argmax(free)
    overflow = ~jnp.any(free)
    widx = jnp.where(overflow, tempo.PK, idx)
    return dict(
        ps,
        pend_clock=oh_set2(ps["pend_clock"], key, widx, clock),
        pend_src=oh_set2(ps["pend_src"], key, widx, src),
        pend_seq=oh_set2(ps["pend_seq"], key, widx, seq),
        pend_client=oh_set2(ps["pend_client"], key, widx, client),
        err=ps["err"] | ERR_CAPACITY * overflow,
    )


# ----------------------------------------------------------------------
# handlers
# ----------------------------------------------------------------------


def _submit(tempo, ps, msg, me, ctx, dims):
    """tempo.rs:267-339: next dot; clock proposal with the coordinator's
    own attached vote kept locally (sent later inside MCommit)."""
    client = msg["payload"][0]
    key = msg["payload"][2]
    seq = ps["own_seq"] + 1
    slot = dot_slot(seq, dims)

    cur = oh_get(ps["clocks"], key)
    clock = cur + 1  # max(0, highest key clock + 1), single key
    if tempo.skip_capable:
        # skip_fast_ack lanes ship the coordinator's votes inside the
        # MCollect (tempo.rs:330-335) instead of holding them locally
        own_vote = jnp.where(ctx["skip_fast_ack"], 0, 1)
    else:
        own_vote = 1
    ps = dict(
        ps,
        # (source, sequence) packing in the drain scan requires seq < bound
        err=ps["err"] | ERR_SEQ * (seq >= SEQ_BOUND),
        own_seq=seq,
        clocks=oh_set(ps["clocks"], key, clock),
        ack_cnt=oh_set(ps["ack_cnt"], slot, 0),
        max_clock=oh_set(ps["max_clock"], slot, 0),
        max_cnt=oh_set(ps["max_cnt"], slot, 0),
        slow_acks=oh_set(ps["slow_acks"], slot, 0),
        votes_n=oh_set(ps["votes_n"], slot, own_vote),
        votes_by=oh_set2(ps["votes_by"], slot, 0, me),
        votes_s=oh_set2(ps["votes_s"], slot, 0, cur + 1),
        votes_e=oh_set2(ps["votes_e"], slot, 0, clock),
    )
    ob = emit_broadcast(
        empty_outbox(dims),
        TempoDev.MCOLLECT,
        [seq, key, clock, client, cur + 1, clock],
        ctx["n"],
    )
    return ps, ob


def _mcollect(tempo, ps, msg, me, ctx, dims):
    """tempo.rs:341-459: store payload; quorum members re-propose with
    the remote clock as a floor and report their vote range."""
    s = msg["src"]
    seq, key, rclock, client = (
        msg["payload"][0],
        msg["payload"][1],
        msg["payload"][2],
        msg["payload"][3],
    )
    slot = dot_slot(seq, dims)
    dirty = oh_get(oh_get(ps["seq_in_slot"], s), slot) != 0
    ps = dict(
        ps,
        err=ps["err"] | ERR_DOT * dirty,
        seq_in_slot=oh_set2(ps["seq_in_slot"], s, slot, seq),
        key_of=oh_set2(ps["key_of"], s, slot, key),
        client_of=oh_set2(ps["client_of"], s, slot, client),
    )
    in_q = oh_get(oh_get(ctx["fast_quorum"], s), me)
    from_self = s == me

    # non-self quorum member: proposal(cmd, remote clock)
    cur = oh_get(ps["clocks"], key)
    clock = jnp.maximum(rclock, cur + 1)
    propose = in_q & ~from_self
    ps = dict(
        ps,
        clocks=oh_set(ps["clocks"], key, jnp.where(propose, clock, cur)),
    )
    ack_clock = jnp.where(from_self, rclock, clock)
    vs = jnp.where(propose, cur + 1, 0)
    ve = jnp.where(propose, clock, 0)
    if tempo.skip_capable:
        # tempo.rs:442-455: with a pair fast quorum, the non-coordinator
        # member commits directly — its proposal plus the coordinator's
        # shipped votes are the whole quorum's votes — and no ack flows
        skipv = ctx["skip_fast_ack"] & in_q & ~from_self
        vs_c, ve_c = msg["payload"][4], msg["payload"][5]
        pay = jnp.zeros((dims.P,), I32)
        pay = (
            pay.at[0].set(s).at[1].set(seq).at[2].set(clock)
            .at[3].set(key).at[4].set(client).at[5].set(2)
            .at[6].set(s).at[7].set(vs_c).at[8].set(ve_c)
            .at[9].set(me).at[10].set(vs).at[11].set(ve)
        )
        obc = emit_broadcast(
            empty_outbox(dims), TempoDev.MCOMMIT, pay, ctx["n"]
        )
        obc = dict(obc, valid=obc["valid"] & skipv)
    else:
        skipv = False
    ob = emit(
        empty_outbox(dims),
        0,
        s,
        TempoDev.MCOLLECTACK,
        [seq, ack_clock, vs, ve],
        valid=in_q & ~jnp.asarray(skipv, bool),
    )
    if tempo.skip_capable:
        ob = jax.tree_util.tree_map(
            lambda a, b: jnp.where(
                skipv.reshape((-1,) + (1,) * (a.ndim - 1))
                if a.ndim > 1
                else skipv,
                a,
                b,
            ),
            obc,
            ob,
        )
    return ps, ob


def _mcollectack(tempo, ps, msg, me, ctx, dims):
    """tempo.rs:461-554: aggregate clocks + votes; fast path iff the max
    clock was reported >= f times; bump own keys to the running max."""
    src = msg["src"]
    seq, clock, vs, ve = (
        msg["payload"][0],
        msg["payload"][1],
        msg["payload"][2],
        msg["payload"][3],
    )
    slot = dot_slot(seq, dims)

    # merge the ack's vote range
    nv = oh_get(ps["votes_n"], slot)
    has_vote = vs > 0
    fits = has_vote & (nv < dims.N)
    widx = jnp.where(fits, nv, dims.N)
    ps = dict(
        ps,
        votes_by=oh_set2(ps["votes_by"], slot, widx, src),
        votes_s=oh_set2(ps["votes_s"], slot, widx, vs),
        votes_e=oh_set2(ps["votes_e"], slot, widx, ve),
        votes_n=oh_set(ps["votes_n"], slot, nv + fits.astype(I32)),
        err=ps["err"] | ERR_CAPACITY * (has_vote & ~fits),
    )

    # quorum clock aggregation
    old_max = oh_get(ps["max_clock"], slot)
    new_max = jnp.maximum(old_max, clock)
    new_cnt = jnp.where(
        clock > old_max, 1, oh_get(ps["max_cnt"], slot) + (clock == old_max)
    )
    cnt = oh_get(ps["ack_cnt"], slot) + 1
    ps = dict(
        ps,
        max_clock=oh_set(ps["max_clock"], slot, new_max),
        max_cnt=oh_set(ps["max_cnt"], slot, new_cnt),
        ack_cnt=oh_set(ps["ack_cnt"], slot, cnt),
    )

    # bump own keys to the running max (tempo.rs:497-514)
    key = oh_get(oh_get(ps["key_of"], me), slot)
    ps = _bump(tempo, ps, key, new_max, src != me)

    all_acks = cnt == ctx["fq_size"]
    fast = all_acks & (new_cnt >= ctx["f"])
    slow = all_acks & ~fast
    ps = dict(
        ps,
        m_fast=ps["m_fast"] + fast.astype(I32),
        m_slow=ps["m_slow"] + slow.astype(I32),
    )

    client = oh_get(oh_get(ps["client_of"], me), slot)
    ob = _commit_broadcast(
        tempo, ps, me, seq, new_max, key, client, ctx, dims, fast
    )
    obc = emit_broadcast(
        empty_outbox(dims),
        TempoDev.MCONSENSUS,
        [me, seq, new_max],
        ctx["n"],
    )
    wq = jnp.zeros((dims.F,), bool).at[: dims.N].set(
        oh_get(ctx["write_quorum"], me)
    )
    obc = dict(obc, valid=obc["valid"] & slow & wq)
    ob = jax.tree_util.tree_map(
        lambda a, b: jnp.where(
            fast.reshape((-1,) + (1,) * (a.ndim - 1)) if a.ndim > 1 else fast,
            a,
            b,
        ),
        ob,
        obc,
    )
    return ps, ob


def _commit_broadcast(tempo, ps, me, seq, clock, key, client, ctx, dims,
                      valid):
    """Build the MCommit broadcast carrying the aggregated votes."""
    slot = dot_slot(seq, dims)
    P = dims.P
    pay = jnp.zeros((P,), I32)
    pay = pay.at[0].set(me)
    pay = pay.at[1].set(seq)
    pay = pay.at[2].set(clock)
    pay = pay.at[3].set(key)
    pay = pay.at[4].set(client)
    pay = pay.at[5].set(oh_get(ps["votes_n"], slot))
    pay = jax.lax.dynamic_update_slice(
        pay,
        jnp.stack(
            [
                oh_get(ps["votes_by"], slot),
                oh_get(ps["votes_s"], slot),
                oh_get(ps["votes_e"], slot),
            ],
            axis=1,
        ).reshape(-1),
        (6,),
    )
    ob = emit_broadcast(
        empty_outbox(dims), TempoDev.MCOMMIT, pay, ctx["n"]
    )
    return dict(ob, valid=ob["valid"] & jnp.asarray(valid, bool))


def _mcommit(tempo, ps, msg, me, ctx, dims):
    """tempo.rs:556-654: detached-bump the committed clock, feed the
    votes table (attached votes + pending entry), record the commit for
    GC, then drain."""
    # the dot source rides in a payload word; clamp it to a process id
    # so the drain's (src, seq) i32 packing (src * SEQ_BOUND + seq)
    # cannot wrap on an out-of-range word (lint GL001)
    dsrc = jnp.clip(msg["payload"][0], 0, dims.N - 1)
    seq = msg["payload"][1]
    clock = msg["payload"][2]
    key = msg["payload"][3]
    client = msg["payload"][4]
    nv = msg["payload"][5]
    slot = dot_slot(seq, dims)
    have = oh_get(oh_get(ps["seq_in_slot"], dsrc), slot) == seq
    ps = dict(ps, err=ps["err"] | ERR_PROTO * ~have)

    # clock management (real-time mode defers to the periodic bump)
    bump_mode = ctx["clock_bump_mode"]
    ps = dict(
        ps,
        max_commit_clock=jnp.where(
            bump_mode,
            jnp.maximum(ps["max_commit_clock"], clock),
            ps["max_commit_clock"],
        ),
    )
    ps = _bump(tempo, ps, key, clock, ~bump_mode)

    # executor: attached votes + pending entry. Voters in an MCommit are
    # distinct (one range per quorum member), so scatter the ranges to
    # per-voter lanes and union them with one vmapped interval-set add
    # instead of a sequential loop.
    idxs = 6 + 3 * jnp.arange(dims.N, dtype=I32)
    bys = oh_take(msg["payload"], idxs)
    enable = jnp.arange(dims.N, dtype=I32) < nv
    bys = jnp.where(enable, bys, dims.N)
    # voters are distinct, so route (start, end, enable) to per-voter
    # lanes with one-hot sums (each .at[bys].set was a scatter kernel)
    starts = oh_take(msg["payload"], idxs + 1)
    ends = oh_take(msg["payload"], idxs + 2)
    per_s = oh_route(bys, starts, dims.N)
    per_e = oh_route(bys, ends, dims.N)
    oh_by = bys[:, None] == jnp.arange(dims.N, dtype=I32)[None, :]
    per_enable = jnp.any(oh_by & enable[:, None], axis=0)
    fronts, gaps, ovf = jax.vmap(iset_add_range)(
        oh_get(ps["vote_front"], key),
        oh_get(ps["vote_gaps"], key),
        per_s,
        per_e,
        per_enable,
    )
    ps = dict(
        ps,
        vote_front=oh_set(ps["vote_front"], key, fronts),
        vote_gaps=oh_set(ps["vote_gaps"], key, gaps),
        err=ps["err"] | ERR_CAPACITY * jnp.any(ovf),
    )
    ps = _pend_insert(tempo, ps, key, clock, dsrc, seq, client)

    # GC committed clock
    cf, cg, overflow = iset_add(
        oh_get(ps["comm_front"], dsrc), oh_get(ps["comm_gaps"], dsrc), seq
    )
    ps = dict(
        ps,
        comm_front=oh_set(ps["comm_front"], dsrc, cf),
        comm_gaps=oh_set(ps["comm_gaps"], dsrc, cg),
        err=ps["err"] | ERR_CAPACITY * overflow,
    )
    return _drain(
        tempo, ps, key, me, ctx, dims, empty_outbox(dims), 0, 1
    )


def _mdetached(tempo, ps, msg, me, ctx, dims):
    """tempo.rs:703-716: union the sender's detached ranges into its
    vote clock for the key, then drain."""
    voter = msg["src"]
    key = msg["payload"][0]
    nr = msg["payload"][1]

    # statically unrolled (payload indexes become slices and the whole
    # chain fuses; as a lax loop each iteration pays kernel launches)
    for i in range(tempo.detached_per_msg(dims)):
        s = msg["payload"][2 + 2 * i]
        e = msg["payload"][2 + 2 * i + 1]
        ps = _vote_add(tempo, ps, key, voter, s, e, i < nr)
    return _drain(tempo, ps, key, me, ctx, dims, empty_outbox(dims), 0, 1)


def _mconsensus(tempo, ps, msg, me, ctx, dims):
    """tempo.rs:718-773 (no recovery: the initial ballot always wins, so
    the acceptor just bumps its keys and acks)."""
    dsrc, seq, clock = (
        msg["payload"][0],
        msg["payload"][1],
        msg["payload"][2],
    )
    slot = dot_slot(seq, dims)
    key = oh_get(oh_get(ps["key_of"], dsrc), slot)
    has_cmd = oh_get(oh_get(ps["seq_in_slot"], dsrc), slot) == seq
    ps = _bump(tempo, ps, key, clock, has_cmd)
    ob = emit(
        empty_outbox(dims),
        0,
        msg["src"],
        TempoDev.MCONSENSUSACK,
        [dsrc, seq],
    )
    return ps, ob


def _mconsensusack(tempo, ps, msg, me, ctx, dims):
    """tempo.rs:775-812: f+1 accepts choose the slow-path clock; commit
    with the votes gathered during collect."""
    seq = msg["payload"][1]
    slot = dot_slot(seq, dims)
    cnt = oh_get(ps["slow_acks"], slot) + 1
    chosen = cnt == ctx["wq_size"]
    ps = dict(ps, slow_acks=oh_set(ps["slow_acks"], slot, cnt))
    key = oh_get(oh_get(ps["key_of"], me), slot)
    client = oh_get(oh_get(ps["client_of"], me), slot)
    ob = _commit_broadcast(
        tempo,
        ps,
        me,
        seq,
        oh_get(ps["max_clock"], slot),
        key,
        client,
        ctx,
        dims,
        chosen,
    )
    return ps, ob


def _mgc(tempo, ps, msg, me, ctx, dims):
    """Committed-clock GC, identical to Basic's flow but with interval-
    set committed clocks (commits may arrive out of source order)."""
    N = dims.N
    s = msg["src"]
    frontier = msg["payload"][:N]
    of = oh_set(
        ps["others_frontier"],
        s,
        jnp.maximum(oh_get(ps["others_frontier"], s), frontier),
    )
    seen = oh_set(ps["seen"], s, True)
    procs = jnp.arange(N, dtype=I32)
    nmask = procs < ctx["n"]
    others = nmask & (procs != me)
    ready = jnp.all(seen | ~others)
    min_others = jnp.min(jnp.where(others[:, None], of, INF), axis=0)
    stable = jnp.minimum(ps["comm_front"], min_others)
    stable = jnp.where(ready & nmask, stable, 0)
    delta = jnp.maximum(stable - ps["prev_stable"], 0)
    prev_stable = jnp.maximum(ps["prev_stable"], stable)
    freed = (ps["seq_in_slot"] > 0) & (
        ps["seq_in_slot"] <= prev_stable[:, None]
    )
    ps = dict(
        ps,
        others_frontier=of,
        seen=seen,
        prev_stable=prev_stable,
        m_stable=ps["m_stable"] + jnp.sum(delta),
        seq_in_slot=jnp.where(freed, 0, ps["seq_in_slot"]),
    )
    return ps, empty_outbox(dims)


def _mdrain(tempo, ps, msg, me, ctx, dims):
    key = msg["payload"][0]
    return _drain(tempo, ps, key, me, ctx, dims, empty_outbox(dims), 0, 1)


def _detach_drain(tempo, ps, msg, me, ctx, dims):
    """Send one key's pending detached ranges to everyone, then continue
    the chain while any key still has ranges (the oracle's single
    MDetached with all keys, split at the same simulated instant)."""
    det = ps["det"]  # [K, R, 2]
    has = det[:, :, 0] > 0  # [K, R]
    key_has = jnp.any(has, axis=1)  # [K]
    key = jnp.argmax(key_has)
    any_key = jnp.any(key_has)

    row = oh_get(det, key)  # [R, 2]
    occ = row[:, 0] > 0
    order = cumsum_i32(occ)
    per_msg = tempo.detached_per_msg(dims)
    take = occ & (order <= per_msg)
    nr = jnp.sum(take)

    # pack taken ranges into the payload (one-hot writes; each
    # .at[lo].set was a scatter kernel)
    pay = jnp.zeros((dims.P,), I32)
    pay = pay.at[0].set(key)
    pay = pay.at[1].set(nr)
    lo = jnp.where(take, 2 + 2 * (order - 1), dims.P)
    pay = oh_pack_pairs(pay, lo, row[:, 0], row[:, 1])

    det = oh_set(det, key, jnp.where(take[:, None], 0, row))
    ps = dict(ps, det=det)

    ob = emit_broadcast(
        empty_outbox(dims), TempoDev.MDETACHED, pay, ctx["n"]
    )
    ob = dict(ob, valid=ob["valid"] & any_key)
    more = jnp.any(det[:, :, 0] > 0)
    ob = emit(
        ob,
        dims.N,
        me,
        TempoDev.DETACH_DRAIN,
        [0],
        valid=any_key & more,
    )
    return ps, ob

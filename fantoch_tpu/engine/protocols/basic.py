"""Device twin of the ``Basic`` protocol (fantoch/src/protocol/basic.rs,
host oracle: fantoch_tpu/protocol/basic.py).

Semantics: coordinator broadcasts MStore; the f+1 fast-quorum members
ack; on the f+1'th ack the coordinator broadcasts MCommit; commits feed
the committed-clock GC flow (periodic MGarbageCollection frontier
exchange → stable dots; gc/clock.rs:10-171). 100% fast path.

State encoding (per process, fixed shapes):
- ``seq_in_slot[N, D]``  — which command sequence currently occupies each
  dot slot per source (0 = free); slots recycle modulo D after GC, with
  a dirty-slot check surfacing overflow instead of corrupting state;
- ``committed_cnt[N]``   — per-source committed frontier (commits arrive
  in order per source because delays are constant per process pair; an
  out-of-order commit raises the lane error flag);
- ``acks[D]``/``client_of[D]``/``own_seq`` — coordinator bookkeeping;
- ``others_frontier[N, N]``/``seen[N]``/``prev_stable[N]`` — the GC
  tracker (VClockGCTrack): stable = meet of all advertised frontiers.
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np

from ..core import (
    I32, emit, emit_broadcast, empty_outbox, oh_get, oh_set, oh_set2,
)
from ..dims import ERR_DOT, ERR_PROTO, INF, EngineDims
from ..monitor import mon_exec
from .identity import DevIdentity


class BasicDev(DevIdentity):
    SUBMIT = 0
    MSTORE = 1
    MSTOREACK = 2
    MCOMMIT = 3
    MGC = 4
    NUM_TYPES = 5
    TO_CLIENT = 6  # any id ≥ NUM_TYPES; routing is by dst ≥ N

    PERIODIC_ROWS = 1  # garbage collection
    MONITORED = True
    # Basic's executor applies commits in arrival order and guarantees
    # no cross-process order, so only the exactly-once counters are
    # checked (all executions share monitor key 0)
    MONITOR_ORDER = False
    # per-command counters the sweep driver may store narrowed
    # (engine/spec.py narrow_spec): each increments at most once per
    # command per process (fast-path decision at commit, stability at
    # GC), so a lane's total command budget bounds every entry
    NARROW_METRICS = ("m_fast_path", "m_stable")

    # -- host-side builders -------------------------------------------

    @staticmethod
    def payload_width(n: int) -> int:
        return max(n, 3)  # MGC carries an n-wide frontier

    @staticmethod
    def periodic_intervals(config, dims: EngineDims):
        gc = config.gc_interval_ms
        return [gc if gc is not None else INF]

    @staticmethod
    def min_live(config) -> int:
        """f+1 store-quorum members must ack every MStore
        (engine/faults.py flags deeper crash plans ERR_UNAVAIL)."""
        return config.basic_quorum_size()

    @staticmethod
    def lane_ctx(config, dims: EngineDims, sorted_idx: np.ndarray):
        """Fast quorum = first f+1 processes in each process's discovery
        order (base.rs:107-131 with basic_quorum_size, config.rs:265)."""
        N = dims.N
        q = config.basic_quorum_size()
        quorum = np.zeros((N, N), bool)
        n = config.n
        for p in range(n):
            for member in sorted_idx[p][:q]:
                quorum[p, member] = True
        return {"quorum": quorum, "q_size": np.int32(q)}

    @staticmethod
    def init_state(dims: EngineDims, ctx_np) -> Dict[str, np.ndarray]:
        N, D = dims.N, dims.D
        return {
            "seq_in_slot": np.zeros((N, N, D), np.int32),
            "buffered_commit": np.zeros((N, N, D), bool),
            "committed_cnt": np.zeros((N, N), np.int32),
            "acks": np.zeros((N, D), np.int32),
            "client_of": np.zeros((N, D), np.int32),
            "own_seq": np.zeros((N,), np.int32),
            "others_frontier": np.zeros((N, N, N), np.int32),
            "seen": np.zeros((N, N), bool),
            "prev_stable": np.zeros((N, N), np.int32),
            "m_fast_path": np.zeros((N,), np.int32),
            "m_stable": np.zeros((N,), np.int32),
            "err": np.zeros((N,), np.int32),
        }

    @staticmethod
    def error(ps):
        return ps["err"]

    @staticmethod
    def metrics(ps_np) -> Dict[str, np.ndarray]:
        return {
            "fast_path": ps_np["m_fast_path"],
            "stable": ps_np["m_stable"],
        }

    # -- device handlers ----------------------------------------------

    @staticmethod
    def ready(ps, msg, me, ctx, dims: EngineDims):
        """Readiness gate: MStore needs a free dot slot; commits apply
        in per-source order (committed_cnt is a frontier counter)."""
        t = msg["mtype"]
        store_slot = _slot(msg["payload"][0], dims)
        store_ok = oh_get(oh_get(ps["seq_in_slot"], msg["src"]), store_slot) == 0
        dsrc, seq = msg["payload"][0], msg["payload"][1]
        in_order = seq == oh_get(ps["committed_cnt"], dsrc) + 1
        ok = jnp.where(t == BasicDev.MSTORE, store_ok, True)
        return jnp.where(t == BasicDev.MCOMMIT, in_order, ok)

    @staticmethod
    def handle(ps, msg, me, now, ctx, dims: EngineDims):
        def _noop(ps, msg):
            return ps, empty_outbox(dims)

        branches = [
            lambda ps, msg: _submit(ps, msg, me, ctx, dims),
            lambda ps, msg: _mstore(ps, msg, me, ctx, dims),
            lambda ps, msg: _mstoreack(ps, msg, me, ctx, dims),
            lambda ps, msg: _mcommit(ps, msg, me, ctx, dims),
            lambda ps, msg: _mgc(ps, msg, me, ctx, dims),
            _noop,
        ]
        idx = jnp.clip(msg["mtype"], 0, BasicDev.NUM_TYPES)
        return jax.lax.switch(idx, branches, ps, msg)

    @staticmethod
    def periodic(ps, fire, me, now, ctx, dims: EngineDims):
        """GARBAGE_COLLECTION: broadcast my committed frontier to
        all-but-me (basic.rs handle_event)."""
        ob = emit_broadcast(
            empty_outbox(dims),
            BasicDev.MGC,
            ps["committed_cnt"],
            ctx["n"],
            me,
            exclude_me=True,
        )
        ob = dict(ob, valid=ob["valid"] & fire[0])
        return ps, ob


# ----------------------------------------------------------------------
# handlers (module-level so the switch branches stay small closures)
# ----------------------------------------------------------------------


def _slot(seq, dims):
    return (seq - 1) % dims.D


def _apply_commit(ps, src, seq, me, do, ob, ob_slot, dims):
    """Commit (src, seq): advance the per-source frontier, and if I am
    the coordinator, report back to the waiting client. ``do`` masks the
    whole operation (commit may be buffered awaiting the payload)."""
    expected = oh_get(ps["committed_cnt"], src) + 1
    # safety monitor (engine/monitor.py; the ``if`` is a trace-time
    # gate): count-only, see MONITOR_ORDER above
    if "_mon_hash" in ps:
        ps = mon_exec(ps, 0, src, seq, do)
    ps = dict(
        ps,
        err=ps["err"] | ERR_PROTO * (do & (seq != expected)),
        committed_cnt=oh_set(
            ps["committed_cnt"], src,
            oh_get(ps["committed_cnt"], src) + do.astype(I32),
        ),
    )
    slot = _slot(seq, dims)
    client = oh_get(ps["client_of"], slot)
    ob = emit(
        ob,
        ob_slot,
        dims.N + client,
        BasicDev.TO_CLIENT,
        [seq],
        valid=do & (me == src),
    )
    return ps, ob


def _submit(ps, msg, me, ctx, dims):
    """Client SUBMIT → next dot, MStore to all (basic.rs:113-129)."""
    client = msg["payload"][0]
    key = msg["payload"][2]
    seq = ps["own_seq"] + 1
    slot = _slot(seq, dims)
    ps = dict(
        ps,
        own_seq=seq,
        client_of=oh_set(ps["client_of"], slot, client),
        acks=oh_set(ps["acks"], slot, 0),
    )
    ob = emit_broadcast(
        empty_outbox(dims), BasicDev.MSTORE, [seq, key], ctx["n"]
    )
    ob = dict(ob, valid=ob["valid"] & msg["valid"])
    return ps, ob


def _mstore(ps, msg, me, ctx, dims):
    """Store payload; quorum members ack; a buffered commit (commit seen
    before payload) is applied now (basic.rs:152-162)."""
    s, seq = msg["src"], msg["payload"][0]
    slot = _slot(seq, dims)
    dirty = oh_get(oh_get(ps["seq_in_slot"], s), slot) != 0
    ps = dict(
        ps,
        err=ps["err"] | ERR_DOT * dirty,
        seq_in_slot=oh_set2(ps["seq_in_slot"], s, slot, seq),
    )
    ob = emit(
        empty_outbox(dims),
        0,
        s,
        BasicDev.MSTOREACK,
        [seq],
        valid=oh_get(oh_get(ctx["quorum"], s), me),
    )
    buffered = oh_get(oh_get(ps["buffered_commit"], s), slot)
    ps, ob = _apply_commit(ps, s, seq, me, buffered, ob, 1, dims)
    ps = dict(
        ps, buffered_commit=oh_set2(ps["buffered_commit"], s, slot, False)
    )
    return ps, ob


def _mstoreack(ps, msg, me, ctx, dims):
    """Count acks; on exactly f+1, commit everywhere
    (basic.rs:163-169)."""
    seq = msg["payload"][0]
    slot = _slot(seq, dims)
    cnt = oh_get(ps["acks"], slot) + 1
    reached = cnt == ctx["q_size"]
    ps = dict(
        ps,
        acks=oh_set(ps["acks"], slot, cnt),
        m_fast_path=ps["m_fast_path"] + reached.astype(I32),
    )
    ob = emit_broadcast(
        empty_outbox(dims), BasicDev.MCOMMIT, [me, seq], ctx["n"]
    )
    ob = dict(ob, valid=ob["valid"] & reached)
    return ps, ob


def _mcommit(ps, msg, me, ctx, dims):
    """Apply the commit if the payload has arrived, else buffer it
    (basic.rs:171-186)."""
    dsrc, seq = msg["payload"][0], msg["payload"][1]
    slot = _slot(seq, dims)
    have = oh_get(oh_get(ps["seq_in_slot"], dsrc), slot) == seq
    ps, ob = _apply_commit(
        ps, dsrc, seq, me, have, empty_outbox(dims), 0, dims
    )
    ps = dict(
        ps,
        buffered_commit=oh_set2(
            ps["buffered_commit"], dsrc, slot,
            oh_get(oh_get(ps["buffered_commit"], dsrc), slot) | ~have,
        ),
    )
    return ps, ob


def _mgc(ps, msg, me, ctx, dims):
    """Join the sender's committed frontier; recompute the stable clock
    (meet over everyone) and free newly stable dot slots
    (gc/clock.rs:51-120)."""
    N = dims.N
    s = msg["src"]
    frontier = msg["payload"][:N]
    of = ps["others_frontier"]
    of = oh_set(of, s, jnp.maximum(oh_get(of, s), frontier))
    seen = oh_set(ps["seen"], s, True)

    procs = jnp.arange(N, dtype=I32)
    nmask = procs < ctx["n"]
    others = nmask & (procs != me)
    ready = jnp.all(seen | ~others)

    min_others = jnp.min(jnp.where(others[:, None], of, INF), axis=0)
    stable = jnp.minimum(ps["committed_cnt"], min_others)
    stable = jnp.where(ready & nmask, stable, 0)
    delta = jnp.maximum(stable - ps["prev_stable"], 0)
    prev_stable = jnp.maximum(ps["prev_stable"], stable)

    freed = (ps["seq_in_slot"] > 0) & (
        ps["seq_in_slot"] <= prev_stable[:, None]
    )
    ps = dict(
        ps,
        others_frontier=of,
        seen=seen,
        prev_stable=prev_stable,
        m_stable=ps["m_stable"] + jnp.sum(delta),
        seq_in_slot=jnp.where(freed, 0, ps["seq_in_slot"]),
        buffered_commit=jnp.where(freed, False, ps["buffered_commit"]),
    )
    return ps, empty_outbox(dims)

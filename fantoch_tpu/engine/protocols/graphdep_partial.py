"""Device Atlas with partial replication and multi-key commands.

The partial-mode twin of :class:`AtlasDev` — the dependency-protocol
core (fantoch_ps/src/protocol/atlas.rs, host oracle protocol/atlas.py)
plus the reference's shard coordination and the graph executor's
cross-shard dependency protocol:

- ``MForwardSubmit`` hands the dot to the closest process of every
  other touched shard (partial.rs:8-35); each shard runs its own
  collect round over *its* keys' dependencies;
- per-shard dep sets aggregate at the dot owner — ``MShardCommit``
  carries a shard's decided deps, the owner unions them and sends
  ``MShardAggregatedCommit`` back (partial.rs:37-167, atlas.py
  _handle_mshard_commit: the aggregation is a set union); every shard
  coordinator then broadcasts the final ``MCommit`` inside its shard;
- the graph executor requests vertices owned by remote shards
  (executor-to-executor ``Request``/``RequestReply``,
  executor/graph/mod.rs:279-408): a committed-but-blocked dependency
  whose command does not touch this shard is fetched from the closest
  process of the dot owner's shard; the responder answers with the
  vertex (command identity + its aggregated deps) or an
  executed marker, buffering unknown dots until the periodic cleanup
  tick re-checks them (task/server/executor.rs:281-330);
- clients aggregate per-key result partials — the engine core's
  ``cmd_parts`` completion counting; a vertex executes all of this
  shard's keys at once (graph/mod.rs _execute).

Dependencies are (source, sequence, shard-bitmask) triples: the mask
is the dep command's touched shards, which decides replicated-here
(request needed?) exactly like the reference's ``Dependency.shards``
(deps/keys/mod.rs:19-35). Commands are otherwise ctx-determined by
(client, cseq) via the lane's ``cmd_skey``/``cmd_kmask`` tables
(engine/spec.py), so messages carry identity, not key lists.

Single-shard single-key lanes should use :class:`AtlasDev`; this class
exists for ``shard_count > 1`` / ``keys_per_cmd > 1`` lanes and matches
the oracle on tie-free schedules (tests/test_engine_partial.py).
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np

from ..core import (
    I32, compact_order, emit, emit_broadcast, empty_outbox, oh_get,
    oh_set, oh_set2, oh_take,
)
from ..dims import (
    ERR_CAPACITY, ERR_DOT, ERR_PROTO, ERR_SEQ, INF, SEQ_BOUND, EngineDims,
    dot_slot,
)
from ..iset import iset_add, iset_contains, iset_contains_gathered
from .graphdep import AtlasDev
from .tempo_partial import (
    _cmd_tables,
    _get2,
    _my_keys,
    _p_mgc,
    _popcount,
    _shard_base,
    _shard_mask,
)


class AtlasPartialDev(AtlasDev):
    SUBMIT = 0
    MCOLLECT = 1
    MCOLLECTACK = 2
    MCOMMIT = 3
    MCONSENSUS = 4
    MCONSENSUSACK = 5
    MGC = 6
    MDRAIN = 7
    MFWDSUBMIT = 8
    MSHARDCOMMIT = 9
    MSHARDAGG = 10
    GREQ = 11
    GREPLY = 12
    GREPLYEXEC = 13
    NUM_TYPES = 14
    TO_CLIENT = 15

    PERIODIC_ROWS = 2  # [garbage collection, executor cleanup]
    # the partial twin's handlers don't carry the safety-monitor hooks
    # (fuzzing is single-shard, like fault plans) — don't inherit the
    # base class's capability flag
    MONITORED = False

    def __init__(
        self,
        keys: int,
        shards: int = 2,
        keys_per_cmd: int = 2,
        gap_slots: int = 8,
        # buffered cross-shard requests awaiting a local commit; grows
        # with shard count x in-flight commands (reference-scale runs
        # at 4 shards measured ERR_CAPACITY at 8)
        req_buffer: int = 16,
    ):
        super().__init__(keys, gap_slots)
        self.S = shards
        self.KPC = keys_per_cmd
        self.B = req_buffer

    # -- host-side builders -------------------------------------------

    def q_shard(self, n: int) -> int:
        """Per-shard dep-slot bound: each of the n reporters contributes
        up to KPC latest deps plus the coordinator's KPC."""
        return self.KPC * (n + 1)

    def q_union(self, n: int) -> int:
        """Aggregated (cross-shard union) dep bound."""
        return self.S * self.q_shard(n)

    def payload_width(self, n: int) -> int:
        # MCommit/GReply: [dsrc, dseq, client, cseq, nd] + 3 * QS
        # MGC: the committed frontier over all S*n sources
        return max(5 + 3 * self.q_union(n), self.S * n, 8)

    def fanout(self, n: int) -> int:
        N = self.S * n
        # shard broadcast + forwards; cleanup replies ride slots
        # N+1..N+B on the periodic outbox; drain needs KPC client
        # partials + one request + the chain slot
        return max(N + self.B + 2, N + self.S + 2, self.KPC + 3)

    def periodic_intervals(self, config, dims: EngineDims):
        gc = config.gc_interval_ms
        cl = config.executor_cleanup_interval_ms
        return [gc if gc is not None else INF, cl if cl else INF]

    def lane_ctx(self, config, dims: EngineDims, sorted_idx: np.ndarray):
        N, n, S = dims.N, config.n, config.shard_count
        fq_size, wq_size = self._quorum_sizes(config)
        fq = np.zeros((N, N), bool)
        wq = np.zeros((N, N), bool)
        for s in range(S):
            for p in range(n):
                row = s * n + p
                for member in sorted_idx[p][:fq_size]:
                    fq[row, s * n + member] = True
                for member in sorted_idx[p][:wq_size]:
                    wq[row, s * n + member] = True
        ack_self = self._ack_self()
        return {
            "fast_quorum": fq,
            "write_quorum": wq,
            "expected_acks": np.int32(fq_size if ack_self else fq_size - 1),
            "fp_mode": np.int32(self._fp_mode()),
            "ack_self": np.bool_(ack_self),
        }

    def init_state(self, dims: EngineDims, ctx_np) -> Dict[str, np.ndarray]:
        N, D, G = dims.N, dims.D, self.G
        n = int(ctx_np["n"])
        K, KPC, B = self.K, self.KPC, self.B
        Q, QS = self.q_shard(n), self.q_union(n)
        return {
            # conflict index: latest dep per key, with its command's
            # shard mask (Dependency.shards)
            "latest_src": np.zeros((N, K), np.int32),
            "latest_seq": np.zeros((N, K), np.int32),
            "latest_km": np.zeros((N, K), np.int32),
            # per-dot payload pointers (dot → (client, cseq))
            "seq_in_slot": np.zeros((N, N, D), np.int32),
            "client_of": np.zeros((N, N, D), np.int32),
            "cseq_of": np.zeros((N, N, D), np.int32),
            # coordinator per (dot source, slot): forwarded shard
            # coordinators track foreign dots
            "own_seq": np.zeros((N,), np.int32),
            "ack_cnt": np.zeros((N, N, D), np.int32),
            "slow_acks": np.zeros((N, N, D), np.int32),
            "qd_src": np.zeros((N, N, D, Q), np.int32),
            "qd_seq": np.zeros((N, N, D, Q), np.int32),
            "qd_km": np.zeros((N, N, D, Q), np.int32),
            "qd_cnt": np.zeros((N, N, D, Q), np.int32),
            # shard-union aggregation at the dot owner (own dots)
            "sh_cnt": np.zeros((N, D), np.int32),
            "sh_src": np.zeros((N, D, QS), np.int32),
            "sh_seq": np.zeros((N, D, QS), np.int32),
            "sh_km": np.zeros((N, D, QS), np.int32),
            # graph-executor vertex store (aggregated deps)
            "vx_committed": np.zeros((N, N, D), bool),
            "vx_seq": np.zeros((N, N, D), np.int32),
            "vx_client": np.zeros((N, N, D), np.int32),
            "vx_cseq": np.zeros((N, N, D), np.int32),
            "vx_nd": np.zeros((N, N, D), np.int32),
            "vx_dep_src": np.zeros((N, N, D, QS), np.int32),
            "vx_dep_seq": np.zeros((N, N, D, QS), np.int32),
            "vx_dep_km": np.zeros((N, N, D, QS), np.int32),
            # cross-shard request bookkeeping: per-dot requested marker
            # + buffered incoming requests (requester row, dsrc, dseq)
            "req_seq": np.zeros((N, N, D), np.int32),
            "breq_from": np.full((N, B), -1, np.int32),
            "breq_src": np.zeros((N, B), np.int32),
            "breq_seq": np.zeros((N, B), np.int32),
            # executed clock per source
            "exec_front": np.zeros((N, N), np.int32),
            "exec_gaps": np.zeros((N, N, G, 2), np.int32),
            # committed-clock GC (own-shard sources only)
            "comm_front": np.zeros((N, N), np.int32),
            "comm_gaps": np.zeros((N, N, G, 2), np.int32),
            "others_frontier": np.zeros((N, N, N), np.int32),
            "seen": np.zeros((N, N), bool),
            "prev_stable": np.zeros((N, N), np.int32),
            "m_fast": np.zeros((N,), np.int32),
            "m_slow": np.zeros((N,), np.int32),
            "m_stable": np.zeros((N,), np.int32),
            "err": np.zeros((N,), np.int32),
        }

    # -- device handlers ----------------------------------------------

    def ready(self, ps, msg, me, ctx, dims: EngineDims):
        t = msg["mtype"]
        dsrc, dseq = msg["payload"][0], msg["payload"][1]
        slot = dot_slot(dseq, dims)
        free = (
            (_get2(ps["seq_in_slot"], dsrc, slot) == 0)
            & (_get2(ps["vx_seq"], dsrc, slot) == 0)
        )
        have = _get2(ps["seq_in_slot"], dsrc, slot) == dseq
        ok = jnp.where(t == self.MCOLLECT, free, True)
        needs_payload = (
            (t == self.MCOMMIT)
            | (t == self.MSHARDCOMMIT)
            | (t == self.MSHARDAGG)
        )
        return jnp.where(needs_payload, have, ok)

    def handle(self, ps, msg, me, now, ctx, dims: EngineDims):
        def _noop(ps, msg):
            return ps, empty_outbox(dims)

        branches = [
            lambda ps, msg: _g_submit(self, ps, msg, me, ctx, dims),
            lambda ps, msg: _g_mcollect(self, ps, msg, me, ctx, dims),
            lambda ps, msg: _g_mcollectack(self, ps, msg, me, ctx, dims),
            lambda ps, msg: _g_mcommit(self, ps, msg, me, ctx, dims),
            lambda ps, msg: _g_mconsensus(self, ps, msg, me, ctx, dims),
            lambda ps, msg: _g_mconsensusack(self, ps, msg, me, ctx, dims),
            lambda ps, msg: _g_mgc(self, ps, msg, me, ctx, dims),
            lambda ps, msg: _g_mdrain(self, ps, msg, me, ctx, dims),
            lambda ps, msg: _g_mfwdsubmit(self, ps, msg, me, ctx, dims),
            lambda ps, msg: _g_mshardcommit(self, ps, msg, me, ctx, dims),
            lambda ps, msg: _g_mshardagg(self, ps, msg, me, ctx, dims),
            lambda ps, msg: _g_request(self, ps, msg, me, ctx, dims),
            lambda ps, msg: _g_reply(self, ps, msg, me, ctx, dims),
            lambda ps, msg: _g_replyexec(self, ps, msg, me, ctx, dims),
            _noop,
        ]
        idx = jnp.clip(msg["mtype"], 0, self.NUM_TYPES)
        return jax.lax.switch(idx, branches, ps, msg)

    def periodic(self, ps, fire, me, now, ctx, dims: EngineDims):
        """Row 0: GC frontier broadcast within this shard. Row 1: the
        executor cleanup tick — answer buffered cross-shard requests
        whose dots have since committed or executed locally
        (task/server/executor.rs:281-330; GraphExecutor.cleanup)."""
        base = _shard_base(ctx, me)
        ob = emit_broadcast(
            empty_outbox(dims),
            self.MGC,
            ps["comm_front"],
            ctx["n"],
            me,
            exclude_me=True,
            base=base,
        )
        ob = dict(ob, valid=ob["valid"] & fire[0])
        ps, ob = _g_cleanup(self, ps, me, ctx, dims, ob, fire[1])
        return ps, ob

# ----------------------------------------------------------------------
# dep-set helpers: (src, seq, kmask) triples in fixed-width tables
# ----------------------------------------------------------------------


def _dep_row_add(src_row, seq_row, km_row, cnt_row, dsrc, dseq, dkm,
                 enable):
    """Merge one dep into a table row (QuorumDeps.add, quorum.rs:24-34):
    bump its report count when present, else take a free slot. Returns
    (src, seq, km, cnt, overflow)."""
    Q = src_row.shape[0]
    do = jnp.asarray(enable, bool) & (dseq > 0)
    match = (seq_row == dseq) & (src_row == dsrc)
    found = jnp.any(match)
    midx = jnp.argmax(match)
    free = seq_row == 0
    fidx = jnp.argmax(free)
    overflow = do & ~found & ~jnp.any(free)
    widx = jnp.where(do & ~overflow, jnp.where(found, midx, fidx), Q)
    hit = jnp.arange(Q, dtype=I32) == widx
    src_row = jnp.where(hit, dsrc, src_row)
    seq_row = jnp.where(hit, dseq, seq_row)
    km_row = jnp.where(hit, dkm, km_row)
    cnt_row = jnp.where(hit, jnp.where(found, cnt_row + 1, 1), cnt_row)
    return src_row, seq_row, km_row, cnt_row, overflow


def _pack_deps(pay, lo_base, src_row, seq_row, km_row, present, limit):
    """Pack present dep triples contiguously into the payload starting
    at ``lo_base``; returns (payload, count)."""
    order, nd = compact_order(present, limit)
    P = pay.shape[0]
    # bound the INF sentinel before the affine packing math: masked
    # entries pick P below anyway, and 3 * INF would wrap i32
    safe_order = jnp.minimum(order, limit)
    lo = jnp.where(order < limit, lo_base + 3 * safe_order, P)
    iota = jnp.arange(P, dtype=I32)
    oh0 = lo[:, None] == iota[None, :]
    oh1 = (lo + 1)[:, None] == iota[None, :]
    oh2 = (lo + 2)[:, None] == iota[None, :]
    pay = pay + jnp.sum(
        jnp.where(oh0, src_row[:, None], 0)
        + jnp.where(oh1, seq_row[:, None], 0)
        + jnp.where(oh2, km_row[:, None], 0),
        axis=0,
        dtype=I32,
    )
    return pay, nd


def _take_deps(payload, lo_base, count, slots):
    """Read up to ``slots`` dep triples from the payload; entries at or
    past ``count`` zero out."""
    idxs = lo_base + 3 * jnp.arange(slots, dtype=I32)
    en = jnp.arange(slots, dtype=I32) < count
    dsrc = jnp.where(en, oh_take(payload, idxs), 0)
    dseq = jnp.where(en, oh_take(payload, idxs + 1), 0)
    dkm = jnp.where(en, oh_take(payload, idxs + 2), 0)
    return dsrc, dseq, dkm


# ----------------------------------------------------------------------
# submit / forward / collect
# ----------------------------------------------------------------------


def _g_own_deps(pp, ps, keys):
    """This shard's latest dep per command key, deduplicated — the
    coordinator/member side of key_deps.add_cmd (sequential.rs:62-86)
    before the latest pointers move. Returns [KPC] triples."""
    valid = keys >= 0
    dsrc = jnp.where(valid, oh_take(ps["latest_src"], keys), 0)
    dseq = jnp.where(valid, oh_take(ps["latest_seq"], keys), 0)
    dkm = jnp.where(valid, oh_take(ps["latest_km"], keys), 0)
    # drop duplicates (two keys sharing one latest dot) and empties
    keep = dseq > 0
    for i in range(1, pp.KPC):
        for j in range(i):
            dup = (dsrc[i] == dsrc[j]) & (dseq[i] == dseq[j])
            keep = keep.at[i].set(keep[i] & ~dup)
    return (
        jnp.where(keep, dsrc, 0),
        jnp.where(keep, dseq, 0),
        jnp.where(keep, dkm, 0),
    )


def _g_bump_latest(pp, ps, keys, dsrc, dseq, kmask, enable):
    """Point every command key's latest dep at this dot."""
    latest_src, latest_seq, latest_km = (
        ps["latest_src"], ps["latest_seq"], ps["latest_km"],
    )
    for d in range(pp.KPC):
        k = jnp.where(
            jnp.asarray(enable, bool) & (keys[d] >= 0), keys[d], -1
        )
        latest_src = oh_set(latest_src, k, dsrc)
        latest_seq = oh_set(latest_seq, k, dseq)
        latest_km = oh_set(latest_km, k, kmask)
    return dict(
        ps,
        latest_src=latest_src,
        latest_seq=latest_seq,
        latest_km=latest_km,
    )


def _g_start(pp, ps, dsrc, dseq, client, cseq, me, ctx, dims, forward):
    """Shared coordinator start (atlas.rs:210-248 at the target shard;
    MForwardSubmit runs the same flow without re-forwarding)."""
    kmask, skey = _cmd_tables(ctx, client, cseq)
    keys = _my_keys(pp, ctx, me, skey)
    slot = dot_slot(dseq, dims)
    n = ctx["n"]

    d_src, d_seq, d_km = _g_own_deps(pp, ps, keys)
    ps = _g_bump_latest(pp, ps, keys, dsrc, dseq, kmask, True)

    for name in ("ack_cnt", "slow_acks"):
        ps = dict(ps, **{name: oh_set2(ps[name], dsrc, slot, 0)})
    zero_q = jnp.zeros_like(_get2(ps["qd_src"], dsrc, slot))
    ps = dict(
        ps,
        qd_src=oh_set2(ps["qd_src"], dsrc, slot, zero_q),
        qd_seq=oh_set2(ps["qd_seq"], dsrc, slot, zero_q),
        qd_km=oh_set2(ps["qd_km"], dsrc, slot, zero_q),
        qd_cnt=oh_set2(ps["qd_cnt"], dsrc, slot, zero_q),
    )

    pay = jnp.zeros((dims.P,), I32)
    pay = (
        pay.at[0].set(dsrc).at[1].set(dseq)
        .at[2].set(client).at[3].set(cseq)
    )
    pay, nd = _pack_deps(pay, 5, d_src, d_seq, d_km, d_seq > 0, pp.KPC)
    pay = pay.at[4].set(nd)
    base = _shard_base(ctx, me)
    ob = emit_broadcast(
        empty_outbox(dims), pp.MCOLLECT, pay, n, base=base
    )
    if forward:
        ps = dict(
            ps,
            sh_cnt=oh_set(ps["sh_cnt"], slot, 0),
            sh_src=oh_set(
                ps["sh_src"], slot, jnp.zeros_like(ps["sh_src"][0])
            ),
            sh_seq=oh_set(
                ps["sh_seq"], slot, jnp.zeros_like(ps["sh_seq"][0])
            ),
            sh_km=oh_set(
                ps["sh_km"], slot, jnp.zeros_like(ps["sh_km"][0])
            ),
        )
        s_me = oh_get(ctx["shard_of"], me)
        for s in range(pp.S):
            touched = ((kmask >> s) & 1) == 1
            ob = emit(
                ob,
                dims.N + s,
                oh_get(oh_get(ctx["closest"], me), jnp.int32(s)),
                pp.MFWDSUBMIT,
                [dsrc, dseq, client, cseq],
                valid=touched & (s != s_me),
            )
    return ps, ob


def _g_submit(pp, ps, msg, me, ctx, dims):
    client, cseq = msg["payload"][0], msg["payload"][1]
    dseq = ps["own_seq"] + 1
    ps = dict(
        ps,
        own_seq=dseq,
        err=ps["err"] | ERR_SEQ * (dseq >= SEQ_BOUND),
    )
    return _g_start(
        pp, ps, me, dseq, client, cseq, me, ctx, dims, forward=True
    )


def _g_mfwdsubmit(pp, ps, msg, me, ctx, dims):
    dsrc, dseq, client, cseq = (
        msg["payload"][0],
        msg["payload"][1],
        msg["payload"][2],
        msg["payload"][3],
    )
    return _g_start(
        pp, ps, dsrc, dseq, client, cseq, me, ctx, dims, forward=False
    )


def _g_mcollect(pp, ps, msg, me, ctx, dims):
    """atlas.rs:250-323 with the dot source decoupled from the sender
    (the shard coordinator)."""
    coord = msg["src"]
    dsrc, dseq, client, cseq, cnd = (
        msg["payload"][0],
        msg["payload"][1],
        msg["payload"][2],
        msg["payload"][3],
        msg["payload"][4],
    )
    slot = dot_slot(dseq, dims)
    dirty = (
        (_get2(ps["seq_in_slot"], dsrc, slot) != 0)
        | (_get2(ps["vx_seq"], dsrc, slot) != 0)
    )
    ps = dict(
        ps,
        err=ps["err"] | ERR_DOT * dirty,
        seq_in_slot=oh_set2(ps["seq_in_slot"], dsrc, slot, dseq),
        client_of=oh_set2(ps["client_of"], dsrc, slot, client),
        cseq_of=oh_set2(ps["cseq_of"], dsrc, slot, cseq),
    )
    in_q = oh_get(oh_get(ctx["fast_quorum"], coord), me)
    from_self = coord == me

    kmask, skey = _cmd_tables(ctx, client, cseq)
    keys = _my_keys(pp, ctx, me, skey)
    c_src, c_seq, c_km = _take_deps(msg["payload"], 5, cnd, pp.KPC)

    # member: own latest per key union the coordinator's (add_cmd with
    # past deps, sequential.rs:62-86); the self-collect acks the
    # coordinator's own deps unchanged
    member = in_q & ~from_self
    o_src, o_seq, o_km = _g_own_deps(pp, ps, keys)
    ps2 = _g_bump_latest(pp, ps, keys, dsrc, dseq, kmask, True)
    ps = jax.tree_util.tree_map(
        lambda a, b: jnp.where(member, a, b), ps2, ps
    )
    # drop coordinator entries duplicating the member's own
    keep = c_seq > 0
    for i in range(pp.KPC):
        for j in range(pp.KPC):
            dup = (c_src[i] == o_src[j]) & (c_seq[i] == o_seq[j]) & (
                o_seq[j] > 0
            )
            keep = keep.at[i].set(keep[i] & ~dup)
    a_src = jnp.concatenate([o_src, jnp.where(keep, c_src, 0)])
    a_seq = jnp.concatenate([o_seq, jnp.where(keep, c_seq, 0)])
    a_km = jnp.concatenate([o_km, jnp.where(keep, c_km, 0)])
    # the self-ack reports exactly the coordinator's deps
    self_src = jnp.concatenate([c_src, jnp.zeros_like(c_src)])
    self_seq = jnp.concatenate([c_seq, jnp.zeros_like(c_seq)])
    self_km = jnp.concatenate([c_km, jnp.zeros_like(c_km)])
    a_src = jnp.where(member, a_src, self_src)
    a_seq = jnp.where(member, a_seq, self_seq)
    a_km = jnp.where(member, a_km, self_km)

    pay = jnp.zeros((dims.P,), I32)
    pay = pay.at[0].set(dsrc).at[1].set(dseq)
    pay, nd = _pack_deps(
        pay, 3, a_src, a_seq, a_km, a_seq > 0, 2 * pp.KPC
    )
    pay = pay.at[2].set(nd)
    ack = in_q & (ctx["ack_self"] | ~from_self)
    ob = emit(
        empty_outbox(dims), 0, coord, pp.MCOLLECTACK, pay, valid=ack
    )
    return ps, ob

# ----------------------------------------------------------------------
# collect-ack / commit paths
# ----------------------------------------------------------------------


def _g_mcollectack(pp, ps, msg, me, ctx, dims):
    """atlas.rs:325-391 at the shard coordinator (possibly of a foreign
    dot): aggregate dep reports, run the fast-path predicate on the
    last expected ack."""
    dsrc, dseq, nd = (
        msg["payload"][0],
        msg["payload"][1],
        msg["payload"][2],
    )
    slot = dot_slot(dseq, dims)
    r_src, r_seq, r_km = _take_deps(msg["payload"], 3, nd, 2 * pp.KPC)

    src_row = _get2(ps["qd_src"], dsrc, slot)
    seq_row = _get2(ps["qd_seq"], dsrc, slot)
    km_row = _get2(ps["qd_km"], dsrc, slot)
    cnt_row = _get2(ps["qd_cnt"], dsrc, slot)
    overflow = jnp.asarray(False)
    for i in range(2 * pp.KPC):
        src_row, seq_row, km_row, cnt_row, ovf = _dep_row_add(
            src_row, seq_row, km_row, cnt_row,
            r_src[i], r_seq[i], r_km[i], True,
        )
        overflow = overflow | ovf
    cnt = _get2(ps["ack_cnt"], dsrc, slot) + 1
    ps = dict(
        ps,
        qd_src=oh_set2(ps["qd_src"], dsrc, slot, src_row),
        qd_seq=oh_set2(ps["qd_seq"], dsrc, slot, seq_row),
        qd_km=oh_set2(ps["qd_km"], dsrc, slot, km_row),
        qd_cnt=oh_set2(ps["qd_cnt"], dsrc, slot, cnt_row),
        ack_cnt=oh_set2(ps["ack_cnt"], dsrc, slot, cnt),
        err=ps["err"] | ERR_CAPACITY * overflow,
    )

    all_acks = cnt == ctx["expected_acks"]
    present = seq_row > 0
    threshold = jnp.where(
        ctx["fp_mode"] == 0, ctx["f"], ctx["expected_acks"]
    )
    fp_ok = jnp.all(~present | (cnt_row >= threshold))
    fast = all_acks & fp_ok
    slow = all_acks & ~fast
    ps = dict(
        ps,
        m_fast=ps["m_fast"] + fast.astype(I32),
        m_slow=ps["m_slow"] + slow.astype(I32),
    )

    client = _get2(ps["client_of"], dsrc, slot)
    cseq = _get2(ps["cseq_of"], dsrc, slot)
    kmask, _ = _cmd_tables(ctx, client, cseq)
    ob = _g_commit_actions(
        pp, ps, me, dsrc, dseq, client, cseq, kmask, ctx, dims, fast
    )
    base = _shard_base(ctx, me)
    obc = emit_broadcast(
        empty_outbox(dims),
        pp.MCONSENSUS,
        [dsrc, dseq],
        ctx["n"],
        base=base,
    )
    procs = jnp.arange(dims.F, dtype=I32) + base
    wq = oh_take(
        oh_get(ctx["write_quorum"], me),
        jnp.clip(procs, 0, dims.N - 1),
    )
    obc = dict(obc, valid=obc["valid"] & slow & wq)
    ob = jax.tree_util.tree_map(
        lambda a, b: jnp.where(
            fast.reshape((-1,) + (1,) * (a.ndim - 1)) if a.ndim > 1 else fast,
            a,
            b,
        ),
        ob,
        obc,
    )
    return ps, ob


def _g_commit_actions(
    pp, ps, me, dsrc, dseq, client, cseq, kmask, ctx, dims, valid
):
    """partial.rs:37-101: single-shard commands broadcast MCommit with
    this shard's dep union; multi-shard commands send the union to the
    dot owner as an MShardCommit."""
    nsh = _popcount(kmask, pp.S)
    single = nsh == 1
    slot = dot_slot(dseq, dims)
    src_row = _get2(ps["qd_src"], dsrc, slot)
    seq_row = _get2(ps["qd_seq"], dsrc, slot)
    km_row = _get2(ps["qd_km"], dsrc, slot)
    Q = src_row.shape[0]

    ob_commit = _g_commit_broadcast(
        pp, ps, me, dsrc, dseq, client, cseq,
        src_row, seq_row, km_row, ctx, dims,
        jnp.asarray(valid, bool) & single,
    )
    pay = jnp.zeros((dims.P,), I32)
    pay = pay.at[0].set(dsrc).at[1].set(dseq)
    pay, nd = _pack_deps(pay, 3, src_row, seq_row, km_row, seq_row > 0, Q)
    pay = pay.at[2].set(nd)
    ob_shard = emit(
        empty_outbox(dims),
        0,
        dsrc,
        pp.MSHARDCOMMIT,
        pay,
        valid=jnp.asarray(valid, bool) & ~single,
    )
    return jax.tree_util.tree_map(
        lambda a, b: jnp.where(
            single.reshape((-1,) + (1,) * (a.ndim - 1))
            if a.ndim > 1
            else single,
            a,
            b,
        ),
        ob_commit,
        ob_shard,
    )


def _g_commit_broadcast(
    pp, ps, me, dsrc, dseq, client, cseq, src_row, seq_row, km_row,
    ctx, dims, valid,
):
    Q = src_row.shape[0]
    pay = jnp.zeros((dims.P,), I32)
    pay = (
        pay.at[0].set(dsrc).at[1].set(dseq)
        .at[2].set(client).at[3].set(cseq)
    )
    pay, nd = _pack_deps(pay, 5, src_row, seq_row, km_row, seq_row > 0, Q)
    pay = pay.at[4].set(nd)
    base = _shard_base(ctx, me)
    ob = emit_broadcast(
        empty_outbox(dims), pp.MCOMMIT, pay, ctx["n"], base=base
    )
    return dict(ob, valid=ob["valid"] & jnp.asarray(valid, bool))


def _g_mshardcommit(pp, ps, msg, me, ctx, dims):
    """partial.rs:103-142 at the dot owner: union each shard's deps;
    when every touched shard reported, send the union back to the
    participants."""
    dsrc, dseq, nd = (
        msg["payload"][0],
        msg["payload"][1],
        msg["payload"][2],
    )
    slot = dot_slot(dseq, dims)
    ps = dict(ps, err=ps["err"] | ERR_PROTO * (dsrc != me))
    n = int(dims.N // pp.S)
    Q = pp.q_shard(n)
    r_src, r_seq, r_km = _take_deps(msg["payload"], 3, nd, Q)

    src_row = oh_get(ps["sh_src"], slot)
    seq_row = oh_get(ps["sh_seq"], slot)
    km_row = oh_get(ps["sh_km"], slot)
    cnt_row = jnp.zeros_like(src_row)  # counts unused for the union
    overflow = jnp.asarray(False)
    for i in range(Q):
        src_row, seq_row, km_row, cnt_row, ovf = _dep_row_add(
            src_row, seq_row, km_row, cnt_row,
            r_src[i], r_seq[i], r_km[i], True,
        )
        overflow = overflow | ovf
    scnt = oh_get(ps["sh_cnt"], slot) + 1
    ps = dict(
        ps,
        sh_src=oh_set(ps["sh_src"], slot, src_row),
        sh_seq=oh_set(ps["sh_seq"], slot, seq_row),
        sh_km=oh_set(ps["sh_km"], slot, km_row),
        sh_cnt=oh_set(ps["sh_cnt"], slot, scnt),
        err=ps["err"] | ERR_CAPACITY * overflow,
    )

    client = _get2(ps["client_of"], me, slot)
    cseq = _get2(ps["cseq_of"], me, slot)
    kmask, _ = _cmd_tables(ctx, client, cseq)
    done = scnt == _popcount(kmask, pp.S)
    pay = jnp.zeros((dims.P,), I32)
    pay = pay.at[0].set(dsrc).at[1].set(dseq)
    pay, und = _pack_deps(
        pay, 3, src_row, seq_row, km_row, seq_row > 0, src_row.shape[0]
    )
    pay = pay.at[2].set(und)
    ob = emit(empty_outbox(dims), 0, me, pp.MSHARDAGG, pay, valid=done)
    s_me = oh_get(ctx["shard_of"], me)
    for s in range(pp.S):
        touched = ((kmask >> s) & 1) == 1
        ob = emit(
            ob,
            1 + s,
            oh_get(oh_get(ctx["closest"], me), jnp.int32(s)),
            pp.MSHARDAGG,
            pay,
            valid=done & touched & (s != s_me),
        )
    return ps, ob


def _g_mshardagg(pp, ps, msg, me, ctx, dims):
    """partial.rs:144-167 at each shard coordinator: broadcast the
    final MCommit inside this shard with the aggregated union."""
    dsrc, dseq, nd = (
        msg["payload"][0],
        msg["payload"][1],
        msg["payload"][2],
    )
    slot = dot_slot(dseq, dims)
    client = _get2(ps["client_of"], dsrc, slot)
    cseq = _get2(ps["cseq_of"], dsrc, slot)
    n = int(dims.N // pp.S)
    QS = pp.q_union(n)
    r_src, r_seq, r_km = _take_deps(msg["payload"], 3, nd, QS)
    ob = _g_commit_broadcast(
        pp, ps, me, dsrc, dseq, client, cseq,
        r_src, r_seq, r_km, ctx, dims, True,
    )
    return ps, ob


def _g_mcommit(pp, ps, msg, me, ctx, dims):
    """atlas.rs:393-464: install the vertex with the aggregated deps,
    record the commit for GC (own-shard dots only), drain the graph."""
    dsrc = msg["payload"][0]
    dseq = msg["payload"][1]
    client = msg["payload"][2]
    cseq = msg["payload"][3]
    nd = msg["payload"][4]
    slot = dot_slot(dseq, dims)
    n = int(dims.N // pp.S)
    QS = pp.q_union(n)

    have = _get2(ps["seq_in_slot"], dsrc, slot) == dseq
    already = _get2(ps["vx_seq"], dsrc, slot) == dseq
    do = have & ~already
    ps = dict(ps, err=ps["err"] | ERR_PROTO * ~have)

    d_src, d_seq, d_km = _take_deps(msg["payload"], 5, nd, QS)
    wsrc = jnp.where(do, dsrc, dims.N)
    ps = dict(
        ps,
        vx_committed=oh_set2(ps["vx_committed"], wsrc, slot, True),
        vx_seq=oh_set2(ps["vx_seq"], wsrc, slot, dseq),
        vx_client=oh_set2(ps["vx_client"], wsrc, slot, client),
        vx_cseq=oh_set2(ps["vx_cseq"], wsrc, slot, cseq),
        vx_nd=oh_set2(ps["vx_nd"], wsrc, slot, nd),
        vx_dep_src=oh_set2(ps["vx_dep_src"], wsrc, slot, d_src),
        vx_dep_seq=oh_set2(ps["vx_dep_seq"], wsrc, slot, d_seq),
        vx_dep_km=oh_set2(ps["vx_dep_km"], wsrc, slot, d_km),
    )

    my_dot = oh_get(ctx["shard_of"], dsrc) == oh_get(ctx["shard_of"], me)
    cf, cg, overflow = iset_add(
        oh_get(ps["comm_front"], dsrc),
        oh_get(ps["comm_gaps"], dsrc),
        dseq,
        enable=do & my_dot,
    )
    ps = dict(
        ps,
        comm_front=oh_set(ps["comm_front"], dsrc, cf),
        comm_gaps=oh_set(ps["comm_gaps"], dsrc, cg),
        err=ps["err"] | ERR_CAPACITY * overflow,
        # foreign dots free their payload slot now (gc_single); the
        # vertex itself lives until executed
        seq_in_slot=oh_set2(
            ps["seq_in_slot"], dsrc, slot,
            jnp.where(my_dot, dseq, 0),
        ),
    )
    return _g_drain(pp, ps, me, ctx, dims, empty_outbox(dims))


def _g_mconsensus(pp, ps, msg, me, ctx, dims):
    dsrc, dseq = msg["payload"][0], msg["payload"][1]
    ob = emit(
        empty_outbox(dims),
        0,
        msg["src"],
        pp.MCONSENSUSACK,
        [dsrc, dseq],
    )
    return ps, ob


def _g_mconsensusack(pp, ps, msg, me, ctx, dims):
    dsrc, dseq = msg["payload"][0], msg["payload"][1]
    slot = dot_slot(dseq, dims)
    cnt = _get2(ps["slow_acks"], dsrc, slot) + 1
    chosen = cnt == ctx["f"] + 1
    ps = dict(
        ps, slow_acks=oh_set2(ps["slow_acks"], dsrc, slot, cnt)
    )
    client = _get2(ps["client_of"], dsrc, slot)
    cseq = _get2(ps["cseq_of"], dsrc, slot)
    kmask, _ = _cmd_tables(ctx, client, cseq)
    ob = _g_commit_actions(
        pp, ps, me, dsrc, dseq, client, cseq, kmask, ctx, dims, chosen
    )
    return ps, ob

# ----------------------------------------------------------------------
# graph-executor drain: relaxation + cross-shard requests
# ----------------------------------------------------------------------


def _g_drain(pp, ps, me, ctx, dims, ob):
    """Execute one dot whose transitive dep closure is committed
    (graphdep._drain's relaxation), then fetch one missing
    foreign-shard dependency if any blocked vertex needs it
    (executor/graph/mod.rs:279-367's Request path)."""
    N, D = dims.N, dims.D
    dep_src = ps["vx_dep_src"]  # [N, D, QS]
    dep_seq = ps["vx_dep_seq"]
    dep_km = ps["vx_dep_km"]
    dslot = dot_slot(dep_seq, dims)

    absent = dep_seq == 0
    dep_executed = iset_contains_gathered(
        ps["exec_front"], ps["exec_gaps"], dep_src, dep_seq
    )
    dep_cell_valid = ps["vx_seq"][dep_src, dslot] == dep_seq
    dep_pass_static = absent | dep_executed

    def body(carry):
        ok, _changed = carry
        dep_ok = ok[dep_src, dslot] & dep_cell_valid
        new_ok = ok & jnp.all(dep_pass_static | dep_ok, axis=2)
        return new_ok, jnp.any(new_ok != ok)

    ok0 = ps["vx_committed"]
    ok, _ = jax.lax.while_loop(
        lambda c: c[1], body, (ok0, jnp.asarray(True))
    )

    num_ok = jnp.sum(ok)
    ready = ok & jnp.all(dep_pass_static, axis=2)
    sel = jnp.where(jnp.any(ready), ready, ok)
    srcs = jnp.arange(N, dtype=I32)[:, None]
    packed = srcs * SEQ_BOUND + ps["vx_seq"]
    flat_idx = jnp.argmin(jnp.where(sel, packed, INF))
    esrc, eslot = flat_idx // D, flat_idx % D
    eseq = _get2(ps["vx_seq"], esrc, eslot)
    client = _get2(ps["vx_client"], esrc, eslot)
    cseq = _get2(ps["vx_cseq"], esrc, eslot)

    do = num_ok > 0
    front, gaps, overflow = iset_add(
        oh_get(ps["exec_front"], esrc), oh_get(ps["exec_gaps"], esrc),
        eseq, do,
    )
    ps = dict(
        ps,
        exec_front=oh_set(ps["exec_front"], esrc, front),
        exec_gaps=oh_set(ps["exec_gaps"], esrc, gaps),
        vx_committed=oh_set2(
            ps["vx_committed"], jnp.where(do, esrc, N), eslot, False
        ),
        vx_seq=oh_set2(ps["vx_seq"], jnp.where(do, esrc, N), eslot, 0),
        err=ps["err"] | ERR_CAPACITY * overflow,
    )
    # execute: one result partial per local key (graph/mod.rs _execute)
    _, skey = _cmd_tables(ctx, client, cseq)
    keys = _my_keys(pp, ctx, me, skey)
    s_me = oh_get(ctx["shard_of"], me)
    connected = oh_get(oh_get(ctx["client_attach_s"], client), s_me) == me
    for d in range(pp.KPC):
        ob = emit(
            ob,
            d,
            dims.N + client,
            pp.TO_CLIENT,
            [0],
            valid=do & connected & (keys[d] >= 0),
        )

    # one request for a blocked foreign dependency: committed vertices
    # with a dep that is neither executed nor locally present, whose
    # command never touches this shard — fetch it from the closest
    # process of the dot owner's shard (mod.rs:279-367). One per drain;
    # the chain re-issues until all are requested.
    still = ps["vx_committed"]
    touches_me = ((dep_km >> s_me) & 1) == 1
    req_done = ps["req_seq"][dep_src, dslot] == dep_seq
    missing = (
        still[:, :, None]
        & ~dep_pass_static
        & ~dep_cell_valid
        & ~touches_me
        & ~req_done
        & (dep_seq > 0)
    )
    any_missing = jnp.any(missing)
    # dep sources ride in from payload words; clamp before the i32
    # (src, seq) packing so a corrupt word cannot wrap it (lint GL001)
    m_packed = jnp.clip(dep_src, 0, dims.N) * SEQ_BOUND + dep_seq
    m_flat = jnp.argmin(jnp.where(missing, m_packed, INF))
    mi = m_flat // (D * missing.shape[2])
    rest = m_flat % (D * missing.shape[2])
    mj, mq = rest // missing.shape[2], rest % missing.shape[2]
    r_src = dep_src[mi, mj, mq]
    r_seq = dep_seq[mi, mj, mq]
    r_shard = oh_get(ctx["shard_of"], r_src)
    ps = dict(
        ps,
        req_seq=oh_set2(
            ps["req_seq"],
            jnp.where(any_missing, r_src, N),
            dot_slot(r_seq, dims),
            r_seq,
        ),
    )
    ob = emit(
        ob,
        pp.KPC,
        oh_get(oh_get(ctx["closest"], me), r_shard),
        pp.GREQ,
        [r_src, r_seq],
        valid=any_missing,
    )
    more = (do & (num_ok > 1)) | (any_missing & (jnp.sum(missing) > 1))
    ob = emit(ob, pp.KPC + 1, me, pp.MDRAIN, [0], valid=more)
    return ps, ob


def _g_mdrain(pp, ps, msg, me, ctx, dims):
    return _g_drain(pp, ps, me, ctx, dims, empty_outbox(dims))


def _g_request(pp, ps, msg, me, ctx, dims):
    """mod.rs:372-393 at the responder: answer with the vertex or an
    executed marker; buffer unknown dots for the cleanup tick."""
    dsrc, dseq = msg["payload"][0], msg["payload"][1]
    from_shard = oh_get(ctx["shard_of"], msg["src"])
    slot = dot_slot(dseq, dims)
    ps, ob, answered = _g_answer(
        pp, ps, me, ctx, dims, empty_outbox(dims), 0, from_shard,
        dsrc, dseq, True,
    )
    # buffer unanswered requests (dedup like the oracle's per-shard set)
    dup = jnp.any(
        (ps["breq_from"] == from_shard)
        & (ps["breq_src"] == dsrc)
        & (ps["breq_seq"] == dseq)
    )
    free = ps["breq_from"] < 0
    fidx = jnp.argmax(free)
    store = ~answered & ~dup
    overflow = store & ~jnp.any(free)
    widx = jnp.where(store & ~overflow, fidx, pp.B)
    ps = dict(
        ps,
        breq_from=oh_set(ps["breq_from"], widx, from_shard),
        breq_src=oh_set(ps["breq_src"], widx, dsrc),
        breq_seq=oh_set(ps["breq_seq"], widx, dseq),
        err=ps["err"] | ERR_CAPACITY * overflow,
    )
    return ps, ob


def _g_answer(pp, ps, me, ctx, dims, ob, slot_i, from_shard, dsrc, dseq,
              enable):
    """Emit a GREPLY (pending vertex) or GREPLYEXEC (already executed)
    for one requested dot; returns (ps, ob, answered)."""
    slot = dot_slot(dseq, dims)
    pending = (
        (_get2(ps["vx_seq"], dsrc, slot) == dseq)
        & _get2(ps["vx_committed"], dsrc, slot)
    )
    executed = iset_contains(
        oh_get(ps["exec_front"], dsrc),
        oh_get(ps["exec_gaps"], dsrc),
        dseq,
    )
    en = jnp.asarray(enable, bool) & (dseq > 0)
    dst = oh_get(oh_get(ctx["closest"], me), from_shard)

    pay = jnp.zeros((dims.P,), I32)
    pay = (
        pay.at[0].set(dsrc).at[1].set(dseq)
        .at[2].set(_get2(ps["vx_client"], dsrc, slot))
        .at[3].set(_get2(ps["vx_cseq"], dsrc, slot))
        .at[4].set(_get2(ps["vx_nd"], dsrc, slot))
    )
    QS = ps["vx_dep_src"].shape[-1]
    d_src = _get2(ps["vx_dep_src"], dsrc, slot)
    d_seq = _get2(ps["vx_dep_seq"], dsrc, slot)
    d_km = _get2(ps["vx_dep_km"], dsrc, slot)
    pay, _nd = _pack_deps(pay, 5, d_src, d_seq, d_km, d_seq > 0, QS)
    pay_exec = jnp.zeros((dims.P,), I32).at[0].set(dsrc).at[1].set(dseq)

    ob = emit(
        ob,
        slot_i,
        dst,
        jnp.where(pending, pp.GREPLY, pp.GREPLYEXEC),
        jnp.where(pending, pay, pay_exec),
        valid=en & (pending | executed),
    )
    return ps, ob, en & (pending | executed)


def _g_reply(pp, ps, msg, me, ctx, dims):
    """mod.rs:395-398 at the requester: install the remote vertex with
    its deps and drain (transitively missing deps re-request through
    the drain chain)."""
    dsrc = msg["payload"][0]
    dseq = msg["payload"][1]
    client = msg["payload"][2]
    cseq = msg["payload"][3]
    nd = msg["payload"][4]
    slot = dot_slot(dseq, dims)
    n = int(dims.N // pp.S)
    QS = pp.q_union(n)
    cell = _get2(ps["vx_seq"], dsrc, slot)
    already = cell == dseq
    # a live different-sequence vertex in this dot slot is a window
    # collision — surface it like MCOLLECT's dirty check (ERR_DOT)
    # instead of silently clobbering the vertex
    dirty = (cell != 0) & ~already
    do = ~already & ~dirty
    ps = dict(ps, err=ps["err"] | ERR_DOT * dirty)
    d_src, d_seq, d_km = _take_deps(msg["payload"], 5, nd, QS)
    wsrc = jnp.where(do, dsrc, dims.N)
    ps = dict(
        ps,
        vx_committed=oh_set2(ps["vx_committed"], wsrc, slot, True),
        vx_seq=oh_set2(ps["vx_seq"], wsrc, slot, dseq),
        vx_client=oh_set2(ps["vx_client"], wsrc, slot, client),
        vx_cseq=oh_set2(ps["vx_cseq"], wsrc, slot, cseq),
        vx_nd=oh_set2(ps["vx_nd"], wsrc, slot, nd),
        vx_dep_src=oh_set2(ps["vx_dep_src"], wsrc, slot, d_src),
        vx_dep_seq=oh_set2(ps["vx_dep_seq"], wsrc, slot, d_seq),
        vx_dep_km=oh_set2(ps["vx_dep_km"], wsrc, slot, d_km),
    )
    return _g_drain(pp, ps, me, ctx, dims, empty_outbox(dims))


def _g_replyexec(pp, ps, msg, me, ctx, dims):
    """mod.rs:399-407: mark the remote dot executed and drain."""
    dsrc, dseq = msg["payload"][0], msg["payload"][1]
    front, gaps, overflow = iset_add(
        oh_get(ps["exec_front"], dsrc),
        oh_get(ps["exec_gaps"], dsrc),
        dseq,
    )
    ps = dict(
        ps,
        exec_front=oh_set(ps["exec_front"], dsrc, front),
        exec_gaps=oh_set(ps["exec_gaps"], dsrc, gaps),
        err=ps["err"] | ERR_CAPACITY * overflow,
    )
    return _g_drain(pp, ps, me, ctx, dims, empty_outbox(dims))


def _g_cleanup(pp, ps, me, ctx, dims, ob, fire):
    """The executor cleanup tick: re-check buffered requests and answer
    the ones whose dots have since committed or executed here."""
    for b in range(pp.B):
        from_shard = ps["breq_from"][b]
        dsrc = ps["breq_src"][b]
        dseq = ps["breq_seq"][b]
        en = jnp.asarray(fire, bool) & (from_shard >= 0)
        ps, ob, answered = _g_answer(
            pp, ps, me, ctx, dims, ob, dims.N + 1 + b, from_shard,
            dsrc, dseq, en,
        )
        clear = jnp.where(answered, b, pp.B)
        ps = dict(
            ps, breq_from=oh_set(ps["breq_from"], clear, -1)
        )
    return ps, ob


def _g_mgc(pp, ps, msg, me, ctx, dims):
    """Committed-clock GC within this shard — identical state shape to
    Tempo's, so the one shard-scoped handler serves both twins."""
    return _p_mgc(pp, ps, msg, me, ctx, dims)

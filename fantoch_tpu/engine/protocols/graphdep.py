"""Device twin of the dependency-based protocols — Atlas
(fantoch_ps/src/protocol/atlas.rs, host oracle:
fantoch_tpu/protocol/atlas.py) and EPaxos (epaxos.rs, host oracle:
fantoch_tpu/protocol/epaxos.py) — sharing one array machinery.

Flow: the coordinator takes its per-key latest-dot as the command's
dependencies and broadcasts MCollect (atlas.rs:210-248); fast-quorum
members merge the coordinator's deps with their own latest-dot and ack
(250-323); the coordinator aggregates per-dependency report counts and
takes the fast path iff

- Atlas: every reported dep was reported by >= f members — the
  threshold-union == union test (atlas.rs:353-390, quorum.rs:46-64);
- EPaxos: all members reported identical dep sets (epaxos.rs:299-364,
  quorum.rs:67-98);

else a single-decree consensus round on the dep set runs through the
write quorum (chosen at model-f+1 accepts, synod/single.rs). Commits
carry (key, client, deps) into the graph executor.

The two protocols differ only in quorum sizes and the fast-path
predicate, so both compile to the same step function; per-lane ctx flags
(``fp_mode``, ``ack_self``, quorum masks) select the behavior — one
compiled sweep can mix Atlas and EPaxos lanes.

Graph executor: the reference executes strongly-connected components of
the dependency graph in topological order via Tarjan with
executed-clock pruning (fantoch_ps/src/executor/graph/tarjan.rs:99-319).
Tarjan's sequential DFS is hostile to SIMT, so the device computes the
*greatest fixed point* of

    ok(d) = committed(d) and for every dep e: executed(e) or ok(e)

by masked relaxation (SURVEY.md §7.1): ok converges to exactly the dots
whose transitive dependency closure is fully committed — the union of
the SCCs Tarjan would pop — because SCC members keep each other in the
set and any uncommitted transitive dep evicts the whole chain. One dot
executes per drain step (DAG-ready dots first, then cycle members, in
(source, sequence) order), chained through zero-delay self-messages so
outbox shapes stay fixed; everything in one chain executes at the same
simulated instant, matching the oracle's batched SCC execution.

Array encoding (per process):
- ``latest_{src,seq}[K]`` — latest-dep-per-key conflict index
  (sequential.rs:8-60);
- ``qd_{src,seq,cnt}[D, Q]`` — the coordinator's per-dot dependency
  report counts (QuorumDeps; Q = N+1 bounds distinct deps because each
  ack carries at most its reporter's latest plus the coordinator's);
- ``vx_*[N, D]`` — the executor's vertex store (committed flag, key,
  client, dep list per dot);
- ``exec_front/exec_gaps`` — per-source executed interval set (execution
  order need not follow sequence order);
- committed-clock GC identical to the Tempo/Basic device flow.

Like the oracle, recovery is not modeled (the reference's is ``todo!``,
atlas.rs:427-430) and commits overtaking their MCollect payload raise
the lane error flag instead of buffering (cannot happen on tie-free
FIFO schedules).
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np

from ..core import (
    I32, compact_order, emit, emit_broadcast, empty_outbox, oh_get,
    oh_pack_pairs, oh_set, oh_set2, oh_take,
)
from ..dims import ERR_CAPACITY, ERR_DOT, ERR_PROTO, ERR_SEQ, INF, SEQ_BOUND, EngineDims, dot_slot
from .identity import DevIdentity
from ..iset import iset_add, iset_contains, iset_contains_gathered
from ..monitor import mon_exec


class _DepDev(DevIdentity):
    """Shared device machinery; subclasses pick quorum formulas and the
    fast-path predicate via lane ctx."""

    SUBMIT = 0
    MCOLLECT = 1
    MCOLLECTACK = 2
    MCOMMIT = 3
    MCONSENSUS = 4
    MCONSENSUSACK = 5
    MGC = 6
    MDRAIN = 7
    NUM_TYPES = 8
    TO_CLIENT = 9

    PERIODIC_ROWS = 1  # garbage collection
    MONITORED = True  # mon_exec hook at the graph executor's drain
    # per-command counters the sweep driver may store narrowed
    # (engine/spec.py narrow_spec): m_fast/m_slow increment once per
    # command at its coordinator, m_stable once per command per process
    # at GC — a lane's total command budget bounds every entry (Atlas,
    # EPaxos and the partial twin all inherit the same fields and
    # increment discipline)
    NARROW_METRICS = ("m_fast", "m_slow", "m_stable")

    def __init__(self, keys: int, gap_slots: int = 8):
        self.K = keys
        self.G = gap_slots

    # -- host-side builders -------------------------------------------

    @staticmethod
    def dep_slots(n: int) -> int:
        """Q: each of the <= n ack reporters contributes at most its own
        latest dep, plus the coordinator's dep rides in every ack."""
        return n + 1

    def payload_width(self, n: int) -> int:
        # MCOMMIT: [dsrc, seq, key, client, nd] + (src, seq) * Q
        return max(5 + 2 * self.dep_slots(n), n)

    def periodic_intervals(self, config, dims: EngineDims):
        gc = config.gc_interval_ms
        return [gc if gc is not None else INF]

    def min_live(self, config) -> int:
        """Every collect waits on the full fast quorum and the slow
        path on the write quorum; recovery is not modeled, so fewer
        survivors than either cannot commit (engine/faults.py flags
        such crash plans ERR_UNAVAIL)."""
        fq_size, wq_size = self._quorum_sizes(config)
        return max(fq_size, wq_size)

    def _quorum_sizes(self, config):
        raise NotImplementedError

    def _fp_mode(self) -> int:
        raise NotImplementedError

    def _ack_self(self) -> bool:
        raise NotImplementedError

    def lane_ctx(self, config, dims: EngineDims, sorted_idx: np.ndarray):
        N = dims.N
        fq_size, wq_size = self._quorum_sizes(config)
        fq = np.zeros((N, N), bool)
        wq = np.zeros((N, N), bool)
        for p in range(config.n):
            for member in sorted_idx[p][:fq_size]:
                fq[p, member] = True
            for member in sorted_idx[p][:wq_size]:
                wq[p, member] = True
        ack_self = self._ack_self()
        return {
            "fast_quorum": fq,
            "write_quorum": wq,
            "expected_acks": np.int32(fq_size if ack_self else fq_size - 1),
            "fp_mode": np.int32(self._fp_mode()),
            "ack_self": np.bool_(ack_self),
        }

    def init_state(self, dims: EngineDims, ctx_np) -> Dict[str, np.ndarray]:
        N, D, K, G = dims.N, dims.D, self.K, self.G
        Q = self.dep_slots(N)
        return {
            # conflict index (protocol)
            "latest_src": np.zeros((N, K), np.int32),
            "latest_seq": np.zeros((N, K), np.int32),
            # per-dot payload (every process)
            "seq_in_slot": np.zeros((N, N, D), np.int32),
            "key_of": np.zeros((N, N, D), np.int32),
            "client_of": np.zeros((N, N, D), np.int32),
            # coordinator per own dot
            "own_seq": np.zeros((N,), np.int32),
            "ack_cnt": np.zeros((N, D), np.int32),
            "qd_src": np.zeros((N, D, Q), np.int32),
            "qd_seq": np.zeros((N, D, Q), np.int32),
            "qd_cnt": np.zeros((N, D, Q), np.int32),
            "slow_acks": np.zeros((N, D), np.int32),
            # graph-executor vertex store
            "vx_committed": np.zeros((N, N, D), bool),
            "vx_seq": np.zeros((N, N, D), np.int32),
            "vx_key": np.zeros((N, N, D), np.int32),
            "vx_client": np.zeros((N, N, D), np.int32),
            "vx_nd": np.zeros((N, N, D), np.int32),
            "vx_dep_src": np.zeros((N, N, D, Q), np.int32),
            "vx_dep_seq": np.zeros((N, N, D, Q), np.int32),
            # executed clock per source
            "exec_front": np.zeros((N, N), np.int32),
            "exec_gaps": np.zeros((N, N, G, 2), np.int32),
            # committed-clock GC
            "comm_front": np.zeros((N, N), np.int32),
            "comm_gaps": np.zeros((N, N, G, 2), np.int32),
            "others_frontier": np.zeros((N, N, N), np.int32),
            "seen": np.zeros((N, N), bool),
            "prev_stable": np.zeros((N, N), np.int32),
            "m_fast": np.zeros((N,), np.int32),
            "m_slow": np.zeros((N,), np.int32),
            "m_stable": np.zeros((N,), np.int32),
            "err": np.zeros((N,), np.int32),
        }

    @staticmethod
    def error(ps):
        return ps["err"]

    @staticmethod
    def metrics(ps_np) -> Dict[str, np.ndarray]:
        return {
            "fast_path": ps_np["m_fast"],
            "slow_path": ps_np["m_slow"],
            "stable": ps_np["m_stable"],
        }

    # -- device handlers ----------------------------------------------

    def ready(self, ps, msg, me, ctx, dims: EngineDims):
        """Readiness gate: MCollect needs a free dot slot, MCommit needs
        the MCollect payload (atlas.rs buffers early commits)."""
        t = msg["mtype"]
        c_slot = dot_slot(msg["payload"][0], dims)
        collect_ok = (
            (oh_get(oh_get(ps["seq_in_slot"], msg["src"]), c_slot) == 0)
            & (oh_get(oh_get(ps["vx_seq"], msg["src"]), c_slot) == 0)
        )
        dsrc, seq = msg["payload"][0], msg["payload"][1]
        have = (
            oh_get(oh_get(ps["seq_in_slot"], dsrc), dot_slot(seq, dims))
            == seq
        )
        ok = jnp.where(t == _DepDev.MCOLLECT, collect_ok, True)
        return jnp.where(t == _DepDev.MCOMMIT, have, ok)

    # the hoisted graph drain (see handle) needs 2 outbox slots beyond
    # what a branch itself fills
    EXTRA_SLOTS = 2

    def handle(self, ps, msg, me, now, ctx, dims: EngineDims):
        def _noop(ps, msg):
            return ps, empty_outbox(dims), jnp.zeros((), bool)

        branches = [
            lambda ps, msg: _submit(self, ps, msg, me, ctx, dims),
            lambda ps, msg: _mcollect(self, ps, msg, me, ctx, dims),
            lambda ps, msg: _mcollectack(self, ps, msg, me, ctx, dims),
            lambda ps, msg: _mcommit(self, ps, msg, me, ctx, dims),
            lambda ps, msg: _mconsensus(self, ps, msg, me, ctx, dims),
            lambda ps, msg: _mconsensusack(self, ps, msg, me, ctx, dims),
            lambda ps, msg: _mgc(self, ps, msg, me, ctx, dims),
            lambda ps, msg: _mdrain(self, ps, msg, me, ctx, dims),
            _noop,
        ]
        idx = jnp.clip(msg["mtype"], 0, _DepDev.NUM_TYPES)
        ps, ob, do_drain = jax.lax.switch(idx, branches, ps, msg)
        # under vmap the switch executes every branch each step, so the
        # graph drain (relaxation fixed point + per-dep executed-set
        # walk — the heaviest subgraph here) must exist ONCE per step,
        # hoisted behind an enable flag, not inlined into two branches.
        # Reserved slots are the LAST EXTRA_SLOTS rows (dims adds them
        # on top of the branch fanout), immune to fanout growth.
        base = dims.F - _DepDev.EXTRA_SLOTS
        ps, ob = _drain(
            self, ps, me, ctx, dims, ob, base, base + 1, do_drain
        )
        return ps, ob

    def periodic(self, ps, fire, me, now, ctx, dims: EngineDims):
        """GARBAGE_COLLECTION: broadcast my committed frontier
        (atlas.rs handle_event -> MGarbageCollection)."""
        ob = emit_broadcast(
            empty_outbox(dims),
            _DepDev.MGC,
            ps["comm_front"],
            ctx["n"],
            me,
            exclude_me=True,
        )
        ob = dict(ob, valid=ob["valid"] & fire[0])
        return ps, ob


class AtlasDev(_DepDev):
    """Atlas: fast quorum n/2+f, write quorum f+1 (config.rs:275-281);
    coordinator acks itself (atlas.rs:306-323); threshold-union fast
    path."""

    def _quorum_sizes(self, config):
        return config.atlas_quorum_sizes()

    def _fp_mode(self) -> int:
        return 0

    def _ack_self(self) -> bool:
        return True


class EPaxosDev(_DepDev):
    """EPaxos: minority-based quorums with f = n//2 (config.rs:284-292);
    the coordinator does not ack itself (epaxos.rs:285-295); all-equal
    fast path."""

    def _quorum_sizes(self, config):
        return config.epaxos_quorum_sizes()

    def _fp_mode(self) -> int:
        return 1

    def _ack_self(self) -> bool:
        return False


# ----------------------------------------------------------------------
# helpers
# ----------------------------------------------------------------------



def _qd_add(ps, slot, dsrc, dseq, enable):
    """Merge one reported dep into the coordinator's count table
    (QuorumDeps.add, quorum.rs:24-34)."""
    src_row = oh_get(ps["qd_src"], slot)
    seq_row = oh_get(ps["qd_seq"], slot)
    Q = src_row.shape[0]
    do = jnp.asarray(enable, bool) & (dseq > 0)
    match = (seq_row == dseq) & (src_row == dsrc)
    found = jnp.any(match)
    midx = jnp.argmax(match)
    free = seq_row == 0
    fidx = jnp.argmax(free)
    overflow = do & ~found & ~jnp.any(free)
    widx = jnp.where(do & ~overflow, jnp.where(found, midx, fidx), Q)
    return dict(
        ps,
        qd_src=oh_set2(ps["qd_src"], slot, widx, dsrc),
        qd_seq=oh_set2(ps["qd_seq"], slot, widx, dseq),
        qd_cnt=oh_set2(
            ps["qd_cnt"], slot, widx,
            jnp.where(
                found, oh_get(oh_get(ps["qd_cnt"], slot), widx) + 1, 1
            ),
        ),
        err=ps["err"] | ERR_CAPACITY * overflow,
    )


def _commit_broadcast(dev, ps, me, seq, key, client, ctx, dims, valid):
    """MCommit to all with the aggregated dep union (the single-shard arm
    of mcommit_actions, atlas.rs:393-409)."""
    slot = dot_slot(seq, dims)
    Q = dev.dep_slots(dims.N)
    P = dims.P
    qd_seq_row = oh_get(ps["qd_seq"], slot)
    present = qd_seq_row > 0
    # compact present deps to the front so nd prefixes are meaningful
    order, nd = compact_order(present, Q)
    pay = jnp.zeros((P,), I32)
    pay = pay.at[0].set(me)
    pay = pay.at[1].set(seq)
    pay = pay.at[2].set(key)
    pay = pay.at[3].set(client)
    pay = pay.at[4].set(nd)
    lo = 5 + 2 * jnp.minimum(order, P)  # > P when order==INF
    pay = oh_pack_pairs(pay, lo, oh_get(ps["qd_src"], slot), qd_seq_row)

    ob = emit_broadcast(
        empty_outbox(dims), _DepDev.MCOMMIT, pay, ctx["n"]
    )
    return dict(ob, valid=ob["valid"] & jnp.asarray(valid, bool))


# ----------------------------------------------------------------------
# graph-executor drain (relaxation replacing Tarjan)
# ----------------------------------------------------------------------


def _drain(dev, ps, me, ctx, dims, ob, exec_slot, drain_slot, enable=True):
    """Execute one dot whose transitive dep closure is committed, and
    re-schedule while more remain (tarjan.rs:99-319 as a greatest fixed
    point; see module docstring for the equivalence argument)."""
    N, D = dims.N, dims.D
    dep_src = ps["vx_dep_src"]  # [N, D, Q]
    dep_seq = ps["vx_dep_seq"]
    dslot = dot_slot(dep_seq, dims)

    # per-dep static facts: absent deps pass; executed deps pass
    # (gathered membership: the full [N, D, Q, G, 2] gap gather in one
    # fusion overflows VMEM at sweep scale)
    absent = dep_seq == 0
    dep_executed = iset_contains_gathered(
        ps["exec_front"], ps["exec_gaps"], dep_src, dep_seq
    )
    # the dep's vertex-store cell only counts if it still holds that seq
    dep_cell_valid = ps["vx_seq"][dep_src, dslot] == dep_seq
    dep_pass_static = absent | dep_executed

    def body(carry):
        ok, _changed = carry
        dep_ok = ok[dep_src, dslot] & dep_cell_valid
        new_ok = ok & jnp.all(dep_pass_static | dep_ok, axis=2)
        return new_ok, jnp.any(new_ok != ok)

    ok0 = ps["vx_committed"]
    ok, _ = jax.lax.while_loop(
        lambda c: c[1], body, (ok0, jnp.asarray(True))
    )

    num_ok = jnp.sum(ok)
    # DAG-ready dots (all deps executed outright) execute before cycle
    # members; ties in (source, sequence) order — the oracle's SCC pop
    # order within one instant
    ready = ok & jnp.all(dep_pass_static, axis=2)
    sel = jnp.where(jnp.any(ready), ready, ok)
    srcs = jnp.arange(N, dtype=I32)[:, None]
    packed = srcs * SEQ_BOUND + ps["vx_seq"]
    flat_idx = jnp.argmin(jnp.where(sel, packed, INF))
    esrc, eslot = flat_idx // D, flat_idx % D
    eseq = oh_get(oh_get(ps["vx_seq"], esrc), eslot)
    client = oh_get(oh_get(ps["vx_client"], esrc), eslot)

    do = jnp.asarray(enable, bool) & (num_ok > 0)
    # safety monitor (engine/monitor.py; the ``if`` is a trace-time
    # gate): the execute-before-commit guard checks the GC
    # committed-clock record, an independent data path from the vertex
    # store's committed flags
    if "_mon_hash" in ps:
        ekey = oh_get(oh_get(ps["vx_key"], esrc), eslot)
        ps = mon_exec(
            ps, ekey, esrc, eseq, do,
            premature=~iset_contains(
                oh_get(ps["comm_front"], esrc),
                oh_get(ps["comm_gaps"], esrc),
                eseq,
            ),
        )
    front, gaps, overflow = iset_add(
        oh_get(ps["exec_front"], esrc), oh_get(ps["exec_gaps"], esrc),
        eseq, do,
    )
    ps = dict(
        ps,
        exec_front=oh_set(ps["exec_front"], esrc, front),
        exec_gaps=oh_set(ps["exec_gaps"], esrc, gaps),
        vx_committed=oh_set2(
            ps["vx_committed"], jnp.where(do, esrc, N), eslot, False
        ),
        vx_seq=oh_set2(ps["vx_seq"], jnp.where(do, esrc, N), eslot, 0),
        err=ps["err"] | ERR_CAPACITY * overflow,
    )
    ob = emit(
        ob,
        exec_slot,
        dims.N + client,
        _DepDev.TO_CLIENT,
        [0],
        valid=do & (oh_get(ctx["client_attach"], client) == me),
    )
    ob = emit(
        ob,
        drain_slot,
        me,
        _DepDev.MDRAIN,
        [0],
        valid=do & (num_ok > 1),
    )
    return ps, ob


# ----------------------------------------------------------------------
# handlers
# ----------------------------------------------------------------------


def _submit(dev, ps, msg, me, ctx, dims):
    """atlas.rs:210-248 / epaxos.rs:199-220: next dot; deps = my latest
    dot on the key; broadcast MCollect to all."""
    client = msg["payload"][0]
    key = msg["payload"][2]
    seq = ps["own_seq"] + 1
    slot = dot_slot(seq, dims)
    Q = dev.dep_slots(dims.N)

    prev_src = oh_get(ps["latest_src"], key)
    prev_seq = oh_get(ps["latest_seq"], key)
    ps = dict(
        ps,
        # (source, sequence) packing in the drain requires seq < bound
        err=ps["err"] | ERR_SEQ * (seq >= SEQ_BOUND),
        own_seq=seq,
        latest_src=oh_set(ps["latest_src"], key, me),
        latest_seq=oh_set(ps["latest_seq"], key, seq),
        ack_cnt=oh_set(ps["ack_cnt"], slot, 0),
        slow_acks=oh_set(ps["slow_acks"], slot, 0),
        qd_src=oh_set(ps["qd_src"], slot, jnp.zeros((Q,), I32)),
        qd_seq=oh_set(ps["qd_seq"], slot, jnp.zeros((Q,), I32)),
        qd_cnt=oh_set(ps["qd_cnt"], slot, jnp.zeros((Q,), I32)),
    )
    ob = emit_broadcast(
        empty_outbox(dims),
        _DepDev.MCOLLECT,
        [seq, key, client, prev_src, prev_seq],
        ctx["n"],
    )
    ob = dict(ob, valid=ob["valid"] & msg["valid"])
    return ps, ob, jnp.zeros((), bool)


def _mcollect(dev, ps, msg, me, ctx, dims):
    """atlas.rs:250-323: store payload; fast-quorum members merge the
    coordinator's deps with their own latest and ack; the coordinator
    acks its own deps iff ack_self (Atlas)."""
    s = msg["src"]
    seq, key, client, cdsrc, cdseq = (
        msg["payload"][0],
        msg["payload"][1],
        msg["payload"][2],
        msg["payload"][3],
        msg["payload"][4],
    )
    slot = dot_slot(seq, dims)
    dirty = (
        oh_get(oh_get(ps["seq_in_slot"], s), slot) != 0
    ) | (oh_get(oh_get(ps["vx_seq"], s), slot) != 0)
    ps = dict(
        ps,
        err=ps["err"] | ERR_DOT * dirty,
        seq_in_slot=oh_set2(ps["seq_in_slot"], s, slot, seq),
        key_of=oh_set2(ps["key_of"], s, slot, key),
        client_of=oh_set2(ps["client_of"], s, slot, client),
    )
    in_q = oh_get(oh_get(ctx["fast_quorum"], s), me)
    from_self = s == me

    # quorum member (not the coordinator): add_cmd with the
    # coordinator's deps as past (sequential.rs:62-86)
    member = in_q & ~from_self
    d1src = jnp.where(member, oh_get(ps["latest_src"], key), cdsrc)
    d1seq = jnp.where(member, oh_get(ps["latest_seq"], key), cdseq)
    # second dep = coordinator's, dropped when identical to mine
    dup = (d1src == cdsrc) & (d1seq == cdseq)
    d2src = jnp.where(member & ~dup, cdsrc, 0)
    d2seq = jnp.where(member & ~dup, cdseq, 0)
    ps = dict(
        ps,
        latest_src=oh_set(
            ps["latest_src"], jnp.where(member, key, dev.K), s
        ),
        latest_seq=oh_set(
            ps["latest_seq"], jnp.where(member, key, dev.K), seq
        ),
    )
    ack = in_q & (ctx["ack_self"] | ~from_self)
    ob = emit(
        empty_outbox(dims),
        0,
        s,
        _DepDev.MCOLLECTACK,
        [seq, d1src, d1seq, d2src, d2seq],
        valid=ack,
    )
    return ps, ob, jnp.zeros((), bool)


def _mcollectack(dev, ps, msg, me, ctx, dims):
    """atlas.rs:325-391 / epaxos.rs:297-364: aggregate dep reports; on
    the last expected ack run the fast-path predicate."""
    seq = msg["payload"][0]
    slot = dot_slot(seq, dims)
    ps = _qd_add(ps, slot, msg["payload"][1], msg["payload"][2], True)
    ps = _qd_add(ps, slot, msg["payload"][3], msg["payload"][4], True)
    cnt = ps["ack_cnt"][slot] + 1
    ps = dict(ps, ack_cnt=ps["ack_cnt"].at[slot].set(cnt))

    all_acks = cnt == ctx["expected_acks"]
    qd_seq_row = oh_get(ps["qd_seq"], slot)
    present = qd_seq_row > 0
    counts = ps["qd_cnt"][slot]
    # Atlas: every dep seen >= f times; EPaxos: every dep seen by all
    threshold = jnp.where(
        ctx["fp_mode"] == 0, ctx["f"], ctx["expected_acks"]
    )
    fp_ok = jnp.all(~present | (counts >= threshold))
    fast = all_acks & fp_ok
    slow = all_acks & ~fast
    ps = dict(
        ps,
        m_fast=ps["m_fast"] + fast.astype(I32),
        m_slow=ps["m_slow"] + slow.astype(I32),
    )

    key = oh_get(oh_get(ps["key_of"], me), slot)
    client = oh_get(oh_get(ps["client_of"], me), slot)
    ob = _commit_broadcast(dev, ps, me, seq, key, client, ctx, dims, fast)
    obc = emit_broadcast(
        empty_outbox(dims),
        _DepDev.MCONSENSUS,
        [me, seq],
        ctx["n"],
    )
    wq = jnp.zeros((dims.F,), bool).at[: dims.N].set(
        oh_get(ctx["write_quorum"], me)
    )
    obc = dict(obc, valid=obc["valid"] & slow & wq)
    ob = jax.tree_util.tree_map(
        lambda a, b: jnp.where(
            fast.reshape((-1,) + (1,) * (a.ndim - 1)) if a.ndim > 1 else fast,
            a,
            b,
        ),
        ob,
        obc,
    )
    return ps, ob, jnp.zeros((), bool)


def _mcommit(dev, ps, msg, me, ctx, dims):
    """atlas.rs:393-464: feed the vertex store, record the committed dot
    for GC, then drain the graph."""
    dsrc = msg["payload"][0]
    seq = msg["payload"][1]
    key = msg["payload"][2]
    client = msg["payload"][3]
    nd = msg["payload"][4]
    slot = dot_slot(seq, dims)
    Q = dev.dep_slots(dims.N)

    have = oh_get(oh_get(ps["seq_in_slot"], dsrc), slot) == seq
    already = oh_get(oh_get(ps["vx_seq"], dsrc), slot) == seq
    do = have & ~already
    ps = dict(ps, err=ps["err"] | ERR_PROTO * ~have)

    idxs = 5 + 2 * jnp.arange(Q, dtype=I32)
    dep_en = jnp.arange(Q, dtype=I32) < nd
    dsrcs = jnp.where(dep_en, oh_take(msg["payload"], idxs), 0)
    dseqs = jnp.where(dep_en, oh_take(msg["payload"], idxs + 1), 0)

    wsrc = jnp.where(do, dsrc, dims.N)
    ps = dict(
        ps,
        vx_committed=oh_set2(ps["vx_committed"], wsrc, slot, True),
        vx_seq=oh_set2(ps["vx_seq"], wsrc, slot, seq),
        vx_key=oh_set2(ps["vx_key"], wsrc, slot, key),
        vx_client=oh_set2(ps["vx_client"], wsrc, slot, client),
        vx_nd=oh_set2(ps["vx_nd"], wsrc, slot, nd),
        vx_dep_src=oh_set2(ps["vx_dep_src"], wsrc, slot, dsrcs),
        vx_dep_seq=oh_set2(ps["vx_dep_seq"], wsrc, slot, dseqs),
    )

    cf, cg, overflow = iset_add(
        oh_get(ps["comm_front"], dsrc), oh_get(ps["comm_gaps"], dsrc),
        seq, do,
    )
    ps = dict(
        ps,
        comm_front=oh_set(ps["comm_front"], dsrc, cf),
        comm_gaps=oh_set(ps["comm_gaps"], dsrc, cg),
        err=ps["err"] | ERR_CAPACITY * overflow,
    )
    # the graph drain runs hoisted after the switch (handle)
    return ps, empty_outbox(dims), jnp.ones((), bool)


def _mconsensus(dev, ps, msg, me, ctx, dims):
    """Slow-path accept (synod/single.rs:107-131): with no recovery the
    initial ballot always wins, so the acceptor just acks."""
    dsrc, seq = msg["payload"][0], msg["payload"][1]
    ob = emit(
        empty_outbox(dims),
        0,
        msg["src"],
        _DepDev.MCONSENSUSACK,
        [dsrc, seq],
    )
    return ps, ob, jnp.zeros((), bool)


def _mconsensusack(dev, ps, msg, me, ctx, dims):
    """Chosen at model-f+1 accepts (synod/single.rs:159; the synod is
    built with the model f even for EPaxos, epaxos.rs:45-70), then
    commit with the dep union gathered during collect."""
    seq = msg["payload"][1]
    slot = dot_slot(seq, dims)
    cnt = oh_get(ps["slow_acks"], slot) + 1
    chosen = cnt == ctx["f"] + 1
    ps = dict(ps, slow_acks=oh_set(ps["slow_acks"], slot, cnt))
    key = oh_get(oh_get(ps["key_of"], me), slot)
    client = oh_get(oh_get(ps["client_of"], me), slot)
    ob = _commit_broadcast(
        dev, ps, me, seq, key, client, ctx, dims, chosen
    )
    return ps, ob, jnp.zeros((), bool)


def _mgc(dev, ps, msg, me, ctx, dims):
    """Committed-clock GC (gc/clock.rs:10-171): meet of advertised
    frontiers frees stable payload slots."""
    N = dims.N
    s = msg["src"]
    frontier = msg["payload"][:N]
    of = oh_set(
        ps["others_frontier"],
        s,
        jnp.maximum(oh_get(ps["others_frontier"], s), frontier),
    )
    seen = oh_set(ps["seen"], s, True)
    procs = jnp.arange(N, dtype=I32)
    nmask = procs < ctx["n"]
    others = nmask & (procs != me)
    ready = jnp.all(seen | ~others)
    min_others = jnp.min(jnp.where(others[:, None], of, INF), axis=0)
    stable = jnp.minimum(ps["comm_front"], min_others)
    stable = jnp.where(ready & nmask, stable, 0)
    delta = jnp.maximum(stable - ps["prev_stable"], 0)
    prev_stable = jnp.maximum(ps["prev_stable"], stable)
    freed = (ps["seq_in_slot"] > 0) & (
        ps["seq_in_slot"] <= prev_stable[:, None]
    )
    ps = dict(
        ps,
        others_frontier=of,
        seen=seen,
        prev_stable=prev_stable,
        m_stable=ps["m_stable"] + jnp.sum(delta),
        seq_in_slot=jnp.where(freed, 0, ps["seq_in_slot"]),
    )
    return ps, empty_outbox(dims), jnp.zeros((), bool)


def _mdrain(dev, ps, msg, me, ctx, dims):
    # the graph drain runs hoisted after the switch (handle)
    return ps, empty_outbox(dims), jnp.ones((), bool)

"""Union state skeleton for heterogeneous protocol megabatches.

ROADMAP item 1 wants every (protocol, config) lane of a campaign grid
advancing in ONE compiled step, dispatched per lane by ``lax.switch``.
The switch precondition is brutal: every branch must consume and
produce *identical avals*, but the eight audited protocol variants
carry eight different lane-state trees (different ``ps`` fields,
different pool/dot/fanout extents). This module is the proven
unification layer underneath that runner:

- :func:`classify_planes` decides, per dotted state/ctx plane, how the
  cross-protocol union stores it — ``SHARED`` (same rank + dtype in
  every audit, padded to the elementwise-max extent), ``CASTABLE``
  (same rank everywhere, storage widened to a dtype every native dtype
  casts to losslessly), or ``PRIVATE`` (protocol-specific, slotted
  per-audit into union storage). The GL601 lint gate
  (fantoch_tpu/lint/skeleton.py) ledgers these verdicts in a
  checked-in baseline with reviewed reasons, so the taxonomy below is
  machine-pinned, not folklore.
- :func:`build_skeleton` turns classified planes into a
  :class:`Skeleton` — the union pytree spec all eight protocols share.
- :func:`pack_state` / :func:`unpack_state` (and the ``ctx`` twins)
  are the adapters: byte-exact round-trip for every audit (zero-pad up
  / slice back, widen up / cast back — both value-preserving by
  construction), refusing by name on any plane the skeleton does not
  know (a monitored state, a drifted dtype) instead of silently
  truncating. ``protocol_id`` rides in the packed state as the lane
  plane the eventual ``lax.switch`` dispatches on.

The switch-dispatched runner itself is NOT here — it lands in a later
PR on top of these proofs, exactly as ``parallel/partition.py`` landed
on the GL5xx shardability ledger. Until then the adapters are exercised
by the GL602/GL604 provers and their tests only.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass
from typing import Any, Dict, Mapping, Tuple

import numpy as np

# plane verdicts — the GL601 taxonomy
SHARED = "SHARED"        # every audit: same rank, same dtype; pad to max
CASTABLE = "CASTABLE"    # every audit: same rank; storage dtype widened
PRIVATE = "PRIVATE"      # protocol-specific: per-audit slot in the union
VERDICTS = (SHARED, CASTABLE, PRIVATE)


class SkeletonMismatchError(RuntimeError):
    """A tree handed to the pack/unpack adapters disagrees with the
    proven skeleton (unknown plane, missing plane, drifted shape or
    dtype, foreign ``protocol_id``). Always refused by name — a
    silently truncated or zero-filled plane would be a wrong-result
    bug, not a crash."""


# ----------------------------------------------------------------------
# dotted-plane walking (dict-only trees, the engine's state/ctx shape)
# ----------------------------------------------------------------------

def walk_planes(tree, prefix: str) -> Dict[str, Any]:
    """Flatten a nested-dict tree into ``{dotted-name: leaf}`` with
    ``prefix`` as the root segment — the same names GL501/GL601 ledger
    (``state.ps.clock``, ``ctx.delay_pp``). Engine state and ctx are
    pure nested dicts; any other container (and any key containing a
    ``.``) is refused by name so dotted paths stay invertible."""
    out: Dict[str, Any] = {}

    def rec(node, path):
        if isinstance(node, dict):
            for k in sorted(node):
                if not isinstance(k, str) or "." in k:
                    raise SkeletonMismatchError(
                        f"skeleton planes need dot-free string keys; "
                        f"got {k!r} under {path}"
                    )
                rec(node[k], f"{path}.{k}")
        elif isinstance(node, (list, tuple)):
            raise SkeletonMismatchError(
                f"skeleton trees are nested dicts of arrays; {path} "
                f"is a {type(node).__name__}"
            )
        else:
            out[path] = node

    rec(tree, prefix)
    return out


def unflatten_planes(leaves: Mapping[str, Any]) -> dict:
    """Invert :func:`walk_planes` (names WITHOUT the root prefix)."""
    root: dict = {}
    for name in sorted(leaves):
        parts = name.split(".")
        node = root
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = leaves[name]
    return root


# ----------------------------------------------------------------------
# classification — the GL601 taxonomy over per-audit plane specs
# ----------------------------------------------------------------------

def _lossless_cast(src: np.dtype, dst: np.dtype) -> bool:
    """True iff every value of ``src`` survives a round-trip through
    ``dst``. Stricter than ``np.can_cast(..., casting="safe")``, which
    blesses int64 -> float64 even though float64's 52-bit mantissa
    cannot hold every int64 — for integer -> float widens we require
    the mantissa to cover the integer's value bits."""
    if src == dst:
        return True
    if src.kind in "iu" and dst.kind == "f":
        value_bits = src.itemsize * 8 - (1 if src.kind == "i" else 0)
        return value_bits <= np.finfo(dst).nmant
    return np.can_cast(src, dst, casting="safe")


def classify_planes(
    specs: Mapping[str, Mapping[str, Tuple[tuple, str]]],
) -> Dict[str, dict]:
    """Classify every plane of ``{audit: {name: (shape, dtype)}}``
    against the cross-audit union. Returns ``{name: entry}`` where an
    entry carries ``verdict``, per-audit ``native`` specs, and (for
    SHARED/CASTABLE) the ``union`` storage spec. Pure shape/dtype
    arithmetic — no jax, no tracing — so the lint gate, the selfcheck
    fixtures, and the unit tests all share one classifier."""
    audits = sorted(specs)
    assert audits, "classify_planes needs at least one audit"
    names = sorted({n for a in audits for n in specs[a]})
    entries: Dict[str, dict] = {}
    for name in names:
        native = {
            a: {
                "shape": [int(d) for d in specs[a][name][0]],
                "dtype": str(specs[a][name][1]),
            }
            for a in audits
            if name in specs[a]
        }
        entry: Dict[str, Any] = {"native": native}
        ranks = {len(v["shape"]) for v in native.values()}
        dtypes = sorted({v["dtype"] for v in native.values()})
        if len(native) < len(audits) or len(ranks) != 1:
            # absent from some audit, or rank disagrees: there is no
            # single union plane both sides can index — per-audit slot
            entry["verdict"] = PRIVATE
        else:
            shape = [
                max(v["shape"][i] for v in native.values())
                for i in range(ranks.pop())
            ]
            if len(dtypes) == 1:
                entry["verdict"] = SHARED
                entry["union"] = {"shape": shape, "dtype": dtypes[0]}
            else:
                try:
                    union_dt = np.dtype(dtypes[0])
                    for d in dtypes[1:]:
                        union_dt = np.promote_types(union_dt, d)
                    lossless = all(
                        _lossless_cast(np.dtype(d), union_dt)
                        for d in dtypes
                    )
                except TypeError:  # pragma: no cover — exotic dtypes
                    lossless = False
                if lossless:
                    entry["verdict"] = CASTABLE
                    entry["union"] = {
                        "shape": shape,
                        "dtype": str(union_dt),
                    }
                else:
                    # no value-preserving widen exists (e.g. i64 + f32:
                    # the promotion target f64 cannot hold every i64)
                    entry["verdict"] = PRIVATE
        entries[name] = entry
    return entries


# ----------------------------------------------------------------------
# the skeleton — union pytree spec shared by every audit
# ----------------------------------------------------------------------

@dataclass(frozen=True)
class Skeleton:
    """The proven union: ordered audits (index = ``protocol_id``) and
    classified planes. Built from live classification
    (:func:`build_skeleton`) or from the checked-in GL601 ledger —
    both roads produce the same spec or the lint gate fails."""

    audits: Tuple[str, ...]
    planes: Mapping[str, dict]

    def protocol_id(self, audit: str) -> int:
        try:
            return self.audits.index(audit)
        except ValueError:
            raise SkeletonMismatchError(
                f"audit {audit!r} is not in this skeleton's grid "
                f"{list(self.audits)}"
            ) from None

    def slots(self, prefix: str):
        """``(sub-name, entry)`` pairs under ``prefix`` ("state" or
        "ctx"), sub-names stripped of the prefix, sorted."""
        p = prefix + "."
        for name in sorted(self.planes):
            if name.startswith(p):
                yield name[len(p):], self.planes[name]


def build_skeleton(entries: Mapping[str, dict],
                   audits=None) -> Skeleton:
    """Assemble a :class:`Skeleton` from classified plane entries (live
    :func:`classify_planes` output or the checked-in ledger's
    ``planes`` map). Validates the taxonomy instead of trusting it:
    unknown verdicts, SHARED/CASTABLE entries without a union spec, or
    native specs for audits outside the grid are refused by name."""
    if audits is None:
        audits = sorted(
            {a for e in entries.values() for a in e.get("native", {})}
        )
    audits = tuple(audits)
    for name, ent in sorted(entries.items()):
        v = ent.get("verdict")
        if v not in VERDICTS:
            raise SkeletonMismatchError(
                f"plane {name}: unknown verdict {v!r}"
            )
        if v in (SHARED, CASTABLE) and not ent.get("union"):
            raise SkeletonMismatchError(
                f"plane {name}: {v} without a union storage spec"
            )
        if not ent.get("native"):
            raise SkeletonMismatchError(
                f"plane {name}: no native specs"
            )
        stray = sorted(set(ent["native"]) - set(audits))
        if stray:
            raise SkeletonMismatchError(
                f"plane {name}: native specs for audits outside the "
                f"grid: {stray}"
            )
    return Skeleton(audits=audits, planes=dict(entries))


def skeleton_fingerprint(skeleton: Skeleton) -> str:
    """Content hash of the union spec (audit order + every slot's
    verdict/union/native shapes and dtypes) — the marker threaded
    through AOT executable signatures and checkpoint manifests so a
    megabatch artifact can never be loaded by a worker holding a
    different (or no) skeleton."""
    from .checkpoint import canonical_json

    spec = {
        "audits": list(skeleton.audits),
        "planes": {
            name: {
                "verdict": ent["verdict"],
                **({"union": ent["union"]} if ent.get("union") else {}),
                "native": ent["native"],
            }
            for name, ent in skeleton.planes.items()
        },
    }
    return hashlib.sha256(canonical_json(spec).encode()).hexdigest()


def packed_spec(skeleton: Skeleton, prefix: str = "state") -> dict:
    """Shape/dtype spec of the packed union tree — identical for every
    audit by construction (the ``lax.switch`` operand contract). Layout
    mirrors :func:`pack_state`: ``shared`` slots at union extents,
    ``priv`` per-audit slots at native extents, plus the
    ``protocol_id`` lane plane for the state tree."""
    shared: Dict[str, tuple] = {}
    priv: Dict[str, Dict[str, tuple]] = {a: {} for a in skeleton.audits}
    for sub, ent in skeleton.slots(prefix):
        if ent["verdict"] == PRIVATE:
            for a, nat in sorted(ent["native"].items()):
                priv[a][sub] = (tuple(nat["shape"]), nat["dtype"])
        else:
            u = ent["union"]
            shared[sub] = (tuple(u["shape"]), u["dtype"])
    spec: Dict[str, Any] = {"shared": shared, "priv": priv}
    if prefix == "state":
        spec["protocol_id"] = ((), "int32")
    return spec


# ----------------------------------------------------------------------
# pack / unpack adapters — byte-exact round-trip, refusal by name
# ----------------------------------------------------------------------

def _pad_to(arr, shape, xp):
    if tuple(arr.shape) == tuple(shape):
        return arr
    pads = tuple((0, t - s) for s, t in zip(arr.shape, shape))
    if any(p[1] < 0 for p in pads):  # pragma: no cover — gated earlier
        raise SkeletonMismatchError(
            f"cannot pad {tuple(arr.shape)} down to {tuple(shape)}"
        )
    return xp.pad(arr, pads)


def _pack_tree(skeleton: Skeleton, audit: str, tree, prefix: str, xp):
    skeleton.protocol_id(audit)  # refuse a foreign audit before any
    # plane-level message can misattribute the mismatch to a plane
    leaves = walk_planes(tree, prefix)
    shared: Dict[str, Any] = {}
    priv: Dict[str, Dict[str, Any]] = {a: {} for a in skeleton.audits}
    for sub, ent in skeleton.slots(prefix):
        name = f"{prefix}.{sub}"
        nat = ent["native"].get(audit)
        arr = None
        if nat is not None:
            if name not in leaves:
                raise SkeletonMismatchError(
                    f"{audit}: {prefix} tree is missing plane {name} "
                    f"the skeleton expects"
                )
            arr = xp.asarray(leaves.pop(name))
            if (tuple(arr.shape) != tuple(nat["shape"])
                    or str(arr.dtype) != nat["dtype"]):
                raise SkeletonMismatchError(
                    f"{audit}: plane {name} is "
                    f"{tuple(arr.shape)}/{arr.dtype}, skeleton native "
                    f"spec says {tuple(nat['shape'])}/{nat['dtype']}"
                )
        elif name in leaves:
            raise SkeletonMismatchError(
                f"{audit}: plane {name} is not carried by this audit "
                f"in the skeleton, yet the {prefix} tree has it"
            )
        if ent["verdict"] == PRIVATE:
            # every audit's slot is materialised in every lane — that
            # is the amplification GL603 budgets, not an accident
            for a, na in sorted(ent["native"].items()):
                if a == audit and arr is not None:
                    priv[a][sub] = arr
                else:
                    priv[a][sub] = xp.zeros(
                        tuple(na["shape"]), dtype=na["dtype"]
                    )
        else:
            u = ent["union"]
            shared[sub] = _pad_to(arr, u["shape"], xp).astype(u["dtype"])
    if leaves:
        raise SkeletonMismatchError(
            f"{audit}: {prefix} tree carries planes the skeleton does "
            f"not know (would be silently dropped): "
            f"{sorted(leaves)}"
        )
    return {"shared": shared, "priv": priv}


def _unpack_tree(skeleton: Skeleton, audit: str, packed, prefix: str,
                 xp):
    for part in ("shared", "priv"):
        if part not in packed:
            raise SkeletonMismatchError(
                f"{audit}: packed {prefix} tree has no {part!r} slot"
            )
    out: Dict[str, Any] = {}
    for sub, ent in skeleton.slots(prefix):
        nat = ent["native"].get(audit)
        if nat is None:
            continue
        if ent["verdict"] == PRIVATE:
            try:
                arr = packed["priv"][audit][sub]
            except KeyError:
                raise SkeletonMismatchError(
                    f"{audit}: packed tree is missing private slot "
                    f"{prefix}.{sub}"
                ) from None
        else:
            u = ent["union"]
            try:
                arr = packed["shared"][sub]
            except KeyError:
                raise SkeletonMismatchError(
                    f"{audit}: packed tree is missing shared slot "
                    f"{prefix}.{sub}"
                ) from None
            if (tuple(arr.shape) != tuple(u["shape"])
                    or str(arr.dtype) != u["dtype"]):
                raise SkeletonMismatchError(
                    f"{audit}: shared slot {prefix}.{sub} is "
                    f"{tuple(arr.shape)}/{arr.dtype}, union spec says "
                    f"{tuple(u['shape'])}/{u['dtype']}"
                )
            arr = arr[tuple(slice(0, s) for s in nat["shape"])]
        arr = xp.asarray(arr).astype(nat["dtype"])
        if tuple(arr.shape) != tuple(nat["shape"]):
            raise SkeletonMismatchError(
                f"{audit}: slot {prefix}.{sub} unpacked to "
                f"{tuple(arr.shape)}, native spec says "
                f"{tuple(nat['shape'])} — the union extent does not "
                f"cover the native extent"
            )
        out[sub] = arr
    return unflatten_planes(out)


def pack_state(skeleton: Skeleton, audit: str, state, *, xp=np):
    """Pack one audit's native lane state into the union skeleton:
    SHARED/CASTABLE planes zero-padded to union extents and widened to
    union storage, PRIVATE planes into this audit's slots (every other
    audit's slots zero-filled so the packed structure is identical
    across protocols), plus the ``protocol_id`` dispatch plane. Pass
    ``xp=jax.numpy`` to trace it; the default keeps host round-trips
    pure numpy (byte-exact, no device transfer)."""
    packed = _pack_tree(skeleton, audit, state, "state", xp)
    packed["protocol_id"] = xp.asarray(
        skeleton.protocol_id(audit), dtype=np.int32
    )
    return packed


def unpack_state(skeleton: Skeleton, audit: str, packed, *, xp=np):
    """Invert :func:`pack_state` for ``audit``: slice padded planes
    back to native extents, cast widened storage back to native dtypes
    (both exact for values that came through :func:`pack_state`).
    A concrete ``protocol_id`` that names a different audit is refused
    by name; a traced one is left to the eventual ``lax.switch``."""
    pid = packed.get("protocol_id")
    if pid is None:
        raise SkeletonMismatchError(
            f"{audit}: packed state has no protocol_id plane"
        )
    want = skeleton.protocol_id(audit)
    try:
        got = int(pid)
    except Exception:  # a tracer — dispatch happens at the switch
        got = None
    if got is not None and got != want:
        raise SkeletonMismatchError(
            f"packed state carries protocol_id {got} "
            f"({skeleton.audits[got] if 0 <= got < len(skeleton.audits) else '?'}), "
            f"but unpack was asked for {audit!r} (id {want})"
        )
    return _unpack_tree(skeleton, audit, packed, "state", xp)


def pack_ctx(skeleton: Skeleton, audit: str, ctx, *, xp=np):
    """The ctx twin of :func:`pack_state` (no ``protocol_id`` — the
    dispatch plane rides in the state tree)."""
    return _pack_tree(skeleton, audit, ctx, "ctx", xp)


def unpack_ctx(skeleton: Skeleton, audit: str, packed, *, xp=np):
    """The ctx twin of :func:`unpack_state`."""
    return _unpack_tree(skeleton, audit, packed, "ctx", xp)

"""Per-lane fault plans for the batched device engine.

The reference framework evaluates consensus protocols that are designed
around tolerating ``f`` replica failures, yet the batched engine only
ever simulated fault-free runs. A :class:`FaultPlan` is the pure-array
encoding of one lane's adversity:

* **crash-stop faults** — process ``p`` dies at local time ``t`` and
  never handles or emits again. Messages addressed to it at or past
  ``t`` are lost, its timers stop, and — because neither the reference
  nor this repo models recovery — processes that are going to crash are
  *suspected from the start*: quorum selection ranks them last (they
  join no quorum) and the clients attached to them are halted (their
  command budget is zeroed and they are excused from the termination
  predicate). Until ``t`` the doomed process still participates as a
  quorum outsider: it stores payloads, votes, executes and advances the
  stability frontier, so the surviving lanes measure exactly the
  "tail latency with a degraded membership" question. Under a leader
  protocol (``config.leader`` set) a leader crash halts every client —
  nothing can commit after the leader stops and there is no election;
* **link-degradation windows** — during ``[t0, t1)`` (by the *send*
  time at the emitter) the ``(src, dst)`` delay is multiplied or
  overridden; an override at or past ``INF`` is a partition and the
  message is lost on the wire;
* **probabilistic message drops** — each process→process emission is
  lost with probability ``drop_bp / 10_000``, decided by a threefry
  draw keyed on ``(src, dst, channel-emission-index)`` so the host
  oracle and the device draw bit-identical verdicts on identical
  histories (the same schedule-independence argument as the engine's
  tie-break keys);
* **schedule jitter** — each process→process emission's delay is
  multiplied by an independent threefry draw in ``[1, jitter_max]``,
  keyed on ``(src, dst, channel-emission-index)`` exactly like drops,
  so the host oracle replays the identical perturbed schedule. Unlike
  the engine's legacy ``reorder`` perturbation (per-step draws the
  oracle cannot mirror), jitter schedules are *host-replayable* — the
  schedule-fuzzing subsystem (``fantoch_tpu/mc/fuzz.py``) is built on
  it. Multipliers are >= 1, so the conservative-lookahead matrix
  computed from base delays stays a valid lower bound and jittered
  lanes keep parallel stepping. Host-side shrinking
  (``fantoch_tpu/mc/shrink.py``) uses the explicit forms
  ``jitter_overrides``/``drop_list`` — per-message ``(src, dst,
  channel-index)`` entries the device does not implement (fuzz repro
  artifacts replay through the host oracle).

Drops and windows apply to process→process wire hops only: client hops
(SUBMIT / TO_CLIENT) model the in-process client stack, self-messages
never cross the network, and readiness-gate requeues are deferred
deliveries, not new sends. Lost prerequisites can legitimately surface
as ``ERR_STUCK`` (a commit endlessly requeued behind a dropped collect)
— that is a measured deadlock, not an engine bug; bound such lanes with
``horizon_ms``, which ends the simulation at a fixed instant on both
the device and the oracle (closed-loop clients have no retransmission,
so a lossy lane may otherwise never complete its budget).

Availability: a plan whose crashes exceed ``f`` — or leave fewer
survivors than the protocol's largest quorum/threshold
(``protocol.min_live``) — cannot reach quorum; such lanes terminate
immediately with ``ERR_UNAVAIL`` instead of hanging.

Fault plans are single-shard for now (partial-replication twins reject
them loudly).
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Dict, List, Mapping, NamedTuple, Optional, Tuple

import numpy as np

from .dims import INF

# static window-slot bound shared by every lane of a batch (fixed
# shapes under jit); plans with more windows fail loudly at build time
MAX_WINDOWS = 8

# drop probabilities are basis points out of this denominator
DROP_DENOM = 10_000


class FaultFlags(NamedTuple):
    """Trace-time fault capabilities of a compiled runner (hashable —
    part of the sweep driver's compile-cache key). A batch mixing
    fault-free and faulty lanes compiles once with the union of its
    lanes' flags; fault-free lanes' ctx arrays are inert."""

    crash: bool = False
    windows: bool = False
    drops: bool = False
    horizon: bool = False
    jitter: bool = False

    def __or__(self, other: "FaultFlags") -> "FaultFlags":
        return FaultFlags(*(bool(a or b) for a, b in zip(self, other)))


NO_FAULTS = FaultFlags()


@dataclass(frozen=True)
class LinkWindow:
    """One ``(src, dst)`` degradation interval, by send time."""

    src: int
    dst: int
    t0: int
    t1: int
    mult: int = 1              # delay multiplier (>= 1)
    delay: Optional[int] = None  # absolute override; >= INF partitions

    def __post_init__(self):
        assert self.src != self.dst, "self-links never cross the wire"
        assert 0 <= self.t0 < self.t1, "empty or negative window"
        assert self.mult >= 1, "degradation cannot speed a link up"
        assert self.delay is None or self.delay >= 1, (
            "override must be >= 1 ms (0-delay links create same-instant "
            "ties the exact-match contract excludes) or INF to partition"
        )

    def effective(self, base_delay: int) -> int:
        if self.delay is not None:
            return min(self.delay, INF)
        return min(base_delay * self.mult, INF)


@dataclass(frozen=True)
class FaultPlan:
    """One lane's fault schedule (host-side; see module docstring)."""

    crashes: Mapping[int, int] = field(default_factory=dict)
    windows: Tuple[LinkWindow, ...] = ()
    drop_bp: int = 0
    drop_seed: int = 0
    horizon_ms: Optional[int] = None
    # seeded schedule jitter: every wire hop's delay × U{1..jitter_max}
    # keyed on (src, dst, channel emission index); <= 1 disables
    jitter_max: int = 0
    jitter_seed: int = 0
    # host-only explicit perturbations (shrink/replay artifacts): exact
    # per-message delay multipliers and losses by (src, dst, channel
    # emission index). The device engine rejects plans that carry them
    # (make_lane asserts) — repro artifacts replay via the host oracle.
    jitter_overrides: Mapping[Tuple[int, int, int], int] = field(
        default_factory=dict
    )
    drop_list: Tuple[Tuple[int, int, int], ...] = ()

    def __post_init__(self):
        object.__setattr__(self, "crashes", dict(self.crashes))
        object.__setattr__(self, "windows", tuple(self.windows))
        object.__setattr__(
            self, "jitter_overrides", dict(self.jitter_overrides)
        )
        object.__setattr__(
            self, "drop_list", tuple(sorted(set(self.drop_list)))
        )
        assert len(self.windows) <= MAX_WINDOWS, (
            f"{len(self.windows)} windows > MAX_WINDOWS={MAX_WINDOWS}"
        )
        assert 0 <= self.drop_bp <= DROP_DENOM
        assert self.jitter_max >= 0
        assert all(
            m >= 1 for m in self.jitter_overrides.values()
        ), "jitter overrides only slow messages down (mult >= 1)"
        for row, t in self.crashes.items():
            assert row >= 0 and t >= 0, f"bad crash ({row}, {t})"
        # windows of one (src, dst) pair must not overlap: the device
        # selects the active window with a masked sum, which is only a
        # selection when at most one window matches an instant
        by_pair: Dict[Tuple[int, int], List[LinkWindow]] = {}
        for w in self.windows:
            by_pair.setdefault((w.src, w.dst), []).append(w)
        for pair, ws in by_pair.items():
            ws = sorted(ws, key=lambda w: w.t0)
            for a, b in zip(ws, ws[1:]):
                assert a.t1 <= b.t0, f"overlapping windows on {pair}"
        lossy = self.drop_bp > 0 or bool(self.drop_list) or any(
            w.delay is not None and w.delay >= INF for w in self.windows
        )
        if lossy:
            assert self.horizon_ms is not None, (
                "lossy plans (drops or partition windows) need "
                "horizon_ms: closed-loop clients have no "
                "retransmission, so a lost message can stall the lane "
                "forever (the oracle would loop and the device would "
                "burn to max_steps)"
            )

    # -- capability flags ---------------------------------------------

    @property
    def flags(self) -> FaultFlags:
        return FaultFlags(
            crash=bool(self.crashes),
            windows=bool(self.windows),
            drops=self.drop_bp > 0,
            horizon=self.horizon_ms is not None,
            jitter=self.jitter_max > 1,
        )

    def is_noop(self) -> bool:
        return (
            self.flags == NO_FAULTS
            and not self.jitter_overrides
            and not self.drop_list
        )

    def host_only(self) -> bool:
        """Plans carrying explicit per-message perturbations replay
        through the host oracle only (shrunk repro artifacts)."""
        return bool(self.jitter_overrides) or bool(self.drop_list)

    # -- host-side model ----------------------------------------------

    def crash_ms(self, row: int) -> int:
        return self.crashes.get(row, INF)

    def window_at(self, src: int, dst: int, send_ms: int
                  ) -> Optional[LinkWindow]:
        for w in self.windows:
            if w.src == src and w.dst == dst and w.t0 <= send_ms < w.t1:
                return w
        return None

    def wire(self, src: int, dst: int, send_ms: int, base_delay: int,
             kcnt: int, drop_table: "np.ndarray | None" = None,
             jitter_table: "np.ndarray | None" = None,
             ) -> Tuple[int, bool]:
        """The oracle's wire model: (effective delay, lost?). Mirrors
        the device's emission choke point exactly — window by send
        time, then the jitter multiplier, then the threefry drop
        verdict, all by channel emission index. Explicit
        ``jitter_overrides``/``drop_list`` entries (host-only shrunk
        plans) take the seeded tables' place per message."""
        delay, lost = base_delay, False
        w = self.window_at(src, dst, send_ms)
        if w is not None:
            delay = w.effective(base_delay)
            if delay >= INF:
                return delay, True
        mult = self.jitter_mult(src, dst, kcnt, jitter_table)
        if mult is not None and mult > 1:
            delay = min(delay * mult, INF)
            if delay >= INF:
                return delay, True
        if (src, dst, kcnt) in self._drop_set:
            lost = True
        elif drop_table is not None:
            assert kcnt < drop_table.shape[2], (
                "drop table too small; raise kmax"
            )
            lost = bool(drop_table[src, dst, kcnt])
        return delay, lost

    def jitter_mult(self, src: int, dst: int, kcnt: int,
                    jitter_table: "np.ndarray | None" = None
                    ) -> Optional[int]:
        """The jitter multiplier this plan applies to one message —
        explicit override first, else the seeded table. The single
        source of truth for :meth:`wire` AND the shrinker's recording
        wrapper (mc/shrink.py), so the recorder can never drift from
        the real wire model."""
        mult = self.jitter_overrides.get((src, dst, kcnt))
        if mult is None and jitter_table is not None:
            assert kcnt < jitter_table.shape[2], (
                "jitter table too small; raise kmax"
            )
            mult = int(jitter_table[src, dst, kcnt])
        return mult

    @property
    def _drop_set(self):
        s = self.__dict__.get("_drop_set_cache")
        if s is None:
            s = frozenset(self.drop_list)
            object.__setattr__(self, "_drop_set_cache", s)
        return s

    def drop_table(self, n: int, kmax: int = 1 << 14) -> np.ndarray:
        """Precomputed ``[n, n, kmax]`` drop verdicts for the host
        oracle — one batched threefry call instead of one per message.
        ``table[src, dst, k]`` must equal the device's in-loop draw for
        channel emission ``k`` (see ``drop_draw``)."""
        num = self.drop_bp
        return _wire_table(
            self.drop_key(), n, kmax,
            lambda key, s, d, k: drop_draw(key, s, d, k) < num,
        )

    def drop_key(self) -> np.ndarray:
        import jax.random as jr

        return np.asarray(
            jr.fold_in(jr.PRNGKey(self.drop_seed), 0xFA17)
        )

    def jitter_table(self, n: int, kmax: int = 1 << 14) -> np.ndarray:
        """Precomputed ``[n, n, kmax]`` delay multipliers for the host
        oracle (the jitter analog of :meth:`drop_table`):
        ``table[src, dst, k]`` equals the device's in-loop draw for
        channel emission ``k`` (see ``jitter_draw``)."""
        jmax = self.jitter_max
        return _wire_table(
            self.jitter_key(), n, kmax,
            lambda key, s, d, k: jitter_draw(key, s, d, k, jmax),
        )

    def jitter_key(self) -> np.ndarray:
        import jax.random as jr

        return np.asarray(
            jr.fold_in(jr.PRNGKey(self.jitter_seed), 0x717E)
        )

    # -- serialization (CLI --faults spec) ----------------------------

    @staticmethod
    def from_json(obj: dict) -> "FaultPlan":
        """``{"crash": {"1": 200}, "windows": [{"src": 0, "dst": 1,
        "t0": 100, "t1": 400, "mult": 5}], "drop_bp": 50, "seed": 1,
        "horizon": 5000}`` — window ``"delay": "inf"`` partitions.
        Accepts :meth:`meta` output too (``horizon_ms``/``drop_seed``
        spellings), so repro artifacts round-trip through it."""
        windows = []
        for w in obj.get("windows", ()):
            delay = w.get("delay")
            if isinstance(delay, str):
                assert delay.lower() == "inf", delay
                delay = INF
            windows.append(
                LinkWindow(
                    src=int(w["src"]), dst=int(w["dst"]),
                    t0=int(w["t0"]), t1=int(w["t1"]),
                    mult=int(w.get("mult", 1)), delay=delay,
                )
            )
        return FaultPlan(
            crashes={
                int(k): int(v) for k, v in obj.get("crash", {}).items()
            },
            windows=tuple(windows),
            drop_bp=int(obj.get("drop_bp", 0)),
            drop_seed=int(obj.get("seed", obj.get("drop_seed", 0))),
            horizon_ms=obj.get("horizon", obj.get("horizon_ms")),
            jitter_max=int(obj.get("jitter_max", 0)),
            jitter_seed=int(obj.get("jitter_seed", 0)),
            jitter_overrides={
                (int(o["src"]), int(o["dst"]), int(o["k"])): int(o["mult"])
                for o in obj.get("jitter_overrides", ())
            },
            drop_list=tuple(
                (int(o["src"]), int(o["dst"]), int(o["k"]))
                for o in obj.get("drop_list", ())
            ),
        )

    def meta(self, **extra) -> dict:
        """Compact per-lane metadata surfaced through LaneResults and
        the sweep results table."""
        out: dict = {}
        if self.crashes:
            out["crash"] = {str(k): int(v) for k, v in
                            sorted(self.crashes.items())}
        if self.windows:
            out["windows"] = [
                {
                    "src": w.src, "dst": w.dst, "t0": w.t0, "t1": w.t1,
                    "mult": w.mult,
                    **(
                        {"delay": "inf" if w.delay >= INF else w.delay}
                        if w.delay is not None else {}
                    ),
                }
                for w in self.windows
            ]
        if self.drop_bp:
            out["drop_bp"] = self.drop_bp
            out["drop_seed"] = self.drop_seed
        if self.horizon_ms is not None:
            out["horizon_ms"] = int(self.horizon_ms)
        if self.jitter_max > 1:
            out["jitter_max"] = self.jitter_max
            out["jitter_seed"] = self.jitter_seed
        if self.jitter_overrides:
            out["jitter_overrides"] = [
                {"src": s, "dst": d, "k": k, "mult": m}
                for (s, d, k), m in sorted(self.jitter_overrides.items())
            ]
        if self.drop_list:
            out["drop_list"] = [
                {"src": s, "dst": d, "k": k} for s, d, k in self.drop_list
            ]
        out.update(extra)
        return out


def parse_fault_specs(text: str) -> List[Optional[FaultPlan]]:
    """Parse a CLI ``--faults`` spec: a JSON object (one plan), a JSON
    list of objects (one plan per entry; ``{}``/``null`` = fault-free),
    or ``@path`` to a file holding either. Every sweep grid point is
    replicated once per returned plan, so one spec mixes fault-free and
    faulty lanes in a single compiled sweep."""
    if text.startswith("@"):
        with open(text[1:]) as fh:
            text = fh.read()
    obj = json.loads(text)
    if isinstance(obj, dict):
        obj = [obj]
    out: List[Optional[FaultPlan]] = []
    for entry in obj:
        if not entry:
            out.append(None)
            continue
        plan = FaultPlan.from_json(entry)
        out.append(None if plan.is_noop() else plan)
    return out


def _wire_table(key, n: int, kmax: int, draw_one) -> np.ndarray:
    """Batch one per-message wire draw over the full ``[n, n, kmax]``
    (src, dst, channel-emission-index) grid — the host oracle's
    precomputed twin of a device in-loop draw. ``draw_one(key, s, d,
    k)`` must be the exact device function so both sides agree on
    every message."""
    import jax
    import jax.numpy as jnp

    key = jnp.asarray(key)
    grid = jnp.arange
    table = jax.jit(
        jax.vmap(
            lambda s: jax.vmap(
                lambda d: jax.vmap(
                    lambda k: draw_one(key, s, d, k)
                )(grid(kmax))
            )(grid(n))
        )
    )(grid(n))
    return np.asarray(table)


# ----------------------------------------------------------------------
# device-side primitives (shared by engine/core.py and the wire tables)
# ----------------------------------------------------------------------


def drop_draw(key, src, dst, kcnt):
    """The drop verdict's threefry draw in [0, DROP_DENOM) — one pure
    function of (plan key, src, dst, channel emission index), so any
    two executions of the same history agree."""
    import jax.random as jr

    k = jr.fold_in(jr.fold_in(jr.fold_in(key, src), dst), kcnt)
    return jr.randint(k, (), 0, DROP_DENOM)


def jitter_draw(key, src, dst, kcnt, jmax):
    """The jitter multiplier's threefry draw in [1, jmax] — the same
    schedule-independent keying as :func:`drop_draw`, so the host
    oracle's precomputed table and the device's in-loop draw agree on
    every message. ``jmax <= 1`` yields the identity multiplier."""
    import jax.numpy as jnp
    import jax.random as jr

    k = jr.fold_in(jr.fold_in(jr.fold_in(key, src), dst), kcnt)
    return jr.randint(k, (), 0, jnp.maximum(jmax, 1)) + 1


# ----------------------------------------------------------------------
# host-side lane construction helpers (used by engine/spec.py, sim/)
# ----------------------------------------------------------------------


def batch_fault_flags(plans_or_specs) -> FaultFlags:
    """Union of fault capabilities across a batch (compile-once for
    mixed fault-free/faulty sweeps). Accepts FaultPlans, LaneSpecs, or
    None entries."""
    flags = NO_FAULTS
    for item in plans_or_specs:
        if item is None:
            continue
        f = getattr(item, "fault_flags", None)
        if f is None:
            f = item.flags
        flags = flags | f
    return flags


def min_live(protocol, config) -> int:
    """Smallest membership the protocol can make progress with: the
    protocol's own bound when it declares one, else the generic n - f."""
    fn = getattr(protocol, "min_live", None)
    if fn is None:
        return config.n - config.f
    return int(fn(config))


def unavailable(plan: FaultPlan, protocol, config) -> bool:
    """True when the plan's crashes exceed what the (recovery-free)
    protocol tolerates: more than f crashes, or fewer survivors than
    its largest quorum/threshold. A leader crash is NOT unavailability
    — it halts every client (nothing commits, vacuously clean)."""
    k = len(plan.crashes)
    if k == 0:
        return False
    if k > config.f:
        return True
    doomed = set(plan.crashes)
    if config.leader is not None and (config.leader - 1) in doomed:
        return False
    return config.n - k < min_live(protocol, config)


def reorder_doomed_last(sorted_idx: np.ndarray, doomed) -> np.ndarray:
    """Stable-partition each process's discovery order so processes
    that are going to crash rank last — quorum selection (first k of
    each row) then never includes them. The host oracle applies the
    same reorder to its discovery lists, keeping faulty schedules
    bit-identical."""
    doomed = set(doomed)
    out = sorted_idx.copy()
    for p in range(out.shape[0]):
        row = list(sorted_idx[p])
        out[p] = [q for q in row if q not in doomed] + [
            q for q in row if q in doomed
        ]
    return out


def halted_client_mask(plan: FaultPlan, config,
                       attach_rows: np.ndarray) -> np.ndarray:
    """Clients halted by the plan: attached to a doomed process, or any
    client at all under a doomed leader (no election — nothing commits
    after the leader stops)."""
    doomed = set(plan.crashes)
    halted = np.asarray(
        [int(a) in doomed for a in attach_rows], dtype=bool
    )
    if config.leader is not None and (config.leader - 1) in doomed:
        halted[:] = True
    return halted


def min_link_delays(plan: FaultPlan, delay_pp: np.ndarray,
                    total: int) -> np.ndarray:
    """Per-pair lower bound of the wire delay over the whole run —
    what the conservative-lookahead matrix must be computed from, since
    a window *override* may undercut the base delay (multipliers only
    slow links down). Returns a ``[total, total]`` copy."""
    out = delay_pp[:total, :total].astype(np.int64).copy()
    for w in plan.windows:
        if w.src >= total or w.dst >= total:
            continue
        eff = w.effective(int(out[w.src, w.dst]))
        if eff < out[w.src, w.dst]:
            out[w.src, w.dst] = eff
    return out


def fault_ctx(plan: Optional[FaultPlan], dims) -> Dict[str, np.ndarray]:
    """The plan's fixed-shape device context arrays. Present in every
    lane (inert defaults when ``plan`` is None) so batches can mix
    faulty and fault-free lanes under one compiled runner."""
    N = dims.N
    crash_t = np.full((N,), INF, np.int32)
    win_src = np.full((MAX_WINDOWS,), -1, np.int32)
    win_dst = np.full((MAX_WINDOWS,), -1, np.int32)
    win_t0 = np.zeros((MAX_WINDOWS,), np.int32)
    win_t1 = np.zeros((MAX_WINDOWS,), np.int32)
    win_mul = np.ones((MAX_WINDOWS,), np.int32)
    win_ovr = np.full((MAX_WINDOWS,), -1, np.int32)
    drop_bp = 0
    jitter_num = 1
    horizon = INF
    if plan is not None:
        assert not plan.host_only(), (
            "explicit per-message perturbations (jitter_overrides/"
            "drop_list) replay through the host oracle only"
        )
        for row, t in plan.crashes.items():
            assert row < N, f"crash row {row} out of range"
            crash_t[row] = min(t, INF)
        for i, w in enumerate(plan.windows):
            win_src[i] = w.src
            win_dst[i] = w.dst
            win_t0[i] = w.t0
            win_t1[i] = min(w.t1, INF)
            win_mul[i] = w.mult
            win_ovr[i] = -1 if w.delay is None else min(w.delay, INF)
        drop_bp = plan.drop_bp
        jitter_num = max(plan.jitter_max, 1)
        if plan.horizon_ms is not None:
            horizon = min(plan.horizon_ms, INF)
    drop_key = (
        plan.drop_key() if plan is not None and plan.drop_bp
        else FaultPlan().drop_key()
    )
    jitter_key = (
        plan.jitter_key() if plan is not None and plan.jitter_max > 1
        else FaultPlan().jitter_key()
    )
    return {
        "fault_crash_t": crash_t,
        "fault_win_src": win_src,
        "fault_win_dst": win_dst,
        "fault_win_t0": win_t0,
        "fault_win_t1": win_t1,
        "fault_win_mul": win_mul,
        "fault_win_ovr": win_ovr,
        "fault_drop_num": np.int32(drop_bp),
        "fault_drop_key": drop_key,
        "fault_jitter_num": np.int32(jitter_num),
        "fault_jitter_key": jitter_key,
        "fault_horizon": np.int32(horizon),
        # set by make_lane after the availability check
        "fault_unavail": np.int32(0),
    }

"""Host-side result collection: device arrays → the same shapes the
oracle runner reports (per-region latency histograms, per-process
protocol metrics; fantoch/src/sim/runner.rs:597-681)."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Sequence

import jax
import numpy as np

from ..core.metrics import Histogram
from .core import host_fetch
from .dims import INF, EngineDims, err_names
from .monitor import viol_names
from .spec import LaneSpec


@dataclass
class LaneResults:
    """One lane's outputs in oracle-comparable form."""

    region_rows: List[str]
    hist: np.ndarray        # [RR, H] 1 ms buckets
    lat_sum: np.ndarray     # [RR]
    lat_count: np.ndarray   # [RR]
    protocol_metrics: Dict[str, np.ndarray]  # name → per-process [N]
    steps: int
    err: int  # error bitmask (dims.ERR_*); 0 = clean run
    completed: int
    pool_peak: int = 0  # max in-flight messages (EngineDims.M sizing)
    # readiness-gate bounces; > 0 in a FIFO lane means the dot window
    # (EngineDims.D) stalled deliveries — results are correct under
    # backpressure but latencies deviate from the unbounded reference
    requeues: int = 0
    # fault-plan metadata (engine/faults.py FaultPlan.meta; None for
    # fault-free lanes) and messages lost to windows/drops
    faults: "dict | None" = None
    dropped: int = 0
    # safety-monitor outputs (engine/monitor.py; monitored runs only):
    # violation bitmask (VIOL_*) and the first violating engine step
    violation: int = 0
    violation_step: int = INF
    # the lane's interleaving coverage digest (monitor.cov_digest,
    # folded on device in finalize_lane; 0 on unmonitored runs) — what
    # mc/coverage.py buckets AFL-style across sessions
    coverage: int = 0

    @property
    def err_cause(self) -> str:
        return err_names(self.err)

    @property
    def violation_cause(self) -> str:
        return viol_names(self.violation)

    def latency_mean(self, region: str) -> float:
        row = self.region_rows.index(region)
        assert self.lat_count[row] > 0
        return float(self.lat_sum[row]) / float(self.lat_count[row])

    def histogram(self, region: str) -> Histogram:
        row = self.region_rows.index(region)
        h = Histogram()
        for ms, count in enumerate(self.hist[row]):
            if count:
                h.increment(ms, int(count))
        return h

    def issued(self, region: str) -> int:
        row = self.region_rows.index(region)
        return int(self.lat_count[row])

    # -- durable serialization (campaign journal, docs/CAMPAIGN.md) ----

    def to_json(self) -> dict:
        """Deterministic JSON-able form: every array as nested int
        lists, metrics in sorted key order — two identical results
        serialize to identical bytes under ``json.dumps(...,
        sort_keys=True)``, which is what the campaign resume contract
        (byte-identical results.jsonl) is pinned against."""
        return {
            "region_rows": list(self.region_rows),
            "hist": np.asarray(self.hist).tolist(),
            "lat_sum": np.asarray(self.lat_sum).tolist(),
            "lat_count": np.asarray(self.lat_count).tolist(),
            "protocol_metrics": {
                k: np.asarray(v).tolist()
                for k, v in sorted(self.protocol_metrics.items())
            },
            "steps": int(self.steps),
            "err": int(self.err),
            "completed": int(self.completed),
            "pool_peak": int(self.pool_peak),
            "requeues": int(self.requeues),
            "faults": self.faults,
            "dropped": int(self.dropped),
            "violation": int(self.violation),
            "violation_step": int(self.violation_step),
            "coverage": int(self.coverage),
        }

    @staticmethod
    def from_json(obj: dict) -> "LaneResults":
        return LaneResults(
            region_rows=list(obj["region_rows"]),
            hist=np.asarray(obj["hist"], np.int64),
            lat_sum=np.asarray(obj["lat_sum"], np.int64),
            lat_count=np.asarray(obj["lat_count"], np.int64),
            protocol_metrics={
                k: np.asarray(v, np.int64)
                for k, v in obj["protocol_metrics"].items()
            },
            steps=int(obj["steps"]),
            err=int(obj["err"]),
            completed=int(obj["completed"]),
            pool_peak=int(obj["pool_peak"]),
            requeues=int(obj["requeues"]),
            faults=obj.get("faults"),
            dropped=int(obj.get("dropped", 0)),
            violation=int(obj.get("violation", 0)),
            violation_step=int(obj.get("violation_step", INF)),
            coverage=int(obj.get("coverage", 0)),
        )


def collect_results(
    protocol,
    dims: EngineDims,
    final_state,
    specs: Sequence[LaneSpec],
) -> List[LaneResults]:
    st = host_fetch(
        final_state, tier="sweep", reason="lane results fetch"
    )
    out: List[LaneResults] = []
    for lane, spec in enumerate(specs):
        ps = jax.tree_util.tree_map(lambda a: a[lane], st["ps"])
        out.append(
            LaneResults(
                region_rows=spec.region_rows,
                hist=st["metrics"]["hist"][lane],
                lat_sum=st["metrics"]["lat_sum"][lane],
                lat_count=st["metrics"]["lat_count"][lane],
                protocol_metrics=protocol.metrics(ps),
                steps=int(st["steps"][lane]),
                err=int(st["err"][lane]),
                completed=int(st["clients"]["completed"][lane].sum()),
                pool_peak=int(st["pool_peak"][lane]),
                requeues=int(st["requeues"][lane]),
                faults=spec.fault_meta,
                dropped=(
                    int(st["fault_dropped"][lane])
                    if "fault_dropped" in st
                    else 0
                ),
                violation=(
                    int(st["viol"][lane]) if "viol" in st else 0
                ),
                violation_step=(
                    int(st["viol_step"][lane]) if "viol_step" in st
                    else INF
                ),
                coverage=(
                    int(st["cov"][lane]) if "cov" in st else 0
                ),
            )
        )
    return out

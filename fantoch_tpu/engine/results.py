"""Host-side result collection: device arrays → the same shapes the
oracle runner reports (per-region latency histograms, per-process
protocol metrics; fantoch/src/sim/runner.rs:597-681)."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Sequence

import jax
import numpy as np

from ..core.metrics import Histogram
from .dims import INF, EngineDims, err_names
from .monitor import viol_names
from .spec import LaneSpec


@dataclass
class LaneResults:
    """One lane's outputs in oracle-comparable form."""

    region_rows: List[str]
    hist: np.ndarray        # [RR, H] 1 ms buckets
    lat_sum: np.ndarray     # [RR]
    lat_count: np.ndarray   # [RR]
    protocol_metrics: Dict[str, np.ndarray]  # name → per-process [N]
    steps: int
    err: int  # error bitmask (dims.ERR_*); 0 = clean run
    completed: int
    pool_peak: int = 0  # max in-flight messages (EngineDims.M sizing)
    # readiness-gate bounces; > 0 in a FIFO lane means the dot window
    # (EngineDims.D) stalled deliveries — results are correct under
    # backpressure but latencies deviate from the unbounded reference
    requeues: int = 0
    # fault-plan metadata (engine/faults.py FaultPlan.meta; None for
    # fault-free lanes) and messages lost to windows/drops
    faults: "dict | None" = None
    dropped: int = 0
    # safety-monitor outputs (engine/monitor.py; monitored runs only):
    # violation bitmask (VIOL_*) and the first violating engine step
    violation: int = 0
    violation_step: int = INF

    @property
    def err_cause(self) -> str:
        return err_names(self.err)

    @property
    def violation_cause(self) -> str:
        return viol_names(self.violation)

    def latency_mean(self, region: str) -> float:
        row = self.region_rows.index(region)
        assert self.lat_count[row] > 0
        return float(self.lat_sum[row]) / float(self.lat_count[row])

    def histogram(self, region: str) -> Histogram:
        row = self.region_rows.index(region)
        h = Histogram()
        for ms, count in enumerate(self.hist[row]):
            if count:
                h.increment(ms, int(count))
        return h

    def issued(self, region: str) -> int:
        row = self.region_rows.index(region)
        return int(self.lat_count[row])


def collect_results(
    protocol,
    dims: EngineDims,
    final_state,
    specs: Sequence[LaneSpec],
) -> List[LaneResults]:
    st = jax.device_get(final_state)
    out: List[LaneResults] = []
    for lane, spec in enumerate(specs):
        ps = jax.tree_util.tree_map(lambda a: a[lane], st["ps"])
        out.append(
            LaneResults(
                region_rows=spec.region_rows,
                hist=st["metrics"]["hist"][lane],
                lat_sum=st["metrics"]["lat_sum"][lane],
                lat_count=st["metrics"]["lat_count"][lane],
                protocol_metrics=protocol.metrics(ps),
                steps=int(st["steps"][lane]),
                err=int(st["err"][lane]),
                completed=int(st["clients"]["completed"][lane].sum()),
                pool_peak=int(st["pool_peak"][lane]),
                requeues=int(st["requeues"][lane]),
                faults=spec.fault_meta,
                dropped=(
                    int(st["fault_dropped"][lane])
                    if "fault_dropped" in st
                    else 0
                ),
                violation=(
                    int(st["viol"][lane]) if "viol" in st else 0
                ),
                violation_step=(
                    int(st["viol_step"][lane]) if "viol_step" in st
                    else INF
                ),
            )
        )
    return out

"""The batched device event loop.

Replaces the reference's heap-driven single-config loop
(fantoch/src/sim/runner.rs:233-313, schedule.rs:6-61) with a fixed-shape,
vmappable step:

  1. T := min arrival time over the lane's message pool and periodic
     timers (masked min-reduction — the "heap pop");
  2. every process with a pending message at time T handles its earliest
     one (tie-break by global sequence number, which makes runs exactly
     reproducible — the reference leaves heap ties unspecified,
     schedule.rs:109-119);
  3. handlers run as one `lax.switch` over message type, `vmap`'d over
     the process axis; periodic timers fire on steps where their process
     has no message at T;
  4. emitted messages are scattered into free pool slots; messages bound
     for clients are *rewritten in place* into the client's next SUBMIT
     (closed-loop clients are deterministic: record latency, then either
     issue the next command or finish — client/mod.rs:91-137), so clients
     never occupy pool destinations.

The whole lane step sits in a `lax.while_loop` whose condition is the
lane's termination predicate; `vmap` over lanes gives the config batch,
`jit` compiles the sweep once per (protocol, dims).
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
import jax.random as jr
import numpy as np

from .dims import INF, EngineDims

I32 = jnp.int32

# per-client latency-log depth (debugging aid for differential tests)
LAT_LOG = 64

# optional per-process handled-message log depth (0 disables); set via
# enable_debug_log() before building states/runners
DEBUG_LOG = 0


def enable_debug_log(depth: int) -> None:
    global DEBUG_LOG
    DEBUG_LOG = depth


# ----------------------------------------------------------------------
# outbox helpers (used by protocol handler modules)
# ----------------------------------------------------------------------

def empty_outbox(dims: EngineDims, slots: int | None = None) -> Dict[str, Any]:
    f = dims.F if slots is None else slots
    return {
        "valid": jnp.zeros((f,), bool),
        "dst": jnp.zeros((f,), I32),
        "mtype": jnp.zeros((f,), I32),
        "payload": jnp.zeros((f, dims.P), I32),
    }


def emit(outbox, i, dst, mtype, payload, valid=True):
    """Write one message into outbox slot ``i`` (functional)."""
    pay = jnp.zeros((outbox["payload"].shape[1],), I32)
    payload = jnp.asarray(payload, I32)
    pay = jax.lax.dynamic_update_slice(pay, payload.reshape(-1), (0,))
    return {
        "valid": outbox["valid"].at[i].set(jnp.asarray(valid, bool)),
        "dst": outbox["dst"].at[i].set(jnp.asarray(dst, I32)),
        "mtype": outbox["mtype"].at[i].set(jnp.asarray(mtype, I32)),
        "payload": outbox["payload"].at[i].set(pay),
    }


def compact_order(mask, limit):
    """Scatter order for compacting masked entries: each True entry of
    ``mask`` gets its 0-based position in mask order; masked-out entries
    and positions >= ``limit`` get INF, which can never alias a valid
    index of a ``limit``-wide destination (pair with mode="drop").
    Returns (order, true_count) — callers flag ``true_count > limit`` as
    their overflow condition."""
    mask = jnp.asarray(mask, bool)
    order = jnp.cumsum(mask.astype(I32)) - 1
    order = jnp.where(mask & (order < limit), order, INF)
    return order, jnp.sum(mask)


def emit_broadcast(outbox, mtype, payload, n, me=None, exclude_me=False):
    """Fill slots 0..N-1 with a broadcast to processes < n (the
    reference's ``ToSend{target: all()}``; ``all_but_me()`` with
    ``exclude_me``). Occupies the first N outbox slots."""
    nmax = outbox["dst"].shape[0]
    procs = jnp.arange(nmax, dtype=I32)
    valid = procs < n
    if exclude_me:
        valid = valid & (procs != me)
    pay = jnp.zeros((nmax, outbox["payload"].shape[1]), I32)
    payload = jnp.asarray(payload, I32).reshape(-1)
    pay = jax.lax.dynamic_update_slice(
        pay, jnp.broadcast_to(payload, (nmax, payload.shape[0])), (0, 0)
    )
    return {
        "valid": valid,
        "dst": procs,
        "mtype": jnp.full((nmax,), mtype, I32),
        "payload": pay,
    }


# ----------------------------------------------------------------------
# client workload (key generation; mirrors client/key_gen.rs semantics)
# ----------------------------------------------------------------------

def gen_key(ctx, client, cmd_seq):
    """One key for (client, command) — counter-based so the device needs
    no generator state.

    ConflictPool (key_gen.rs:96-110): with probability conflict_rate% a
    key from the shared pool, otherwise the client's private key
    (encoded as pool_size + client). Zipf (key_gen.rs:62-77,113-119):
    inverse-CDF sampling over the precomputed weight table in
    ``ctx["zipf_cum"]``. ``ctx["key_gen_kind"]`` selects (0 = pool,
    1 = zipf)."""
    k = jr.fold_in(jr.fold_in(ctx["rng_key"], client), cmd_seq)
    conflict = jr.randint(k, (), 0, 100) < ctx["conflict_rate"]
    pool_key = jr.randint(jr.fold_in(k, 1), (), 0, jnp.maximum(ctx["pool_size"], 1))
    pool = jnp.where(conflict, pool_key, ctx["pool_size"] + client)
    u = jr.uniform(jr.fold_in(k, 2), ())
    # clamp: float32 rounding can leave cum[-1] < 1.0, and a draw at or
    # above it would index one past the table
    zipf = jnp.minimum(
        jnp.searchsorted(ctx["zipf_cum"], u, side="right"),
        ctx["zipf_cum"].shape[0] - 1,
    )
    return jnp.where(ctx["key_gen_kind"] == 0, pool, zipf).astype(I32)


# ----------------------------------------------------------------------
# lane state
# ----------------------------------------------------------------------

def init_lane_state(protocol, dims: EngineDims, ctx_np: Dict[str, np.ndarray]):
    """Build one lane's initial state (numpy, host side).

    Prepopulates the pool with every live client's first SUBMIT — the
    reference's ``Simulation::start_clients`` (runner.rs:211-220) — and
    arms the periodic timers at t = interval.
    """
    N, C, M, P, R = dims.N, dims.C, dims.M, dims.P, dims.R
    pool = {
        "arrival": np.full((M,), INF, np.int32),
        "seq": np.zeros((M,), np.int32),
        "src": np.zeros((M,), np.int32),
        "dst": np.zeros((M,), np.int32),
        "mtype": np.zeros((M,), np.int32),
        "payload": np.zeros((M, P), np.int32),
        # self-messages are delivered inline by the oracle (recursive
        # ToForward/self-target handling, runner.rs:455-471): they beat
        # any other message pending at the same instant
        "prio": np.zeros((M,), bool),
    }
    budget = ctx_np["cmd_budget"]          # [C]
    attach = ctx_np["client_attach"]       # [C]
    live = budget > 0
    assert live.sum() <= M, "pool must hold the initial submit wave"
    # first keys for every client, with the same counter scheme the
    # device uses for subsequent commands
    keyctx = {
        k: jnp.asarray(ctx_np[k])
        for k in (
            "rng_key",
            "conflict_rate",
            "pool_size",
            "key_gen_kind",
            "zipf_cum",
        )
    }
    first_keys = np.asarray(
        jax.vmap(lambda c: gen_key(keyctx, c, 1))(jnp.arange(C, dtype=I32))
    )
    slot = 0
    for c in range(C):
        if not live[c]:
            continue
        pool["arrival"][slot] = ctx_np["client_delay"][c, attach[c]]
        pool["seq"][slot] = slot
        pool["src"][slot] = N + c
        pool["dst"][slot] = attach[c]
        pool["mtype"][slot] = protocol.SUBMIT
        pool["payload"][slot, 0] = c
        pool["payload"][slot, 1] = 1
        pool["payload"][slot, 2] = first_keys[c]
        slot += 1

    intervals = ctx_np["periodic_intervals"]  # [R]
    next_periodic = np.broadcast_to(
        np.where(intervals >= INF, INF, intervals), (N, R)
    ).astype(np.int32).copy()
    # timers only run on live processes
    next_periodic[ctx_np["n"]:, :] = INF

    return {
        "pool": pool,
        "ps": protocol.init_state(dims, ctx_np),
        "next_periodic": next_periodic,
        "clients": {
            "issued": live.astype(np.int32),
            "completed": np.zeros((C,), np.int32),
            "start_time": np.zeros((C,), np.int32),
        },
        "metrics": {
            "hist": np.zeros((dims.RR, dims.H), np.int32),
            "lat_sum": np.zeros((dims.RR,), np.int32),
            "lat_count": np.zeros((dims.RR,), np.int32),
            # per-client in-order latency log (first LAT_LOG commands) —
            # differential-debugging aid, negligible memory
            "lat_log": np.full((C, LAT_LOG), -1, np.int32),
        },
        "now": np.int32(0),
        "msg_seq": np.int32(slot),
        "steps": np.int32(0),
        "done_time": np.int32(INF),
        "err": np.zeros((), bool),
        "hlog": np.full((N, max(DEBUG_LOG, 1), 6), -1, np.int32),
        "hlog_n": np.zeros((N,), np.int32),
    }


# ----------------------------------------------------------------------
# the step function
# ----------------------------------------------------------------------

def _lane_step(protocol, dims: EngineDims, st, ctx):
    N, C, M, F, R, P = dims.N, dims.C, dims.M, dims.F, dims.R, dims.P
    pool = st["pool"]
    arrival, seq = pool["arrival"], pool["seq"]

    # 1. advance time to the earliest pending event ---------------------
    T = jnp.minimum(jnp.min(arrival), jnp.min(st["next_periodic"]))

    # 2. pop at most one message per process at time T ------------------
    # (T == INF means the lane is idle: consumed slots also hold INF, so
    # without the guard they would be replayed as stale messages)
    # periodic timers take the whole step for their process: the oracle
    # pops them first (enqueued an interval ago, lowest seq) and delivers
    # their self-targeted emissions inline before any same-instant
    # message — so pending messages wait for the next step
    fire = (st["next_periodic"] == T) & (T < INF)  # [N, R]
    fired_any = jnp.any(fire, axis=1)              # [N]

    at_t = (arrival == T) & (T < INF)
    procs = jnp.arange(N, dtype=I32)
    cand = (
        at_t[None, :]
        & (pool["dst"][None, :] == procs[:, None])
        & ~fired_any[:, None]
    )  # [N, M]
    # inline self-messages first (oracle recursion), then seq order
    cand_prio = cand & pool["prio"][None, :]
    use = jnp.where(jnp.any(cand_prio, axis=1)[:, None], cand_prio, cand)
    order = jnp.where(use, seq[None, :], INF)
    slot = jnp.argmin(order, axis=1)                                  # [N]
    seq_handled = jnp.min(order, axis=1)                              # [N]
    has = seq_handled < INF
    msg = {
        "valid": has,
        "src": pool["src"][slot],
        "mtype": jnp.where(has, pool["mtype"][slot], protocol.NUM_TYPES),
        "payload": pool["payload"][slot],
    }
    arrival = arrival.at[jnp.where(has, slot, M)].set(INF, mode="drop")

    # 3. handlers -------------------------------------------------------
    def periodic_one(ps_slice, f, me):
        return protocol.periodic(ps_slice, f, me, T, ctx, dims)

    ps, pout = jax.vmap(periodic_one)(st["ps"], fire, procs)  # pout [N,F]
    next_periodic = jnp.where(
        fire, T + ctx["periodic_intervals"][None, :], st["next_periodic"]
    )

    def handle_one(ps_slice, m, me):
        return protocol.handle(ps_slice, m, me, T, ctx, dims)

    ps, outbox = jax.vmap(handle_one)(ps, msg, procs)  # outbox [N,F]

    # optional debug timeline of handled messages
    hlog, hlog_n = st["hlog"], st["hlog_n"]
    if DEBUG_LOG:
        entry = jnp.stack(
            [
                jnp.broadcast_to(T, (N,)),
                msg["mtype"],
                msg["src"],
                msg["payload"][:, 0],
                msg["payload"][:, 1],
                msg["payload"][:, 2],
            ],
            axis=1,
        )
        widx = jnp.where(has, jnp.minimum(hlog_n, DEBUG_LOG - 1), DEBUG_LOG)
        hlog = hlog.at[procs, widx].set(entry, mode="drop")
        hlog_n = hlog_n + has.astype(I32)

    # 4. flatten emissions (periodic first, mirroring handler order) ----
    def flat(ob):
        return jax.tree_util.tree_map(
            lambda a: a.reshape((-1,) + a.shape[2:]), ob
        )

    out = jax.tree_util.tree_map(
        lambda a, b: jnp.concatenate([a, b], axis=0), flat(pout), flat(outbox)
    )
    emitter = jnp.concatenate([jnp.repeat(procs, F), jnp.repeat(procs, F)])
    E = 2 * N * F
    valid, dst = out["valid"], out["dst"]

    # sequence-number ordering for emissions: the oracle assigns schedule
    # seqs in pop order — periodic events first (group 0, by process),
    # then messages in the order they were handled (their own seq), each
    # handler's emissions in outbox-slot order
    grp = jnp.concatenate(
        [jnp.zeros((N * F,), I32), jnp.ones((N * F,), I32)]
    )
    trig = jnp.concatenate(
        [jnp.repeat(procs, F), jnp.repeat(seq_handled, F)]
    )
    slotk = jnp.tile(jnp.arange(F, dtype=I32), 2 * N)

    # 5. client rewrite: TO_CLIENT → latency record + next SUBMIT -------
    is_client = valid & (dst >= N)
    c = jnp.where(is_client, dst - N, 0)
    d_back = ctx["client_delay"][c, emitter]
    t_arr = T + d_back
    latency = t_arr - st["clients"]["start_time"][c]

    cl = st["clients"]
    completed = cl["completed"].at[jnp.where(is_client, c, C)].add(
        1, mode="drop"
    )
    more = cl["issued"][c] < ctx["cmd_budget"][c]
    issue = is_client & more
    issued = cl["issued"].at[jnp.where(issue, c, C)].add(1, mode="drop")
    start_time = cl["start_time"].at[jnp.where(issue, c, C)].set(
        t_arr, mode="drop"
    )
    next_seq = cl["issued"][c] + 1
    key = jax.vmap(lambda cc, ss: gen_key(ctx, cc, ss))(c, next_seq)
    sub_payload = jnp.zeros((E, P), I32)
    sub_payload = sub_payload.at[:, 0].set(c)
    sub_payload = sub_payload.at[:, 1].set(next_seq)
    sub_payload = sub_payload.at[:, 2].set(key)

    # metrics
    row = jnp.where(is_client, ctx["client_region_row"][c], dims.RR)
    bucket = jnp.clip(latency, 0, dims.H - 1)
    metrics = st["metrics"]
    hist = metrics["hist"].at[row, bucket].add(1, mode="drop")
    lat_sum = metrics["lat_sum"].at[row].add(latency, mode="drop")
    lat_count = metrics["lat_count"].at[row].add(1, mode="drop")
    log_idx = jnp.where(is_client, cl["completed"][c], LAT_LOG)
    lat_log = metrics["lat_log"].at[
        jnp.where(is_client, c, C), log_idx
    ].set(latency, mode="drop")

    # rewrite entries in place
    dst = jnp.where(issue, ctx["client_attach"][c], dst)
    mtype = jnp.where(issue, protocol.SUBMIT, out["mtype"])
    payload = jnp.where(issue[:, None], sub_payload, out["payload"])
    src = jnp.where(is_client, N + c, emitter)
    base = jnp.where(issue, t_arr, T)
    delay = jnp.where(
        issue,
        ctx["client_delay"][c, ctx["client_attach"][c]],
        ctx["delay_pp"][emitter, jnp.clip(dst, 0, N - 1)],
    )
    valid = valid & (~is_client | issue)
    msg_arrival = base + delay
    prio = ~is_client & (dst == emitter)

    # 6. scatter into free pool slots ----------------------------------
    # rank entries in oracle schedule order (grp, trig, slotk) so that
    # same-instant ties break identically to the host oracle
    perm = jnp.lexsort((slotk, trig, grp))
    pos_sorted = jnp.cumsum(valid[perm].astype(I32))          # [E], 1-based
    rank = jnp.zeros((E,), I32).at[perm].set(pos_sorted)
    free = arrival == INF
    free_cum = jnp.cumsum(free.astype(I32))                   # [M]
    target = jnp.searchsorted(free_cum, rank, side="left")
    target = jnp.where(valid, target, M)
    pool_overflow = jnp.sum(valid) > jnp.sum(free)
    new_pool = {
        "arrival": arrival.at[target].set(msg_arrival, mode="drop"),
        "seq": seq.at[target].set(st["msg_seq"] + rank - 1, mode="drop"),
        "src": pool["src"].at[target].set(src, mode="drop"),
        "dst": pool["dst"].at[target].set(dst, mode="drop"),
        "mtype": pool["mtype"].at[target].set(mtype, mode="drop"),
        "payload": pool["payload"].at[target].set(payload, mode="drop"),
        "prio": pool["prio"].at[target].set(prio, mode="drop"),
    }

    # 7. termination bookkeeping ---------------------------------------
    live = ctx["cmd_budget"] > 0
    all_done = jnp.all(~live | (completed >= ctx["cmd_budget"]))
    last_completion = jnp.max(jnp.where(is_client, t_arr, 0))
    done_time = jnp.where(
        (st["done_time"] == INF) & all_done,
        jnp.maximum(st["now"], last_completion),
        st["done_time"],
    )
    err = st["err"] | pool_overflow | jnp.any(protocol.error(ps))

    return {
        "pool": new_pool,
        "ps": ps,
        "next_periodic": next_periodic,
        "clients": {
            "issued": issued,
            "completed": completed,
            "start_time": start_time,
        },
        "metrics": {
            "hist": hist,
            "lat_sum": lat_sum,
            "lat_count": lat_count,
            "lat_log": lat_log,
        },
        "now": T,
        "msg_seq": st["msg_seq"] + jnp.sum(valid, dtype=I32),
        "steps": st["steps"] + 1,
        "hlog": hlog,
        "hlog_n": hlog_n,
        "done_time": done_time,
        "err": err,
    }


def _lane_running(dims, st, ctx, max_steps):
    end = jnp.where(
        st["done_time"] >= INF, INF, st["done_time"] + ctx["extra_time"]
    )
    finished = (st["done_time"] < INF) & (st["now"] >= end)
    idle = st["now"] >= INF  # nothing scheduled at all
    return ~(finished | idle | st["err"]) & (st["steps"] < max_steps)


def build_runner(protocol, dims: EngineDims, max_steps: int = 1 << 22):
    """Compile the batched sweep runner: (batched state, batched ctx) →
    final batched state. vmap supplies the config-batch axis; the sweep
    driver shards that axis over the TPU mesh."""

    def run_lane(st, ctx):
        out = jax.lax.while_loop(
            lambda s: _lane_running(dims, s, ctx, max_steps),
            lambda s: _lane_step(protocol, dims, s, ctx),
            st,
        )
        # a lane truncated by max_steps must never look like a clean run
        truncated = (out["steps"] >= max_steps) & (out["done_time"] >= INF)
        return dict(out, err=out["err"] | truncated)

    return jax.jit(jax.vmap(run_lane))

"""The batched device event loop.

Replaces the reference's heap-driven single-config loop
(fantoch/src/sim/runner.rs:233-313, schedule.rs:6-61) with a fixed-shape,
vmappable step built on conservative-lookahead parallel DES (the
Chandy-Misra condition, evaluated with shared memory instead of null
messages):

  1. every process p finds its earliest local event time e_p (message
     arrival or periodic timer) and qualifies to run whenever
     e_p <= min_q(e_q + lookahead[q, p]), where lookahead is the
     all-pairs shortest-path matrix over the WAN delay graph — no chain
     of still-unsent messages can reach p before e_p. The process at the
     lane-wide minimum always qualifies, so time always advances; with
     WAN delays large relative to event spacing, most processes qualify
     every step — this recovers the ~N-fold concurrency a global-time
     step forfeits when arrivals land at distinct instants;
  2. each qualifying process handles its earliest message (prio
     self-messages first, then lowest (src, per-channel emission index)
     key — a deterministic total order the host oracle's heap shares;
     the reference leaves heap ties unspecified, schedule.rs:109-119)
     at its *own* local time. The key is src-major on purpose: counter
     values are only ever compared between messages of the same
     (src, dst) channel, where both sides count identically, so no
     global emission counter has to be reproduced across the
     out-of-order step interleavings the lookahead rule allows;
  3. handlers run as one `lax.switch` over message type, `vmap`'d over
     the process axis; a periodic timer due at e_p takes the whole step
     for its process;
  4. emitted messages are scattered into free pool slots with arrival =
     emitter's local time + pair delay; messages bound for clients are
     *rewritten in place* into the client's next SUBMIT (closed-loop
     clients are deterministic: record latency, then either issue the
     next command or finish — client/mod.rs:91-137), so clients never
     occupy pool destinations.

Event timestamps (and so all latency results) are schedule-independent;
on tie-free schedules the outcome is bit-identical to the host oracle,
which the differential tests assert per protocol.

The whole lane step sits in a `lax.while_loop` whose condition is the
lane's termination predicate; `vmap` over lanes gives the config batch,
`jit` compiles the sweep once per (protocol, dims).
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
import jax.random as jr
import numpy as np

from .dims import (
    ERR_POOL,
    ERR_STUCK,
    ERR_TRUNCATED,
    ERR_UNAVAIL,
    F32_EXACT,
    INF,
    REQUEUE_LIMIT,
    EngineDims,
)
from .faults import NO_FAULTS, FaultFlags, drop_draw, jitter_draw
from . import monitor

I32 = jnp.int32

# per-client latency-log depth (debugging aid for differential tests)
LAT_LOG = 64

# optional per-process handled-message log depth (0 disables); set via
# enable_debug_log() before building states/runners
DEBUG_LOG = 0


def enable_debug_log(depth: int) -> None:
    global DEBUG_LOG
    DEBUG_LOG = depth


# ----------------------------------------------------------------------
# kernel-lean primitives
#
# The target runtime charges a large fixed cost per emitted kernel
# (measured ~0.25 ms on the tunneled v5e VM runtime), so the engine
# avoids multi-kernel lowerings where a single fusion or one MXU matmul
# does the job: jnp.cumsum lowers to log-depth shifted adds (8+ kernels
# at M≈200) and jnp.searchsorted to a while loop (~3 ms); both collapse
# to one kernel below.
# ----------------------------------------------------------------------

# above this size the O(m²) matmul / O(v·m) comparison materialization
# stops paying for itself and the stock lowerings win
_MM_CUMSUM_LIMIT = 4096


def cumsum_i32(x, bound: "int | None" = None):
    """Inclusive cumsum along the last axis as one f32 matmul.

    The matmul is exact only while every partial sum stays within the
    float32-exact integer range (``F32_EXACT`` = 2^24): for bool masks
    that bound is the axis length. Non-bool inputs must pass ``bound``
    (a static ceiling on element magnitude) so the exactness check
    ``m * bound <= F32_EXACT`` can run at trace time — inputs that
    could exceed it fall back to the stock (multi-kernel) cumsum
    lowering instead of silently returning rounded sums."""
    m = x.shape[-1]
    if bound is None:
        if x.dtype != jnp.bool_:
            raise TypeError(
                "cumsum_i32 on non-bool input needs an explicit "
                "`bound` (static max element magnitude) to prove the "
                "f32 matmul stays integer-exact; got dtype "
                f"{x.dtype}"
            )
        bound = 1
    if m > _MM_CUMSUM_LIMIT or m * bound > F32_EXACT:
        return jnp.cumsum(x.astype(I32), axis=-1)
    tri = jnp.triu(jnp.ones((m, m), jnp.float32))
    return (x.astype(jnp.float32) @ tri).astype(I32)


def searchsorted_left(a, v):
    """``jnp.searchsorted(a, v, side="left")`` for a nondecreasing last
    axis of ``a``, as one comparison/reduction fusion."""
    if a.shape[-1] * v.shape[-1] > 1 << 22:
        return jnp.searchsorted(a, v, side="left")
    return jnp.sum(
        a[..., None, :] < v[..., :, None], axis=-1
    ).astype(I32)


def oh_set(arr, i, v):
    """``arr.at[i].set(v)`` for a scalar index on axis 0 as a one-hot
    select: fuses into neighboring elementwise work where a scatter
    would be its own kernel. An out-of-range index (a drop sentinel)
    selects nothing — same as ``mode="drop"``."""
    hit = jnp.arange(arr.shape[0], dtype=I32) == i
    return jnp.where(hit.reshape(hit.shape + (1,) * (arr.ndim - 1)), v, arr)


def oh_set2(arr, i, j, v):
    """``arr.at[i, j].set(v)`` for scalar indexes, as one fused select."""
    hit = (jnp.arange(arr.shape[0], dtype=I32)[:, None] == i) & (
        jnp.arange(arr.shape[1], dtype=I32)[None, :] == j
    )
    return jnp.where(hit.reshape(hit.shape + (1,) * (arr.ndim - 2)), v, arr)


def oh_get(arr, i):
    """``arr[i]`` for a scalar index on axis 0 as a masked reduction
    (gathers at small sizes are kernels too). OOB yields 0/False."""
    hit = jnp.arange(arr.shape[0], dtype=I32) == i
    hit = hit.reshape(hit.shape + (1,) * (arr.ndim - 1))
    if arr.dtype == jnp.bool_:
        return jnp.any(hit & arr, axis=0)
    return jnp.sum(jnp.where(hit, arr, 0), axis=0).astype(arr.dtype)


def oh_pack_pairs(pay, lo, a, b):
    """Scatter (a[i], b[i]) pairs into payload positions (lo[i],
    lo[i] + 1) as one-hot add-reductions — the fusable form of two
    ``pay.at[lo].set`` scatters. Correct only because the target slots
    are zero (add == set there); out-of-range lo entries drop."""
    iota = jnp.arange(pay.shape[0], dtype=I32)
    oh_lo = lo[:, None] == iota[None, :]
    oh_hi = (lo + 1)[:, None] == iota[None, :]
    return pay + jnp.sum(
        jnp.where(oh_lo, a[:, None], 0) + jnp.where(oh_hi, b[:, None], 0),
        axis=0,
        dtype=I32,
    )


def oh_match(match, vals):
    """Select ``vals[i]`` into output position ``j`` where
    ``match[i, j]`` — for precomputed one-hot pairings (at most one
    True per column by the caller's contract, e.g. rank-matching the
    i-th new entry onto the i-th free slot). Columns with no match
    yield 0."""
    return jnp.sum(jnp.where(match, vals[:, None], 0), axis=0, dtype=I32)


def oh_route(idx, vals, n):
    """Route ``vals[i]`` to lane ``idx[i]`` of an ``[n]`` output — the
    fusable inverse of a gather, as a one-hot sum. The ``idx`` entries
    must be distinct by the caller's contract (out-of-range entries
    drop); with duplicates the sums would silently merge, so callers
    route only naturally-unique ids (e.g. one vote range per quorum
    member)."""
    oh = idx[:, None] == jnp.arange(n, dtype=I32)[None, :]
    return jnp.sum(jnp.where(oh, vals[:, None], 0), axis=0, dtype=I32)


def oh_take(vec, idxs):
    """``vec[idxs]`` for a small 1-D ``vec`` and an index array, as one
    masked-sum fusion instead of a gather kernel. OOB yields 0/False."""
    hit = idxs[..., None] == jnp.arange(vec.shape[0], dtype=I32)
    if vec.dtype == jnp.bool_:
        return jnp.any(hit & vec, axis=-1)
    return jnp.sum(jnp.where(hit, vec, 0), axis=-1).astype(vec.dtype)


# fold_health's i32 branch carries dims.ERR_* bitmasks; the per-bit
# pred embedding below is exact exactly while every flag lives in this
# many low bits (pinned against the dims catalogue at import)
_HEALTH_NBITS = 8
assert max(ERR_POOL, ERR_STUCK, ERR_TRUNCATED, ERR_UNAVAIL) < (
    1 << _HEALTH_NBITS
), "dims.ERR_* outgrew fold_health's bit embedding — raise _HEALTH_NBITS"


def fold_health(flags):
    """OR-fold a per-process flag vector into one lane scalar. The
    step's health verdicts (requeue ``stuck``, the protocol error
    codes) are the only *scalar* cross-process reductions in the step;
    under the state-sharded mesh (ROADMAP item 3) each is one tiny
    psum per step, mirroring ``parallel/partition.py``'s liveness
    psum. Declared by name as a GL501 choke point (lint/shard.py
    ``CHOKE_FNS``) — keep the reduction inside this function.

    The i32 branch (dims.ERR_* masks) ORs per *bit* through one pred
    reduction rather than ``jnp.bitwise_or.reduce``: the SPMD
    partitioner on the pinned jaxlib has no cross-shard ``or``
    computation for s32 (pred is supported), and the state-sharded
    layout turns this fold into exactly that collective. Bit-exact
    for any mask in the low :data:`_HEALTH_NBITS` bits — the static
    assert above pins that to the ERR catalogue — and still one
    reduce kernel (the bit spread/recombine is fusable elementwise,
    so the GL201 ledger is unchanged)."""
    flags = jnp.asarray(flags)
    if flags.dtype == jnp.bool_:
        return jnp.any(flags)
    masks = jnp.asarray([1 << b for b in range(_HEALTH_NBITS)], I32)
    bits = jnp.any((flags[:, None] & masks[None, :]) != 0, axis=0)
    out = jnp.zeros((), I32)
    for b in range(_HEALTH_NBITS):
        out = out | jnp.where(bits[b], I32(1 << b), I32(0))
    return out


def fold_count(flags):
    """Population-count companion to :func:`fold_health`: the per-step
    requeue diagnostic sums a per-process flag vector to one lane
    scalar — a small sum-psum on the state-sharded mesh. Declared by
    name as a GL501 choke point (lint/shard.py ``CHOKE_FNS``)."""
    return jnp.sum(flags, dtype=I32)


def emitter_times(ep, emitter):
    """Per-emission read of each emitter's local time. The ``[N]``
    time vector rides the same all-gather as the emission merge
    (:func:`merge_emissions`), so the wire batch can stamp departure
    times without a second hop. Declared by name as a GL501 choke
    point (lint/shard.py ``CHOKE_FNS``)."""
    return ep[emitter]


def mark_popped(slot, has, m):
    """One-hot OR-combine of the per-process pops into the pool's
    ``[M]`` free map. Under the state-sharded mesh (ROADMAP item 3)
    the pool stays replicated per lane shard, so the pop commit is a
    small OR-psum of each process shard's one-hot pop mask. Declared
    by name as a GL501 choke point (lint/shard.py ``CHOKE_FNS``)."""
    return jnp.any(
        (jnp.arange(m, dtype=I32)[None, :] == slot[:, None])
        & has[:, None],
        axis=0,
    )


def frontier_min(reach, ep):
    """The virtual-time frontier all-reduce: the per-destination safe
    bound (column min of the reachability matrix) and the lane-wide
    minimum event time. This is the one unavoidable per-step
    cross-process reduction of the time oracle — a small min-psum
    pair on the state-sharded mesh. Declared by name as a GL501 choke
    point (lint/shard.py ``CHOKE_FNS``)."""
    return jnp.min(reach, axis=0), jnp.min(ep)


# ----------------------------------------------------------------------
# message pool layout: one packed [M, 8 + P] i32 image so pops gather a
# whole message row in one kernel and the step's emissions land in one
# row scatter (field-per-array pools cost one scatter per field)
# ----------------------------------------------------------------------

PA = 0    # arrival time (INF = free slot)
PKS = 1   # tie-break key: emitting src
PKC = 2   # tie-break key: per-(src, dst) channel emission index
PSRC = 3  # sender
PDST = 4  # destination process
PMT = 5   # message type
PRQ = 6   # readiness-gate bounce count
PPR = 7   # priority (inline self-message) flag
PPAY = 8  # payload words start here
POOL_FIELDS = 8


# ----------------------------------------------------------------------
# outbox helpers (used by protocol handler modules)
# ----------------------------------------------------------------------

def empty_outbox(dims: EngineDims, slots: int | None = None) -> Dict[str, Any]:
    f = dims.F if slots is None else slots
    return {
        "valid": jnp.zeros((f,), bool),
        "dst": jnp.zeros((f,), I32),
        "mtype": jnp.zeros((f,), I32),
        "payload": jnp.zeros((f, dims.P), I32),
        # -1 = engine-assigned WAN delay; >= 0 overrides it (requeues)
        "delay": jnp.full((f,), -1, I32),
        # -1 = the emitting process; >= 0 preserves an original sender
        "src": jnp.full((f,), -1, I32),
    }


def emit(outbox, i, dst, mtype, payload, valid=True, delay=-1, src=-1):
    """Write one message into outbox slot ``i`` (functional).

    ``delay >= 0`` overrides the engine's WAN delay and ``src >= 0``
    overrides the recorded sender — used by the engine's readiness-gate
    requeue row (see ``_lane_step`` step 4), not by protocol
    handlers."""
    pay = jnp.zeros((outbox["payload"].shape[1],), I32)
    payload = jnp.asarray(payload, I32)
    pay = jax.lax.dynamic_update_slice(pay, payload.reshape(-1), (0,))
    return {
        "valid": outbox["valid"].at[i].set(jnp.asarray(valid, bool)),
        "dst": outbox["dst"].at[i].set(jnp.asarray(dst, I32)),
        "mtype": outbox["mtype"].at[i].set(jnp.asarray(mtype, I32)),
        "payload": outbox["payload"].at[i].set(pay),
        "delay": outbox["delay"].at[i].set(jnp.asarray(delay, I32)),
        "src": outbox["src"].at[i].set(jnp.asarray(src, I32)),
    }


def pack_outbox(valid, dst, mtype, payload, delay=None, src=None):
    """Assemble a whole outbox from bulk row arrays — the third
    sanctioned emission constructor next to :func:`emit` and
    :func:`emit_broadcast`, for handlers that build every row with
    vectorized writes (FPaxos's forward + accept fan-out). Keeping
    construction inside this module lets the AST lint (docs/LINT.md
    rule GL101) prove every protocol emission flows through the
    engine's choke points (fault masks, channel counters)."""
    f = valid.shape[0]
    return {
        "valid": jnp.asarray(valid, bool),
        "dst": jnp.asarray(dst, I32),
        "mtype": jnp.asarray(mtype, I32),
        "payload": jnp.asarray(payload, I32),
        "delay": jnp.full((f,), -1, I32) if delay is None else delay,
        "src": jnp.full((f,), -1, I32) if src is None else src,
    }


def compact_order(mask, limit):
    """Scatter order for compacting masked entries: each True entry of
    ``mask`` gets its 0-based position in mask order; masked-out entries
    and positions >= ``limit`` get INF, which can never alias a valid
    index of a ``limit``-wide destination (pair with mode="drop").
    Returns (order, true_count) — callers flag ``true_count > limit`` as
    their overflow condition."""
    mask = jnp.asarray(mask, bool)
    order = cumsum_i32(mask) - 1
    order = jnp.where(mask & (order < limit), order, INF)
    return order, jnp.sum(mask)


def emit_broadcast(outbox, mtype, payload, n, me=None, exclude_me=False,
                   base=0):
    """Fill slots 0..N-1 with a broadcast to processes ``base`` ..
    ``base + n - 1`` (the reference's ``ToSend{target: all()}``;
    ``all_but_me()`` with ``exclude_me``; ``base`` > 0 targets one
    shard's process block under partial replication). Occupies the
    first N outbox slots; destinations are base + slot index."""
    nmax = outbox["dst"].shape[0]
    procs = jnp.arange(nmax, dtype=I32) + base
    valid = procs < base + n  # i.e. slot index < n
    if exclude_me:
        valid = valid & (procs != me)
    pay = jnp.zeros((nmax, outbox["payload"].shape[1]), I32)
    payload = jnp.asarray(payload, I32).reshape(-1)
    pay = jax.lax.dynamic_update_slice(
        pay, jnp.broadcast_to(payload, (nmax, payload.shape[0])), (0, 0)
    )
    return {
        "valid": valid,
        "dst": procs,
        "mtype": jnp.full((nmax,), mtype, I32),
        "payload": pay,
        "delay": jnp.full((nmax,), -1, I32),
        "src": jnp.full((nmax,), -1, I32),
    }


def merge_emissions(n, f2, *parts):
    """Flatten the per-process emission blocks ``[N, *, ...]`` into one
    ``[N*F2, ...]`` wire batch. This is the lone *structural* N-mix in
    the step outside the routing helpers: every process's rows
    interleave into a single emission axis, so under a state-sharded
    mesh (ROADMAP item 3) this is where the cross-device all-gather
    happens. Declared by name as a GL501 choke point (lint/shard.py
    ``CHOKE_FNS``) — keep the concatenate+reshape inside this
    function. The explicit flatten/unflatten loop (rather than a
    ``tree_map`` lambda) keeps every mixing equation's source frame
    named ``merge_emissions``, which is what the choke match keys on."""
    all_leaves = [jax.tree_util.tree_leaves(p) for p in parts]
    treedef = jax.tree_util.tree_structure(parts[0])
    merged = []
    for xs in zip(*all_leaves):
        merged.append(
            jnp.concatenate(xs, axis=1).reshape(
                (n * f2,) + xs[0].shape[2:]
            )
        )
    return jax.tree_util.tree_unflatten(treedef, merged)


def run_handlers(protocol, ps, msg, procs, ep, ctx, dims):
    """Apply each process's message handler at its own local time.
    Elementwise over the process axis by construction — GL501 proves
    the ``ps`` N axis mixes nowhere in here — so a state-sharded mesh
    (ROADMAP item 3) can run this phase under ``shard_map`` with no
    collectives. Named and exported for exactly that use (and for the
    shard-family bit-identity pin in tests/test_lint_shard.py)."""
    def handle_one(ps_slice, m, me, t):
        return protocol.handle(ps_slice, m, me, t, ctx, dims)

    return jax.vmap(handle_one)(ps, msg, procs, ep)


# ----------------------------------------------------------------------
# client workload (key generation; mirrors client/key_gen.rs semantics)
# ----------------------------------------------------------------------

def gen_key(ctx, client, cmd_seq):
    """One key for (client, command) — counter-based so the device needs
    no generator state.

    ConflictPool (key_gen.rs:96-110): with probability conflict_rate% a
    key from the shared pool, otherwise the client's private key
    (encoded as pool_size + client). Zipf (key_gen.rs:62-77,113-119):
    inverse-CDF sampling over the precomputed weight table in
    ``ctx["zipf_cum"]``. ``ctx["key_gen_kind"]`` selects (0 = pool,
    1 = zipf).

    Time-varying traffic (fantoch_tpu/traffic, docs/TRAFFIC.md) is
    structure-gated like ``key_table``/``cmd_target``: when the lane
    carries compiled schedule tables, the ConflictPool parameters come
    from the command's epoch (seq → epoch index, then per-epoch knob
    gathers) and the shared pool rotates with ``pool_base`` — hot-key
    churn with the boundary on the exact command seq. A schedule-less
    (or flat) lane takes the branchless static path below and traces
    the bit-identical jaxpr."""
    k = jr.fold_in(jr.fold_in(ctx["rng_key"], client), cmd_seq)
    if "traffic_seq_epoch" in ctx:
        tbl = ctx["traffic_seq_epoch"]
        e = oh_take(
            tbl,
            jnp.minimum(jnp.asarray(cmd_seq, I32), tbl.shape[0] - 1),
        )
        conflict = (
            jr.randint(k, (), 0, 100) < oh_take(ctx["traffic_conflict"], e)
        )
        pool_key = oh_take(ctx["traffic_pool_base"], e) + jr.randint(
            jr.fold_in(k, 1), (), 0,
            jnp.maximum(oh_take(ctx["traffic_pool_size"], e), 1),
        )
        # private keys sit above EVERY epoch's pool so churn rotation
        # can never alias a client's private key
        pool = jnp.where(
            conflict, pool_key, ctx["traffic_pool_span"] + client
        )
    else:
        conflict = jr.randint(k, (), 0, 100) < ctx["conflict_rate"]
        pool_key = jr.randint(
            jr.fold_in(k, 1), (), 0, jnp.maximum(ctx["pool_size"], 1)
        )
        pool = jnp.where(conflict, pool_key, ctx["pool_size"] + client)
    u = jr.uniform(jr.fold_in(k, 2), ())
    if "traffic_zipf_cum" in ctx:
        # epoch-varying Zipf (KeyGen::Zipf under a schedule): the [E, K]
        # cumulative table's row for this command's epoch replaces the
        # static zipf_cum before the inverse-CDF draw — same fold-in
        # stream, so a single-epoch override degenerates to the static
        # draw over the overridden table
        tbl = ctx["traffic_seq_epoch"]
        ze = oh_take(
            tbl,
            jnp.minimum(jnp.asarray(cmd_seq, I32), tbl.shape[0] - 1),
        )
        zipf_cum = oh_get(ctx["traffic_zipf_cum"], ze)
    else:
        zipf_cum = ctx["zipf_cum"]
    # clamp: float32 rounding can leave cum[-1] < 1.0, and a draw at or
    # above it would index one past the table
    zipf = jnp.minimum(
        jnp.searchsorted(zipf_cum, u, side="right"),
        zipf_cum.shape[0] - 1,
    )
    return jnp.where(ctx["key_gen_kind"] == 0, pool, zipf).astype(I32)


# ----------------------------------------------------------------------
# lane state
# ----------------------------------------------------------------------

KEYGEN_CTX_FIELDS = (
    "rng_key",
    "conflict_rate",
    "pool_size",
    "key_gen_kind",
    "zipf_cum",
)

# traffic-schedule tables (fantoch_tpu/traffic; present only on lanes
# with a non-flat schedule — structure-gating keeps static traces
# bit-identical). traffic_think/traffic_read_pct ride in ctx for the
# step/mirror but do not feed gen_key.
TRAFFIC_CTX_FIELDS = (
    "traffic_seq_epoch",
    "traffic_conflict",
    "traffic_pool_base",
    "traffic_pool_size",
    "traffic_pool_span",
)


def keygen_ctx_fields(ctx) -> tuple:
    """The ctx keys :func:`gen_key` reads for this lane's structure —
    the base generator fields plus, when the lane carries a traffic
    schedule, its epoch tables (and the epoch-varying zipf table when
    present). Every caller that slices a keygen ctx (key tables,
    lane-state init, the host DeviceStream mirror) must use this so
    schedule-driven keys stay bit-identical everywhere."""
    fields = KEYGEN_CTX_FIELDS
    if "traffic_seq_epoch" in ctx:
        fields = fields + TRAFFIC_CTX_FIELDS
    if "traffic_zipf_cum" in ctx:
        fields = fields + ("traffic_zipf_cum",)
    return fields


def first_keys_fn(C: int):
    """Jit-able: keygen ctx slice → every client's first command key.
    Sweep drivers vmap this over the lane batch so host-side state init
    does one device call instead of one per lane."""

    def one(ctx):
        return jax.vmap(lambda c: gen_key(ctx, c, 1))(
            jnp.arange(C, dtype=I32)
        )

    return one


def key_table_fn(C: int, T: int):
    """Jit-able: keygen ctx slice → the full [C, T] key table (seq is
    the column index; column 0 is unused — seqs are 1-based).

    Threefry is the dominant per-step cost when keys are drawn inside
    the engine loop (6 foldings per emission row per step); since a
    key depends only on (client, seq), the sweep driver precomputes the
    whole table in one batched call and the step gathers from
    ``ctx["key_table"]`` instead (bit-identical keys, RNG work moved
    entirely out of the loop)."""

    def one(ctx):
        return jax.vmap(
            lambda c: jax.vmap(lambda s: gen_key(ctx, c, s))(
                jnp.arange(T, dtype=I32)
            )
        )(jnp.arange(C, dtype=I32))

    return one


def init_lane_state(
    protocol,
    dims: EngineDims,
    ctx_np: Dict[str, np.ndarray],
    first_keys: "np.ndarray | None" = None,
    monitor_keys: int = 0,
):
    """Build one lane's initial state (numpy, host side).

    Prepopulates the pool with every live client's first SUBMIT — the
    reference's ``Simulation::start_clients`` (runner.rs:211-220) — and
    arms the periodic timers at t = interval. ``first_keys`` ([C], from
    :func:`first_keys_fn`) skips the per-lane device round trip.
    ``monitor_keys > 0`` adds the on-device safety-monitor state
    (engine/monitor.py) with that per-key capacity; it must match the
    runner's ``monitor_keys``.
    """
    N, C, M, P, R = dims.N, dims.C, dims.M, dims.P, dims.R
    # packed pool image: columns PA..PPR then P payload words (see the
    # layout constants above); tie-break key (ksrc, kcnt) = (emitting
    # src, emission index on the (src, dst) channel), compared
    # lexicographically; prio marks self-messages the oracle delivers
    # inline (recursive ToForward/self-target handling,
    # runner.rs:455-471) — they beat any other same-instant message
    pool = np.zeros((M, POOL_FIELDS + P), np.int32)
    pool[:, PA] = INF
    budget = ctx_np["cmd_budget"]          # [C]
    if "cmd_target" in ctx_np:
        # partial replication: each client's first SUBMIT targets its
        # connected process of the first command's target shard
        attach = ctx_np["client_attach_s"][
            np.arange(C), ctx_np["cmd_target"][:, 1]
        ]
    else:
        attach = ctx_np["client_attach"]   # [C]
    live = budget > 0
    assert live.sum() <= M, "pool must hold the initial submit wave"
    # first keys for every client, with the same counter scheme the
    # device uses for subsequent commands
    if first_keys is None:
        keyctx = {
            k: jnp.asarray(ctx_np[k]) for k in keygen_ctx_fields(ctx_np)
        }
        first_keys = np.asarray(first_keys_fn(C)(keyctx))
    # time-varying traffic: the first SUBMIT leaves after the first
    # command's epoch think delay (the oracle schedules start_clients
    # submits with the same extra distance)
    if "traffic_think" in ctx_np:
        think0 = int(
            ctx_np["traffic_think"][int(ctx_np["traffic_seq_epoch"][1])]
        )
    else:
        think0 = 0
    # open loop: the first SUBMIT leaves at its *arrival* time A(c, 1)
    # instead of t=0 (the schedule's first inter-arrival gap; think is
    # asserted zero for open-loop lanes in make_lane)
    open_loop = "ol_arrival" in ctx_np
    slot = 0
    for c in range(C):
        if not live[c]:
            continue
        release0 = (
            int(ctx_np["ol_arrival"][c, 1]) if open_loop else think0
        )
        pool[slot, PA] = ctx_np["client_delay"][c, attach[c]] + release0
        # each client's first SUBMIT is emission #1 on its channel
        pool[slot, PKS] = N + c
        pool[slot, PKC] = 1
        pool[slot, PSRC] = N + c
        pool[slot, PDST] = attach[c]
        pool[slot, PMT] = protocol.SUBMIT
        pool[slot, PPAY + 0] = c
        pool[slot, PPAY + 1] = 1
        pool[slot, PPAY + 2] = first_keys[c]
        slot += 1

    intervals = ctx_np["periodic_intervals"]  # [R]
    next_periodic = np.broadcast_to(
        np.where(intervals >= INF, INF, intervals), (N, R)
    ).astype(np.int32).copy()
    # timers only run on live processes (``rows`` = all shards' rows
    # under partial replication; single-shard lanes predate the key)
    live_rows = int(ctx_np.get("rows", ctx_np["n"]))
    next_periodic[live_rows:, :] = INF

    mon = monitor.mon_init(dims, monitor_keys) if monitor_keys else {}
    clients = {
        "issued": live.astype(np.int32),
        "completed": np.zeros((C,), np.int32),
        "start_time": np.zeros((C,), np.int32),
        # result parts (per-key/per-shard partials) of the command
        # in flight + latest part arrival
        "parts": np.zeros((C,), np.int32),
        "part_max": np.zeros((C,), np.int32),
    }
    if open_loop:
        W = int(ctx_np["ol_window"])
        clients.update({
            # completion-time ring (GL202-bounded arrival-queue plane):
            # completion #k of client c lands at slot (k-1) mod W —
            # overwrite-safe because command s stages only after s-W
            # completed, so at most W live entries exist at once
            "ol_comp_t": np.zeros((C, W), np.int32),
            # monotone release clamp R(s) = max(A(s), F(s), R(s-1));
            # seeds at the first arrival
            "ol_last_rel": ctx_np["ol_arrival"][:, 1].astype(np.int32),
        })
    return {
        **mon,
        "pool": pool,
        "ps": protocol.init_state(dims, ctx_np),
        "next_periodic": next_periodic,
        "clients": clients,
        "metrics": {
            "hist": np.zeros((dims.RR, dims.H), np.int32),
            "lat_sum": np.zeros((dims.RR,), np.int32),
            "lat_count": np.zeros((dims.RR,), np.int32),
            # per-client in-order latency log (first LAT_LOG commands) —
            # differential-debugging aid, negligible memory
            "lat_log": np.full((C, LAT_LOG), -1, np.int32),
        },
        "now": np.int32(0),
        # per-(src, dst) channel emission counters (dst < N: clients'
        # SUBMITs use the client's own submit number instead)
        "pair_cnt": np.zeros((N, N), np.int32),
        "steps": np.int32(0),
        "pool_peak": np.int32(int(live.sum())),
        # messages lost to fault windows/drops (per-lane diagnostic)
        "fault_dropped": np.int32(0),
        # total readiness-gate bounces: > 0 in a FIFO (non-reorder) lane
        # means an undersized dot window stalled deliveries and latency
        # results deviate from the unbounded-buffer reference — loud in
        # LaneResults without failing the lane (backpressure is still
        # correct, just slower)
        "requeues": np.int32(0),
        "max_completion": np.int32(0),
        "done_time": np.int32(INF),
        "err": np.zeros((), np.int32),  # error bitmask (dims.ERR_*)
        "hlog": np.full((N, max(DEBUG_LOG, 1), 6), -1, np.int32),
        "hlog_n": np.zeros((N,), np.int32),
    }


# ----------------------------------------------------------------------
# dtype-narrowed storage planes
#
# The steady-state sweep is bandwidth-bound past ~512 lanes
# (docs/PERF.md "cost model"): every while-loop iteration writes the
# whole carried state back to HBM, so bytes-in-the-carry is the tax the
# narrowing pass attacks. Cold i32 planes whose values provably stay
# tiny for the batch at hand — command counters bounded by the batch's
# host-known command budget, result-part counts bounded by the cmd
# tables, per-command protocol metric counters the protocols declare —
# are *stored* as i16/i8 in the carry and widened back to i32 at the
# top of each step, so every handler computes in exactly the arithmetic
# GL001 audited and results stay bit-identical (tests/test_pipeline.py
# pins narrow ≡ wide byte-for-byte). The spec is static per batch
# (engine/spec.py narrow_spec), keyed into the runner cache.
# ----------------------------------------------------------------------


def cast_state_planes(state, narrow, *, store: bool):
    """Cast the planes named by ``narrow`` (a tuple of
    ``("clients/issued", "int16")``-style entries from
    :func:`~fantoch_tpu.engine.spec.narrow_spec`) to their storage
    dtype (``store=True``) or back to i32 (``store=False``). Works on
    numpy trees (host-side init/fetch) and tracers (inside the jitted
    runner) alike; an empty spec returns the tree untouched, so the
    narrow-free trace is bit-identical to the pre-narrowing graph.
    Paths missing from ``state`` are skipped — result fetches carry
    only a sub-tree of the full state."""
    if not narrow:
        return state
    out = dict(state)
    for path, dtname in narrow:
        parts = path.split("/")
        node = out
        ok = True
        for p in parts[:-1]:
            if not isinstance(node.get(p), dict):
                ok = False
                break
            node[p] = dict(node[p])
            node = node[p]
        if not ok or parts[-1] not in node:
            continue
        node[parts[-1]] = node[parts[-1]].astype(
            dtname if store else jnp.int32
        )
    return out


# ----------------------------------------------------------------------
# the step function
# ----------------------------------------------------------------------

def _lane_step(protocol, dims: EngineDims, st, ctx, reorder: bool = False,
               faults: FaultFlags = NO_FAULTS, monitor_keys: int = 0):
    N, C, M, F, R, P = dims.N, dims.C, dims.M, dims.F, dims.R, dims.P
    # safety monitors (engine/monitor.py) ride inside ps through the
    # handler vmaps; a trace-time no-op when monitor_keys == 0
    ps_in = monitor.merge_mon(st) if monitor_keys else st["ps"]
    pool = st["pool"]                     # [M, POOL_FIELDS + P]
    arrival = pool[:, PA]
    pool_dst = pool[:, PDST]
    pool_ksrc = pool[:, PKS]
    pool_prio = pool[:, PPR] != 0
    procs = jnp.arange(N, dtype=I32)

    # fault choke point 0 (crash-stop): a message addressed to a process
    # at or past its crash time is lost, and a crashed process's timers
    # stop — the oracle skips the same events at pop time. Both masks
    # are idempotent, so re-applying them every step needs no extra
    # bookkeeping; once purged, a crashed process's earliest event time
    # is INF and it stops qualifying, emitting, or gating anyone's
    # lookahead bound (its e_q drops out of the Chandy-Misra condition,
    # which is exactly the per-window recomputation the conservative
    # rule needs).
    if faults.crash:
        crash_t = ctx["fault_crash_t"]                        # [N]
        arrival = jnp.where(
            arrival >= oh_take(crash_t, pool_dst), INF, arrival
        )
        next_periodic_in = jnp.where(
            st["next_periodic"] >= crash_t[:, None], INF,
            st["next_periodic"],
        )
    else:
        next_periodic_in = st["next_periodic"]

    # 1. per-process local event times + conservative lookahead ---------
    # Each process p advances to its own earliest pending event e_p
    # (message arrival or periodic timer) and may process it whenever
    # e_p <= min_q(e_q + lookahead[q, p]) — no chain of still-unsent
    # messages can reach p earlier (lookahead = all-pairs shortest path
    # over the delay matrix, built host-side in make_lane). The process
    # holding the lane-wide minimum always qualifies, so time advances
    # every step; typically most processes qualify at once, which is
    # what beats the one-event-per-step serialization of a heap DES.
    dstmask = pool_dst[None, :] == procs[:, None]             # [N, M]
    arr_p = jnp.min(
        jnp.where(dstmask, arrival[None, :], INF), axis=1
    )                                                         # [N]
    ep = jnp.minimum(arr_p, jnp.min(next_periodic_in, axis=1))
    reach = jnp.where(
        (ep[:, None] >= INF) | (ctx["lookahead"] >= INF),
        INF,
        ep[:, None] + ctx["lookahead"],
    )                                                         # [q, p]
    bound, T = frontier_min(reach, ep)  # [N], lane-wide virtual time
    # strictly below the bound: at ep == bound a message with a smaller
    # tie key could still arrive at exactly ep. Processes at the global
    # minimum T are always safe (nothing can arrive before T) — that
    # also guarantees progress whatever the delay matrix.
    active = (ep < INF) & ((ep < bound) | (ep == T))
    if faults.horizon:
        # events at or past the fault horizon are never handled (the
        # oracle stops popping at the same instant); once every pending
        # event sits past it, now >= horizon and the lane ends
        active = active & (ep < ctx["fault_horizon"])

    # 2. pop at most one message per active process at its local time --
    # periodic timers take the whole step for their process: the oracle
    # pops them first (enqueued an interval ago, lowest seq) and delivers
    # their self-targeted emissions inline before any same-instant
    # message — so pending messages wait for the next step
    fire = (
        (next_periodic_in == ep[:, None]) & active[:, None]
    )                                                         # [N, R]
    fired_any = jnp.any(fire, axis=1)                         # [N]

    at_t = arrival[None, :] == ep[:, None]                    # [N, M]
    cand = (
        at_t
        & dstmask
        & active[:, None]
        & ~fired_any[:, None]
    )  # [N, M]
    # inline self-messages first (oracle recursion), then lexicographic
    # (ksrc, kcnt) order
    cand_prio = cand & pool_prio[None, :]
    use = jnp.where(jnp.any(cand_prio, axis=1)[:, None], cand_prio, cand)
    usrc = jnp.where(use, pool_ksrc[None, :], INF)
    min_src = jnp.min(usrc, axis=1)                                   # [N]
    order = jnp.where(
        use & (pool_ksrc[None, :] == min_src[:, None]),
        pool[:, PKC][None, :],
        INF,
    )
    slot = jnp.argmin(order, axis=1)                                  # [N]
    has = jnp.any(use, axis=1)
    popped_rows = pool[slot]               # [N, POOL_FIELDS + P]
    msg = {
        "valid": has,
        "src": popped_rows[:, PSRC],
        "mtype": jnp.where(
            has, popped_rows[:, PMT], protocol.NUM_TYPES
        ),
        "payload": popped_rows[:, PPAY:],
    }
    # free the popped slots (one-hot, fuses; a scatter is a kernel)
    popped = mark_popped(slot, has, M)
    arrival = jnp.where(popped, INF, arrival)

    # readiness gate: a message that overtook its prerequisite (possible
    # only under reordering — FIFO channels deliver prerequisites first)
    # is requeued to arrive 1 ms later instead of reaching its handler,
    # the fixed-shape analog of the reference's buffered-commit stores
    # (tempo.rs buffered mcommits, executor/slot.rs:17-69)
    if hasattr(protocol, "ready"):
        rdy = jax.vmap(
            lambda p, m, me_: protocol.ready(p, m, me_, ctx, dims)
        )(ps_in, msg, procs)
        rdy = jnp.asarray(rdy, bool)
    else:
        rdy = jnp.ones((N,), bool)
    requeued = has & ~rdy
    rq_next = jnp.where(requeued, popped_rows[:, PRQ] + 1, 0)  # [N]
    stuck = fold_health(rq_next > REQUEUE_LIMIT)
    msg = dict(
        msg,
        valid=has & rdy,
        mtype=jnp.where(has & rdy, msg["mtype"], protocol.NUM_TYPES),
    )

    # 3. handlers (each at its process's own local time) ----------------
    def periodic_one(ps_slice, f, me, t):
        return protocol.periodic(ps_slice, f, me, t, ctx, dims)

    ps, pout = jax.vmap(periodic_one)(ps_in, fire, procs, ep)
    next_periodic = jnp.where(
        fire, ep[:, None] + ctx["periodic_intervals"][None, :],
        next_periodic_in,
    )

    ps, outbox = run_handlers(
        protocol, ps, msg, procs, ep, ctx, dims
    )  # outbox [N, F]
    if monitor_keys:
        ps, mon = monitor.strip_mon(ps)
        viol, viol_step = monitor.step_viol(st, mon["mon_flags"])

    # optional debug timeline of handled messages
    hlog, hlog_n = st["hlog"], st["hlog_n"]
    if DEBUG_LOG:
        entry = jnp.stack(
            [
                ep,
                msg["mtype"],
                msg["src"],
                msg["payload"][:, 0],
                msg["payload"][:, 1],
                msg["payload"][:, 2],
            ],
            axis=1,
        )
        widx = jnp.where(has, jnp.minimum(hlog_n, DEBUG_LOG - 1), DEBUG_LOG)
        hlog = hlog.at[procs, widx].set(entry, mode="drop")
        hlog_n = hlog_n + has.astype(I32)

    # 4. flatten emissions, keeping each process's rows contiguous with
    # its periodic emissions first (the oracle pops periodic events
    # before same-instant messages, so their emissions count first on
    # each channel); one engine row per process re-emits a message the
    # readiness gate bounced
    rq = {
        "valid": requeued[:, None],
        "dst": procs[:, None],
        "mtype": jnp.where(requeued, popped_rows[:, PMT], 0)[:, None],
        "payload": popped_rows[:, PPAY:][:, None, :],
        "delay": jnp.ones((N, 1), I32),
        "src": popped_rows[:, PSRC][:, None],
    }
    open_loop = "ol_arrival" in ctx
    if open_loop:
        # open-loop trigger 1 — arrival-queue staging at SUBMIT pop
        # (docs/TRAFFIC.md "Open-loop arrivals"): when a process pops
        # client sc's SUBMIT for command s and the in-flight window
        # already admits command q = s+1, the NEXT SUBMIT is staged
        # immediately with release time R(q) = max(A(q), F(q),
        # R(s)) — arrival A from the precomputed table, window gate F
        # from the completion-time ring, monotone clamp from
        # ol_last_rel — independent of s's completion. One extra
        # emission row per process carries it, with the engine's
        # delay/src override mechanism pinning its pool arrival to
        # R(q) + d_sub (>= ep by R(q) >= R(s) = ep - d_sub, single
        # shard). Window-full commands are staged by trigger 2 at the
        # gate-crossing completion instead (step 5) — the two triggers
        # target the same command under contradictory window gates, so
        # they are mutually exclusive by construction.
        cl0 = st["clients"]
        A_tbl = ctx["ol_arrival"]                          # [C, T]
        Wd = cl0["ol_comp_t"].shape[1]
        sc = jnp.clip(msg["src"] - N, 0, C - 1)           # [N]
        s_seq = msg["payload"][:, 1]
        q_next = s_seq + 1
        stage1 = (
            msg["valid"]
            & (msg["mtype"] == protocol.SUBMIT)
            & (msg["src"] >= N)
            & (s_seq == cl0["issued"][sc])
            & (q_next <= ctx["cmd_budget"][sc])
            & (cl0["completed"][sc] + Wd >= q_next)
        )
        f_gate = jnp.where(
            q_next > Wd,
            cl0["ol_comp_t"][sc, jnp.mod(q_next - Wd - 1, Wd)],
            0,
        )
        rel1 = jnp.maximum(
            jnp.maximum(
                A_tbl[sc, jnp.minimum(q_next, A_tbl.shape[1] - 1)],
                f_gate,
            ),
            cl0["ol_last_rel"][sc],
        )
        attach1 = ctx["client_attach"][sc]
        d_sub1 = ctx["client_delay"][sc, attach1]
        if "key_table" in ctx:
            T_keys = ctx["key_table"].shape[1]
            key1 = ctx["key_table"][
                sc, jnp.minimum(q_next, T_keys - 1)
            ]
        else:
            key1 = jax.vmap(lambda cc, ss: gen_key(ctx, cc, ss))(
                sc, q_next
            )
        stage_payload = jnp.zeros((N, 1, P), I32)
        stage_payload = stage_payload.at[:, 0, 0].set(sc)
        stage_payload = stage_payload.at[:, 0, 1].set(q_next)
        stage_payload = stage_payload.at[:, 0, 2].set(key1)
        stage = {
            "valid": stage1[:, None],
            "dst": attach1[:, None],
            "mtype": jnp.full((N, 1), protocol.SUBMIT, I32),
            "payload": stage_payload,
            "delay": jnp.where(stage1, rel1 + d_sub1 - ep, 0)[:, None],
            "src": (N + sc)[:, None],
        }
        F2 = 2 * F + 2
        out = merge_emissions(N, F2, pout, outbox, stage, rq)
    else:
        F2 = 2 * F + 1
        out = merge_emissions(N, F2, pout, outbox, rq)
    emitter = jnp.repeat(procs, F2)
    E = N * F2
    valid, dst = out["valid"], out["dst"]
    # each process's last emission row is its readiness-gate requeue
    is_rq = jnp.zeros((N, F2), bool).at[:, F2 - 1].set(True).reshape(E)
    if open_loop:
        # the stage row sits just before the requeue row; like requeues
        # it is excluded from channel counting (its kcnt is the
        # client's submit number) — its delay override already keeps it
        # out of wire faults, scaling and prio marking
        is_stage = (
            jnp.zeros((N, F2), bool).at[:, F2 - 2].set(True).reshape(E)
        )
        stage_seq_e = (
            jnp.zeros((N, F2), I32).at[:, F2 - 2].set(q_next).reshape(E)
        )

    # 5. client rewrite: TO_CLIENT → latency record + next SUBMIT -------
    # reorder perturbation (runner.rs:520-524): every hop's delay scales
    # by an independent uniform [0, 10) draw; the three hop kinds in
    # this stage (TO_CLIENT return, next SUBMIT, process send) each use
    # their own slice of the per-step draw block. ``reorder`` is a
    # trace-time flag so normal sweeps compile without any RNG work.
    if reorder:
        u = jr.uniform(
            jr.fold_in(ctx["reorder_key"], st["steps"]), (3, E),
            maxval=10.0,
        )

        def scaled(d, row):
            return (d * u[row]).astype(I32)

    else:

        def scaled(d, row):
            return d

    ep_e = emitter_times(ep, emitter)  # emissions leave at local time
    is_client = valid & (dst >= N)
    c = jnp.where(is_client, dst - N, 0)
    d_back = scaled(ctx["client_delay"][c, emitter], 0)
    t_arr = ep_e + d_back

    cl = st["clients"]
    # per-client updates as one-hot reductions (C is tiny; scatters are
    # one kernel each on the target runtime, these fuse away). Each
    # TO_CLIENT is one result *part* (a per-key/per-shard partial under
    # partial replication, run/task/client/pending.rs); a command
    # completes when its parts count reaches ``cmd_parts`` (1 without
    # multi-key tables), at the latest part's arrival time. The closed
    # loop guarantees at most one *completion* per client per step.
    iota_c = jnp.arange(C, dtype=I32)
    if faults.horizon:
        # a result that would reach its client at or past the fault
        # horizon is never delivered (the oracle never pops it), so it
        # completes nothing and issues nothing
        is_client_done = is_client & (t_arr < ctx["fault_horizon"])
    else:
        is_client_done = is_client
    oh_done = (
        is_client_done[:, None] & (c[:, None] == iota_c[None, :])
    )  # [E, C]
    arrivals = jnp.sum(oh_done, axis=0, dtype=I32)                  # [C]
    if open_loop:
        # open-loop completion accounting: every TO_CLIENT is one
        # whole completion (single-shard single-key is asserted in
        # make_lane, so cmd_parts is always 1) and — unlike the closed
        # loop — several commands of one client can complete in one
        # step (up to W are in flight). Attribution is count-based:
        # the k-th completion of client c closes the k-th arrival.
        # This is exactly the oracle's fold order: all of a client's
        # TO_CLIENTs come from its single attach process, per-process
        # handled times are nondecreasing and d_back is constant per
        # (client, attach), so count order = time order; same-step
        # completions all share one t_arr (one handler per process per
        # step), making the within-step assignment multiset-invariant.
        k0 = cl["completed"]
        Wd = cl["ol_comp_t"].shape[1]
        completed = k0 + arrivals
        # one completion-arrival instant per client per step (see
        # above); completions k0+1..k0+arrivals land in the ring at
        # slots (k0 .. k0+arrivals-1) mod W — overwrite-safe because
        # entry #k is next needed to gate command k+W, which cannot
        # have been staged while #k was still in flight
        t_c = jnp.max(jnp.where(oh_done, t_arr[:, None], 0), axis=0)
        w_iota = jnp.arange(Wd, dtype=I32)
        in_ring = (
            jnp.mod(w_iota[None, :] - k0[:, None], Wd)
            < arrivals[:, None]
        )                                                       # [C, W]
        ol_comp_t = jnp.where(in_ring, t_c[:, None], cl["ol_comp_t"])
        # the closed loop's parts/start_time machinery idles (zeros)
        parts = cl["parts"]
        part_max = cl["part_max"]
        start_time = cl["start_time"]
        done_t = t_c                                            # [C]
        row_idx = jnp.arange(E, dtype=I32)
        last_row = jnp.max(
            jnp.where(oh_done, row_idx[:, None], -1), axis=0
        )                                                       # [C]
        is_completing = (
            is_client & (row_idx == last_row[c]) & (arrivals[c] > 0)
        )
        # open-loop trigger 2 — gate-crossing completion: command
        # pend = issued+1 was window-blocked at its SUBMIT pop
        # (trigger 1's gate failed, so ~gate_old) and this step's
        # completions just admitted it. The last completing row is
        # rewritten into its SUBMIT with release R(pend) =
        # max(A(pend), t_c, R(pend-1)) — F(pend) = t_c because the
        # gate crossed this very step, so completion #(pend-W)
        # happened now. Mutually exclusive with trigger 1 (gate_old
        # there is exactly ~gate_old here).
        pend = cl["issued"] + 1                                 # [C]
        more_c = cl["issued"] < ctx["cmd_budget"]
        gate_new = completed + Wd >= pend
        gate_old = k0 + Wd >= pend
        trigger2_c = (arrivals > 0) & more_c & gate_new & ~gate_old
        issue = is_completing & trigger2_c[c]
        oh_issue = (
            oh_done & (row_idx[:, None] == last_row[None, :])
            & trigger2_c[None, :]
        )                                                       # [E, C]
        A_tbl = ctx["ol_arrival"]
        rel2_c = jnp.maximum(
            jnp.maximum(
                A_tbl[iota_c, jnp.minimum(pend, A_tbl.shape[1] - 1)],
                t_c,
            ),
            cl["ol_last_rel"],
        )
        # fold trigger 1 (per-process, step 4) to per-client: at most
        # one SUBMIT per client pops per step (single attach process,
        # one pop per process), so the one-hot has <= 1 hit per column
        oh_t1 = stage1[:, None] & (sc[:, None] == iota_c[None, :])
        staged1_c = jnp.any(oh_t1, axis=0)                      # [C]
        rel1_c = jnp.sum(
            jnp.where(oh_t1, rel1[:, None], 0), axis=0, dtype=I32
        )
        issued = (
            cl["issued"]
            + jnp.sum(oh_issue, axis=0, dtype=I32)
            + staged1_c.astype(I32)
        )
        ol_last_rel = jnp.maximum(
            cl["ol_last_rel"],
            jnp.where(
                staged1_c,
                rel1_c,
                jnp.where(trigger2_c, rel2_c, cl["ol_last_rel"]),
            ),
        )
    else:
        if "cmd_parts" in ctx:
            T_parts = ctx["cmd_parts"].shape[1]
            need = ctx["cmd_parts"][
                iota_c, jnp.minimum(cl["issued"], T_parts - 1)
            ]
        else:
            need = jnp.ones((C,), I32)
        parts_new = cl["parts"] + arrivals
        # latest part arrival per client (parts can arrive out of step
        # order under lookahead execution, so carry a running max)
        part_max = jnp.maximum(
            cl["part_max"],
            jnp.max(jnp.where(oh_done, t_arr[:, None], 0), axis=0),
        )
        complete_c = (arrivals > 0) & (parts_new >= need)           # [C]
        completed = cl["completed"] + complete_c.astype(I32)
        parts = jnp.where(complete_c, 0, parts_new)
        done_t = part_max                                           # [C]
        latency_c = done_t - cl["start_time"]
        part_max = jnp.where(complete_c, 0, part_max)

        # the completing row: the last row per client this step (row
        # choice only picks which outbox slot carries the next SUBMIT;
        # its base time comes from done_t)
        row_idx = jnp.arange(E, dtype=I32)
        last_row = jnp.max(
            jnp.where(oh_done, row_idx[:, None], -1), axis=0
        )                                                           # [C]
        is_completing = (
            is_client & (row_idx == last_row[c]) & complete_c[c]
        )

        more = cl["issued"][c] < ctx["cmd_budget"][c]
        issue = is_completing & more
        oh_issue = (
            oh_done & (row_idx[:, None] == last_row[None, :])
            & complete_c[None, :] & more[:, None]
        )                                                           # [E, C]
        issued = cl["issued"] + jnp.sum(oh_issue, axis=0, dtype=I32)
        st_new = jnp.where(jnp.any(oh_issue, axis=0), done_t, -1)
        start_time = jnp.where(st_new >= 0, st_new, cl["start_time"])
    next_seq = cl["issued"][c] + 1
    if "key_table" in ctx:
        # precomputed (client, seq) → key table: no RNG in the loop
        T_keys = ctx["key_table"].shape[1]
        key = ctx["key_table"][c, jnp.minimum(next_seq, T_keys - 1)]
    elif "cmd_target" in ctx:
        key = jnp.zeros((E,), I32)  # keys live in ctx cmd tables
    else:
        key = jax.vmap(lambda cc, ss: gen_key(ctx, cc, ss))(c, next_seq)
    sub_payload = jnp.zeros((E, P), I32)
    sub_payload = sub_payload.at[:, 0].set(c)
    sub_payload = sub_payload.at[:, 1].set(next_seq)
    sub_payload = sub_payload.at[:, 2].set(key)

    # metrics on completion only (hist/lat_log keep their scatters —
    # their one-hot forms would materialize [E, RR, H]-scale
    # intermediates)
    if open_loop:
        # queue-delay-inclusive latency, one record per TO_CLIENT row
        # (several of one client can land in a step): completion
        # #(k0 + within-step row rank) closes arrival #k, so latency =
        # t_arr - A(k) — the arrival-queue wait plus the full protocol
        # round trip. Ranks among same-step rows are by row order,
        # which is sound because they all share one t_arr (see the
        # completion-accounting comment above).
        same_cd = (c[:, None] == c[None, :]) & is_client_done[None, :]
        rank_e = jnp.sum(
            same_cd & (row_idx[None, :] <= row_idx[:, None]),
            axis=1, dtype=I32,
        )
        k_i = cl["completed"][c] + rank_e
        latency = t_arr - ctx["ol_arrival"][
            c, jnp.minimum(k_i, ctx["ol_arrival"].shape[1] - 1)
        ]
        rec = is_client_done
        log_src = k_i - 1
    else:
        latency = latency_c[c]
        rec = is_completing
        log_src = cl["completed"][c]
    row = jnp.where(rec, ctx["client_region_row"][c], dims.RR)
    bucket = jnp.clip(latency, 0, dims.H - 1)
    metrics = st["metrics"]
    hist = metrics["hist"].at[row, bucket].add(1, mode="drop")
    oh_row = row[:, None] == jnp.arange(dims.RR, dtype=I32)[None, :]
    lat_sum = metrics["lat_sum"] + jnp.sum(
        jnp.where(oh_row, latency[:, None], 0), axis=0, dtype=I32
    )
    lat_count = metrics["lat_count"] + jnp.sum(oh_row, axis=0, dtype=I32)
    log_idx = jnp.where(rec, log_src, LAT_LOG)
    lat_log = metrics["lat_log"].at[
        jnp.where(rec, c, C), log_idx
    ].set(latency, mode="drop")

    # rewrite entries in place
    if "cmd_target" in ctx:
        # partial replication: the next SUBMIT goes to the client's
        # connected process of the command's target shard (the shard
        # of its first key, client/workload.py:84)
        T_t = ctx["cmd_target"].shape[1]
        tgt_shard = ctx["cmd_target"][c, jnp.minimum(next_seq, T_t - 1)]
        attach = ctx["client_attach_s"][c, tgt_shard]
    else:
        attach = ctx["client_attach"][c]
    dst = jnp.where(issue, attach, dst)
    mtype = jnp.where(issue, protocol.SUBMIT, out["mtype"])
    payload = jnp.where(issue[:, None], sub_payload, out["payload"])
    src = jnp.where(is_client, N + c, emitter)
    src = jnp.where(out["src"] >= 0, out["src"], src)
    # the next SUBMIT leaves at the command's completion time (the
    # latest part's arrival, == t_arr for single-part commands); a
    # traffic schedule adds the issued command's epoch think delay —
    # diurnal load — which the oracle mirrors as extra submit distance
    # (structure-gated: schedule-less lanes trace the exact line below)
    if open_loop:
        # trigger-2 SUBMITs leave at the staged release time R(pend),
        # not at completion: queue delay (release - arrival) is the
        # open loop's latency component, not an issue-time shift.
        # Think delays are asserted zero for open-loop lanes.
        base = jnp.where(issue, rel2_c[c], ep_e)
    elif "traffic_think" in ctx:
        tbl = ctx["traffic_seq_epoch"]
        e_next = oh_take(tbl, jnp.minimum(next_seq, tbl.shape[0] - 1))
        think = oh_take(ctx["traffic_think"], e_next)
        base = jnp.where(issue, done_t[c] + think, ep_e)
    else:
        base = jnp.where(issue, done_t[c], ep_e)
    overridden = out["delay"] >= 0  # requeues: fixed delay, never scaled
    delay = jnp.where(
        issue,
        scaled(ctx["client_delay"][c, attach], 1),
        scaled(ctx["delay_pp"][emitter, jnp.clip(dst, 0, N - 1)], 2),
    )
    delay = jnp.where(overridden, out["delay"], delay)

    # fault choke point 1 (wire faults apply to process->process sends
    # only: client hops model the in-process client stack, requeues are
    # deferred deliveries, self-messages never cross the network)
    wired = valid & ~is_client & ~is_rq & ~overridden & (dst != emitter)
    if faults.windows:
        # link-degradation windows, by the emitter's local send time;
        # an effective delay at or past INF is a partition and the
        # message is lost on the wire (after taking its channel
        # counter value — the oracle counts before it drops too)
        wm = (
            (ctx["fault_win_src"][None, :] == emitter[:, None])
            & (ctx["fault_win_dst"][None, :] == dst[:, None])
            & (ctx["fault_win_t0"][None, :] <= ep_e[:, None])
            & (ep_e[:, None] < ctx["fault_win_t1"][None, :])
            & wired[:, None]
        )                                                     # [E, W]
        w_hit = jnp.any(wm, axis=1)
        # windows of one (src, dst) pair never overlap (validated at
        # plan construction), so masked sums select the active window
        w_mul = jnp.sum(
            jnp.where(wm, ctx["fault_win_mul"][None, :], 0), axis=1
        )
        w_ovr = jnp.sum(
            jnp.where(wm, ctx["fault_win_ovr"][None, :], 0), axis=1
        )
        # multiply with an overflow clamp: mul > INF // delay implies
        # delay * mul > INF, exactly the oracle's min(base*mult, INF)
        # (an i32 wraparound would deliver at a negative arrival time)
        w_mul = jnp.maximum(w_mul, 1)
        mul_cap = INF // jnp.maximum(delay, 1)
        eff_mul = jnp.where(w_mul > mul_cap, INF, delay * w_mul)
        eff = jnp.where(w_ovr >= 0, w_ovr, eff_mul)
        lost = w_hit & (eff >= INF)
        delay = jnp.where(w_hit & ~lost, eff, delay)
    else:
        lost = jnp.zeros((E,), bool)

    valid = valid & (~is_client | issue)
    if not faults.jitter:
        # computed here (not after choke point 1b) so the jitter-free
        # trace keeps the exact op order of a jitter-incapable engine —
        # same serialized HLO, same persistent-compile-cache key
        msg_arrival = base + delay
    prio = ~is_client & (dst == emitter) & ~overridden

    # sequence keys: the schedule-independent tie-break total order
    # (ksrc, kcnt) with kcnt counting emissions per (src, dst) channel.
    # Same-(arrival, dst) ties compare src first; the counter is only
    # ever compared between messages of one channel, where both the
    # oracle and the engine count the same per-channel emission order —
    # so key values never depend on how steps interleave across
    # processes. Rewritten SUBMITs carry the client's submit number (the
    # oracle keys them by the client's counter); a zero-delay client
    # round trip is safe because every process src ranks before every
    # client src, so the freshly inserted SUBMIT can never overtake a
    # process message the oracle had already popped at that instant.
    rows = jnp.arange(F2)
    # requeue rows re-enter the pool with their ORIGINAL (ksrc, kcnt)
    # key — they are deliveries deferred, not new emissions — so they
    # keep their place in the per-channel FIFO order and never consume
    # channel counter values
    dst_b = dst.reshape(N, F2)
    if open_loop:
        # staged SUBMITs are client emissions (kcnt = submit number
        # below), never channel-counted process sends
        chan_b = (valid & ~is_client & ~is_rq & ~is_stage).reshape(
            N, F2
        )
    else:
        chan_b = (
            (valid & ~is_client & ~is_rq).reshape(N, F2)
        )  # channel-counted rows
    same = (dst_b[:, None, :] == dst_b[:, :, None]) & chan_b[:, None, :]
    rank_b = jnp.sum(
        same & (rows[None, :] < rows[:, None])[None], axis=2
    )                                                         # [N, F2]
    safe_dst = jnp.clip(dst, 0, N - 1)
    orig_kcnt = (
        jnp.zeros((N, F2), I32)
        .at[:, F2 - 1]
        .set(popped_rows[:, PKC])
        .reshape(E)
    )
    kcnt = jnp.where(
        issue,
        next_seq,
        st["pair_cnt"][emitter, safe_dst] + rank_b.reshape(E) + 1,
    )
    kcnt = jnp.where(is_rq, orig_kcnt, kcnt)
    if open_loop:
        # a staged SUBMIT's tie-break key is the client's submit
        # number, like rewritten SUBMITs — same (ksrc, kcnt) contract
        # the oracle keys client channels by
        kcnt = jnp.where(is_stage, stage_seq_e, kcnt)
        counted = valid & ~is_client & ~is_rq & ~is_stage
    else:
        counted = valid & ~is_client & ~is_rq
    ksrc = src  # N + c for client-issued SUBMITs, emitter otherwise
    ohe = emitter[:, None] == procs[None, :]                  # [E, N]
    ohd = (dst[:, None] == procs[None, :]) & counted[:, None]
    pair_cnt = st["pair_cnt"] + jnp.sum(
        ohe[:, :, None] & ohd[:, None, :], axis=0, dtype=I32
    )

    # fault choke point 1b (schedule jitter): every wire hop's delay is
    # multiplied by an independent threefry draw in [1, jitter_max],
    # keyed on (src, dst, channel emission index) — the same schedule-
    # independence argument as drops, so the host oracle's precomputed
    # jitter table replays the identical perturbed schedule. This is
    # the fuzz subsystem's host-replayable alternative to the legacy
    # per-step ``reorder`` draws. Multipliers >= 1 keep the lane's
    # base-delay lookahead matrix a valid lower bound.
    if faults.jitter:
        jm = jax.vmap(
            lambda s, d, k: jitter_draw(
                ctx["fault_jitter_key"], s, d, k, ctx["fault_jitter_num"]
            )
        )(emitter, jnp.clip(dst, 0, N - 1), kcnt)
        j_cap = INF // jnp.maximum(delay, 1)
        j_eff = jnp.where(jm > j_cap, INF, delay * jm)
        j_lost = wired & (j_eff >= INF)
        delay = jnp.where(wired & ~j_lost, j_eff, delay)
        lost = lost | j_lost
        msg_arrival = base + delay

    # fault choke point 2 (probabilistic wire loss): the verdict is a
    # pure threefry function of (src, dst, channel emission index), so
    # the host oracle draws the identical verdict for the identical
    # message whatever the step interleaving — the same schedule-
    # independence argument as the tie-break keys. Lost messages KEEP
    # their channel counter value (pair_cnt above counts pre-loss,
    # like the oracle) but never land in the pool.
    if faults.drops:
        draw = jax.vmap(
            lambda s, d, k: drop_draw(ctx["fault_drop_key"], s, d, k)
        )(emitter, jnp.clip(dst, 0, N - 1), kcnt)
        lost = lost | (wired & (draw < ctx["fault_drop_num"]))
    if faults.windows or faults.drops or faults.jitter:
        deliver = valid & ~lost
        n_lost = jnp.sum(valid & lost, dtype=I32)
    else:
        deliver = valid
        n_lost = jnp.zeros((), I32)

    # 6. pack the emissions and land them in free pool slots with ONE
    # row scatter (slot choice is arbitrary — ordering lives in the
    # (ksrc, kcnt) keys)
    rank = cumsum_i32(deliver)                                # [E], 1-based
    free = arrival == INF
    free_cum = cumsum_i32(free)                               # [M]
    target = searchsorted_left(free_cum, rank)
    target = jnp.where(deliver, target, M)
    n_free = jnp.sum(free)
    pool_overflow = jnp.sum(deliver) > n_free
    # the requeue-count column joins the wire batch through the same
    # flatten choke as the emissions themselves
    rq_arr = merge_emissions(
        N, F2, jnp.zeros((N, F2), I32).at[:, F2 - 1].set(rq_next)
    )
    # diagnostic: peak pool occupancy, for sizing EngineDims.M
    pool_peak = jnp.maximum(
        st["pool_peak"], M - n_free + jnp.sum(deliver, dtype=I32)
    )
    new_rows = jnp.concatenate(
        [
            msg_arrival[:, None],
            ksrc[:, None],
            kcnt[:, None],
            src[:, None],
            dst[:, None],
            mtype[:, None],
            rq_arr[:, None],
            prio.astype(I32)[:, None],
            payload,
        ],
        axis=1,
    )                                                         # [E, 8 + P]
    new_pool = pool.at[:, PA].set(arrival).at[target].set(
        new_rows, mode="drop"
    )

    # 7. termination bookkeeping ---------------------------------------
    # under out-of-order (lookahead) execution the globally latest
    # completion may be recorded steps before all_done flips, so carry a
    # running max (the oracle anchors extra_sim_time at the pop time of
    # the last final TO_CLIENT, i.e. the max arrival time)
    live = ctx["cmd_budget"] > 0
    all_done = jnp.all(~live | (completed >= ctx["cmd_budget"]))
    max_completion = jnp.maximum(
        st["max_completion"],
        jnp.max(jnp.where(is_completing, done_t[c], 0)),
    )
    done_time = jnp.where(
        (st["done_time"] == INF) & all_done,
        max_completion,
        st["done_time"],
    )
    err = (
        st["err"]
        | ERR_POOL * pool_overflow
        | ERR_STUCK * stuck
        | fold_health(jnp.asarray(protocol.error(ps), I32))
    )
    if faults.crash:
        # statically-known unavailability (crashes exceed what the
        # protocol tolerates): terminate now, never hang toward
        # ERR_STUCK/ERR_TRUNCATED
        err = err | ERR_UNAVAIL * (ctx["fault_unavail"] != 0)

    out_mon = (
        # cov rides the carry untouched: the digest is derived once per
        # lane by monitor.finalize_lane, never inside the step
        dict(mon, viol=viol, viol_step=viol_step, cov=st["cov"])
        if monitor_keys
        else {}
    )
    clients_out = {
        "issued": issued,
        "completed": completed,
        "start_time": start_time,
        "parts": parts,
        "part_max": part_max,
    }
    if open_loop:
        clients_out["ol_comp_t"] = ol_comp_t
        clients_out["ol_last_rel"] = ol_last_rel
    return {
        **out_mon,
        "pool": new_pool,
        "ps": ps,
        "next_periodic": next_periodic,
        "clients": clients_out,
        "metrics": {
            "hist": hist,
            "lat_sum": lat_sum,
            "lat_count": lat_count,
            "lat_log": lat_log,
        },
        "now": T,
        "pair_cnt": pair_cnt,
        "pool_peak": pool_peak,
        "fault_dropped": st["fault_dropped"] + n_lost,
        "requeues": st["requeues"] + fold_count(requeued),
        "max_completion": max_completion,
        "steps": st["steps"] + 1,
        "hlog": hlog,
        "hlog_n": hlog_n,
        "done_time": done_time,
        "err": err,
    }


def _lane_running(dims, st, ctx, max_steps, faults: FaultFlags = NO_FAULTS):
    end = jnp.where(
        st["done_time"] >= INF, INF, st["done_time"] + ctx["extra_time"]
    )
    finished = (st["done_time"] < INF) & (st["now"] >= end)
    idle = st["now"] >= INF  # nothing scheduled at all
    running = (
        ~(finished | idle | (st["err"] != 0)) & (st["steps"] < max_steps)
    )
    if faults.horizon:
        # fault-plan horizon: the lane ends at a fixed simulated
        # instant (lossy lanes may never complete their budget)
        running = running & (st["now"] < ctx["fault_horizon"])
    return running


def _check_monitorable(protocol, monitor_keys: int) -> None:
    if monitor_keys:
        assert getattr(protocol, "MONITORED", False), (
            f"{type(protocol).__name__ if not isinstance(protocol, type) else protocol.__name__}"
            " has no monitor hooks (mon_exec at its executor choke "
            "point); fuzzing it would report every lane as "
            "missing-execution"
        )


def build_runner(
    protocol, dims: EngineDims, max_steps: int = 1 << 22,
    reorder: bool = False, faults: FaultFlags = NO_FAULTS,
    monitor_keys: int = 0,
):
    """Compile the batched sweep runner: (batched state, batched ctx) →
    final batched state. vmap supplies the config-batch axis; the sweep
    driver shards that axis over the TPU mesh. ``reorder`` must match
    the lanes' ``make_lane(reorder=...)`` flag (one compiled runner per
    setting — mixing both in one batch is not supported). ``faults``
    is the batch's fault-capability union (engine/faults.py): lanes
    with and without fault plans share one compiled runner, and an
    all-False ``faults`` compiles exactly the fault-free graph.
    ``monitor_keys > 0`` compiles the safety monitors in
    (engine/monitor.py) and reduces them to a per-lane violation
    bitmask at lane end; 0 compiles the exact unmonitored graph."""
    _check_monitorable(protocol, monitor_keys)

    def run_lane(st, ctx):
        out = jax.lax.while_loop(
            lambda s: _lane_running(dims, s, ctx, max_steps, faults),
            lambda s: _lane_step(
                protocol, dims, s, ctx, reorder, faults, monitor_keys
            ),
            st,
        )
        # a lane truncated by max_steps must never look like a clean run
        truncated = (out["steps"] >= max_steps) & (out["done_time"] >= INF)
        out = dict(out, err=out["err"] | ERR_TRUNCATED * truncated)
        if monitor_keys:
            out = monitor.finalize_lane(
                protocol, dims, out, ctx, faults, running=False
            )
        return out

    return jax.jit(jax.vmap(run_lane))


#: first jaxlib where executable deserialization preserves donation
#: aliasing, killing the donation-vs-deserialization corruption class
#: for good. On the current 0.4.x pin the bug is REAL and re-measured
#: (docs/PERF.md "Pipelined dispatch & donation"): a process that has
#: deserialized any executable from the persistent compile cache
#: corrupts donated state, and the AOT serialization surface
#: (parallel/aot.py) reproduces the purest form — a donated
#: executable loaded via ``serialize_executable`` returns garbage
#: counters in ANY process, cache or no cache. Donation therefore
#: stays version-gated: old jaxlib → the old cache-free-process rule
#: (and never on deserialized AOT executables); once the pin moves to
#: or past this version the exclusions retire themselves with no code
#: change.
DONATION_CACHE_FIX_JAXLIB = (0, 5, 0)


def _jaxlib_version() -> tuple:
    import jaxlib

    parts = []
    for p in jaxlib.__version__.split("."):
        digits = "".join(ch for ch in p if ch.isdigit())
        if not digits:
            break
        parts.append(int(digits))
    return tuple(parts)


def donation_safe() -> bool:
    """Whether ``donate_argnums`` buffer donation is safe in THIS
    process — a *version gate* around the jaxlib
    donation-vs-deserialization corruption
    (:data:`DONATION_CACHE_FIX_JAXLIB`).

    On the pinned 0.4.x jaxlib, donation and the persistent compile
    cache are mutually exclusive at process granularity: once a
    process has deserialized ANY executable from the cache, running a
    donated executable — even one compiled fresh in-process — flakily
    segfaults or silently corrupts the aliased state (reproduced:
    cache-free processes are bit-correct across every run; warm-cache
    processes return garbage counters or abort in malloc; docs/PERF.md
    carries the repro notes, re-confirmed while building the AOT
    path). Silent corruption is disqualifying, so donation
    auto-engages exactly when the persistent cache is off for this
    process. On jaxlib >= the fix version the exclusion retires itself
    and donation engages unconditionally.
    ``FANTOCH_SWEEP_DONATE=0/1`` forces it either way (the repro
    knob); serialized AOT executables are gated separately and harder
    — :func:`aot_donation_safe` ignores the env override because a
    donated deserialized executable is *known* to corrupt."""
    import os

    env = os.environ.get("FANTOCH_SWEEP_DONATE")
    if env is not None:
        return env != "0"
    if _jaxlib_version() >= DONATION_CACHE_FIX_JAXLIB:
        return True
    return not (
        jax.config.jax_enable_compilation_cache
        and jax.config.jax_compilation_cache_dir
    )


def aot_donation_safe() -> bool:
    """Whether an executable that round-trips through
    ``jax.experimental.serialize_executable`` (parallel/aot.py) may
    donate its input state. On the pinned jaxlib the answer is a hard
    no — deserialization drops the donation aliasing and the loaded
    executable reads freed buffers (measured: garbage counters on the
    very first donated call, cache-free process included), so
    ``run_sweep(aot=...)`` compiles and serializes *undonated*
    runners, whatever ``FANTOCH_SWEEP_DONATE`` says — this is a
    known-corruption gate, not a preference. Retires itself at
    :data:`DONATION_CACHE_FIX_JAXLIB` like :func:`donation_safe`."""
    return _jaxlib_version() >= DONATION_CACHE_FIX_JAXLIB


def host_fetch(value, *, tier, reason):
    """The audited device→host *fetch* choke point: a blocking
    ``jax.device_get`` that carries its GL301 ledger entry in the call
    itself. Every host-side fetch of device values must flow through
    here (or :func:`host_sync`) the way every traced emission flows
    through :func:`emit`/:func:`pack_outbox` — the static sync ledger
    (fantoch_tpu/lint/transfer.py, docs/LINT.md GL301) reads the
    ``tier``/``reason`` keywords off the call site, checks the declared
    tier against the site's loop-nesting depth, and gates the whole
    ledger against ``lint/transfer_baseline.json``, so a new sync (or
    one migrating into a hotter loop) fails lint by name instead of
    silently re-paying the ~1 s/round-trip dispatch tax (docs/PERF.md).

    ``tier`` must be a string literal — one of ``"sweep"`` /
    ``"checkpoint"`` / ``"window"`` / ``"segment"``, coldest to
    hottest — and ``reason`` a short literal justification ("window
    liveness fetch", "checkpoint drain", ...). Both are metadata for
    the AST pass; at runtime this is exactly ``jax.device_get``."""
    del tier, reason  # ledger metadata, read statically by GL301
    return jax.device_get(value)


def host_sync(value, *, tier, reason):
    """The audited device→host *barrier* choke point: blocks until
    ``value``'s computation finishes without copying it home, then
    returns ``value`` itself (still on device). Same GL301 ledger
    contract as :func:`host_fetch`; use this when the host needs a
    completion guarantee (timing fences, watchdog probes) but not the
    bytes."""
    del tier, reason  # ledger metadata, read statically by GL301
    jax.block_until_ready(value)
    return value


def segment_lane_fn(
    protocol, dims: EngineDims, max_steps: int = 1 << 22,
    reorder: bool = False, faults: FaultFlags = NO_FAULTS,
    monitor_keys: int = 0, narrow: tuple = (),
):
    """The per-lane bounded-segment function the batched runners share:
    ``run_lane(st, ctx, until) -> (state, running)`` advances ONE lane
    by at most ``until - steps`` steps. :func:`build_segment_runner`
    vmaps it under plain ``jax.jit`` (single-device / NamedSharding),
    and ``parallel/partition.py`` vmaps the identical function per
    shard inside a ``shard_map`` over a named device mesh — both paths
    therefore trace the exact same per-lane step, which is what keeps
    the checkpoint signature (engine/checkpoint.py hashes this very
    trace) and the GL005 gating pin stable across execution layouts."""
    _check_monitorable(protocol, monitor_keys)

    def run_lane(st, ctx, until):
        lim = jnp.minimum(until, max_steps)

        def body(s):
            wide = cast_state_planes(s, narrow, store=False)
            out = _lane_step(
                protocol, dims, wide, ctx, reorder, faults, monitor_keys
            )
            return cast_state_planes(out, narrow, store=True)

        # the loop condition reads only per-lane scalars (done_time,
        # now, err, steps) — never a narrowed plane
        out = jax.lax.while_loop(
            lambda s: _lane_running(dims, s, ctx, max_steps, faults)
            & (s["steps"] < lim),
            body,
            st,
        )
        running = _lane_running(dims, out, ctx, max_steps, faults)
        if monitor_keys:
            # idempotent per segment: a finished lane's state is frozen,
            # so re-running the end-of-lane reduction only re-derives
            # the same bits; running lanes keep their in-run bits
            wide = cast_state_planes(out, narrow, store=False)
            wide = monitor.finalize_lane(
                protocol, dims, wide, ctx, faults, running=running
            )
            out = cast_state_planes(wide, narrow, store=True)
        return out, running

    return run_lane


def build_segment_runner(
    protocol, dims: EngineDims, max_steps: int = 1 << 22,
    reorder: bool = False, faults: FaultFlags = NO_FAULTS,
    monitor_keys: int = 0, narrow: tuple = (), donate: bool = False,
):
    """Like :func:`build_runner` but each device call advances every
    still-running lane by at most ``until - steps`` steps and returns,
    so one sweep becomes several bounded executions with host-side
    resume — long sweeps stay under transport/watchdog execution-time
    limits (a single multi-minute while_loop call can kill a tunneled
    device worker). Returns ``(runner, alive)`` where
    ``runner(state, ctx, until) -> (state, any_alive)`` (the liveness
    flag rides back with the state — a separate call would pay the
    tunnel's per-call overhead every segment) and ``alive(state, ctx)``
    serves callers resuming saved states; drive ``until`` up in fixed
    increments until the flag is false, then apply truncation via
    ``finish_segmented``.

    A finished batch is a fixed point: every lane's running predicate
    is already false, so the while loop never runs and the state comes
    back bit-identical. The pipelined sweep driver
    (parallel/pipeline.py) leans on this — segments dispatched
    speculatively past the batch's end are byte-exact no-ops.

    ``narrow`` (engine/spec.py :func:`~fantoch_tpu.engine.spec
    .narrow_spec`) selects state planes stored as i16/i8 in the
    while-loop carry; the body widens them to i32 before the step and
    re-narrows its output, so handler arithmetic is untouched and only
    the bytes the carry moves through HBM shrink. The runner's
    input/output state uses the same storage dtypes (host init must
    pre-narrow via :func:`cast_state_planes`).

    ``donate=True`` donates the input state to each call
    (``donate_argnums``, the pjit donation pattern): a segment updates
    the lane state in place instead of allocating a second full copy
    per call and round-tripping it through HBM. Callers must treat the
    state they pass in as consumed — ``run_sweep`` rebinds the output
    every segment and takes an explicit undonated host copy
    (:func:`host_fetch`) before a checkpoint save, the only boundary
    where the pre-segment state is still needed. Do NOT donate in a process that uses the
    persistent compile cache: gate on :func:`donation_safe` (the sweep
    driver does) — the current jaxlib corrupts donated state in
    warm-cache processes."""

    run_lane = segment_lane_fn(
        protocol, dims, max_steps, reorder, faults, monitor_keys,
        narrow=narrow,
    )

    def run_batch(st, ctx, until):
        out, alive = jax.vmap(run_lane, in_axes=(0, 0, None))(
            st, ctx, until
        )
        # the alive flag rides back with the state: a separate jitted
        # alive() call would pay the tunnel's ~1s per-call overhead
        # once per segment
        return out, jnp.any(alive)

    runner = jax.jit(
        run_batch, donate_argnums=(0,) if donate else ()
    )
    alive = jax.jit(
        lambda st, ctx: jnp.any(
            jax.vmap(
                lambda s, c: _lane_running(dims, s, c, max_steps, faults)
            )(st, ctx)
        )
    )
    return runner, alive


def window_batch_fn(
    protocol, dims: EngineDims, max_steps: int = 1 << 22,
    reorder: bool = False, faults: FaultFlags = NO_FAULTS,
    monitor_keys: int = 0, narrow: tuple = (),
):
    """The un-jitted scan-fused window body both execution layouts
    share: ``run_window(st, ctx, untils) -> (state, any_alive)``
    advances the whole batch through ``len(untils)`` consecutive
    segments in ONE device call — a ``lax.scan`` whose body is exactly
    the batched segment step (the vmapped :func:`segment_lane_fn`, the
    same per-lane trace the checkpoint signature hashes and GL203
    proves), so the host pays its dispatch round-trip once per
    *window* instead of once per segment.

    Safety is the segment runner's fixed-point property: a finished
    batch re-running a segment is a byte-exact no-op, so the dead tail
    iterations of the window a batch finishes inside change nothing —
    scan-fused results are byte-identical to the serial segment loop
    (pinned in tests/test_scan_window.py). Liveness is *carried
    through the scan* and comes home once per window: the flag
    returned is the last segment's ``any(running)`` verdict, exactly
    the value the segment loop would have resolved there.

    ``jax.jit`` (:func:`build_window_runner`) serves the single-device
    / NamedSharding layout; ``parallel/partition.py`` runs the same
    scan per shard inside ``shard_map`` with one liveness ``psum``
    after the scan."""
    run_lane = segment_lane_fn(
        protocol, dims, max_steps, reorder, faults, monitor_keys,
        narrow=narrow,
    )

    def run_window(st, ctx, untils):
        def seg(carry, until):
            s, _alive = carry
            out, running = jax.vmap(run_lane, in_axes=(0, 0, None))(
                s, ctx, until
            )
            return (out, jnp.any(running)), ()

        # the initial alive flag is immediately overwritten by the
        # first segment (every window runs >= 1 segment)
        (out, alive), _ = jax.lax.scan(
            seg, (st, jnp.asarray(True)), untils
        )
        return out, alive

    return run_window


def build_window_runner(
    protocol, dims: EngineDims, max_steps: int = 1 << 22,
    reorder: bool = False, faults: FaultFlags = NO_FAULTS,
    monitor_keys: int = 0, narrow: tuple = (), donate: bool = False,
):
    """Like :func:`build_segment_runner` but one device call advances
    a whole checkpoint *window* of segments:
    ``runner(state, ctx, untils) -> (state, any_alive)`` where
    ``untils`` is the window's ``[W]`` i32 segment-boundary ladder
    (values past ``max_steps`` clamp inside the per-lane step, so the
    tail window just passes a clipped ladder). The window length is
    static (it is the scan's trip count — part of the compiled
    executable, like the batch shape); the boundary *values* are
    runtime arguments, so one compiled runner serves every window of a
    sweep. ``donate=True`` has exactly the segment runner's contract:
    the input state is consumed per call."""
    run_window = window_batch_fn(
        protocol, dims, max_steps, reorder, faults, monitor_keys,
        narrow=narrow,
    )
    runner = jax.jit(
        run_window, donate_argnums=(0,) if donate else ()
    )
    alive = jax.jit(
        lambda st, ctx: jnp.any(
            jax.vmap(
                lambda s, c: _lane_running(dims, s, c, max_steps, faults)
            )(st, ctx)
        )
    )
    return runner, alive


def finish_segmented(state, max_steps: int):
    """Apply the truncation error bit after a segmented run (host side,
    numpy arrays)."""
    truncated = (np.asarray(state["steps"]) >= max_steps) & (
        np.asarray(state["done_time"]) >= INF
    )
    state = dict(state)
    state["err"] = np.asarray(state["err"]) | ERR_TRUNCATED * truncated
    return state

"""Durable checkpoint/restore for batched engine runs.

The reference survives machine churn by re-running whole experiments
from its orchestrator (fantoch_exp); the device engine instead packs
thousands of lanes into one process, so a preemption, TPU-worker death
or budget timeout used to lose the entire campaign. This module makes
the stacked lane state durable: ``save_sweep_checkpoint`` serializes
the full batched state tree + lane ctx to a versioned host artifact
(npz payload + JSON manifest) and ``load_sweep_checkpoint`` restores it
**bit-exactly** — a run checkpointed at a segment boundary and resumed
produces byte-identical ``LaneResults`` to an uninterrupted run,
because the segmented runner's state advances deterministically and
``host_fetch``/``device_put`` round-trips preserve every bit. This
module never fetches: callers hand it host-side state taken through
the ``host_fetch`` choke point (engine/core.py) at a drained
boundary, so the GL301 sync ledger and the GL302 donation-lifetime
prover (docs/LINT.md) audit the fetch at the call site.

Staleness is *refused, never silently misloaded*: the manifest carries
a signature of the things bit-exact resume depends on — protocol
identity, ``EngineDims``, the jax version, the trace-time runner flags,
and a content hash of the step function's jaxpr — and a mismatch on any
component raises :class:`CheckpointMismatchError` naming it. A
truncated or tampered payload fails its recorded sha256 and raises
:class:`CheckpointCorruptError`. The loader additionally compares the
saved lane ctx against the freshly built one, so a checkpoint can never
be resumed onto different sweep specs.

Artifact layout (a directory)::

    <path>/manifest.json        # version, signature, meta, payload ref
    <path>/payload-<sha12>.npz  # every state + ctx leaf, flat-keyed

Writes are crash-safe: the payload is written and renamed into place
*before* the manifest referencing it, and both renames are atomic, so a
SIGKILL mid-save leaves either the previous consistent pair or the new
one — never a manifest pointing at a half-written payload.

What bit-exact resume does NOT guarantee: identity across jax versions
(the jaxpr — and therefore the compiled arithmetic — may change; the
signature refuses such checkpoints on purpose), across protocol or
dims edits, or across different ``segment_steps`` ladders (refused via
manifest meta, conservatively). See docs/CAMPAIGN.md.
"""

from __future__ import annotations

import hashlib
import io
import json
import os
from dataclasses import dataclass
from typing import Dict, Optional

import numpy as np

CHECKPOINT_KIND = "fantoch-tpu-checkpoint"
CHECKPOINT_VERSION = 1

_MANIFEST = "manifest.json"


class CheckpointError(RuntimeError):
    """Base class: a checkpoint could not be used. Never caught
    silently — callers surface the reason and refuse to resume."""


class CheckpointMismatchError(CheckpointError):
    """The checkpoint is *stale*: it was written under a different
    protocol / EngineDims / jax version / step jaxpr / lane grid than
    the run trying to resume from it. Resuming would not be bit-exact,
    so it is refused with the mismatched component(s) named."""


class CheckpointCorruptError(CheckpointError):
    """The artifact itself is damaged: unreadable manifest, missing
    payload, or a payload whose bytes fail the recorded sha256
    (truncation, tampering, torn write)."""


class SweepInterrupted(RuntimeError):
    """``run_sweep`` stopped at a segment boundary with its state saved
    (signal flush, wall-clock budget, or an explicit segment limit).
    The checkpoint at ``path`` resumes the run exactly where it
    stopped."""

    def __init__(self, path: str, until: int, reason: str):
        self.path = path
        self.until = int(until)
        self.reason = reason
        super().__init__(
            f"sweep interrupted ({reason}) at step {until}; checkpoint "
            f"saved at {path}"
        )


@dataclass
class CheckpointSpec:
    """How ``run_sweep`` should checkpoint.

    path
        artifact directory (created on first save).
    every
        checkpoint *windows* between saves (1 = every boundary). A
        window is one host round-trip of the sweep loop — ``run_sweep
        (scan_window=W)`` fuses W segments into it, so cadence counts
        device calls, not raw segments (docs/CAMPAIGN.md). Each save
        fetches the full batched state to host (~100 MB per 512
        lanes), so raise this when the window cost dwarfs the work
        between boundaries — docs/PERF.md "checkpoint cadence".
    resume
        load an existing valid checkpoint at ``path`` before running
        (a stale/corrupt one is refused loudly, never ignored).
    keep
        keep the artifact after a successful completion (default:
        removed — the results are the durable output at that point).
    budget_s
        wall-clock budget measured from the ``run_sweep`` call; once
        exceeded the run saves and raises :class:`SweepInterrupted` at
        the next window boundary.
    stop_after_segments
        stop (save + raise) after this many completed checkpoint
        windows (the name predates scan fusion; with ``scan_window=1``
        a window IS one segment) — the deterministic interruption hook
        the tests and the CI smoke job's corrupted-manifest self-check
        drive.
    """

    path: str
    every: int = 1
    resume: bool = True
    keep: bool = False
    budget_s: Optional[float] = None
    stop_after_segments: Optional[int] = None


# ----------------------------------------------------------------------
# signatures: what bit-exact resume depends on
# ----------------------------------------------------------------------

# one trace per (protocol, dims, flags, structure) per process — the
# same memoization shape as parallel/sweep.py's _LANE_PROOFS
_SIGNATURES: dict = {}


def _tree_sig(tree) -> tuple:
    import jax

    leaves = jax.tree_util.tree_flatten_with_path(tree)[0]
    return tuple(
        (
            str(path),
            tuple(np.shape(leaf)),
            str(getattr(leaf, "dtype", type(leaf).__name__)),
        )
        for path, leaf in leaves
    )


def protocol_ident(protocol) -> str:
    """Stable identity string for a device protocol: class path plus
    the shape-bound attributes that parameterize its traced step
    (device protocols have value identity — protocols/identity.py)."""
    cls = protocol if isinstance(protocol, type) else type(protocol)
    ident = f"{cls.__module__}.{cls.__qualname__}"
    if not isinstance(protocol, type):
        ident += repr(sorted(vars(protocol).items()))
    return ident


def step_signature(protocol, dims, *, reorder: bool, faults,
                   monitor_keys: int, state, ctx) -> Dict[str, str]:
    """The signature dict stored in (and checked against) a manifest.

    ``state``/``ctx`` are one *unbatched* lane's arrays — the jaxpr of
    the step traced over them is hashed, so any edit to the step
    function, a protocol handler, or the trace-time flags changes the
    signature and stale checkpoints are refused by name.
    """
    import jax

    key = (
        protocol, dims, bool(reorder), faults, int(monitor_keys),
        _tree_sig(state), _tree_sig(ctx),
    )
    if key not in _SIGNATURES:
        from .core import _lane_step

        jaxpr = jax.make_jaxpr(
            lambda lane_state, lane_ctx: _lane_step(
                protocol, dims, lane_state, lane_ctx, reorder, faults,
                monitor_keys,
            )
        )(state, ctx)
        _SIGNATURES[key] = {
            "kind": CHECKPOINT_KIND,
            "protocol": protocol_ident(protocol),
            "dims": repr(dims),
            "jax": jax.__version__,
            "reorder": repr(bool(reorder)),
            "faults": repr(faults),
            "monitor_keys": repr(int(monitor_keys)),
            "step_jaxpr_sha256": hashlib.sha256(
                str(jaxpr).encode()
            ).hexdigest(),
        }
    return dict(_SIGNATURES[key])


# ----------------------------------------------------------------------
# pytree <-> flat npz keys
# ----------------------------------------------------------------------


def _flatten_tree(tree, prefix: str) -> Dict[str, np.ndarray]:
    """Nested dict pytree -> flat ``prefix/key/.../leaf`` arrays."""
    import jax

    out: Dict[str, np.ndarray] = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        parts = [str(getattr(p, "key", p)) for p in path]
        out["/".join([prefix] + parts)] = np.asarray(leaf)
    return out


def _unflatten_tree(flat: Dict[str, np.ndarray], prefix: str) -> dict:
    """Inverse of :func:`_flatten_tree` for dict-of-dicts pytrees."""
    root: dict = {}
    want = prefix + "/"
    for key in sorted(flat):
        if not key.startswith(want):
            continue
        parts = key[len(want):].split("/")
        node = root
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = flat[key]
    return root


# ----------------------------------------------------------------------
# raw artifact I/O
# ----------------------------------------------------------------------


def checkpoint_exists(path: str) -> bool:
    return os.path.exists(os.path.join(path, _MANIFEST))


def atomic_write(path: str, data: "bytes | str") -> None:
    """Crash-safe file write: temp file in the same directory, flush +
    fsync, then atomic rename. The one implementation every durable
    artifact in the repo shares (checkpoint payload/manifest, campaign
    journal side-files, fuzz repro artifacts) so a crash-safety fix
    lands everywhere at once. The temp name is pid-unique: concurrent
    fleet workers first-touching one campaign dir (fleet/worker.py)
    race this write with IDENTICAL bytes, and a shared temp name would
    let one writer rename the other's half-written file into place (or
    crash on the vanished temp)."""
    tmp = f"{path}.{os.getpid()}.tmp"
    mode = "wb" if isinstance(data, bytes) else "w"
    with open(tmp, mode) as fh:
        fh.write(data)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)


def canonical_json(obj, *, indent=None) -> str:
    """The one JSON serialization every durable artifact shares:
    ``sort_keys=True`` always, float formatting through the stdlib's
    single ``repr`` path, no localized separators. Byte-identity pins
    (fleet ``--merge`` ≡ 1-worker control, campaign resume ≡ control,
    AOT manifest drift refusal) compare these bytes across machines,
    so key order must never depend on dict insertion history — the
    GL403 lint audit statically requires every artifact writer to come
    through here (or spell ``sort_keys=True`` literally at the call
    site)."""
    return json.dumps(obj, indent=indent, sort_keys=True)


def save_artifact(path: str, arrays: Dict[str, np.ndarray],
                  signature: Dict[str, str], meta: dict) -> None:
    """Atomic write: payload first (renamed into place under a name
    derived from its own hash), then the manifest referencing it, so a
    kill at any instant leaves a loadable artifact."""
    os.makedirs(path, exist_ok=True)
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    payload = buf.getvalue()
    digest = hashlib.sha256(payload).hexdigest()
    pname = f"payload-{digest[:12]}.npz"
    atomic_write(os.path.join(path, pname), payload)
    manifest = {
        "kind": CHECKPOINT_KIND,
        "version": CHECKPOINT_VERSION,
        "signature": signature,
        "payload": pname,
        "payload_sha256": digest,
        "meta": meta,
    }
    atomic_write(
        os.path.join(path, _MANIFEST),
        canonical_json(manifest, indent=2),
    )
    # previous payloads are unreferenced once the manifest lands
    for fn in os.listdir(path):
        if fn.startswith("payload-") and fn != pname:
            try:
                os.remove(os.path.join(path, fn))
            except OSError:
                pass


def load_artifact(path: str,
                  expected_signature: "Dict[str, str] | None" = None,
                  ) -> "tuple[Dict[str, np.ndarray], dict]":
    """Read + verify an artifact. Raises the named refusal errors; a
    valid artifact returns ``(flat arrays, manifest)``."""
    mpath = os.path.join(path, _MANIFEST)
    if not os.path.exists(mpath):
        raise CheckpointCorruptError(f"no checkpoint manifest at {path}")
    try:
        with open(mpath) as fh:
            manifest = json.load(fh)
    except (OSError, ValueError) as e:
        raise CheckpointCorruptError(
            f"checkpoint manifest unreadable at {mpath}: {e}"
        ) from e
    if manifest.get("kind") != CHECKPOINT_KIND:
        raise CheckpointMismatchError(
            f"not a {CHECKPOINT_KIND} artifact: kind="
            f"{manifest.get('kind')!r}"
        )
    if manifest.get("version") != CHECKPOINT_VERSION:
        raise CheckpointMismatchError(
            f"checkpoint version {manifest.get('version')!r} != "
            f"supported {CHECKPOINT_VERSION}"
        )
    if expected_signature is not None:
        saved = manifest.get("signature") or {}
        bad = sorted(
            k for k in expected_signature
            if saved.get(k) != expected_signature[k]
        )
        if bad:
            detail = "; ".join(
                f"{k}: saved {str(saved.get(k))[:80]!r} != current "
                f"{str(expected_signature[k])[:80]!r}"
                for k in bad
            )
            raise CheckpointMismatchError(
                f"stale checkpoint refused ({', '.join(bad)} changed "
                f"since it was written): {detail}"
            )
    pname = manifest.get("payload")
    ppath = os.path.join(path, str(pname))
    if not pname or not os.path.exists(ppath):
        raise CheckpointCorruptError(
            f"checkpoint payload {pname!r} missing from {path}"
        )
    with open(ppath, "rb") as fh:
        payload = fh.read()
    digest = hashlib.sha256(payload).hexdigest()
    if digest != manifest.get("payload_sha256"):
        raise CheckpointCorruptError(
            f"checkpoint payload {pname} truncated or corrupted: "
            f"sha256 {digest[:12]}... != recorded "
            f"{str(manifest.get('payload_sha256'))[:12]}..."
        )
    try:
        with np.load(io.BytesIO(payload), allow_pickle=False) as npz:
            arrays = {k: npz[k] for k in npz.files}
    except Exception as e:  # zipfile/format errors vary by numpy
        raise CheckpointCorruptError(
            f"checkpoint payload {pname} unreadable: {e}"
        ) from e
    return arrays, manifest


def discard_checkpoint(path: str) -> None:
    """Remove an artifact this module wrote (manifest + payloads +
    leftover temp files; the directory itself if then empty)."""
    if not os.path.isdir(path):
        return
    for fn in os.listdir(path):
        if fn == _MANIFEST or fn.startswith("payload-") or (
            fn.startswith(_MANIFEST) and fn.endswith(".tmp")
        ):
            try:
                os.remove(os.path.join(path, fn))
            except OSError:
                pass
    try:
        os.rmdir(path)
    except OSError:
        pass


# ----------------------------------------------------------------------
# sweep-level wrappers (the shapes run_sweep and bench.py use)
# ----------------------------------------------------------------------


def save_sweep_checkpoint(path: str, *, state, ctx,
                          signature: Dict[str, str], until: int,
                          meta: dict) -> None:
    """Serialize one batched sweep's full state + ctx. ``state`` must
    already be host-side — an undonated copy taken through
    ``host_fetch`` (engine/core.py) at a drained boundary; GL302
    statically refuses saves of device-fresh bindings."""
    arrays = {**_flatten_tree(state, "state"), **_flatten_tree(ctx, "ctx")}
    save_artifact(path, arrays, signature, dict(meta, until=int(until)))


def load_sweep_checkpoint(path: str, *, signature: Dict[str, str],
                          ctx, meta_expect: "dict | None" = None,
                          ) -> "tuple[dict, dict]":
    """Restore a sweep checkpoint: verify signature, meta, payload
    integrity AND that the saved lane ctx is bit-identical to the
    freshly built one (``ctx``) — a checkpoint never resumes onto
    different sweep specs. Returns ``(state tree, manifest meta)``."""
    arrays, manifest = load_artifact(path, signature)
    meta = manifest.get("meta") or {}
    for k, v in (meta_expect or {}).items():
        if meta.get(k) != v:
            raise CheckpointMismatchError(
                f"checkpoint {k}={meta.get(k)!r} does not match the "
                f"current run's {k}={v!r}"
            )
    fresh = _flatten_tree(ctx, "ctx")
    saved_flat = {k: v for k, v in arrays.items() if k.startswith("ctx/")}
    if sorted(saved_flat) != sorted(fresh):
        raise CheckpointMismatchError(
            "checkpoint lane ctx has different fields than the current "
            f"specs (saved {len(saved_flat)} vs current {len(fresh)})"
        )
    for k, cur in fresh.items():
        sav = saved_flat[k]
        if sav.dtype != cur.dtype or sav.shape != cur.shape or not (
            np.array_equal(sav, cur)
        ):
            raise CheckpointMismatchError(
                f"checkpoint lane ctx differs from the current specs at "
                f"{k!r} — resuming onto different lanes is refused"
            )
    return _unflatten_tree(arrays, "state"), meta

"""Convenience driver: build, run, and collect a batch of lanes on the
default device. The mesh-sharded sweep driver (pjit over a config batch
across chips) lives in ``fantoch_tpu.parallel``."""

from __future__ import annotations

from typing import List, Sequence

import jax
import numpy as np

from .core import build_runner, init_lane_state
from .dims import EngineDims
from .faults import batch_fault_flags
from .results import LaneResults, collect_results
from .spec import LaneSpec, stack_lanes


def stack_states(protocol, dims: EngineDims, specs: Sequence[LaneSpec],
                 monitor_keys: int = 0):
    states = [
        init_lane_state(protocol, dims, s.ctx, monitor_keys=monitor_keys)
        for s in specs
    ]
    return jax.tree_util.tree_map(lambda *xs: np.stack(xs), *states)


def batch_reorder_flag(specs: Sequence[LaneSpec]) -> bool:
    """A batch compiles one step function, so every lane must agree on
    the reorder perturbation (a trace-time flag)."""
    flags = {bool(s.ctx["reorder"]) for s in specs}
    assert len(flags) == 1, "cannot mix reorder and FIFO lanes in a batch"
    return flags.pop()


def run_lanes(
    protocol,
    dims: EngineDims,
    specs: Sequence[LaneSpec],
    max_steps: int = 1 << 22,
    monitor_keys: int = 0,
) -> List[LaneResults]:
    ctx = stack_lanes(specs)
    state = stack_states(protocol, dims, specs, monitor_keys)
    runner = build_runner(
        protocol, dims, max_steps,
        reorder=batch_reorder_flag(specs),
        # fault-capability union: fault-free and faulty lanes share one
        # compiled runner (fault-free lanes' ctx arrays are inert)
        faults=batch_fault_flags(specs),
        # > 0 compiles the safety monitors in (engine/monitor.py)
        monitor_keys=monitor_keys,
    )
    final = runner(state, ctx)
    return collect_results(protocol, dims, final, specs)

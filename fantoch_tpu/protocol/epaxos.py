"""EPaxos protocol (SOSP'13): dependency-based consensus that always
tolerates a minority of faults.

Capability parity with ``fantoch_ps/src/protocol/epaxos.rs``: quorums are
f-independent with f = ⌊n/2⌋ (config.rs:284-292); the coordinator
computes deps at submit and broadcasts ``MCollect`` (epaxos.rs:199-220);
fast-quorum members other than the coordinator merge the coordinator's
deps as "past" and ack (222-295); the fast path is taken iff *all*
reported dependency sets are equal (297-364, quorum.rs:67-98); the slow
path is single-decree Paxos on the deps; commits feed the graph executor
and the committed-clock GC flow. No partial-replication support (the
reference's EPaxos is single-shard: epaxos.rs:660-695 has no shard
messages).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Set, Tuple

from ..core.command import Command
from ..core.config import Config
from ..core.ids import Dot, ProcessId, ShardId
from ..core.timing import SysTime
from ..executor.graph import GraphAdd, GraphExecutor
from .atlas import (
    COLLECT,
    COMMIT,
    GARBAGE_COLLECTION,
    PAYLOAD,
    START,
    ConsensusValue,
    MCollect,
    MCollectAck,
    MCommit,
    MCommitDot,
    MConsensus,
    MConsensusAck,
    MGarbageCollection,
    MStable,
    _proposal_gen,
)
from .base import (
    BaseProcess,
    CommandsInfo,
    GCTrack,
    Protocol,
    ProtocolMetrics,
    ToForward,
    ToSend,
)
from .graph_deps import QuorumDeps, SequentialKeyDeps
from .synod import S_ACCEPT, S_ACCEPTED, S_CHOSEN, Synod


class _EPaxosInfo:
    """Per-command record (epaxos.rs:622-668). ``QuorumDeps`` is sized
    ``fast_quorum_size - 1`` because the coordinator, being a quorum
    member, does not ack itself (epaxos.rs:645-656)."""

    def __init__(self, process_id: ProcessId, n: int, f: int,
                 fast_quorum_size: int):
        self.status = START
        self.quorum: Set[ProcessId] = set()
        self.synod: Synod[ConsensusValue] = Synod(
            process_id, n, f, _proposal_gen, ConsensusValue()
        )
        self.cmd: Optional[Command] = None
        self.quorum_deps = QuorumDeps(fast_quorum_size - 1)


class EPaxos(Protocol):
    EXECUTOR = GraphExecutor

    def __init__(self, process_id: ProcessId, shard_id: ShardId, config: Config):
        super().__init__(process_id, shard_id, config)
        fast_quorum_size, write_quorum_size = config.epaxos_quorum_sizes()
        self.bp = BaseProcess(
            process_id, shard_id, config, fast_quorum_size, write_quorum_size
        )
        self.key_deps = SequentialKeyDeps(shard_id)
        # NOTE: like the reference, the synod is built with the *model*
        # f (config.f), while quorum sizes use the minority-based
        # formulas (epaxos.rs:45-70 via the Info trait)
        self.cmds: CommandsInfo[_EPaxosInfo] = CommandsInfo(
            lambda: _EPaxosInfo(process_id, config.n, config.f,
                                fast_quorum_size)
        )
        self.gc_track = GCTrack(process_id, shard_id, config.n)
        self.buffered_commits: Dict[Dot, Tuple[ProcessId, ConsensusValue]] = {}

    # -- Protocol interface -------------------------------------------

    def periodic_events(self):
        if self.bp.config.gc_interval_ms is not None:
            return [(GARBAGE_COLLECTION, self.bp.config.gc_interval_ms)]
        return []

    def id(self) -> ProcessId:
        return self.bp.process_id

    def shard_id(self) -> ShardId:
        return self.bp.shard_id

    def discover(self, processes):
        ok = self.bp.discover(processes)
        return ok, self.bp.closest_shard_process()

    def submit(self, dot: Optional[Dot], cmd: Command, time: SysTime) -> None:
        dot = dot if dot is not None else self.bp.next_dot()
        deps = self.key_deps.add_cmd(dot, cmd, None)
        self.to_processes_buf.append(
            ToSend(
                target=self.bp.all(),
                msg=MCollect(dot, cmd, deps, self.bp.fast_quorum()),
            )
        )

    def handle(self, from_, from_shard_id, msg, time) -> None:
        if isinstance(msg, MCollect):
            self._handle_mcollect(from_, msg, time)
        elif isinstance(msg, MCollectAck):
            self._handle_mcollectack(from_, msg)
        elif isinstance(msg, MCommit):
            self._handle_mcommit(from_, msg.dot, msg.value)
        elif isinstance(msg, MConsensus):
            self._handle_mconsensus(from_, msg)
        elif isinstance(msg, MConsensusAck):
            self._handle_mconsensusack(from_, msg)
        elif isinstance(msg, MCommitDot):
            assert from_ == self.id()
            self.gc_track.add_to_clock(msg.dot)
        elif isinstance(msg, MGarbageCollection):
            self.gc_track.update_clock_of(from_, msg.committed)
            stable = self.gc_track.stable()
            if stable:
                self.to_processes_buf.append(ToForward(MStable(stable)))
        elif isinstance(msg, MStable):
            assert from_ == self.id()
            self.bp.stable(self.cmds.gc(msg.stable))
        else:
            raise TypeError(f"unexpected message {msg!r}")

    def handle_event(self, event, time) -> None:
        assert event == GARBAGE_COLLECTION
        self.to_processes_buf.append(
            ToSend(
                target=self.bp.all_but_me(),
                msg=MGarbageCollection(self.gc_track.clock_frontier()),
            )
        )

    @staticmethod
    def parallel() -> bool:
        # EPaxosLocked equivalent under cooperative workers (see Atlas)
        return True

    @staticmethod
    def leaderless() -> bool:
        return True

    def metrics(self) -> ProtocolMetrics:
        return self.bp.metrics

    # -- handlers (epaxos.rs:221-482) ----------------------------------

    def _handle_mcollect(self, from_, msg: MCollect, time) -> None:
        dot = msg.dot
        info = self.cmds.get(dot)
        if info.status != START:
            return
        if self.id() not in msg.quorum:
            info.status = PAYLOAD
            info.cmd = msg.cmd
            buffered = self.buffered_commits.pop(dot, None)
            if buffered is not None:
                self._handle_mcommit(buffered[0], dot, buffered[1])
            return
        message_from_self = from_ == self.id()
        if message_from_self:
            deps = msg.deps
        else:
            deps = self.key_deps.add_cmd(dot, msg.cmd, msg.deps)
        info.status = COLLECT
        info.quorum = set(msg.quorum)
        info.cmd = msg.cmd
        was_set = info.synod.set_if_not_accepted(
            lambda: ConsensusValue(deps=set(deps))
        )
        assert was_set
        # the coordinator does not ack itself (epaxos.rs:285-295)
        if not message_from_self:
            self.to_processes_buf.append(
                ToSend(target={from_}, msg=MCollectAck(dot, deps))
            )

    def _handle_mcollectack(self, from_, msg: MCollectAck) -> None:
        assert from_ != self.id()
        info = self.cmds.get(msg.dot)
        if info.status != COLLECT:
            return
        info.quorum_deps.add(from_, msg.deps)
        if not info.quorum_deps.all():
            return
        # fast path iff all reported deps are equal (epaxos.rs:329-364)
        final_deps, all_equal = info.quorum_deps.check_union()
        value = ConsensusValue(deps=final_deps)
        if all_equal:
            self.bp.fast_path()
            self.to_processes_buf.append(
                ToSend(target=self.bp.all(), msg=MCommit(msg.dot, value))
            )
        else:
            self.bp.slow_path()
            ballot = info.synod.skip_prepare()
            self.to_processes_buf.append(
                ToSend(
                    target=self.bp.write_quorum(),
                    msg=MConsensus(msg.dot, ballot, value),
                )
            )

    def _handle_mcommit(self, from_, dot: Dot, value: ConsensusValue) -> None:
        info = self.cmds.get(dot)
        if info.status == START:
            self.buffered_commits[dot] = (from_, value)
            return
        if info.status == COMMIT:
            return
        assert not value.is_noop, "noop handling not implemented yet"
        cmd = info.cmd
        assert cmd is not None
        self.to_executors_buf.append(GraphAdd(dot, cmd, set(value.deps)))
        info.status = COMMIT
        chosen_out = info.synod.handle(from_, (S_CHOSEN, value))
        assert chosen_out is None
        if self._gc_running():
            self.to_processes_buf.append(ToForward(MCommitDot(dot)))
        else:
            self.cmds.gc_single(dot)

    def _handle_mconsensus(self, from_, msg: MConsensus) -> None:
        info = self.cmds.get(msg.dot)
        out = info.synod.handle(from_, (S_ACCEPT, msg.ballot, msg.value))
        if out is None:
            return
        kind = out[0]
        if kind == S_ACCEPTED:
            reply = MConsensusAck(msg.dot, out[1])
        elif kind == S_CHOSEN:
            reply = MCommit(msg.dot, out[1])
        else:
            raise AssertionError(f"unexpected synod output {out!r}")
        self.to_processes_buf.append(ToSend(target={from_}, msg=reply))

    def _handle_mconsensusack(self, from_, msg: MConsensusAck) -> None:
        info = self.cmds.get(msg.dot)
        out = info.synod.handle(from_, (S_ACCEPTED, msg.ballot))
        if out is None:
            return
        assert out[0] == S_CHOSEN
        self.to_processes_buf.append(
            ToSend(target=self.bp.all(), msg=MCommit(msg.dot, out[1]))
        )

    def _gc_running(self) -> bool:
        return self.bp.config.gc_interval_ms is not None

"""Caesar protocol (DSN'17): timestamp + dependency consensus with a
wait condition.

Capability parity with ``fantoch_ps/src/protocol/caesar.rs``: the
coordinator proposes a logical clock for the command (caesar.rs:245-264)
and every process computes the command's predecessors (lower-clock
conflicts) and blockers (higher-clock conflicts, caesar.rs:266-510);
when blocked, the *wait condition* holds the reply until the blockers
reach safe clocks — accepting if this command appears in their deps,
rejecting otherwise (932-1096); the fast path commits when every
fast-quorum member replied ok (⌊3n/4⌋+1, config.rs:295-300), while any
rejection after a majority triggers an ``MRetry`` round through the
write quorum (560-822). Execution goes through the two-phase
predecessors executor, whose executed notifications drive the
all-processes-executed GC (824-891).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Set, Tuple

from ..core.command import Command
from ..core.config import Config
from ..core.ids import Dot, ProcessId, ShardId
from ..core.timing import SysTime
from ..executor.pred import PredecessorsExecutionInfo, PredecessorsExecutor
from .base import (
    BaseProcess,
    CommandsInfo,
    Message,
    Protocol,
    ProtocolMetrics,
    ProtocolMetricsKind,
    ToForward,
    ToSend,
)
from .pred import CaesarDeps, Clock, KeyClocks, QuorumClocks, QuorumRetries

# statuses (caesar.rs Status)
START, PROPOSE_BEGIN, PROPOSE_END, REJECT, ACCEPT, COMMIT = range(6)


# messages (caesar.rs:1232-1271)
@dataclass
class MPropose(Message):
    dot: Dot
    cmd: Command
    clock: Clock


@dataclass
class MProposeAck(Message):
    dot: Dot
    clock: Clock
    deps: CaesarDeps
    ok: bool


@dataclass
class MCommit(Message):
    dot: Dot
    clock: Clock
    deps: CaesarDeps


@dataclass
class MRetry(Message):
    dot: Dot
    clock: Clock
    deps: CaesarDeps


@dataclass
class MRetryAck(Message):
    dot: Dot
    deps: CaesarDeps


@dataclass
class MGarbageCollection(Message):
    WORKER = "gc"

    executed: List[Dot]


@dataclass
class MGCDot(Message):
    dot: Dot


GARBAGE_COLLECTION = "garbage_collection"


class BasicGCTrack:
    """Dot is stable once seen executed at all n processes
    (fantoch/src/protocol/gc/basic.rs)."""

    def __init__(self, n: int):
        self.n = n
        self.dot_to_count: Dict[Dot, int] = {}

    def add(self, dot: Dot) -> bool:
        count = self.dot_to_count.get(dot, 0) + 1
        if count == self.n:
            self.dot_to_count.pop(dot, None)
            return True
        self.dot_to_count[dot] = count
        return False


class _CaesarInfo:
    """Per-command lifecycle record (caesar.rs:1178-1230)."""

    def __init__(self, process_id: ProcessId, fast_quorum_size: int,
                 write_quorum_size: int):
        self.status = START
        self.cmd: Optional[Command] = None
        self.clock = Clock.zero(process_id)
        self.deps: CaesarDeps = set()
        self.blocking: Set[Dot] = set()
        self.blocked_by: Set[Dot] = set()
        self.quorum_clocks = QuorumClocks(
            process_id, fast_quorum_size, write_quorum_size
        )
        self.quorum_retries = QuorumRetries(write_quorum_size)
        self.start_time_ms: Optional[int] = None
        self.wait_start_time_ms: Optional[int] = None


class Caesar(Protocol):
    EXECUTOR = PredecessorsExecutor

    def __init__(self, process_id: ProcessId, shard_id: ShardId, config: Config):
        super().__init__(process_id, shard_id, config)
        fast_quorum_size, write_quorum_size = config.caesar_quorum_sizes()
        self.bp = BaseProcess(
            process_id, shard_id, config, fast_quorum_size, write_quorum_size
        )
        self.key_clocks = KeyClocks(process_id, shard_id)
        self.cmds: CommandsInfo[_CaesarInfo] = CommandsInfo(
            lambda: _CaesarInfo(process_id, fast_quorum_size,
                                write_quorum_size)
        )
        self.gc_track = BasicGCTrack(config.n)
        self.committed_dots = 0
        self.executed_dots = 0
        self.new_executed_dots: List[Dot] = []
        self.buffered_retries: Dict[Dot, Tuple[ProcessId, Clock, CaesarDeps]] = {}
        self.buffered_commits: Dict[Dot, Tuple[ProcessId, Clock, CaesarDeps]] = {}
        self.try_to_unblock_again: List[
            Tuple[Dot, Clock, CaesarDeps, Set[Dot]]
        ] = []
        self.wait_condition = config.caesar_wait_condition

    # -- Protocol interface -------------------------------------------

    def periodic_events(self):
        if self.bp.config.gc_interval_ms is not None:
            return [(GARBAGE_COLLECTION, self.bp.config.gc_interval_ms)]
        return []

    def id(self) -> ProcessId:
        return self.bp.process_id

    def shard_id(self) -> ShardId:
        return self.bp.shard_id

    def discover(self, processes):
        ok = self.bp.discover(processes)
        return ok, self.bp.closest_shard_process()

    def submit(self, dot: Optional[Dot], cmd: Command, time: SysTime) -> None:
        dot = dot if dot is not None else self.bp.next_dot()
        clock = self.key_clocks.clock_next()
        # sent to everyone: the fastest ok-replying fast quorum wins
        # (caesar.rs:252-257)
        self.to_processes_buf.append(
            ToSend(target=self.bp.all(), msg=MPropose(dot, cmd, clock))
        )

    def handle(self, from_, from_shard_id, msg, time) -> None:
        if isinstance(msg, MPropose):
            self._handle_mpropose(from_, msg, time)
        elif isinstance(msg, MProposeAck):
            self._handle_mproposeack(from_, msg)
        elif isinstance(msg, MCommit):
            self._handle_mcommit(from_, msg.dot, msg.clock, msg.deps, time)
        elif isinstance(msg, MRetry):
            self._handle_mretry(from_, msg.dot, msg.clock, msg.deps, time)
        elif isinstance(msg, MRetryAck):
            self._handle_mretryack(from_, msg)
        elif isinstance(msg, MGarbageCollection):
            for dot in msg.executed:
                self._gc_track_add(dot)
        elif isinstance(msg, MGCDot):
            self._gc_command(msg.dot)
            self.bp.stable(1)
        else:
            raise TypeError(f"unexpected message {msg!r}")
        # after every message, retry unblock attempts that found commands
        # still mid-propose (caesar.rs:177-183)
        again, self.try_to_unblock_again = self.try_to_unblock_again, []
        for dot, clock, deps, blocking in again:
            self._try_to_unblock(dot, clock, deps, blocking, time)

    def handle_event(self, event, time) -> None:
        assert event == GARBAGE_COLLECTION
        executed, self.new_executed_dots = self.new_executed_dots, []
        self.to_processes_buf.append(
            ToSend(
                target=self.bp.all_but_me(),
                msg=MGarbageCollection(executed),
            )
        )

    def handle_executed(self, committed_and_executed, time: SysTime) -> None:
        """Executor feedback: executed dots feed GC (caesar.rs:194-213)."""
        new_committed, new_executed = committed_and_executed
        for dot in new_executed:
            self._gc_track_add(dot)
        self.committed_dots += new_committed
        self.executed_dots += len(new_executed)
        self.new_executed_dots.extend(new_executed)

    @staticmethod
    def parallel() -> bool:
        # the reference's only Caesar variant is CaesarLocked
        # (LockedCommandsInfo); cooperative workers give the same
        # per-message atomicity with no locks
        return True

    @staticmethod
    def leaderless() -> bool:
        return True

    def metrics(self) -> ProtocolMetrics:
        return self.bp.metrics

    # -- handlers ------------------------------------------------------

    def _handle_mpropose(self, from_, msg: MPropose, time) -> None:
        dot, cmd, remote_clock = msg.dot, msg.cmd, msg.clock
        assert dot.source == from_
        self.key_clocks.clock_join(remote_clock)
        info = self.cmds.get(dot)
        if info.status != START:
            return
        info.start_time_ms = time.millis()

        blocked_by: Set[Dot] = set()
        deps = self.key_clocks.predecessors(dot, cmd, remote_clock, blocked_by)
        info.status = PROPOSE_BEGIN
        info.cmd = cmd
        info.deps = deps
        self._update_clock(dot, info, remote_clock)
        clock = info.clock
        info.blocked_by = set(blocked_by)

        # decide between ACCEPT / REJECT / WAIT (caesar.rs:326-494)
        ACCEPT_R, REJECT_R, WAIT_R = range(3)
        reply = WAIT_R
        blocked_by_to_ignore: Set[Dot] = set()
        if not blocked_by:
            reply = ACCEPT_R
        elif not self.wait_condition:
            reply = REJECT_R
        else:
            for blocked_by_dot in blocked_by:
                blocked_by_info = self.cmds.peek(blocked_by_dot)
                if blocked_by_info is not None:
                    has_safe_clock_and_deps = blocked_by_info.status in (
                        ACCEPT,
                        COMMIT,
                    )
                    if has_safe_clock_and_deps:
                        if self._safe_to_ignore(
                            dot, clock, blocked_by_info.clock,
                            blocked_by_info.deps,
                        ):
                            blocked_by_to_ignore.add(blocked_by_dot)
                        else:
                            reply = REJECT_R
                            break
                    else:
                        # blocked until the blocker reaches a safe state
                        blocked_by_info.blocking.add(dot)
                else:
                    # blocker already GC'd, thus executed everywhere
                    blocked_by_to_ignore.add(blocked_by_dot)
            if len(blocked_by_to_ignore) == len(blocked_by):
                assert reply == WAIT_R
                reply = ACCEPT_R

        info = self.cmds.peek(dot)
        assert info is not None and info.status == PROPOSE_BEGIN
        info.status = PROPOSE_END
        if reply == ACCEPT_R:
            self._accept_command(dot, info)
        elif reply == REJECT_R:
            self._reject_command(dot, info)
        else:
            info.blocked_by -= blocked_by_to_ignore
            assert info.blocked_by
            info.wait_start_time_ms = time.millis()

        buffered = self.buffered_retries.pop(dot, None)
        if buffered is not None:
            self._handle_mretry(buffered[0], dot, buffered[1], buffered[2],
                                time)
        buffered = self.buffered_commits.pop(dot, None)
        if buffered is not None:
            self._handle_mcommit(buffered[0], dot, buffered[1], buffered[2],
                                 time)

    def _handle_mproposeack(self, from_, msg: MProposeAck) -> None:
        info = self.cmds.get(msg.dot)
        # the coordinator can reject its own command (caesar.rs:536-547)
        if info.status not in (PROPOSE_END, REJECT):
            return
        assert not info.quorum_clocks.all(), (
            "already had all MProposeAck needed"
        )
        info.quorum_clocks.add(from_, msg.clock, msg.deps, msg.ok)
        if not info.quorum_clocks.all():
            return
        clock, deps, ok = info.quorum_clocks.aggregated()
        if ok:
            assert clock == info.clock
            self.bp.fast_path()
            self.to_processes_buf.append(
                ToSend(target=self.bp.all(), msg=MCommit(msg.dot, clock, deps))
            )
        else:
            self.bp.slow_path()
            # sent to everyone: the retry's safe clock may unblock waiting
            # commands anywhere (caesar.rs:593-596)
            self.to_processes_buf.append(
                ToSend(target=self.bp.all(), msg=MRetry(msg.dot, clock, deps))
            )

    def _handle_mcommit(self, from_, dot, clock, deps, time) -> None:
        self.key_clocks.clock_join(clock)
        info = self.cmds.get(dot)
        if info.status == START:
            self.buffered_commits[dot] = (from_, clock, set(deps))
            return
        if info.status == COMMIT:
            return
        if info.start_time_ms is not None:
            latency = time.millis() - info.start_time_ms
            info.start_time_ms = None
            self.bp.collect_metric(
                ProtocolMetricsKind.COMMIT_LATENCY, latency
            )
        self.bp.collect_metric(
            ProtocolMetricsKind.COMMITTED_DEPS_LEN, len(deps)
        )
        # a command may end up depending on itself; the executor assumes
        # otherwise (caesar.rs:665-668)
        deps = set(deps)
        deps.discard(dot)
        info.status = COMMIT
        info.deps = deps
        self._update_clock(dot, info, clock)
        assert info.cmd is not None
        self.to_executors_buf.append(
            PredecessorsExecutionInfo(dot, info.cmd, clock, set(deps))
        )
        blocking, info.blocking = info.blocking, set()
        self._try_to_unblock(dot, clock, info.deps, blocking, time)
        if not self._gc_running():
            self._gc_command(dot)

    def _handle_mretry(self, from_, dot, clock, deps, time) -> None:
        self.key_clocks.clock_join(clock)
        info = self.cmds.get(dot)
        if info.status == START:
            self.buffered_retries[dot] = (from_, clock, set(deps))
            return
        if info.status == COMMIT:
            return
        info.status = ACCEPT
        info.deps = set(deps)
        self._update_clock(dot, info, clock)
        assert info.cmd is not None
        new_deps = self.key_clocks.predecessors(dot, info.cmd, clock, None)
        new_deps |= deps
        self.to_processes_buf.append(
            ToSend(target={from_}, msg=MRetryAck(dot, new_deps))
        )
        blocking, info.blocking = info.blocking, set()
        self._try_to_unblock(dot, clock, info.deps, blocking, time)

    def _handle_mretryack(self, from_, msg: MRetryAck) -> None:
        info = self.cmds.get(msg.dot)
        # ignore stragglers once the MCommit went out (caesar.rs:785-798)
        if info.status != ACCEPT:
            return
        assert not info.quorum_retries.all(), (
            "already had all MRetryAck needed"
        )
        info.quorum_retries.add(from_, msg.deps)
        if info.quorum_retries.all():
            aggregated = info.quorum_retries.aggregated()
            self.to_processes_buf.append(
                ToSend(
                    target=self.bp.all(),
                    msg=MCommit(msg.dot, info.clock, aggregated),
                )
            )

    # -- wait condition (caesar.rs:932-1096) ---------------------------

    def _safe_to_ignore(
        self, my_dot: Dot, my_clock: Clock, their_clock: Clock,
        their_deps: CaesarDeps,
    ) -> bool:
        """A higher-clock blocker can be ignored only if we appear in its
        dependencies (clocks only increase, caesar.rs:932-956)."""
        assert my_clock < their_clock
        return my_dot in their_deps

    def _try_to_unblock(self, dot, clock, deps, blocking, time) -> None:
        at_propose_begin: Set[Dot] = set()
        for blocked_dot in blocking:
            blocked_info = self.cmds.peek(blocked_dot)
            if blocked_info is None:
                continue  # already GC'd
            if blocked_info.status == PROPOSE_BEGIN:
                at_propose_begin.add(blocked_dot)
            elif blocked_info.status == PROPOSE_END:
                end_of_wait = False
                if self._safe_to_ignore(
                    blocked_dot, blocked_info.clock, clock, deps
                ):
                    blocked_info.blocked_by.discard(dot)
                    if not blocked_info.blocked_by:
                        self._accept_command(blocked_dot, blocked_info)
                        end_of_wait = True
                else:
                    # reject ASAP (caesar.rs:1036-1050)
                    self._reject_command(blocked_dot, blocked_info)
                    end_of_wait = True
                if end_of_wait:
                    wait_start = blocked_info.wait_start_time_ms
                    assert wait_start is not None
                    blocked_info.wait_start_time_ms = None
                    self.bp.collect_metric(
                        ProtocolMetricsKind.WAIT_CONDITION_DELAY,
                        time.millis() - wait_start,
                    )
            # else: no longer at PROPOSE, nothing to do
        if at_propose_begin:
            self.try_to_unblock_again.append(
                (dot, clock, deps, at_propose_begin)
            )

    def _accept_command(self, dot: Dot, info: _CaesarInfo) -> None:
        self._send_mpropose_ack(dot, info.clock, set(info.deps), ok=True)

    def _reject_command(self, dot: Dot, info: _CaesarInfo) -> None:
        info.status = REJECT
        new_clock = self.key_clocks.clock_next()
        assert info.cmd is not None
        new_deps = self.key_clocks.predecessors(dot, info.cmd, new_clock, None)
        self._send_mpropose_ack(dot, new_clock, new_deps, ok=False)

    def _send_mpropose_ack(self, dot, clock, deps, ok) -> None:
        self.to_processes_buf.append(
            ToSend(target={dot.source}, msg=MProposeAck(dot, clock, deps, ok))
        )

    # -- clocks + GC ---------------------------------------------------

    def _update_clock(self, dot: Dot, info: _CaesarInfo, new_clock: Clock):
        """Swap the command's registered tentative clock
        (caesar.rs:893-918)."""
        assert info.cmd is not None
        if not info.clock.is_zero():
            self.key_clocks.remove(info.cmd, info.clock)
        self.key_clocks.add(dot, info.cmd, new_clock)
        info.clock = new_clock

    def _gc_track_add(self, dot: Dot) -> None:
        if self.gc_track.add(dot):
            self.to_processes_buf.append(ToForward(MGCDot(dot)))

    def _gc_command(self, dot: Dot) -> None:
        info = self.cmds.gc_single(dot)
        if info is None:
            # already removed (e.g. gc'd at commit when the periodic GC
            # is disabled; caesar.rs:921 tolerates this too)
            return
        assert info.cmd is not None
        if not info.clock.is_zero():
            self.key_clocks.remove(info.cmd, info.clock)

    def _gc_running(self) -> bool:
        return self.bp.config.gc_interval_ms is not None

"""Atlas protocol (EuroSys'20): dependency-based consensus with
f-dependent fast quorums.

Capability parity with ``fantoch_ps/src/protocol/atlas.rs``: submit
computes deps from per-key conflict indexes and broadcasts ``MCollect``
(atlas.rs:210-248); fast-quorum members merge the coordinator's deps as
"past" and reply (250-323); the fast path is taken iff the threshold
union (every dep reported by ≥ f processes) equals the plain union
(325-391); otherwise single-decree Paxos runs on the dependency set
(466-547); commits feed the graph executor (393-464) and the
committed-clock GC flow (630-703).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from ..core.command import Command
from ..core.config import Config
from ..core.ids import Dot, ProcessId, ShardId
from ..core.timing import SysTime
from ..executor.graph import GraphAdd, GraphExecutor
from . import partial
from .base import (
    BaseProcess,
    CommandsInfo,
    GCTrack,
    Message,
    Protocol,
    ProtocolMetrics,
    ToForward,
    ToSend,
)
from .graph_deps import Dependency, QuorumDeps, SequentialKeyDeps
from .synod import S_ACCEPT, S_ACCEPTED, S_CHOSEN, Synod

# statuses (atlas.rs:898-905)
START, PAYLOAD, COLLECT, COMMIT = range(4)


@dataclass
class ConsensusValue:
    """(is_noop, deps) pair agreed on by consensus (atlas.rs:743-760)."""

    is_noop: bool = False
    deps: Set[Dependency] = field(default_factory=set)


def _proposal_gen(_values):
    raise NotImplementedError("recovery not implemented yet")


# messages (atlas.rs:804-854)
@dataclass
class MCollect(Message):
    dot: Dot
    cmd: Command
    deps: Set[Dependency]
    quorum: Set[ProcessId]


@dataclass
class MCollectAck(Message):
    dot: Dot
    deps: Set[Dependency]


@dataclass
class MCommit(Message):
    dot: Dot
    value: ConsensusValue


@dataclass
class MConsensus(Message):
    dot: Dot
    ballot: int
    value: ConsensusValue


@dataclass
class MConsensusAck(Message):
    dot: Dot
    ballot: int


@dataclass
class MForwardSubmit(Message):
    dot: Dot
    cmd: Command


@dataclass
class MShardCommit(Message):
    dot: Dot
    deps: Set[Dependency]


@dataclass
class MShardAggregatedCommit(Message):
    dot: Dot
    deps: Set[Dependency]


@dataclass
class MCommitDot(Message):
    WORKER = "gc"

    dot: Dot


@dataclass
class MGarbageCollection(Message):
    WORKER = "gc"

    committed: Dict[ProcessId, int]


@dataclass
class MStable(Message):
    WORKER = "gc"

    stable: List[Tuple[ProcessId, int, int]]


GARBAGE_COLLECTION = "garbage_collection"


class _AtlasInfo:
    """Per-command lifecycle record (atlas.rs:766-802)."""

    def __init__(self, process_id: ProcessId, n: int, f: int,
                 fast_quorum_size: int):
        self.status = START
        self.quorum: Set[ProcessId] = set()
        self.synod: Synod[ConsensusValue] = Synod(
            process_id, n, f, _proposal_gen, ConsensusValue()
        )
        self.cmd: Optional[Command] = None
        self.quorum_deps = QuorumDeps(fast_quorum_size)
        self.shards_commits = None


class Atlas(Protocol):
    # implements partial.rs's multi-shard coordination paths
    PARTIAL_REPLICATION = True

    EXECUTOR = GraphExecutor

    def __init__(self, process_id: ProcessId, shard_id: ShardId, config: Config):
        super().__init__(process_id, shard_id, config)
        fast_quorum_size, write_quorum_size = config.atlas_quorum_sizes()
        self.bp = BaseProcess(
            process_id, shard_id, config, fast_quorum_size, write_quorum_size
        )
        self.key_deps = SequentialKeyDeps(shard_id)
        self.cmds: CommandsInfo[_AtlasInfo] = CommandsInfo(
            lambda: _AtlasInfo(process_id, config.n, config.f,
                               fast_quorum_size)
        )
        self.gc_track = GCTrack(process_id, shard_id, config.n)
        from ..core.ids import process_ids

        self.shard_processes = set(process_ids(shard_id, config.n))
        self.buffered_commits: Dict[Dot, Tuple[ProcessId, ConsensusValue]] = {}

    # -- Protocol interface -------------------------------------------

    def periodic_events(self):
        if self.bp.config.gc_interval_ms is not None:
            return [(GARBAGE_COLLECTION, self.bp.config.gc_interval_ms)]
        return []

    def id(self) -> ProcessId:
        return self.bp.process_id

    def shard_id(self) -> ShardId:
        return self.bp.shard_id

    def discover(self, processes):
        ok = self.bp.discover(processes)
        return ok, self.bp.closest_shard_process()

    def submit(self, dot: Optional[Dot], cmd: Command, time: SysTime) -> None:
        self._handle_submit(dot, cmd, target_shard=True)

    def handle(self, from_, from_shard_id, msg, time) -> None:
        if isinstance(msg, MCollect):
            self._handle_mcollect(from_, msg, time)
        elif isinstance(msg, MCollectAck):
            self._handle_mcollectack(from_, msg)
        elif isinstance(msg, MCommit):
            self._handle_mcommit(from_, msg.dot, msg.value)
        elif isinstance(msg, MConsensus):
            self._handle_mconsensus(from_, msg)
        elif isinstance(msg, MConsensusAck):
            self._handle_mconsensusack(from_, msg)
        elif isinstance(msg, MForwardSubmit):
            self._handle_submit(msg.dot, msg.cmd, target_shard=False)
        elif isinstance(msg, MShardCommit):
            self._handle_mshard_commit(from_, msg)
        elif isinstance(msg, MShardAggregatedCommit):
            self._handle_mshard_aggregated_commit(msg)
        elif isinstance(msg, MCommitDot):
            assert from_ == self.id()
            self.gc_track.add_to_clock(msg.dot)
        elif isinstance(msg, MGarbageCollection):
            self._handle_mgc(from_, msg)
        elif isinstance(msg, MStable):
            assert from_ == self.id()
            self.bp.stable(self.cmds.gc(msg.stable))
        else:
            raise TypeError(f"unexpected message {msg!r}")

    def handle_event(self, event, time) -> None:
        assert event == GARBAGE_COLLECTION
        self.to_processes_buf.append(
            ToSend(
                target=self.bp.all_but_me(),
                msg=MGarbageCollection(self.gc_track.clock_frontier()),
            )
        )

    @staticmethod
    def parallel() -> bool:
        # the reference ships AtlasLocked (RwLock-per-key KeyDeps) to make
        # W worker threads safe on shared state; this runtime's workers
        # are cooperative asyncio tasks, so every handle() is atomic and
        # the Locked capability holds with no locks
        return True

    @staticmethod
    def leaderless() -> bool:
        return True

    def metrics(self) -> ProtocolMetrics:
        return self.bp.metrics

    # -- handlers (atlas.rs:208-738) -----------------------------------

    def _handle_submit(self, dot, cmd, target_shard: bool) -> None:
        dot = dot if dot is not None else self.bp.next_dot()
        partial.submit_actions(
            self.bp, dot, cmd, target_shard, MForwardSubmit,
            self.to_processes_buf,
        )
        deps = self.key_deps.add_cmd(dot, cmd, None)
        self.to_processes_buf.append(
            ToSend(
                target=self.bp.all(),
                msg=MCollect(dot, cmd, deps, self.bp.fast_quorum()),
            )
        )

    def _handle_mcollect(self, from_, msg: MCollect, time) -> None:
        dot = msg.dot
        info = self.cmds.get(dot)
        if info.status != START:
            return
        if self.id() not in msg.quorum:
            # not in the fast quorum: just keep the payload; replay a
            # commit that overtook the collect (atlas.rs:278-293)
            info.status = PAYLOAD
            info.cmd = msg.cmd
            buffered = self.buffered_commits.pop(dot, None)
            if buffered is not None:
                self._handle_mcommit(buffered[0], dot, buffered[1])
            return
        if from_ == self.id():
            deps = msg.deps  # do not recompute own deps
        else:
            deps = self.key_deps.add_cmd(dot, msg.cmd, msg.deps)
        info.status = COLLECT
        info.quorum = set(msg.quorum)
        info.cmd = msg.cmd
        was_set = info.synod.set_if_not_accepted(
            lambda: ConsensusValue(deps=set(deps))
        )
        assert was_set
        self.to_processes_buf.append(
            ToSend(target={from_}, msg=MCollectAck(dot, deps))
        )

    def _handle_mcollectack(self, from_, msg: MCollectAck) -> None:
        info = self.cmds.get(msg.dot)
        if info.status != COLLECT:
            return
        info.quorum_deps.add(from_, msg.deps)
        if not info.quorum_deps.all():
            return
        # fast path iff threshold-union(f) == union (atlas.rs:353-390)
        all_deps, equal_to_union = info.quorum_deps.check_threshold_union(
            self.bp.config.f
        )
        value = ConsensusValue(deps=all_deps)
        if equal_to_union:
            self.bp.fast_path()
            self._mcommit_actions(info, msg.dot, value)
        else:
            self.bp.slow_path()
            ballot = info.synod.skip_prepare()
            self.to_processes_buf.append(
                ToSend(
                    target=self.bp.write_quorum(),
                    msg=MConsensus(msg.dot, ballot, value),
                )
            )

    def _handle_mcommit(self, from_, dot: Dot, value: ConsensusValue) -> None:
        info = self.cmds.get(dot)
        if info.status == START:
            # commit overtook the collect; buffer it (atlas.rs:411-419)
            self.buffered_commits[dot] = (from_, value)
            return
        if info.status == COMMIT:
            return
        assert not value.is_noop, "noop handling not implemented yet"
        cmd = info.cmd
        assert cmd is not None
        self.to_executors_buf.append(GraphAdd(dot, cmd, set(value.deps)))
        info.status = COMMIT
        chosen_out = info.synod.handle(from_, (S_CHOSEN, value))
        assert chosen_out is None
        my_shard = dot.source in self.shard_processes
        if self._gc_running() and my_shard:
            self.to_processes_buf.append(ToForward(MCommitDot(dot)))
        else:
            self.cmds.gc_single(dot)

    def _handle_mconsensus(self, from_, msg: MConsensus) -> None:
        info = self.cmds.get(msg.dot)
        out = info.synod.handle(from_, (S_ACCEPT, msg.ballot, msg.value))
        if out is None:
            return  # ballot too low
        kind = out[0]
        if kind == S_ACCEPTED:
            reply = MConsensusAck(msg.dot, out[1])
        elif kind == S_CHOSEN:
            reply = MCommit(msg.dot, out[1])
        else:
            raise AssertionError(f"unexpected synod output {out!r}")
        self.to_processes_buf.append(ToSend(target={from_}, msg=reply))

    def _handle_mconsensusack(self, from_, msg: MConsensusAck) -> None:
        info = self.cmds.get(msg.dot)
        out = info.synod.handle(from_, (S_ACCEPTED, msg.ballot))
        if out is None:
            return  # not enough accepts yet
        assert out[0] == S_CHOSEN
        self._mcommit_actions(info, msg.dot, out[1])

    def _handle_mshard_commit(self, from_, msg: MShardCommit) -> None:
        info = self.cmds.get(msg.dot)
        shard_count = info.cmd.shard_count()
        partial.handle_mshard_commit(
            self.bp,
            info,
            shard_count,
            from_,
            msg.dot,
            msg.deps,
            lambda current, deps: current.update(deps),
            lambda dot, current: MShardAggregatedCommit(dot, set(current)),
            self.to_processes_buf,
            set,
        )

    def _handle_mshard_aggregated_commit(
        self, msg: MShardAggregatedCommit
    ) -> None:
        info = self.cmds.get(msg.dot)
        partial.handle_mshard_aggregated_commit(
            self.bp,
            info,
            msg.dot,
            msg.deps,
            lambda _info: None,
            lambda dot, deps, _extra: MCommit(dot, ConsensusValue(deps=deps)),
            self.to_processes_buf,
        )

    def _handle_mgc(self, from_, msg: MGarbageCollection) -> None:
        self.gc_track.update_clock_of(from_, msg.committed)
        stable = self.gc_track.stable()
        if stable:
            self.to_processes_buf.append(ToForward(MStable(stable)))

    def _mcommit_actions(self, info, dot: Dot, value: ConsensusValue) -> None:
        shard_count = info.cmd.shard_count()
        partial.mcommit_actions(
            self.bp,
            info,
            shard_count,
            dot,
            value,
            None,
            lambda dot, value, _extra: MCommit(dot, value),
            lambda dot, value: MShardCommit(dot, set(value.deps)),
            lambda _info, _extra: None,
            self.to_processes_buf,
            set,
        )

    def _gc_running(self) -> bool:
        return self.bp.config.gc_interval_ms is not None

"""Tempo (EuroSys'21): timestamp-stability consensus — the flagship
protocol.

Capability parity with ``fantoch_ps/src/protocol/tempo.rs``:

- submit bumps per-key clocks into a timestamp proposal with attached vote
  ranges (tempo.rs:267-339);
- fast path iff the max clock over the fast quorum was reported by >= f
  members (tempo.rs:517-536); otherwise single-decree Paxos on the
  timestamp (``MConsensus``/``MConsensusAck``, tempo.rs:538-552, 718-812);
- commit emits per-key attached votes to the ``TableExecutor``
  (tempo.rs:589-617); detached votes accelerate stability
  (``MDetached``, periodic ``SendDetached``); periodic ``ClockBump``
  implements real-time clocks (bump to ``max(max_commit_clock,
  time.micros())``, tempo.rs:972-992);
- partial replication via ``MForwardSubmit``/``MBump``/``MShardCommit``/
  ``MShardAggregatedCommit`` (tempo.rs:814-895, partial.rs);
- committed-clock GC identical to Basic's (tempo.rs:897-970).

The reference's ``skip_fast_ack`` optimization (fast-quorum processes
commit directly when the fast quorum size is 2; tempo.rs:91-93, 442-455)
is supported.
"""

from __future__ import annotations

import copy
from dataclasses import dataclass, field
from enum import IntEnum
from typing import Dict, List, Optional, Set, Tuple

from ..core.command import Command
from ..core.config import Config
from ..core.ids import Dot, ProcessId, ShardId, process_ids
from ..core.timing import SysTime
from ..executor.table import AttachedVotes, DetachedVotes, TableExecutor
from . import partial
from .base import (
    BaseProcess,
    CommandsInfo,
    GCTrack,
    Message,
    Protocol,
    ToForward,
    ToSend,
)
from .synod import S_ACCEPT, S_ACCEPTED, S_CHOSEN, Synod
from .table import KeyClocks, QuorumClocks, Votes


class Status(IntEnum):
    START = 0
    PAYLOAD = 1
    COLLECT = 2
    COMMIT = 3


# messages (tempo.rs:1160-1224)
@dataclass
class MCollect(Message):
    dot: Dot
    cmd: Command
    quorum: Set[ProcessId]
    clock: int
    coordinator_votes: Votes


@dataclass
class MCollectAck(Message):
    dot: Dot
    clock: int
    process_votes: Votes


@dataclass
class MCommit(Message):
    dot: Dot
    clock: int
    votes: Votes


@dataclass
class MCommitClock(Message):
    WORKER = "aux"  # CLOCK_BUMP_WORKER_INDEX (tempo.rs:1226-1243)

    clock: int


@dataclass
class MDetached(Message):
    WORKER = "aux"  # CLOCK_BUMP_WORKER_INDEX (tempo.rs:1243-1245)

    detached: Votes


@dataclass
class MConsensus(Message):
    dot: Dot
    ballot: int
    clock: int


@dataclass
class MConsensusAck(Message):
    dot: Dot
    ballot: int


@dataclass
class MForwardSubmit(Message):
    dot: Dot
    cmd: Command


@dataclass
class MBump(Message):
    dot: Dot
    clock: int


@dataclass
class MShardCommit(Message):
    dot: Dot
    clock: int


@dataclass
class MShardAggregatedCommit(Message):
    dot: Dot
    clock: int


@dataclass
class MCommitDot(Message):
    WORKER = "gc"  # tempo.rs:1256-1262

    dot: Dot


@dataclass
class MGarbageCollection(Message):
    WORKER = "gc"

    committed: Dict[ProcessId, int]


@dataclass
class MStable(Message):
    WORKER = "gc"  # self-forwarded by the GC worker; stays there

    stable: List[Tuple[ProcessId, int, int]]


# periodic events (tempo.rs:1271-1276)
GARBAGE_COLLECTION = "garbage_collection"
CLOCK_BUMP = "clock_bump"
SEND_DETACHED = "send_detached"


def _proposal_gen(_values):
    raise NotImplementedError("recovery not implemented yet")  # tempo.rs:1098


@dataclass
class _ShardsCommitsInfo:
    """tempo.rs:1144-1158."""

    max_clock: int = 0
    votes: Optional[Votes] = None

    def add(self, clock: int) -> None:
        self.max_clock = max(self.max_clock, clock)

    def set_votes(self, votes: Votes) -> None:
        self.votes = votes


class _TempoInfo:
    """tempo.rs:1102-1141."""

    __slots__ = (
        "status",
        "quorum",
        "synod",
        "cmd",
        "votes",
        "quorum_clocks",
        "shards_commits",
    )

    def __init__(self, process_id: ProcessId, n: int, f: int, fast_quorum_size: int):
        self.status = Status.START
        self.quorum: Set[ProcessId] = set()
        self.synod: Synod[int] = Synod(process_id, n, f, _proposal_gen, 0)
        self.cmd: Optional[Command] = None
        self.votes = Votes()
        self.quorum_clocks = QuorumClocks(fast_quorum_size)
        self.shards_commits = None


class Tempo(Protocol):
    # implements partial.rs's multi-shard coordination paths
    PARTIAL_REPLICATION = True

    EXECUTOR = TableExecutor
    KEY_CLOCKS = KeyClocks

    def __init__(self, process_id: ProcessId, shard_id: ShardId, config: Config):
        super().__init__(process_id, shard_id, config)
        fast_quorum_size, write_quorum_size, _ = config.tempo_quorum_sizes()
        self.bp = BaseProcess(
            process_id, shard_id, config, fast_quorum_size, write_quorum_size
        )
        self.key_clocks = self.KEY_CLOCKS(process_id, shard_id)
        n, f = config.n, config.f
        self.cmds: CommandsInfo[_TempoInfo] = CommandsInfo(
            lambda: _TempoInfo(process_id, n, f, fast_quorum_size)
        )
        self.gc_track = GCTrack(process_id, shard_id, config.n)
        self.detached = Votes()
        self.buffered_mcommits: Dict[Dot, Tuple[ProcessId, int, Votes]] = {}
        self.buffered_mbumps: Dict[Dot, int] = {}
        self.max_commit_clock = 0
        self.skip_fast_ack = config.skip_fast_ack and fast_quorum_size == 2

    # -- Protocol interface --------------------------------------------

    def periodic_events(self):
        events = []
        cfg = self.bp.config
        if cfg.gc_interval_ms is not None:
            events.append((GARBAGE_COLLECTION, cfg.gc_interval_ms))
        if cfg.tempo_clock_bump_interval_ms is not None:
            events.append((CLOCK_BUMP, cfg.tempo_clock_bump_interval_ms))
        if cfg.tempo_detached_send_interval_ms is not None:
            events.append((SEND_DETACHED, cfg.tempo_detached_send_interval_ms))
        return events

    def id(self) -> ProcessId:
        return self.bp.process_id

    def shard_id(self) -> ShardId:
        return self.bp.shard_id

    def discover(self, processes):
        ok = self.bp.discover(processes)
        return ok, self.bp.closest_shard_process()

    def submit(self, dot: Optional[Dot], cmd: Command, time: SysTime) -> None:
        self._handle_submit(dot, cmd, target_shard=True)

    def handle(self, from_, from_shard_id, msg, time) -> None:
        if isinstance(msg, MCollect):
            self._handle_mcollect(from_, msg, time)
        elif isinstance(msg, MCollectAck):
            self._handle_mcollectack(from_, msg)
        elif isinstance(msg, MCommit):
            self._handle_mcommit(from_, msg.dot, msg.clock, msg.votes)
        elif isinstance(msg, MCommitClock):
            assert from_ == self.id()
            self.max_commit_clock = max(self.max_commit_clock, msg.clock)
        elif isinstance(msg, MDetached):
            self._handle_mdetached(msg.detached)
        elif isinstance(msg, MConsensus):
            self._handle_mconsensus(from_, msg)
        elif isinstance(msg, MConsensusAck):
            self._handle_mconsensusack(from_, msg)
        elif isinstance(msg, MForwardSubmit):
            self._handle_submit(msg.dot, msg.cmd, target_shard=False)
        elif isinstance(msg, MBump):
            self._handle_mbump(msg)
        elif isinstance(msg, MShardCommit):
            self._handle_mshard_commit(from_, msg)
        elif isinstance(msg, MShardAggregatedCommit):
            self._handle_mshard_aggregated_commit(msg)
        elif isinstance(msg, MCommitDot):
            assert from_ == self.id()
            self.gc_track.add_to_clock(msg.dot)
        elif isinstance(msg, MGarbageCollection):
            self._handle_mgc(from_, msg)
        elif isinstance(msg, MStable):
            assert from_ == self.id()
            self.bp.stable(self.cmds.gc(msg.stable))
        else:
            raise TypeError(f"unexpected message {msg!r}")

    def handle_event(self, event, time: SysTime) -> None:
        if event == GARBAGE_COLLECTION:
            self.to_processes_buf.append(
                ToSend(
                    target=self.bp.all_but_me(),
                    msg=MGarbageCollection(self.gc_track.clock_frontier()),
                )
            )
        elif event == CLOCK_BUMP:
            # bump all clocks to max(highest committed clock, current time
            # in MICROS) — millis lack precision with many clients
            # (tempo.rs:972-992)
            min_clock = max(self.max_commit_clock, time.micros())
            self.key_clocks.detached_all(min_clock, self.detached)
        elif event == SEND_DETACHED:
            detached, self.detached = self.detached, Votes()
            if not detached.is_empty():
                self.to_processes_buf.append(
                    ToSend(target=self.bp.all(), msg=MDetached(detached))
                )
        else:
            raise TypeError(f"unexpected event {event!r}")

    @staticmethod
    def parallel() -> bool:
        return True

    @staticmethod
    def leaderless() -> bool:
        return True

    @staticmethod
    def event_worker(event) -> str:
        """tempo.rs:1271-1276: clock-bump and send-detached run on the
        reserved clock-bump worker; GC on the GC worker."""
        return "gc" if event == GARBAGE_COLLECTION else "aux"

    def metrics(self):
        return self.bp.metrics

    # -- handlers -------------------------------------------------------

    def _handle_submit(
        self, dot: Optional[Dot], cmd: Command, target_shard: bool
    ) -> None:
        """tempo.rs:267-339."""
        dot = dot if dot is not None else self.bp.next_dot()

        partial.submit_actions(
            self.bp,
            dot,
            cmd,
            target_shard,
            lambda d, c: MForwardSubmit(d, c),
            self.to_processes_buf,
        )

        clock, process_votes = self.key_clocks.proposal(cmd, 0)
        shard_count = cmd.shard_count()

        if self.skip_fast_ack and shard_count == 1:
            coordinator_votes = process_votes
        else:
            info = self.cmds.get(dot)
            info.votes = process_votes
            coordinator_votes = Votes()

        self.to_processes_buf.append(
            ToSend(
                target=self.bp.all(),
                msg=MCollect(
                    dot, cmd, self.bp.fast_quorum(), clock, coordinator_votes
                ),
            )
        )

    def _handle_mcollect(self, from_, msg: MCollect, time: SysTime) -> None:
        """tempo.rs:341-459."""
        dot, cmd = msg.dot, msg.cmd
        info = self.cmds.get(dot)
        if info.status != Status.START:
            return

        if self.id() not in msg.quorum:
            # not in the fast quorum: save payload only
            if self.bp.config.tempo_clock_bump_interval_ms is not None:
                self.key_clocks.init_clocks(cmd)
            info.status = Status.PAYLOAD
            info.cmd = cmd
            buffered = self.buffered_mcommits.pop(dot, None)
            if buffered is not None:
                bfrom, bclock, bvotes = buffered
                self._handle_mcommit(bfrom, dot, bclock, bvotes)
            return

        message_from_self = from_ == self.bp.process_id
        if message_from_self:
            clock, process_votes = msg.clock, Votes()
        else:
            clock, process_votes = self.key_clocks.proposal(cmd, msg.clock)

        bump_to = self.buffered_mbumps.pop(dot, None)
        if bump_to is not None:
            self.key_clocks.detached(cmd, bump_to, self.detached)

        shard_count = cmd.shard_count()
        info.status = Status.COLLECT
        info.cmd = cmd
        info.quorum = set(msg.quorum)
        was_set = info.synod.set_if_not_accepted(lambda: clock)
        assert was_set

        if not message_from_self and self.skip_fast_ack and shard_count == 1:
            votes = msg.coordinator_votes
            votes.merge(process_votes)
            self._mcommit_actions(info, shard_count, dot, clock, votes)
        else:
            self._mcollect_actions(
                from_, dot, clock, process_votes, shard_count
            )

    def _handle_mcollectack(self, from_, msg: MCollectAck) -> None:
        """tempo.rs:461-554."""
        dot = msg.dot
        info = self.cmds.get(dot)
        if info.status != Status.COLLECT:
            return

        info.votes.merge(msg.process_votes)
        max_clock, max_count = info.quorum_clocks.add(from_, msg.clock)
        message_from_self = from_ == self.bp.process_id

        # optimization: bump keys to max_clock to avoid delaying this
        # command's execution (tempo.rs:497-514)
        cmd = info.cmd
        assert cmd is not None
        if not message_from_self:
            self.key_clocks.detached(cmd, max_clock, self.detached)

        if info.quorum_clocks.all():
            if max_count >= self.bp.config.f:
                self.bp.fast_path()
                votes, info.votes = info.votes, Votes()
                self._mcommit_actions(
                    info, cmd.shard_count(), dot, max_clock, votes
                )
            else:
                self.bp.slow_path()
                ballot = info.synod.skip_prepare()
                self.to_processes_buf.append(
                    ToSend(
                        target=self.bp.write_quorum(),
                        msg=MConsensus(dot, ballot, max_clock),
                    )
                )

    def _handle_mcommit(self, from_, dot: Dot, clock: int, votes: Votes) -> None:
        """tempo.rs:556-654."""
        info = self.cmds.get(dot)
        if info.status == Status.START:
            self.buffered_mcommits[dot] = (from_, clock, votes)
            return
        if info.status == Status.COMMIT:
            return

        cmd = info.cmd
        assert cmd is not None
        for key, ops in cmd.items(self.bp.shard_id):
            key_votes = votes.remove(key)
            self.to_executors_buf.append(
                AttachedVotes(
                    dot=dot,
                    clock=clock,
                    key=key,
                    rifl=cmd.rifl,
                    shard_to_keys={
                        s: list(keys) for s, keys in cmd.shard_to_ops.items()
                    },
                    ops=list(ops),
                    votes=key_votes,
                )
            )

        info.status = Status.COMMIT
        chosen_out = info.synod.handle(from_, (S_CHOSEN, clock))
        assert chosen_out is None

        if self.bp.config.tempo_clock_bump_interval_ms is not None:
            # real-time mode: just notify the clock-bump role
            self.to_processes_buf.append(ToForward(MCommitClock(clock)))
        else:
            self.key_clocks.detached(cmd, clock, self.detached)

        my_shard = dot.source in process_ids(
            self.bp.shard_id, self.bp.config.n
        )
        if self._gc_running() and my_shard:
            self.to_processes_buf.append(ToForward(MCommitDot(dot)))
        else:
            self.cmds.gc_single(dot)

    def _handle_mdetached(self, detached: Votes) -> None:
        """tempo.rs:703-716."""
        for key, key_votes in detached.items():
            self.to_executors_buf.append(DetachedVotes(key, key_votes))

    def _handle_mconsensus(self, from_, msg: MConsensus) -> None:
        """tempo.rs:718-773."""
        info = self.cmds.get(msg.dot)
        if info.cmd is not None:
            self.key_clocks.detached(info.cmd, msg.clock, self.detached)
        out = info.synod.handle(from_, (S_ACCEPT, msg.ballot, msg.clock))
        if out is None:
            return
        if out[0] == S_ACCEPTED:
            reply = MConsensusAck(msg.dot, out[1])
        elif out[0] == S_CHOSEN:
            # already-chosen: reply with an MCommit carrying known votes
            reply = MCommit(msg.dot, out[1], copy.deepcopy(info.votes))
        else:
            raise AssertionError(out)
        self.to_processes_buf.append(ToSend(target={from_}, msg=reply))

    def _handle_mconsensusack(self, from_, msg: MConsensusAck) -> None:
        """tempo.rs:775-812."""
        info = self.cmds.get(msg.dot)
        out = info.synod.handle(from_, (S_ACCEPTED, msg.ballot))
        if out is None:
            return
        assert out[0] == S_CHOSEN
        clock = out[1]
        votes, info.votes = info.votes, Votes()
        assert info.cmd is not None
        self._mcommit_actions(
            info, info.cmd.shard_count(), msg.dot, clock, votes
        )

    def _handle_mbump(self, msg: MBump) -> None:
        """tempo.rs:674-701."""
        info = self.cmds.get(msg.dot)
        if info.cmd is not None:
            self.key_clocks.detached(info.cmd, msg.clock, self.detached)
        else:
            current = self.buffered_mbumps.get(msg.dot, 0)
            self.buffered_mbumps[msg.dot] = max(current, msg.clock)

    def _handle_mshard_commit(self, from_, msg: MShardCommit) -> None:
        """tempo.rs:814-858."""
        info = self.cmds.get(msg.dot)
        assert info.cmd is not None
        shard_count = info.cmd.shard_count()
        partial.handle_mshard_commit(
            self.bp,
            info,
            shard_count,
            from_,
            msg.dot,
            msg.clock,
            lambda i, clock: i.add(clock),
            lambda dot, i: MShardAggregatedCommit(dot, i.max_clock),
            self.to_processes_buf,
            _ShardsCommitsInfo,
        )

    def _handle_mshard_aggregated_commit(
        self, msg: MShardAggregatedCommit
    ) -> None:
        """tempo.rs:860-895."""
        info = self.cmds.get(msg.dot)
        partial.handle_mshard_aggregated_commit(
            self.bp,
            info,
            msg.dot,
            msg.clock,
            lambda i: i.votes,
            lambda dot, clock, votes: MCommit(dot, clock, votes),
            self.to_processes_buf,
        )

    def _handle_mgc(self, from_, msg: MGarbageCollection) -> None:
        self.gc_track.update_clock_of(from_, msg.committed)
        stable = self.gc_track.stable()
        if stable:
            self.to_processes_buf.append(ToForward(MStable(stable)))

    # -- helpers --------------------------------------------------------

    def _mcollect_actions(
        self, from_, dot, clock, process_votes, shard_count
    ) -> None:
        """tempo.rs:1013-1049."""
        self.to_processes_buf.append(
            ToSend(target={from_}, msg=MCollectAck(dot, clock, process_votes))
        )
        if shard_count > 1:
            info = self.cmds.get(dot)
            assert info.cmd is not None
            for shard_id in info.cmd.shards():
                if shard_id != self.bp.shard_id:
                    self.to_processes_buf.append(
                        ToSend(
                            target={self.bp.closest_process(shard_id)},
                            msg=MBump(dot, clock),
                        )
                    )

    def _mcommit_actions(self, info, shard_count, dot, clock, votes) -> None:
        """tempo.rs:1051-1081."""
        partial.mcommit_actions(
            self.bp,
            info,
            shard_count,
            dot,
            clock,
            votes,
            lambda d, c, v: MCommit(d, c, v),
            lambda d, c: MShardCommit(d, c),
            lambda i, v: i.set_votes(v),
            self.to_processes_buf,
            _ShardsCommitsInfo,
        )

    def _gc_running(self) -> bool:
        return self.bp.config.gc_interval_ms is not None


class TempoAtomic(Tempo):
    """Tempo over the native lock-free AtomicKeyClocks — the
    ``tempo_atomic`` binary's variant (fantoch_ps/src/bin/
    tempo_atomic.rs; clock state in common/table/clocks/keys/
    atomic.rs:13-90). Byte-identical behavior to :class:`Tempo` under
    one worker; under thread-parallel workers the per-key CAS bumps
    interleave safely without the GIL."""

    from .table import NativeAtomicKeyClocks as KEY_CLOCKS  # noqa: N814

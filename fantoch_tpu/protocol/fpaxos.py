"""FPaxos: leader-based Multi-Paxos with flexible quorums.

Capability parity with ``fantoch_ps/src/protocol/fpaxos.rs``: the leader,
per-slot commanders and acceptors are folded into one process via
``MultiSynod`` (fpaxos.rs:16-23); a submit at a non-leader forwards to the
leader (fpaxos.rs:167-196); the leader self-forwards ``MSpawnCommander``
(enabling parallel commanders, fpaxos.rs:198-238); accepts go to the f+1
write quorum; chosen slots are broadcast and executed in slot order by the
``SlotExecutor``; stable slots are GC'd via committed-frontier exchange
(fpaxos.rs:343-378, synod/gc.rs).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from ..core.command import Command
from ..core.config import Config
from ..core.ids import Dot, ProcessId, ShardId
from ..core.timing import SysTime
from ..executor.slot import SlotExecutionInfo, SlotExecutor
from .base import BaseProcess, Message, Protocol, ToForward, ToSend
from .synod import (
    ACCEPT,
    ACCEPTED,
    CHOSEN,
    FORWARD_SUBMIT,
    SPAWN_COMMANDER,
    MultiSynod,
    SynodGCTrack,
)


# messages (fpaxos.rs:382-408)
@dataclass
class MForwardSubmit(Message):
    WORKER = "leader"  # fpaxos.rs:383-453 routing

    cmd: Command


@dataclass
class MSpawnCommander(Message):
    WORKER = "slot"

    ballot: int
    slot: int
    cmd: Command


@dataclass
class MAccept(Message):
    WORKER = "aux"  # ACCEPTOR_WORKER_INDEX

    ballot: int
    slot: int
    cmd: Command


@dataclass
class MAccepted(Message):
    WORKER = "slot"  # back to the spawned commander

    ballot: int
    slot: int


@dataclass
class MChosen(Message):
    WORKER = "aux"

    slot: int
    cmd: Command


@dataclass
class MGarbageCollection(Message):
    WORKER = "aux"  # the acceptor holds the slots to gc

    committed: int


GARBAGE_COLLECTION = "garbage_collection"


class FPaxos(Protocol):
    EXECUTOR = SlotExecutor

    def __init__(self, process_id: ProcessId, shard_id: ShardId, config: Config):
        super().__init__(process_id, shard_id, config)
        fast_quorum_size = 0  # no fast paths (fpaxos.rs:37)
        write_quorum_size = config.fpaxos_quorum_size()
        self.bp = BaseProcess(
            process_id, shard_id, config, fast_quorum_size, write_quorum_size
        )
        assert config.leader is not None, (
            "in a leader-based protocol, the initial leader should be defined"
        )
        self.leader = config.leader
        self.multi_synod: MultiSynod[Command] = MultiSynod(
            process_id, self.leader, config.n, config.f
        )
        self.gc_track = SynodGCTrack(process_id, config.n)

    def periodic_events(self):
        if self.bp.config.gc_interval_ms is not None:
            return [(GARBAGE_COLLECTION, self.bp.config.gc_interval_ms)]
        return []

    def id(self) -> ProcessId:
        return self.bp.process_id

    def shard_id(self) -> ShardId:
        return self.bp.shard_id

    def discover(self, processes):
        ok = self.bp.discover(processes)
        return ok, self.bp.closest_shard_process()

    def submit(self, dot: Optional[Dot], cmd: Command, time: SysTime) -> None:
        self._handle_submit(cmd)

    def handle(self, from_, from_shard_id, msg, time) -> None:
        if isinstance(msg, MForwardSubmit):
            self._handle_submit(msg.cmd)
        elif isinstance(msg, MSpawnCommander):
            self._handle_mspawn_commander(from_, msg)
        elif isinstance(msg, MAccept):
            self._handle_maccept(from_, msg)
        elif isinstance(msg, MAccepted):
            self._handle_maccepted(from_, msg)
        elif isinstance(msg, MChosen):
            self._handle_mchosen(msg)
        elif isinstance(msg, MGarbageCollection):
            self._handle_mgc(from_, msg)
        else:
            raise TypeError(f"unexpected message {msg!r}")

    def handle_event(self, event, time) -> None:
        assert event == GARBAGE_COLLECTION
        self.to_processes_buf.append(
            ToSend(
                target=self.bp.all_but_me(),
                msg=MGarbageCollection(self.gc_track.committed()),
            )
        )

    @staticmethod
    def event_worker(event) -> str:
        # the acceptor worker holds the slots to gc (fpaxos.rs routing)
        return "aux"

    @staticmethod
    def parallel() -> bool:
        return True

    @staticmethod
    def leaderless() -> bool:
        return False

    def metrics(self):
        return self.bp.metrics

    # -- handlers (fpaxos.rs:165-378) -----------------------------------

    def _handle_submit(self, cmd: Command) -> None:
        out = self.multi_synod.submit(cmd)
        if out[0] == SPAWN_COMMANDER:
            _, ballot, slot, cmd = out
            self.to_processes_buf.append(
                ToForward(MSpawnCommander(ballot, slot, cmd))
            )
        elif out[0] == FORWARD_SUBMIT:
            self.to_processes_buf.append(
                ToSend(target={self.leader}, msg=MForwardSubmit(out[1]))
            )
        else:
            raise AssertionError(out)

    def _handle_mspawn_commander(self, from_, msg: MSpawnCommander) -> None:
        assert from_ == self.id()
        out = self.multi_synod.handle_spawn_commander(
            msg.ballot, msg.slot, msg.cmd
        )
        assert out[0] == ACCEPT
        _, ballot, slot, cmd = out
        self.to_processes_buf.append(
            ToSend(
                target=self.bp.write_quorum(), msg=MAccept(ballot, slot, cmd)
            )
        )

    def _handle_maccept(self, from_, msg: MAccept) -> None:
        out = self.multi_synod.handle_accept(msg.ballot, msg.slot, msg.cmd)
        if out is not None:
            _, ballot, slot = out
            self.to_processes_buf.append(
                ToSend(target={from_}, msg=MAccepted(ballot, slot))
            )

    def _handle_maccepted(self, from_, msg: MAccepted) -> None:
        out = self.multi_synod.handle_accepted(from_, msg.ballot, msg.slot)
        if out is not None:
            _, slot, cmd = out
            self.to_processes_buf.append(
                ToSend(target=self.bp.all(), msg=MChosen(slot, cmd))
            )

    def _handle_mchosen(self, msg: MChosen) -> None:
        self.to_executors_buf.append(SlotExecutionInfo(msg.slot, msg.cmd))
        if self._gc_running():
            self.gc_track.commit(msg.slot)
        else:
            self.multi_synod.gc_single(msg.slot)

    def _handle_mgc(self, from_, msg: MGarbageCollection) -> None:
        self.gc_track.committed_by(from_, msg.committed)
        stable = self.gc_track.stable()
        stable_count = self.multi_synod.gc(stable)
        self.bp.stable(stable_count)

    def _gc_running(self) -> bool:
        return self.bp.config.gc_interval_ms is not None

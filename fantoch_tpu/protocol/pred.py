"""Caesar common structures: logical clocks, per-key clock indexes, and
quorum aggregators.

Capability parity with ``fantoch_ps/src/protocol/common/pred/``:
``Clock`` is a totally-ordered (seq, process_id) pair with lexicographic
join (clocks/mod.rs:27-117); ``KeyClocks`` stores, per key, the set of
known commands by tentative timestamp and computes predecessors (lower
clock) and blockers (higher clock) in one sweep (clocks/keys/locked.rs);
``QuorumClocks`` aggregates MProposeAck replies with the early-reject
rule (a majority with some !ok ends the wait before the full fast
quorum, clocks/quorum.rs:58-69); ``QuorumRetries`` aggregates MRetryAck
deps over the write quorum (quorum.rs:84-124).

Device-engine note: ``Clock`` packs into one i64 as ``seq * N + pid``;
the per-key index becomes a [K, slots] clock-sorted table.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional, Set, Tuple

from ..core.command import Command
from ..core.ids import Dot, ProcessId, ShardId
from ..core.kvs import Key

# deps are plain dot sets (CaesarDeps, pred/mod.rs:14-50)
CaesarDeps = Set[Dot]


@dataclass(frozen=True, order=True)
class Clock:
    """Totally-ordered logical timestamp (clocks/mod.rs:27-60)."""

    seq: int
    process_id: ProcessId

    @classmethod
    def zero(cls, process_id: ProcessId) -> "Clock":
        return cls(0, process_id)

    def join(self, other: "Clock") -> "Clock":
        """Lexicographic join (clocks/mod.rs:41-56)."""
        return max(self, other)

    def is_zero(self) -> bool:
        return self.seq == 0


class KeyClocks:
    """Sequential equivalent of ``LockedKeyClocks``
    (clocks/keys/locked.rs:20-134): per key, a map from tentative
    timestamp to command dot; timestamps are unique per key."""

    def __init__(self, process_id: ProcessId, shard_id: ShardId):
        self.process_id = process_id
        self.shard_id = shard_id
        self.seq = 0
        self.clocks: Dict[Key, Dict[Clock, Dot]] = {}

    def clock_next(self) -> Clock:
        self.seq += 1
        return Clock(self.seq, self.process_id)

    def clock_join(self, other: Clock) -> None:
        self.seq = max(self.seq, other.seq)

    def add(self, dot: Dot, cmd: Command, clock: Clock) -> None:
        for key in cmd.keys(self.shard_id):
            commands = self.clocks.setdefault(key, {})
            assert clock not in commands, (
                "can't add a timestamp belonging to a command already added"
            )
            commands[clock] = dot

    def remove(self, cmd: Command, clock: Clock) -> None:
        for key in cmd.keys(self.shard_id):
            removed = self.clocks.get(key, {}).pop(clock, None)
            assert removed is not None, (
                "can't remove a timestamp belonging to a command never added"
            )

    def predecessors(
        self,
        dot: Dot,
        cmd: Command,
        clock: Clock,
        blocking: Optional[Set[Dot]] = None,
    ) -> CaesarDeps:
        """All conflicting commands with a lower timestamp; fills
        ``blocking`` with the higher-timestamp ones
        (clocks/keys/locked.rs:85-131)."""
        predecessors: CaesarDeps = set()
        for key in cmd.keys(self.shard_id):
            for cmd_clock, cmd_dot in self.clocks.get(key, {}).items():
                if cmd_clock < clock:
                    predecessors.add(cmd_dot)
                elif cmd_clock > clock:
                    if blocking is not None:
                        blocking.add(cmd_dot)
                else:
                    assert cmd_dot == dot, (
                        "found different command with the same timestamp"
                    )
        return predecessors

    @staticmethod
    def parallel() -> bool:
        return False


class QuorumClocks:
    """MProposeAck aggregation (clocks/quorum.rs:7-81)."""

    def __init__(
        self,
        process_id: ProcessId,
        fast_quorum_size: int,
        write_quorum_size: int,
    ):
        self.fast_quorum_size = fast_quorum_size
        self.write_quorum_size = write_quorum_size
        self.participants: Set[ProcessId] = set()
        self.clock = Clock.zero(process_id)
        self.deps: CaesarDeps = set()
        self.ok = True

    def add(
        self, process_id: ProcessId, clock: Clock, deps: CaesarDeps, ok: bool
    ) -> None:
        assert len(self.participants) < self.fast_quorum_size
        self.participants.add(process_id)
        self.clock = self.clock.join(clock)
        self.deps |= deps
        self.ok = self.ok and ok

    def all(self) -> bool:
        """Done on a full fast quorum, or early on a majority once some
        process rejected (clocks/quorum.rs:58-69)."""
        replied = len(self.participants)
        some_not_ok_after_majority = (
            not self.ok and replied >= self.write_quorum_size
        )
        return some_not_ok_after_majority or replied == self.fast_quorum_size

    def aggregated(self) -> Tuple[Clock, CaesarDeps, bool]:
        self.participants = set()
        deps, self.deps = self.deps, set()
        return self.clock, deps, self.ok


class QuorumRetries:
    """MRetryAck aggregation over the write quorum
    (clocks/quorum.rs:84-124)."""

    def __init__(self, write_quorum_size: int):
        self.write_quorum_size = write_quorum_size
        self.participants: Set[ProcessId] = set()
        self.deps: CaesarDeps = set()

    def add(self, process_id: ProcessId, deps: CaesarDeps) -> None:
        assert len(self.participants) < self.write_quorum_size
        self.participants.add(process_id)
        self.deps |= deps

    def all(self) -> bool:
        return len(self.participants) == self.write_quorum_size

    def aggregated(self) -> CaesarDeps:
        self.participants = set()
        deps, self.deps = self.deps, set()
        return deps

"""Toy ``Basic`` protocol: f+1 store-acks then commit.

Capability parity with ``fantoch/src/protocol/basic.rs``: the coordinator
sends ``MStore`` to all; fast-quorum members ack; after ``f+1`` acks the
coordinator broadcasts ``MCommit``; committed commands go straight to the
``BasicExecutor``; commit notifications feed the committed-clock GC flow
(basic.rs:20-330). 100% fast path — there is no write quorum / slow path.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from ..core.command import Command
from ..core.config import Config
from ..core.ids import Dot, ProcessId, ShardId
from ..core.timing import SysTime
from ..executor.base import BasicExecutionInfo, BasicExecutor
from .base import (
    BaseProcess,
    CommandsInfo,
    GCTrack,
    Message,
    Protocol,
    ProtocolMetrics,
    ToForward,
    ToSend,
)


# messages (basic.rs:362-385)
@dataclass
class MStore(Message):
    dot: Dot
    cmd: Command
    quorum: Set[ProcessId]


@dataclass
class MStoreAck(Message):
    dot: Dot


@dataclass
class MCommit(Message):
    dot: Dot


@dataclass
class MCommitDot(Message):
    WORKER = "gc"

    dot: Dot


@dataclass
class MGarbageCollection(Message):
    WORKER = "gc"

    committed: Dict[ProcessId, int]


@dataclass
class MStable(Message):
    WORKER = "gc"

    stable: List[Tuple[ProcessId, int, int]]


GARBAGE_COLLECTION = "garbage_collection"


@dataclass
class _BasicInfo:
    cmd: Optional[Command] = None
    acks: Set[ProcessId] = field(default_factory=set)


class Basic(Protocol):
    EXECUTOR = BasicExecutor

    def __init__(self, process_id: ProcessId, shard_id: ShardId, config: Config):
        super().__init__(process_id, shard_id, config)
        fast_quorum_size = config.basic_quorum_size()
        write_quorum_size = 0  # 100% fast paths (basic.rs:42)
        self.bp = BaseProcess(
            process_id, shard_id, config, fast_quorum_size, write_quorum_size
        )
        self.cmds: CommandsInfo[_BasicInfo] = CommandsInfo(_BasicInfo)
        self.gc_track = GCTrack(process_id, shard_id, config.n)
        self.buffered_mcommits: Set[Dot] = set()

    # -- Protocol interface -------------------------------------------

    def periodic_events(self):
        if self.bp.config.gc_interval_ms is not None:
            return [(GARBAGE_COLLECTION, self.bp.config.gc_interval_ms)]
        return []

    def id(self) -> ProcessId:
        return self.bp.process_id

    def shard_id(self) -> ShardId:
        return self.bp.shard_id

    def discover(self, processes):
        ok = self.bp.discover(processes)
        return ok, self.bp.closest_shard_process()

    def submit(self, dot: Optional[Dot], cmd: Command, time: SysTime) -> None:
        dot = dot if dot is not None else self.bp.next_dot()
        self.to_processes_buf.append(
            ToSend(
                target=self.bp.all(),
                msg=MStore(dot, cmd, self.bp.fast_quorum()),
            )
        )

    def handle(self, from_, from_shard_id, msg, time) -> None:
        if isinstance(msg, MStore):
            self._handle_mstore(from_, msg)
        elif isinstance(msg, MStoreAck):
            self._handle_mstoreack(from_, msg)
        elif isinstance(msg, MCommit):
            self._handle_mcommit(msg.dot)
        elif isinstance(msg, MCommitDot):
            self._handle_mcommit_dot(from_, msg)
        elif isinstance(msg, MGarbageCollection):
            self._handle_mgc(from_, msg)
        elif isinstance(msg, MStable):
            self._handle_mstable(from_, msg)
        else:
            raise TypeError(f"unexpected message {msg!r}")

    def handle_event(self, event, time) -> None:
        assert event == GARBAGE_COLLECTION
        self.to_processes_buf.append(
            ToSend(
                target=self.bp.all_but_me(),
                msg=MGarbageCollection(self.gc_track.clock_frontier()),
            )
        )

    @staticmethod
    def parallel() -> bool:
        return True

    @staticmethod
    def leaderless() -> bool:
        return True

    def metrics(self) -> ProtocolMetrics:
        return self.bp.metrics

    # -- handlers (basic.rs:169-334) -----------------------------------

    def _handle_mstore(self, from_: ProcessId, msg: MStore) -> None:
        info = self.cmds.get(msg.dot)
        info.cmd = msg.cmd
        if self.id() in msg.quorum:
            self.to_processes_buf.append(
                ToSend(target={from_}, msg=MStoreAck(msg.dot))
            )
        if msg.dot in self.buffered_mcommits:
            self.buffered_mcommits.remove(msg.dot)
            self._handle_mcommit(msg.dot)

    def _handle_mstoreack(self, from_: ProcessId, msg: MStoreAck) -> None:
        info = self.cmds.get(msg.dot)
        info.acks.add(from_)
        if len(info.acks) == self.bp.config.basic_quorum_size():
            self.to_processes_buf.append(
                ToSend(target=self.bp.all(), msg=MCommit(msg.dot))
            )

    def _handle_mcommit(self, dot: Dot) -> None:
        info = self.cmds.get(dot)
        if info.cmd is not None:
            cmd = info.cmd
            for key, ops in cmd.items(self.shard_id()):
                self.to_executors_buf.append(
                    BasicExecutionInfo(cmd.rifl, key, list(ops))
                )
            if self._gc_running():
                self.to_processes_buf.append(ToForward(MCommitDot(dot)))
            else:
                self.cmds.gc_single(dot)
        else:
            # payload hasn't arrived yet; buffer the commit notification
            self.buffered_mcommits.add(dot)

    def _handle_mcommit_dot(self, from_: ProcessId, msg: MCommitDot) -> None:
        assert from_ == self.id()
        self.gc_track.add_to_clock(msg.dot)

    def _handle_mgc(self, from_: ProcessId, msg: MGarbageCollection) -> None:
        self.gc_track.update_clock_of(from_, msg.committed)
        stable = self.gc_track.stable()
        if stable:
            self.to_processes_buf.append(ToForward(MStable(stable)))

    def _handle_mstable(self, from_: ProcessId, msg: MStable) -> None:
        assert from_ == self.id()
        stable_count = self.cmds.gc(msg.stable)
        self.bp.stable(stable_count)

    def _gc_running(self) -> bool:
        return self.bp.config.gc_interval_ms is not None

"""Protocol abstraction.

Capability parity with ``fantoch/src/protocol/``: the ``Protocol`` interface
(protocol/mod.rs:41-115), ``Action`` (mod.rs:196-205), ``BaseProcess``
(quorum membership from distance-sorted discovery, dot generation, fast/slow
path metrics; base.rs:10-204), per-dot command-info stores (info/mod.rs) and
the committed-clock GC tracker (gc/clock.rs:10-171).

Design note for the TPU build: every concrete protocol here is the *oracle*
(host, one config at a time, dict-based) used for differential testing; its
array twin lives in ``fantoch_tpu/engine/protocols`` where ``handle``
becomes a batched message-type dispatch over fixed-shape state.
"""

from __future__ import annotations

from abc import ABC, abstractmethod
from dataclasses import dataclass
from enum import Enum
from typing import (
    Dict,
    Generic,
    List,
    Optional,
    Sequence,
    Set,
    Tuple,
    TypeVar,
    Union,
)

from ..core.command import Command
from ..core.config import Config
from ..core.ids import Dot, DotGen, ProcessId, ShardId
from ..core.metrics import Metrics
from ..core.timing import SysTime


class ProtocolMetricsKind(Enum):
    """protocol/mod.rs:147-180."""

    FAST_PATH = "fast_path"
    SLOW_PATH = "slow_path"
    STABLE = "stable"
    COMMIT_LATENCY = "commit_latency"
    WAIT_CONDITION_DELAY = "wait_condition_delay"
    COMMITTED_DEPS_LEN = "committed_deps_len"


ProtocolMetrics = Metrics


@dataclass
class ToSend:
    """Send ``msg`` to every process in ``target`` (mod.rs:196-201)."""

    target: Set[ProcessId]
    msg: "Message"


@dataclass
class ToForward:
    """Deliver ``msg`` to self immediately — used to route work between
    worker roles within a process (mod.rs:202-205)."""

    msg: "Message"


Action = Union[ToSend, ToForward]


@dataclass
class Message:
    """Base class for protocol messages; concrete protocols define
    dataclass subclasses (one per reference message variant).

    ``WORKER`` is the ``MessageIndex`` analog (protocol/mod.rs:182-194
    routed through lib.rs:44-76's reserved indexes) used by the run
    layer to pick one of W protocol workers:

    - ``"dot"``: shift past the two reserved workers by the message's
      dot sequence (``worker_dot_index_shift``) — the default; dotless
      messages fall back to the GC worker;
    - ``"slot"``: shift by ``self.slot`` (FPaxos commanders);
    - ``"gc"``: reserved worker 0 (``GC_WORKER_INDEX``);
    - ``"leader"``: reserved worker 0 (``LEADER_WORKER_INDEX``);
    - ``"aux"``: reserved worker 1 (Tempo's clock-bump role, FPaxos's
      acceptor role).
    """

    WORKER = "dot"


class BaseProcess:
    """base.rs:10-204."""

    def __init__(
        self,
        process_id: ProcessId,
        shard_id: ShardId,
        config: Config,
        fast_quorum_size: int,
        write_quorum_size: int,
    ):
        # ids must be non-zero: processes lead with ballot `id` in the slow
        # path and 0 means "never been through phase-2" (base.rs:36-39)
        assert process_id != 0
        self.process_id = process_id
        self.shard_id = shard_id
        self.config = config
        self.fast_quorum_size = fast_quorum_size
        self.write_quorum_size = write_quorum_size
        self._all: Optional[List[ProcessId]] = None
        self._all_but_me: Optional[List[ProcessId]] = None
        self._fast_quorum: Optional[List[ProcessId]] = None
        self._write_quorum: Optional[List[ProcessId]] = None
        self._closest_shard_process: Dict[ShardId, ProcessId] = {}
        self.dot_gen = DotGen(process_id)
        self.metrics: ProtocolMetrics = Metrics()

    def discover(self, processes: Sequence[Tuple[ProcessId, ShardId]]) -> bool:
        """``processes`` is sorted by distance (base.rs:57-131). Processes
        of other shards must each be the closest of their shard."""
        my_shard = []
        self._closest_shard_process = {}
        for process_id, shard_id in processes:
            if shard_id == self.shard_id:
                my_shard.append(process_id)
            else:
                assert shard_id not in self._closest_shard_process
                self._closest_shard_process[shard_id] = process_id
        self._all = list(my_shard)
        self._all_but_me = [p for p in my_shard if p != self.process_id]
        fast = my_shard[: self.fast_quorum_size]
        write = my_shard[: self.write_quorum_size]
        self._fast_quorum = fast if len(fast) == self.fast_quorum_size else None
        self._write_quorum = (
            write if len(write) == self.write_quorum_size else None
        )
        return self._fast_quorum is not None and self._write_quorum is not None

    def next_dot(self) -> Dot:
        return self.dot_gen.next_id()

    def all(self) -> Set[ProcessId]:
        assert self._all is not None
        return set(self._all)

    def all_but_me(self) -> Set[ProcessId]:
        assert self._all_but_me is not None
        return set(self._all_but_me)

    def fast_quorum(self) -> Set[ProcessId]:
        assert self._fast_quorum is not None
        return set(self._fast_quorum)

    def fast_quorum_sorted(self) -> List[ProcessId]:
        """Fast quorum in distance order (closest first); the reference
        keeps a HashSet but protocols like Tempo rely only on membership."""
        assert self._fast_quorum is not None
        return list(self._fast_quorum)

    def write_quorum(self) -> Set[ProcessId]:
        assert self._write_quorum is not None
        return set(self._write_quorum)

    def closest_process(self, shard_id: ShardId) -> ProcessId:
        return self._closest_shard_process[shard_id]

    def closest_shard_process(self) -> Dict[ShardId, ProcessId]:
        return self._closest_shard_process

    # metrics (base.rs:184-203)
    def fast_path(self) -> None:
        self.metrics.aggregate(ProtocolMetricsKind.FAST_PATH, 1)

    def slow_path(self) -> None:
        self.metrics.aggregate(ProtocolMetricsKind.SLOW_PATH, 1)

    def stable(self, count: int) -> None:
        self.metrics.aggregate(ProtocolMetricsKind.STABLE, count)

    def collect_metric(self, kind: ProtocolMetricsKind, value: int) -> None:
        self.metrics.collect(kind, value)


I = TypeVar("I")


class CommandsInfo(Generic[I]):
    """Per-dot info store (protocol/info/mod.rs): creates per-command info
    records on demand and garbage-collects stable dots."""

    def __init__(self, info_factory):
        self._factory = info_factory
        self._infos: Dict[Dot, I] = {}

    def get(self, dot: Dot) -> I:
        info = self._infos.get(dot)
        if info is None:
            info = self._factory()
            self._infos[dot] = info
        return info

    def peek(self, dot: Dot) -> Optional[I]:
        return self._infos.get(dot)

    def gc(self, stable: List[Tuple[ProcessId, int, int]]) -> int:
        """Remove stable dots; returns how many were removed
        (info/mod.rs; used by the Stable metric)."""
        from ..core.ids import dots as expand

        count = 0
        for dot in expand(stable):
            if self._infos.pop(dot, None) is not None:
                count += 1
        return count

    def gc_single(self, dot: Dot) -> Optional[I]:
        """Remove ``dot``'s info, returning it for cleanup if present
        (LockedCommandsInfo::gc_single returns the removed record)."""
        return self._infos.pop(dot, None)

    def __len__(self) -> int:
        return len(self._infos)


# above-exact event set (the `threshold` crate's AboveExSet used by
# gc/clock.rs); interval-backed so huge vote ranges stay cheap
from ..core.intervals import IntervalSet as AEClockSet  # noqa: E402


class GCTrack:
    """Committed-clock intersection GC (``VClockGCTrack``,
    gc/clock.rs:10-138).

    The single GC role per process tracks (a) its own committed dots as an
    exact clock and (b) the committed frontiers advertised by every other
    process; a dot is *stable* (present everywhere) when it is at or below
    the meet of all frontiers. Newly stable dots are returned as compressed
    (process, start, end) ranges.
    """

    def __init__(self, process_id: ProcessId, shard_id: ShardId, n: int):
        from ..core.ids import process_ids

        self.process_id = process_id
        self.n = n
        self.ids = process_ids(shard_id, n)
        self.my_clock: Dict[ProcessId, AEClockSet] = {
            p: AEClockSet() for p in self.ids
        }
        self.all_but_me: Dict[ProcessId, Dict[ProcessId, int]] = {}
        self.previous_stable: Dict[ProcessId, int] = {p: 0 for p in self.ids}

    def clock_frontier(self) -> Dict[ProcessId, int]:
        return {p: c.frontier for p, c in self.my_clock.items()}

    def add_to_clock(self, dot: Dot) -> None:
        self.my_clock[dot.source].add(dot.sequence)

    def update_clock_of(
        self, from_: ProcessId, clock: Dict[ProcessId, int]
    ) -> None:
        """Join (max) — messages can be reordered (gc/clock.rs:51-63)."""
        current = self.all_but_me.setdefault(from_, dict(clock))
        for p, seq in clock.items():
            if seq > current.get(p, 0):
                current[p] = seq

    def _stable_clock(self) -> Dict[ProcessId, int]:
        if len(self.all_but_me) != self.n - 1:
            return {p: 0 for p in self.ids}
        stable = self.clock_frontier()
        for clock in self.all_but_me.values():
            for p in stable:
                stable[p] = min(stable[p], clock.get(p, 0))
        return stable

    def stable(self) -> List[Tuple[ProcessId, int, int]]:
        """gc/clock.rs:76-120."""
        new_stable = self._stable_clock()
        out = []
        for p, previous in self.previous_stable.items():
            start, end = previous + 1, new_stable[p]
            # never go backwards (reordered messages)
            new_stable[p] = max(new_stable[p], previous)
            if start <= end:
                out.append((p, start, end))
        self.previous_stable = new_stable
        return out


class Protocol(ABC):
    """protocol/mod.rs:41-115: the single interface implemented by every
    protocol; drivers (oracle simulator, and conceptually the device
    engine) only speak this interface."""

    def __init__(self, process_id: ProcessId, shard_id: ShardId, config: Config):
        self.to_processes_buf: List = []
        self.to_executors_buf: List = []

    # -- identity ------------------------------------------------------
    @abstractmethod
    def id(self) -> ProcessId: ...

    @abstractmethod
    def shard_id(self) -> ShardId: ...

    # -- lifecycle -----------------------------------------------------
    def periodic_events(self) -> List[Tuple[object, int]]:
        """(event, interval_ms) pairs to schedule at start (the second
        element of the reference's ``Protocol::new`` return)."""
        return []

    @staticmethod
    def event_worker(event) -> str:
        """Worker kind (``Message.WORKER`` vocabulary) a periodic event
        routes to under workers > 1 — the ``PeriodicEventIndex`` analog.
        Defaults to the GC worker; protocols with other periodic roles
        (Tempo's clock bump, FPaxos's acceptor GC) override."""
        return "gc"

    @abstractmethod
    def discover(
        self, processes: Sequence[Tuple[ProcessId, ShardId]]
    ) -> Tuple[bool, Dict[ShardId, ProcessId]]: ...

    @abstractmethod
    def submit(self, dot: Optional[Dot], cmd: Command, time: SysTime) -> None: ...

    @abstractmethod
    def handle(
        self,
        from_: ProcessId,
        from_shard_id: ShardId,
        msg: Message,
        time: SysTime,
    ) -> None: ...

    def handle_event(self, event: object, time: SysTime) -> None:
        pass

    def handle_executed(self, committed_and_executed, time: SysTime) -> None:
        """Periodic executed notification from the executor
        (mod.rs:97-104); only Caesar uses it."""

    # -- outboxes (pull-style, like to_processes/to_executors) ---------
    def to_processes(self) -> List:
        out, self.to_processes_buf = self.to_processes_buf, []
        return out

    def to_executors(self) -> List:
        out, self.to_executors_buf = self.to_executors_buf, []
        return out

    # -- static capabilities -------------------------------------------
    @staticmethod
    def parallel() -> bool:
        """Whether intra-process protocol state supports multiple workers."""
        return False

    @staticmethod
    def leaderless() -> bool:
        return True

    @abstractmethod
    def metrics(self) -> ProtocolMetrics: ...

"""Partial-replication (multi-shard) coordination helpers.

Capability parity with ``fantoch_ps/src/protocol/partial.rs``, shared by
Tempo and Atlas: forward a submit to the closest process of each other
shard touched by the command (partial.rs:8-35), and aggregate per-shard
commit data at the dot-owner process before the final ``MCommit``
(partial.rs:37-203).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Generic, Optional, Set, TypeVar

from ..core.command import Command
from ..core.ids import Dot, ProcessId
from .base import BaseProcess, ToSend

I = TypeVar("I")


def submit_actions(
    bp: BaseProcess,
    dot: Dot,
    cmd: Command,
    target_shard: bool,
    create_mforward_submit,
    to_processes: list,
) -> None:
    """partial.rs:8-35."""
    if not target_shard:
        return
    for shard_id in cmd.shards():
        if shard_id != bp.shard_id:
            to_processes.append(
                ToSend(
                    target={bp.closest_process(shard_id)},
                    msg=create_mforward_submit(dot, cmd),
                )
            )


@dataclass
class ShardsCommits(Generic[I]):
    """partial.rs:205-246."""

    process_id: ProcessId
    shard_count: int
    info: I
    participants: Set[ProcessId] = field(default_factory=set)

    def add(self, from_: ProcessId, add) -> bool:
        assert from_ not in self.participants
        self.participants.add(from_)
        add(self.info)
        return len(self.participants) == self.shard_count

    def update(self, update) -> None:
        update(self.info)


def _init_shards_commits(holder, bp: BaseProcess, shard_count: int, default):
    """``holder`` is the per-dot info record; its ``shards_commits`` field
    is created lazily (partial.rs:187-203)."""
    if holder.shards_commits is None:
        holder.shards_commits = ShardsCommits(
            bp.process_id, shard_count, default()
        )
    return holder.shards_commits


def mcommit_actions(
    bp: BaseProcess,
    holder,
    shard_count: int,
    dot: Dot,
    data1,
    data2,
    create_mcommit,
    create_mshard_commit,
    update_shards_commits_info,
    to_processes: list,
    default_info,
) -> None:
    """partial.rs:37-101."""
    if shard_count == 1:
        to_processes.append(
            ToSend(target=bp.all(), msg=create_mcommit(dot, data1, data2))
        )
        return
    shards_commits = _init_shards_commits(holder, bp, shard_count, default_info)
    shards_commits.update(
        lambda info: update_shards_commits_info(info, data2)
    )
    # aggregate at the dot-owner process (the client-targetted shard)
    to_processes.append(
        ToSend(target={dot.source}, msg=create_mshard_commit(dot, data1))
    )


def handle_mshard_commit(
    bp: BaseProcess,
    holder,
    shard_count: int,
    from_: ProcessId,
    dot: Dot,
    data,
    add_shards_commits_info,
    create_mshard_aggregated_commit,
    to_processes: list,
    default_info,
) -> None:
    """partial.rs:103-142."""
    shards_commits = _init_shards_commits(holder, bp, shard_count, default_info)
    done = shards_commits.add(
        from_, lambda info: add_shards_commits_info(info, data)
    )
    if done:
        to_processes.append(
            ToSend(
                target=set(shards_commits.participants),
                msg=create_mshard_aggregated_commit(dot, shards_commits.info),
            )
        )


def handle_mshard_aggregated_commit(
    bp: BaseProcess,
    holder,
    dot: Dot,
    data1,
    extract_mcommit_extra_data,
    create_mcommit,
    to_processes: list,
) -> None:
    """partial.rs:144-167."""
    shards_commits = holder.shards_commits
    assert shards_commits is not None, (
        f"no shards commit info when handling MShardAggregatedCommit {dot}"
    )
    holder.shards_commits = None
    data2 = extract_mcommit_extra_data(shards_commits.info)
    to_processes.append(
        ToSend(target=bp.all(), msg=create_mcommit(dot, data1, data2))
    )

"""Dependency structures shared by Atlas and EPaxos.

Capability parity with ``fantoch_ps/src/protocol/common/graph/``:
``Dependency`` (deps/keys/mod.rs:19-35), ``KeyDeps``/``SequentialKeyDeps``
(latest-dep-per-key map, sequential.rs:8-144) and ``QuorumDeps`` with the
two fast-path tests — threshold-union for Atlas and equal-union for
EPaxos (quorum.rs:8-98).

Device-engine note: the array twin encodes latest-dep-per-key as an
``[K]`` dot table and quorum deps as per-dep report counts; the
threshold/equality tests become masked count comparisons.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, FrozenSet, Optional, Set, Tuple

from ..core.command import Command
from ..core.ids import Dot, ShardId
from ..core.kvs import Key


@dataclass(frozen=True)
class Dependency:
    """deps/keys/mod.rs:19-35: a dot plus the shards that replicate it
    (``None`` for noops)."""

    dot: Dot
    shards: Optional[FrozenSet[ShardId]] = None

    @classmethod
    def from_cmd(cls, dot: Dot, cmd: Command) -> "Dependency":
        return cls(dot, frozenset(cmd.shards()))

    @classmethod
    def from_noop(cls, dot: Dot) -> "Dependency":
        return cls(dot, None)


class SequentialKeyDeps:
    """Latest-command-per-key conflict index (sequential.rs:8-144)."""

    def __init__(self, shard_id: ShardId):
        self.shard_id = shard_id
        self.latest_deps: Dict[Key, Dependency] = {}
        self.noop_latest_dep: Optional[Dependency] = None

    def add_cmd(
        self,
        dot: Dot,
        cmd: Command,
        past: Optional[Set[Dependency]] = None,
    ) -> Set[Dependency]:
        """Sets ``dot`` as the latest on each of the command's keys and
        returns its dependencies (the previous latests, plus ``past``)."""
        deps: Set[Dependency] = set(past) if past is not None else set()
        new_dep = Dependency.from_cmd(dot, cmd)
        for key in cmd.keys(self.shard_id):
            prev = self.latest_deps.get(key)
            if prev is not None:
                deps.add(prev)
            self.latest_deps[key] = new_dep
        if self.noop_latest_dep is not None:
            deps.add(self.noop_latest_dep)
        return deps

    def add_noop(self, dot: Dot) -> Set[Dependency]:
        """Noops depend on everything (sequential.rs:106-132)."""
        deps: Set[Dependency] = set()
        prev = self.noop_latest_dep
        self.noop_latest_dep = Dependency.from_noop(dot)
        if prev is not None:
            deps.add(prev)
        deps.update(self.latest_deps.values())
        return deps

    @staticmethod
    def parallel() -> bool:
        return False


class QuorumDeps:
    """Aggregates deps reported by fast-quorum members (quorum.rs:8-98)."""

    def __init__(self, fast_quorum_size: int):
        self.fast_quorum_size = fast_quorum_size
        self.participants: Set = set()
        self.threshold_deps: Dict[Dependency, int] = {}

    def add(self, process_id, deps: Set[Dependency]) -> None:
        assert len(self.participants) < self.fast_quorum_size
        self.participants.add(process_id)
        for dep in deps:
            self.threshold_deps[dep] = self.threshold_deps.get(dep, 0) + 1

    def all(self) -> bool:
        return len(self.participants) == self.fast_quorum_size

    def check_threshold_union(
        self, threshold: int
    ) -> Tuple[Set[Dependency], bool]:
        """Atlas fast path: union == threshold-union(f), i.e. every dep
        was reported at least ``threshold`` times (quorum.rs:46-64)."""
        assert self.all()
        equal_to_union = all(
            count >= threshold for count in self.threshold_deps.values()
        )
        return set(self.threshold_deps), equal_to_union

    def check_union(self) -> Tuple[Set[Dependency], bool]:
        """EPaxos fast path: all quorum members reported identical deps
        (quorum.rs:67-98)."""
        assert self.all()
        counts = set(self.threshold_deps.values())
        if not counts:
            equal = True
        elif len(counts) == 1:
            equal = counts.pop() == self.fast_quorum_size
        else:
            equal = False
        return set(self.threshold_deps), equal

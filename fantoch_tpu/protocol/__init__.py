"""Protocol layer: abstraction + oracle implementations.

Reference: ``fantoch/src/protocol/`` (abstraction) and
``fantoch_ps/src/protocol/`` (Tempo, Atlas, EPaxos, FPaxos, Caesar).
"""

from .base import (
    Action,
    BaseProcess,
    CommandsInfo,
    GCTrack,
    Message,
    Protocol,
    ProtocolMetrics,
    ProtocolMetricsKind,
    ToForward,
    ToSend,
)
from .atlas import Atlas
from .basic import Basic
from .caesar import Caesar
from .epaxos import EPaxos
from .fpaxos import FPaxos
from .tempo import Tempo, TempoAtomic

"""Protocol layer: abstraction + oracle implementations.

Reference: ``fantoch/src/protocol/`` (abstraction) and
``fantoch_ps/src/protocol/`` (Tempo, Atlas, EPaxos, FPaxos, Caesar).
"""

from .base import (
    Action,
    BaseProcess,
    CommandsInfo,
    GCTrack,
    Message,
    Protocol,
    ProtocolMetrics,
    ProtocolMetricsKind,
    ToForward,
    ToSend,
)
from .atlas import Atlas
from .basic import Basic
from .caesar import Caesar
from .epaxos import EPaxos
from .fpaxos import FPaxos
from .tempo import Tempo, TempoAtomic

# the one protocol-name -> host (oracle) class table; the CLI and the
# schedule fuzzer both resolve through here so a new protocol is one
# registration, not a drift hazard across hand-maintained copies
BY_NAME = {
    "basic": Basic,
    "fpaxos": FPaxos,
    "tempo": Tempo,
    "tempo_atomic": TempoAtomic,
    "atlas": Atlas,
    "epaxos": EPaxos,
    "caesar": Caesar,
}

"""Tempo's timestamp structures: key clocks, votes, quorum clock
aggregation.

Capability parity with ``fantoch_ps/src/protocol/common/table/``:

- ``VoteRange``/``Votes``: per-key vote ranges with contiguous-range
  compression (votes.rs:9-160);
- ``SequentialKeyClocks``: per-key u64 clocks; ``proposal`` bumps to
  ``max(min_clock, max-key-clock + 1)`` and votes the vacated range
  (clocks/keys/sequential.rs:36-104);
- ``QuorumClocks``: max clock + occurrence count over a fast quorum
  (clocks/quorum.rs:7-60).

The reference's ``AtomicKeyClocks``/``LockedKeyClocks`` exist to allow
multiple intra-process workers to bump clocks concurrently; the TPU
engine gets its concurrency from batching whole configurations, so the
sequential variant is the default — and :class:`NativeAtomicKeyClocks`
(below) is the AtomicKeyClocks twin over the native C++ CAS map, which
``TempoAtomic`` swaps in for the run layer's worker axis.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from ..core.command import Command
from ..core.ids import ProcessId, ShardId
from ..core.kvs import Key


@dataclass
class VoteRange:
    """Votes ``start..=end`` by process ``by`` (votes.rs:100-160)."""

    by: ProcessId
    start: int
    end: int

    def __post_init__(self) -> None:
        assert self.start <= self.end

    def try_compress(self, other: "VoteRange") -> Optional["VoteRange"]:
        """Extend self with ``other`` if contiguous; returns ``other`` back
        when it couldn't be compressed (votes.rs:131-147)."""
        assert self.by == other.by
        if self.end + 1 == other.start:
            self.end = other.end
            return None
        return other


class Votes:
    """key -> list of VoteRange (votes.rs:8-97)."""

    __slots__ = ("votes",)

    def __init__(self) -> None:
        self.votes: Dict[Key, List[VoteRange]] = {}

    def add(self, key: Key, vote: VoteRange) -> None:
        current = self.votes.setdefault(key, [])
        if current:
            rest = current[-1].try_compress(vote)
            if rest is not None:
                current.append(rest)
        else:
            current.append(vote)

    def set_(self, key: Key, key_votes: List[VoteRange]) -> None:
        assert key not in self.votes
        self.votes[key] = key_votes

    def merge(self, remote: "Votes") -> None:
        for key, key_votes in remote.votes.items():
            self.votes.setdefault(key, []).extend(key_votes)

    def get(self, key: Key) -> Optional[List[VoteRange]]:
        return self.votes.get(key)

    def remove(self, key: Key) -> List[VoteRange]:
        return self.votes.pop(key, [])

    def __len__(self) -> int:
        return len(self.votes)

    def is_empty(self) -> bool:
        return not self.votes

    def items(self):
        return self.votes.items()

    def __repr__(self) -> str:
        return f"Votes({self.votes!r})"


class SequentialKeyClocks:
    """clocks/keys/sequential.rs:9-104."""

    def __init__(self, process_id: ProcessId, shard_id: ShardId):
        self.process_id = process_id
        self.shard_id = shard_id
        self.clocks: Dict[Key, int] = {}

    def init_clocks(self, cmd: Command) -> None:
        for key in cmd.keys(self.shard_id):
            self.clocks.setdefault(key, 0)

    def proposal(self, cmd: Command, min_clock: int) -> Tuple[int, Votes]:
        """Bump to ``max(min_clock, highest-key-clock + 1)`` and vote the
        vacated ranges on every key (sequential.rs:36-47)."""
        clock = max(min_clock, self._clock(cmd) + 1)
        votes = Votes()
        self.detached(cmd, clock, votes)
        return clock, votes

    def detached(self, cmd: Command, up_to: int, votes: Votes) -> None:
        for key in cmd.keys(self.shard_id):
            self._maybe_bump(key, up_to, votes)

    def detached_all(self, up_to: int, votes: Votes) -> None:
        for key in list(self.clocks):
            self._maybe_bump(key, up_to, votes)

    @staticmethod
    def parallel() -> bool:
        return False

    def _clock(self, cmd: Command) -> int:
        return max(
            (self.clocks.get(key, 0) for key in cmd.keys(self.shard_id)),
            default=0,
        )

    def _maybe_bump(self, key: Key, up_to: int, votes: Votes) -> None:
        current = self.clocks.get(key, 0)
        if current < up_to:
            votes.add(key, VoteRange(self.process_id, current + 1, up_to))
            self.clocks[key] = up_to


class NativeAtomicKeyClocks:
    """The ``AtomicKeyClocks`` variant (common/table/clocks/keys/
    atomic.rs:13-90), backed by the native C++ sharded CAS map
    (fantoch_tpu/native/keyclocks.cpp). Same observable semantics as
    :class:`SequentialKeyClocks` single-threaded; the clock bumps are
    lock-free CAS loops with the GIL released, and key interning takes
    a short lock on first sighting, so the structure stays safe if the
    runtime ever moves workers onto OS threads. The native key table
    is fixed-capacity (``$FANTOCH_NATIVE_KEYS``, default 65,536
    distinct keys); exhaustion raises instead of degrading."""

    def __init__(self, process_id: ProcessId, shard_id: ShardId,
                 capacity: Optional[int] = None):
        import os
        import threading

        from ..native.keyclocks import AtomicKeyClocks

        if capacity is None:
            capacity = int(
                os.environ.get("FANTOCH_NATIVE_KEYS", str(1 << 16))
            )
        self.process_id = process_id
        self.shard_id = shard_id
        self._kc = AtomicKeyClocks(capacity)
        self._ids: Dict[Key, int] = {}
        self._names: List[Key] = []
        self._intern_lock = threading.Lock()

    def _id(self, key: Key) -> int:
        i = self._ids.get(key)  # dict reads are GIL-atomic
        if i is None:
            with self._intern_lock:
                i = self._ids.get(key)
                if i is None:
                    i = len(self._names)
                    self._names.append(key)
                    self._ids[key] = i
        return i

    def init_clocks(self, cmd: Command) -> None:
        for key in cmd.keys(self.shard_id):
            self._id(key)

    def _add(self, votes: Votes, triples) -> None:
        for kid, start, end in triples:
            votes.add(
                self._names[kid], VoteRange(self.process_id, start, end)
            )

    def proposal(self, cmd: Command, min_clock: int) -> Tuple[int, Votes]:
        ids = [self._id(k) for k in cmd.keys(self.shard_id)]
        clock, triples = self._kc.proposal(ids, min_clock)
        votes = Votes()
        self._add(votes, triples)
        return clock, votes

    def detached(self, cmd: Command, up_to: int, votes: Votes) -> None:
        ids = [self._id(k) for k in cmd.keys(self.shard_id)]
        if ids:
            self._add(votes, self._kc.detached(ids, up_to))

    def detached_all(self, up_to: int, votes: Votes) -> None:
        ids = list(range(len(self._names)))
        if ids:
            self._add(votes, self._kc.detached(ids, up_to))

    @staticmethod
    def parallel() -> bool:
        return True


# canonical name used by the protocol; TempoAtomic swaps in the native
# variant (the reference selects per-binary, bin/tempo_atomic.rs)
KeyClocks = SequentialKeyClocks


class QuorumClocks:
    """Max-clock/count aggregation over fast-quorum replies
    (clocks/quorum.rs:7-60)."""

    def __init__(self, fast_quorum_size: int):
        self.fast_quorum_size = fast_quorum_size
        self.participants: set = set()
        self.max_clock = 0
        self.max_clock_count = 0

    def add(self, process_id: ProcessId, clock: int) -> Tuple[int, int]:
        assert len(self.participants) < self.fast_quorum_size
        self.participants.add(process_id)
        if clock > self.max_clock:
            self.max_clock = clock
            self.max_clock_count = 1
        elif clock == self.max_clock:
            self.max_clock_count += 1
        return self.max_clock, self.max_clock_count

    def all(self) -> bool:
        return len(self.participants) == self.fast_quorum_size

"""Paxos engines: single-decree ``Synod`` and multi-decree ``MultiSynod``.

Capability parity with ``fantoch_ps/src/protocol/common/synod/``:

- ``Synod`` (single.rs:11-130): single-decree Flexible Paxos used for the
  slow path of Tempo/Atlas/EPaxos; supports ``skip_prepare`` since the
  initial coordinator owns its ballot.
- ``MultiSynod`` (multi.rs:18-306): multi-decree engine for FPaxos, folding
  leader + per-slot commanders + acceptor roles into one process; phase-2
  waits for f+1 accepts.
- ``SynodGCTrack`` (gc.rs): slot-stability tracking (min committed frontier
  across processes).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Generic, Optional, Set, Tuple, TypeVar

from ..core.ids import ProcessId

V = TypeVar("V")
Ballot = int
Slot = int


# ---------------------------------------------------------------------------
# single-decree synod (single.rs)
# ---------------------------------------------------------------------------


# Synod message kinds (single.rs:11-20); messages are tagged tuples:
#   (S_CHOSEN, value) | (S_PREPARE, b) | (S_ACCEPT, b, value)
#   | (S_PROMISE, b, (accepted_ballot, accepted_value)) | (S_ACCEPTED, b)
S_CHOSEN = "chosen"
S_PREPARE = "prepare"
S_ACCEPT = "accept"
S_PROMISE = "promise"
S_ACCEPTED = "accepted"


class Synod(Generic[V]):
    """Single-decree Flexible Paxos (single.rs:22-137).

    Phase-1 waits n-f promises; phase-2 waits f+1 accepts.
    ``skip_prepare`` generates the first ballot (= the coordinator's
    process id) without a prepare phase — safe because any prepared ballot
    is > n (single.rs:83-89).
    """

    def __init__(
        self,
        process_id: ProcessId,
        n: int,
        f: int,
        proposal_gen,
        initial_value: V,
    ):
        self.process_id = process_id
        self.n = n
        self.f = f
        self.proposal_gen = proposal_gen
        # acceptor state (single.rs:365-375)
        self.acc_ballot: Ballot = 0
        self.acc_accepted: Tuple[Ballot, V] = (0, initial_value)
        # proposer state (single.rs:142-163)
        self.prop_ballot: Ballot = 0
        self.promises: Dict[ProcessId, Tuple[Ballot, V]] = {}
        self.accepts: Set[ProcessId] = set()
        self.proposal: Optional[V] = None
        self.chosen = False

    # -- public API (single.rs:30-137) ---------------------------------

    def set_if_not_accepted(self, value_gen) -> bool:
        if self.acc_ballot == 0:
            self.acc_accepted = (0, value_gen())
            return True
        return False

    def value(self) -> V:
        return self.acc_accepted[1]

    def new_prepare(self):
        assert self.acc_ballot >= self.prop_ballot
        round_ = self.acc_ballot // self.n
        self.prop_ballot = self.process_id + self.n * (round_ + 1)
        self.promises = {}
        self.accepts = set()
        self.proposal = None
        return (S_PREPARE, self.prop_ballot)

    def skip_prepare(self) -> Ballot:
        assert self.acc_ballot == 0
        self.prop_ballot = self.process_id
        return self.prop_ballot

    def handle(self, from_: ProcessId, msg):
        kind = msg[0]
        if kind == S_CHOSEN:
            self.chosen = True
            self.acc_accepted = (0, msg[1])
            return None
        if kind == S_PREPARE:
            return self._chosen() or self._acc_handle_prepare(msg[1])
        if kind == S_ACCEPT:
            return self._chosen() or self._acc_handle_accept(msg[1], msg[2])
        if kind == S_PROMISE:
            return self._prop_handle_promise(from_, msg[1], msg[2])
        if kind == S_ACCEPTED:
            return self._prop_handle_accepted(from_, msg[1])
        raise TypeError(f"unexpected synod message {msg!r}")

    def _chosen(self):
        if self.chosen:
            return (S_CHOSEN, self.value())
        return None

    # -- acceptor (single.rs:377-430) ----------------------------------

    def _acc_handle_prepare(self, b: Ballot):
        if b > self.acc_ballot:
            self.acc_ballot = b
            return (S_PROMISE, b, self.acc_accepted)
        return None

    def _acc_handle_accept(self, b: Ballot, value: V):
        if b >= self.acc_ballot:
            self.acc_ballot = b
            self.acc_accepted = (b, value)
            return (S_ACCEPTED, b)
        return None

    # -- proposer (single.rs:246-358) ----------------------------------

    def _prop_handle_promise(self, from_, b: Ballot, accepted):
        if self.prop_ballot != b:
            return None
        self.promises[from_] = accepted
        if len(self.promises) != self.n - self.f:
            return None
        promises, self.promises = self.promises, {}
        self.accepts = set()
        highest_ballot, highest_from = max(
            ((ballot, pid) for pid, (ballot, _v) in promises.items()),
        )
        if highest_ballot == 0:
            values = {pid: v for pid, (_b, v) in promises.items()}
            proposal = self.proposal_gen(values)
        else:
            proposal = promises[highest_from][1]
        self.proposal = proposal
        return (S_ACCEPT, b, proposal)

    def _prop_handle_accepted(self, from_, b: Ballot):
        if self.prop_ballot != b:
            return None
        self.accepts.add(from_)
        if len(self.accepts) != self.f + 1:
            return None
        proposal, self.proposal = self.proposal, None
        self.promises = {}
        self.accepts = set()
        if proposal is None:
            # still at the first (skip-prepare) ballot: the value is the one
            # our own acceptor accepted (single.rs:336-350)
            acc_ballot, acc_value = self.acc_accepted
            assert acc_ballot == self.process_id, (
                "there should have been a proposal before a value is chosen"
            )
            proposal = acc_value
        return (S_CHOSEN, proposal)


# ---------------------------------------------------------------------------
# multi-decree synod (multi.rs)
# ---------------------------------------------------------------------------


@dataclass
class _Leader:
    """multi.rs:169-210."""

    process_id: ProcessId
    is_leader: bool
    ballot: Ballot
    last_slot: Slot = 0

    def try_submit(self) -> Optional[Tuple[Ballot, Slot]]:
        if not self.is_leader:
            return None
        self.last_slot += 1
        return self.ballot, self.last_slot


@dataclass
class _Commander(Generic[V]):
    """Watches accepts for one slot (multi.rs:212-259)."""

    f: int
    ballot: Ballot
    value: V
    accepts: Set[ProcessId] = field(default_factory=set)

    def handle_accepted(self, from_: ProcessId, ballot: Ballot) -> bool:
        if self.ballot != ballot:
            return False
        self.accepts.add(from_)
        return len(self.accepts) == self.f + 1


class _Acceptor(Generic[V]):
    """multi.rs:261-320: joins the initial leader's ballot at bootstrap."""

    def __init__(self, initial_leader: ProcessId):
        self.ballot: Ballot = initial_leader
        self.accepted: Dict[Slot, Tuple[Ballot, V]] = {}

    def handle_accept(self, b: Ballot, slot: Slot, value: V) -> bool:
        if b >= self.ballot:
            self.ballot = b
            self.accepted[slot] = (b, value)
            return True
        return False

    def gc(self, stable: Tuple[int, int]) -> int:
        start, end = stable
        count = 0
        for slot in range(start, end + 1):
            if self.accepted.pop(slot, None) is not None:
                count += 1
        return count

    def gc_single(self, slot: Slot) -> None:
        self.accepted.pop(slot, None)


# outputs of MultiSynod.submit/handle — tagged tuples mirroring
# MultiSynodMessage (multi.rs:18-31)
SPAWN_COMMANDER = "spawn_commander"
FORWARD_SUBMIT = "forward_submit"
ACCEPT = "accept"
ACCEPTED = "accepted"
CHOSEN = "chosen"


class MultiSynod(Generic[V]):
    """multi.rs:33-166."""

    def __init__(
        self, process_id: ProcessId, initial_leader: ProcessId, n: int, f: int
    ):
        self.n = n
        self.f = f
        self.leader = _Leader(
            process_id,
            is_leader=(process_id == initial_leader),
            ballot=initial_leader if process_id == initial_leader else 0,
        )
        self.acceptor: _Acceptor[V] = _Acceptor(initial_leader)
        self.commanders: Dict[Slot, _Commander[V]] = {}

    def submit(self, value: V):
        res = self.leader.try_submit()
        if res is not None:
            ballot, slot = res
            return (SPAWN_COMMANDER, ballot, slot, value)
        return (FORWARD_SUBMIT, value)

    def handle_spawn_commander(self, ballot: Ballot, slot: Slot, value: V):
        assert slot not in self.commanders
        self.commanders[slot] = _Commander(self.f, ballot, value)
        return (ACCEPT, ballot, slot, value)

    def handle_accept(self, ballot: Ballot, slot: Slot, value: V):
        if self.acceptor.handle_accept(ballot, slot, value):
            return (ACCEPTED, ballot, slot)
        return None

    def handle_accepted(self, from_: ProcessId, ballot: Ballot, slot: Slot):
        commander = self.commanders.get(slot)
        if commander is None:
            return None
        if commander.handle_accepted(from_, ballot):
            value = self.commanders.pop(slot).value
            return (CHOSEN, slot, value)
        return None

    def gc(self, stable: Tuple[int, int]) -> int:
        return self.acceptor.gc(stable)

    def gc_single(self, slot: Slot) -> None:
        self.acceptor.gc_single(slot)


class SynodGCTrack:
    """Slot-stability tracking for FPaxos (synod/gc.rs:7-77)."""

    def __init__(self, process_id: ProcessId, n: int):
        from ..protocol.base import AEClockSet

        self.process_id = process_id
        self.n = n
        self.committed_set = AEClockSet()
        self.all_but_me: Dict[ProcessId, int] = {}
        self.previous_stable = 0

    def commit(self, slot: Slot) -> None:
        self.committed_set.add(slot)

    def committed(self) -> int:
        return self.committed_set.frontier

    def committed_by(self, from_: ProcessId, committed: int) -> None:
        self.all_but_me[from_] = committed

    def _stable_slot(self) -> int:
        if len(self.all_but_me) != self.n - 1:
            return 0
        return min(
            [self.committed_set.frontier, *self.all_but_me.values()]
        )

    def stable(self) -> Tuple[int, int]:
        new_stable = self._stable_slot()
        slot_range = (self.previous_stable + 1, new_stable)
        self.previous_stable = new_stable
        return slot_range

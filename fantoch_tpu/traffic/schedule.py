"""Traffic schedule spec + compilation to per-epoch ctx tables.

A schedule is piecewise over the per-client *command sequence* axis —
the closed-loop client's logical clock (seqs are 1-based, exactly the
engine's SUBMIT payload seq). Each :class:`TrafficPhase` covers a fixed
number of commands and pins the workload knobs for that span:

* ``conflict_rate`` / ``pool_size`` — the ConflictPool draw parameters
  (key_gen.rs:96-110), now time-indexed;
* ``pool_base`` — hot-key churn: the shared pool covers keys
  ``[pool_base, pool_base + pool_size)``, so rotating the base moves
  the hot key set. Private keys live above every epoch's pool
  (``pool_span + client``) so churn never aliases them;
* ``think_ms`` — diurnal load: a delay between a command's completion
  and the next SUBMIT leaving the client, lowering the issue rate in
  off-peak epochs (0 = the closed loop's back-to-back issue);
* ``read_pct`` — read/write mix. The device engine's conflict
  detection is key-based, so the mix does not change latency results;
  the host oracle mirror draws the per-command read flag from the same
  counter-based stream so both sides agree on which commands are reads
  (docs/TRAFFIC.md spells out this guarantee boundary).

``compile(commands_per_client)`` lowers a schedule to fixed-shape numpy
ctx tables: a ``[T]`` command-seq → epoch index (``T = budget + 2``,
column 0 unused like the key table) plus one ``[E]`` array per knob —
**per-epoch, not per-seq**, so the in-loop footprint the GL202 VMEM
gate sees stays bounded by the (small) epoch count, not the command
budget (docs/PERF.md). Epoch boundaries land on the exact command seq —
the seq → epoch table is exact by construction, there is no ±1 rounding
— which the differential tests pin.

A *flat* schedule (one effective phase, no think, no rotation) is
collapsed by ``make_lane`` into the legacy static ctx path — no tables,
bit-identical jaxpr — so the seed-warmed XLA cache and the GL005 gating
pin survive (engine/spec.py).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional, Tuple, Union

import numpy as np


@dataclass(frozen=True)
class TrafficPhase:
    """One epoch of the schedule, covering ``commands`` command seqs.

    ``zipf_coef`` is the per-epoch Zipf skew for lanes running the
    ``KeyGen::Zipf`` workload: 0.0 (the default) means "the lane's base
    coefficient", a nonzero value overrides it for this epoch — so a
    schedule can move the key-popularity skew over time the same way it
    moves the conflict pool. Pool-only lanes ignore it entirely."""

    commands: int
    conflict_rate: int
    pool_size: int = 1
    pool_base: int = 0
    think_ms: int = 0
    read_pct: int = 0
    zipf_coef: float = 0.0

    def __post_init__(self) -> None:
        assert self.commands >= 1, "a phase must cover >= 1 command"
        assert 0 <= self.conflict_rate <= 100, self.conflict_rate
        assert self.pool_size >= 1, self.pool_size
        assert self.pool_base >= 0, self.pool_base
        assert self.think_ms >= 0, self.think_ms
        assert 0 <= self.read_pct <= 100, self.read_pct
        assert self.zipf_coef >= 0.0, self.zipf_coef

    def knobs(self) -> Tuple[int, int, int, int, float]:
        """The parameters whose variation makes a schedule non-flat
        (read_pct rides along in the tables but never reaches the
        engine's arithmetic, so a read-mix-only schedule is still
        flat for the device)."""
        return (
            self.conflict_rate, self.pool_size, self.pool_base,
            self.think_ms, self.zipf_coef,
        )


@dataclass(frozen=True)
class TrafficSchedule:
    """A named piecewise schedule. ``cycle=True`` repeats the phase
    pattern over the whole command budget (a diurnal day repeating);
    ``cycle=False`` extends the last phase forever (a one-shot ramp).

    Hashable by value so it can ride inside the frozen
    :class:`~fantoch_tpu.client.key_gen.DeviceStream` dataclass."""

    name: str
    phases: Tuple[TrafficPhase, ...]
    cycle: bool = False

    def __post_init__(self) -> None:
        assert self.phases, "a schedule needs at least one phase"

    # -- host helpers (the oracle mirror uses exactly these) -----------

    @property
    def pattern_len(self) -> int:
        return sum(p.commands for p in self.phases)

    def epoch_of(self, seq: int) -> int:
        """Phase index of 1-based command ``seq`` (unbounded axis:
        cycling or last-phase-extends past the pattern)."""
        assert seq >= 1, "command seqs are 1-based"
        idx = (seq - 1) % self.pattern_len if self.cycle else min(
            seq - 1, self.pattern_len - 1
        )
        for e, p in enumerate(self.phases):
            if idx < p.commands:
                return e
            idx -= p.commands
        return len(self.phases) - 1  # unreachable

    def phase_at(self, seq: int) -> TrafficPhase:
        return self.phases[self.epoch_of(seq)]

    def think_ms(self, seq: int) -> int:
        """The submit delay the oracle runner adds for command ``seq``
        — the bit-exact mirror of the engine's per-epoch think gather
        (engine/core.py ``_lane_step`` step 5)."""
        return self.phase_at(seq).think_ms

    def pool_span(self) -> int:
        """First key above every epoch's shared pool: private client
        keys are ``pool_span + client`` (the static path's
        ``pool_size + client`` generalized over rotation)."""
        return max(p.pool_base + p.pool_size for p in self.phases)

    def is_flat(self) -> bool:
        """True when the schedule is indistinguishable from the static
        ConflictPool path: one effective knob tuple, no think delay, no
        pool rotation, no zipf override. Flat schedules compile to NO
        ctx tables."""
        knobs = {p.knobs() for p in self.phases}
        if len(knobs) != 1:
            return False
        (conflict, _size, base, think, zcoef) = next(iter(knobs))
        del conflict
        return base == 0 and think == 0 and zcoef == 0.0

    # -- device lowering ----------------------------------------------

    def compile(self, commands_per_client: int) -> Dict[str, np.ndarray]:
        """Lower to the engine's ctx tables. ``traffic_seq_epoch`` is
        indexed by command seq (1-based; entry 0 mirrors seq 1, like
        the key table's unused column); length ``budget + 2`` matches
        the key table so the engine's index clamp never binds for a
        real command."""
        E = len(self.phases)
        T = commands_per_client + 2
        seq_epoch = np.zeros((T,), np.int32)
        seq_epoch[0] = self.epoch_of(1)
        for s in range(1, T):
            seq_epoch[s] = self.epoch_of(s)
        return {
            "traffic_seq_epoch": seq_epoch,
            "traffic_conflict": np.asarray(
                [p.conflict_rate for p in self.phases], np.int32
            ),
            "traffic_pool_base": np.asarray(
                [p.pool_base for p in self.phases], np.int32
            ),
            "traffic_pool_size": np.asarray(
                [p.pool_size for p in self.phases], np.int32
            ),
            "traffic_think": np.asarray(
                [p.think_ms for p in self.phases], np.int32
            ),
            "traffic_read_pct": np.asarray(
                [p.read_pct for p in self.phases], np.int32
            ),
            "traffic_pool_span": np.int32(self.pool_span()),
        }

    def zipf_tables(
        self, base_coefficient: float, total_keys: int
    ) -> Dict[str, np.ndarray]:
        """The epoch-varying ``KeyGen::Zipf`` extension: one cumulative
        weight row per phase, ``[E, K]``, row ``e`` built from phase
        e's ``zipf_coef`` (0.0 = the lane's base coefficient). The
        engine's ``gen_key`` gathers the row for the command's epoch
        before the searchsorted draw; the host oracle mirror
        (client/key_gen.py) builds the identical table from the same
        schedule, so the two sides agree bit-exactly."""
        from ..client.key_gen import zipf_weights

        rows = []
        for p in self.phases:
            coef = p.zipf_coef if p.zipf_coef > 0.0 else base_coefficient
            rows.append(
                np.cumsum(zipf_weights(total_keys, coef)).astype(
                    np.float32
                )
            )
        return {"traffic_zipf_cum": np.stack(rows, axis=0)}

    def has_zipf_override(self) -> bool:
        return any(p.zipf_coef > 0.0 for p in self.phases)

    def meta(self) -> dict:
        """Compact JSON-able lane metadata (LaneSpec.traffic_meta)."""
        return {
            "name": self.name,
            "epochs": len(self.phases),
            "cycle": bool(self.cycle),
            "pattern_commands": self.pattern_len,
            "pool_span": self.pool_span(),
        }

    # -- JSON round-trip (campaign grids, repro artifacts) ------------

    def to_json(self) -> dict:
        # zipf_coef is emitted only when set so every pre-zipf schedule
        # round-trips byte-identically (repro artifacts, campaign
        # journals, checkpoint meta all compare canonical JSON)
        return {
            "name": self.name,
            "cycle": bool(self.cycle),
            "phases": [
                {
                    "commands": p.commands,
                    "conflict_rate": p.conflict_rate,
                    "pool_size": p.pool_size,
                    "pool_base": p.pool_base,
                    "think_ms": p.think_ms,
                    "read_pct": p.read_pct,
                    **(
                        {"zipf_coef": p.zipf_coef}
                        if p.zipf_coef > 0.0
                        else {}
                    ),
                }
                for p in self.phases
            ],
        }

    @staticmethod
    def from_json(obj: dict) -> "TrafficSchedule":
        return TrafficSchedule(
            name=str(obj["name"]),
            cycle=bool(obj.get("cycle", False)),
            phases=tuple(
                TrafficPhase(**phase) for phase in obj["phases"]
            ),
        )


TrafficLike = Union[None, str, dict, TrafficSchedule]


def resolve_traffic(
    spec: TrafficLike,
    *,
    conflict: int,
    pool_size: int = 1,
    commands: int,
) -> Optional[TrafficSchedule]:
    """Resolve a traffic spec to a schedule (or None = static path).

    ``spec`` may be a preset name from :data:`fantoch_tpu.registry
    .TRAFFIC_PRESETS` (parameterized by the lane's base conflict rate /
    pool size / command budget, so the sweep's conflict axis composes
    with the traffic axis), a JSON schedule dict, an already-built
    :class:`TrafficSchedule`, or None. ``"flat"`` resolves to None —
    the static path, by construction."""
    if spec is None or isinstance(spec, TrafficSchedule):
        return spec
    if isinstance(spec, dict):
        return TrafficSchedule.from_json(spec)
    from ..registry import traffic_preset

    obj = traffic_preset(
        str(spec), conflict=conflict, pool_size=pool_size,
        commands=commands,
    )
    return None if obj is None else TrafficSchedule.from_json(obj)


def traffic_key_capacity(
    specs,
    *,
    conflict: int,
    pool_size: int,
    commands: int,
    clients: int,
) -> Optional[int]:
    """Protocol key capacity covering every schedule in ``specs`` (an
    iterable of preset names / schedules / None): private keys sit at
    ``pool_span + client``, so a rotated pool needs
    ``max(pool_span) + clients`` keys — the single source of the
    invariant ``make_lane`` asserts (``span + live_clients <= K``),
    shared by the CLI sweep and the campaign manager so the two can
    never drift.

    Returns None when every spec resolves flat: callers then keep
    their legacy default capacity (``dev_protocol``'s ``1 + clients``),
    preserving the pre-traffic lane shapes bit-for-bit so old campaign
    journals and checkpoints resume unchanged."""
    span: Optional[int] = None
    for spec in specs:
        sched = resolve_traffic(
            spec, conflict=conflict, pool_size=pool_size,
            commands=commands,
        )
        if sched is not None:
            span = max(span if span is not None else pool_size,
                       sched.pool_span())
    return None if span is None else span + clients


# ----------------------------------------------------------------------
# Open-loop arrival schedules (docs/TRAFFIC.md "Open-loop arrivals").
#
# A closed-loop client arms command s+1 only when command s completes —
# the one workload shape planet-scale services never have (Schroeder et
# al., NSDI'06: closed-loop load generation hides saturation and
# suffers coordinated omission). An ArrivalSchedule instead timestamps
# every command by a seeded arrival process *independent of
# completion*: per-client exponential inter-arrival gaps whose mean is
# piecewise over the command-seq axis, exactly like the traffic knobs.
# The whole arrival table is drawn host-side once per lane
# (``arrival_table``) and shipped verbatim to both the device engine
# and the host oracle, so the two mirror bit-exactly by construction.
# ----------------------------------------------------------------------

# salt for the per-client arrival PRNG streams, so arrival draws never
# collide with any other seeded stream derived from the lane seed
ARRIVAL_STREAM_SALT = 0x0A21


@dataclass(frozen=True)
class ArrivalPhase:
    """One epoch of an arrival schedule: ``commands`` command seqs
    arriving with exponential gaps of mean ``mean_gap_ms`` (>= 1; the
    engine clock is integer ms and a 0-mean phase would collapse every
    arrival onto one tick)."""

    commands: int
    mean_gap_ms: int

    def __post_init__(self) -> None:
        assert self.commands >= 1, "a phase must cover >= 1 command"
        assert self.mean_gap_ms >= 1, self.mean_gap_ms


@dataclass(frozen=True)
class ArrivalSchedule:
    """A named piecewise arrival-rate schedule over the per-client
    command-seq axis. ``cycle=True`` repeats the pattern over the whole
    budget; ``cycle=False`` extends the last phase forever."""

    name: str
    phases: Tuple[ArrivalPhase, ...]
    cycle: bool = False

    def __post_init__(self) -> None:
        assert self.phases, "a schedule needs at least one phase"

    @property
    def pattern_len(self) -> int:
        return sum(p.commands for p in self.phases)

    def epoch_of(self, seq: int) -> int:
        """Phase index of 1-based command ``seq`` (same axis semantics
        as :meth:`TrafficSchedule.epoch_of`)."""
        assert seq >= 1, "command seqs are 1-based"
        idx = (seq - 1) % self.pattern_len if self.cycle else min(
            seq - 1, self.pattern_len - 1
        )
        for e, p in enumerate(self.phases):
            if idx < p.commands:
                return e
            idx -= p.commands
        return len(self.phases) - 1  # unreachable

    def mean_gap_ms(self, seq: int) -> int:
        return self.phases[self.epoch_of(seq)].mean_gap_ms

    def scale(self, load_pct: int) -> "ArrivalSchedule":
        """The offered-load axis: scale every phase's mean gap so the
        arrival *rate* becomes ``load_pct`` percent of this schedule's
        (gap 100/load times the base, floored at the 1 ms tick). A
        scaled schedule is renamed ``name@load`` so checkpoint and
        campaign meta refuse a resumed sweep whose load drifted — by
        name, before any bit compare."""
        assert load_pct >= 1, load_pct
        if load_pct == 100:
            return self
        return ArrivalSchedule(
            name=f"{self.name}@{load_pct}",
            cycle=self.cycle,
            phases=tuple(
                ArrivalPhase(
                    commands=p.commands,
                    mean_gap_ms=max(
                        1, round(p.mean_gap_ms * 100 / load_pct)
                    ),
                )
                for p in self.phases
            ),
        )

    def arrival_table(
        self, *, seed: int, clients: int, commands: int
    ) -> np.ndarray:
        """The per-lane arrival-time table: ``[C, T]`` i32 cumulative
        arrival times (ms), ``T = commands + 2`` with column 0 unused
        so 1-based command seqs index directly (the key-table layout).
        Client c's gaps come from its own counter-salted stream
        ``default_rng([seed, SALT, c])`` — insertion-ordered and
        independent of draw interleaving, the GL402 discipline — with
        the gap before command s drawn exponential with the mean of
        s's epoch, floored at 1 ms. ``A[c, 1]`` is the first command's
        arrival (the first gap after t=0); the engine and the host
        oracle both consume THIS array verbatim, which is the whole
        bit-exactness argument."""
        T = commands + 2
        table = np.zeros((clients, T), np.int64)
        for c in range(clients):
            rng = np.random.default_rng(
                [int(seed), ARRIVAL_STREAM_SALT, int(c)]
            )
            t = 0
            for s in range(1, T):
                gap = max(
                    1,
                    int(round(rng.exponential(
                        self.mean_gap_ms(s)
                    ))),
                )
                t += gap
                table[c, s] = t
        table[:, 0] = table[:, 1]  # unused column mirrors seq 1
        assert int(table.max()) < np.iinfo(np.int32).max
        return table.astype(np.int32)

    def meta(self) -> dict:
        """Compact JSON-able lane metadata (LaneSpec.arrival_meta)."""
        return {
            "name": self.name,
            "epochs": len(self.phases),
            "cycle": bool(self.cycle),
            "pattern_commands": self.pattern_len,
            "mean_gaps_ms": [p.mean_gap_ms for p in self.phases],
        }

    def to_json(self) -> dict:
        return {
            "name": self.name,
            "cycle": bool(self.cycle),
            "phases": [
                {
                    "commands": p.commands,
                    "mean_gap_ms": p.mean_gap_ms,
                }
                for p in self.phases
            ],
        }

    @staticmethod
    def from_json(obj: dict) -> "ArrivalSchedule":
        return ArrivalSchedule(
            name=str(obj["name"]),
            cycle=bool(obj.get("cycle", False)),
            phases=tuple(
                ArrivalPhase(**phase) for phase in obj["phases"]
            ),
        )


ArrivalLike = Union[None, str, dict, "ArrivalSchedule"]


def resolve_arrivals(
    spec: ArrivalLike,
    *,
    mean_gap_ms: int,
    commands: int,
    load_pct: int = 100,
) -> Optional[ArrivalSchedule]:
    """Resolve an arrival spec to a schedule (or None = closed loop).

    ``spec`` may be a preset name from :data:`fantoch_tpu.registry
    .ARRIVAL_PRESETS` (parameterized by the lane's base mean gap and
    command budget), a JSON schedule dict, an already-built
    :class:`ArrivalSchedule`, or None. ``"closed"`` resolves to None —
    the closed-loop static path, by construction. ``load_pct`` applies
    the offered-load scaling (:meth:`ArrivalSchedule.scale`) after
    resolution."""
    if spec is None:
        return None
    if isinstance(spec, ArrivalSchedule):
        return spec.scale(load_pct)
    if isinstance(spec, dict):
        return ArrivalSchedule.from_json(spec).scale(load_pct)
    from ..registry import arrival_preset

    obj = arrival_preset(
        str(spec), mean_gap_ms=mean_gap_ms, commands=commands
    )
    if obj is None:
        return None
    return ArrivalSchedule.from_json(obj).scale(load_pct)

"""Traffic schedule spec + compilation to per-epoch ctx tables.

A schedule is piecewise over the per-client *command sequence* axis —
the closed-loop client's logical clock (seqs are 1-based, exactly the
engine's SUBMIT payload seq). Each :class:`TrafficPhase` covers a fixed
number of commands and pins the workload knobs for that span:

* ``conflict_rate`` / ``pool_size`` — the ConflictPool draw parameters
  (key_gen.rs:96-110), now time-indexed;
* ``pool_base`` — hot-key churn: the shared pool covers keys
  ``[pool_base, pool_base + pool_size)``, so rotating the base moves
  the hot key set. Private keys live above every epoch's pool
  (``pool_span + client``) so churn never aliases them;
* ``think_ms`` — diurnal load: a delay between a command's completion
  and the next SUBMIT leaving the client, lowering the issue rate in
  off-peak epochs (0 = the closed loop's back-to-back issue);
* ``read_pct`` — read/write mix. The device engine's conflict
  detection is key-based, so the mix does not change latency results;
  the host oracle mirror draws the per-command read flag from the same
  counter-based stream so both sides agree on which commands are reads
  (docs/TRAFFIC.md spells out this guarantee boundary).

``compile(commands_per_client)`` lowers a schedule to fixed-shape numpy
ctx tables: a ``[T]`` command-seq → epoch index (``T = budget + 2``,
column 0 unused like the key table) plus one ``[E]`` array per knob —
**per-epoch, not per-seq**, so the in-loop footprint the GL202 VMEM
gate sees stays bounded by the (small) epoch count, not the command
budget (docs/PERF.md). Epoch boundaries land on the exact command seq —
the seq → epoch table is exact by construction, there is no ±1 rounding
— which the differential tests pin.

A *flat* schedule (one effective phase, no think, no rotation) is
collapsed by ``make_lane`` into the legacy static ctx path — no tables,
bit-identical jaxpr — so the seed-warmed XLA cache and the GL005 gating
pin survive (engine/spec.py).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional, Tuple, Union

import numpy as np


@dataclass(frozen=True)
class TrafficPhase:
    """One epoch of the schedule, covering ``commands`` command seqs."""

    commands: int
    conflict_rate: int
    pool_size: int = 1
    pool_base: int = 0
    think_ms: int = 0
    read_pct: int = 0

    def __post_init__(self) -> None:
        assert self.commands >= 1, "a phase must cover >= 1 command"
        assert 0 <= self.conflict_rate <= 100, self.conflict_rate
        assert self.pool_size >= 1, self.pool_size
        assert self.pool_base >= 0, self.pool_base
        assert self.think_ms >= 0, self.think_ms
        assert 0 <= self.read_pct <= 100, self.read_pct

    def knobs(self) -> Tuple[int, int, int, int]:
        """The parameters whose variation makes a schedule non-flat
        (read_pct rides along in the tables but never reaches the
        engine's arithmetic, so a read-mix-only schedule is still
        flat for the device)."""
        return (
            self.conflict_rate, self.pool_size, self.pool_base,
            self.think_ms,
        )


@dataclass(frozen=True)
class TrafficSchedule:
    """A named piecewise schedule. ``cycle=True`` repeats the phase
    pattern over the whole command budget (a diurnal day repeating);
    ``cycle=False`` extends the last phase forever (a one-shot ramp).

    Hashable by value so it can ride inside the frozen
    :class:`~fantoch_tpu.client.key_gen.DeviceStream` dataclass."""

    name: str
    phases: Tuple[TrafficPhase, ...]
    cycle: bool = False

    def __post_init__(self) -> None:
        assert self.phases, "a schedule needs at least one phase"

    # -- host helpers (the oracle mirror uses exactly these) -----------

    @property
    def pattern_len(self) -> int:
        return sum(p.commands for p in self.phases)

    def epoch_of(self, seq: int) -> int:
        """Phase index of 1-based command ``seq`` (unbounded axis:
        cycling or last-phase-extends past the pattern)."""
        assert seq >= 1, "command seqs are 1-based"
        idx = (seq - 1) % self.pattern_len if self.cycle else min(
            seq - 1, self.pattern_len - 1
        )
        for e, p in enumerate(self.phases):
            if idx < p.commands:
                return e
            idx -= p.commands
        return len(self.phases) - 1  # unreachable

    def phase_at(self, seq: int) -> TrafficPhase:
        return self.phases[self.epoch_of(seq)]

    def think_ms(self, seq: int) -> int:
        """The submit delay the oracle runner adds for command ``seq``
        — the bit-exact mirror of the engine's per-epoch think gather
        (engine/core.py ``_lane_step`` step 5)."""
        return self.phase_at(seq).think_ms

    def pool_span(self) -> int:
        """First key above every epoch's shared pool: private client
        keys are ``pool_span + client`` (the static path's
        ``pool_size + client`` generalized over rotation)."""
        return max(p.pool_base + p.pool_size for p in self.phases)

    def is_flat(self) -> bool:
        """True when the schedule is indistinguishable from the static
        ConflictPool path: one effective knob tuple, no think delay, no
        pool rotation. Flat schedules compile to NO ctx tables."""
        knobs = {p.knobs() for p in self.phases}
        if len(knobs) != 1:
            return False
        (conflict, _size, base, think) = next(iter(knobs))
        del conflict
        return base == 0 and think == 0

    # -- device lowering ----------------------------------------------

    def compile(self, commands_per_client: int) -> Dict[str, np.ndarray]:
        """Lower to the engine's ctx tables. ``traffic_seq_epoch`` is
        indexed by command seq (1-based; entry 0 mirrors seq 1, like
        the key table's unused column); length ``budget + 2`` matches
        the key table so the engine's index clamp never binds for a
        real command."""
        E = len(self.phases)
        T = commands_per_client + 2
        seq_epoch = np.zeros((T,), np.int32)
        seq_epoch[0] = self.epoch_of(1)
        for s in range(1, T):
            seq_epoch[s] = self.epoch_of(s)
        return {
            "traffic_seq_epoch": seq_epoch,
            "traffic_conflict": np.asarray(
                [p.conflict_rate for p in self.phases], np.int32
            ),
            "traffic_pool_base": np.asarray(
                [p.pool_base for p in self.phases], np.int32
            ),
            "traffic_pool_size": np.asarray(
                [p.pool_size for p in self.phases], np.int32
            ),
            "traffic_think": np.asarray(
                [p.think_ms for p in self.phases], np.int32
            ),
            "traffic_read_pct": np.asarray(
                [p.read_pct for p in self.phases], np.int32
            ),
            "traffic_pool_span": np.int32(self.pool_span()),
        }

    def meta(self) -> dict:
        """Compact JSON-able lane metadata (LaneSpec.traffic_meta)."""
        return {
            "name": self.name,
            "epochs": len(self.phases),
            "cycle": bool(self.cycle),
            "pattern_commands": self.pattern_len,
            "pool_span": self.pool_span(),
        }

    # -- JSON round-trip (campaign grids, repro artifacts) ------------

    def to_json(self) -> dict:
        return {
            "name": self.name,
            "cycle": bool(self.cycle),
            "phases": [
                {
                    "commands": p.commands,
                    "conflict_rate": p.conflict_rate,
                    "pool_size": p.pool_size,
                    "pool_base": p.pool_base,
                    "think_ms": p.think_ms,
                    "read_pct": p.read_pct,
                }
                for p in self.phases
            ],
        }

    @staticmethod
    def from_json(obj: dict) -> "TrafficSchedule":
        return TrafficSchedule(
            name=str(obj["name"]),
            cycle=bool(obj.get("cycle", False)),
            phases=tuple(
                TrafficPhase(**phase) for phase in obj["phases"]
            ),
        )


TrafficLike = Union[None, str, dict, TrafficSchedule]


def resolve_traffic(
    spec: TrafficLike,
    *,
    conflict: int,
    pool_size: int = 1,
    commands: int,
) -> Optional[TrafficSchedule]:
    """Resolve a traffic spec to a schedule (or None = static path).

    ``spec`` may be a preset name from :data:`fantoch_tpu.registry
    .TRAFFIC_PRESETS` (parameterized by the lane's base conflict rate /
    pool size / command budget, so the sweep's conflict axis composes
    with the traffic axis), a JSON schedule dict, an already-built
    :class:`TrafficSchedule`, or None. ``"flat"`` resolves to None —
    the static path, by construction."""
    if spec is None or isinstance(spec, TrafficSchedule):
        return spec
    if isinstance(spec, dict):
        return TrafficSchedule.from_json(spec)
    from ..registry import traffic_preset

    obj = traffic_preset(
        str(spec), conflict=conflict, pool_size=pool_size,
        commands=commands,
    )
    return None if obj is None else TrafficSchedule.from_json(obj)


def traffic_key_capacity(
    specs,
    *,
    conflict: int,
    pool_size: int,
    commands: int,
    clients: int,
) -> Optional[int]:
    """Protocol key capacity covering every schedule in ``specs`` (an
    iterable of preset names / schedules / None): private keys sit at
    ``pool_span + client``, so a rotated pool needs
    ``max(pool_span) + clients`` keys — the single source of the
    invariant ``make_lane`` asserts (``span + live_clients <= K``),
    shared by the CLI sweep and the campaign manager so the two can
    never drift.

    Returns None when every spec resolves flat: callers then keep
    their legacy default capacity (``dev_protocol``'s ``1 + clients``),
    preserving the pre-traffic lane shapes bit-for-bit so old campaign
    journals and checkpoints resume unchanged."""
    span: Optional[int] = None
    for spec in specs:
        sched = resolve_traffic(
            spec, conflict=conflict, pool_size=pool_size,
            commands=commands,
        )
        if sched is not None:
            span = max(span if span is not None else pool_size,
                       sched.pool_span())
    return None if span is None else span + clients

"""Time-varying traffic schedules for the device engine.

The reference evaluates every configuration under a *static* per-client
workload (one ConflictPool/Zipf draw per command, ``fantoch/src/client/
key_gen.rs``). Real planet-scale traffic is time-varying — diurnal load
curves, flash crowds, hot-key churn, shifting read/write mixes — and
conflict rate dominates tail latency (Atlas, EuroSys'20; Tempo,
EuroSys'21), so a schedule that moves the conflict structure over a
lane's lifetime opens a workload class the static draw cannot model.

A :class:`~fantoch_tpu.traffic.schedule.TrafficSchedule` is a piecewise
sequence of phases over the per-client command sequence axis (the
closed-loop client's logical clock), compiled into small ``[E]``-shaped
per-epoch ctx tables plus a command-seq → epoch index that the engine's
``gen_key``/``_lane_step`` consume as structure-gated extensions
(engine/core.py). A *flat* schedule compiles to **no tables at all** —
the lane traces the bit-identical jaxpr of the static path, so the
seed-warmed XLA cache and the GL005 gating pin survive. The host oracle
mirrors every schedule bit-exactly (client/key_gen.py ``DeviceStream``
+ sim/runner.py think delays), so the differential tests extend to
time-varying workloads. See docs/TRAFFIC.md.
"""

from .schedule import (
    ArrivalPhase,
    ArrivalSchedule,
    TrafficPhase,
    TrafficSchedule,
    resolve_arrivals,
    resolve_traffic,
)

__all__ = [
    "ArrivalPhase",
    "ArrivalSchedule",
    "TrafficPhase",
    "TrafficSchedule",
    "resolve_arrivals",
    "resolve_traffic",
]

#!/usr/bin/env python
"""Headline benchmark: batched Tempo-sweep throughput on device.

Runs a (region-set × f × conflict-rate) sweep of the flagship Tempo
protocol through the on-device engine — the TPU-native replacement for
the reference's rayon sweep (fantoch_ps/src/bin/simulation.rs:165-217,
one CPU thread per config) — and reports swept configs/second.

Baseline: the north-star target from BASELINE.md is 10,000 sweep points
in under 60 s on a v5e-8, i.e. ~20.8 points/s per chip; ``vs_baseline``
is measured single-chip points/s over that per-chip rate (>1.0 beats
the target rate pro-rata).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import json
import time

import jax

from fantoch_tpu.core import Config, Planet
from fantoch_tpu.engine import EngineDims
from fantoch_tpu.engine.protocols import TempoDev
from fantoch_tpu.parallel import make_sweep_specs, run_sweep

N = 3
COMMANDS = 50
CLIENTS_PER_REGION = 1
CONFLICTS = [0, 10, 50, 100]
FS = [1]
SUBSETS = 16  # region sets → 16 × 1 × 4 = 64 sweep points


def main() -> None:
    planet = Planet.new()
    regions = planet.regions()
    region_sets = [regions[i : i + N] for i in range(SUBSETS)]
    clients = N * CLIENTS_PER_REGION
    tempo = TempoDev(keys=1 + clients)
    total = COMMANDS * clients
    dims = EngineDims.for_protocol(
        tempo,
        n=N,
        clients=clients,
        payload=tempo.payload_width(N),
        total_commands=total,
        dot_slots=total + 1,
        regions=N,
    )
    base = Config(
        n=N, f=1, gc_interval_ms=100, tempo_detached_send_interval_ms=100
    )
    specs = make_sweep_specs(
        tempo,
        planet,
        region_sets=region_sets,
        fs=FS,
        conflicts=CONFLICTS,
        commands_per_client=COMMANDS,
        clients_per_region=CLIENTS_PER_REGION,
        dims=dims,
        config_base=base,
    )

    # compile + warm up, then time
    results = run_sweep(tempo, dims, specs)
    assert not any(r.err for r in results), "lanes overflowed"
    t0 = time.perf_counter()
    results = run_sweep(tempo, dims, specs)
    elapsed = time.perf_counter() - t0

    points_per_sec = len(specs) / elapsed
    per_chip_target = 10_000 / 60.0 / 8.0  # north-star rate, per chip
    print(
        json.dumps(
            {
                "metric": "sweep_points_per_sec",
                "value": round(points_per_sec, 2),
                "unit": f"Tempo configs/s (n={N}, {total} cmds each, "
                f"{len(jax.devices())} device(s))",
                "vs_baseline": round(points_per_sec / per_chip_target, 3),
            }
        )
    )


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""Headline benchmark: batched protocol-simulation sweep throughput.

Runs a batch of independent (region-set × f × conflict-rate)
configurations of the Basic protocol through the on-device engine — the
TPU-native replacement for the reference's rayon sweep
(fantoch_ps/src/bin/simulation.rs:165-217, one CPU thread per config) —
and reports swept configs/second.

Baseline: the north-star target from BASELINE.md is 10,000 sweep points
in under 60 s on a v5e-8, i.e. ~166.7 points/s per 8 chips ≈ 20.8
points/s per chip; ``vs_baseline`` is measured single-chip points/s
divided by that per-chip rate (>1.0 beats the target rate pro-rata).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import itertools
import json
import time

import jax

from fantoch_tpu.core import Config, Planet
from fantoch_tpu.engine import EngineDims, make_lane, run_lanes
from fantoch_tpu.engine.driver import stack_states
from fantoch_tpu.engine.core import build_runner
from fantoch_tpu.engine.spec import stack_lanes
from fantoch_tpu.engine.protocols import BasicDev

COMMANDS_PER_CLIENT = 50
N = 3
CONFLICTS = [0, 10, 50, 100]
FS = [1, 2]


def build_specs(planet: Planet):
    regions = planet.regions()
    # 8 distinct 3-region subsets × f × conflict = 64 sweep points
    subsets = [regions[i : i + N] for i in range(8)]
    total_cmds = N * COMMANDS_PER_CLIENT
    dims = EngineDims.for_protocol(
        BasicDev,
        n=N,
        clients=N,
        payload=BasicDev.payload_width(N),
        total_commands=total_cmds,
        dot_slots=total_cmds + 1,
        regions=N,
    )
    specs = [
        make_lane(
            BasicDev,
            planet,
            Config(n=N, f=f, gc_interval_ms=100),
            conflict_rate=conflict,
            pool_size=1,
            commands_per_client=COMMANDS_PER_CLIENT,
            clients_per_region=1,
            process_regions=subset,
            client_regions=subset,
            dims=dims,
            extra_time_ms=500,
            seed=i,
        )
        for i, (subset, f, conflict) in enumerate(
            itertools.product(subsets, FS, CONFLICTS)
        )
    ]
    return dims, specs


def main() -> None:
    planet = Planet.new()
    dims, specs = build_specs(planet)
    ctx = stack_lanes(specs)
    state = stack_states(BasicDev, dims, specs)
    runner = build_runner(BasicDev, dims)

    # compile + warm up, then time
    jax.block_until_ready(runner(state, ctx))
    t0 = time.perf_counter()
    final = runner(state, ctx)
    jax.block_until_ready(final)
    elapsed = time.perf_counter() - t0

    errs = int(final["err"].sum())
    assert errs == 0, f"{errs} lanes overflowed"
    points_per_sec = len(specs) / elapsed
    per_chip_target = 10_000 / 60.0 / 8.0  # north-star rate, per chip
    print(
        json.dumps(
            {
                "metric": "sweep_points_per_sec",
                "value": round(points_per_sec, 2),
                "unit": "configs/s (Basic n=3, 150 cmds, 1 chip)",
                "vs_baseline": round(points_per_sec / per_chip_target, 3),
            }
        )
    )


if __name__ == "__main__":
    main()

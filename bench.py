#!/usr/bin/env python
"""Headline benchmark: the all-protocol batched sweep on device.

Runs the north-star shape — all five protocols × (region-set × f ×
conflict-rate) sweep points through the on-device engine, the
TPU-native replacement for the reference's rayon sweep
(fantoch_ps/src/bin/simulation.rs:161-217, one CPU thread per config;
protocols iterate in its outer loop) — and reports mixed configs/s
plus per-protocol rates.

Shape: n=5 replicas, f ∈ {1, 2}, 4 conflict rates, 256 five-region
subsets of the 20-region GCP planet = 2,048 sweep points per protocol,
10,240 points total, 250 commands each, run per protocol in
device-sized chunks (vmapped lanes run to their batch's slowest lane,
so chunks sort by (f, conflict) to stay cost-homogeneous).

Baseline: BASELINE.md's north star is 10,000 points over all five
protocols on a v5e-8 in <60 s ⇒ ~20.8 points/s per chip;
``vs_baseline`` is measured single-chip points/s over that per-chip
rate (>1.0 beats the target pro-rata). Timing excludes compilation
(one warmup chunk per protocol) but includes host-side lane
construction and result collection for every counted point.
"""

from __future__ import annotations

import itertools
import json
import time

import jax

from fantoch_tpu.core import Config, Planet
from fantoch_tpu.engine import EngineDims
from fantoch_tpu.engine.protocols import dev_config_kwargs, dev_protocol
from fantoch_tpu.parallel import make_sweep_specs, run_sweep

import os as _os

N = 5
COMMANDS = int(_os.environ.get("FANTOCH_BENCH_COMMANDS", "50"))
CLIENTS_PER_REGION = 1
CONFLICTS = [0, 10, 50, 100]
FS = [1, 2]
# region sets → 256 × 2 × 4 = 2,048 points per protocol by default;
# env overrides support smoke runs on CPU (tiny) and device tuning
SUBSETS = int(_os.environ.get("FANTOCH_BENCH_SUBSETS", "256"))
CHUNK = int(_os.environ.get("FANTOCH_BENCH_CHUNK", "512"))
PROTOCOLS = tuple(
    _os.environ.get(
        "FANTOCH_BENCH_PROTOCOLS", "tempo,atlas,epaxos,fpaxos,caesar"
    ).split(",")
)


def _build(name: str, clients: int):
    dev = dev_protocol(name, clients)
    return dev, Config(**dev_config_kwargs(name, N, 1))


def main() -> None:
    # smoke runs (JAX_PLATFORMS=cpu) force the CPU backend even under
    # the axon site hook; driver runs leave the env unset and get the
    # real device
    from fantoch_tpu.platform import force_cpu_from_env

    force_cpu_from_env()
    planet = Planet.new()
    regions = planet.regions()
    # stride through C(20,5) so subsets are genuinely distinct (the
    # first-256 lexicographic combinations share a long prefix)
    combos = list(itertools.combinations(range(len(regions)), N))
    stride = max(1, len(combos) // SUBSETS)
    region_sets = [
        [regions[i] for i in combo] for combo in combos[::stride][:SUBSETS]
    ]
    clients = N * CLIENTS_PER_REGION

    jobs = []  # (name, dev, dims, chunks)
    for name in PROTOCOLS:
        dev, base = _build(name, clients)
        dims = EngineDims.for_protocol(
            dev,
            n=N,
            clients=clients,
            payload=dev.payload_width(N),
            # steady-state pool bound (closed-loop clients pace at WAN
            # RTT) and a recycled dot window; both overflow loudly
            # (ERR_POOL / ERR_DOT), never silently
            dot_slots=64,
            regions=N,
            hist_buckets=2048,  # 1 ms buckets; f=2 tails stay in range
        )
        specs = make_sweep_specs(
            dev,
            planet,
            region_sets=region_sets,
            fs=FS,
            conflicts=CONFLICTS,
            commands_per_client=COMMANDS,
            clients_per_region=CLIENTS_PER_REGION,
            dims=dims,
            config_base=base,
        )
        specs.sort(key=lambda s: (s.config.f, int(s.ctx["conflict_rate"])))
        chunks = [specs[i:i + CHUNK] for i in range(0, len(specs), CHUNK)]
        jobs.append((name, dev, dims, chunks))

    # compile + warm up each protocol's batch shape, then time the
    # full mixed sweep
    import sys

    for name, dev, dims, chunks in jobs:
        t1 = time.perf_counter()
        run_sweep(dev, dims, chunks[0])
        print(
            f"warmup {name}: {time.perf_counter() - t1:.1f}s",
            file=sys.stderr,
            flush=True,
        )

    per_proto = {}
    total_points = 0
    t0 = time.perf_counter()
    for name, dev, dims, chunks in jobs:
        t1 = time.perf_counter()
        results = []
        for chunk in chunks:
            results.extend(run_sweep(dev, dims, chunk))
        dt = time.perf_counter() - t1
        bad = [(i, r.err_cause) for i, r in enumerate(results) if r.err]
        assert not bad, f"{name}: failing lanes {bad[:8]}"
        stalled = [
            (i, r.requeues) for i, r in enumerate(results) if r.requeues
        ]
        assert not stalled, (
            f"{name}: dot-window stalls distort latency {stalled[:8]}"
        )
        points = sum(len(c) for c in chunks)
        total_points += points
        per_proto[name] = round(points / dt, 2)
        print(
            f"timed {name}: {points} points in {dt:.1f}s "
            f"({per_proto[name]}/s)",
            file=sys.stderr,
            flush=True,
        )
    elapsed = time.perf_counter() - t0

    points_per_sec = total_points / elapsed
    per_chip_target = 10_000 / 60.0 / 8.0  # north-star rate, per chip
    print(
        json.dumps(
            {
                "metric": "sweep_points_per_sec",
                "value": round(points_per_sec, 2),
                "unit": (
                    f"all-protocol configs/s (n={N}, f=1-2, "
                    f"{COMMANDS * clients} cmds each, {total_points} "
                    f"points, per-protocol "
                    + ",".join(
                        f"{k}={v}" for k, v in per_proto.items()
                    )
                    + f", {len(jax.devices())} device(s))"
                ),
                "vs_baseline": round(points_per_sec / per_chip_target, 3),
            }
        )
    )


def _retriable(e: BaseException) -> bool:
    """A crash worth retrying in a fresh process.

    Three shapes have been observed from the tunneled device backend:
    * connection errors (ConnectionResetError, BrokenPipeError,
      TimeoutError) when the tunnel drops mid-run — NOT all OSErrors;
      a missing/unwritable path is deterministic and must not burn
      the 5-minute retry ladder;
    * jax/jaxlib runtime errors (JaxRuntimeError, XlaRuntimeError)
      when the device worker crashes — matched by module prefix since
      their import path moves between jax versions;
    * plain RuntimeError("Unable to initialize backend ...") when the
      backend is down at startup (the exact failure BENCH_r02 hit).
    Deterministic failures (failing-lane assertions) are never retried.
    """
    if isinstance(e, (ConnectionError, BrokenPipeError, TimeoutError)):
        return True
    mod = type(e).__module__ or ""
    if mod.startswith(("jax", "jaxlib")):
        return True
    if isinstance(e, RuntimeError):
        msg = str(e).lower()
        return "backend" in msg or "tpu" in msg or "device" in msg
    return False


# waits before each fresh-process retry: quick for transient worker
# crashes, then long enough to ride out a backend restart
RETRY_WAITS_S = (5, 60, 240)


if __name__ == "__main__":
    import os
    import sys

    try:
        main()
    except Exception as e:
        import traceback

        traceback.print_exc()
        attempt = int(os.environ.get("FANTOCH_BENCH_RETRIED", "0"))
        if _retriable(e) and attempt < len(RETRY_WAITS_S):
            wait = RETRY_WAITS_S[attempt]
            print(
                f"bench: retriable backend failure ({type(e).__name__}); "
                f"retry {attempt + 1}/{len(RETRY_WAITS_S)} in {wait}s",
                file=sys.stderr,
            )
            time.sleep(wait)
            os.environ["FANTOCH_BENCH_RETRIED"] = str(attempt + 1)
            # fresh process: the in-process JAX client is dead after a
            # worker crash, so re-exec rather than re-call main()
            os.execv(sys.executable, [sys.executable] + sys.argv)
        if _retriable(e):
            print(
                "bench: backend still unavailable after "
                f"{len(RETRY_WAITS_S)} retries over "
                f"{sum(RETRY_WAITS_S)}s — giving up",
                file=sys.stderr,
            )
        raise

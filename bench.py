#!/usr/bin/env python
"""Headline benchmark: the all-protocol batched sweep on device.

Runs the north-star shape — all five protocols × (region-set × f ×
conflict-rate) sweep points through the on-device engine, the
TPU-native replacement for the reference's rayon sweep
(fantoch_ps/src/bin/simulation.rs:161-217, one CPU thread per config;
protocols iterate in its outer loop) — and reports mixed configs/s
plus per-protocol rates.

Shape: n=5 replicas, f ∈ {1, 2}, 4 conflict rates, 256 five-region
subsets of the 20-region GCP planet = 2,048 sweep points per protocol,
10,240 points total, 250 commands each, run per protocol in
device-sized chunks (vmapped lanes run to their batch's slowest lane,
so chunks sort by (f, conflict) to stay cost-homogeneous).

Baseline: BASELINE.md's north star is 10,000 points over all five
protocols on a v5e-8 in <60 s ⇒ ~20.8 points/s per chip;
``vs_baseline`` is measured single-chip points/s over that per-chip
rate (>1.0 beats the target pro-rata). Timing excludes compilation
(one warmup chunk per protocol) but includes host-side lane
construction and result collection for every counted point.
"""

from __future__ import annotations

import itertools
import json
import time

import jax

from fantoch_tpu.core import Config, Planet
from fantoch_tpu.engine import EngineDims
from fantoch_tpu.engine.protocols import dev_config_kwargs, dev_protocol
from fantoch_tpu.parallel import make_sweep_specs, run_sweep

import os as _os

N = 5
COMMANDS = int(_os.environ.get("FANTOCH_BENCH_COMMANDS", "50"))
CLIENTS_PER_REGION = 1
CONFLICTS = [0, 10, 50, 100]
FS = [1, 2]
# region sets → 256 × 2 × 4 = 2,048 points per protocol by default;
# env overrides support smoke runs on CPU (tiny) and device tuning
SUBSETS = int(_os.environ.get("FANTOCH_BENCH_SUBSETS", "256"))
CHUNK = int(_os.environ.get("FANTOCH_BENCH_CHUNK", "512"))
PROTOCOLS = tuple(
    _os.environ.get(
        "FANTOCH_BENCH_PROTOCOLS", "tempo,atlas,epaxos,fpaxos,caesar"
    ).split(",")
)


def _build(name: str, clients: int):
    dev = dev_protocol(name, clients)
    return dev, Config(**dev_config_kwargs(name, N, 1))


# schedule-fuzzing self-check + throughput (mc/fuzz.py): a fixed-seed
# Tempo point with the mixed jitter/crash/drop lane draw; the monitors
# must flag nothing on the correct protocol, and the measured
# schedules/sec lands in the artifact next to the sweep rate with the
# same platform provenance
FUZZ_SCHEDULES = int(_os.environ.get("FANTOCH_BENCH_FUZZ_SCHEDULES", "256"))

# minimum remaining total budget for attempting the fuzz self-check (a
# cold monitored-runner compile is minutes on a CPU mesh; the sweep
# artifact must never be lost to a driver timeout mid-compile)
FUZZ_MIN_BUDGET_S = float(
    _os.environ.get("FANTOCH_BENCH_FUZZ_MIN_BUDGET", "420")
)

# coverage-discovery self-check shape (mc/coverage.py): blind vs
# coverage-steered distinct-bucket discovery over the SAME chunked
# schedule budget on a fixed-seed tempo n=3 point, in one process (the
# two modes share the compiled COV_CHUNK-lane monitored runner, so the
# delta isolates what seed mutation buys, not a compile)
COV_CHUNK = int(_os.environ.get("FANTOCH_BENCH_COV_CHUNK", "32"))
COV_CHUNKS = int(_os.environ.get("FANTOCH_BENCH_COV_CHUNKS", "4"))
COV_MIN_BUDGET_S = float(
    _os.environ.get("FANTOCH_BENCH_COV_MIN_BUDGET", "420")
)
# the farm's fault classes, steered individually over the same budget
# (mc/fuzz.py class_spec; "mixed" is the headline steered number)
COV_CLASSES = ("crash", "drop", "jitter")

# covmap-compaction self-check shape (mc/covmap.py): time to persist a
# synthetic COVMAP_BUCKETS-bucket map as a versioned binary file plus
# a keep-2 compaction, vs the per-chunk canonical-JSON state rewrite
# it replaced — pure host I/O, measured even in dead-backend artifacts
COVMAP_BUCKETS = int(
    _os.environ.get("FANTOCH_BENCH_COVMAP_BUCKETS", "100000")
)

# checkpoint-roundtrip self-check shape (engine/checkpoint.py): the
# documented 512-lane tempo sweep state, reduced by the CPU-fallback
# env so a host-mesh run still finishes inside the driver budget
CKPT_LANES = int(_os.environ.get("FANTOCH_BENCH_CKPT_LANES", "512"))

# dispatch-overhead self-check shape (parallel/pipeline.py): a fixed
# small tempo grid run serial (pipeline_depth=1) vs pipelined (K=2)
# with deliberately small segments so the per-call dispatch tax
# dominates and the delta isolates what the in-flight window buys;
# byte-identity of the two results is asserted in the same breath
DISPATCH_SUBSETS = int(_os.environ.get("FANTOCH_BENCH_DISPATCH_SUBSETS", "2"))
DISPATCH_SEGMENT = int(
    _os.environ.get("FANTOCH_BENCH_DISPATCH_SEGMENT", "64")
)
DISPATCH_MIN_BUDGET_S = float(
    _os.environ.get("FANTOCH_BENCH_DISPATCH_MIN_BUDGET", "300")
)

# AOT cold-start self-check shape (parallel/aot.py): a fresh
# subprocess builds a small tempo grid and acquires its sweep runner
# twice — once with an empty artifact dir (trace + compile) and once
# against the executable the first run serialized (load) — so
# `aot_load_s` vs `trace_compile_s` measures exactly what a fleet
# worker stops paying per process. Budget-guarded (the first child IS
# a deliberate cold compile), shapes shrunk in _CPU_FALLBACK_ENV.
AOT_COMMANDS = int(_os.environ.get("FANTOCH_BENCH_AOT_COMMANDS", "10"))
AOT_SUBSETS = int(_os.environ.get("FANTOCH_BENCH_AOT_SUBSETS", "1"))
AOT_MIN_BUDGET_S = float(
    _os.environ.get("FANTOCH_BENCH_AOT_MIN_BUDGET", "300")
)

# heterogeneous-megabatch self-check shape (engine/hetero.py): the same
# fixed small grid run (a) as ONE protocol_id-switched mixed batch and
# (b) as per-protocol homogeneous batches — hetero_points_per_sec vs
# the homogeneous control at identical total lane count, per-lane byte
# identity asserted in the same breath (the GL605 property, measured).
# The cold-start twin runs both layouts in fresh subprocesses with no
# compile cache, so `hetero_cold_start_s` vs `hetero_cold_start_homo_s`
# is the compile-collapse the switch buys a cold fleet worker: one
# executable instead of one per protocol.
HETERO_PROTOCOLS = tuple(
    _os.environ.get(
        "FANTOCH_BENCH_HETERO_PROTOCOLS", "basic,fpaxos,tempo,atlas"
    ).split(",")
)
HETERO_COMMANDS = int(_os.environ.get("FANTOCH_BENCH_HETERO_COMMANDS", "10"))
HETERO_SUBSETS = int(_os.environ.get("FANTOCH_BENCH_HETERO_SUBSETS", "2"))
HETERO_MIN_BUDGET_S = float(
    _os.environ.get("FANTOCH_BENCH_HETERO_MIN_BUDGET", "420")
)
# each cold child pays |protocols|+1 deliberate compiles between them
HETERO_COLD_MIN_BUDGET_S = float(
    _os.environ.get("FANTOCH_BENCH_HETERO_COLD_MIN_BUDGET", "600")
)

# ms/step shapes: the documented ~512-lane sweet spot plus the
# 2048-lane bandwidth-bound regime docs/PERF.md measured at 30 vs
# 230 ms/step — the two points the narrowing pass targets. The 512
# shape reuses the main sweep's cached runner; 2048 is one extra
# compile, so it rides behind the same budget guard as the other
# self-checks.
MSSTEP_LANES = tuple(
    int(x)
    for x in _os.environ.get(
        "FANTOCH_BENCH_MSSTEP_LANES", "512,2048"
    ).split(",")
)
MSSTEP_STEPS = int(_os.environ.get("FANTOCH_BENCH_MSSTEP_STEPS", "128"))

# mesh_shard self-check shape (parallel/partition.py): the small tempo
# grid run through the explicit shard_map partitioning —
# sweep_points_per_sec at the same shape, different execution layout.
# The shard_map runner is its own compile, so it rides behind a budget
# guard like the other self-checks.
MESH_SUBSETS = int(_os.environ.get("FANTOCH_BENCH_MESH_SUBSETS", "2"))
MESH_MIN_BUDGET_S = float(
    _os.environ.get("FANTOCH_BENCH_MESH_MIN_BUDGET", "300")
)

# fleet self-check shape (fantoch_tpu/fleet): a small tempo campaign
# grid (2 subsets × 2 conflicts, batch_lanes=1 → 4 lease units) drained
# by subprocess fleet workers — 2-worker vs 1-worker units/sec, with
# one untimed 1-worker pass first so the persistent compile cache is
# warm and the timed runs measure orchestration, not XLA
FLEET_COMMANDS = int(_os.environ.get("FANTOCH_BENCH_FLEET_COMMANDS", "10"))
FLEET_SEGMENT = int(_os.environ.get("FANTOCH_BENCH_FLEET_SEGMENT", "2048"))
FLEET_UNITS = 4
FLEET_MIN_BUDGET_S = float(
    _os.environ.get("FANTOCH_BENCH_FLEET_MIN_BUDGET", "420")
)

# traffic-schedule self-check shape (fantoch_tpu/traffic): lanes whose
# epoch tables are timed host-side, and the small tempo sweep measured
# flat vs diurnal (the diurnal trace is a separate compile, so the
# delta isolates what the epoch gathers cost per point)
TRAFFIC_TABLE_LANES = int(
    _os.environ.get("FANTOCH_BENCH_TRAFFIC_LANES", "512")
)
TRAFFIC_SUBSETS = int(_os.environ.get("FANTOCH_BENCH_TRAFFIC_SUBSETS", "2"))

# minimum remaining total budget for the traffic sweep self-check (a
# cold diurnal-trace compile is minutes on a CPU mesh, like the fuzz
# runner's)
TRAFFIC_MIN_BUDGET_S = float(
    _os.environ.get("FANTOCH_BENCH_TRAFFIC_MIN_BUDGET", "420")
)

# open-loop serving self-check shape (fantoch_tpu/serving): the small
# tempo grid measured closed vs open loop (the open-loop step is its
# own compile, so the delta isolates the arrival-release arithmetic
# per point), and a tiny knee campaign timed end-to-end
OPENLOOP_SUBSETS = int(
    _os.environ.get("FANTOCH_BENCH_OPENLOOP_SUBSETS", "2")
)
KNEE_COMMANDS = int(_os.environ.get("FANTOCH_BENCH_KNEE_COMMANDS", "10"))
KNEE_LOADS = tuple(
    int(x)
    for x in _os.environ.get("FANTOCH_BENCH_KNEE_LOADS", "50,200").split(",")
)

# minimum remaining total budget for the open-loop self-checks (two
# cold compiles: the open-loop n=5 grid and the n=3 knee campaign)
OPENLOOP_MIN_BUDGET_S = float(
    _os.environ.get("FANTOCH_BENCH_OPENLOOP_MIN_BUDGET", "420")
)


def _region_subsets(planet, count: int):
    """``count`` genuinely-distinct N-region subsets: stride through
    C(regions, N) so they don't share a long lexicographic prefix —
    the one enumeration both the main sweep and the traffic self-check
    must agree on."""
    regions = planet.regions()
    combos = list(itertools.combinations(range(len(regions)), N))
    stride = max(1, len(combos) // count)
    return [
        [regions[i] for i in combo] for combo in combos[::stride][:count]
    ]


def _traffic_table_build() -> "float | None":
    """Host-side cost of compiling one diurnal schedule's epoch tables
    per lane for a ``TRAFFIC_TABLE_LANES``-lane sweep — the table tax a
    traffic campaign pays before any device work. Degrades to None
    (never an exception) like the other auxiliary metrics."""
    import sys

    try:
        from fantoch_tpu.traffic.schedule import resolve_traffic

        t0 = time.perf_counter()
        for i in range(TRAFFIC_TABLE_LANES):
            sched = resolve_traffic(
                "diurnal", conflict=(i * 13) % 101, pool_size=1,
                commands=COMMANDS,
            )
            tables = sched.compile(COMMANDS)
        assert tables["traffic_seq_epoch"].shape[0] == COMMANDS + 2
        return time.perf_counter() - t0
    except Exception as e:  # noqa: BLE001
        print(f"bench: traffic table build unavailable: {e!r}",
              file=sys.stderr)
        return None


def _traffic_sweep_delta() -> "tuple[float, float] | None":
    """Measured flat-vs-diurnal sweep rate on a small tempo grid
    (``TRAFFIC_SUBSETS`` × f × conflicts points, same shape both
    sides): one warmup + one timed run per schedule, so the reported
    delta is the per-point cost of the compiled epoch gathers + think
    arithmetic, not compile time. Returns (flat_pps, diurnal_pps) or
    None."""
    import sys

    try:
        planet = Planet.new()
        region_sets = _region_subsets(planet, TRAFFIC_SUBSETS)
        clients = N * CLIENTS_PER_REGION
        # churn-free presets keep the pool span at pool_size, so the
        # default key capacity (and therefore dims) matches the flat
        # side exactly — the measured delta is the schedule, not shapes
        dev, base = _build("tempo", clients)
        dims = EngineDims.for_protocol(
            dev, n=N, clients=clients, payload=dev.payload_width(N),
            dot_slots=64, regions=N, hist_buckets=2048,
        )

        def specs(traffic):
            out = make_sweep_specs(
                dev, planet, region_sets=region_sets, fs=FS,
                conflicts=CONFLICTS, commands_per_client=COMMANDS,
                clients_per_region=CLIENTS_PER_REGION, dims=dims,
                config_base=base, traffic=traffic,
            )
            out.sort(
                key=lambda s: (s.config.f, int(s.ctx["conflict_rate"]))
            )
            return out

        rates = []
        for traffic in (None, "diurnal"):
            batch = specs(traffic)
            run_sweep(dev, dims, batch)  # warmup/compile
            t0 = time.perf_counter()
            results = run_sweep(dev, dims, batch)
            dt = time.perf_counter() - t0
            bad = [r.err_cause for r in results if r.err]
            assert not bad, f"traffic self-check failing lanes: {bad[:4]}"
            rates.append(len(batch) / dt)
        return rates[0], rates[1]
    except Exception as e:  # noqa: BLE001
        import traceback

        traceback.print_exc()
        print(f"bench: traffic sweep delta unavailable: {e!r}",
              file=sys.stderr)
        return None


def _openloop_sweep_delta() -> "tuple[float, float] | None":
    """Measured closed-vs-open-loop sweep rate on a small tempo grid
    (``OPENLOOP_SUBSETS`` × f × conflicts points, same shape both
    sides): one warmup + one timed run per client mode, so the
    reported delta is the per-point cost of the compiled arrival
    gathers + release recursion (engine/core.py open-loop step 5),
    not compile time. Returns (closed_pps, open_pps) or None."""
    import sys

    try:
        planet = Planet.new()
        region_sets = _region_subsets(planet, OPENLOOP_SUBSETS)
        clients = N * CLIENTS_PER_REGION
        total = COMMANDS * clients
        dev, base = _build("tempo", clients)
        # open-loop lanes keep up to open_window commands of every
        # client in flight, so the queue planes size by total commands
        # (the campaign manager's shape) — shared by the closed side,
        # keeping both timings on identical dims
        dims = EngineDims.for_protocol(
            dev, n=N, clients=clients, payload=dev.payload_width(N),
            total_commands=total, dot_slots=total + 1, regions=N,
            hist_buckets=2048,
        )

        def specs(arrivals):
            # window 2: at n=5/f=2/conflict=100 a deeper in-flight
            # window overflows tempo's fixed detached-vote slots
            # (ERR_CAPACITY, loud) — the self-check measures arrival
            # arithmetic, not that protocol bound
            out = make_sweep_specs(
                dev, planet, region_sets=region_sets, fs=FS,
                conflicts=CONFLICTS, commands_per_client=COMMANDS,
                clients_per_region=CLIENTS_PER_REGION, dims=dims,
                config_base=base, arrivals=arrivals, open_window=2,
            )
            out.sort(
                key=lambda s: (s.config.f, int(s.ctx["conflict_rate"]))
            )
            return out

        rates = []
        for arrivals in (None, "poisson"):
            batch = specs(arrivals)
            run_sweep(dev, dims, batch)  # warmup/compile
            t0 = time.perf_counter()
            results = run_sweep(dev, dims, batch)
            dt = time.perf_counter() - t0
            bad = [r.err_cause for r in results if r.err]
            assert not bad, f"open-loop self-check failing lanes: {bad[:4]}"
            rates.append(len(batch) / dt)
        return rates[0], rates[1]
    except Exception as e:  # noqa: BLE001
        import traceback

        traceback.print_exc()
        print(f"bench: open-loop sweep delta unavailable: {e!r}",
              file=sys.stderr)
        return None


def _knee_sweep_rate() -> "tuple[float, int] | None":
    """Measured curve points per second of a tiny tempo knee sweep
    (serving/knee.py) run end-to-end through the campaign manager —
    journaling, checkpoints, artifact write included, so the rate is
    what a real knee campaign pays per (region-set, protocol, load)
    point. Returns (points_per_sec, points) or None."""
    import shutil
    import sys
    import tempfile

    try:
        from fantoch_tpu.serving import run_knee_sweep

        work = tempfile.mkdtemp(prefix="fantoch_knee_bench_")
        try:
            t0 = time.perf_counter()
            artifact, summary = run_knee_sweep(
                work, protocols=("tempo",), ns=(3,),
                loads=KNEE_LOADS, commands_per_client=KNEE_COMMANDS,
                batch_lanes=64, segment_steps=512,
            )
            dt = time.perf_counter() - t0
            assert artifact is not None, f"knee sweep interrupted: {summary}"
            points = sum(len(p["curve"]) for p in artifact["points"])
            assert points > 0, "knee sweep measured no curve points"
            return points / dt, points
        finally:
            shutil.rmtree(work, ignore_errors=True)
    except Exception as e:  # noqa: BLE001
        import traceback

        traceback.print_exc()
        print(f"bench: knee sweep rate unavailable: {e!r}",
              file=sys.stderr)
        return None


def _bench_dims(dev):
    """The one dims construction every tempo self-check shares with the
    main sweep job, so the cached segment runner compiles once."""
    clients = N * CLIENTS_PER_REGION
    return EngineDims.for_protocol(
        dev, n=N, clients=clients, payload=dev.payload_width(N),
        dot_slots=64, regions=N, hist_buckets=2048,
    )


def _dispatch_overhead() -> (
    "tuple[float, float, float, dict, str | None] | None"
):
    """Serial vs pipelined vs scan-fused wall time on a fixed small
    tempo grid (``DISPATCH_SUBSETS`` × f × conflicts points,
    ``DISPATCH_SEGMENT``-step segments so each run makes many device
    calls): serial-minus-pipelined is the dispatch tax the in-flight
    window (parallel/pipeline.py) amortizes, and the scan-fused run
    (``scan_window`` default, parallel/sweep.py) shows what is left
    once host round-trips drop to one per window. All three runs'
    results are compared byte-for-byte — the live twin of the
    tests/test_pipeline.py and tests/test_scan_window.py pins, and the
    only one that runs on the real backend. The returned
    ``window_roundtrips`` dict carries each variant's measured host
    dispatch count (``parallel.sweep.LAST_STATS``): the segment loop
    pays one per segment, the scan-fused loop one per window. Returns
    ``(serial_s, pipelined_s, fused_s, window_roundtrips, None)``; a
    byte divergence returns a tuple whose note starts with
    ``IDENTITY VIOLATION`` so the artifact flags a correctness bug
    DISTINGUISHABLY from the transient-skip notes; other failures
    return None."""
    import json as _json
    import sys

    from fantoch_tpu.parallel.sweep import LAST_STATS

    try:
        planet = Planet.new()
        region_sets = _region_subsets(planet, DISPATCH_SUBSETS)
        dev, base = _build("tempo", N * CLIENTS_PER_REGION)
        dims = _bench_dims(dev)
        specs = make_sweep_specs(
            dev, planet, region_sets=region_sets, fs=FS,
            conflicts=CONFLICTS, commands_per_client=COMMANDS,
            clients_per_region=CLIENTS_PER_REGION, dims=dims,
            config_base=base,
        )
        specs.sort(key=lambda s: (s.config.f, int(s.ctx["conflict_rate"])))

        def timed(depth, win):
            # min of 3: single-shot wall times on a shared 2-core host
            # swing by seconds (docs/PERF.md warns ±50% run-to-run even
            # on the tunnel); the minimum is the run least disturbed by
            # unrelated load, which is what the overhead delta needs
            best, best_out, calls = None, None, 0
            for _ in range(3):
                t0 = time.perf_counter()
                out = run_sweep(
                    dev, dims, specs, segment_steps=DISPATCH_SEGMENT,
                    pipeline_depth=depth, scan_window=win,
                )
                dt = time.perf_counter() - t0
                if best is None or dt < best:
                    best, best_out = dt, out
                calls = LAST_STATS["device_calls"]
            return best, best_out, calls

        timed(1, 1)  # warmup/compile (this batch shape is its own compile)
        serial_s, serial, serial_calls = timed(1, 1)
        piped_s, piped, _piped_calls = timed(2, 1)
        # the scan-fused window flavor is its own compile; warm it up
        # outside the timed window like the segment flavor
        timed(2, None)
        fused_s, fused, fused_calls = timed(2, None)
        fused_win = LAST_STATS["scan_window"]
        roundtrips = {
            # host dispatch round-trips for the whole grid: the
            # segment loop pays scan_window of them per checkpoint
            # window, the scan-fused loop exactly one
            "scan_window": fused_win,
            "segment_loop": serial_calls,
            "scan_fused": fused_calls,
        }
        a = _json.dumps([r.to_json() for r in serial], sort_keys=True)
        for label, out in (("pipelined", piped), ("scan-fused", fused)):
            b = _json.dumps([r.to_json() for r in out], sort_keys=True)
            if a != b:
                # a real divergence on this backend is a correctness
                # bug, not a degraded measurement — it must never hide
                # behind the same note a transient compile failure
                # produces
                print(
                    f"bench: IDENTITY VIOLATION: {label} sweep results "
                    "diverged from serial on this backend",
                    file=sys.stderr,
                )
                return 0.0, 0.0, 0.0, {}, (
                    f"IDENTITY VIOLATION: {label} sweep diverged from "
                    "serial on this backend — correctness bug, not a "
                    "transient skip (see stderr)"
                )
        bad = [r.err_cause for r in serial if r.err]
        assert not bad, f"dispatch self-check failing lanes: {bad[:4]}"
        return serial_s, piped_s, fused_s, roundtrips, None
    except Exception as e:  # noqa: BLE001
        import traceback

        traceback.print_exc()
        print(f"bench: dispatch overhead unavailable: {e!r}",
              file=sys.stderr)
        return None


def _ms_per_step(lanes: int) -> "float | None":
    """Measured ms/step of the tempo segment runner at ``lanes`` lanes
    (one lane's state stacked, so host-side lane construction stays out
    of the way): one warmup segment (compile + first dispatch), then
    one timed ``MSSTEP_STEPS``-step segment in the lanes' steady state.
    Shares ``run_sweep``'s runner cache — at the 512-lane main-sweep
    shape this is compile-free. Degrades to None, never an
    exception."""
    import sys

    try:
        import numpy as np
        from jax.sharding import Mesh, NamedSharding, PartitionSpec

        from fantoch_tpu.engine import make_lane
        from fantoch_tpu.engine.core import (
            cast_state_planes,
            donation_safe,
            init_lane_state,
        )
        from fantoch_tpu.engine.faults import NO_FAULTS
        from fantoch_tpu.engine.spec import narrow_spec, stack_lanes
        from fantoch_tpu.parallel.sweep import _cached_runner

        planet = Planet.new()
        regions = planet.regions()[:N]
        dev, base = _build("tempo", N)
        dims = _bench_dims(dev)
        lane = make_lane(
            dev, planet, base, conflict_rate=100,
            commands_per_client=COMMANDS, clients_per_region=1,
            process_regions=regions, client_regions=regions, dims=dims,
        )
        state0 = init_lane_state(dev, dims, lane.ctx)
        state = jax.tree_util.tree_map(
            lambda x: np.stack([np.asarray(x)] * lanes), state0
        )
        ctx = stack_lanes([lane] * lanes)
        nspec = narrow_spec(dev, ctx)
        state = cast_state_planes(state, nspec, store=True)
        runner, _alive = _cached_runner(
            dev, dims, 1 << 22, False, NO_FAULTS, 0, nspec,
            donation_safe(),
        )
        mesh = Mesh(np.asarray(jax.devices()), ("sweep",))
        sharding = NamedSharding(mesh, PartitionSpec("sweep"))
        put = lambda tree: jax.tree_util.tree_map(  # noqa: E731
            lambda a: jax.device_put(a, sharding), tree
        )
        state, ctx = put(state), put(ctx)
        # warmup: compile + advance into the steady state; the timed
        # segment then runs [MSSTEP_STEPS, 2*MSSTEP_STEPS), where every
        # lane is still live (COMMANDS budgets run for hundreds of
        # steps — docs/PERF.md round-3 measurements)
        state, alive = runner(state, ctx, np.int32(MSSTEP_STEPS))
        jax.block_until_ready(state)
        assert bool(alive), (
            "ms/step window overran the lanes; raise COMMANDS or lower "
            "MSSTEP_STEPS"
        )
        t0 = time.perf_counter()
        state, _a = runner(state, ctx, np.int32(2 * MSSTEP_STEPS))
        jax.block_until_ready(state)
        dt = time.perf_counter() - t0
        return dt * 1000.0 / MSSTEP_STEPS
    except Exception as e:  # noqa: BLE001
        import traceback

        traceback.print_exc()
        print(f"bench: ms/step@{lanes} unavailable: {e!r}",
              file=sys.stderr)
        return None


def _mesh_shard_rate() -> "float | None":
    """sweep_points_per_sec through the explicit shard_map partition
    layout (run_sweep(mesh_shard=True)) on a small tempo grid: one
    warmup (compile + GL203 proof) then one timed run. Degrades to
    None, never an exception — a LaneMixingError here is a real
    finding and lands on stderr."""
    import sys

    try:
        from fantoch_tpu.parallel.sweep import run_sweep as _run

        planet = Planet.new()
        region_sets = _region_subsets(planet, MESH_SUBSETS)
        clients = N * CLIENTS_PER_REGION
        dev, base = _build("tempo", clients)
        dims = _bench_dims(dev)
        specs = make_sweep_specs(
            dev, planet, region_sets=region_sets, fs=FS,
            conflicts=CONFLICTS, commands_per_client=COMMANDS,
            clients_per_region=CLIENTS_PER_REGION, dims=dims,
            config_base=base,
        )
        specs.sort(key=lambda s: (s.config.f, int(s.ctx["conflict_rate"])))
        _run(dev, dims, specs, mesh_shard=True)  # warmup + proof
        t0 = time.perf_counter()
        results = _run(dev, dims, specs, mesh_shard=True)
        dt = time.perf_counter() - t0
        bad = [r.err_cause for r in results if r.err]
        assert not bad, f"mesh_shard self-check failing lanes: {bad[:4]}"
        return len(specs) / dt
    except Exception as e:  # noqa: BLE001
        import traceback

        traceback.print_exc()
        print(f"bench: mesh_shard rate unavailable: {e!r}",
              file=sys.stderr)
        return None


def _fleet_units() -> "tuple[float, float, str | None] | None":
    """Fleet orchestration throughput (fantoch_tpu/fleet): drain a
    FLEET_UNITS-unit tempo campaign with subprocess fleet workers —
    (1-worker units/s, 2-worker units/s, identity-note). One untimed
    1-worker pass warms the persistent compile cache first; the merged
    2-worker results must be byte-identical to the 1-worker control
    (a divergence surfaces as a distinguishable IDENTITY-VIOLATION
    note, not a silent number)."""
    import shutil
    import subprocess
    import sys
    import tempfile

    try:
        grid = json.dumps(
            {
                "kind": "sweep",
                "protocols": ["tempo"],
                "ns": [3],
                "conflicts": [0, 100],
                "subsets": 2,
                "commands_per_client": FLEET_COMMANDS,
                "batch_lanes": 1,
                "segment_steps": FLEET_SEGMENT,
            }
        )
        platform = (
            "cpu" if _os.environ.get("JAX_PLATFORMS") == "cpu" else "auto"
        )
        tmp = tempfile.mkdtemp(prefix="fantoch_fleet_bench_")

        def drain(dirname: str, workers: int) -> float:
            d = _os.path.join(tmp, dirname)
            cmd = [
                sys.executable, "-m", "fantoch_tpu",
                "--platform", platform, "fleet", "--dir", d,
                "--grid", grid, "--workers", str(workers),
            ]
            t0 = time.perf_counter()
            res = subprocess.run(
                cmd, capture_output=True, text=True, timeout=900
            )
            dt = time.perf_counter() - t0
            if res.returncode != 0:
                raise RuntimeError(
                    f"{workers}-worker fleet rc={res.returncode}: "
                    f"{res.stderr[-400:]}"
                )
            return dt

        try:
            drain("warm", 1)  # compile-cache warmup, untimed
            t_solo = drain("solo", 1)
            t_duo = drain("duo", 2)
            from fantoch_tpu.fleet import merge_campaign

            note = None
            for dirname in ("solo", "duo"):
                m = merge_campaign(_os.path.join(tmp, dirname))
                assert m["merged"] and m["errors"] == 0, m
            with open(
                _os.path.join(tmp, "solo", "results.jsonl"), "rb"
            ) as fh:
                solo_bytes = fh.read()
            with open(
                _os.path.join(tmp, "duo", "results.jsonl"), "rb"
            ) as fh:
                duo_bytes = fh.read()
            if solo_bytes != duo_bytes:
                note = (
                    "IDENTITY-VIOLATION: 2-worker merged results "
                    "diverged from the 1-worker control"
                )
        finally:
            shutil.rmtree(tmp, ignore_errors=True)
        return FLEET_UNITS / t_solo, FLEET_UNITS / t_duo, note
    except Exception as e:  # noqa: BLE001
        import traceback

        traceback.print_exc()
        print(f"bench: fleet units/s unavailable: {e!r}", file=sys.stderr)
        return None


# the AOT cold-start child: a fresh process acquiring the sweep runner
# for a small tempo grid through run_sweep(aot=...). The printed
# `seconds` is parallel/aot.py's runner-acquisition time — trace +
# compile (+ serialize) on the first run, deserialize + load on the
# second — exactly the per-process tax the serialized executable
# removes; interpreter/jax startup is identical either way and
# excluded on purpose.
_AOT_CHILD = r"""
import json

from fantoch_tpu.core import Config, Planet
from fantoch_tpu.engine import EngineDims
from fantoch_tpu.engine.protocols import dev_config_kwargs, dev_protocol
from fantoch_tpu.parallel.sweep import (
    LAST_STATS,
    make_sweep_specs,
    run_sweep,
)

planet = Planet.new()
regions = planet.regions()
clients = {clients}
dev = dev_protocol("tempo", clients)
total = {commands} * clients
dims = EngineDims.for_protocol(
    dev, n=3, clients=clients, payload=dev.payload_width(3),
    total_commands=total, dot_slots=total + 1, regions=3,
)
specs = make_sweep_specs(
    dev, planet,
    region_sets=[regions[i:i + 3] for i in range({subsets})],
    fs=[1], conflicts=[0, 100], commands_per_client={commands},
    clients_per_region=1, dims=dims,
    config_base=Config(**dev_config_kwargs("tempo", 3, 1)),
)
results = run_sweep(
    dev, dims, specs, segment_steps={segment}, aot={aot_dir!r}
)
blob = json.dumps([r.to_json() for r in results], sort_keys=True)
print("AOT-COLD " + json.dumps(
    dict(LAST_STATS["aot"], blob_sha=__import__("hashlib").sha256(
        blob.encode()).hexdigest())
))
"""


def _aot_cold_start() -> "tuple[float, float, str | None] | None":
    """Fresh-subprocess cold-start cost with and without a serialized
    sweep executable (parallel/aot.py): child 1 starts against an
    empty artifact dir and pays the full trace + compile (serializing
    the result), child 2 starts against that artifact and loads it.
    Returns ``(trace_compile_s, aot_load_s, note)`` — the two runner-
    acquisition times a fleet respawn round pays per worker, byte
    identity of the two children's results asserted via sha256; an
    identity violation rides in the note like the dispatch
    self-check's, other failures return None."""
    import subprocess
    import sys
    import tempfile

    try:
        tmp = tempfile.mkdtemp(prefix="fantoch_aot_bench_")
        script = _AOT_CHILD.format(
            clients=3 * CLIENTS_PER_REGION,
            commands=AOT_COMMANDS,
            subsets=AOT_SUBSETS,
            segment=DISPATCH_SEGMENT,
            aot_dir=_os.path.join(tmp, "aot"),
        )
        env = dict(_os.environ)
        # the children must measure what a REAL cold worker pays: no
        # persistent compile cache (it would hide the trace+compile
        # the artifact exists to remove, and bench's own cache dir is
        # per-machine, not per-campaign)
        env.pop("FANTOCH_COMPILE_CACHE", None)
        env.pop("JAX_COMPILATION_CACHE_DIR", None)

        def child():
            out = subprocess.run(
                [sys.executable, "-c", script],
                capture_output=True, text=True, timeout=600, env=env,
            )
            if out.returncode != 0:
                raise RuntimeError(
                    f"aot cold-start child failed: {out.stderr[-1500:]}"
                )
            line = [
                ln for ln in out.stdout.splitlines()
                if ln.startswith("AOT-COLD ")
            ][0]
            return json.loads(line[len("AOT-COLD "):])

        first = child()
        second = child()
        if (
            first["source"] != "trace-compile"
            or second["source"] != "aot-load"
        ):
            raise RuntimeError(
                f"unexpected aot provenance: {first['source']} then "
                f"{second['source']}"
            )
        if first["blob_sha"] != second["blob_sha"]:
            print(
                "bench: IDENTITY VIOLATION: loaded AOT executable "
                "results diverged from the traced control",
                file=sys.stderr,
            )
            return 0.0, 0.0, (
                "IDENTITY VIOLATION: loaded AOT executable diverged "
                "from the traced control — correctness bug, not a "
                "transient skip (see stderr)"
            )
        return float(first["seconds"]), float(second["seconds"]), None
    except Exception as e:  # noqa: BLE001
        import traceback

        traceback.print_exc()
        print(
            f"bench: aot cold-start unavailable: {e!r}",
            file=sys.stderr,
        )
        return None


_HETERO_CHILD = r"""
import hashlib
import json
import time

from fantoch_tpu.core import Config, Planet
from fantoch_tpu.engine import EngineDims
from fantoch_tpu.engine.protocols import dev_config_kwargs, dev_protocol
from fantoch_tpu.parallel.sweep import make_sweep_specs, run_sweep

protocols = {protocols!r}
planet = Planet.new()
regions = planet.regions()
clients = {clients}
protos, dmap, lanes = {{}}, {{}}, {{}}
for name in protocols:
    dev = dev_protocol(name, clients)
    total = {commands} * clients
    dims = EngineDims.for_protocol(
        dev, n=3, clients=clients, payload=dev.payload_width(3),
        total_commands=total, dot_slots=total + 1, regions=3,
    )
    specs = make_sweep_specs(
        dev, planet,
        region_sets=[regions[i:i + 3] for i in range({subsets})],
        fs=[1], conflicts=[0, 100], commands_per_client={commands},
        clients_per_region=1, dims=dims,
        config_base=Config(**dev_config_kwargs(name, 3, 1)),
    )
    protos[name], dmap[name], lanes[name] = dev, dims, specs

# the timed window is runner acquisition + execution, cold: exactly
# what a fresh fleet worker pays before its first unit completes
t0 = time.perf_counter()
by_name = {{}}
if {hetero}:
    mixed = []
    for i in range(max(len(v) for v in lanes.values())):
        for name in protocols:
            if i < len(lanes[name]):
                mixed.append((name, lanes[name][i]))
    results = run_sweep(
        protos, dmap, mixed, hetero=True, segment_steps={segment}
    )
    for (name, _spec), r in zip(mixed, results):
        by_name.setdefault(name, []).append(r.to_json())
else:
    for name in protocols:
        rs = run_sweep(
            protos[name], dmap[name], lanes[name],
            segment_steps={segment},
        )
        by_name[name] = [r.to_json() for r in rs]
dt = time.perf_counter() - t0
blob = json.dumps(by_name, sort_keys=True)
print("HETERO-COLD " + json.dumps(
    dict(
        seconds=dt,
        layout="hetero" if {hetero} else "homo",
        compiles=1 if {hetero} else len(protocols),
        blob_sha=hashlib.sha256(blob.encode()).hexdigest(),
    )
))
"""


def _hetero_rate() -> "tuple[float, float, str | None] | None":
    """hetero_points_per_sec: the fixed small HETERO_PROTOCOLS grid as
    one protocol_id-switched mixed batch vs the same lanes as
    per-protocol homogeneous batches, both warmed — so the delta
    isolates the switch's compute amplification (every branch runs for
    every lane) against what fuller batches and one dispatch stream buy
    back. Per-lane byte identity against the homogeneous controls is
    asserted in the same breath (GL605's property); a divergence rides
    in the note, other failures return None."""
    import sys

    try:
        from fantoch_tpu.engine.checkpoint import canonical_json
        from fantoch_tpu.parallel.sweep import run_sweep as _run

        planet = Planet.new()
        region_sets = _region_subsets(planet, HETERO_SUBSETS)
        clients = N * CLIENTS_PER_REGION
        protos, dmap, lanes = {}, {}, {}
        for name in HETERO_PROTOCOLS:
            dev, base = _build(name, clients)
            dims = _bench_dims(dev)
            specs = make_sweep_specs(
                dev, planet, region_sets=region_sets, fs=FS,
                conflicts=CONFLICTS,
                commands_per_client=HETERO_COMMANDS,
                clients_per_region=CLIENTS_PER_REGION, dims=dims,
                config_base=base,
            )
            specs.sort(
                key=lambda s: (s.config.f, int(s.ctx["conflict_rate"]))
            )
            protos[name], dmap[name], lanes[name] = dev, dims, specs
        mixed = []
        for i in range(max(len(v) for v in lanes.values())):
            for name in HETERO_PROTOCOLS:
                if i < len(lanes[name]):
                    mixed.append((name, lanes[name][i]))
        _run(protos, dmap, mixed, hetero=True)  # warmup (compile)
        for name in HETERO_PROTOCOLS:  # warm each homogeneous shape
            _run(protos[name], dmap[name], lanes[name])
        t0 = time.perf_counter()
        hres = _run(protos, dmap, mixed, hetero=True)
        dt_h = time.perf_counter() - t0
        t0 = time.perf_counter()
        cres = {
            name: _run(protos[name], dmap[name], lanes[name])
            for name in HETERO_PROTOCOLS
        }
        dt_c = time.perf_counter() - t0
        seen = {name: 0 for name in HETERO_PROTOCOLS}
        diverged = 0
        for (name, _spec), r in zip(mixed, hres):
            ctrl = cres[name][seen[name]]
            seen[name] += 1
            if canonical_json(r.to_json()) != canonical_json(
                ctrl.to_json()
            ):
                diverged += 1
        if diverged:
            print(
                "bench: IDENTITY VIOLATION: mixed-batch lanes diverged "
                "from their homogeneous controls",
                file=sys.stderr,
            )
            return 0.0, 0.0, (
                f"IDENTITY VIOLATION: {diverged}/{len(mixed)} mixed "
                "lanes diverged from their homogeneous controls — "
                "correctness bug, not a transient skip (see stderr)"
            )
        return len(mixed) / dt_h, len(mixed) / dt_c, None
    except Exception as e:  # noqa: BLE001
        import traceback

        traceback.print_exc()
        print(f"bench: hetero rate unavailable: {e!r}", file=sys.stderr)
        return None


def _hetero_cold_collapse() -> "tuple[float, float, str | None] | None":
    """Fresh-subprocess cold wall time of the same small grid as one
    mixed switch batch (ONE compile) vs per-protocol homogeneous
    batches (one compile EACH) — the compile-collapse a cold fleet
    worker pockets. Returns ``(hetero_cold_s, homo_cold_s, note)``;
    byte identity of the two layouts' results asserted via sha256, a
    violation rides in the note, other failures return None."""
    import subprocess
    import sys

    try:
        env = dict(_os.environ)
        # both children must pay their real compiles: no persistent
        # compile cache (it would hide exactly the collapse measured)
        env.pop("FANTOCH_COMPILE_CACHE", None)
        env.pop("JAX_COMPILATION_CACHE_DIR", None)

        def child(hetero: bool):
            script = _HETERO_CHILD.format(
                protocols=list(HETERO_PROTOCOLS),
                clients=3 * CLIENTS_PER_REGION,
                commands=AOT_COMMANDS,
                subsets=AOT_SUBSETS,
                segment=DISPATCH_SEGMENT,
                hetero=hetero,
            )
            out = subprocess.run(
                [sys.executable, "-c", script],
                capture_output=True, text=True, timeout=600, env=env,
            )
            if out.returncode != 0:
                raise RuntimeError(
                    f"hetero cold child failed: {out.stderr[-1500:]}"
                )
            line = [
                ln for ln in out.stdout.splitlines()
                if ln.startswith("HETERO-COLD ")
            ][0]
            return json.loads(line[len("HETERO-COLD "):])

        hot = child(True)
        homo = child(False)
        if hot["blob_sha"] != homo["blob_sha"]:
            print(
                "bench: IDENTITY VIOLATION: cold mixed-batch results "
                "diverged from the homogeneous layout",
                file=sys.stderr,
            )
            return 0.0, 0.0, (
                "IDENTITY VIOLATION: cold mixed-batch results diverged "
                "from the homogeneous layout — correctness bug, not a "
                "transient skip (see stderr)"
            )
        return float(hot["seconds"]), float(homo["seconds"]), None
    except Exception as e:  # noqa: BLE001
        import traceback

        traceback.print_exc()
        print(
            f"bench: hetero cold-start unavailable: {e!r}",
            file=sys.stderr,
        )
        return None


def _checkpoint_roundtrip() -> "float | None":
    """Save + restore + bit-exact compare of a ``CKPT_LANES``-lane
    tempo state through engine/checkpoint.py — the durability tax a
    campaign pays per checkpointed segment (docs/CAMPAIGN.md). The
    step-signature trace is computed (and cached) outside the timed
    window; the timed part is exactly serialize + deserialize +
    compare. Degrades to None (never an exception) so the measured
    sweep metric can't be lost to a checkpoint bug."""
    import shutil
    import sys
    import tempfile

    import numpy as np

    try:
        from fantoch_tpu.engine import make_lane
        from fantoch_tpu.engine.checkpoint import (
            load_sweep_checkpoint,
            save_sweep_checkpoint,
            step_signature,
        )
        from fantoch_tpu.engine.core import init_lane_state
        from fantoch_tpu.engine.faults import NO_FAULTS
        from fantoch_tpu.engine.spec import stack_lanes

        planet = Planet.new()
        regions = planet.regions()[:N]
        dev, base = _build("tempo", N)
        dims = EngineDims.for_protocol(
            dev, n=N, clients=N, payload=dev.payload_width(N),
            dot_slots=64, regions=N,
        )
        lane = make_lane(
            dev, planet, base, conflict_rate=100,
            commands_per_client=10, clients_per_region=1,
            process_regions=regions, client_regions=regions, dims=dims,
        )
        state0 = init_lane_state(dev, dims, lane.ctx)
        state = jax.tree_util.tree_map(
            lambda x: np.stack([np.asarray(x)] * CKPT_LANES), state0
        )
        ctx = stack_lanes([lane] * CKPT_LANES)
        sig = step_signature(
            dev, dims, reorder=False, faults=NO_FAULTS, monitor_keys=0,
            state=state0, ctx=lane.ctx,
        )
        work = tempfile.mkdtemp(prefix="fantoch-ckpt-bench-")
        try:
            t0 = time.perf_counter()
            save_sweep_checkpoint(
                work, state=state, ctx=ctx, signature=sig, until=0,
                meta={"lanes": CKPT_LANES},
            )
            restored, _meta = load_sweep_checkpoint(
                work, signature=sig, ctx=ctx,
                meta_expect={"lanes": CKPT_LANES},
            )
            before = jax.tree_util.tree_flatten_with_path(state)[0]
            after = jax.tree_util.tree_flatten_with_path(restored)[0]
            assert len(before) == len(after)
            for (pa, a), (pb, b) in zip(before, after):
                assert str(pa) == str(pb), (pa, pb)
                a, b = np.asarray(a), np.asarray(b)
                assert a.dtype == b.dtype and a.shape == b.shape, pa
                assert np.array_equal(a, b), f"restore not bit-exact: {pa}"
            return time.perf_counter() - t0
        finally:
            shutil.rmtree(work, ignore_errors=True)
    except Exception as e:  # noqa: BLE001
        print(
            f"bench: checkpoint roundtrip unavailable: {e!r}",
            file=sys.stderr,
        )
        return None


def _static_kernel_cost(timeout_s: float = 240.0) -> "dict | None":
    """Device-free kernel-cost estimate of the tempo 512-lane step
    (the GL201 ledger, fantoch_tpu/lint/cost.py) — a real static
    number the artifact carries even when the TPU backend is
    unreachable. Runs in a throwaway JAX_PLATFORMS=cpu subprocess so a
    dead device tunnel can neither hang nor pollute this process's
    backend, and degrades to None (never an exception) — the measured
    sweep metric must not be lost to a lint import error."""
    import subprocess
    import sys

    env = dict(_os.environ, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)  # the ledger traces; it never executes
    try:
        out = subprocess.run(
            [sys.executable, "-m", "fantoch_tpu.lint.cost", "tempo"],
            capture_output=True,
            text=True,
            timeout=timeout_s,
            env=env,
        )
        line = out.stdout.strip().splitlines()[-1]
        cost = json.loads(line)
        assert cost.get("kernels")
        return cost
    except Exception as e:  # noqa: BLE001
        import sys as _sys

        print(f"bench: static kernel cost unavailable: {e!r}",
              file=_sys.stderr)
        return None


def _host_sync_ledger() -> "dict | None":
    """Device->host sync ledger of the host orchestration layers (the
    GL301 scan, fantoch_tpu/lint/transfer.py) — per-tier counts of
    every blocking fetch the sweep drivers perform, the static
    complement to the measured dispatch_overhead_s numbers. Pure AST
    in-process (imports no jax), so it is honest even when the device
    backend is unreachable; degrades to an error record, never an
    exception."""
    try:
        from fantoch_tpu.lint.transfer import ledger_summary

        return ledger_summary()
    except Exception as e:  # noqa: BLE001
        import sys as _sys

        print(f"bench: host sync ledger unavailable: {e!r}",
              file=_sys.stderr)
        return {"error": repr(e)}


def _determinism_ledger() -> "dict | None":
    """Determinism-hazard ledger of the artifact-writer layers (the
    GL401-GL404 scan, fantoch_tpu/lint/determinism.py) — per-rule
    counts of every baselined ordering/PRNG/serialization/atomicity
    exception behind the byte-identity pins. Pure AST in-process
    (imports no jax), so it is honest even when the device backend is
    unreachable; degrades to an error record, never an exception."""
    try:
        from fantoch_tpu.lint.determinism import ledger_summary

        return ledger_summary()
    except Exception as e:  # noqa: BLE001
        import sys as _sys

        print(f"bench: determinism ledger unavailable: {e!r}",
              file=_sys.stderr)
        return {"error": repr(e)}


def _shard_axis_ledger() -> "dict | None":
    """Axis-shardability ledger of the per-protocol state planes (the
    GL501 prover, fantoch_tpu/lint/shard.py) — per-protocol
    SHARDABLE/COLLECTIVE/REPLICATED verdict counts from the checked-in
    shard baseline, the static complement to the measured 2-D-mesh
    sweep numbers. Reads only the JSON artifact (imports no jax), so
    it is honest even when the device backend is unreachable; degrades
    to an error record, never an exception."""
    try:
        from fantoch_tpu.lint.shard import shard_axis_ledger_summary

        return shard_axis_ledger_summary()
    except Exception as e:  # noqa: BLE001
        import sys as _sys

        print(f"bench: shard axis ledger unavailable: {e!r}",
              file=_sys.stderr)
        return {"error": repr(e)}


def _skeleton_waste_ratio() -> "dict | None":
    """Megabatch padding-amplification ratios (the GL601/GL603 ledger,
    fantoch_tpu/lint/skeleton.py) — unified-skeleton bytes over native
    per-protocol bytes for every grid composition declared in
    engine/dims.py SKELETON_GRIDS, from the checked-in skeleton
    baseline. Reads only the JSON artifact (imports no jax), so it is
    honest even when the device backend is unreachable; degrades to an
    error record, never an exception."""
    try:
        from fantoch_tpu.lint.skeleton import skeleton_waste_summary

        return skeleton_waste_summary()
    except Exception as e:  # noqa: BLE001
        import sys as _sys

        print(f"bench: skeleton waste ledger unavailable: {e!r}",
              file=_sys.stderr)
        return {"error": repr(e)}


def _fuzz_selfcheck() -> float:
    from fantoch_tpu.mc.fuzz import FuzzSpec, run_fuzz_point

    spec = FuzzSpec(
        protocol="tempo",
        n=N,
        f=1,
        schedules=FUZZ_SCHEDULES,
        commands_per_client=10,
        seed=0xF022,
    )
    # warmup compiles the monitored fuzz runner (same batch shape as
    # the timed run; the sweep timing above already excludes compiles)
    run_fuzz_point(spec, confirm=False)
    res = run_fuzz_point(spec, confirm=False)
    assert res.flagged == 0, (
        f"fuzz self-check flagged violations on correct Tempo: "
        f"{res.summary()}"
    )
    bad = {
        k: v for k, v in res.engine_errors.items()
        if k != "requeue-livelock"  # legitimate under drop lanes
    }
    assert not bad, f"fuzz self-check engine errors: {res.engine_errors}"
    return res.schedules_per_sec


def _fuzz_coverage() -> "tuple[float, float, dict]":
    """Blind vs coverage-steered bucket discovery per 1000 schedules
    (mc/coverage.py) on a fixed-seed tempo n=3 point: both modes spend
    the identical chunked budget (COV_CHUNKS chunks of COV_CHUNK
    schedules) in this process, the steered mode feeding each chunk's
    new-bucket plans back through the seed mutators. The farm's
    non-mixed fault classes (mc/fuzz.py class_spec) are then each
    steered over the same budget — their salted streams and zeroed
    envelopes reuse the compiled COV_CHUNK-lane runner, so the
    per-class rates isolate how rich each fault slice's interleaving
    space is, not a compile. Returns (blind, steered,
    {class: steered buckets/ksched})."""
    from fantoch_tpu.mc import coverage as cov
    from fantoch_tpu.mc.fuzz import (
        FuzzSpec,
        class_spec,
        draw_plans,
        plan_rng,
        point_config,
        point_protocol,
        run_fuzz_point,
    )

    base = FuzzSpec(
        protocol="tempo",
        n=3,
        f=1,
        schedules=COV_CHUNK,
        commands_per_client=5,
        seed=0xC0F,
    )
    total = COV_CHUNK * COV_CHUNKS

    def run(spec, steered: bool) -> float:
        config = point_config(spec)
        dev = point_protocol(spec)
        rng = plan_rng(spec)
        cmap, pool, mrng = cov.restore_steering(spec, None)
        for _ in range(COV_CHUNKS):
            if steered:
                plans = cov.draw_steered(
                    spec, config, dev, COV_CHUNK, rng, mrng, pool
                )
            else:
                plans = draw_plans(
                    spec, config, dev, count=COV_CHUNK, rng=rng
                )
            res = run_fuzz_point(spec, confirm=False, plans=plans)
            cov.fold_chunk(cmap, pool, res.digests, plans)
        return cmap.bucket_count * 1000.0 / total

    blind = run(base, False)
    steered = run(base, True)
    per_class = {
        c: run(class_spec(base, c), True) for c in COV_CLASSES
    }
    return blind, steered, per_class


def _covmap_compact() -> "tuple[float, float]":
    """Binary coverage-map persistence tax (mc/covmap.py): build a
    synthetic COVMAP_BUCKETS-bucket map, then time (a) one versioned
    binary write plus the keep-2 compaction a farm chunk pays, vs (b)
    the canonical-JSON point-state rewrite it replaced. Pure host I/O
    against a tmpdir — no device, usable even in dead-backend
    artifacts. Returns (binary_s, json_s)."""
    import shutil
    import tempfile

    from fantoch_tpu.engine.checkpoint import atomic_write, canonical_json
    from fantoch_tpu.mc import covmap as cvm
    from fantoch_tpu.mc.coverage import CoverageMap

    sig = {"bench": "covmap_compact", "buckets": COVMAP_BUCKETS}
    # deterministic synthetic digests (a PCG stream would do too, but
    # the shape — sorted i64 pairs — is all the format cares about)
    cmap = CoverageMap(
        signature=sig,
        buckets={(i * 0x9E3779B97F4A7C15) & ((1 << 63) - 1): 1
                 for i in range(COVMAP_BUCKETS)},
    )
    d = tempfile.mkdtemp(prefix="fantoch_covmap_bench_")
    key = "bench/n3"
    try:
        # pre-seed two older versions so the timed write triggers a
        # real keep-2 compaction (the steady-state farm cost)
        cvm.save_point_map(d, key, 1, cmap)
        cvm.save_point_map(d, key, 2, cmap)
        t0 = time.time()
        cvm.save_point_map(d, key, 3, cmap)
        cvm.compact_point_maps(d, key, keep=2)
        binary_s = time.time() - t0
        t0 = time.time()
        atomic_write(
            _os.path.join(d, "state.json"),
            canonical_json({"coverage": cmap.to_json()}) + "\n",
        )
        json_s = time.time() - t0
    finally:
        shutil.rmtree(d, ignore_errors=True)
    return binary_s, json_s


def main() -> None:
    # smoke runs (JAX_PLATFORMS=cpu) force the CPU backend even under
    # the axon site hook; driver runs leave the env unset and get the
    # real device
    from fantoch_tpu.platform import enable_compile_cache, force_cpu_from_env

    force_cpu_from_env()
    cache_dir = enable_compile_cache()
    import sys as _sys

    print(f"bench: compile cache at {cache_dir}", file=_sys.stderr)
    planet = Planet.new()
    region_sets = _region_subsets(planet, SUBSETS)
    clients = N * CLIENTS_PER_REGION

    jobs = []  # (name, dev, dims, chunks)
    for name in PROTOCOLS:
        dev, base = _build(name, clients)
        dims = EngineDims.for_protocol(
            dev,
            n=N,
            clients=clients,
            payload=dev.payload_width(N),
            # steady-state pool bound (closed-loop clients pace at WAN
            # RTT) and a recycled dot window; both overflow loudly
            # (ERR_POOL / ERR_DOT), never silently
            dot_slots=64,
            regions=N,
            hist_buckets=2048,  # 1 ms buckets; f=2 tails stay in range
        )
        specs = make_sweep_specs(
            dev,
            planet,
            region_sets=region_sets,
            fs=FS,
            conflicts=CONFLICTS,
            commands_per_client=COMMANDS,
            clients_per_region=CLIENTS_PER_REGION,
            dims=dims,
            config_base=base,
        )
        specs.sort(key=lambda s: (s.config.f, int(s.ctx["conflict_rate"])))
        chunks = [specs[i:i + CHUNK] for i in range(0, len(specs), CHUNK)]
        jobs.append((name, dev, dims, chunks))

    # compile + warm up each protocol's batch shape, then time the
    # full mixed sweep
    import sys

    for name, dev, dims, chunks in jobs:
        t1 = time.perf_counter()
        run_sweep(dev, dims, chunks[0])
        print(
            f"warmup {name}: {time.perf_counter() - t1:.1f}s",
            file=sys.stderr,
            flush=True,
        )

    per_proto = {}
    total_points = 0
    t0 = time.perf_counter()
    for name, dev, dims, chunks in jobs:
        t1 = time.perf_counter()
        results = []
        for chunk in chunks:
            results.extend(run_sweep(dev, dims, chunk))
        dt = time.perf_counter() - t1
        bad = [(i, r.err_cause) for i, r in enumerate(results) if r.err]
        assert not bad, f"{name}: failing lanes {bad[:8]}"
        stalled = [
            (i, r.requeues) for i, r in enumerate(results) if r.requeues
        ]
        assert not stalled, (
            f"{name}: dot-window stalls distort latency {stalled[:8]}"
        )
        points = sum(len(c) for c in chunks)
        total_points += points
        per_proto[name] = round(points / dt, 2)
        print(
            f"timed {name}: {points} points in {dt:.1f}s "
            f"({per_proto[name]}/s)",
            file=sys.stderr,
            flush=True,
        )
    elapsed = time.perf_counter() - t0

    # the self-check cold-compiles a monitored fuzz runner (minutes on
    # a CPU mesh) AFTER the sweep rate is already measured — never let
    # it widen the no-artifact window the budget machinery closes:
    # skip it (honest zero) when too little of the total budget remains
    fuzz_sps, fuzz_note = 0.0, None
    if TOTAL_BUDGET_S - _since_birth() < FUZZ_MIN_BUDGET_S:
        fuzz_note = "skipped: insufficient budget for the fuzz compile"
        print(f"fuzz self-check {fuzz_note}", file=sys.stderr, flush=True)
    else:
        try:
            fuzz_sps = _fuzz_selfcheck()
            print(
                f"fuzz self-check: {FUZZ_SCHEDULES} schedules clean, "
                f"{fuzz_sps:.1f}/s",
                file=sys.stderr,
                flush=True,
            )
        except Exception as e:  # noqa: BLE001
            # the headline sweep metric is already measured — a failed
            # self-check (flagged lane, engine error, compile failure)
            # must degrade the fuzz field honestly, never lose the
            # whole artifact
            import traceback

            traceback.print_exc()
            fuzz_sps = 0.0
            fuzz_note = f"failed: {type(e).__name__}: {e}"[:300]
            print(
                f"fuzz self-check {fuzz_note}", file=sys.stderr,
                flush=True,
            )

    # coverage-discovery rates (mc/coverage.py): blind vs steered
    # buckets per 1000 schedules over the same chunked budget — its
    # COV_CHUNK-lane monitored runner is one more compile, so it rides
    # behind the same budget guard as the other self-checks
    cov_rates, cov_note = None, None
    if TOTAL_BUDGET_S - _since_birth() < COV_MIN_BUDGET_S:
        cov_note = "skipped: insufficient budget for the coverage compile"
        print(f"coverage self-check {cov_note}", file=sys.stderr,
              flush=True)
    else:
        try:
            cov_rates = _fuzz_coverage()
            per_cls = ", ".join(
                f"{c}={r:.1f}" for c, r in cov_rates[2].items()
            )
            print(
                f"coverage self-check: {COV_CHUNK * COV_CHUNKS} "
                f"schedules, {cov_rates[0]:.1f} blind vs "
                f"{cov_rates[1]:.1f} steered buckets/ksched "
                f"(per class: {per_cls})",
                file=sys.stderr,
                flush=True,
            )
        except Exception as e:  # noqa: BLE001
            import traceback

            traceback.print_exc()
            cov_rates = None
            cov_note = f"failed: {type(e).__name__}: {e}"[:300]
            print(
                f"coverage self-check {cov_note}", file=sys.stderr,
                flush=True,
            )

    # covmap persistence tax (mc/covmap.py): pure host I/O, no device
    # and no compile — runs unconditionally, honest-zero only if the
    # write itself fails
    covmap_s, covmap_note = None, None
    try:
        covmap_s = _covmap_compact()
        print(
            f"covmap self-check: {COVMAP_BUCKETS} buckets, "
            f"binary+compact {covmap_s[0]:.3f}s vs JSON "
            f"{covmap_s[1]:.3f}s",
            file=sys.stderr,
            flush=True,
        )
    except Exception as e:  # noqa: BLE001
        covmap_note = f"failed: {type(e).__name__}: {e}"[:300]
        print(f"covmap self-check {covmap_note}", file=sys.stderr,
              flush=True)

    # traffic-schedule tax (fantoch_tpu/traffic): host-side epoch-table
    # build time, plus the measured flat-vs-diurnal rate delta on a
    # small tempo grid — both honest-zero when skipped/failed, like the
    # fuzz self-check (the diurnal trace is its own compile, so the
    # budget guard protects the already-measured sweep artifact)
    table_s = _traffic_table_build()
    traffic_rates, traffic_note = None, None
    if TOTAL_BUDGET_S - _since_birth() < TRAFFIC_MIN_BUDGET_S:
        traffic_note = "skipped: insufficient budget for the diurnal compile"
        print(f"traffic self-check {traffic_note}", file=sys.stderr,
              flush=True)
    else:
        traffic_rates = _traffic_sweep_delta()
        if traffic_rates is None:
            traffic_note = "failed (see stderr)"
        else:
            print(
                f"traffic self-check: flat {traffic_rates[0]:.2f}/s vs "
                f"diurnal {traffic_rates[1]:.2f}/s",
                file=sys.stderr,
                flush=True,
            )

    # open-loop serving tax (fantoch_tpu/serving): closed-vs-open rate
    # on the small tempo grid plus a tiny end-to-end knee campaign —
    # both honest-zero when skipped/failed, like the traffic self-check
    # (the open-loop step and the knee grid are their own compiles, so
    # the budget guard protects the already-measured sweep artifact)
    openloop_rates, knee_rate, openloop_note = None, None, None
    if TOTAL_BUDGET_S - _since_birth() < OPENLOOP_MIN_BUDGET_S:
        openloop_note = (
            "skipped: insufficient budget for the open-loop compiles"
        )
        print(f"open-loop self-check {openloop_note}", file=sys.stderr,
              flush=True)
    else:
        openloop_rates = _openloop_sweep_delta()
        knee_rate = _knee_sweep_rate()
        if openloop_rates is None or knee_rate is None:
            openloop_note = "failed (see stderr)"
        else:
            print(
                f"open-loop self-check: closed "
                f"{openloop_rates[0]:.2f}/s vs open "
                f"{openloop_rates[1]:.2f}/s; knee "
                f"{knee_rate[0]:.2f} curve points/s",
                file=sys.stderr,
                flush=True,
            )

    # dispatch tax (parallel/pipeline.py): serial vs pipelined on the
    # small tempo grid, plus measured ms/step at the 512/2048-lane
    # shapes. Budget-guarded like the other self-checks — the small
    # grid and the 2048-lane batch are their own compiles — and
    # honest-zero on skip/failure so the sweep artifact survives.
    dispatch, dispatch_note = None, None
    msstep: dict = {}
    if TOTAL_BUDGET_S - _since_birth() < DISPATCH_MIN_BUDGET_S:
        dispatch_note = (
            "skipped: insufficient budget for the pipeline self-check"
        )
        print(f"dispatch self-check {dispatch_note}", file=sys.stderr,
              flush=True)
    else:
        dispatch = _dispatch_overhead()
        if dispatch is None:
            dispatch_note = "failed (see stderr)"
        elif dispatch[4] is not None:
            # the byte-identity tripwire fired: surface the violation
            # note verbatim and zero the measurement
            dispatch_note, dispatch = dispatch[4], None
        else:
            print(
                f"dispatch self-check: serial {dispatch[0]:.2f}s vs "
                f"pipelined {dispatch[1]:.2f}s vs scan-fused "
                f"{dispatch[2]:.2f}s "
                f"(overhead {dispatch[0] - dispatch[1]:+.2f}s piped, "
                f"{dispatch[0] - dispatch[2]:+.2f}s fused; host "
                f"round-trips {dispatch[3]['segment_loop']} -> "
                f"{dispatch[3]['scan_fused']}; byte-identical results)",
                file=sys.stderr,
                flush=True,
            )
        for lanes in MSSTEP_LANES:
            msstep[lanes] = _ms_per_step(lanes)
            if msstep[lanes] is not None:
                print(
                    f"ms/step @ {lanes} lanes: {msstep[lanes]:.2f}",
                    file=sys.stderr,
                    flush=True,
                )

    # mesh partitioning (parallel/partition.py): the same small-grid
    # rate through the explicit shard_map layout; budget-guarded (the
    # partitioned runner is its own compile), honest-zero on skip/fail
    mesh_rate, mesh_note = None, None
    if TOTAL_BUDGET_S - _since_birth() < MESH_MIN_BUDGET_S:
        mesh_note = "skipped: insufficient budget for the mesh_shard compile"
        print(f"mesh_shard self-check {mesh_note}", file=sys.stderr,
              flush=True)
    else:
        mesh_rate = _mesh_shard_rate()
        if mesh_rate is None:
            mesh_note = "failed (see stderr)"
        else:
            print(
                f"mesh_shard self-check: {mesh_rate:.2f} points/s "
                f"({len(jax.devices())}-device shard_map)",
                file=sys.stderr,
                flush=True,
            )

    # fleet orchestration (fantoch_tpu/fleet): 1- vs 2-worker
    # subprocess drains of a small campaign grid; budget-guarded (two
    # extra subprocess runs + a possible cold compile), honest-zero on
    # skip/fail, byte-identity tripwire like the dispatch self-check
    fleet_rates, fleet_note = None, None
    if TOTAL_BUDGET_S - _since_birth() < FLEET_MIN_BUDGET_S:
        fleet_note = (
            "skipped: insufficient budget for the fleet subprocess runs"
        )
        print(f"fleet self-check {fleet_note}", file=sys.stderr,
              flush=True)
    else:
        fleet_rates = _fleet_units()
        if fleet_rates is None:
            fleet_note = "failed (see stderr)"
        elif fleet_rates[2] is not None:
            fleet_note, fleet_rates = fleet_rates[2], None
        else:
            print(
                f"fleet self-check: {fleet_rates[0]:.2f} units/s solo "
                f"vs {fleet_rates[1]:.2f} units/s 2-worker "
                "(merged byte-identical)",
                file=sys.stderr,
                flush=True,
            )

    # AOT cold start (parallel/aot.py): two fresh subprocesses acquire
    # the same sweep runner — trace+compile+serialize, then load — so
    # `trace_compile_s` vs `aot_load_s` is the per-worker tax the
    # fleet-shared executable removes; budget-guarded (the first child
    # IS a deliberate cold compile), honest-zero on skip/fail,
    # byte-identity tripwire like the dispatch self-check
    aot_times, aot_note = None, None
    if TOTAL_BUDGET_S - _since_birth() < AOT_MIN_BUDGET_S:
        aot_note = (
            "skipped: insufficient budget for the aot cold-start "
            "subprocess runs"
        )
        print(f"aot cold-start {aot_note}", file=sys.stderr, flush=True)
    else:
        aot_times = _aot_cold_start()
        if aot_times is None:
            aot_note = "failed (see stderr)"
        elif aot_times[2] is not None:
            aot_note, aot_times = aot_times[2], None
        else:
            print(
                f"aot cold start: trace+compile {aot_times[0]:.2f}s vs "
                f"serialized load {aot_times[1]:.2f}s "
                "(byte-identical results)",
                file=sys.stderr,
                flush=True,
            )

    # heterogeneous megabatch (engine/hetero.py): mixed switch batch vs
    # per-protocol homogeneous batches, warm rate + cold compile
    # collapse — each its own compiles, so each rides a budget guard
    hetero_rates, hetero_note = None, None
    if TOTAL_BUDGET_S - _since_birth() < HETERO_MIN_BUDGET_S:
        hetero_note = (
            "skipped: insufficient budget for the hetero runner compile"
        )
        print(f"hetero self-check {hetero_note}", file=sys.stderr,
              flush=True)
    else:
        hetero_rates = _hetero_rate()
        if hetero_rates is None:
            hetero_note = "failed (see stderr)"
        elif hetero_rates[2] is not None:
            hetero_note, hetero_rates = hetero_rates[2], None
        else:
            print(
                f"hetero self-check: {hetero_rates[0]:.2f} points/s "
                f"mixed vs {hetero_rates[1]:.2f} points/s homogeneous "
                "(byte-identical per lane)",
                file=sys.stderr,
                flush=True,
            )

    hetero_cold, hetero_cold_note = None, None
    if TOTAL_BUDGET_S - _since_birth() < HETERO_COLD_MIN_BUDGET_S:
        hetero_cold_note = (
            "skipped: insufficient budget for the hetero cold-start "
            "subprocess runs"
        )
        print(f"hetero cold-start {hetero_cold_note}", file=sys.stderr,
              flush=True)
    else:
        hetero_cold = _hetero_cold_collapse()
        if hetero_cold is None:
            hetero_cold_note = "failed (see stderr)"
        elif hetero_cold[2] is not None:
            hetero_cold_note, hetero_cold = hetero_cold[2], None
        else:
            print(
                f"hetero cold start: 1 compile {hetero_cold[0]:.2f}s "
                f"vs {len(HETERO_PROTOCOLS)} compiles "
                f"{hetero_cold[1]:.2f}s (byte-identical results)",
                file=sys.stderr,
                flush=True,
            )

    # durability tax: one checkpointed segment's save+restore+compare
    # (device-state fetch excluded — measured on host arrays)
    ckpt_s = _checkpoint_roundtrip()
    if ckpt_s is not None:
        print(
            f"checkpoint roundtrip: {CKPT_LANES} tempo lanes in "
            f"{ckpt_s:.2f}s (bit-exact)",
            file=sys.stderr,
            flush=True,
        )

    points_per_sec = total_points / elapsed
    per_chip_target = 10_000 / 60.0 / 8.0  # north-star rate, per chip
    platform = jax.devices()[0].platform
    fallback = bool(int(_os.environ.get("FANTOCH_BENCH_CPU_FALLBACK", "0")))
    static_cost = _static_kernel_cost()
    print(
        json.dumps(
            {
                "metric": "sweep_points_per_sec",
                "value": round(points_per_sec, 2),
                "unit": (
                    ("CPU-mesh fallback (TPU unreachable): " if fallback
                     else "")
                    + f"all-protocol configs/s (n={N}, f=1-2, "
                    f"{COMMANDS * clients} cmds each, {total_points} "
                    f"points, per-protocol "
                    + ",".join(
                        f"{k}={v}" for k, v in per_proto.items()
                    )
                    + f", {len(jax.devices())} {platform} device(s))"
                ),
                "platform": platform,
                "vs_baseline": round(points_per_sec / per_chip_target, 3),
                "fuzz_schedules_per_sec": round(fuzz_sps, 2),
                **({"fuzz_note": fuzz_note} if fuzz_note else {}),
                # distinct coverage buckets per 1000 schedules on the
                # fixed-seed tempo n=3 point, same in-process budget
                # (0.0 = skipped/failed; note carries the reason)
                "fuzz_buckets_per_ksched": (
                    round(cov_rates[1], 2) if cov_rates else 0.0
                ),
                "fuzz_buckets_per_ksched_blind": (
                    round(cov_rates[0], 2) if cov_rates else 0.0
                ),
                # each fault class steered alone over the same budget
                # (zeros = the shared skip/failure reason above)
                "fuzz_buckets_per_ksched_class": (
                    {c: round(r, 2) for c, r in cov_rates[2].items()}
                    if cov_rates
                    else {c: 0.0 for c in COV_CLASSES}
                ),
                "fuzz_cov_schedules": COV_CHUNK * COV_CHUNKS,
                **({"fuzz_cov_note": cov_note} if cov_note else {}),
                # binary map write + keep-2 compaction vs the JSON
                # state rewrite, COVMAP_BUCKETS synthetic buckets
                # (0.0 = write failed; note carries the reason)
                "covmap_compact_s": (
                    round(covmap_s[0], 3) if covmap_s else 0.0
                ),
                "covmap_json_s": (
                    round(covmap_s[1], 3) if covmap_s else 0.0
                ),
                "covmap_buckets": COVMAP_BUCKETS,
                **({"covmap_note": covmap_note} if covmap_note else {}),
                # save + restore + bit-exact compare of a CKPT_LANES
                # tempo state (0.0 = self-check unavailable, see stderr)
                "checkpoint_roundtrip_s": (
                    round(ckpt_s, 3) if ckpt_s is not None else 0.0
                ),
                "checkpoint_lanes": CKPT_LANES,
                # epoch-table build time for TRAFFIC_TABLE_LANES lanes
                # (0.0 = self-check unavailable, see stderr)
                "traffic_table_build_s": (
                    round(table_s, 3) if table_s is not None else 0.0
                ),
                "traffic_table_lanes": TRAFFIC_TABLE_LANES,
                # measured flat vs diurnal rate on the small tempo grid
                # (0.0 = skipped/failed; note carries the reason)
                "sweep_points_per_sec_flat_small": (
                    round(traffic_rates[0], 2) if traffic_rates else 0.0
                ),
                "sweep_points_per_sec_diurnal": (
                    round(traffic_rates[1], 2) if traffic_rates else 0.0
                ),
                **({"traffic_note": traffic_note} if traffic_note else {}),
                # measured closed vs open-loop rate on the small tempo
                # grid, and the relative per-point slowdown the arrival
                # machinery costs (0.0 = skipped/failed; note carries
                # the reason)
                "sweep_points_per_sec_closed_small": (
                    round(openloop_rates[0], 2) if openloop_rates else 0.0
                ),
                "sweep_points_per_sec_openloop": (
                    round(openloop_rates[1], 2) if openloop_rates else 0.0
                ),
                "openloop_vs_closed_overhead": (
                    round(openloop_rates[0] / openloop_rates[1] - 1.0, 3)
                    if openloop_rates and openloop_rates[1] > 0
                    else 0.0
                ),
                # curve points per second of a tiny tempo knee campaign
                # run end-to-end (journal + checkpoints + artifact;
                # 0.0 = skipped/failed, same note)
                "knee_points_per_sec": (
                    round(knee_rate[0], 2) if knee_rate else 0.0
                ),
                "knee_points": knee_rate[1] if knee_rate else 0,
                "knee_loads": list(KNEE_LOADS),
                **(
                    {"openloop_note": openloop_note}
                    if openloop_note
                    else {}
                ),
                # serial-minus-pipelined wall time on the fixed small
                # tempo grid (positive = the in-flight window wins;
                # 0.0 = skipped/failed, note carries the reason)
                "dispatch_overhead_s": (
                    round(dispatch[0] - dispatch[1], 3) if dispatch
                    else 0.0
                ),
                "dispatch_serial_s": (
                    round(dispatch[0], 3) if dispatch else 0.0
                ),
                "dispatch_pipelined_s": (
                    round(dispatch[1], 3) if dispatch else 0.0
                ),
                # the scan-fused window run of the same grid, and each
                # variant's measured host dispatch count (empty dict =
                # skipped/failed) — the segment loop pays scan_window
                # round-trips per checkpoint window, the fused loop one
                "dispatch_fused_s": (
                    round(dispatch[2], 3) if dispatch else 0.0
                ),
                "window_roundtrips": dispatch[3] if dispatch else {},
                **(
                    {"dispatch_note": dispatch_note}
                    if dispatch_note
                    else {}
                ),
                # measured segment-runner ms/step, self-describing:
                # every measured shape lands under its ACTUAL lane
                # count (a CPU-fallback round never masquerades as the
                # documented shapes), and the canonical 512/2048 keys
                # are non-zero only when measured at exactly those
                # shapes (0.0 = unavailable at that shape this round)
                "ms_per_step_512": (
                    lambda v: round(v, 3) if v is not None else 0.0
                )(msstep.get(512)),
                "ms_per_step_2048": (
                    lambda v: round(v, 3) if v is not None else 0.0
                )(msstep.get(2048)),
                "ms_per_step_measured": {
                    str(lanes): round(v, 3)
                    for lanes, v in sorted(msstep.items())
                    if v is not None
                },
                "msstep_lanes": list(MSSTEP_LANES),
                # the explicit shard_map layout at the small-grid shape
                # (0.0 = skipped/failed; note carries the reason)
                "sweep_points_per_sec_mesh_shard": (
                    round(mesh_rate, 2) if mesh_rate is not None else 0.0
                ),
                **({"mesh_shard_note": mesh_note} if mesh_note else {}),
                # subprocess fleet drain of a FLEET_UNITS-unit campaign
                # (0.0 = skipped/failed; note carries the reason — an
                # IDENTITY-VIOLATION note means the 2-worker merge
                # diverged from the 1-worker control)
                "fleet_units_per_sec": (
                    round(fleet_rates[1], 3) if fleet_rates else 0.0
                ),
                "fleet_units_per_sec_single": (
                    round(fleet_rates[0], 3) if fleet_rates else 0.0
                ),
                "fleet_units": FLEET_UNITS,
                **({"fleet_note": fleet_note} if fleet_note else {}),
                # fresh-subprocess runner acquisition with vs without a
                # serialized executable (parallel/aot.py): the
                # per-worker cold-start tax fleet-shared AOT artifacts
                # remove (0.0 = skipped/failed; note carries the
                # reason — an IDENTITY-VIOLATION note means the loaded
                # executable diverged from the traced control)
                "trace_compile_s": (
                    round(aot_times[0], 3) if aot_times else 0.0
                ),
                "aot_load_s": (
                    round(aot_times[1], 3) if aot_times else 0.0
                ),
                **({"aot_note": aot_note} if aot_note else {}),
                # the protocol_id-switched mixed batch vs per-protocol
                # homogeneous batches at identical total lanes, warm
                # (0.0 = skipped/failed; note carries the reason — an
                # IDENTITY-VIOLATION note means a mixed lane diverged
                # from its homogeneous control)
                "hetero_points_per_sec": (
                    round(hetero_rates[0], 2) if hetero_rates else 0.0
                ),
                "hetero_points_per_sec_homo": (
                    round(hetero_rates[1], 2) if hetero_rates else 0.0
                ),
                "hetero_protocols": list(HETERO_PROTOCOLS),
                **({"hetero_note": hetero_note} if hetero_note else {}),
                # cold-subprocess compile collapse: the same grid as
                # ONE switch executable vs one executable per protocol
                # (0.0 = skipped/failed; note carries the reason)
                "hetero_cold_start_s": (
                    round(hetero_cold[0], 3) if hetero_cold else 0.0
                ),
                "hetero_cold_start_homo_s": (
                    round(hetero_cold[1], 3) if hetero_cold else 0.0
                ),
                "hetero_compile_collapse": (
                    [1, len(HETERO_PROTOCOLS)] if hetero_cold else [0, 0]
                ),
                **(
                    {"hetero_cold_note": hetero_cold_note}
                    if hetero_cold_note
                    else {}
                ),
                **(
                    {"static_kernel_cost": static_cost}
                    if static_cost
                    else {}
                ),
                # per-tier device->host sync counts of the host sweep
                # drivers (GL301 ledger) — static twin of the measured
                # dispatch_overhead_s above
                "host_sync_ledger": _host_sync_ledger(),
                # per-rule determinism-exception counts of the artifact
                # writers (GL401-GL404 ledger) — the static surface
                # behind every byte-identity cmp in this report
                "determinism_ledger": _determinism_ledger(),
                # per-protocol axis-shardability verdict counts
                # (GL501 ledger) — the static twin of the 2-D-mesh
                # sweep numbers, proving which state planes may shard
                "shard_axis_ledger": _shard_axis_ledger(),
                # per-grid megabatch amplification ratios (GL601/GL603
                # ledger) — unified-skeleton bytes over native bytes,
                # the static cost of a heterogeneous lax.switch batch
                "skeleton_waste_ratio": _skeleton_waste_ratio(),
            }
        )
    )


# trace-time jax error classes are OUR bugs (bad shapes, concretizing a
# tracer), never the backend's — they must surface as a red run instead
# of burning the retry ladder or masquerading as infra downtime
_TRACE_BUG_MARKERS = ("Tracer", "Concretization")

# XLA error statuses that reproduce on every attempt regardless of
# backend health — retrying or downgrading them would hide a code bug
_DETERMINISTIC_XLA_STATUSES = (
    "INVALID_ARGUMENT",
    "FAILED_PRECONDITION",
    "UNIMPLEMENTED",
    "NOT_FOUND",
    "OUT_OF_RANGE",
    "ALREADY_EXISTS",
)


def _infra_shaped(e: BaseException) -> bool:
    """True for failures that point at the device backend/tunnel rather
    than at our code; exactly these are retried in a fresh process and,
    once the retry budget is spent, downgraded to a zero-value artifact.

    Three shapes have been observed from the tunneled device backend:
    * connection errors (ConnectionResetError, BrokenPipeError,
      TimeoutError) when the tunnel drops mid-run — NOT all OSErrors;
      a missing/unwritable path is deterministic and must not burn
      the 5-minute retry ladder;
    * jax/jaxlib runtime errors (JaxRuntimeError, XlaRuntimeError)
      when the device worker crashes — matched by module prefix since
      their import path moves between jax versions, minus trace-time
      error classes (see _TRACE_BUG_MARKERS);
    * plain RuntimeError("Unable to initialize backend ...") when the
      backend is down at startup (the exact failure BENCH_r02 hit).
    Deterministic failures (failing-lane assertions) are never retried.
    """
    if isinstance(e, (ConnectionError, BrokenPipeError, TimeoutError)):
        return True
    mod = type(e).__module__ or ""
    if mod.startswith(("jax", "jaxlib")):
        name = type(e).__name__
        if any(m in name for m in _TRACE_BUG_MARKERS):
            return False
        msg = str(e)
        # availability markers win outright: a tunneled-backend failure
        # often embeds secondary status text (NOT_FOUND inside an
        # UNAVAILABLE chain) that must not be mistaken for a code bug
        if "UNAVAILABLE" in msg or "Unable to initialize backend" in msg:
            return True
        # deterministic XLA statuses are code bugs (a bad lane shape
        # raises INVALID_ARGUMENT on every attempt); they may be
        # wrapped ("Error loading program: INVALID_ARGUMENT: ..."), so
        # match anywhere — the availability precedence above already
        # protects the tunneled-outage case ADVICE r4 flagged
        return not any(s in msg for s in _DETERMINISTIC_XLA_STATUSES)
    if isinstance(e, RuntimeError):
        msg = str(e).lower()
        return "backend" in msg or "tpu" in msg or "device" in msg
    return False


# one predicate on purpose: what we retry is exactly what we would
# blame on infra when the budget runs out
_retriable = _infra_shaped


# waits before each fresh-process retry: quick for transient worker
# crashes, then long enough to ride out a backend restart
RETRY_WAITS_S = (5, 60, 240)

# Budgets.  BENCH_r03 died rc=124: axon backend init HANGS in-process
# when the tunnel is down, the retry sleeps stacked on top, and the
# driver's own timeout killed the run with no artifact.  Backend init
# now happens first in a throwaway subprocess (fantoch_tpu.platform)
# where a hard timeout can kill it, under two budgets:
# * DEADLINE_S bounds ONE process's pre-run probe phase (measured from
#   FANTOCH_BENCH_T0, which a crash-retried child resets so it gets a
#   short re-probe window instead of a spent deadline);
# * TOTAL_BUDGET_S bounds probing + retry sleeps across ALL re-execs
#   (measured from FANTOCH_BENCH_BIRTH, never reset) — past it no
#   further retry sleep is started, so the driver's own timeout cannot
#   catch us mid-sleep with no artifact.
# Once a budget is spent on an infra failure we emit one honest
# zero-value JSON line and exit 0 so the driver always gets a parsed
# artifact; code bugs (non-infra exceptions) still exit nonzero.
DEADLINE_S = float(_os.environ.get("FANTOCH_BENCH_DEADLINE", "600"))
TOTAL_BUDGET_S = float(
    _os.environ.get("FANTOCH_BENCH_TOTAL_BUDGET", "1500")
)
RETRY_PROBE_BUDGET_S = 180.0  # re-probe window after a mid-run crash
PROBE_TIMEOUT_S = 120.0
PROBE_WAITS_S = (15, 60, 120)

_PROC_T0 = time.time()  # this process's start, for honest reporting


def _since_birth() -> float:
    birth = float(
        _os.environ.setdefault("FANTOCH_BENCH_BIRTH", repr(_PROC_T0))
    )
    return time.time() - birth


def _remaining() -> float:
    t0 = float(_os.environ.setdefault("FANTOCH_BENCH_T0", repr(_PROC_T0)))
    return DEADLINE_S - (time.time() - t0)


def _covmap_compact_or_none() -> "tuple[float, float] | None":
    import sys

    try:
        return _covmap_compact()
    except Exception as e:  # noqa: BLE001
        print(
            f"covmap self-check failed: {type(e).__name__}: {e}",
            file=sys.stderr,
        )
        return None


def _emit_unreachable(reason: str = "unreachable at startup") -> None:
    import sys

    spent = time.time() - _PROC_T0
    print(
        f"bench: device backend {reason} ({spent:.0f}s this process, "
        f"{_since_birth():.0f}s total) — emitting zero-value artifact",
        file=sys.stderr,
    )
    # the artifact still carries a real device-free number: the static
    # kernel ledger of the tempo 512-lane step (CPU subprocess, never
    # touches the dead backend)
    static_cost = _static_kernel_cost(timeout_s=180.0)
    print(
        json.dumps(
            {
                "metric": "sweep_points_per_sec",
                "value": 0.0,
                "unit": (
                    f"no measurement: TPU backend {reason} after "
                    f"{_since_birth():.0f}s "
                    "(harness verified on CPU in tests/)"
                ),
                "platform": "none",
                "vs_baseline": 0.0,
                "fuzz_schedules_per_sec": 0.0,
                # coverage discovery needs the monitored device runner
                # too — honest zeros with the shared reason
                "fuzz_buckets_per_ksched": 0.0,
                "fuzz_buckets_per_ksched_blind": 0.0,
                "fuzz_buckets_per_ksched_class": {
                    c: 0.0 for c in COV_CLASSES
                },
                "fuzz_cov_schedules": COV_CHUNK * COV_CHUNKS,
                "fuzz_cov_note": f"skipped: TPU backend {reason}",
                # covmap persistence is pure host I/O — still a real
                # measurement here, like the table build below
                **(
                    (lambda s: {
                        "covmap_compact_s": round(s[0], 3),
                        "covmap_json_s": round(s[1], 3),
                    } if s else {
                        "covmap_compact_s": 0.0,
                        "covmap_json_s": 0.0,
                        "covmap_note": "failed (see stderr)",
                    })(_covmap_compact_or_none())
                ),
                "covmap_buckets": COVMAP_BUCKETS,
                # the roundtrip needs a live (CPU) jax backend to build
                # the tempo state; the CPU-fallback path measures it,
                # this last-ditch artifact records an honest zero
                "checkpoint_roundtrip_s": 0.0,
                "checkpoint_lanes": CKPT_LANES,
                # table build is device-free and still measurable here
                "traffic_table_build_s": (
                    lambda s: round(s, 3) if s is not None else 0.0
                )(_traffic_table_build()),
                "traffic_table_lanes": TRAFFIC_TABLE_LANES,
                "sweep_points_per_sec_flat_small": 0.0,
                "sweep_points_per_sec_diurnal": 0.0,
                "traffic_note": f"sweeps skipped: TPU backend {reason}",
                # the open-loop grid and knee campaign need the device
                # runner too — honest zeros with the shared reason
                "sweep_points_per_sec_closed_small": 0.0,
                "sweep_points_per_sec_openloop": 0.0,
                "openloop_vs_closed_overhead": 0.0,
                "knee_points_per_sec": 0.0,
                "knee_points": 0,
                "knee_loads": list(KNEE_LOADS),
                "openloop_note": f"skipped: TPU backend {reason}",
                "dispatch_overhead_s": 0.0,
                "dispatch_serial_s": 0.0,
                "dispatch_pipelined_s": 0.0,
                "dispatch_fused_s": 0.0,
                "window_roundtrips": {},
                "dispatch_note": f"skipped: TPU backend {reason}",
                "ms_per_step_512": 0.0,
                "ms_per_step_2048": 0.0,
                "ms_per_step_measured": {},
                "msstep_lanes": list(MSSTEP_LANES),
                "sweep_points_per_sec_mesh_shard": 0.0,
                "mesh_shard_note": f"skipped: TPU backend {reason}",
                "fleet_units_per_sec": 0.0,
                "fleet_units_per_sec_single": 0.0,
                "fleet_units": FLEET_UNITS,
                "fleet_note": f"skipped: TPU backend {reason}",
                # the aot cold-start children need a live backend to
                # compile against — honest zeros with the shared reason
                "trace_compile_s": 0.0,
                "aot_load_s": 0.0,
                "aot_note": f"skipped: TPU backend {reason}",
                # the mixed switch batch compiles against the device
                # runner too — honest zeros with the shared reason
                "hetero_points_per_sec": 0.0,
                "hetero_points_per_sec_homo": 0.0,
                "hetero_protocols": list(HETERO_PROTOCOLS),
                "hetero_note": f"skipped: TPU backend {reason}",
                "hetero_cold_start_s": 0.0,
                "hetero_cold_start_homo_s": 0.0,
                "hetero_compile_collapse": [0, 0],
                "hetero_cold_note": f"skipped: TPU backend {reason}",
                **(
                    {"static_kernel_cost": static_cost}
                    if static_cost
                    else {}
                ),
                # the sync + determinism + shard + skeleton ledgers
                # are static (pure AST / checked-in JSON) — real
                # numbers even in this dead-backend artifact, not
                # placeholder zeros
                "host_sync_ledger": _host_sync_ledger(),
                "determinism_ledger": _determinism_ledger(),
                "shard_axis_ledger": _shard_axis_ledger(),
                "skeleton_waste_ratio": _skeleton_waste_ratio(),
            }
        )
    )
    sys.exit(0)


# CPU-fallback shape: small enough that a full five-protocol mesh run
# fits what is left of the driver budget after the probe ladder, big
# enough to be a real measurement (2 subsets x 2 f x 4 conflicts = 16
# points per protocol, 80 total)
_CPU_FALLBACK_ENV = {
    "FANTOCH_BENCH_SUBSETS": "2",
    "FANTOCH_BENCH_COMMANDS": "10",
    "FANTOCH_BENCH_CHUNK": "16",
    "FANTOCH_BENCH_FUZZ_SCHEDULES": "8",
    "FANTOCH_BENCH_COV_CHUNK": "8",
    "FANTOCH_BENCH_COV_CHUNKS": "3",
    # the per-class steered passes triple the coverage self-check's
    # schedule count, and the synthetic compaction map shrinks to keep
    # the host-mesh run's I/O share negligible
    "FANTOCH_BENCH_COVMAP_BUCKETS": "20000",
    "FANTOCH_BENCH_CKPT_LANES": "64",
    "FANTOCH_BENCH_TRAFFIC_LANES": "64",
    "FANTOCH_BENCH_TRAFFIC_SUBSETS": "1",
    # open-loop self-checks on the host mesh: one subset for the
    # closed-vs-open delta, a 2-load knee ladder with short lanes
    "FANTOCH_BENCH_OPENLOOP_SUBSETS": "1",
    "FANTOCH_BENCH_KNEE_COMMANDS": "6",
    "FANTOCH_BENCH_KNEE_LOADS": "50,200",
    "FANTOCH_BENCH_DISPATCH_SUBSETS": "1",
    # measured on the 2-core CPU mesh: 4-step segments make the
    # per-call dispatch tax a visible fraction (serial 4.8s vs
    # pipelined 3.9s on the tune grid); 8+ steps wash it out
    "FANTOCH_BENCH_DISPATCH_SEGMENT": "4",
    "FANTOCH_BENCH_MSSTEP_LANES": "16,64",
    "FANTOCH_BENCH_MSSTEP_STEPS": "32",
    # fleet + mesh_shard self-checks on the host mesh: tiny units (the
    # subprocess workers pay CLI + jax startup per run, so the unit
    # compute must not dominate the orchestration being measured) and
    # a single-subset mesh grid
    "FANTOCH_BENCH_FLEET_COMMANDS": "5",
    "FANTOCH_BENCH_FLEET_SEGMENT": "256",
    "FANTOCH_BENCH_MESH_SUBSETS": "1",
    # aot cold-start children: each pays a full cold compile by design,
    # so the unit shape must be the smallest real sweep (one subset,
    # few commands) for two subprocess compiles to fit the budget
    "FANTOCH_BENCH_AOT_COMMANDS": "5",
    "FANTOCH_BENCH_AOT_SUBSETS": "1",
    # hetero self-checks on the host mesh: two protocols (the switch
    # still exercises real cross-branch routing), one subset, short
    # lanes — the cold twin pays 3 deliberate compiles between its
    # children, so the shapes must stay minimal
    "FANTOCH_BENCH_HETERO_PROTOCOLS": "basic,tempo",
    "FANTOCH_BENCH_HETERO_SUBSETS": "1",
    "FANTOCH_BENCH_HETERO_COMMANDS": "5",
}

# below this remaining total budget a CPU fallback run cannot plausibly
# finish (cold compiles alone can eat minutes) — emit the honest zero
# instead of starting a run the driver's timeout would kill mid-flight,
# artifact-less
_CPU_FALLBACK_MIN_BUDGET_S = 300.0


def _cpu_fallback(reason: str = "unreachable at startup") -> None:
    """Probe ladder exhausted: re-exec as a full CPU-mesh bench run
    (reduced shape, 8-device host mesh) so the artifact carries a
    MEASURED value with explicit cpu provenance instead of an
    honest-zero (VERDICT r5 next-round #1). Falls back to the zero
    artifact if the CPU run itself already failed once, or when too
    little of the total budget remains for it to finish."""
    import sys

    if int(_os.environ.get("FANTOCH_BENCH_CPU_FALLBACK", "0")):
        _emit_unreachable(f"{reason}; CPU fallback failed too")
    if TOTAL_BUDGET_S - _since_birth() < _CPU_FALLBACK_MIN_BUDGET_S:
        _emit_unreachable(
            f"{reason}; no budget left for a CPU fallback run"
        )
    print(
        f"bench: device backend {reason} after {_since_birth():.0f}s — "
        "falling back to a CPU-mesh bench run",
        file=sys.stderr,
    )
    _os.environ["FANTOCH_BENCH_CPU_FALLBACK"] = "1"
    _os.environ["JAX_PLATFORMS"] = "cpu"
    flags = _os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        _os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    for k, v in _CPU_FALLBACK_ENV.items():
        _os.environ.setdefault(k, v)
    _os.execv(sys.executable, [sys.executable] + sys.argv)


if __name__ == "__main__":
    import os
    import sys

    cpu_mode = os.environ.get("JAX_PLATFORMS") == "cpu"
    os.environ.setdefault("FANTOCH_BENCH_BIRTH", repr(_PROC_T0))
    if not cpu_mode:
        # never touch jax in-process until a throwaway probe proves the
        # backend can initialize (see the budget notes above)
        from fantoch_tpu.platform import probe_device_backend

        if int(os.environ.get("FANTOCH_BENCH_RETRIED", "0")):
            os.environ["FANTOCH_BENCH_T0"] = repr(
                time.time() - max(DEADLINE_S - RETRY_PROBE_BUDGET_S, 0.0)
            )
        probe_attempt = 0
        while True:
            # bounded by this process's deadline AND the never-reset
            # total budget, so late crash-retries can't push probing
            # past what the driver's own timeout allows
            budget = min(
                _remaining(), TOTAL_BUDGET_S - _since_birth()
            )
            if budget < 30:
                _cpu_fallback()
            status, plat = probe_device_backend(
                min(PROBE_TIMEOUT_S, budget)
            )
            if status == "up":
                print(f"bench: backend up ({plat})", file=sys.stderr)
                break
            if status == "cpu-only":
                # deterministic: this jax install has no device plugin
                # at all — retrying can never fix it
                _cpu_fallback("absent (cpu-only jax install)")
            wait = PROBE_WAITS_S[
                min(probe_attempt, len(PROBE_WAITS_S) - 1)
            ]
            probe_attempt += 1
            if (
                min(_remaining(), TOTAL_BUDGET_S - _since_birth())
                < wait + 30
            ):
                _cpu_fallback()
            print(
                f"bench: backend probe failed; retry in {wait}s "
                f"({_remaining():.0f}s of budget left)",
                file=sys.stderr,
            )
            time.sleep(wait)
    try:
        main()
    except Exception as e:
        import traceback

        traceback.print_exc()
        attempt = int(os.environ.get("FANTOCH_BENCH_RETRIED", "0"))
        if (
            not cpu_mode
            and _retriable(e)
            and attempt < len(RETRY_WAITS_S)
            and _since_birth() + RETRY_WAITS_S[attempt] < TOTAL_BUDGET_S
        ):
            wait = RETRY_WAITS_S[attempt]
            print(
                f"bench: retriable backend failure ({type(e).__name__}); "
                f"retry {attempt + 1}/{len(RETRY_WAITS_S)} in {wait}s",
                file=sys.stderr,
            )
            time.sleep(wait)
            # the child resets FANTOCH_BENCH_T0 itself (see above)
            os.environ["FANTOCH_BENCH_RETRIED"] = str(attempt + 1)
            # fresh process: the in-process JAX client is dead after a
            # worker crash, so re-exec rather than re-call main()
            os.execv(sys.executable, [sys.executable] + sys.argv)
        if not cpu_mode and _infra_shaped(e):
            print(
                "bench: backend still unavailable and retry budget "
                "spent — giving up",
                file=sys.stderr,
            )
            _cpu_fallback("crashed mid-run, retry budget spent")
        if cpu_mode and int(
            os.environ.get("FANTOCH_BENCH_CPU_FALLBACK", "0")
        ):
            # we are the degraded-mode child: the driver must still get
            # a parsed artifact, so close the ladder with the honest
            # zero instead of a bare traceback
            _emit_unreachable(
                f"unreachable; CPU fallback crashed "
                f"({type(e).__name__})"
            )
        raise

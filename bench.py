#!/usr/bin/env python
"""Headline benchmark: batched Tempo-sweep throughput on device.

Runs a (region-set × f × conflict-rate) sweep of the flagship Tempo
protocol through the on-device engine — the TPU-native replacement for
the reference's rayon sweep (fantoch_ps/src/bin/simulation.rs:165-217,
one CPU thread per config) — and reports swept configs/second.

Shape: n=5 replicas, f ∈ {1, 2}, 4 conflict rates, 128 five-region
subsets of the 20-region GCP planet = 1,024 sweep points, 250 commands
each, run in device-sized chunks (512 lanes is the measured per-step
throughput sweet spot on a v5e chip).

Baseline: the north-star target from BASELINE.md is 10,000 sweep points
in under 60 s on a v5e-8, i.e. ~20.8 points/s per chip; ``vs_baseline``
is measured single-chip points/s over that per-chip rate (>1.0 beats
the target rate pro-rata). Timing excludes compilation (cached across
chunks) but includes host-side lane construction and result collection.
"""

from __future__ import annotations

import itertools
import json
import time

import jax

from fantoch_tpu.core import Config, Planet
from fantoch_tpu.engine import EngineDims
from fantoch_tpu.engine.protocols import TempoDev
from fantoch_tpu.parallel import make_sweep_specs, run_sweep

N = 5
COMMANDS = 50
CLIENTS_PER_REGION = 1
CONFLICTS = [0, 10, 50, 100]
FS = [1, 2]
SUBSETS = 128  # region sets → 128 × 2 × 4 = 1,024 sweep points
CHUNK = 512


def main() -> None:
    planet = Planet.new()
    regions = planet.regions()
    # stride through C(20,5) so subsets are genuinely distinct (the
    # first-128 lexicographic combinations all share a 3-region prefix)
    combos = list(itertools.combinations(range(len(regions)), N))
    stride = max(1, len(combos) // SUBSETS)
    region_sets = [
        [regions[i] for i in combo] for combo in combos[::stride][:SUBSETS]
    ]
    clients = N * CLIENTS_PER_REGION
    tempo = TempoDev.for_load(keys=1 + clients, clients=clients)
    dims = EngineDims.for_protocol(
        tempo,
        n=N,
        clients=clients,
        payload=tempo.payload_width(N),
        # steady-state pool bound (closed-loop clients pace at WAN RTT;
        # measured peak ~124 at n=5) and a recycled dot window; both
        # overflow loudly (ERR_POOL / ERR_DOT), never silently
        dot_slots=64,
        regions=N,
    )
    base = Config(
        n=N, f=1, gc_interval_ms=100, tempo_detached_send_interval_ms=100
    )
    specs = make_sweep_specs(
        tempo,
        planet,
        region_sets=region_sets,
        fs=FS,
        conflicts=CONFLICTS,
        commands_per_client=COMMANDS,
        clients_per_region=CLIENTS_PER_REGION,
        dims=dims,
        config_base=base,
    )

    # vmapped lanes run until the slowest lane of their batch finishes,
    # so chunk by expected cost (f, conflict drive the step count) to
    # keep each batch homogeneous instead of letting every chunk pay
    # the global straggler
    specs.sort(key=lambda s: (s.config.f, int(s.ctx["conflict_rate"])))
    chunks = [specs[i : i + CHUNK] for i in range(0, len(specs), CHUNK)]
    # compile + warm up on the first chunk, then time the full sweep
    run_sweep(tempo, dims, chunks[0])
    t0 = time.perf_counter()
    results = []
    for chunk in chunks:
        results.extend(run_sweep(tempo, dims, chunk))
    elapsed = time.perf_counter() - t0

    bad = [(i, r.err_cause) for i, r in enumerate(results) if r.err]
    assert not bad, f"failing lanes: {bad[:8]}"
    stalled = [(i, r.requeues) for i, r in enumerate(results) if r.requeues]
    assert not stalled, f"dot-window stalls distort latency: {stalled[:8]}"
    steps = sum(r.steps for r in results)
    points_per_sec = len(specs) / elapsed
    per_chip_target = 10_000 / 60.0 / 8.0  # north-star rate, per chip
    print(
        json.dumps(
            {
                "metric": "sweep_points_per_sec",
                "value": round(points_per_sec, 2),
                "unit": f"Tempo configs/s (n={N}, f=1-2, "
                f"{COMMANDS * clients} cmds each, {len(specs)} points, "
                f"{steps / elapsed:,.0f} lane-steps/s, "
                f"{len(jax.devices())} device(s))",
                "vs_baseline": round(points_per_sec / per_chip_target, 3),
            }
        )
    )


if __name__ == "__main__":
    import os
    import sys

    try:
        main()
    except Exception as e:
        # the tunneled device worker occasionally crashes/restarts
        # mid-run; one retry IN A FRESH PROCESS (the in-process JAX
        # client is dead after a worker crash) distinguishes a flake
        # from a real failure. Deterministic failures (assertion on
        # failing lanes) are not retried.
        import traceback

        traceback.print_exc()
        retriable = type(e).__name__ in (
            "JaxRuntimeError", "XlaRuntimeError", "OSError",
        )
        if retriable and not os.environ.get("FANTOCH_BENCH_RETRIED"):
            os.environ["FANTOCH_BENCH_RETRIED"] = "1"
            os.execv(sys.executable, [sys.executable] + sys.argv)
        raise

"""The verified lane-sharding contracts (parallel/sweep.py +
parallel/partition.py + lint/lanes.py): `run_sweep(shard_lanes=True)`
and `run_sweep(mesh_shard=True)` both first *prove* the step
lane-independent (GL203 taint over the batched trace) and then split
the lane axis over the 8-device CPU mesh — implicitly (NamedSharding
inputs under jit) and explicitly (shard_map) respectively; both must
be bit-identical to the unsharded single-device path
(`shard_lanes=False`). This is the empirical pin behind the prover's
soundness note — vmap's select-masking of batched `while` trip counts
is accepted as control-only because these tests hold bitwise."""

import json

import numpy as np
import pytest

from fantoch_tpu.core import Config, Planet
from fantoch_tpu.engine import EngineDims
from fantoch_tpu.engine.protocols import dev_config_kwargs, dev_protocol
from fantoch_tpu.parallel.sweep import make_sweep_specs, run_sweep

COMMANDS = 2


def _basic_specs(lanes=8, conflicts=(0, 100)):
    planet = Planet.new()
    regions = planet.regions()
    clients = 3
    dev = dev_protocol("basic", clients)
    total = COMMANDS * clients
    dims = EngineDims.for_protocol(
        dev, n=3, clients=clients, payload=dev.payload_width(3),
        total_commands=total, dot_slots=total + 1, regions=3,
    )
    specs = make_sweep_specs(
        dev,
        planet,
        region_sets=[
            regions[i : i + 3] for i in range(lanes // len(conflicts))
        ],
        fs=[1],
        conflicts=list(conflicts),
        commands_per_client=COMMANDS,
        clients_per_region=1,
        dims=dims,
        config_base=Config(**dev_config_kwargs("basic", 3, 1)),
    )
    return dev, dims, specs


def _assert_results_equal(xs, ys):
    assert len(xs) == len(ys)
    for a, b in zip(xs, ys):
        assert a.err == b.err
        assert a.completed == b.completed
        assert a.steps == b.steps
        np.testing.assert_array_equal(np.asarray(a.hist), np.asarray(b.hist))
        for key in a.protocol_metrics:
            np.testing.assert_array_equal(
                np.asarray(a.protocol_metrics[key]),
                np.asarray(b.protocol_metrics[key]),
            )


def test_sharded_sweep_bit_identical_to_unsharded():
    import jax

    assert len(jax.devices()) == 8, "conftest must force 8 CPU devices"
    dev, dims, specs = _basic_specs()
    assert len(specs) == 8  # one lane per mesh device when sharded

    sharded = run_sweep(dev, dims, specs, shard_lanes=True)
    unsharded = run_sweep(dev, dims, specs, shard_lanes=False)
    _assert_results_equal(sharded, unsharded)


def test_mesh_shard_bit_identical_to_unsharded():
    """The explicit shard_map layout: byte-identical LaneResults on
    the 8-device mesh, including the non-divisible tail (5 lanes pad
    to 8 — padding must never leak)."""
    dev, dims, specs = _basic_specs()
    meshed = run_sweep(dev, dims, specs, mesh_shard=True)
    reference = run_sweep(dev, dims, specs, shard_lanes=False)
    _assert_results_equal(meshed, reference)
    a = [json.dumps(r.to_json(), sort_keys=True) for r in meshed]
    b = [json.dumps(r.to_json(), sort_keys=True) for r in reference]
    assert a == b, "mesh_shard serialized results diverged"

    # the tail-padding seam under shard_map: 5 specs on 8 devices
    tail = specs[:5]
    meshed5 = run_sweep(dev, dims, tail, mesh_shard=True)
    _assert_results_equal(meshed5, reference[:5])


def test_mesh_shard_rejects_contradictory_arguments():
    dev, dims, specs = _basic_specs(lanes=2, conflicts=(0, 100))
    with pytest.raises(ValueError, match="shard_lanes=False"):
        run_sweep(dev, dims, specs, mesh_shard=True, shard_lanes=False)
    from jax.sharding import Mesh

    import jax

    mesh = Mesh(np.asarray(jax.devices()[:1]), ("sweep",))
    with pytest.raises(ValueError, match="explicit mesh"):
        run_sweep(dev, dims, specs, mesh_shard=True, mesh=mesh)


def test_mesh_shard_refuses_lane_mixing_step(monkeypatch):
    """The GL203 gate guards the shard_map layout exactly like the
    NamedSharding one: a mixing step raises LaneMixingError instead
    of partitioning."""
    from fantoch_tpu.lint.report import Finding
    from fantoch_tpu.parallel import sweep as sweep_mod
    from fantoch_tpu.parallel.sweep import LaneMixingError

    monkeypatch.setattr(
        "fantoch_tpu.lint.lanes.prove_step_lane_independent",
        lambda *a, **k: [
            Finding("GL203", "syn", "x:y:reduce_sum", "cross-lane")
        ],
    )
    sweep_mod._LANE_PROOFS.clear()
    dev, dims, specs = _basic_specs(lanes=2, conflicts=(0, 100))
    try:
        with pytest.raises(LaneMixingError, match="GL203"):
            run_sweep(dev, dims, specs, mesh_shard=True)
    finally:
        sweep_mod._LANE_PROOFS.clear()


def test_mesh_shard_checkpoint_interchanges_with_reference(tmp_path):
    """Composition pin: a run interrupted under mesh_shard resumes
    under the single-device reference layout (and vice versa) —
    bit-exactly, because saves land on drained determinate boundaries,
    the layout is deliberately not a checkpoint meta key, and the
    artifact is pad-free. The NON-divisible 5-lane case is the sharp
    edge: the 8-device mesh pads 5→8 while the single-device reference
    pads 5→5, so a padded payload could never interchange — the
    artifact carries exactly the caller's lanes and each layout
    re-grows its own padding from the bit-identical last real lane."""
    from fantoch_tpu.engine.checkpoint import (
        CheckpointSpec,
        SweepInterrupted,
    )

    dev, dims, all_specs = _basic_specs()
    specs = all_specs[:5]  # 5 lanes: pad 3 on the mesh, 0 single-device
    reference = run_sweep(dev, dims, specs, shard_lanes=False)

    ck = CheckpointSpec(path=str(tmp_path / "ck"), every=1,
                        stop_after_segments=2)
    # scan_window=1 pins the per-segment ladder the stop hook counts
    # (the default window would finish the tiny batch before boundary
    # 2); the resumes below deliberately run the default window — the
    # artifact interchanges across window sizes like it does across
    # layouts
    with pytest.raises(SweepInterrupted):
        run_sweep(dev, dims, specs, mesh_shard=True, segment_steps=8,
                  scan_window=1, checkpoint=ck)
    resumed = run_sweep(
        dev, dims, specs, shard_lanes=False, segment_steps=8,
        checkpoint=CheckpointSpec(path=str(tmp_path / "ck")),
    )
    _assert_results_equal(resumed, reference)

    # and the reverse hop: reference-layout checkpoint resumed under
    # the 8-device mesh_shard partitioning
    ck2 = CheckpointSpec(path=str(tmp_path / "ck2"), every=1,
                         stop_after_segments=2)
    with pytest.raises(SweepInterrupted):
        run_sweep(dev, dims, specs, shard_lanes=False, segment_steps=8,
                  scan_window=1, checkpoint=ck2)
    resumed2 = run_sweep(
        dev, dims, specs, mesh_shard=True, segment_steps=8,
        checkpoint=CheckpointSpec(path=str(tmp_path / "ck2")),
    )
    _assert_results_equal(resumed2, reference)

"""The verified lane-sharding contract (parallel/sweep.py +
lint/lanes.py): `run_sweep(shard_lanes=True)` first *proves* the step
lane-independent (GL203 taint over the batched trace) and then shards
the lane axis over the 8-device CPU mesh; its results must be
bit-identical to the unsharded single-device path
(`shard_lanes=False`). This is the empirical pin behind the prover's
soundness note — vmap's select-masking of batched `while` trip counts
is accepted as control-only because this test holds bitwise."""

import numpy as np

from fantoch_tpu.core import Config, Planet
from fantoch_tpu.engine import EngineDims
from fantoch_tpu.engine.protocols import dev_config_kwargs, dev_protocol
from fantoch_tpu.parallel.sweep import make_sweep_specs, run_sweep

COMMANDS = 2


def test_sharded_sweep_bit_identical_to_unsharded():
    import jax

    assert len(jax.devices()) == 8, "conftest must force 8 CPU devices"
    planet = Planet.new()
    regions = planet.regions()
    clients = 3
    dev = dev_protocol("basic", clients)
    total = COMMANDS * clients
    dims = EngineDims.for_protocol(
        dev, n=3, clients=clients, payload=dev.payload_width(3),
        total_commands=total, dot_slots=total + 1, regions=3,
    )
    specs = make_sweep_specs(
        dev,
        planet,
        region_sets=[regions[i : i + 3] for i in range(4)],
        fs=[1],
        conflicts=[0, 100],
        commands_per_client=COMMANDS,
        clients_per_region=1,
        dims=dims,
        config_base=Config(**dev_config_kwargs("basic", 3, 1)),
    )
    assert len(specs) == 8  # one lane per mesh device when sharded

    sharded = run_sweep(dev, dims, specs, shard_lanes=True)
    unsharded = run_sweep(dev, dims, specs, shard_lanes=False)

    assert len(sharded) == len(unsharded) == len(specs)
    for a, b in zip(sharded, unsharded):
        assert a.err == b.err
        assert a.completed == b.completed
        assert a.steps == b.steps
        np.testing.assert_array_equal(np.asarray(a.hist), np.asarray(b.hist))
        for key in a.protocol_metrics:
            np.testing.assert_array_equal(
                np.asarray(a.protocol_metrics[key]),
                np.asarray(b.protocol_metrics[key]),
            )

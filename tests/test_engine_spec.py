"""Lane-spec construction unit tests (no engine loop needed).

Guards the ctx contract between `make_lane` (engine/spec.py) and
`init_lane_state`/`gen_key` (engine/core.py) — the round-1 breakage —
and the Zipf workload wiring (key_gen.rs:113-119 parity).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fantoch_tpu.client.key_gen import zipf_weights
from fantoch_tpu.core import Config, Planet
from fantoch_tpu.engine import EngineDims, make_lane, run_lanes
from fantoch_tpu.engine.core import PA, gen_key, init_lane_state
from fantoch_tpu.engine.dims import INF
from fantoch_tpu.engine.protocols import TempoDev


def _spec(zipf=None, conflict=50, keys=8):
    planet = Planet.new()
    n = 3
    regions = planet.regions()[:n]
    tempo = TempoDev(keys=keys)
    dims = EngineDims.for_protocol(
        tempo,
        n=n,
        clients=n,
        payload=tempo.payload_width(n),
        total_commands=5 * n,
        dot_slots=5 * n + 1,
        regions=n,
    )
    config = Config(
        n=n, f=1, gc_interval_ms=100, tempo_detached_send_interval_ms=100
    )
    spec = make_lane(
        tempo,
        planet,
        config,
        conflict_rate=conflict,
        pool_size=1,
        zipf=zipf,
        commands_per_client=5,
        clients_per_region=1,
        process_regions=regions,
        client_regions=regions,
        dims=dims,
    )
    return tempo, dims, spec


def test_make_lane_pool_ctx_feeds_init_lane_state():
    tempo, dims, spec = _spec(zipf=None)
    assert spec.ctx["key_gen_kind"] == 0
    assert spec.ctx["zipf_cum"].shape == (1,)
    st = init_lane_state(tempo, dims, spec.ctx)  # round-1 KeyError site
    # one SUBMIT per live client, keyed (emission #1, client src)
    live = (st["pool"][:, PA] < INF).sum()
    assert int(live) == dims.C


def test_make_lane_zipf_ctx():
    total_keys = 64
    tempo, dims, spec = _spec(zipf=(1.0, total_keys), keys=total_keys)
    assert spec.ctx["key_gen_kind"] == 1
    assert spec.ctx["zipf_cum"].shape == (total_keys,)
    assert spec.ctx["zipf_cum"][-1] == pytest.approx(1.0)
    st = init_lane_state(tempo, dims, spec.ctx)
    live = (st["pool"][:, PA] < INF).sum()
    assert int(live) == dims.C


def test_device_zipf_matches_weight_table():
    """Empirical device key frequencies converge to the Zipf pmf the
    host generator samples from (client/key_gen.py:52-57)."""
    total_keys = 16
    coefficient = 1.0
    tempo, dims, spec = _spec(zipf=(coefficient, total_keys), keys=total_keys)
    ctx = {k: jnp.asarray(v) for k, v in spec.ctx.items()}
    draws = 4000
    keys = jax.vmap(lambda s: gen_key(ctx, jnp.int32(0), s))(
        jnp.arange(draws, dtype=jnp.int32)
    )
    keys = np.asarray(keys)
    assert keys.min() >= 0 and keys.max() < total_keys
    freq = np.bincount(keys, minlength=total_keys) / draws
    want = zipf_weights(total_keys, coefficient)
    assert np.abs(freq - want).max() < 0.03


def test_engine_runs_zipf_lane_end_to_end():
    total_keys = 8
    tempo, dims, spec = _spec(zipf=(1.0, total_keys), keys=total_keys)
    res = run_lanes(tempo, dims, [spec])[0]
    assert not res.err
    # every issued command takes exactly one path at its coordinator
    assert int(res.protocol_metrics["fast_path"].sum()) + int(
        res.protocol_metrics["slow_path"].sum()
    ) == 5 * dims.C


def test_iset_contains_forms_agree():
    """`iset_contains` (vectorized reference form) and
    `iset_contains_gathered` (the VMEM-safe per-g form the protocols
    use) must agree on random interval sets."""
    import numpy as np

    from fantoch_tpu.engine.iset import (
        iset_contains,
        iset_contains_gathered,
    )

    rng = np.random.default_rng(7)
    S, G = 4, 5
    front = rng.integers(0, 6, size=(S,)).astype(np.int32)
    gaps = np.zeros((S, G, 2), np.int32)
    for s in range(S):
        for g in range(rng.integers(0, G + 1)):
            start = int(rng.integers(int(front[s]) + 2, 20))
            gaps[s, g] = (start, start + int(rng.integers(0, 4)))
    src = rng.integers(0, S, size=(3, 7)).astype(np.int32)
    x = rng.integers(0, 25, size=(3, 7)).astype(np.int32)

    got = np.asarray(iset_contains_gathered(front, gaps, src, x))
    want = np.asarray(iset_contains(front[src], gaps[src], x))
    np.testing.assert_array_equal(got, want)

"""Planet/latency-data tests (mirrors fantoch/src/planet/mod.rs:180-301 and
planet/dat.rs:111-155)."""

import numpy as np

from fantoch_tpu.core import Planet
from fantoch_tpu.core.util import sort_processes_by_distance


def symmetric(a, b, planet):
    return planet.ping_latency(a, b) == planet.ping_latency(b, a)


def test_latency():
    planet = Planet.new()
    assert symmetric("europe-west3", "us-central1", planet)
    # sometimes it's not symmetric
    assert not symmetric("us-east1", "europe-west3", planet)
    assert not symmetric("us-east4", "us-west1", planet)
    assert not symmetric("us-west1", "europe-west3", planet)


def test_gcp_latency_values():
    # values from planet/dat.rs:125-154 (europe-west3.dat)
    planet = Planet.new()
    expected = {
        "europe-west3": 0, "europe-west4": 7, "europe-west6": 7,
        "europe-west1": 8, "europe-west2": 13, "europe-north1": 31,
        "us-east4": 86, "northamerica-northeast1": 87, "us-east1": 98,
        "us-central1": 105, "us-west1": 136, "us-west2": 139,
        "southamerica-east1": 214, "asia-northeast1": 224,
        "asia-northeast2": 233, "asia-east1": 258, "asia-east2": 268,
        "australia-southeast1": 276, "asia-southeast1": 289,
        "asia-south1": 352,
    }
    for to, lat in expected.items():
        assert planet.ping_latency("europe-west3", to) == lat


def test_sorted():
    planet = Planet.new()
    expected = [
        "europe-west3", "europe-west4", "europe-west6", "europe-west1",
        "europe-west2", "europe-north1", "us-east4",
        "northamerica-northeast1", "us-east1", "us-central1", "us-west1",
        "us-west2", "southamerica-east1", "asia-northeast1",
        "asia-northeast2", "asia-east1", "asia-east2",
        "australia-southeast1", "asia-southeast1", "asia-south1",
    ]
    got = [r for _, r in planet.sorted("europe-west3")]
    assert got == expected


def test_equidistant():
    regions, planet = Planet.equidistant(10, 3)
    assert len(regions) == 3
    for a in regions:
        for b in regions:
            assert planet.ping_latency(a, b) == (0 if a == b else 10)


def test_latency_matrix():
    planet = Planet.new()
    regions = ["europe-west3", "us-west1"]
    mat = planet.latency_matrix(regions)
    assert mat.dtype == np.int32
    assert mat[0, 0] == 0 and mat[1, 1] == 0
    assert mat[0, 1] == 136


def test_sort_processes_by_distance():
    # mirrors util.rs:223-266
    regions = [
        "asia-east1", "asia-northeast1", "asia-south1", "asia-southeast1",
        "australia-southeast1", "europe-north1", "europe-west1",
        "europe-west2", "europe-west3", "europe-west4",
        "northamerica-northeast1", "southamerica-east1", "us-central1",
        "us-east1", "us-east4", "us-west1", "us-west2",
    ]
    processes = [(i, 0, r) for i, r in enumerate(regions)]
    planet = Planet.new()
    got = sort_processes_by_distance("europe-west3", planet, processes)
    expected = [8, 9, 6, 7, 5, 14, 10, 13, 12, 15, 16, 11, 1, 0, 4, 3, 2]
    assert [pid for pid, _ in got] == expected

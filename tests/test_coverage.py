"""Coverage-guided fuzzing (mc/coverage.py + the on-device digest).

Host-only tier: the coverage map's bucket/JSON/refusal semantics, seed
mutation (device-runnable, ``min_live``-bounded, deterministic given
journaled generator positions — chunked ≡ one-shot), and the steering
allocator's starvation floor + discovery-rate ordering. Device tier-1
(the suite's cheap monitored Basic runner): digests are nonzero,
deterministic, plan-sensitive, and a coverage-steered fuzz campaign's
SIGKILL-equivalent interrupt + resume produces a byte-identical
summary (coverage map included) vs the uninterrupted control — plus
the journaled-counter totals regression (a final chunk smaller than
``chunk`` must never be over-counted). Slow tier widens resume
determinism to tempo and to a steered 2-worker fleet.
"""

import json
import os

import pytest

from fantoch_tpu.campaign import campaign_from_json, run_campaign
from fantoch_tpu.mc.coverage import (
    MAX_SEEDS,
    CoverageError,
    CoverageMap,
    CoverageMismatchError,
    SeedPool,
    discovery_rate,
    draw_steered,
    mutate_plan,
    mutation_rng,
    plan_to_json,
    point_signature,
    rank_points,
)
from fantoch_tpu.mc.fuzz import (
    FuzzSpec,
    draw_plans,
    plan_rng,
    point_config,
    point_protocol,
    restore_rng,
    rng_state,
)

# mirrors the basic shapes of tests/test_campaign.py so device tests
# stay on the suite's cheapest monitored runner
COV_GRID = {
    "kind": "fuzz",
    "protocols": ["basic"],
    "ns": [3],
    "schedules": 6,
    "chunk": 2,
    "commands_per_client": 3,
    "seed": 1,
    "confirm": False,
    "crash_share": 0.0,
    "drop_share": 0.0,
    "coverage": True,
}


def _read(path):
    with open(path, "rb") as fh:
        return fh.read()


# ----------------------------------------------------------------------
# the coverage map (host-only)
# ----------------------------------------------------------------------


def test_coverage_map_observe_and_new_buckets():
    m = CoverageMap(signature={"protocol": "tempo"})
    fresh = m.observe([7, 7, -3, 9])
    assert fresh == [7, -3, 9]  # first-hit order, batch-deduplicated
    assert m.buckets == {7: 2, -3: 1, 9: 1}
    assert m.bucket_count == 3
    # detection without mutation
    assert m.new_buckets([7, 11, 11]) == 1
    assert m.bucket_count == 3
    assert m.observe([7, 11]) == [11]
    assert m.buckets[7] == 3


def test_coverage_map_json_round_trip_and_refusals():
    spec = FuzzSpec(protocol="tempo", n=3, seed=4)
    sig = point_signature(spec)
    m = CoverageMap(signature=sig)
    m.observe([5, -1, 5])
    obj = json.loads(json.dumps(m.to_json(), sort_keys=True))
    back = CoverageMap.from_json(obj, signature=sig)
    assert back.buckets == m.buckets and back.signature == sig
    # identical maps serialize to identical bytes (the merge contract)
    assert json.dumps(back.to_json(), sort_keys=True) == json.dumps(
        m.to_json(), sort_keys=True
    )
    # refusals, by name
    with pytest.raises(CoverageError, match="kind"):
        CoverageMap.from_json({"kind": "nope"})
    with pytest.raises(CoverageMismatchError, match="version"):
        CoverageMap.from_json(dict(obj, version=999))
    other = point_signature(FuzzSpec(protocol="fpaxos", n=5, seed=4))
    with pytest.raises(CoverageMismatchError, match="protocol"):
        CoverageMap.from_json(obj, signature=other)


def test_point_signature_binds_protocol_shape_and_workload():
    base = FuzzSpec(protocol="tempo", n=3, seed=0)
    sig = point_signature(base)
    for variant in (
        FuzzSpec(protocol="atlas", n=3, seed=0),
        FuzzSpec(protocol="tempo", n=5, seed=0),
        FuzzSpec(protocol="tempo", n=3, seed=1),
        FuzzSpec(protocol="tempo", n=3, seed=0, conflict=0),
        FuzzSpec(protocol="tempo", n=3, seed=0, inject_bug=True),
        # the fault envelope is identity too: seeds pooled under one
        # envelope must never re-mutate under another
        FuzzSpec(protocol="tempo", n=3, seed=0, crash_share=0.0),
        FuzzSpec(protocol="tempo", n=3, seed=0, drop_share=0.0),
        FuzzSpec(protocol="tempo", n=3, seed=0, jitter_max=4),
    ):
        assert point_signature(variant) != sig, variant


# ----------------------------------------------------------------------
# seeds + mutation (host-only)
# ----------------------------------------------------------------------


def test_seed_pool_bounded_fifo_dedup_and_round_trip():
    import numpy as np

    spec = FuzzSpec(protocol="tempo", n=3, schedules=MAX_SEEDS + 9,
                    seed=2, crash_share=0.3, drop_share=0.3)
    config, dev = point_config(spec), point_protocol(spec)
    plans = draw_plans(spec, config, dev)
    pool = SeedPool()
    for p in plans:
        pool.add(p)
    pool.add(plans[-1])  # duplicate: no-op
    assert len(pool) <= MAX_SEEDS
    # newest survive and parse back to the exact plans
    kept = [plan_to_json(p) for p in plans]
    uniq = []
    for obj in kept:
        if obj not in uniq:
            uniq.append(obj)
    assert pool.to_json() == uniq[-MAX_SEEDS:]
    back = SeedPool.from_json(json.loads(json.dumps(pool.to_json())))
    assert back.to_json() == pool.to_json()
    assert back.get(0) == pool.get(0)
    assert isinstance(back.get(0).jitter_max, int)
    del np


def test_mutants_stay_device_runnable_and_within_min_live():
    from fantoch_tpu.engine.faults import unavailable

    spec = FuzzSpec(protocol="tempo", n=3, schedules=24, seed=5,
                    crash_share=0.4, drop_share=0.3)
    config, dev = point_config(spec), point_protocol(spec)
    seeds = draw_plans(spec, config, dev)
    rng = mutation_rng(spec)
    for seed in seeds:
        for _ in range(4):
            m = mutate_plan(seed, rng, spec, config, dev)
            # seeded forms only: host-replayable by construction, so
            # confirmation/shrink/replay work unchanged
            assert not m.host_only(), m
            assert 1 <= m.jitter_max <= spec.jitter_max
            assert not (m.crashes and m.drop_bp), (
                "fault classes must stay disjoint like draw_plans"
            )
            if m.drop_bp:
                assert m.horizon_ms is not None
            if m.crashes:
                assert not unavailable(m, dev, config)
                assert all(t >= 0 for t in m.crashes.values())

    # the fault envelope: a point configured fault-free (the CI
    # injected-bug grids) must never GAIN crashes or drops through
    # mutation — the blind control could not have drawn them
    clean = FuzzSpec(protocol="tempo", n=3, schedules=8, seed=5,
                     crash_share=0.0, drop_share=0.0)
    cfg, cdev = point_config(clean), point_protocol(clean)
    pure = draw_plans(clean, cfg, cdev)
    assert all(not p.crashes and not p.drop_bp for p in pure)
    crng = mutation_rng(clean)
    for seed in pure:
        for _ in range(6):
            m = mutate_plan(seed, crng, clean, cfg, cdev)
            assert not m.crashes and not m.drop_bp, m


def test_draw_steered_chunked_equals_one_shot_across_journal_hop():
    spec = FuzzSpec(protocol="tempo", n=3, schedules=12, seed=11,
                    crash_share=0.3, drop_share=0.2)
    config, dev = point_config(spec), point_protocol(spec)
    pool = SeedPool()
    for p in draw_plans(spec, config, dev)[:5]:
        pool.add(p)

    rng, mrng = plan_rng(spec), mutation_rng(spec)
    reference = draw_steered(spec, config, dev, 12, rng, mrng, pool)

    rng, mrng = plan_rng(spec), mutation_rng(spec)
    first = draw_steered(spec, config, dev, 5, rng, mrng, pool)
    # the journal hop: both generator positions JSON-round-tripped
    r_state = json.loads(json.dumps(rng_state(rng)))
    m_state = json.loads(json.dumps(rng_state(mrng)))
    pool2 = SeedPool.from_json(json.loads(json.dumps(pool.to_json())))
    rest = draw_steered(
        spec, config, dev, 7,
        restore_rng(r_state), restore_rng(m_state), pool2,
    )
    assert first + rest == reference
    # the pool is consulted, not just passed: with seeds present some
    # draw must differ from the blind stream
    blind = draw_plans(spec, config, dev, count=12, rng=plan_rng(spec))
    assert reference != blind


def test_draw_steered_dry_pool_falls_back_to_root_stream():
    spec = FuzzSpec(protocol="tempo", n=3, schedules=6, seed=3)
    config, dev = point_config(spec), point_protocol(spec)
    steered = draw_steered(
        spec, config, dev, 6, plan_rng(spec), mutation_rng(spec),
        SeedPool(),
    )
    assert steered == draw_plans(spec, config, dev)


# ----------------------------------------------------------------------
# the budget allocator (host-only)
# ----------------------------------------------------------------------


def test_discovery_rate_reads_recent_window():
    assert discovery_rate(None) == 0.0
    assert discovery_rate({}) == 0.0
    assert discovery_rate({"cov_recent": [[4, 2], [4, 0]]}) == 0.25


def test_rank_points_floor_then_rate_then_canonical():
    points = [("tempo", 3), ("tempo", 5), ("fpaxos", 3), ("atlas", 3)]
    progress = {
        # hot point: high recent discovery
        "tempo/n3": {"tried": 40, "cov_recent": [[8, 6]]},
        # cold point: plateaued
        "tempo/n5": {"tried": 40, "cov_recent": [[8, 0]]},
        # starved, cold: far behind the most-fuzzed (floor fires)
        "fpaxos/n3": {"tried": 4, "cov_recent": [[4, 0]]},
        # starved AND hot — must still queue behind the earlier
        # canonical starved point: the floor is fairness, not promise
        "atlas/n3": {"tried": 2, "cov_recent": [[2, 2]]},
    }
    order = rank_points(points, progress, schedules=100, min_share=0.25)
    # starved first in canonical order, then hot before cold
    assert order == ["fpaxos/n3", "atlas/n3", "tempo/n3", "tempo/n5"]
    # complete points drop out
    progress["tempo/n3"]["tried"] = 100
    assert rank_points(
        points, progress, schedules=100, min_share=0.25
    ) == ["fpaxos/n3", "atlas/n3", "tempo/n5"]
    # nothing left
    assert rank_points(points, {}, schedules=0) == []


# ----------------------------------------------------------------------
# the on-device digest + steered campaigns (device tier-1, basic)
# ----------------------------------------------------------------------


def test_device_digest_nonzero_deterministic_plan_sensitive():
    from fantoch_tpu.mc.fuzz import run_fuzz_point

    spec = FuzzSpec(protocol="basic", n=3, f=1, schedules=4,
                    commands_per_client=3, seed=1,
                    crash_share=0.0, drop_share=0.0)
    a = run_fuzz_point(spec, confirm=False)
    b = run_fuzz_point(spec, confirm=False)
    assert a.digests == b.digests
    assert len(a.digests) == 4
    assert all(d != 0 for d in a.digests), (
        "digest 0 is reserved for unmonitored lanes"
    )
    # different jitter plans drove different interleavings at this
    # fixed seed (pinned: these specific plans produce 4 buckets)
    assert len(set(a.digests)) == 4


def test_steered_campaign_resume_map_and_summary_byte_identical(tmp_path):
    """The resume-determinism headline: a steered campaign interrupted
    mid-grid (budget stop — the same journal state a SIGKILL leaves,
    minus the in-flight chunk) and resumed produces a summary.json —
    coverage map, bucket counts, counters — byte-identical to the
    uninterrupted control's, and the final journal entries carry
    identical maps, seed pools and generator positions."""
    grid = campaign_from_json(COV_GRID)
    ctrl_dir = str(tmp_path / "ctrl")
    ctrl = run_campaign(ctrl_dir, grid)
    assert ctrl["done"]
    point = ctrl["points"]["basic/n3"]
    assert point["cov_buckets"] > 0
    assert point["coverage"]["buckets"]

    intr_dir = str(tmp_path / "intr")
    s1 = run_campaign(intr_dir, grid, budget_s=0.0)
    assert not s1["done"] and s1["interrupted"] == "budget exhausted"
    assert 0 < s1["points"]["basic/n3"]["tried"] < grid.schedules
    s2 = run_campaign(intr_dir, resume=True)
    assert s2["done"]

    assert _read(os.path.join(ctrl_dir, "summary.json")) == _read(
        os.path.join(intr_dir, "summary.json")
    )

    def final_entry(path):
        lines = [
            json.loads(x)
            for x in open(os.path.join(path, "journal.jsonl"))
        ]
        return [e for e in lines if e.get("kind") == "fuzz"][-1]

    a, b = final_entry(ctrl_dir), final_entry(intr_dir)
    for key in ("coverage", "seeds", "rng_state", "mrng_state",
                "cov_recent", "tried"):
        assert a[key] == b[key], key


def test_steered_campaign_refuses_foreign_coverage_map(tmp_path):
    """A journaled map from a different point signature refuses by
    name instead of silently mixing digest spaces."""
    grid = campaign_from_json(COV_GRID)
    path = str(tmp_path / "c")
    s = run_campaign(path, grid, budget_s=0.0)
    assert not s["done"]
    # rewrite the journaled map's signature to a foreign point
    jpath = os.path.join(path, "journal.jsonl")
    entries = [json.loads(x) for x in open(jpath)]
    entries[-1]["coverage"]["signature"]["protocol"] = "tempo"
    with open(jpath, "w") as fh:
        for e in entries:
            fh.write(json.dumps(e, sort_keys=True) + "\n")
    with pytest.raises(CoverageMismatchError, match="protocol"):
        run_campaign(path, resume=True)
    # the refusal rides the standard campaign exit-2 path
    from fantoch_tpu.campaign import CampaignError

    assert issubclass(CoverageMismatchError, CampaignError)


def test_fuzz_summary_reads_journaled_counters_not_chunk_sizes(tmp_path):
    """Regression (the over-count fix): schedules=5 with chunk=2 ends
    on a truncated final chunk; after a mid-campaign budget stop and
    resume, every total must come from the journaled `tried` counters
    — 5, never chunks × chunk-size = 6."""
    grid = campaign_from_json(
        dict(COV_GRID, schedules=5, coverage=False)
    )
    path = str(tmp_path / "c")
    s = run_campaign(path, grid, budget_s=0.0)
    assert not s["done"]
    assert s["schedules_tried"] == s["points"]["basic/n3"]["tried"] == 2
    s = run_campaign(path, resume=True)
    assert s["done"]
    assert s["schedules_tried"] == 5
    assert s["points"]["basic/n3"]["tried"] == 5
    persisted = json.load(open(os.path.join(path, "summary.json")))
    assert persisted["schedules_tried"] == 5
    # and the journal agrees line by line: cumulative, ending at 5
    tried = [
        e["tried"]
        for e in (json.loads(x) for x in open(
            os.path.join(path, "journal.jsonl")
        ))
        if e.get("kind") == "fuzz"
    ]
    assert tried == [2, 4, 5]


def test_steered_fleet_two_workers_merge_equals_solo(tmp_path):
    """Fleet-steered budgets: two workers handing a steered point's
    chunks across the journaled map/pool/generator positions merge to
    a summary.json (coverage map included) byte-identical to the
    1-worker control's."""
    from fantoch_tpu.fleet import merge_campaign, run_fleet_worker

    grid = campaign_from_json(COV_GRID)
    solo = str(tmp_path / "solo")
    s = run_fleet_worker(solo, grid, worker_id="solo")
    assert s["done"]
    assert merge_campaign(solo)["merged"]

    fleet = str(tmp_path / "fleet")
    s1 = run_fleet_worker(fleet, grid, worker_id="w1", budget_s=0.0)
    assert not s1["done"] and s1["interrupted"] == "budget exhausted"
    s2 = run_fleet_worker(fleet, None, worker_id="w2")
    assert s2["done"]
    assert merge_campaign(fleet)["merged"]
    assert _read(os.path.join(fleet, "summary.json")) == _read(
        os.path.join(solo, "summary.json")
    )
    merged = json.load(open(os.path.join(fleet, "summary.json")))
    assert merged["points"]["basic/n3"]["cov_buckets"] > 0
    assert merged["schedules_tried"] == grid.schedules


# ----------------------------------------------------------------------
# the standing farm (docs/MC.md "Standing farm"): fault-class shards,
# frontier weighting, plateau retirement, binary coverage maps
# ----------------------------------------------------------------------


def test_class_split_chunked_equals_one_shot_across_journal_hop():
    """Each fault class is its own fuzz point: salted PCG64 streams,
    restricted envelope, own signature — and each class's steered
    stream splits across a journal hop exactly like the legacy mixed
    stream (chunked ≡ one-shot)."""
    from fantoch_tpu.mc.fuzz import class_spec

    base = FuzzSpec(protocol="tempo", n=3, schedules=8, seed=11,
                    crash_share=0.3, drop_share=0.2)
    # mixed IS the base spec: pre-split journals/maps stay byte-compat
    assert class_spec(base, "mixed") == base
    assert point_signature(class_spec(base, "mixed")) == \
        point_signature(base)
    with pytest.raises(ValueError, match="fault class"):
        class_spec(base, "partition")

    specs = {c: class_spec(base, c)
             for c in ("crash", "drop", "jitter")}
    # restricted envelopes: the excluded fault shares go to zero, so
    # mutation can never re-introduce the excluded class
    assert specs["crash"].drop_share == 0.0
    assert specs["crash"].crash_share == base.crash_share
    assert specs["drop"].crash_share == 0.0
    assert specs["jitter"].crash_share == 0.0
    assert specs["jitter"].drop_share == 0.0
    # class-independent streams + class-distinct signatures
    sigs = {c: point_signature(s) for c, s in specs.items()}
    assert len({json.dumps(s, sort_keys=True)
                for s in sigs.values()}) == 3
    for c, s in sigs.items():
        assert s["fault_class"] == c
        assert s != point_signature(base)

    streams = {}
    for c, spec in specs.items():
        config, dev = point_config(spec), point_protocol(spec)
        pool = SeedPool()
        for p in draw_plans(spec, config, dev)[:4]:
            pool.add(p)
        rng, mrng = plan_rng(spec), mutation_rng(spec)
        reference = draw_steered(spec, config, dev, 8, rng, mrng,
                                 pool)
        rng, mrng = plan_rng(spec), mutation_rng(spec)
        first = draw_steered(spec, config, dev, 3, rng, mrng, pool)
        # the journal hop: both generator positions + the pool
        # JSON-round-tripped, exactly as a chunk boundary persists
        r_state = json.loads(json.dumps(rng_state(rng)))
        m_state = json.loads(json.dumps(rng_state(mrng)))
        pool2 = SeedPool.from_json(
            json.loads(json.dumps(pool.to_json()))
        )
        rest = draw_steered(
            spec, config, dev, 5,
            restore_rng(r_state), restore_rng(m_state), pool2,
        )
        assert first + rest == reference, c
        streams[c] = [plan_to_json(p) for p in reference]
    # the salted seeds give every class a distinct plan stream
    assert streams["crash"] != streams["drop"]
    assert streams["crash"] != streams["jitter"]
    assert streams["drop"] != streams["jitter"]


def test_farm_spec_validation_refuses_bad_shapes():
    from fantoch_tpu.campaign import CampaignError

    for bad in (
        dict(COV_GRID, classes=["crash", "nope"]),
        dict(COV_GRID, classes=[]),
        dict(COV_GRID, classes=["crash", "crash"]),
        dict(COV_GRID, retire_after=-1),
        dict(COV_GRID, coverage=False, retire_after=2),
        dict(COV_GRID, coverage=False, binary_maps=True),
    ):
        with pytest.raises(CampaignError):
            campaign_from_json(bad)


def test_frontier_weights_favor_isolated_buckets():
    """The frontier-weighted draw: a pooled seed whose digest sits far
    (Hamming-wise) from every other hit bucket weighs more than one in
    a dense cluster; seeds without digest anchors (legacy pools) and
    cmap-less call sites stay uniform — the legacy draw, bit for
    bit."""
    from fantoch_tpu.mc.coverage import frontier_weights

    spec = FuzzSpec(protocol="tempo", n=3, schedules=6, seed=3)
    cmap = CoverageMap(signature=point_signature(spec))
    # one tight cluster (pairwise distance 1) + one far outlier
    cmap.observe([0b0000, 0b0001, 0b0011, 0b1111000011110000])
    pool = SeedPool()
    plans = draw_plans(spec, point_config(spec),
                       point_protocol(spec))
    pool.add(plans[0], digest=0b0000)
    pool.add(plans[1], digest=0b1111000011110000)
    pool.add(plans[2], digest=None)  # legacy seed: no anchor
    w = frontier_weights(pool, cmap)
    assert w[2] == 1                     # anchor-less → uniform
    assert w[1] > w[0] > 1               # outlier outweighs cluster
    # no map → every weight 1 (the uniform legacy draw)
    assert frontier_weights(pool, None) == [1, 1, 1]
    # round-tripping the pool WITH its digest anchors preserves the
    # weights (the journal carries them in `seed_digests`)
    pool2 = SeedPool.from_json(
        json.loads(json.dumps(pool.to_json())),
        digests=json.loads(json.dumps(pool.digests_json())),
    )
    assert frontier_weights(pool2, cmap) == w
    # ...and a legacy pool (no digests key) degrades to uniform
    pool3 = SeedPool.from_json(json.loads(json.dumps(pool.to_json())))
    assert frontier_weights(pool3, cmap) == [1, 1, 1]


def test_legacy_mixed_journal_and_campaign_json_resume(tmp_path):
    """A pre-split campaign dir — campaign.json without the farm
    fields, journal keyed `proto/nN` with inline JSON maps — resumes
    under the split-aware code to a summary byte-identical to a
    fresh control's: `mixed` elision keeps every legacy artifact
    valid."""
    grid = campaign_from_json(COV_GRID)
    ctrl = str(tmp_path / "ctrl")
    assert run_campaign(ctrl, grid)["done"]

    intr = str(tmp_path / "intr")
    s = run_campaign(intr, grid, budget_s=0.0)
    assert not s["done"]
    # rewrite campaign.json as a pre-farm file: drop the new fields
    cpath = os.path.join(intr, "campaign.json")
    stored = json.load(open(cpath))
    for k in ("classes", "retire_after", "binary_maps"):
        stored.pop(k)
    with open(cpath, "w") as fh:
        json.dump(stored, fh, indent=2, sort_keys=True)
    s = run_campaign(intr, resume=True)
    assert s["done"]
    assert set(s["points"]) == {"basic/n3"}  # the legacy key, intact
    assert _read(os.path.join(ctrl, "summary.json")) == _read(
        os.path.join(intr, "summary.json")
    )


def test_retirement_deterministic_across_interruption(tmp_path):
    """Plateau retirement: a point whose last `retire_after` chunks
    opened zero new buckets retires via a journaled entry at a
    deterministic chunk — and a farm interrupted mid-plateau and
    resumed retires the identical set at the identical chunk, with
    byte-identical summaries."""
    # jitter_max=1 disables jitter ⇒ every schedule of the jitter
    # class drives the same interleaving ⇒ coverage saturates on the
    # first chunk and the point goes dry immediately
    grid = campaign_from_json(dict(
        COV_GRID, schedules=20, classes=["jitter"], retire_after=2,
        jitter_max=1,
    ))
    ctrl = str(tmp_path / "ctrl")
    s = run_campaign(ctrl, grid)
    assert s["done"]
    assert s["retired"] == ["basic/n3/jitter"]
    # first chunk opens buckets, then exactly retire_after dry chunks:
    # retirement lands at chunk 3 ⇒ tried == 6, never the full 20
    assert s["points"]["basic/n3/jitter"]["tried"] == 6
    entries = [
        json.loads(x) for x in open(os.path.join(ctrl,
                                                 "journal.jsonl"))
    ]
    retire = [e for e in entries if e.get("kind") == "retire"]
    assert retire == [{
        "kind": "retire", "point": "basic/n3/jitter",
        "tried": 6, "cov_dry": 2,
    }]

    intr = str(tmp_path / "intr")
    run_campaign(intr, grid, budget_s=0.0)  # one chunk, then stop
    run_campaign(intr, resume=True, budget_s=0.0)  # mid-plateau stop
    s = run_campaign(intr, resume=True)
    assert s["done"]
    assert s["retired"] == ["basic/n3/jitter"]
    assert _read(os.path.join(ctrl, "summary.json")) == _read(
        os.path.join(intr, "summary.json")
    )
    # retired points never re-rank: a further resume is a no-op that
    # re-summarizes without another chunk
    before = _read(os.path.join(intr, "journal.jsonl"))
    assert run_campaign(intr, resume=True)["done"]
    assert _read(os.path.join(intr, "journal.jsonl")) == before


def test_binary_covmap_round_trip_compact_and_migration(tmp_path):
    """The compact binary map format: canonical bytes (save → load →
    re-save is byte-stable), versioned per-chunk files compact down to
    a bounded window, and a JSON point state migrates losslessly
    (golden round-trip, original left untouched)."""
    from fantoch_tpu.mc import covmap as cvm
    from fantoch_tpu.mc.coverage import save_point_state

    spec = FuzzSpec(protocol="tempo", n=3, seed=4)
    sig = point_signature(spec)
    m = CoverageMap(signature=sig)
    m.observe([5, -1, 5, 1 << 62, -(1 << 62)])

    data = cvm.covmap_bytes(m)
    back = cvm.covmap_from_bytes(data, signature=sig)
    assert back.buckets == m.buckets and back.signature == sig
    assert cvm.covmap_bytes(back) == data  # canonical: byte-stable
    p = str(tmp_path / "m.covmap")
    cvm.save_covmap(p, m)
    assert cvm.covmap_bytes(cvm.load_covmap(p, signature=sig)) == data

    # versioned farm files + compaction window
    d = str(tmp_path / "farm")
    key = "tempo/n3/crash"
    for tried in (2, 4, 6):
        m.observe([tried])
        cvm.save_point_map(d, key, tried, m)
    names = sorted(os.listdir(os.path.join(d, "covmaps")))
    assert names == [
        "tempo_n3_crash.t00000002.covmap",
        "tempo_n3_crash.t00000004.covmap",
        "tempo_n3_crash.t00000006.covmap",
    ]
    cvm.compact_point_maps(d, key, keep=2)
    assert sorted(os.listdir(os.path.join(d, "covmaps"))) == names[1:]
    got = cvm.load_point_map(d, key, 6, signature=sig)
    assert got.buckets == m.buckets
    tried, latest = cvm.latest_point_map(d, key)
    assert tried == 6 and latest.buckets == m.buckets

    # lossless JSON → binary migration, original untouched
    cd = str(tmp_path / "covdir")
    state = {
        "kind": "fuzz-coverage", "version": m.to_json()["version"],
        "tried": 6, "coverage": m.to_json(), "seeds": [],
    }
    save_point_state(cd, spec, state)
    before = _read(os.path.join(cd, "cov_tempo_n3.json"))
    written = cvm.migrate_point_states(cd)
    assert [os.path.basename(w) for w in written] == [
        "cov_tempo_n3.covmap"
    ]
    assert _read(os.path.join(cd, "cov_tempo_n3.json")) == before
    mig = cvm.load_covmap(written[0], signature=sig)
    assert mig.buckets == m.buckets
    # migration is idempotent byte-for-byte
    first = _read(written[0])
    assert cvm.migrate_point_states(cd) == written
    assert _read(written[0]) == first


def test_binary_covmap_foreign_version_and_signature_refused(tmp_path):
    """Refusals, by name: a foreign container version, a foreign point
    signature and structural damage never load (and never silently
    rebuild)."""
    from fantoch_tpu.mc import covmap as cvm

    spec = FuzzSpec(protocol="tempo", n=3, seed=4)
    sig = point_signature(spec)
    m = CoverageMap(signature=sig)
    m.observe([5, -1])
    p = str(tmp_path / "m.covmap")
    cvm.save_covmap(p, m)

    data = _read(p)
    # container version lives right after the 8-byte magic (<I)
    foreign = data[:8] + (99).to_bytes(4, "little") + data[12:]
    fp = str(tmp_path / "foreign.covmap")
    with open(fp, "wb") as fh:
        fh.write(foreign)
    with pytest.raises(cvm.CovmapVersionError, match="version"):
        cvm.load_covmap(fp, signature=sig)

    other = point_signature(FuzzSpec(protocol="fpaxos", n=5, seed=4))
    with pytest.raises(CoverageMismatchError, match="protocol"):
        cvm.load_covmap(p, signature=other)

    with open(str(tmp_path / "trunc.covmap"), "wb") as fh:
        fh.write(data[:-3])
    with pytest.raises(cvm.CovmapError):
        cvm.load_covmap(str(tmp_path / "trunc.covmap"), signature=sig)
    # the refusal hierarchy rides the existing exit-2 path
    assert issubclass(cvm.CovmapError, CoverageError)
    assert issubclass(cvm.CovmapVersionError, CoverageMismatchError)


def test_binary_maps_farm_resume_and_final_maps_byte_identical(
    tmp_path,
):
    """The farm identity pin (device tier-1 shape): a binary-map farm
    interrupted and resumed produces summary.json AND the final
    per-point `.covmap` files byte-identical to the uninterrupted
    control's; journal entries carry `cov_sha256` instead of the
    inline JSON map."""
    grid = campaign_from_json(dict(
        COV_GRID, classes=["crash", "jitter"], crash_share=0.3,
        drop_share=0.2, binary_maps=True,
    ))
    ctrl = str(tmp_path / "ctrl")
    s = run_campaign(ctrl, grid)
    assert s["done"]
    assert set(s["points"]) == {"basic/n3/crash", "basic/n3/jitter"}
    for e in (json.loads(x)
              for x in open(os.path.join(ctrl, "journal.jsonl"))):
        if e.get("kind") == "fuzz":
            assert "coverage" not in e and "cov_sha256" in e
    finals = sorted(os.listdir(os.path.join(ctrl, "covmaps")))
    # done farms keep ONLY the canonical final maps (versioned
    # generations compacted away)
    assert finals == [
        "basic_n3_crash.covmap", "basic_n3_jitter.covmap"
    ]

    intr = str(tmp_path / "intr")
    run_campaign(intr, grid, budget_s=0.0)
    assert run_campaign(intr, resume=True)["done"]
    assert _read(os.path.join(ctrl, "summary.json")) == _read(
        os.path.join(intr, "summary.json")
    )
    for name in finals:
        assert _read(os.path.join(ctrl, "covmaps", name)) == _read(
            os.path.join(intr, "covmaps", name)
        ), name


# ----------------------------------------------------------------------
# slow tier: tempo + subprocess SIGKILL
# ----------------------------------------------------------------------


@pytest.mark.slow
def test_tempo_steered_campaign_resume_byte_identical(tmp_path):
    grid = campaign_from_json(
        {
            "kind": "fuzz",
            "protocols": ["tempo"],
            "ns": [3],
            "schedules": 8,
            "chunk": 4,
            "commands_per_client": 5,
            "seed": 7,
            "confirm": False,
            "coverage": True,
        }
    )
    ctrl = str(tmp_path / "ctrl")
    assert run_campaign(ctrl, grid)["done"]
    intr = str(tmp_path / "intr")
    run_campaign(intr, grid, budget_s=0.0)
    assert run_campaign(intr, resume=True)["done"]
    assert _read(os.path.join(ctrl, "summary.json")) == _read(
        os.path.join(intr, "summary.json")
    )


@pytest.mark.slow
def test_steered_fleet_worker_sigkilled_resumes_byte_identical(tmp_path):
    """The real preemption shape for a steered fleet: a subprocess
    worker is SIGKILLed mid-campaign; reclaimers finish the grid from
    the journaled coverage state and the merged summary equals the
    uninterrupted control's."""
    import signal
    import subprocess
    import sys
    import time

    from fantoch_tpu.fleet import merge_campaign, run_fleet_worker

    grid = campaign_from_json(dict(COV_GRID, schedules=8, chunk=2))
    solo = str(tmp_path / "solo")
    assert run_fleet_worker(solo, grid, worker_id="solo")["done"]
    assert merge_campaign(solo)["merged"]

    fleet = str(tmp_path / "fleet")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "fantoch_tpu", "--platform", "cpu",
            "fleet", "--dir", fleet, "--grid",
            json.dumps(dict(COV_GRID, schedules=8, chunk=2)),
            "--worker-id", "doomed", "--ttl-s", "1.5",
        ],
        env=env,
        stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL,
    )
    try:
        deadline = time.monotonic() + 180
        jdir = os.path.join(fleet, "journals")
        while time.monotonic() < deadline:
            # kill once the worker has journaled at least one chunk
            if os.path.isdir(jdir) and any(
                os.path.getsize(os.path.join(jdir, f))
                for f in os.listdir(jdir)
            ):
                break
            if proc.poll() is not None:
                break
            time.sleep(0.05)
        proc.send_signal(signal.SIGKILL)
        proc.wait()
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()

    deadline = time.monotonic() + 180
    while True:
        s = run_fleet_worker(fleet, grid, worker_id="reclaimer",
                             ttl_s=1.5)
        if s["done"]:
            break
        assert time.monotonic() < deadline, s
        time.sleep(0.5)
    assert merge_campaign(fleet)["merged"]
    assert _read(os.path.join(fleet, "summary.json")) == _read(
        os.path.join(solo, "summary.json")
    )
